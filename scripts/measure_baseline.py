#!/usr/bin/env python
"""Measure the reference CPU baseline and this repo's CLI on identical inputs.

Produces BASELINE_measured.md: cut + wall-clock for the reference binary
(`build_ref/apps/KaMinPar`, built from /root/reference) and for
`python -m kaminpar_tpu`, per graph/k/seed (VERDICT r1 next-step #2 — every
perf claim must be anchored to a *measured* reference run, not a guessed
constant).

Usage:  python scripts/measure_baseline.py [--quick]

Notes on comparability: this box exposes ONE cpu core, so the reference runs
single-threaded (-t 1); the reference's published numbers use 96 cores.  The
table is an apples-to-apples single-host comparison, not the north-star
TPU-vs-multicore target (BASELINE.md).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_BIN = os.path.join(REPO, "build_ref", "apps", "KaMinPar")

CONFIGS = [
    # (graph path, k, label)
    ("/root/reference/misc/rgg2d.metis", 4, "rgg2d k=4 (BASELINE eval 1)"),
    ("/root/reference/misc/rgg2d.metis", 64, "rgg2d k=64"),
    ("bench_data/rmat16.metis", 16, "rmat16 k=16"),
    ("bench_data/rmat18.metis", 16, "rmat18 k=16 (BASELINE eval 2 analog)"),
    ("bench_data/rmat18.metis", 64, "rmat18 k=64"),
]


def run_reference(graph: str, k: int, seed: int):
    t0 = time.perf_counter()
    out = subprocess.run(
        [REF_BIN, graph, str(k), "-P", "default", f"--seed={seed}", "-t", "1"],
        capture_output=True,
        text=True,
        timeout=3600,
        cwd=REPO,
    )
    wall = time.perf_counter() - t0
    if out.returncode != 0:
        raise RuntimeError(
            f"reference failed on {graph} k={k}:\n{out.stdout}\n{out.stderr}"
        )
    cut = int(re.search(r"Edge cut:\s+(\d+)", out.stdout).group(1))
    imb = float(re.search(r"Imbalance:\s+([\d.e-]+)", out.stdout).group(1))
    return {"cut": cut, "imbalance": imb, "wall_s": wall}


def run_ours(graph: str, k: int, seed: int):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO  # strip the axon site hook (it force-connects
    env["JAX_PLATFORMS"] = "cpu"  # the TPU tunnel even for CPU runs)
    t0 = time.perf_counter()
    out = subprocess.run(
        [
            sys.executable, "-m", "kaminpar_tpu", graph, str(k),
            "-P", "default", "-s", str(seed), "-E",
        ],
        capture_output=True,
        text=True,
        timeout=3600,
        env=env,
        cwd=REPO,
    )
    wall = time.perf_counter() - t0
    m = re.search(r"RESULT cut=(\d+) imbalance=([\d.e-]+)", out.stdout)
    if not m:
        raise RuntimeError(f"no RESULT line:\n{out.stdout}\n{out.stderr}")
    return {"cut": int(m.group(1)), "imbalance": float(m.group(2)), "wall_s": wall}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="one seed, small configs")
    ap.add_argument("--out", default=os.path.join(REPO, "BASELINE_measured.md"))
    ap.add_argument("--json", default=os.path.join(REPO, "bench_data", "baseline.json"))
    args = ap.parse_args()

    seeds = [1] if args.quick else [1, 2, 3]
    configs = CONFIGS[:1] if args.quick else CONFIGS
    rows = []
    for graph, k, label in configs:
        if not os.path.exists(os.path.join(REPO, graph)) and not os.path.exists(graph):
            print(f"skip {label}: {graph} missing", file=sys.stderr)
            continue
        ref_runs = [run_reference(graph, k, s) for s in seeds]
        our_runs = [run_ours(graph, k, s) for s in seeds]
        best = min  # compare best cuts (both sides pick their best seed)
        row = {
            "label": label,
            "graph": graph,
            "k": k,
            "ref_cut_best": best(r["cut"] for r in ref_runs),
            "ref_cut_mean": sum(r["cut"] for r in ref_runs) / len(ref_runs),
            "ref_wall_mean": sum(r["wall_s"] for r in ref_runs) / len(ref_runs),
            "our_cut_best": best(r["cut"] for r in our_runs),
            "our_cut_mean": sum(r["cut"] for r in our_runs) / len(our_runs),
            "our_wall_mean": sum(r["wall_s"] for r in our_runs) / len(our_runs),
            "our_imb_max": max(r["imbalance"] for r in our_runs),
        }
        row["cut_ratio_mean"] = row["our_cut_mean"] / max(row["ref_cut_mean"], 1)
        rows.append(row)
        print(json.dumps(row), flush=True)

    os.makedirs(os.path.dirname(args.json), exist_ok=True)
    with open(args.json, "w") as f:
        json.dump(rows, f, indent=2)

    with open(args.out, "w") as f:
        f.write(
            "# BASELINE_measured — reference binary vs this repo (same box)\n\n"
            "Reference: KaMinPar v3.7.3 built from /root/reference "
            "(Release, TBB, `-t 1`; this box has ONE cpu core — the "
            "reference's published numbers use 96).  Ours: "
            "`python -m kaminpar_tpu -P default` on the CPU backend (same "
            "core).  Cuts are mean over seeds {1,2,3}; wall is end-to-end "
            "including IO and (for ours) jit compilation.\n\n"
            "| config | ref cut | our cut | cut ratio | ref wall s | our wall s | our imb |\n"
            "|---|---|---|---|---|---|---|\n"
        )
        for r in rows:
            f.write(
                f"| {r['label']} | {r['ref_cut_mean']:.0f} | {r['our_cut_mean']:.0f} "
                f"| {r['cut_ratio_mean']:.3f} | {r['ref_wall_mean']:.2f} "
                f"| {r['our_wall_mean']:.2f} | {r['our_imb_max']:.4f} |\n"
            )
        f.write(
            "\nCut ratio ≤ 1.05 is the BASELINE.md quality bar.  Wall-clock "
            "on this 1-core box is not the north-star comparison (that is "
            "TPU vs 96-core, BASELINE.md); it anchors correctness of the "
            "quality story and gives a measured lower bound for the "
            "reference's single-core throughput.\n"
        )
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
