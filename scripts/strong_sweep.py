#!/usr/bin/env python
"""Strong-tier proof sweep (VERDICT r4 next-steps #5).

Runs the strong AND eco presets over the 5 eval configs x 3 seeds, printing
one JSON line per run (progressively, so a killed sweep still yields data)
and a final summary.  Done-criterion: strong >= eco on all configs, <=1.05x
the reference on >= 4 of 5.

Usage: python scripts/strong_sweep.py [--configs ...] [--seeds 1,2,3]
       [--presets strong] [--devext]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, REPO)

from kaminpar_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

CONFIGS = {
    # name: (path, k, ref mean cut over seeds {1,2,3}, ref source preset)
    "rmat14": ("bench_data/rmat14.metis", 16, 116535.0, "default"),
    "grid256": ("bench_data/grid256.metis", 64, 4218.0, "default"),
    "rgg64k": ("bench_data/rgg64k.metis", 64, 120000.0, "default"),
    "road256": ("bench_data/road256.metis", 64, 16698.0, "default"),
    "road512": ("bench_data/road512.metis", 64, 24061.0, "default"),
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="rmat14,grid256,rgg64k,road256,road512")
    ap.add_argument("--seeds", default="1,2,3")
    ap.add_argument("--presets", default="strong")
    ap.add_argument("--devext", action="store_true")
    ap.add_argument("--out", default="bench_data/strong_sweep.jsonl")
    args = ap.parse_args()

    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.io import read_metis
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name

    seeds = [int(s) for s in args.seeds.split(",")]
    out_path = os.path.join(REPO, args.out)
    means: dict = {}
    for name in args.configs.split(","):
        path, k, ref, _ = CONFIGS[name]
        g = read_metis(os.path.join(REPO, path))
        for preset in args.presets.split(","):
            cuts, walls = [], []
            for seed in seeds:
                ctx = create_context_by_preset_name(preset)
                ctx.seed = seed
                if args.devext:
                    ctx.initial_partitioning.device_extension = True
                s = KaMinPar(ctx)
                s.set_graph(g)
                t0 = time.perf_counter()
                part = s.compute_partition(k, epsilon=0.03)
                wall = time.perf_counter() - t0
                cut = int(metrics.edge_cut(g, part))
                feas = bool(s.last_partition.is_feasible())
                rec = {"config": name, "preset": preset, "seed": seed,
                       "cut": cut, "feasible": feas, "wall_s": round(wall, 1),
                       "devext": bool(args.devext)}
                print(json.dumps(rec), flush=True)
                with open(out_path, "a") as fh:
                    fh.write(json.dumps(rec) + "\n")
                cuts.append(cut)
                walls.append(wall)
            mean = sum(cuts) / len(cuts)
            means[(name, preset)] = mean
            print(json.dumps({
                "config": name, "preset": preset, "mean_cut": round(mean, 1),
                "ratio_vs_ref": round(mean / ref, 3),
                "spread": [min(cuts), max(cuts)],
                "mean_wall_s": round(sum(walls) / len(walls), 1),
            }), flush=True)


if __name__ == "__main__":
    main()
