#!/usr/bin/env python
"""Road-network config evaluation (VERDICT r2 next-steps #3, BASELINE eval
config 3 analog).

USA-road-d cannot be fetched (zero egress); per the verdict a large grid
with random edge weights approximates its class (low degree, high diameter).
Measures the reference binary at -P default/eco/strong (strong = the flow
preset) vs ours at default/eco/strong on k=64, so the flow-refiner question
is settled on the graph class where FlowCutter actually pays.

Usage: python scripts/road_eval.py [--side 512] [--seeds 1,2] [--ours-only]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_BIN = os.path.join(REPO, "build_ref", "apps", "KaMinPar")
DATA = os.path.join(REPO, "bench_data")

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, REPO)

from kaminpar_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(1)


def fixture(side: int) -> str:
    import numpy as np

    from kaminpar_tpu.graph.csr import CSRGraph
    from kaminpar_tpu.graph.generators import grid2d_graph
    from kaminpar_tpu.io import write_metis

    os.makedirs(DATA, exist_ok=True)
    path = os.path.join(DATA, f"road{side}.metis")
    if not os.path.exists(path):
        g0 = grid2d_graph(side, side)
        # random integer "travel time" weights, symmetric by construction:
        # weight = f(min(u,v), max(u,v))
        rp = np.asarray(g0.row_ptr)
        col = np.asarray(g0.col_idx).astype(np.int64)
        u = np.repeat(np.arange(g0.n, dtype=np.int64), np.diff(rp))
        key = np.minimum(u, col) * g0.n + np.maximum(u, col)
        ew = (key * 2654435761 % 9 + 1).astype(np.int32)
        g = CSRGraph(g0.row_ptr, g0.col_idx, None, ew)
        write_metis(g, path)
        print(f"wrote {path} n={g.n} m={g.m}", file=sys.stderr)
    return path


def run_ref(path: str, k: int, seed: int, preset: str):
    t0 = time.perf_counter()
    out = subprocess.run(
        [REF_BIN, path, str(k), "-P", preset, f"--seed={seed}", "-t", "1"],
        capture_output=True, text=True, timeout=7200,
    )
    wall = time.perf_counter() - t0
    if out.returncode != 0:
        raise RuntimeError(f"ref {preset} failed: {out.stderr[-300:]}")
    return int(re.search(r"Edge cut:\s+(\d+)", out.stdout).group(1)), wall


def run_ours(path: str, k: int, seed: int, preset: str):
    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.io import read_metis
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name(preset)
    ctx.seed = seed
    g = read_metis(path)
    s = KaMinPar(ctx)
    s.set_graph(g)
    t0 = time.perf_counter()
    part = s.compute_partition(k, epsilon=0.03)
    wall = time.perf_counter() - t0
    return int(metrics.edge_cut(g, part)), wall


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--side", type=int, default=512)
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--seeds", default="1,2")
    ap.add_argument("--skip-ref", action="store_true")
    ap.add_argument("--presets", default="default,eco,strong")
    args = ap.parse_args()
    path = fixture(args.side)
    seeds = [int(s) for s in args.seeds.split(",")]
    results = {}
    for preset in args.presets.split(","):
        if not args.skip_ref:
            cuts, walls = zip(*(run_ref(path, args.k, s, preset) for s in seeds))
            results[f"ref-{preset}"] = dict(
                cut=sum(cuts) / len(cuts), wall=sum(walls) / len(walls)
            )
            print(f"ref  {preset:8s} cut {results[f'ref-{preset}']['cut']:9.0f} "
                  f"wall {results[f'ref-{preset}']['wall']:7.1f}s", flush=True)
        cuts, walls = zip(*(run_ours(path, args.k, s, preset) for s in seeds))
        results[f"ours-{preset}"] = dict(
            cut=sum(cuts) / len(cuts), wall=sum(walls) / len(walls)
        )
        print(f"ours {preset:8s} cut {results[f'ours-{preset}']['cut']:9.0f} "
              f"wall {results[f'ours-{preset}']['wall']:7.1f}s", flush=True)
    with open(os.path.join(DATA, f"road{args.side}_eval.json"), "w") as f:
        json.dump(results, f, indent=2)


if __name__ == "__main__":
    main()
