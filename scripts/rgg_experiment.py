#!/usr/bin/env python
"""rgg64k eco plateau experiment (VERDICT r5 carry-over of r4 next #3).

Hypothesis on record (BASELINE_measured.md r5): the rgg64k eco mean sits
at ~1.12 because of per-seed extension variance (spread 1.07-1.14), so
keep-best repetition over extension — not more FM — is the lever.  Arms:

  base      eco as shipped
  devext2   eco + batched device extension, keep-best of 2
  devext3   eco + batched device extension, keep-best of 3
  nested3   eco + host nested extension with 3 reps (was 2)

3 seeds each, ref cut 120000 (measured r2, bench_data/ref_cache.json).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, REPO)

from kaminpar_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

REF = 120000.0
K = 64
SEEDS = (1, 2, 3)


def run_arm(name: str, mutate) -> dict:
    import numpy as np

    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.io import read_metis
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name

    g = read_metis(os.path.join(REPO, "bench_data", "rgg64k.metis"))
    cuts, walls = [], []
    for seed in SEEDS:
        ctx = create_context_by_preset_name("eco")
        ctx.seed = seed
        mutate(ctx)
        s = KaMinPar(ctx)
        s.set_graph(g)
        t = time.perf_counter()
        part = s.compute_partition(K, epsilon=0.03)
        walls.append(time.perf_counter() - t)
        assert metrics.is_feasible(g, part, K, s.ctx.partition.max_block_weights)
        cuts.append(int(metrics.edge_cut(g, part)))
    rec = {
        "arm": name, "cuts": cuts,
        "mean": float(np.mean(cuts)),
        "ratio": round(float(np.mean(cuts)) / REF, 4),
        "ratio_spread": [round(min(cuts) / REF, 4), round(max(cuts) / REF, 4)],
        "wall_s": [round(w, 1) for w in walls],
    }
    print(json.dumps(rec), flush=True)
    return rec


def main() -> None:
    arms = {
        "base": lambda ctx: None,
        "devext2": lambda ctx: (
            setattr(ctx.initial_partitioning, "device_extension", True),
            setattr(ctx.initial_partitioning, "device_extension_reps", 2),
        ),
        "devext3": lambda ctx: (
            setattr(ctx.initial_partitioning, "device_extension", True),
            setattr(ctx.initial_partitioning, "device_extension_reps", 3),
        ),
        "nested3": lambda ctx: setattr(
            ctx.initial_partitioning, "nested_extension_reps", 3
        ),
    }
    only = sys.argv[1:] or list(arms)
    out = [run_arm(name, arms[name]) for name in only]
    with open(os.path.join(REPO, "bench_data", "rgg_experiment.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
