#!/usr/bin/env python
"""Second-stage on-silicon profile: split lp_round_bucketed into its two
halves (bucketed_best_moves rating vs _commit_moves auction) and time each
alone at scale 16/18, plus the auction's threshold-bisection loop solo.
Names the dominant term behind the 85 ns/edge round cost."""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(**kw):
    print(json.dumps({k: (round(v, 6) if isinstance(v, float) else v)
                      for k, v in kw.items()}), flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from kaminpar_tpu.coarsening.max_cluster_weights import (
        compute_max_cluster_weight,
    )
    from kaminpar_tpu.context import Context
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.ops import lp
    from kaminpar_tpu.ops.bucketed_gains import bucketed_best_moves
    from kaminpar_tpu.utils import RandomState, next_key

    emit(event="init", platform=jax.devices()[0].platform)

    for scale in (16, 18):
        RandomState.reseed(0)
        graph = rmat_graph(scale, edge_factor=16, seed=1)
        pv = graph.padded()
        bv = graph.bucketed()
        ctx = Context()
        max_cw = compute_max_cluster_weight(
            ctx.coarsening, graph.n, graph.total_node_weight, 16, 0.03
        )
        idt = pv.row_ptr.dtype
        labels = jnp.concatenate(
            [jnp.arange(pv.n, dtype=idt),
             jnp.full(pv.n_pad - pv.n, pv.anchor, dtype=idt)]
        )
        state = lp.init_state(labels, pv.node_w, pv.n_pad)
        max_w = jnp.asarray(max_cw, dtype=idt)

        rate = jax.jit(partial(
            bucketed_best_moves, external_only=False, respect_caps=True,
            tie_break="uniform",
        ))

        def run_rate():
            return rate(next_key(), state.labels, bv.buckets, bv.heavy,
                        bv.gather_idx, pv.node_w, state.label_weights, max_w)

        out = run_rate()
        out[0].block_until_ready()
        int(jnp.sum(out[0]) % 7)  # hard sync via readback
        t = time.perf_counter()
        for _ in range(3):
            out = run_rate()
        int(jnp.sum(out[0]) % 7)
        rate_s = (time.perf_counter() - t) / 3
        target, tconn, own_conn, _ = out

        commit = jax.jit(partial(
            lp._commit_moves, num_labels=pv.n_pad, active_prob=1.0,
            allow_tie_moves=False,
        ))

        def run_commit():
            return commit(state, next_key(), target, tconn, own_conn,
                          pv.node_w, max_w)

        st2 = run_commit()
        int(st2.num_moved)
        t = time.perf_counter()
        for _ in range(3):
            st2 = run_commit()
        int(st2.num_moved)
        commit_s = (time.perf_counter() - t) / 3

        emit(event="split", scale=scale, m=graph.m, rate_s=rate_s,
             commit_s=commit_s,
             rate_ns_per_edge=rate_s / graph.m * 1e9,
             commit_ns_per_edge=commit_s / graph.m * 1e9)
        del graph, pv, bv, state, out, st2


if __name__ == "__main__":
    main()
