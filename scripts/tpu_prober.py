#!/usr/bin/env python
"""Round-long TPU availability prober (availability engineering, not a bench bug).

The tunneled TPU backend on this box is flaky in the worst way: ``jax.devices()``
can *hang* for >560 s rather than fail.  A single pre-bench probe therefore
cannot distinguish "tunnel down all round" from "tunnel down for ten minutes".
This daemon runs for the whole round:

  * every attempt spawns a fresh child process (own process group — backend
    init state cannot be retried in-process) that initializes the ambient
    backend and, the moment init succeeds on a non-CPU device, runs the LP
    microbenchmark + a small full partition (reusing ``bench.run_benchmark``);
  * every attempt is logged to ``TPU_PROBE_LOG.jsonl`` with start/end
    timestamps and outcome, so "no TPU number" is *evidenced*, not asserted;
  * the first successful measurement is written to ``TPU_RESULT.json`` and the
    daemon exits; ``bench.py`` prefers that artifact over re-probing.

Counterpart harness: reference
``apps/benchmarks/shm_label_propagation_benchmark.cc:29-80``.

Usage:  python scripts/tpu_prober.py [--daemon]
        python scripts/tpu_prober.py --child   (one attempt, internal)
"""

from __future__ import annotations

import importlib.util
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# Scratch/log dir, overridable so the forced-hang tier-1 test can run a
# real attempt without touching the repo's probe log.
WORK_DIR = os.environ.get("KPTPU_PROBER_DIR", REPO)
LOG_PATH = os.path.join(WORK_DIR, "TPU_PROBE_LOG.jsonl")
RESULT_PATH = os.path.join(WORK_DIR, "TPU_RESULT.json")

# A bare jax.devices() has been observed to hang >560 s before being killed
# (VERDICT r4 missing #1).  Give init well more than that, and the whole
# attempt (init + compile + measure) a multiple of it.
INIT_TIMEOUT_S = float(os.environ.get("KPTPU_PROBER_INIT_TIMEOUT", 1200))
ATTEMPT_TIMEOUT_S = float(os.environ.get("KPTPU_PROBER_ATTEMPT_TIMEOUT", 3600))
RETRY_SLEEP_S = float(os.environ.get("KPTPU_PROBER_RETRY_SLEEP", 600))
# Bounded-exponential retry escalation (ISSUE 12 satellite): after >= 3
# consecutive killed-hang attempts the sleep doubles per further hang up to
# this cap — 16 identical 1200 s init hangs at a fixed 600 s sleep burned a
# whole 11 h window (TPU_PROBE_LOG rounds 15-16) probing a tunnel that was
# evidently down all day.
RETRY_SLEEP_MAX_S = float(os.environ.get("KPTPU_PROBER_RETRY_MAX", 3600))
DEADLINE_H = float(os.environ.get("KPTPU_PROBER_HOURS", 11))


def _flight_recorder_mod():
    """Load telemetry/flight_recorder.py STANDALONE (by file path, pure
    stdlib) so the child can heartbeat before ``import jax`` — backend-init
    hangs are exactly the case the recorder exists for."""
    path = os.path.join(
        REPO, "kaminpar_tpu", "telemetry", "flight_recorder.py"
    )
    spec = importlib.util.spec_from_file_location("kpt_flight_recorder", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _log(rec: dict) -> None:
    rec["ts"] = round(time.time(), 1)
    rec["iso"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(LOG_PATH, "a") as fh:
        fh.write(json.dumps(rec) + "\n")


def child_attempt() -> None:
    """One probe+measure attempt on the ambient backend (runs in a fresh
    process).  Prints flushed JSON lines; exit codes: 0 = measured on
    accelerator, 3 = ambient backend resolved to CPU (tunnel absent), 4 =
    init raised.

    Flight recorder (ISSUE 12): heartbeats start BEFORE jax is imported
    (standalone module load) and a faulthandler stack dump is armed just
    under the parent's kill timeout, so a killed attempt leaves a
    diagnosable dossier instead of ``probe: null``."""
    t0 = time.time()
    recorder = None
    try:
        recorder = _flight_recorder_mod().arm_from_env()
    except Exception:  # noqa: BLE001 — forensics must never fail the probe
        pass
    if recorder is not None:
        recorder.note("backend_init")
    if os.environ.get("KPTPU_PROBER_TEST_HANG") == "init":
        # Forced-hang hook (tests/test_capacity.py): simulate the observed
        # jax.devices() wedge so the kill/dossier path is exercised for
        # real — the parent must SIGKILL this sleep.
        time.sleep(10**7)
    try:
        import jax

        devs = jax.devices()
    except Exception as exc:  # noqa: BLE001
        print(json.dumps({"probe": "init_error",
                          "error": f"{type(exc).__name__}: {exc}"[:300]}), flush=True)
        sys.exit(4)
    if recorder is not None:
        recorder.note("bench")
        # Init is over: re-arm the single faulthandler slot against the
        # ATTEMPT deadline (passed by the parent), so an execute-phase
        # hang killed at ATTEMPT_TIMEOUT_S carries its own dying stack,
        # not a stale init-era dump from 0.8 x INIT_TIMEOUT_S.
        try:
            attempt_dump_at = float(
                os.environ.get("KPTPU_FLIGHT_STACK_AFTER_OK_S", 0)
            )
            recorder.rearm_stack_dump(attempt_dump_at - (time.time() - t0))
        except Exception:  # noqa: BLE001
            pass
    plat = devs[0].platform
    print(json.dumps({
        "probe": "devices_ok",
        "init_s": round(time.time() - t0, 1),
        "platform": plat,
        "device_kind": str(getattr(devs[0], "device_kind", "")),
        "num_devices": len(devs),
    }), flush=True)
    if plat == "cpu":
        sys.exit(3)

    sys.path.insert(0, REPO)
    # Keep the on-silicon run modest: the point is *a* real number with
    # hbm_frac_of_peak_lb, captured inside an availability window that may
    # close again.  Scale 20 LP microbench + scale 18 full partition.
    os.environ.setdefault("KPTPU_BENCH_SCALE", "20")
    os.environ.setdefault("KPTPU_BENCH_FULL", "1")
    os.environ.setdefault("KPTPU_BENCH_FULL_SCALE", "18")
    # Serve-mode A/B (ISSUE 3) rides run_benchmark's phase 3: warm-engine
    # batched throughput vs the single-request pattern inside the same
    # availability window, at a modest on-silicon workload.
    os.environ.setdefault("KPTPU_BENCH_SERVE", "1")
    os.environ.setdefault("KPTPU_BENCH_SERVE_REQS", "16")
    os.environ.setdefault("KPTPU_BENCH_SERVE_SCALES", "10,12")
    # Initial-partitioning pool A/B (ISSUE 4) rides phase 2: host pool vs
    # the lane-vmapped device pool at a deep-pipeline coarsest-graph size
    # (2C = 4000 nodes ~ scale 12).  The new ip_backend / ip_pool /
    # initial_partitioning_* keys land in the same salvaged record.
    os.environ.setdefault("KPTPU_BENCH_IP_AB", "1")
    os.environ.setdefault("KPTPU_BENCH_IP_SCALE", "12")
    # Compressed device-pipeline A/B (ISSUE 10) rides run_benchmark's
    # phase 4: dense vs decode-fused terapart at a modest on-silicon
    # scale — this is where the HBM watermark delta (allocator stats exist
    # on TPU, unlike the CPU fallback) becomes a measured number.
    os.environ.setdefault("KPTPU_BENCH_COMPRESS", "1")
    os.environ.setdefault("KPTPU_BENCH_COMPRESS_SCALE", "16")
    # Sharded deep A/B (ISSUE 11) rides run_benchmark's phase 5 in its own
    # child: single-device vs P-shard dense vs P-shard compressed-resident.
    # On a multi-chip host set KPTPU_BENCH_SHARD_NATIVE=1 to measure the
    # real mesh; single-chip windows carry the virtual-CPU dryrun (the
    # bit-identity + resident-bytes record is backend-exact either way).
    os.environ.setdefault("KPTPU_BENCH_SHARD", "1")
    os.environ.setdefault("KPTPU_BENCH_SHARD_SCALE", "12")
    if len(devs) >= 8:
        os.environ.setdefault("KPTPU_BENCH_SHARD_NATIVE", "1")
    # Mesh-replicated serve-fleet A/B (ISSUE 14) rides run_benchmark's
    # phase 6 in its own child: one warm engine vs P per-device replicas
    # behind the SLO-aware router, at a modest on-silicon workload.  On a
    # multi-chip host the fleet measures the REAL device axis
    # (KPTPU_BENCH_FLEET_NATIVE=1 — this is where the aggregate-throughput
    # claim stops being a dryrun); single-chip windows carry the virtual
    # CPU-mesh routing/occupancy/bit-identity record.
    os.environ.setdefault("KPTPU_BENCH_FLEET", "1")
    os.environ.setdefault("KPTPU_BENCH_FLEET_SCALE", "10")
    os.environ.setdefault("KPTPU_BENCH_FLEET_REQS", "32")
    if len(devs) >= 8:
        os.environ.setdefault("KPTPU_BENCH_FLEET_NATIVE", "1")
    # Run telemetry (ISSUE 5): the full-partition phase records the unified
    # trace on-silicon; its summary (trace path, per-level quality rows,
    # HBM watermark) rides the salvaged record into TPU_RESULT.json and
    # TPU_PROBE_LOG.jsonl.
    os.environ.setdefault(
        "KPTPU_BENCH_TRACE_OUT", os.path.join(REPO, "TPU_trace.json")
    )
    from bench import run_benchmark, run_lp_phase

    run_benchmark()
    # Same-window Pallas A/B (ISSUE 1): re-measure the LP microbench on the
    # fused-kernel path so the round gets an on-silicon xla-vs-pallas
    # number.  A Pallas lowering failure must not void the XLA measurement
    # already flushed above.
    os.environ["KPTPU_BENCH_LP_KERNEL"] = "pallas"
    try:
        run_lp_phase()
    except Exception as exc:  # noqa: BLE001
        print(json.dumps({
            "probe": "pallas_lp_error",
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }), flush=True)


def _salvage_lines(out: str) -> list[dict]:
    recs = []
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                recs.append(json.loads(line))
            except ValueError:
                pass
    return recs


def run_attempt(attempt: int) -> dict | None:
    """Spawn one child attempt; enforce init/attempt deadlines by watching
    its stdout incrementally.  Returns the headline measurement record if the
    child measured on an accelerator, else None.

    The child's stdout goes to a FILE, not a pipe: non-blocking reads on a
    text-mode pipe raise TypeError when no data is buffered (observed on
    this box's Python 3.12 — it killed the round-5 daemon on its first poll),
    and a killed child can never wedge a file the way it wedges a pipe
    reader.

    Killed attempts carry a **dossier** (ISSUE 12): the parent arms the
    child's flight recorder (heartbeat sidecar + faulthandler stack dump
    timed just under the kill) and, after the kill, assembles last
    heartbeat + phase + stack tail + env fingerprint into the log record —
    and classifies the outcome string by the dying phase (init vs compile
    vs execute hang)."""
    t_start = time.time()
    fr = _flight_recorder_mod()
    out_path = os.path.join(WORK_DIR, f".tpu_probe_attempt_{attempt}.out")
    # Sidecar contract single-sourced in flight_recorder.child_sidecar_env;
    # attempt_after_s arms the post-devices_ok re-arm so execute-phase
    # hangs carry their own dying stack (child-clock seconds).
    fr_env, hb_path, stack_path = fr.child_sidecar_env(
        out_path, min(INIT_TIMEOUT_S, ATTEMPT_TIMEOUT_S),
        attempt_after_s=ATTEMPT_TIMEOUT_S,
    )
    child_env = dict(os.environ)
    hb_override = child_env.get("KPTPU_HEARTBEAT_S")
    child_env.update(fr_env)
    if hb_override is not None:
        child_env["KPTPU_HEARTBEAT_S"] = hb_override
    outf = open(out_path, "w+")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=outf,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
        env=child_env,
    )

    def read_so_far() -> str:
        outf.flush()
        with open(out_path) as fh:
            return fh.read()

    buf = ""
    devices_ok = False
    killed = False
    outcome = ""
    poll_s = max(0.2, min(5.0, INIT_TIMEOUT_S / 5.0))
    while True:
        elapsed = time.time() - t_start
        if proc.poll() is not None:
            buf = read_so_far()
            break
        buf = read_so_far()
        if '"devices_ok"' in buf:
            devices_ok = True
        if not devices_ok and elapsed > INIT_TIMEOUT_S:
            killed = True
            outcome = f"init_hang_killed_after_{elapsed:.0f}s"
            break
        if elapsed > ATTEMPT_TIMEOUT_S:
            killed = True
            outcome = f"attempt_killed_after_{elapsed:.0f}s"
            break
        time.sleep(poll_s)
    if proc.poll() is None:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        time.sleep(1.0)
        buf = read_so_far()
    outf.close()
    dossier = None
    if killed:
        try:
            dossier = fr.read_dossier(hb_path, stack_path)
        except Exception:  # noqa: BLE001 — forensics must not mask the kill
            dossier = None
        if dossier is not None:
            # Classify the hang by the phase the child died in: a child
            # that never printed devices_ok but heartbeats past
            # backend_init hung in compile/execute of the measurement, not
            # in init — the distinction the retry policy and `tools
            # doctor` histograms key on.
            cls = dossier.get("phase_class", "init")
            elapsed = time.time() - t_start
            outcome = f"{cls}_hang_killed_after_{elapsed:.0f}s"
    fr.cleanup_sidecars(hb_path, stack_path)
    try:
        os.remove(out_path)
    except OSError:
        pass
    recs = _salvage_lines(buf)
    probe = next((r for r in recs if "probe" in r), None)
    measures = [r for r in recs if "metric" in r]
    rc = proc.returncode
    if not outcome:
        outcome = {0: "measured", 3: "ambient_is_cpu", 4: "init_error"}.get(
            rc, f"child_rc_{rc}")
    # The telemetry summary (trace path / quality rows / HBM watermark) of a
    # measured attempt rides the per-attempt log record too, so the probe
    # log is self-contained evidence even when TPU_RESULT.json moves on.
    telemetry = next(
        (r.get("telemetry") for r in reversed(measures) if r.get("telemetry")),
        None,
    )
    log_rec = {
        "attempt": attempt,
        "t_start": round(t_start, 1),
        "elapsed_s": round(time.time() - t_start, 1),
        "outcome": outcome,
        "probe": probe,
    }
    if dossier is not None:
        log_rec["dossier"] = dossier
    if telemetry:
        log_rec["telemetry"] = {
            k: telemetry.get(k)
            for k in ("trace_path", "spans", "counter_samples",
                      "quality_rows", "hbm")
            if k in telemetry
        }
    _log(log_rec)
    if measures and outcome == "measured":
        # Headline = the XLA-path record; a same-window Pallas LP record is
        # attached as the A/B datum rather than replacing the headline.
        pallas = [r for r in measures if r.get("lp_kernel") == "pallas"]
        main = [r for r in measures if r.get("lp_kernel") != "pallas"]
        best = (main or measures)[-1]
        if pallas:
            best["pallas_lp"] = {
                key: pallas[-1].get(key)
                for key in ("value", "unit", "vs_baseline", "lp_compile",
                            "host_sync_count", "host_sync")
                if key in pallas[-1]
            }
        best["probe_attempt"] = attempt
        best["probe_init_s"] = (probe or {}).get("init_s")
        return best
    return None


def _last_outcome() -> str:
    """Outcome string of the newest attempt record in the log (the daemon
    reads its own log rather than re-plumbing run_attempt's return — the
    log is the source of truth the dossiers land in)."""
    try:
        with open(LOG_PATH) as fh:
            lines = fh.readlines()
    except OSError:
        return ""
    for line in reversed(lines):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if "attempt" in rec:
            return str(rec.get("outcome", ""))
    return ""


def retry_sleep_for(consecutive_hangs: int) -> float:
    """Bounded-exponential retry sleep (ISSUE 12 satellite): the base sleep
    until 3 consecutive killed-hang attempts, then doubling per further
    hang, capped at RETRY_SLEEP_MAX_S — evidence of a down-all-day tunnel
    stops burning 20-minute probes every 10 minutes."""
    if consecutive_hangs < 3:
        return RETRY_SLEEP_S
    return min(RETRY_SLEEP_S * (2 ** (consecutive_hangs - 2)),
               max(RETRY_SLEEP_MAX_S, RETRY_SLEEP_S))


def daemon_loop() -> None:
    t_daemon_start = time.time()
    deadline = t_daemon_start + DEADLINE_H * 3600
    _log({"event": "prober_start", "pid": os.getpid(),
          "init_timeout_s": INIT_TIMEOUT_S, "attempt_timeout_s": ATTEMPT_TIMEOUT_S,
          "retry_sleep_s": RETRY_SLEEP_S, "retry_sleep_max_s": RETRY_SLEEP_MAX_S,
          "deadline_h": DEADLINE_H})
    attempt = 0
    consecutive_hangs = 0
    while time.time() < deadline:
        attempt += 1
        try:
            rec = run_attempt(attempt)
        except Exception as exc:  # noqa: BLE001 — one bad attempt must never
            # kill the round-long daemon (it did, round 5 first launch).
            _log({"attempt": attempt,
                  "outcome": f"prober_error: {type(exc).__name__}: {exc}"[:300]})
            rec = None
        if rec is not None:
            try:
                sys.path.insert(0, REPO)
                from bench import _git_head

                rec["git_head"] = _git_head()
            except Exception:  # noqa: BLE001
                pass
            rec["stale_vs_head"] = False  # captured at head, this round
            with open(RESULT_PATH, "w") as fh:
                json.dump(rec, fh, indent=1)
            # Run ledger (round 13): an on-silicon capture is exactly the
            # entry the regression sentinel wants a window of.
            try:
                from kaminpar_tpu.telemetry import ledger

                ledger.record_run(
                    rec, kind="prober", git_head=rec.get("git_head", "")
                )
            except Exception:  # noqa: BLE001
                pass
            _log({"event": "prober_success", "attempt": attempt})
            return
        if (
            os.path.exists(RESULT_PATH)
            and os.path.getmtime(RESULT_PATH) >= t_daemon_start
        ):
            return  # someone else captured a result THIS round; a stale
            # artifact from an earlier round must not stop the daemon
        if "hang_killed" in _last_outcome():
            consecutive_hangs += 1
        else:
            consecutive_hangs = 0
        sleep_s = retry_sleep_for(consecutive_hangs)
        if sleep_s > RETRY_SLEEP_S:
            _log({"event": "retry_escalation", "attempt": attempt,
                  "consecutive_hangs": consecutive_hangs,
                  "sleep_s": round(sleep_s, 1)})
        time.sleep(min(sleep_s, max(0.0, deadline - time.time())))
    _log({"event": "prober_deadline", "attempts": attempt})


if __name__ == "__main__":
    if "--child" in sys.argv:
        child_attempt()
    else:
        daemon_loop()
