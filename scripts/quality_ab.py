#!/usr/bin/env python
"""A/B harness for the round-3 quality work (VERDICT r2 next-steps #2).

Generates the two gap fixtures (rgg64k deg-50, grid256), measures the
reference binary once (cached), then sweeps our coarsening levers in-process
(one JAX runtime, shared compile cache) and prints a per-variant cut table.

Usage: python scripts/quality_ab.py [--configs rgg64k,grid256] [--seeds 1,2,3]
       [--variants base,lightest,...] [--preset default]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_BIN = os.path.join(REPO, "build_ref", "apps", "KaMinPar")
DATA = os.path.join(REPO, "bench_data")

os.environ["JAX_PLATFORMS"] = "cpu"  # hard override: ambient env says axon
sys.path.insert(0, REPO)

# The axon site hook registers a TPU-tunnel platform whose backend init can
# hang; jax.devices("cpu") inside force_cpu_devices initializes ONLY the CPU
# platform (the proven recipe from conftest.py / round 2).
from kaminpar_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(1)


def fixtures():
    import numpy as np

    from kaminpar_tpu.graph.generators import grid2d_graph, rgg2d_graph, rmat_graph
    from kaminpar_tpu.io import write_metis

    os.makedirs(DATA, exist_ok=True)
    out = {}
    spec = {
        "rgg64k": lambda: rgg2d_graph(
            65536, radius=float(np.sqrt(50 / (np.pi * 65536))), seed=7
        ),
        "grid256": lambda: grid2d_graph(256, 256),
        "rgg4k": lambda: rgg2d_graph(
            4096, radius=float(np.sqrt(24 / (np.pi * 4096))), seed=7
        ),
        "rmat14": lambda: rmat_graph(14, edge_factor=14, seed=1),
    }
    for name, make in spec.items():
        path = os.path.join(DATA, f"{name}.metis")
        if not os.path.exists(path):
            g = make()
            write_metis(g, path)
            print(f"wrote {path} n={g.n} m={g.m}", file=sys.stderr)
        out[name] = path
    return out


def ref_cut(path: str, k: int, seed: int, preset: str = "default") -> int:
    cache = os.path.join(DATA, "ref_cache.json")
    db = {}
    if os.path.exists(cache):
        db = json.load(open(cache))
    key = f"{os.path.basename(path)}:{k}:{seed}:{preset}"
    if key not in db:
        out = subprocess.run(
            [REF_BIN, path, str(k), "-P", preset, f"--seed={seed}", "-t", "1"],
            capture_output=True, text=True, timeout=3600,
        )
        if out.returncode != 0:
            raise RuntimeError(f"ref failed: {out.stderr[-500:]}")
        db[key] = int(re.search(r"Edge cut:\s+(\d+)", out.stdout).group(1))
        json.dump(db, open(cache, "w"))
    return db[key]


VARIANTS = {
    "base": {},
    "lightest": {"tie": "lightest"},
    "overlay2": {"overlay": 2},
    "overlay3": {"overlay": 3},
    "light+ov2": {"tie": "lightest", "overlay": 2},
    "shrink2.5": {"shrink": 2.5},
    "shrink5": {"shrink": 5.0},
    "jetdef": {"jet": True},
    "light+jet": {"tie": "lightest", "jet": True},
    "ov2+jet": {"overlay": 2, "jet": True},
    "ov3+jet": {"overlay": 3, "jet": True},
    "iters10": {"lp_iters": 10},
    "ap75": {"active_prob": 0.75},
    "ov2+jet+it10": {"overlay": 2, "jet": True, "lp_iters": 10},
    "it10+ap75": {"lp_iters": 10, "active_prob": 0.75},
    "it10+jet": {"lp_iters": 10, "jet": True},
    "it15": {"lp_iters": 15},
    "it10+ov2": {"lp_iters": 10, "overlay": 2},
    "ov2+ap75": {"overlay": 2, "active_prob": 0.75},
    "it15+ap75": {"lp_iters": 15, "active_prob": 0.75},
    "ap60": {"active_prob": 0.6},
    "noboost": {"boost_factor": 1},
    "extreps2": {"ext_reps": 2},
    "extreps3": {"ext_reps": 3},
}


def our_cut(path: str, k: int, seed: int, variant: dict, preset: str) -> tuple:
    from kaminpar_tpu.context import RefinementAlgorithm, TieBreakingStrategy
    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.io import read_metis
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name(preset)
    ctx.seed = seed
    if variant.get("tie"):
        ctx.coarsening.lp.tie_breaking = TieBreakingStrategy(variant["tie"])
    if variant.get("overlay"):
        ctx.coarsening.overlay_levels = variant["overlay"]
    if variant.get("shrink"):
        ctx.coarsening.max_shrink_factor = variant["shrink"]
    if variant.get("lp_iters"):
        ctx.coarsening.lp.num_iterations = variant["lp_iters"]
    if variant.get("active_prob"):
        ctx.coarsening.lp.active_prob = variant["active_prob"]
    if variant.get("boost_factor") is not None:
        ctx.coarsening.lp.low_degree_boost_factor = variant["boost_factor"]
    if variant.get("ext_reps"):
        ctx.initial_partitioning.nested_extension_reps = variant["ext_reps"]
    if variant.get("jet") and RefinementAlgorithm.JET not in ctx.refinement.algorithms:
        algs = list(ctx.refinement.algorithms)
        algs.insert(
            algs.index(RefinementAlgorithm.LP) + 1
            if RefinementAlgorithm.LP in algs else len(algs),
            RefinementAlgorithm.JET,
        )
        ctx.refinement.algorithms = tuple(algs)
    g = read_metis(path)
    solver = KaMinPar(ctx)
    solver.set_graph(g)
    t0 = time.perf_counter()
    part = solver.compute_partition(k, epsilon=0.03)
    wall = time.perf_counter() - t0
    return int(metrics.edge_cut(g, part)), wall


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="rgg64k:64,grid256:64")
    ap.add_argument("--seeds", default="1,2,3")
    ap.add_argument("--variants", default="base,lightest,overlay2,light+ov2")
    ap.add_argument("--preset", default="default")
    args = ap.parse_args()

    paths = fixtures()
    seeds = [int(s) for s in args.seeds.split(",")]
    configs = []
    for c in args.configs.split(","):
        name, k = c.split(":")
        configs.append((name, int(k)))

    for name, k in configs:
        refs = [ref_cut(paths[name], k, s) for s in seeds]
        ref_mean = sum(refs) / len(refs)
        print(f"== {name} k={k}: ref mean {ref_mean:.0f} (seeds {refs})", flush=True)
        for vname in args.variants.split(","):
            variant = VARIANTS[vname]
            cuts, walls = [], []
            # Each variant recompiles the static-arg kernels; dropping the
            # old executables keeps the process under vm.max_map_count
            # (LLVM's JIT mmaps per executable; 65530 maps ~= 2 variants).
            import jax

            jax.clear_caches()
            for s in seeds:
                c, w = our_cut(paths[name], k, s, variant, args.preset)
                cuts.append(c)
                walls.append(w)
            mean = sum(cuts) / len(cuts)
            print(
                f"  {vname:12s} mean {mean:8.0f} ratio {mean / ref_mean:5.2f} "
                f"spread [{min(cuts)},{max(cuts)}] wall {sum(walls)/len(walls):6.1f}s",
                flush=True,
            )


if __name__ == "__main__":
    main()
