#!/usr/bin/env python
"""Fast road-class lever loop (VERDICT r3 next #1).

road512 (512^2 weighted grid, k=64) is the recorded target but costs ~5-10
min/run on this box; road256 (256^2, k=64) reproduces the weighted-low-degree
class at ~1/4 the cost for lever iteration.  Each run happens in a fresh
subprocess (XLA:CPU JIT code memory is a finite contiguous region; hundreds
of kernel compiles in one process exhaust it — see QUALITY_NOTES).

Usage:
  python scripts/road_levers.py --side 256 --seeds 1,2,3 --preset eco \
      [--ref] [--lever name=value ...]

Levers are forwarded to the child via KPTPU_LEVER_* env vars; the child
applies them to the context after preset construction (see _apply_levers).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_BIN = os.path.join(REPO, "build_ref", "apps", "KaMinPar")
DATA = os.path.join(REPO, "bench_data")


def fixture(side: int) -> str:
    sys.path.insert(0, REPO)
    import numpy as np

    from kaminpar_tpu.graph.csr import CSRGraph
    from kaminpar_tpu.graph.generators import grid2d_graph
    from kaminpar_tpu.io import write_metis

    os.makedirs(DATA, exist_ok=True)
    path = os.path.join(DATA, f"road{side}.metis")
    if not os.path.exists(path):
        g0 = grid2d_graph(side, side)
        rp = np.asarray(g0.row_ptr)
        col = np.asarray(g0.col_idx).astype(np.int64)
        u = np.repeat(np.arange(g0.n, dtype=np.int64), np.diff(rp))
        key = np.minimum(u, col) * g0.n + np.maximum(u, col)
        ew = (key * 2654435761 % 9 + 1).astype(np.int32)
        g = CSRGraph(g0.row_ptr, g0.col_idx, None, ew)
        write_metis(g, path)
        print(f"wrote {path} n={g.n} m={g.m}", file=sys.stderr)
    return path


_CHILD = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
from kaminpar_tpu.utils.platform import force_cpu_devices
force_cpu_devices(1)
import numpy as np
from kaminpar_tpu.graph import metrics
from kaminpar_tpu.io import read_metis
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.presets import create_context_by_preset_name

ctx = create_context_by_preset_name({preset!r})
ctx.seed = {seed}
for kv in {levers!r}:
    name, val = kv.split("=", 1)
    obj = ctx
    parts = name.split(".")
    for p in parts[:-1]:
        obj = getattr(obj, p)
    cur = getattr(obj, parts[-1])
    typ = type(cur)
    if typ is bool:
        val = val in ("1", "true", "True")
    else:
        val = typ(val)
    setattr(obj, parts[-1], val)
g = read_metis({path!r})
s = KaMinPar(ctx)
s.set_graph(g)
t0 = time.perf_counter()
part = s.compute_partition({k}, epsilon=0.03)
wall = time.perf_counter() - t0
print("CHILD_RESULT", int(metrics.edge_cut(g, part)), f"{{wall:.1f}}")
"""


def run_ours(path: str, k: int, seed: int, preset: str, levers) -> tuple[int, float]:
    code = _CHILD.format(repo=REPO, preset=preset, seed=seed, levers=list(levers), path=path, k=k)
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=7200,
    )
    for line in out.stdout.splitlines():
        if line.startswith("CHILD_RESULT"):
            _, cut, wall = line.split()
            return int(cut), float(wall)
    raise RuntimeError(f"child failed: {out.stderr[-400:]}")


def run_ref(path: str, k: int, seed: int, preset: str) -> tuple[int, float]:
    t0 = time.perf_counter()
    out = subprocess.run(
        [REF_BIN, path, str(k), "-P", preset, f"--seed={seed}", "-t", "1"],
        capture_output=True, text=True, timeout=7200,
    )
    wall = time.perf_counter() - t0
    if out.returncode != 0:
        raise RuntimeError(f"ref {preset} failed: {out.stderr[-300:]}")
    return int(re.search(r"Edge cut:\s+(\d+)", out.stdout).group(1)), wall


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--side", type=int, default=256)
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--seeds", default="1,2,3")
    ap.add_argument("--preset", default="eco")
    ap.add_argument("--ref", action="store_true")
    ap.add_argument("--lever", action="append", default=[])
    ap.add_argument("--tag", default="")
    args = ap.parse_args()
    path = fixture(args.side)
    seeds = [int(s) for s in args.seeds.split(",")]

    if args.ref:
        cuts, walls = zip(*(run_ref(path, args.k, s, args.preset) for s in seeds))
        print(f"ref  {args.preset:7s} mean {sum(cuts)/len(cuts):9.0f} cuts {list(cuts)} "
              f"wall {sum(walls)/len(walls):6.1f}s", flush=True)

    cuts, walls = zip(*(run_ours(path, args.k, s, args.preset, args.lever) for s in seeds))
    tag = args.tag or ",".join(args.lever) or "base"
    print(f"ours {args.preset:7s} [{tag}] mean {sum(cuts)/len(cuts):9.0f} cuts {list(cuts)} "
          f"wall {sum(walls)/len(walls):6.1f}s", flush=True)


if __name__ == "__main__":
    main()
