#!/usr/bin/env python
"""On-silicon microprofile of the LP hot path (run while the tunnel is up).

Separates the three candidate bottlenecks for the weak r5 TPU number
(12.7M e/s, hbm_frac 2e-4):
  * per-dispatch tunnel latency  — trivial jitted op, warm, timed solo
  * transfer bandwidth           — H2D/D2H of a 256 MiB buffer
  * device compute               — lp_round_bucketed at several scales
    (flat per-round time => latency-bound; linear in m => compute-bound),
    plus isolated primitives (row sort, segment_sum, gather) at scale-20
    shapes to name the slow one.

Prints one JSON line per measurement; exit fast and leave the tunnel as we
found it.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(**kw):
    print(json.dumps({k: (round(v, 6) if isinstance(v, float) else v)
                      for k, v in kw.items()}), flush=True)


def main() -> None:
    t0 = time.perf_counter()
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    emit(event="init", platform=dev.platform, init_s=time.perf_counter() - t0)

    # -- dispatch latency --------------------------------------------------
    @jax.jit
    def triv(x):
        return x + 1

    x = jnp.zeros((8,), jnp.int32)
    int(triv(x)[0])  # compile + sync
    for _ in range(3):
        t = time.perf_counter()
        int(triv(x)[0])
        emit(event="dispatch_rtt", seconds=time.perf_counter() - t)

    # -- transfer bandwidth ------------------------------------------------
    import numpy as np

    buf = np.zeros(64 * 1024 * 1024, np.int32)  # 256 MiB
    t = time.perf_counter()
    dbuf = jax.device_put(buf)
    dbuf.block_until_ready()
    h2d = time.perf_counter() - t
    t = time.perf_counter()
    _ = np.asarray(dbuf)
    d2h = time.perf_counter() - t
    emit(event="transfer", h2d_gbps=0.25 / max(h2d, 1e-9),
         d2h_gbps=0.25 / max(d2h, 1e-9), h2d_s=h2d, d2h_s=d2h)
    del dbuf, buf

    # -- primitive compute at scale-20-ish shapes -------------------------
    key = jax.random.PRNGKey(0)
    for name, shape, fn in [
        ("row_sort_64", (1 << 19, 64),
         lambda a: jax.lax.sort(a, dimension=1)),
        ("segment_sum_32m", (1 << 25,),
         lambda a: jax.ops.segment_sum(a, jnp.abs(a) % (1 << 20),
                                       num_segments=1 << 20)),
        ("gather_32m", (1 << 25,),
         lambda a: a[jnp.abs(a) % (1 << 25)]),
        ("sort1d_4m", (1 << 22,), lambda a: jax.lax.sort(a)),
    ]:
        a = jax.random.randint(key, shape, 0, 1 << 20, jnp.int32)
        f = jax.jit(fn)
        r = f(a)
        jax.tree_util.tree_leaves(r)[0].block_until_ready()
        t = time.perf_counter()
        for _ in range(3):
            r = f(a)
        jax.tree_util.tree_leaves(r)[0].block_until_ready()
        emit(event="primitive", name=name,
             seconds_per_call=(time.perf_counter() - t) / 3)
        del a, r

    # -- LP round scaling --------------------------------------------------
    from kaminpar_tpu.coarsening.max_cluster_weights import (
        compute_max_cluster_weight,
    )
    from kaminpar_tpu.context import Context
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.ops import lp
    from kaminpar_tpu.utils import RandomState, next_key

    for scale in (16, 18, 20):
        RandomState.reseed(0)
        t = time.perf_counter()
        graph = rmat_graph(scale, edge_factor=16, seed=1)
        gen_s = time.perf_counter() - t
        pv = graph.padded()
        bv = graph.bucketed()
        ctx = Context()
        max_cw = compute_max_cluster_weight(
            ctx.coarsening, graph.n, graph.total_node_weight, 16, 0.03
        )
        idt = pv.row_ptr.dtype
        labels = jnp.concatenate(
            [jnp.arange(pv.n, dtype=idt),
             jnp.full(pv.n_pad - pv.n, pv.anchor, dtype=idt)]
        )
        state = lp.init_state(labels, pv.node_w, pv.n_pad)
        max_w = jnp.asarray(max_cw, dtype=idt)

        def one(state):
            return lp.lp_round_bucketed(
                state, next_key(), bv.buckets, bv.heavy, bv.gather_idx,
                pv.node_w, max_w, num_labels=pv.n_pad,
            )

        t = time.perf_counter()
        state = one(state)
        int(state.num_moved)
        compile_s = time.perf_counter() - t
        times = []
        for _ in range(3):
            t = time.perf_counter()
            state = one(state)
            int(state.num_moved)
            times.append(time.perf_counter() - t)
        emit(event="lp_round", scale=scale, m=graph.m, gen_s=gen_s,
             compile_plus_first_s=compile_s, round_s=min(times),
             edges_per_sec=graph.m / min(times),
             num_buckets=len(bv.buckets))
        del graph, pv, bv, state


if __name__ == "__main__":
    main()
