#!/usr/bin/env python
"""Characterize the host FM pass at the max_n gate (VERDICT r4 next #7).

Measures ONE localized-FM refinement pass (including the device->host
transfer of graph + partition) at n = 1M and n = 8M, k = 64, on this box —
the data behind the ``fm.max_n`` default (context.py FMContext).  The only
prior anchor was ~1 s at n = 65k (DIVERGENCES #3); naive scaling predicted
minutes at the 2^23 gate, unmeasured until now.

Writes a QUALITY_NOTES-ready JSON line per scale to
``bench_data/fm_characterization.jsonl``.

Usage: python scripts/fm_characterize.py [--scales 20,23] [--k 64]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, REPO)

from kaminpar_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(1)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scales", default="20,23")
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--edge-factor", type=int, default=8)
    args = ap.parse_args()

    import numpy as np

    from kaminpar_tpu.context import Context
    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.graph.partitioned import PartitionedGraph
    from kaminpar_tpu.refinement.fm_refiner import FMRefiner
    from kaminpar_tpu.utils import RandomState

    out_path = os.path.join(REPO, "bench_data", "fm_characterization.jsonl")
    k = args.k
    for scale in (int(s) for s in args.scales.split(",")):
        RandomState.reseed(1)
        t0 = time.perf_counter()
        g = rmat_graph(scale, edge_factor=args.edge_factor, seed=1)
        gen_s = time.perf_counter() - t0
        # A plausible mid-refinement partition: balanced stripes + one LP
        # sweep would be fairer but slower; stripes already produce a busy
        # border, which is what the pass cost scales with.
        part = (np.arange(g.n) * k // max(g.n, 1)).astype(np.int32)
        W = int(g.total_node_weight)
        max_bw = np.full(k, int(np.ceil(W / k) * 1.05) + 64, dtype=np.int64)
        pg = PartitionedGraph.create(g, k, part, max_bw)
        cut0 = pg.edge_cut()

        ctx = Context()
        ctx.refinement.fm.max_n = 1 << 24  # open the gate for measurement
        refiner = FMRefiner(ctx.refinement.fm)
        t0 = time.perf_counter()
        out = refiner.refine(pg)
        pass_s = time.perf_counter() - t0
        cut1 = out.edge_cut()
        rec = {
            "scale": scale, "n": g.n, "m": g.m, "k": k,
            "gen_s": round(gen_s, 1),
            "fm_pass_s": round(pass_s, 1),
            "cut_before": int(cut0), "cut_after": int(cut1),
            "improvement_pct": round(100 * (1 - cut1 / max(cut0, 1)), 2),
        }
        print(json.dumps(rec), flush=True)
        with open(out_path, "a") as fh:
            fh.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
