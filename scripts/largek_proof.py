#!/usr/bin/env python
"""largek proof point (VERDICT r2 next-steps #9).

Runs k=4096 on a ~1M-node graph with the largek preset, prints the RESULT
line and the timer tree so the extension cost is visible (the reference's
flagship largek story is k=30 000, README.MD:16; largek presets tune
contraction_limit=640, presets.cc).

Usage: python scripts/largek_proof.py [--scale 20] [--k 4096] [--preset largek]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, REPO)

from kaminpar_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(1)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=20)
    ap.add_argument("--k", type=int, default=4096)
    ap.add_argument("--preset", default="largek")
    ap.add_argument("--edge-factor", type=int, default=8)
    ap.add_argument("--device-ext", action="store_true",
                    help="enable the batched device-side extension path")
    args = ap.parse_args()

    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.utils import Logger, OutputLevel, Timer

    Logger.level = OutputLevel.EXPERIMENT
    t0 = time.perf_counter()
    g = rmat_graph(args.scale, edge_factor=args.edge_factor, seed=1)
    print(f"generated n={g.n} m={g.m} in {time.perf_counter()-t0:.1f}s",
          file=sys.stderr, flush=True)

    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name(args.preset)
    if args.device_ext:
        ctx.initial_partitioning.device_extension = True
    s = KaMinPar(ctx)
    s.set_graph(g)
    t0 = time.perf_counter()
    part = s.compute_partition(args.k, epsilon=0.03)
    wall = time.perf_counter() - t0

    cut = int(metrics.edge_cut(g, part))
    feas = metrics.is_feasible(g, part, args.k, s.ctx.partition.max_block_weights)
    tree = Timer.global_().machine_readable()
    print(tree, flush=True)
    # host-extension share of wall (VERDICT r4 missing #4 done-criterion)
    ext_s = sum(
        float(kv.split("=")[1])
        for kv in tree.split()
        if kv.startswith("partitioning.extend_partition=")
    )
    rec = {
        "config": f"rmat{args.scale} k={args.k} preset={args.preset}",
        "cut": cut, "feasible": bool(feas), "wall_s": round(wall, 1),
        "extend_partition_s": round(ext_s, 1),
        "extend_share": round(ext_s / max(wall, 1e-9), 3),
        "device_extension": bool(args.device_ext),
    }
    print(json.dumps(rec), flush=True)
    suffix = "_devext" if args.device_ext else ""
    out = os.path.join(
        REPO, "bench_data", f"largek_{args.scale}_{args.k}{suffix}.json"
    )
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump({"result": rec, "timer": tree}, f, indent=2)


if __name__ == "__main__":
    main()
