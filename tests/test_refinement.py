"""Refiner tests: LP refiner, overload balancer, JET (reference tier 2/3)."""

import numpy as np

from kaminpar_tpu.context import BalancerContext, JetContext, LabelPropagationContext
from kaminpar_tpu.graph import generators, metrics
from kaminpar_tpu.graph.partitioned import PartitionedGraph
from kaminpar_tpu.refinement.balancer import OverloadBalancer
from kaminpar_tpu.refinement.jet import JetRefiner
from kaminpar_tpu.refinement.lp_refiner import LPRefiner


def _grid_pgraph(k=2, noise=0.2, seed=0):
    g = generators.grid2d_graph(8, 8)
    rng = np.random.default_rng(seed)
    # stripes partition + noise
    part = (np.arange(64) // (64 // k)).clip(0, k - 1).astype(np.int32)
    flip = rng.random(64) < noise
    part[flip] = rng.integers(0, k, flip.sum())
    per = int(np.ceil(64 / k) * 1.1) + 1
    return PartitionedGraph.create(g, k, part, np.full(k, per, dtype=np.int64))


def test_lp_refiner_improves_cut():
    pg = _grid_pgraph(k=2, noise=0.3)
    before = pg.edge_cut()
    refined = LPRefiner(LabelPropagationContext(num_iterations=8)).refine(pg)
    assert refined.edge_cut() < before
    assert refined.is_feasible()


def test_lp_refiner_keeps_feasibility():
    pg = _grid_pgraph(k=4, noise=0.2)
    refined = LPRefiner(LabelPropagationContext()).refine(pg)
    assert refined.is_feasible()


def test_balancer_fixes_overload():
    g = generators.grid2d_graph(8, 8)
    part = np.zeros(64, dtype=np.int32)  # everything in block 0: max overload
    pg = PartitionedGraph.create(g, 4, part, np.full(4, 20, dtype=np.int64))
    assert not pg.is_feasible()
    balanced = OverloadBalancer(BalancerContext()).refine(pg)
    assert balanced.is_feasible()


def test_balancer_noop_when_feasible():
    pg = _grid_pgraph(k=2, noise=0.0)
    balanced = OverloadBalancer(BalancerContext()).refine(pg)
    assert np.array_equal(np.asarray(balanced.partition), np.asarray(pg.partition))


def test_jet_improves_cut():
    pg = _grid_pgraph(k=2, noise=0.3, seed=5)
    before = pg.edge_cut()
    jet = JetRefiner(JetContext(num_iterations=6), BalancerContext())
    refined = jet.refine(pg)
    assert refined.edge_cut() <= before
    assert refined.is_feasible()


def test_jet_on_rmat():
    g = generators.rmat_graph(8, 8, seed=3)
    rng = np.random.default_rng(2)
    part = rng.integers(0, 4, g.n).astype(np.int32)
    per = int(np.ceil(g.total_node_weight / 4) * 1.1) + 1
    pg = PartitionedGraph.create(g, 4, part, np.full(4, per, dtype=np.int64))
    before = pg.edge_cut()
    refined = JetRefiner(JetContext(num_iterations=8), BalancerContext()).refine(pg)
    assert refined.edge_cut() < before
    assert refined.is_feasible()
