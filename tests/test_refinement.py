"""Refiner tests: LP refiner, overload balancer, JET (reference tier 2/3)."""

import numpy as np

from kaminpar_tpu.context import BalancerContext, JetContext, LabelPropagationContext
from kaminpar_tpu.graph import generators, metrics
from kaminpar_tpu.graph.partitioned import PartitionedGraph
from kaminpar_tpu.refinement.balancer import OverloadBalancer
from kaminpar_tpu.refinement.jet import JetRefiner
from kaminpar_tpu.refinement.lp_refiner import LPRefiner


def _grid_pgraph(k=2, noise=0.2, seed=0):
    g = generators.grid2d_graph(8, 8)
    rng = np.random.default_rng(seed)
    # stripes partition + noise
    part = (np.arange(64) // (64 // k)).clip(0, k - 1).astype(np.int32)
    flip = rng.random(64) < noise
    part[flip] = rng.integers(0, k, flip.sum())
    per = int(np.ceil(64 / k) * 1.1) + 1
    return PartitionedGraph.create(g, k, part, np.full(k, per, dtype=np.int64))


def test_lp_refiner_improves_cut():
    pg = _grid_pgraph(k=2, noise=0.3)
    before = pg.edge_cut()
    refined = LPRefiner(LabelPropagationContext(num_iterations=8)).refine(pg)
    assert refined.edge_cut() < before
    assert refined.is_feasible()


def test_lp_refiner_keeps_feasibility():
    pg = _grid_pgraph(k=4, noise=0.2)
    refined = LPRefiner(LabelPropagationContext()).refine(pg)
    assert refined.is_feasible()


def test_balancer_fixes_overload():
    g = generators.grid2d_graph(8, 8)
    part = np.zeros(64, dtype=np.int32)  # everything in block 0: max overload
    pg = PartitionedGraph.create(g, 4, part, np.full(4, 20, dtype=np.int64))
    assert not pg.is_feasible()
    balanced = OverloadBalancer(BalancerContext()).refine(pg)
    assert balanced.is_feasible()


def test_balancer_noop_when_feasible():
    pg = _grid_pgraph(k=2, noise=0.0)
    balanced = OverloadBalancer(BalancerContext()).refine(pg)
    assert np.array_equal(np.asarray(balanced.partition), np.asarray(pg.partition))


def test_jet_improves_cut():
    pg = _grid_pgraph(k=2, noise=0.3, seed=5)
    before = pg.edge_cut()
    jet = JetRefiner(JetContext(num_iterations=6), BalancerContext())
    refined = jet.refine(pg)
    assert refined.edge_cut() <= before
    assert refined.is_feasible()


def test_jet_on_rmat():
    g = generators.rmat_graph(8, 8, seed=3)
    rng = np.random.default_rng(2)
    part = rng.integers(0, 4, g.n).astype(np.int32)
    per = int(np.ceil(g.total_node_weight / 4) * 1.1) + 1
    pg = PartitionedGraph.create(g, 4, part, np.full(4, per, dtype=np.int64))
    before = pg.edge_cut()
    refined = JetRefiner(JetContext(num_iterations=8), BalancerContext()).refine(pg)
    assert refined.edge_cut() < before
    assert refined.is_feasible()


def test_underload_balancer_fills_empty_blocks():
    """Reference: underload_balancer.cc — pull weight into blocks below
    their minimum, without dropping donors below theirs."""
    from kaminpar_tpu.refinement.balancer import UnderloadBalancer

    g = generators.grid2d_graph(8, 8)
    part = np.zeros(64, dtype=np.int32)  # blocks 1..3 empty
    pg = PartitionedGraph.create(
        g, 4, part,
        np.full(4, 64, dtype=np.int64),  # max: no overload constraint
        np.full(4, 12, dtype=np.int64),  # min: every block needs >= 12
    )
    assert not pg.is_min_feasible()
    balanced = UnderloadBalancer(BalancerContext()).refine(pg)
    assert balanced.is_min_feasible()
    assert balanced.is_feasible()


def test_underload_balancer_noop_without_min_weights():
    from kaminpar_tpu.refinement.balancer import UnderloadBalancer

    pg = _grid_pgraph(k=4, noise=0.1)
    out = UnderloadBalancer(BalancerContext()).refine(pg)
    assert out is pg


def test_underload_balancer_respects_donor_minimums():
    from kaminpar_tpu.refinement.balancer import UnderloadBalancer

    g = generators.grid2d_graph(8, 8)
    # block 0 has 40 nodes, block 1 has 24, block 2 empty; min 16 each
    part = np.zeros(64, dtype=np.int32)
    part[40:] = 1
    pg = PartitionedGraph.create(
        g, 3, part,
        np.full(3, 64, dtype=np.int64),
        np.full(3, 16, dtype=np.int64),
    )
    balanced = UnderloadBalancer(BalancerContext()).refine(pg)
    bw = np.asarray(balanced.block_weights())
    assert (bw >= 16).all(), bw


def test_facade_min_epsilon_end_to_end():
    """CLI/facade path: min_epsilon populates min block weights and the
    default chain's underload balancer enforces them."""
    from kaminpar_tpu.kaminpar import KaMinPar

    g = generators.rgg2d_graph(1024, seed=3)
    s = KaMinPar("default")
    s.set_graph(g)
    part = s.compute_partition(k=4, epsilon=0.10, min_epsilon=0.10)
    bw = np.bincount(part, weights=np.asarray(g.node_w), minlength=4)
    perfect = -(-int(np.asarray(g.node_w).sum()) // 4)
    assert (bw >= np.ceil(0.9 * perfect)).all(), bw


def test_underload_balancer_many_empty_blocks():
    """Review finding: with many empty (no-adjacent-node) deficit blocks the
    fallback must spread movers across all of them, not one per round."""
    from kaminpar_tpu.refinement.balancer import UnderloadBalancer

    g = generators.grid2d_graph(16, 16)  # 256 nodes
    part = np.zeros(256, dtype=np.int32)  # blocks 1..9 empty
    pg = PartitionedGraph.create(
        g, 10, part,
        np.full(10, 256, dtype=np.int64),
        np.full(10, 20, dtype=np.int64),
    )
    balanced = UnderloadBalancer(BalancerContext()).refine(pg)
    bw = np.asarray(balanced.block_weights())
    assert (bw >= 20).all(), bw


def test_rb_mode_enforces_min_weights():
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name
    from kaminpar_tpu.context import PartitioningMode

    ctx = create_context_by_preset_name("default")
    ctx.mode = PartitioningMode.RB
    g = generators.rgg2d_graph(512, seed=5)
    s = KaMinPar(ctx)
    s.set_graph(g)
    part = s.compute_partition(k=4, epsilon=0.10, min_epsilon=0.15)
    bw = np.bincount(part, weights=np.asarray(g.node_w), minlength=4)
    perfect = -(-int(np.asarray(g.node_w).sum()) // 4)
    assert (bw >= np.ceil(0.85 * perfect)).all(), bw
