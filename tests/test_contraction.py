"""Contraction kernel tests (reference tier 2: tests/shm cluster contraction
tests)."""

import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.graph import from_edge_list, generators, validate
from kaminpar_tpu.ops.contraction import contract_clustering, project_partition


def _pad_labels(g, labels):
    pv = g.padded()
    idt = pv.row_ptr.dtype
    return jnp.concatenate(
        [jnp.asarray(labels, dtype=idt), jnp.full(pv.n_pad - pv.n, pv.anchor, dtype=idt)]
    )


def test_contract_path_pairs():
    g = generators.path_graph(6)  # 0-1-2-3-4-5
    labels = np.array([0, 0, 2, 2, 4, 4])
    coarse, coarse_of = contract_clustering(g, _pad_labels(g, labels))
    validate(coarse)
    assert coarse.n == 3
    assert coarse.m == 4  # path of 3 nodes
    assert coarse.total_node_weight == 6
    cw = np.asarray(coarse.node_w)
    assert (cw == 2).all()


def test_contract_weights_aggregate():
    # triangle with two nodes merged -> parallel edges sum
    g = from_edge_list(3, np.array([[0, 1], [1, 2], [0, 2]]))
    labels = np.array([0, 0, 2])
    coarse, _ = contract_clustering(g, _pad_labels(g, labels))
    validate(coarse)
    assert coarse.n == 2
    assert coarse.m == 2
    # edges (0,2) and (1,2) merge into one coarse edge of weight 2
    assert np.asarray(coarse.edge_w).max() == 2


def test_contract_all_one_cluster():
    g = generators.complete_graph(5)
    labels = np.zeros(5, dtype=np.int64)
    coarse, _ = contract_clustering(g, _pad_labels(g, labels))
    assert coarse.n == 1
    assert coarse.m == 0
    assert coarse.total_node_weight == 5


def test_projection_roundtrip():
    g = generators.grid2d_graph(4, 4)
    labels = np.asarray(g.col_idx)[np.asarray(g.row_ptr)[:-1]]  # first neighbor
    labels = np.minimum(labels, np.arange(16))
    coarse, coarse_of = contract_clustering(g, _pad_labels(g, labels))
    part_c = np.arange(coarse.n, dtype=np.int32) % 2
    part_f = np.asarray(project_partition(coarse_of, jnp.asarray(part_c)))
    assert part_f.shape == (16,)
    # nodes in the same cluster share the projected block
    cf = np.asarray(coarse_of)
    for u in range(16):
        assert part_f[u] == part_c[cf[u]]


def test_contract_preserves_cut_weight():
    """Total coarse edge weight = fine cut weight between clusters."""
    g = generators.rmat_graph(8, 6, seed=7)
    rng = np.random.default_rng(1)
    labels = rng.integers(0, 40, g.n)
    coarse, _ = contract_clustering(g, _pad_labels(g, labels))
    validate(coarse)
    u = np.asarray(g.edge_u)
    v = np.asarray(g.col_idx)
    w = np.asarray(g.edge_w)
    inter = labels[u] != labels[v]
    assert np.asarray(coarse.edge_w).sum() == w[inter].sum()
    assert coarse.total_node_weight == g.total_node_weight


def test_contract_zero_degree_coarse_nodes():
    """Clusters whose every edge is internal become zero-degree coarse
    nodes; their rows must exist with matching row_ptr entries."""
    # two disjoint triangles + one isolated node; each triangle a cluster
    edges = np.array([[0, 1], [1, 2], [0, 2], [3, 4], [4, 5], [3, 5]])
    g = from_edge_list(7, edges)
    labels = np.array([0, 0, 0, 3, 3, 3, 6])
    coarse, coarse_of = contract_clustering(g, _pad_labels(g, labels))
    validate(coarse)
    assert coarse.n == 3
    assert coarse.m == 0  # all edges intra-cluster
    assert np.asarray(coarse.row_ptr).tolist() == [0, 0, 0, 0]
    assert np.asarray(coarse.node_w).tolist() == [3, 3, 1]
    assert coarse.total_node_weight == 7
    assert coarse.max_node_weight == 3
    assert coarse.total_edge_weight == 0


def test_contract_single_cluster_level_metadata():
    """All-edges-dropped (single-cluster) level: the padded view and the
    seeded metadata stay consistent."""
    g = generators.complete_graph(6)
    labels = np.zeros(6, dtype=np.int64)
    coarse, _ = contract_clustering(g, _pad_labels(g, labels))
    assert coarse.n == 1 and coarse.m == 0
    assert coarse.max_node_weight == 6
    assert coarse.total_edge_weight == 0
    pv = coarse.padded()
    assert pv.n == 1 and pv.m == 0
    # pure-padding region: zero weights, anchor self-loop cols
    assert np.asarray(pv.node_w)[1:].sum() == 0
    assert (np.asarray(pv.col_idx) == pv.anchor).all()
    assert np.asarray(pv.edge_w).sum() == 0


def test_contract_padded_view_anchor_slicing():
    """The seeded coarse PaddedView must match what csr.padded() would
    build from the sliced arrays (the pure-padding anchor cluster is
    sliced off, pad rows collapse onto m_c, pad edges are weight-0 anchor
    self-loops)."""
    from kaminpar_tpu.graph.csr import CSRGraph

    g = generators.rmat_graph(9, 8, seed=11)
    rng = np.random.default_rng(4)
    labels = rng.integers(0, 60, g.n)
    coarse, _ = contract_clustering(g, _pad_labels(g, labels))
    assert coarse._padded is not None  # seeded, not rebuilt
    rebuilt = CSRGraph(
        np.asarray(coarse.row_ptr), np.asarray(coarse.col_idx),
        np.asarray(coarse.node_w), np.asarray(coarse.edge_w),
    ).padded()
    seeded = coarse.padded()
    assert seeded.n == rebuilt.n and seeded.m == rebuilt.m
    for name in ("row_ptr", "col_idx", "node_w", "edge_w", "edge_u"):
        assert np.array_equal(
            np.asarray(getattr(seeded, name)), np.asarray(getattr(rebuilt, name))
        ), name


def test_fused_sort_matches_lexsort():
    """The fused single-key edge sort is permutation-identical to the
    two-key lexsort (both stable), so coarse graphs are bit-identical."""
    import jax

    from kaminpar_tpu.ops import contraction as C

    rng = np.random.default_rng(7)
    n = 500
    ku = jnp.asarray(rng.integers(0, n + 1, 4096).astype(np.int32))
    kv = jnp.asarray(rng.integers(0, n, 4096).astype(np.int32))
    fused = C._edge_sort_perm(ku, kv, n)  # n small: fused path
    ref = jnp.lexsort((kv, ku))
    assert np.array_equal(np.asarray(fused), np.asarray(ref))

    # whole-kernel check: force the lexsort path and compare coarse graphs
    g = generators.rmat_graph(9, 8, seed=13)
    labels = rng.integers(0, 80, g.n)
    coarse_fused, of_fused = contract_clustering(g, _pad_labels(g, labels))
    orig = C._edge_sort_perm
    C._edge_sort_perm = lambda ku, kv, sentinel: jnp.lexsort((kv, ku))
    try:
        jax.clear_caches()  # _contract_device already traced the fused path
        coarse_lex, of_lex = contract_clustering(g, _pad_labels(g, labels))
    finally:
        C._edge_sort_perm = orig
        jax.clear_caches()
    assert coarse_fused.n == coarse_lex.n and coarse_fused.m == coarse_lex.m
    for attr in ("row_ptr", "col_idx", "node_w", "edge_w", "edge_u"):
        assert np.array_equal(
            np.asarray(getattr(coarse_fused, attr)),
            np.asarray(getattr(coarse_lex, attr)),
        ), attr
    assert np.array_equal(np.asarray(of_fused), np.asarray(of_lex))


def test_local_contraction_matches_global():
    """contract_local_clustering (local_contraction.cc role) must produce
    the SAME coarse graph as the global path for a shard-local clustering
    (both compact ids as per-owner-range ranks + exscan over shards)."""
    import jax
    import jax.numpy as jnp
    import pytest
    from jax.sharding import Mesh

    from kaminpar_tpu.dist.contraction import (
        contract_dist_clustering, contract_local_clustering,
    )
    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.dist.lp import dist_local_cluster_iterate, shard_arrays
    from kaminpar_tpu.graph import generators

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("need 8 devices")
    mesh = Mesh(np.array(devs[:8]), ("nodes",))

    g = generators.rmat_graph(10, 8, seed=5)
    dg = distribute_graph(g, mesh.size)
    labels = jnp.arange(dg.N, dtype=jnp.int32)
    labels, dgs = shard_arrays(mesh, dg, labels)
    lab, _ = dist_local_cluster_iterate(
        mesh, jax.random.key(2), labels, dgs, jnp.int32(16), num_rounds=3
    )

    cl, col, nl = contract_local_clustering(mesh, dgs, lab)
    cg, cog, ng = contract_dist_clustering(mesh, dgs, lab)

    # Identical coarse layout by design (contiguous exscan ids, preserving
    # the prefix-dense invariant) — the paths must agree exactly.
    assert nl == ng
    assert np.array_equal(np.asarray(col), np.asarray(cog))
    assert np.array_equal(np.asarray(cl.node_w), np.asarray(cg.node_w))
    assert cl.n == cg.n and cl.m == cg.m
    assert cl.n_loc == cg.n_loc and cl.g_loc == cg.g_loc
    # coarse total edge weight == weight of inter-cluster fine edges
    lab_np = np.asarray(lab)[: g.n]
    src_g = np.repeat(np.arange(g.n), np.diff(np.asarray(g.row_ptr)))
    dst_g = np.asarray(g.col_idx)
    inter = lab_np[src_g] != lab_np[dst_g]
    assert int(np.asarray(cl.edge_w).sum()) == int(
        np.asarray(g.edge_w)[inter].sum()
    )

    # a clustering that spans shards must be rejected
    spanning = np.zeros(dg.N, dtype=np.int32)  # everyone joins cluster 0
    spanning[g.n:] = np.arange(g.n, dg.N)
    sp, dgs2 = shard_arrays(mesh, dg, jnp.asarray(spanning))
    with pytest.raises(ValueError, match="non-local"):
        contract_local_clustering(mesh, dgs2, sp)


def test_local_contraction_multilevel_prefix_dense():
    """Regression: successive local contractions must conserve total node
    weight and keep the prefix-dense layout (a shard-resident coarse
    layout silently lost ~25% of the weight per level through the
    'real iff id < n' invariant)."""
    import jax
    import jax.numpy as jnp
    import pytest
    from jax.sharding import Mesh

    from kaminpar_tpu.dist.contraction import contract_local_clustering
    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.dist.lp import dist_local_cluster_iterate, shard_arrays
    from kaminpar_tpu.graph import generators

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("need 8 devices")
    mesh = Mesh(np.array(devs[:8]), ("nodes",))

    g = generators.rmat_graph(10, 8, seed=5)
    total_w = g.total_node_weight
    dg = distribute_graph(g, mesh.size)
    for level in range(3):
        labels = jnp.arange(dg.N, dtype=jnp.int32)
        labels, dgs = shard_arrays(mesh, dg, labels)
        lab, _ = dist_local_cluster_iterate(
            mesh, jax.random.key(level), labels, dgs, jnp.int32(8),
            num_rounds=2,
        )
        coarse, _, n_c = contract_local_clustering(mesh, dgs, lab)
        nw = np.asarray(coarse.node_w)
        assert int(nw.sum()) == total_w, (level, int(nw.sum()))
        # prefix-dense: exactly the first n_c ids carry weight
        assert (nw[:n_c] > 0).all()
        assert (nw[n_c:] == 0).all()
        if n_c == dg.n:
            break
        dg = coarse
