"""Contraction kernel tests (reference tier 2: tests/shm cluster contraction
tests)."""

import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.graph import from_edge_list, generators, validate
from kaminpar_tpu.ops.contraction import contract_clustering, project_partition


def _pad_labels(g, labels):
    pv = g.padded()
    idt = pv.row_ptr.dtype
    return jnp.concatenate(
        [jnp.asarray(labels, dtype=idt), jnp.full(pv.n_pad - pv.n, pv.anchor, dtype=idt)]
    )


def test_contract_path_pairs():
    g = generators.path_graph(6)  # 0-1-2-3-4-5
    labels = np.array([0, 0, 2, 2, 4, 4])
    coarse, coarse_of = contract_clustering(g, _pad_labels(g, labels))
    validate(coarse)
    assert coarse.n == 3
    assert coarse.m == 4  # path of 3 nodes
    assert coarse.total_node_weight == 6
    cw = np.asarray(coarse.node_w)
    assert (cw == 2).all()


def test_contract_weights_aggregate():
    # triangle with two nodes merged -> parallel edges sum
    g = from_edge_list(3, np.array([[0, 1], [1, 2], [0, 2]]))
    labels = np.array([0, 0, 2])
    coarse, _ = contract_clustering(g, _pad_labels(g, labels))
    validate(coarse)
    assert coarse.n == 2
    assert coarse.m == 2
    # edges (0,2) and (1,2) merge into one coarse edge of weight 2
    assert np.asarray(coarse.edge_w).max() == 2


def test_contract_all_one_cluster():
    g = generators.complete_graph(5)
    labels = np.zeros(5, dtype=np.int64)
    coarse, _ = contract_clustering(g, _pad_labels(g, labels))
    assert coarse.n == 1
    assert coarse.m == 0
    assert coarse.total_node_weight == 5


def test_projection_roundtrip():
    g = generators.grid2d_graph(4, 4)
    labels = np.asarray(g.col_idx)[np.asarray(g.row_ptr)[:-1]]  # first neighbor
    labels = np.minimum(labels, np.arange(16))
    coarse, coarse_of = contract_clustering(g, _pad_labels(g, labels))
    part_c = np.arange(coarse.n, dtype=np.int32) % 2
    part_f = np.asarray(project_partition(coarse_of, jnp.asarray(part_c)))
    assert part_f.shape == (16,)
    # nodes in the same cluster share the projected block
    cf = np.asarray(coarse_of)
    for u in range(16):
        assert part_f[u] == part_c[cf[u]]


def test_contract_preserves_cut_weight():
    """Total coarse edge weight = fine cut weight between clusters."""
    g = generators.rmat_graph(8, 6, seed=7)
    rng = np.random.default_rng(1)
    labels = rng.integers(0, 40, g.n)
    coarse, _ = contract_clustering(g, _pad_labels(g, labels))
    validate(coarse)
    u = np.asarray(g.edge_u)
    v = np.asarray(g.col_idx)
    w = np.asarray(g.edge_w)
    inter = labels[u] != labels[v]
    assert np.asarray(coarse.edge_w).sum() == w[inter].sum()
    assert coarse.total_node_weight == g.total_node_weight
