"""Executable-grade observability (ISSUE 12): capacity planner
predicted-vs-measured validation, executable-census neutrality, serve
admission preflight, and the hang-forensics flight recorder / prober
dossier round trip."""

import json
import os
import sys

import numpy as np
import pytest

from kaminpar_tpu.serve import CapacityError, PartitionEngine
from kaminpar_tpu.telemetry import capacity, flight_recorder
from kaminpar_tpu.utils import collective_stats, compile_stats, sync_stats
from kaminpar_tpu.utils import heap_profiler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_census():
    """Every test starts and ends with the census disarmed (it is
    process-global, like the compile-shape census)."""
    compile_stats.arm_executable_census(False)
    yield
    compile_stats.arm_executable_census(False)


# -- capacity model vs measured residency (acceptance) -----------------------


def test_predicted_vs_measured_watermark_cpu_scale12():
    """The resident-buffer model must land within the stated tolerance of
    the constructed views' live-array bytes on CPU, for BOTH the dense and
    the device_decode arms (ISSUE 12 acceptance)."""
    out = capacity.validate_cpu(scale=12)
    assert out["watermark_backend"] == "cpu_rss_proxy"
    for arm in ("dense", "device_decode"):
        rel = out[arm]["rel_err"]
        assert rel <= capacity.VALIDATION_TOLERANCE, (
            f"{arm}: predicted {out[arm]['predicted_bytes']} vs measured "
            f"{out[arm]['measured_bytes']} (rel err {rel} > "
            f"{capacity.VALIDATION_TOLERANCE})"
        )
        assert out[arm]["measured_bytes"] > 0


def test_watermark_report_labels_backend():
    """ISSUE 12 satellite: CPU-measured watermarks carry an explicit
    backend label (+ the RSS/live-array proxy numbers) so they can never be
    silently compared against HBM ceilings."""
    rep = heap_profiler.watermark_report()
    assert rep["backend"] in ("cpu_rss_proxy", "cpu_allocator", "tpu_hbm")
    if rep["backend"] == "cpu_rss_proxy":
        assert rep["rss_bytes"] > 0
        assert rep["peak_rss_bytes"] > 0
        assert rep["live_array_bytes"] >= 0


def test_capacity_prediction_and_ladder():
    pred = capacity.predict("rmat", 16, 64, device_kind="v5e")
    assert pred.predicted_peak_bytes > pred.resident_bytes > 0
    assert pred.ceiling_bytes is not None and pred.fits is True
    # Unknown device kind: no ceiling, fits is unknowable, never a crash.
    unk = capacity.predict("rmat", 16, 64, device_kind="weird")
    assert unk.ceiling_bytes is None and unk.fits is None
    lad = capacity.ladder(
        "rmat", 64, device_kind="v5e", scales=range(16, 29, 4)
    )
    fits = [row["dense"].fits for row in lad["rows"]]
    # Monotone: once a scale stops fitting, larger scales don't fit either.
    assert fits == sorted(fits, reverse=True)
    assert lad["max_feasible_scale"]["dense"] is not None
    # The compressed arm fits at least as far as the dense arm.
    assert (lad["max_feasible_scale"]["device_decode"]
            >= lad["max_feasible_scale"]["dense"])


def test_capacity_census_temp_harvest():
    """Armed, the planner reads the cell's temp bytes from XLA's own
    memory_analysis (shape-only lowering — no device arrays exist)."""
    compile_stats.arm_executable_census()
    pred = capacity.predict("rmat", 10, 8, device_kind="v5e")
    assert pred.temp_source == "xla_memory_analysis"
    assert pred.temp_bytes > 0
    snap = compile_stats.executable_census_snapshot()
    rows = {k: v for k, v in snap.items()
            if k.startswith("capacity_contraction|")}
    assert rows, f"census rows missing: {sorted(snap)}"
    row = next(iter(rows.values()))
    assert row["peak_bytes"] >= row["temp_bytes"] > 0
    assert row["flops"] is not None
    # A second predict for the same cell reuses the cached row — no
    # second compile of the identical executable.
    before = compile_stats.compile_time_snapshot()["compile_events"]
    capacity.predict("rmat", 10, 8, device_kind="v5e")
    assert compile_stats.compile_time_snapshot()["compile_events"] == before


def test_harvest_failure_not_retried(monkeypatch):
    """A failed lower/compile is negative-cached: the ladder must not pay
    the failing compile once per row (code-review finding)."""
    compile_stats.arm_executable_census()
    calls = {"n": 0}
    real = compile_stats.harvest_fn

    def counting(*a, **kw):
        calls["n"] += 1
        return None  # simulate a compile failure

    monkeypatch.setattr(compile_stats, "harvest_fn", counting)
    capacity._harvest_attempted.discard((333, 4444))
    assert capacity.harvest_temp_bytes(333, 4444) is None
    assert capacity.harvest_temp_bytes(333, 4444) is None
    assert calls["n"] == 1
    monkeypatch.setattr(compile_stats, "harvest_fn", real)
    capacity._harvest_attempted.discard((333, 4444))


# -- census neutrality (acceptance) ------------------------------------------


def _partition_with_census(arm: bool):
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.utils import RandomState

    RandomState.reseed(7)
    sync_stats.reset()
    collective_stats.reset()
    compile_stats.arm_executable_census(arm)
    g = rmat_graph(9, edge_factor=8, seed=3)
    solver = KaMinPar(ctx="default")
    solver.set_graph(g)
    part = solver.compute_partition(8, 0.03)
    snap = sync_stats.snapshot()
    pulls = {ph: row["count"] for ph, row in snap["phases"].items()}
    colls = collective_stats.snapshot()["count"]
    return np.asarray(part), pulls, colls


def test_census_neutrality_bit_identical_and_pull_counts():
    """Armed vs off must be bit-identical with equal per-phase pull counts
    and zero added collectives (ISSUE 12 acceptance — the census is pure
    host-side compiler introspection)."""
    part_off, pulls_off, colls_off = _partition_with_census(False)
    part_on, pulls_on, colls_on = _partition_with_census(True)
    assert np.array_equal(part_off, part_on)
    assert pulls_on == pulls_off
    assert colls_on == colls_off


def test_census_harvest_adds_no_transfers():
    import jax
    import jax.numpy as jnp

    compile_stats.arm_executable_census()
    sync_stats.reset()
    before = collective_stats.snapshot()["count"]
    from kaminpar_tpu.ops.contraction import _contract_device

    nn = jax.ShapeDtypeStruct((256,), jnp.int32)
    mm = jax.ShapeDtypeStruct((1024,), jnp.int32)
    row = compile_stats.harvest_fn(
        "capacity_contraction", _contract_device, nn, mm, mm, mm, nn,
        cell=(256, 1024),
    )
    assert row is not None and row["temp_bytes"] is not None
    snap = sync_stats.snapshot()
    assert snap["count"] == 0 and snap["implicit"] == 0
    assert collective_stats.snapshot()["count"] == before


def test_census_prometheus_families_render():
    from kaminpar_tpu.telemetry import prometheus

    compile_stats.arm_executable_census()
    capacity.harvest_temp_bytes(512, 2048)
    text = prometheus.render(compile_stats.census_prometheus_families())
    families = prometheus.validate(text)
    assert prometheus.get_sample(
        families, "kaminpar_executable_census_total"
    ) >= 1


# -- serve admission preflight (acceptance) ----------------------------------


def test_preflight_rejects_predicted_oversize():
    from kaminpar_tpu.graph.generators import rmat_graph

    g = rmat_graph(10, edge_factor=8, seed=1)
    engine = PartitionEngine(
        "serve", capacity_ceiling_bytes=64 * 1024
    ).start(warmup=False)
    try:
        # Preflight contract: the submit path NEVER lowers or compiles,
        # even with the census armed — it reads cached rows only.
        compile_stats.arm_executable_census()
        compiles_before = compile_stats.compile_time_snapshot()["compile_events"]
        with pytest.raises(CapacityError) as ei:
            engine.submit(g, 8)
        assert (compile_stats.compile_time_snapshot()["compile_events"]
                == compiles_before)
        err = ei.value
        assert err.predicted_bytes > err.ceiling_bytes == 64 * 1024
        assert len(err.cell) == 3 and err.cell[2] == 8
        assert engine.stats_.counter("rejected_capacity") == 1
        # The reject happened before queueing: nothing admitted, queue empty.
        assert engine.stats_.counter("admitted") == 0
        snap = engine.stats_.snapshot(queue_depth=0)
        assert snap["rejected_capacity"] == 1
    finally:
        engine.shutdown(drain=False)


def test_preflight_passes_within_ceiling_and_off_mode():
    from kaminpar_tpu.graph.generators import rmat_graph

    g = rmat_graph(7, edge_factor=4, seed=1)
    # Huge explicit ceiling: the request must sail through admission.
    engine = PartitionEngine(
        "serve", capacity_ceiling_bytes=1 << 40
    ).start(warmup=False)
    try:
        assert engine.partition(g, 4).shape == (g.n,)
    finally:
        engine.shutdown(drain=True)
    # preflight=off ignores even an absurd ceiling.
    engine = PartitionEngine(
        "serve", capacity_ceiling_bytes=1, capacity_preflight="off"
    ).start(warmup=False)
    try:
        assert engine.partition(g, 4).shape == (g.n,)
    finally:
        engine.shutdown(drain=True)


def test_preflight_default_cpu_passes():
    """On CPU without allocator stats no ceiling is derivable: auto mode
    must not reject anything (the honest no-ceiling reading)."""
    from kaminpar_tpu.graph.generators import rmat_graph

    engine = PartitionEngine("serve").start(warmup=False)
    try:
        if engine._capacity_ceiling is None:
            assert engine.partition(
                rmat_graph(7, edge_factor=4, seed=1), 4
            ).shape == (128,)
    finally:
        engine.shutdown(drain=True)


# -- flight recorder + dossier (acceptance) ----------------------------------


def test_flight_recorder_heartbeats_and_dossier(tmp_path):
    hb = str(tmp_path / "hb.jsonl")
    rec = flight_recorder.FlightRecorder(hb, interval_s=0.05)
    rec.start()
    rec.note("backend_init")
    import time as _time

    _time.sleep(0.3)
    rec.stop()
    dossier = flight_recorder.read_dossier(hb)
    assert dossier is not None
    assert dossier["heartbeats"] >= 3
    assert dossier["phase"] == "backend_init"
    assert dossier["phase_class"] == "init"
    assert dossier["last_heartbeat"]["rss_bytes"] > 0


def test_flight_recorder_reads_phase_board(tmp_path):
    from kaminpar_tpu.utils.timer import scoped_timer

    hb = str(tmp_path / "hb.jsonl")
    rec = flight_recorder.FlightRecorder(hb, interval_s=5.0)
    with scoped_timer("coarsening"):
        rec.beat()
    dossier = flight_recorder.read_dossier(hb)
    assert dossier["phase"] == "coarsening"
    assert dossier["phase_class"] == "execute"


def test_classify_phase():
    assert flight_recorder.classify_phase(None) == "init"
    assert flight_recorder.classify_phase("backend_init") == "init"
    assert flight_recorder.classify_phase("serve_warmup") == "compile"
    assert flight_recorder.classify_phase("lp_refinement") == "execute"


def _load_prober():
    import importlib

    scripts = os.path.join(REPO, "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import tpu_prober

    importlib.reload(tpu_prober)
    return tpu_prober


def test_forced_hang_attempt_carries_dossier(tmp_path, monkeypatch):
    """ISSUE 12 acceptance: a killed prober attempt in a forced-hang run
    carries a non-null dossier with phase + stack tail, and the outcome is
    classified by the dying phase."""
    prober = _load_prober()
    monkeypatch.setenv("KPTPU_PROBER_TEST_HANG", "init")
    monkeypatch.setenv("KPTPU_HEARTBEAT_S", "0.2")
    monkeypatch.setattr(prober, "WORK_DIR", str(tmp_path))
    monkeypatch.setattr(prober, "LOG_PATH", str(tmp_path / "probe.jsonl"))
    monkeypatch.setattr(prober, "INIT_TIMEOUT_S", 4.0)
    monkeypatch.setattr(prober, "ATTEMPT_TIMEOUT_S", 30.0)
    rec = prober.run_attempt(1)
    assert rec is None
    lines = [
        json.loads(ln)
        for ln in (tmp_path / "probe.jsonl").read_text().splitlines()
    ]
    attempt = next(r for r in lines if r.get("attempt") == 1)
    assert attempt["outcome"].startswith("init_hang_killed_after_")
    dossier = attempt["dossier"]
    assert dossier is not None
    assert dossier["phase"] == "backend_init"
    assert dossier["phase_class"] == "init"
    assert dossier["heartbeats"] >= 1
    # The armed faulthandler dump fired before the kill: the stack tail
    # shows the sleep the child was wedged in.
    assert any("sleep" in ln or "child_attempt" in ln
               for ln in dossier.get("stack_tail", [])), dossier
    # Scratch sidecars are cleaned up after the dossier is read.
    assert not list(tmp_path.glob(".tpu_probe_attempt_*"))


def test_retry_sleep_escalation():
    prober = _load_prober()
    base = prober.RETRY_SLEEP_S
    assert prober.retry_sleep_for(0) == base
    assert prober.retry_sleep_for(2) == base
    assert prober.retry_sleep_for(3) == min(2 * base, prober.RETRY_SLEEP_MAX_S)
    assert prober.retry_sleep_for(4) == min(4 * base, prober.RETRY_SLEEP_MAX_S)
    # Bounded: a week of hangs still sleeps at most the cap.
    assert prober.retry_sleep_for(50) == max(
        prober.RETRY_SLEEP_MAX_S, base
    )


# -- tools CLI ----------------------------------------------------------------


def test_tools_capacity_cli(capsys):
    from kaminpar_tpu.tools.tools import capacity as capacity_tool

    rc = capacity_tool([
        "--device-kind", "v5e", "--scales", "16:20", "-k", "8", "--no-census",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "max feasible scale" in out and "dense" in out


def test_tools_capacity_cli_json(capsys):
    from kaminpar_tpu.tools.tools import capacity as capacity_tool

    rc = capacity_tool([
        "--scales", "16:18", "--json", "--no-census",
    ])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["max_feasible_scale"]["dense"] is not None
    assert payload["rows"][0]["dense"]["predicted_peak_bytes"] > 0


def test_tools_doctor_cli(tmp_path, capsys):
    from kaminpar_tpu.tools.tools import doctor

    log = tmp_path / "probe.jsonl"
    records = [
        {"event": "prober_start"},
        {"attempt": 1, "outcome": "init_hang_killed_after_1200s",
         "probe": None,
         "dossier": {"phase": "backend_init", "phase_class": "init",
                     "heartbeats": 99,
                     "last_heartbeat": {"rss_bytes": 123},
                     "stack_tail": ["File x", "  time.sleep(1)"]}},
        {"attempt": 2, "outcome": "init_hang_killed_after_1200s",
         "probe": None},
        {"attempt": 3, "outcome": "measured",
         "probe": {"probe": "devices_ok", "init_s": 42.0}},
    ]
    log.write_text("".join(json.dumps(r) + "\n" for r in records))
    rc = doctor([str(log)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "init_hang_killed_after_1200s: 2" in out
    assert "backend_init: 1" in out
    assert "(no dossier): 1" in out
    assert "time.sleep(1)" in out
    rc = doctor([str(log), "--json"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["attempts"] == 3
    assert payload["hang_phases"]["backend_init"] == 1
    assert payload["init_s"]["mean"] == 42.0


# -- ledger integration -------------------------------------------------------


def test_ledger_entry_carries_executable_census(tmp_path):
    from kaminpar_tpu.telemetry import ledger

    compile_stats.arm_executable_census()
    capacity.harvest_temp_bytes(512, 2048)
    entry = ledger.build_entry({"backend": "cpu", "value": 1.0}, kind="bench")
    census = entry["executable_census"]
    assert census["executables"] >= 1
    assert census["peak_bytes_max"] > 0
    path = str(tmp_path / "runs.jsonl")
    ledger.append(entry, path)
    assert ledger.read(path)[0]["executable_census"]["executables"] >= 1
