"""HEM clusterer tests (reference: hem_clusterer.cc semantics)."""

import numpy as np

from kaminpar_tpu.context import LabelPropagationContext
from kaminpar_tpu.coarsening.hem_clusterer import HEMClustering
from kaminpar_tpu.graph import generators


def _labels(g, max_cw=100):
    hem = HEMClustering(LabelPropagationContext())
    lab = np.asarray(hem.compute_clustering(g, max_cw))[: g.n]
    return lab


def test_hem_produces_valid_matching():
    g = generators.grid2d_graph(16, 16)
    lab = _labels(g)
    # every cluster has size <= 2 (matching, not clustering)
    sizes = np.bincount(lab)
    assert sizes.max() <= 2
    # most nodes matched on a grid
    n_clusters = len(np.unique(lab))
    assert n_clusters <= 0.75 * g.n, n_clusters


def test_hem_prefers_heavy_edges():
    # path 0-1-2-3 with edge weights 1, 100, 1: the heavy pair (1,2) must match
    row_ptr = np.array([0, 1, 3, 5, 6])
    col_idx = np.array([1, 0, 2, 1, 3, 2])
    edge_w = np.array([1, 1, 100, 100, 1, 1])
    from kaminpar_tpu.graph.csr import CSRGraph

    g = CSRGraph(row_ptr, col_idx, None, edge_w)
    lab = _labels(g)
    assert lab[1] == lab[2]
    assert lab[0] != lab[1] and lab[3] != lab[2]


def test_hem_respects_weight_cap():
    g = generators.grid2d_graph(8, 8, node_weights=np.full(64, 10))
    lab = _labels(g, max_cw=15)  # no pair fits (10+10 > 15)
    assert len(np.unique(lab)) == 64


def test_hem_in_pipeline():
    from kaminpar_tpu.context import ClusteringAlgorithm
    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name("default")
    ctx.coarsening.algorithm = ClusteringAlgorithm.HEM
    g = generators.rgg2d_graph(1024, seed=6)
    s = KaMinPar(ctx)
    s.set_graph(g)
    part = s.compute_partition(k=4)
    assert metrics.is_feasible(g, part, 4, s.ctx.partition.max_block_weights)
