"""V-cycle scheme tests (reference: vcycle_deep_multilevel.cc)."""

import numpy as np
import pytest

from kaminpar_tpu.graph import generators, metrics
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.presets import create_context_by_preset_name


@pytest.mark.parametrize("preset", ["vcycle", "restricted-vcycle"])
def test_vcycle_end_to_end(preset):
    ctx = create_context_by_preset_name(preset)
    ctx.vcycles = (4,)
    g = generators.rgg2d_graph(2048, seed=3)
    s = KaMinPar(ctx)
    s.set_graph(g)
    part = s.compute_partition(k=16, epsilon=0.05)
    assert metrics.is_feasible(g, part, 16, s.ctx.partition.max_block_weights)
    assert len(np.unique(part)) == 16


def test_vcycle_quality_not_worse_than_default():
    g = generators.rgg2d_graph(2048, seed=4)
    s0 = KaMinPar("default")
    s0.set_graph(g)
    p0 = s0.compute_partition(k=8)
    cut0 = metrics.edge_cut(g, p0)

    ctx = create_context_by_preset_name("vcycle")
    ctx.vcycles = (2,)
    s1 = KaMinPar(ctx)
    s1.set_graph(g)
    p1 = s1.compute_partition(k=8)
    cut1 = metrics.edge_cut(g, p1)
    assert cut1 <= 1.25 * cut0, (cut1, cut0)


def test_vcycle_rejects_non_refining_steps():
    # 3 -> 4 does not refine under recursive bisection (offsets [0,6,11,16]
    # vs [0,4,8,12,16] share only the endpoints)
    ctx = create_context_by_preset_name("vcycle")
    ctx.vcycles = (3, 4)
    g = generators.grid2d_graph(16, 16)
    s = KaMinPar(ctx)
    s.set_graph(g)
    with pytest.raises(ValueError, match="refine"):
        s.compute_partition(k=16)
