"""Distributed node balancer: feasibility repair across shards.

Reference behavior: kaminpar-dist/refinement/balancer/node_balancer.cc —
the balancer must restore strict feasibility even from grossly infeasible
seeds, which capacity-respecting LP can never do (VERDICT r1 weak #4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from kaminpar_tpu.dist import distribute_graph
from kaminpar_tpu.dist.balancer import dist_balance
from kaminpar_tpu.dist.lp import shard_arrays
from kaminpar_tpu.dist.partitioner import DKaMinPar
from kaminpar_tpu.graph import generators, metrics


def _mesh(num=8):
    devs = jax.devices()
    if len(devs) < num:
        pytest.skip(f"need {num} devices, have {len(devs)}")
    return Mesh(np.array(devs[:num]), ("nodes",))


def _max_bw(g, k, eps=0.03):
    ceil_wk = (g.total_node_weight + k - 1) // k
    return max(int((1 + eps) * ceil_wk), ceil_wk + g.max_node_weight)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_balancer_repairs_infeasible_partition(seed):
    """Seed with everything in ONE block — maximal infeasibility."""
    mesh = _mesh()
    g = generators.grid2d_graph(24, 24)
    k = 8
    dg = distribute_graph(g, mesh.size)
    part = np.zeros(dg.N, dtype=np.int32)  # all nodes in block 0
    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(part))
    bw = _max_bw(g, k)
    cap = jnp.full(k, bw, dtype=jnp.int32)
    out, feasible = dist_balance(
        mesh, jax.random.key(seed), labels, dgs, cap, k=k
    )
    assert feasible
    w = np.bincount(np.asarray(out)[: g.n], weights=np.asarray(g.node_w),
                    minlength=k)
    assert w.max() <= bw


def test_balancer_repairs_skewed_random(seed=3):
    mesh = _mesh()
    g = generators.rmat_graph(10, 8, seed=7)
    k = 16
    dg = distribute_graph(g, mesh.size)
    rng = np.random.default_rng(seed)
    # skewed: 80% of nodes in 2 blocks
    part = np.where(
        rng.random(dg.N) < 0.8, rng.integers(0, 2, dg.N), rng.integers(0, k, dg.N)
    ).astype(np.int32)
    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(part))
    bw = _max_bw(g, k)
    cap = jnp.full(k, bw, dtype=jnp.int32)
    out, feasible = dist_balance(
        mesh, jax.random.key(seed), labels, dgs, cap, k=k
    )
    assert feasible
    w = np.bincount(np.asarray(out)[: g.n], weights=np.asarray(g.node_w),
                    minlength=k)
    assert w.max() <= bw


def test_balancer_noop_on_feasible():
    """A feasible partition must stay untouched (no gratuitous churn)."""
    mesh = _mesh()
    g = generators.grid2d_graph(16, 16)
    k = 4
    dg = distribute_graph(g, mesh.size)
    part = np.zeros(dg.N, dtype=np.int32)
    part[: g.n] = (np.arange(g.n) * k // g.n).astype(np.int32)  # perfect split
    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(part))
    bw = _max_bw(g, k)
    cap = jnp.full(k, bw, dtype=jnp.int32)
    out, feasible = dist_balance(
        mesh, jax.random.key(0), labels, dgs, cap, k=k
    )
    assert feasible
    np.testing.assert_array_equal(np.asarray(out), part)


@pytest.mark.parametrize("gen,k", [
    (lambda: generators.grid2d_graph(24, 24), 4),
    (lambda: generators.rmat_graph(10, 8, seed=9), 8),
])
@pytest.mark.slow  # full dist pipeline on the virtual mesh: tier-2 (pytest -m slow)
def test_dkaminpar_endtoend_strictly_feasible(gen, k):
    """End-to-end dist pipeline now guarantees eps=0.03 feasibility
    (VERDICT r1 next-step #4 done-criterion)."""
    mesh = _mesh()
    g = gen()
    solver = DKaMinPar(mesh)
    part = solver.compute_partition(g, k=k, epsilon=0.03)
    bw = _max_bw(g, k)
    assert metrics.is_feasible(
        g, part, k, jnp.full(k, bw, dtype=jnp.int32)
    )


def test_cluster_balancer_direct_restores_feasibility():
    """The cluster tier alone (no node rounds) repairs an infeasible seed
    by moving whole clusters (reference: cluster_balancer.cc)."""
    from kaminpar_tpu.dist.balancer import dist_cluster_balance

    mesh = _mesh()
    g = generators.grid2d_graph(16, 16)
    k = 4
    dg = distribute_graph(g, mesh.size)
    part = np.zeros(dg.N, dtype=np.int32)
    # pre-seed the other blocks with a few nodes so every target exists
    part[: g.n][64:80] = 1
    part[: g.n][80:96] = 2
    part[: g.n][96:112] = 3
    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(part))
    bw = _max_bw(g, k)
    cap = jnp.full(k, bw, dtype=jnp.int32)
    out, feasible = dist_cluster_balance(
        mesh, jax.random.key(0), labels, dgs, cap, k=k, max_rounds=64
    )
    assert feasible
    w = np.bincount(np.asarray(out)[: g.n], weights=np.asarray(g.node_w),
                    minlength=k)
    assert w.max() <= bw


def test_cluster_balancer_escalation_on_binpack_stuck():
    """Bin-packing stuck case: every mover weighs 10 and each receiver has
    room for exactly one mover.  The node balancer's probabilistic
    commitments routinely bounce (two simultaneous arrivals at a block roll
    back), while the deterministic greedy cluster tier moves exactly one
    unit per block per round — dist_balance must end feasible either way
    (VERDICT r2 next-steps #6 seeded stuck fixture)."""
    mesh = _mesh()
    rows, cols = 8, 16
    g0 = generators.grid2d_graph(rows, cols)
    import kaminpar_tpu.graph.csr as csr_mod

    nw = np.ones(g0.n, dtype=np.int32)
    # the left 2 columns are heavy movers
    heavy = (np.arange(g0.n) % cols) < 2
    nw[heavy] = 10
    g = csr_mod.CSRGraph(g0.row_ptr, g0.col_idx, nw, g0.edge_w)
    k = 8
    dg = distribute_graph(g, mesh.size)
    part = np.zeros(dg.N, dtype=np.int32)
    # blocks 1..7 exist, each with a couple of light nodes
    body = np.arange(g.n)[~heavy]
    for b in range(1, k):
        part[body[(b - 1) * 2 : b * 2]] = b
    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(part))
    # caps: every block can take one heavy node above its seed weight
    w0 = np.bincount(part[: g.n], weights=nw, minlength=k)
    cap_np = np.full(k, int(w0[1:].max()) + 11, dtype=np.int32)
    # block 0 must shed weight down to its cap
    cap_np[0] = int(w0[0]) - 3 * 10 + 5  # force >= 3 heavy departures
    cap = jnp.asarray(cap_np)
    out, feasible = dist_balance(
        mesh, jax.random.key(5), labels, dgs, cap, k=k
    )
    assert feasible
    w = np.bincount(np.asarray(out)[: g.n], weights=nw, minlength=k)
    assert (w <= cap_np).all()
