"""Distributed node balancer: feasibility repair across shards.

Reference behavior: kaminpar-dist/refinement/balancer/node_balancer.cc —
the balancer must restore strict feasibility even from grossly infeasible
seeds, which capacity-respecting LP can never do (VERDICT r1 weak #4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from kaminpar_tpu.dist import distribute_graph
from kaminpar_tpu.dist.balancer import dist_balance
from kaminpar_tpu.dist.lp import shard_arrays
from kaminpar_tpu.dist.partitioner import DKaMinPar
from kaminpar_tpu.graph import generators, metrics


def _mesh(num=8):
    devs = jax.devices()
    if len(devs) < num:
        pytest.skip(f"need {num} devices, have {len(devs)}")
    return Mesh(np.array(devs[:num]), ("nodes",))


def _max_bw(g, k, eps=0.03):
    ceil_wk = (g.total_node_weight + k - 1) // k
    return max(int((1 + eps) * ceil_wk), ceil_wk + g.max_node_weight)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_balancer_repairs_infeasible_partition(seed):
    """Seed with everything in ONE block — maximal infeasibility."""
    mesh = _mesh()
    g = generators.grid2d_graph(24, 24)
    k = 8
    dg = distribute_graph(g, mesh.size)
    part = np.zeros(dg.N, dtype=np.int32)  # all nodes in block 0
    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(part))
    bw = _max_bw(g, k)
    cap = jnp.full(k, bw, dtype=jnp.int32)
    out, feasible = dist_balance(
        mesh, jax.random.key(seed), labels, dgs, cap, k=k
    )
    assert feasible
    w = np.bincount(np.asarray(out)[: g.n], weights=np.asarray(g.node_w),
                    minlength=k)
    assert w.max() <= bw


def test_balancer_repairs_skewed_random(seed=3):
    mesh = _mesh()
    g = generators.rmat_graph(10, 8, seed=7)
    k = 16
    dg = distribute_graph(g, mesh.size)
    rng = np.random.default_rng(seed)
    # skewed: 80% of nodes in 2 blocks
    part = np.where(
        rng.random(dg.N) < 0.8, rng.integers(0, 2, dg.N), rng.integers(0, k, dg.N)
    ).astype(np.int32)
    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(part))
    bw = _max_bw(g, k)
    cap = jnp.full(k, bw, dtype=jnp.int32)
    out, feasible = dist_balance(
        mesh, jax.random.key(seed), labels, dgs, cap, k=k
    )
    assert feasible
    w = np.bincount(np.asarray(out)[: g.n], weights=np.asarray(g.node_w),
                    minlength=k)
    assert w.max() <= bw


def test_balancer_noop_on_feasible():
    """A feasible partition must stay untouched (no gratuitous churn)."""
    mesh = _mesh()
    g = generators.grid2d_graph(16, 16)
    k = 4
    dg = distribute_graph(g, mesh.size)
    part = np.zeros(dg.N, dtype=np.int32)
    part[: g.n] = (np.arange(g.n) * k // g.n).astype(np.int32)  # perfect split
    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(part))
    bw = _max_bw(g, k)
    cap = jnp.full(k, bw, dtype=jnp.int32)
    out, feasible = dist_balance(
        mesh, jax.random.key(0), labels, dgs, cap, k=k
    )
    assert feasible
    np.testing.assert_array_equal(np.asarray(out), part)


@pytest.mark.parametrize("gen,k", [
    (lambda: generators.grid2d_graph(24, 24), 4),
    (lambda: generators.rmat_graph(10, 8, seed=9), 8),
])
def test_dkaminpar_endtoend_strictly_feasible(gen, k):
    """End-to-end dist pipeline now guarantees eps=0.03 feasibility
    (VERDICT r1 next-step #4 done-criterion)."""
    mesh = _mesh()
    g = gen()
    solver = DKaMinPar(mesh)
    part = solver.compute_partition(g, k=k, epsilon=0.03)
    bw = _max_bw(g, k)
    assert metrics.is_feasible(
        g, part, k, jnp.full(k, bw, dtype=jnp.int32)
    )
