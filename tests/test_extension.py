"""Device-side partition extension (partitioning/extension.py, round 5).

Reference behavior being matched: ``extend_partition``
(kaminpar-shm/partitioning/helper.cc:349) splits every block of a cur_k-way
partition into a new_k-way partition whose blocks refine the old ones.
"""

import numpy as np
import pytest

from kaminpar_tpu.graph import generators, metrics
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.partitioning.deep import extend_partition
from kaminpar_tpu.partitioning.partition_utils import (
    intermediate_block_weights,
    split_offsets,
)
from kaminpar_tpu.presets import create_context_by_preset_name
from kaminpar_tpu.utils import RandomState


def _ctx_for(g, k, device: bool):
    ctx = create_context_by_preset_name("default")
    ctx.seed = 1
    ctx.initial_partitioning.device_extension = device
    ctx.initial_partitioning.device_extension_n = 256  # engage on test sizes
    ctx.initial_partitioning.device_extension_cpb = 16
    ctx.partition.setup(int(g.total_node_weight), k, 0.03)
    return ctx


def test_device_extension_refines_blocks_and_balances():
    """Device path: result refines the input blocks (each new block's nodes
    all come from one old block) and respects the intermediate budgets."""
    RandomState.reseed(0)
    g = generators.grid2d_graph(48, 48)
    k, cur_k, new_k = 16, 4, 16
    ctx = _ctx_for(g, k, device=True)
    # a sane starting 4-way partition
    start_ctx = create_context_by_preset_name("fast")
    start_ctx.seed = 1
    s = KaMinPar(start_ctx)
    s.set_graph(g)
    part4 = s.compute_partition(cur_k, epsilon=0.03).astype(np.int32)

    out = extend_partition(g, part4, cur_k, new_k, ctx)
    assert out.shape == (g.n,)
    assert out.min() >= 0 and out.max() < new_k
    # refinement property: new block -> exactly one parent block
    off_new = split_offsets(k, new_k)
    off_cur = split_offsets(k, cur_k)
    lo_of = np.searchsorted(off_new, off_cur)
    parent_of_new = np.searchsorted(lo_of, np.arange(new_k), side="right") - 1
    assert np.array_equal(parent_of_new[out], part4)
    # budgets hold (relaxation bounded by the level's max node weight)
    bw = np.bincount(out, weights=np.asarray(g.node_w), minlength=new_k)
    inter = intermediate_block_weights(
        np.asarray(ctx.partition.max_block_weights, dtype=np.int64), new_k
    )
    assert (bw <= inter + int(g.max_node_weight)).all(), (bw, inter)
    # all new blocks populated on a mesh this size
    assert len(np.unique(out)) == new_k


def test_device_extension_cut_comparable_to_host():
    """The batched device path must land in the same cut regime as the host
    per-block path (quality parity gate; exact ratios tracked in
    BASELINE_measured.md)."""
    RandomState.reseed(0)
    g = generators.grid2d_graph(64, 64)
    k, cur_k, new_k = 16, 4, 16
    start_ctx = create_context_by_preset_name("fast")
    start_ctx.seed = 2
    s = KaMinPar(start_ctx)
    s.set_graph(g)
    part4 = s.compute_partition(cur_k, epsilon=0.03).astype(np.int32)

    cuts = {}
    for dev in (False, True):
        RandomState.reseed(7)
        ctx = _ctx_for(g, k, device=dev)
        out = extend_partition(g, part4, cur_k, new_k, ctx)
        cuts[dev] = int(metrics.edge_cut(g, out))
    # within 35% of the host path (the caller's refinement chain runs after
    # extension in the real pipeline and closes most of the residual gap)
    assert cuts[True] <= 1.35 * cuts[False], cuts


def test_host_extension_unaffected_by_flag_threshold():
    """Below device_extension_n the host path runs even with the flag on."""
    RandomState.reseed(0)
    g = generators.grid2d_graph(12, 12)  # n=144 < 256
    k = 8
    ctx = _ctx_for(g, k, device=True)
    part2 = (np.arange(g.n) % 2).astype(np.int32)
    out = extend_partition(g, part2, 2, 8, ctx)
    assert out.shape == (g.n,)
    assert len(np.unique(out)) == 8
