"""Fused Pallas LP kernels vs the XLA path — bit-identical contract.

The Pallas round (ops/pallas_lp.py) must return the SAME labels, label
weights, and admission decisions as the XLA round (ops/lp.py) — not
approximately, bit for bit: all random draws happen outside the kernels with
the XLA path's key schedule, and the in-kernel math is integer and
order-independent (the stable bitonic sort reproduces lax.sort exactly).
Off-TPU the kernels run with interpret=True, so these tests exercise the
exact kernel logic the TPU would compile.

Also here: the shape-bucket tests for the geometric padding ladder
(utils/intmath.next_shape_bucket) and the label-space bucket
(lp.num_labels_bucket).  Note on scope: full partitions are NOT invariant
to the padding policy because threefry draws depend on the array shape
(verified: jax.random.randint(key, (n,)) is not a prefix of (key, (n+p,))),
so the identity assertions target the stages where padding is exactly inert
(rating, contraction, label-space padding) and end-to-end checks assert
feasibility/quality instead.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.ops import lp, pallas_lp
from kaminpar_tpu.utils import next_key, reseed


def _init(g, num_labels=None):
    pv = g.padded()
    bv = g.bucketed()
    idt = pv.row_ptr.dtype
    labels = jnp.concatenate(
        [jnp.arange(pv.n, dtype=idt), jnp.full(pv.n_pad - pv.n, pv.anchor, dtype=idt)]
    )
    state = lp.init_state(labels, pv.node_w, num_labels or pv.n_pad)
    return pv, bv, state


def _assert_state_equal(a: lp.LPState, b: lp.LPState, ctxmsg=""):
    assert bool(jnp.all(a.labels == b.labels)), f"labels diverge {ctxmsg}"
    assert bool(jnp.all(a.label_weights == b.label_weights)), (
        f"label weights diverge {ctxmsg}"
    )
    assert int(a.num_moved) == int(b.num_moved), f"num_moved diverges {ctxmsg}"


GRAPHS = {
    "rmat": lambda: generators.rmat_graph(9, 8, seed=2),
    "grid": lambda: generators.grid2d_graph(24, 24),
    "star": lambda: generators.star_graph(96),
}


def test_bitonic_matches_stable_sort(rng):
    for w in (8, 32, 128):
        L = jnp.asarray(rng.integers(0, 7, (16, w)).astype(np.int32))
        W = jnp.asarray(rng.integers(0, 100, (16, w)).astype(np.int32))
        Ls, Ws = jax.lax.sort((L, W), dimension=1, num_keys=1)
        Lb, Wb = pallas_lp._bitonic_sort_rows(L, W)
        assert bool(jnp.all(Ls == Lb)), w
        # Stability: equal keys keep original (value) order.
        assert bool(jnp.all(Ws == Wb)), w


@pytest.mark.parametrize("gname", sorted(GRAPHS))
def test_round_bit_identical_clustering(gname):
    g = GRAPHS[gname]()
    pv, bv, state = _init(g)
    st_x, st_p = state, state
    max_w = jnp.asarray(25, dtype=pv.row_ptr.dtype)
    for _ in range(3):
        key = next_key()
        st_x = lp.lp_round_bucketed(
            st_x, key, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w,
            max_w, num_labels=pv.n_pad,
        )
        st_p = pallas_lp.lp_round_bucketed(
            st_p, key, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w,
            max_w, num_labels=pv.n_pad,
        )
        _assert_state_equal(st_x, st_p, f"on {gname}")


@pytest.mark.parametrize("tie_break", ["uniform", "lightest"])
def test_round_bit_identical_refinement(rng, tie_break):
    """num_labels = k instantiation (block mode) with the refiner's option
    surface (active_prob, tie moves, per-block weight table)."""
    g = generators.rmat_graph(9, 8, seed=5)
    pv = g.padded()
    bv = g.bucketed()
    k = 8
    part = pv.pad_node_array(
        jnp.asarray(rng.integers(0, k, g.n).astype(np.int32)), 0
    )
    st_x = lp.init_state(part, pv.node_w, k)
    st_p = st_x
    max_w = jnp.full(k, int(g.total_node_weight / k * 1.3), dtype=pv.node_w.dtype)
    for _ in range(3):
        key = next_key()
        kwargs = dict(
            num_labels=k, active_prob=0.5, allow_tie_moves=True,
            tie_break=tie_break,
        )
        st_x = lp.lp_round_bucketed(
            st_x, key, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w,
            max_w, **kwargs,
        )
        st_p = pallas_lp.lp_round_bucketed(
            st_p, key, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w,
            max_w, **kwargs,
        )
        _assert_state_equal(st_x, st_p, f"tie_break={tie_break}")


def test_commit_admission_bit_identical(rng):
    """The fused commit kernel admits exactly the XLA auction's set — the
    admission mask is compared through the committed labels with contended
    capacities (many movers per target, tight caps)."""
    n, k = 512, 6
    labels = jnp.asarray(rng.integers(0, k, n).astype(np.int32))
    node_w = jnp.asarray(rng.integers(1, 4, n).astype(np.int32))
    state = lp.init_state(labels, node_w, k)
    target = jnp.asarray(rng.integers(0, k, n).astype(np.int32))
    tconn = jnp.asarray(rng.integers(0, 20, n).astype(np.int32))
    own_conn = jnp.asarray(rng.integers(0, 20, n).astype(np.int32))
    max_w = jnp.full(k, int(np.asarray(state.label_weights).max()) + 15,
                     dtype=jnp.int32)
    key = next_key()
    ref = lp._commit_moves(
        state, key, target, tconn, own_conn, node_w, max_w, k,
        active_prob=0.8, allow_tie_moves=True,
    )
    fused = pallas_lp.commit_moves(
        state, key, target, tconn, own_conn, node_w, max_w, k,
        active_prob=0.8, allow_tie_moves=True,
    )
    _assert_state_equal(ref, fused)
    # Strictness must hold for the fused kernel as well.
    assert int(jnp.max(fused.label_weights)) <= int(jnp.max(max_w))


def test_iterate_bit_identical():
    g = generators.rmat_graph(9, 8, seed=3)
    pv, bv, state = _init(g)
    max_w = jnp.asarray(40, dtype=pv.row_ptr.dtype)
    key = next_key()
    args = (bv.buckets, bv.heavy, bv.gather_idx, pv.node_w, max_w,
            jnp.int32(1), jnp.int32(4))
    # The iterate entry points donate their state carry — each call gets an
    # independently built state.
    st_x = lp.lp_iterate_bucketed(state, key, *args, num_labels=pv.n_pad)
    _, _, state2 = _init(g)
    st_p = pallas_lp.lp_iterate_bucketed(state2, key, *args, num_labels=pv.n_pad)
    _assert_state_equal(st_x, st_p)


def test_colored_round_bit_identical(rng):
    g = generators.grid2d_graph(16, 16)
    pv = g.padded()
    bv = g.bucketed()
    k = 4
    part = pv.pad_node_array(
        jnp.asarray(rng.integers(0, k, g.n).astype(np.int32)), 0
    )
    st_x = lp.init_state(part, pv.node_w, k)
    st_p = st_x
    active = jnp.asarray(rng.random(pv.n_pad) < 0.5)
    max_w = jnp.full(k, 100, dtype=pv.node_w.dtype)
    key = next_key()
    st_x = lp.lp_round_colored(
        st_x, key, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w, max_w,
        active, num_labels=k,
    )
    st_p = pallas_lp.lp_round_colored(
        st_p, key, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w, max_w,
        active, num_labels=k,
    )
    _assert_state_equal(st_x, st_p)


def test_clusterer_backend_switch_bit_identical():
    """The lp_kernel config knob routes the clusterer through the Pallas
    iterate and yields the exact same clustering."""
    from kaminpar_tpu.coarsening.lp_clusterer import LPClustering
    from kaminpar_tpu.context import LabelPropagationContext

    g = generators.rmat_graph(9, 8, seed=4)
    out = {}
    for kernel in ("xla", "pallas"):
        reseed(11)
        ctx = LabelPropagationContext(num_iterations=3, lp_kernel=kernel)
        out[kernel] = np.asarray(
            LPClustering(ctx).compute_clustering(g, max_cluster_weight=30)
        )
    assert np.array_equal(out["xla"], out["pallas"])


def test_resolve_lp_kernel():
    assert pallas_lp.resolve_lp_kernel("xla") == "xla"
    assert pallas_lp.resolve_lp_kernel("pallas") == "pallas"
    # CPU test environment: auto falls back to the XLA lowering.
    assert pallas_lp.resolve_lp_kernel("auto") in ("xla", "pallas")
    with pytest.raises(ValueError, match="lp_kernel"):
        pallas_lp.resolve_lp_kernel("mosaic")


def test_lp_kernel_config_roundtrip():
    from kaminpar_tpu.config import dump_toml, load_toml
    from kaminpar_tpu.context import Context

    ctx = Context()
    ctx.coarsening.lp.lp_kernel = "pallas"
    ctx2 = load_toml(dump_toml(ctx))
    assert ctx2.coarsening.lp.lp_kernel == "pallas"


# ---------------------------------------------------------------------------
# Shape buckets
# ---------------------------------------------------------------------------


def test_next_shape_bucket_ladder():
    from kaminpar_tpu.utils.intmath import next_shape_bucket

    prev = 0
    for x in [0, 1, 7, 255, 256, 300, 400, 511, 512, 700, 724, 1000, 5000,
              40347, 65536, 10**6]:
        b = next_shape_bucket(x, 256)
        assert b > x, (x, b)
        assert b >= 256
        # sqrt(2) ladder: never more than ~45% slack (alignment adds a hair)
        assert b <= max(256, int(x * 1.5) + 128), (x, b)
        assert b >= prev or x < prev  # monotone in x
    # O(log n) distinct buckets across 5 decades, ~2 per octave.
    buckets = {next_shape_bucket(x, 256) for x in range(1, 10**6, 997)}
    assert len(buckets) <= 2 * 21  # 2 rungs x log2(1e6) octaves


def test_contraction_invariant_to_padding(rng):
    """Pad slots/nodes are exactly inert in contraction: inflating the
    padding must produce the identical coarse graph."""
    import kaminpar_tpu.graph.csr as csr_mod
    from kaminpar_tpu.graph.csr import CSRGraph
    from kaminpar_tpu.ops.contraction import contract_clustering

    edges = rng.integers(0, 150, (400, 2))
    g1 = generators.from_edge_list(150, edges)
    labels = rng.integers(0, 150, 150).astype(np.int32)

    coarse1, _ = contract_clustering(
        g1, g1.padded().pad_node_array(jnp.asarray(labels), g1.padded().anchor)
    )
    orig = csr_mod._next_bucket
    try:
        csr_mod._next_bucket = lambda x, minimum=256: orig(x, 2048)
        g2 = CSRGraph(g1.row_ptr, g1.col_idx, g1.node_w, g1.edge_w)
        coarse2, _ = contract_clustering(
            g2, g2.padded().pad_node_array(jnp.asarray(labels), g2.padded().anchor)
        )
    finally:
        csr_mod._next_bucket = orig
    assert coarse1.n == coarse2.n and coarse1.m == coarse2.m
    for attr in ("row_ptr", "col_idx", "node_w", "edge_w"):
        assert np.array_equal(
            np.asarray(getattr(coarse1, attr)), np.asarray(getattr(coarse2, attr))
        ), attr


def test_num_labels_bucket_refinement_identical(rng):
    """Padding the label space (refinement k ladder -> one bucket) is
    bit-inert: the same round on num_labels=k and num_labels=bucket(k)
    commits identical labels."""
    g = generators.rmat_graph(9, 8, seed=6)
    pv = g.padded()
    bv = g.bucketed()
    k = 5
    k_pad = lp.num_labels_bucket(k)
    assert k_pad >= 64
    part = pv.pad_node_array(
        jnp.asarray(rng.integers(0, k, g.n).astype(np.int32)), 0
    )
    max_w = jnp.full(k, int(g.total_node_weight / k * 1.2), dtype=pv.node_w.dtype)
    max_w_pad = jnp.concatenate(
        [max_w, jnp.zeros(k_pad - k, dtype=max_w.dtype)]
    )
    st_a = lp.init_state(part, pv.node_w, k)
    st_b = lp.init_state(part, pv.node_w, k_pad)
    key = next_key()
    st_a = lp.lp_round_bucketed(
        st_a, key, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w, max_w,
        num_labels=k,
    )
    st_b = lp.lp_round_bucketed(
        st_b, key, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w, max_w_pad,
        num_labels=k_pad,
    )
    assert bool(jnp.all(st_a.labels == st_b.labels))
    assert bool(jnp.all(st_b.label_weights[k:] == 0))
    assert bool(jnp.all(st_a.label_weights == st_b.label_weights[:k]))


def _run_vcycle(scale: int, k: int = 16):
    from kaminpar_tpu.graph.metrics import edge_cut, is_feasible
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name
    from kaminpar_tpu.utils import compile_stats

    g = generators.rmat_graph(scale, edge_factor=8, seed=1)
    ctx = create_context_by_preset_name("vcycle")
    ctx.vcycles = (4,)
    s = KaMinPar(ctx)
    s.set_graph(g)
    compile_stats.reset()
    part = s.compute_partition(k=k, epsilon=0.03)
    assert is_feasible(g, part, k, s.ctx.partition.max_block_weights)
    return compile_stats.snapshot(), int(edge_cut(g, part))


def test_vcycle_shape_bucket_count_small():
    """Fast census bound: a small v-cycle touches O(log n) padded
    LP/contraction shape buckets."""
    snap, _ = _run_vcycle(11)
    assert snap.get("padded_bucket", 0) <= 12, snap


def test_pallas_round_tpu_lowering(monkeypatch):
    """Mosaic TPU-lowering frontier for the fused round (compiled path, not
    interpret).  On this jaxlib generation Pallas TPU lowering lacks the
    dynamic `gather` primitive the VMEM label lookup needs, so the export
    xfails with that exact signal; on toolchains that implement it
    (tpu.DynamicGatherOp) this test asserts the whole round lowers, so
    first silicon contact measures instead of debugging."""
    from jax import export as jexport

    monkeypatch.setattr(pallas_lp, "_interpret", lambda: False)
    g = generators.rmat_graph(8, 8, seed=2)
    pv, bv, state = _init(g)
    max_w = jnp.asarray(30, dtype=pv.row_ptr.dtype)

    def f(state, key):
        return pallas_lp.lp_round_bucketed(
            state, key, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w,
            max_w, num_labels=pv.n_pad,
        )

    try:
        exp = jexport.export(jax.jit(f), platforms=("tpu",))(
            state, jax.random.PRNGKey(0)
        )
    except NotImplementedError as e:
        pytest.xfail(f"Mosaic lowering gap on this jaxlib: {e}")
    except Exception as e:  # noqa: BLE001 - lowering infra varies by version
        pytest.xfail(f"TPU export unavailable here: {type(e).__name__}: {e}")
    assert len(exp.serialize()) > 0


@pytest.mark.slow
def test_vcycle_shape_bucket_count_scale16():
    """Acceptance bound (ISSUE 1): a scale-16 CPU v-cycle stays within 12
    distinct LP/contraction shape buckets; executable-level specialization
    counts are reported by bench.py alongside."""
    snap, _ = _run_vcycle(16)
    assert snap.get("padded_bucket", 0) <= 12, snap
