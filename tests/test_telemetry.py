"""Unified telemetry (ISSUE 5): run-trace spans, per-level quality probes,
Chrome-trace export, phase registry, serve metrics exposition.

The contracts under test:

- a run under ``telemetry.run`` exports valid Chrome trace-event JSON
  (monotonic per-thread timestamps, matched B/E pairs) with spans for every
  top-level phase plus per-level quality counter samples;
- the quality probes are *sync-budget neutral*: arming telemetry changes
  neither the blocking-transfer counts per phase nor the computed partition
  (probes either reuse already-pulled host values or pack scalars into
  existing pulls);
- ``tools trace`` validates and round-trips a trace file;
- ``engine.metrics_text()`` parses as Prometheus text exposition and carries
  queue depth, occupancy, and latency percentiles;
- the timer tree survives concurrent scopes from engine worker threads
  (per-thread subtrees merged at report time);
- the canonical phase registry and the source tree cannot drift apart.
"""

import json
import re
import threading
from pathlib import Path

import numpy as np
import pytest

import kaminpar_tpu
from kaminpar_tpu import telemetry
from kaminpar_tpu.context import Context, PartitioningMode
from kaminpar_tpu.graph import generators
from kaminpar_tpu.telemetry import phases, prometheus
from kaminpar_tpu.telemetry import trace as ttrace
from kaminpar_tpu.utils import sync_stats


@pytest.fixture(autouse=True)
def _clean_telemetry_state():
    ttrace.stop()  # a leaked recorder from a failed test must not cascade
    sync_stats.reset()
    yield
    ttrace.stop()
    sync_stats.reset()


def _deep_ctx(k=4, contraction_limit=100):
    ctx = Context()
    ctx.mode = PartitioningMode.DEEP
    ctx.partition.k = k
    ctx.coarsening.contraction_limit = contraction_limit
    return ctx


def _partition(graph, ctx, k):
    from kaminpar_tpu.kaminpar import KaMinPar

    solver = KaMinPar(ctx=ctx)
    solver.set_graph(graph)
    return np.asarray(solver.compute_partition(k, epsilon=0.03))


# -- trace export ------------------------------------------------------------


def test_trace_export_valid_with_phase_spans_and_quality(tmp_path):
    """Acceptance: a traced run produces a file that loads as valid Chrome
    trace JSON and contains spans for the top-level phases plus per-level
    quality counter samples."""
    g = generators.rmat_graph(9, 8, seed=1)
    out = tmp_path / "trace.json"
    with telemetry.run(trace_out=str(out)) as rec:
        _partition(g, _deep_ctx(contraction_limit=50), 4)
    assert rec.quality, "no quality rows recorded"
    kinds = {row["kind"] for row in rec.quality}
    assert "coarsening_level" in kinds
    assert "level_quality" in kinds  # packed cut/imbalance probe fired

    obj = json.loads(out.read_text())
    summary = telemetry.validate_chrome_trace(obj)  # raises on malformation
    assert summary["spans"] > 0 and summary["counters"] > 0
    for phase in ("partitioning", "coarsening", "initial_partitioning",
                  "lp_clustering"):
        assert phase in summary["span_names"], summary["span_names"]
    assert "quality/coarsening_level" in summary["counter_names"]
    assert "quality/level_quality" in summary["counter_names"]
    assert "host_sync" in summary["counter_names"]
    assert summary["quality_rows"] == len(rec.quality)
    # level_quality rows carry the packed cut + derived imbalance
    lq = [r for r in rec.quality if r["kind"] == "level_quality"]
    assert all(r["cut"] is not None and r["cut"] >= 0 for r in lq)
    assert any(r["imbalance"] is not None for r in lq)


def test_quality_probes_budget_neutral_and_bit_identical():
    """Arming telemetry changes neither the per-phase blocking-transfer
    counts nor the partition itself (the probes' zero-extra-transfers
    contract, end to end on the deep pipeline)."""
    counts = {}
    parts = {}
    rows = 0
    for armed in (False, True):
        sync_stats.reset()
        g = generators.rmat_graph(10, 8, seed=3)
        ctx = _deep_ctx(k=4, contraction_limit=100)
        ctx.seed = 7
        if armed:
            with telemetry.run() as rec:
                parts[armed] = _partition(g, ctx, 4)
            rows = len(rec.quality)
        else:
            parts[armed] = _partition(g, ctx, 4)
        snap = sync_stats.snapshot()["phases"]
        counts[armed] = {
            ph: snap.get(ph, {"count": 0})["count"]
            for ph in ("coarsening", "initial_partitioning",
                       "extend_partition", "lp_refinement", "clp_refinement")
        }
    assert counts[False] == counts[True], counts
    assert np.array_equal(parts[False], parts[True])
    assert rows > 0


def test_clp_cut_probe_rides_existing_pull():
    """The CLP refiner's per-round cut probe packs into the per-iteration
    moved-count pull: same transfer count, identical result."""
    from kaminpar_tpu.context import ColoredLPContext
    from kaminpar_tpu.graph.partitioned import PartitionedGraph
    from kaminpar_tpu.refinement.clp_refiner import CLPRefiner
    from kaminpar_tpu.utils import reseed

    g = generators.grid2d_graph(16, 16)
    rng = np.random.default_rng(0)
    part = (np.arange(256) // 64).astype(np.int32)
    flip = rng.random(256) < 0.2
    part[flip] = rng.integers(0, 4, flip.sum())
    W = int(np.asarray(g.node_w).sum())
    caps = np.full(4, int(np.ceil(W / 4) * 1.1) + 1, dtype=np.int64)

    results = {}
    pulls = {}
    for armed in (False, True):
        reseed(11)
        sync_stats.reset()
        pg = PartitionedGraph.create(g, 4, part.copy(), caps)
        if armed:
            with telemetry.run() as rec:
                out = CLPRefiner(ColoredLPContext()).refine(pg)
            clp_rows = [r for r in rec.quality if r["kind"] == "clp_refinement"]
            assert clp_rows and all(r["cut"] is not None for r in clp_rows)
        else:
            out = CLPRefiner(ColoredLPContext()).refine(pg)
        results[armed] = np.asarray(out.partition)
        pulls[armed] = sync_stats.snapshot()["phases"]["clp_refinement"]["count"]
    assert pulls[False] == pulls[True]
    assert np.array_equal(results[False], results[True])


# -- validation + tools round-trip ------------------------------------------


def test_validate_rejects_malformed_traces():
    rec = ttrace.TraceRecorder()
    rec.begin("a")
    rec.end("a")
    ok = rec.chrome_trace()
    telemetry.validate_chrome_trace(ok)

    bad_unmatched = {"traceEvents": [
        {"name": "x", "ph": "B", "ts": 1.0, "pid": 1, "tid": 0}]}
    with pytest.raises(ValueError, match="unmatched"):
        telemetry.validate_chrome_trace(bad_unmatched)

    bad_order = {"traceEvents": [
        {"name": "x", "ph": "B", "ts": 5.0, "pid": 1, "tid": 0},
        {"name": "x", "ph": "E", "ts": 4.0, "pid": 1, "tid": 0}]}
    with pytest.raises(ValueError, match="backwards"):
        telemetry.validate_chrome_trace(bad_order)

    bad_cross = {"traceEvents": [
        {"name": "x", "ph": "B", "ts": 1.0, "pid": 1, "tid": 0},
        {"name": "y", "ph": "E", "ts": 2.0, "pid": 1, "tid": 0}]}
    with pytest.raises(ValueError, match="does not match"):
        telemetry.validate_chrome_trace(bad_cross)

    bad_counter = {"traceEvents": [
        {"name": "c", "ph": "C", "ts": 1.0, "pid": 1, "tid": 0,
         "args": {"v": "not-a-number"}}]}
    with pytest.raises(ValueError, match="numeric"):
        telemetry.validate_chrome_trace(bad_counter)


def test_open_spans_closed_at_export():
    """A span still open at export gets a synthetic close so the written
    file always validates (e.g. an engine thread mid-request at stop)."""
    rec = ttrace.TraceRecorder()
    rec.begin("outer")
    rec.begin("inner")
    summary = telemetry.validate_chrome_trace(rec.chrome_trace())
    assert summary["spans"] == 2


def test_tools_trace_roundtrip(tmp_path, capsys):
    rec = ttrace.TraceRecorder()
    rec.begin("partitioning")
    rec.counter("host_sync", {"count": 1, "bytes": 64})
    rec.quality_row("coarsening_level", level=0, n=100, m=400, n_c=40, m_c=120,
                    shrink=0.6)
    rec.end("partitioning")
    src = tmp_path / "t.json"
    dst = tmp_path / "t2.json"
    rec.write(str(src))

    from kaminpar_tpu.tools.__main__ import main as tools_main

    rc = tools_main(["trace", str(src), "--out", str(dst), "--quality"])
    assert rc == 0
    stdout = capsys.readouterr().out
    assert "quality rows: 1" in stdout
    assert "coarsening_level" in stdout
    a = json.loads(src.read_text())
    b = json.loads(dst.read_text())
    assert a["traceEvents"] == b["traceEvents"]
    assert a["otherData"]["quality"] == b["otherData"]["quality"]
    # a corrupt file is rejected, not re-emitted
    src.write_text(json.dumps({"traceEvents": [
        {"name": "x", "ph": "B", "ts": 1.0, "pid": 1, "tid": 0}]}))
    assert tools_main(["trace", str(src)]) == 1


# -- serve metrics exposition ------------------------------------------------


def test_engine_metrics_text_is_valid_prometheus():
    from kaminpar_tpu.serve import PartitionEngine

    engine = PartitionEngine("serve")
    engine.start(warmup=False)
    try:
        fut = engine.submit(generators.rmat_graph(6, 4, seed=1), 2)
        fut.result(timeout=180)
        text = engine.metrics_text()
    finally:
        engine.shutdown(drain=True)
    families = prometheus.validate(text)  # raises on malformed exposition
    assert prometheus.get_sample(families, "kaminpar_serve_queue_depth") is not None
    assert prometheus.get_sample(
        families, "kaminpar_serve_requests_total", outcome="completed") >= 1
    assert prometheus.get_sample(
        families, "kaminpar_serve_batch_occupancy", stat="mean") >= 1
    for quantile in ("0.5", "0.99"):
        assert prometheus.get_sample(
            families, "kaminpar_serve_latency_ms",
            stage="total", quantile=quantile) is not None
    assert prometheus.get_sample(families, "kaminpar_serve_warm_hit_rate") is not None


def test_prometheus_render_and_validate_inverse():
    text = prometheus.render([
        ("x_total", "counter", "help with spaces", [({"a": "b\"c"}, 3)]),
        ("y", "gauge", "h", [({}, 1.5), ({"q": "0.5"}, None)]),
    ])
    families = prometheus.validate(text)
    assert families["x_total"] == [({"a": 'b\\"c'}, 3.0)]
    assert families["y"] == [({}, 1.5)]  # None sample skipped
    with pytest.raises(ValueError):
        prometheus.validate("junk line without value\n# TYPE junk gauge\n")


# -- timer thread-safety (satellite) ----------------------------------------


def test_timer_merges_concurrent_thread_subtrees():
    from kaminpar_tpu.utils.timer import Timer, scoped_timer

    Timer.reset_global()
    n_threads, iters = 6, 25

    def worker():
        for _ in range(iters):
            with scoped_timer("partitioning"):
                with scoped_timer("coarsening"):
                    pass

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    worker()  # the main thread participates concurrently
    for t in threads:
        t.join()
    timer = Timer.global_()
    merged = timer.merged_root()
    total = (n_threads + 1) * iters
    assert merged.children["partitioning"].starts == total
    assert merged.children["partitioning"].children["coarsening"].starts == total
    assert timer.phase_seconds("partitioning", "coarsening") is not None
    assert timer.machine_readable().startswith("TIME partitioning=")
    Timer.reset_global()


def test_threaded_engine_burst_keeps_timer_and_trace_consistent(tmp_path):
    """Regression (satellite): concurrent submits + the engine's dispatcher
    thread running scoped_timer scopes must corrupt neither the timer tree
    nor the trace's per-thread B/E nesting."""
    from kaminpar_tpu.serve import PartitionEngine
    from kaminpar_tpu.utils.timer import Timer

    out = tmp_path / "serve_trace.json"
    engine = PartitionEngine("serve", max_batch=4)
    with telemetry.run(trace_out=str(out)):
        engine.start(warmup=False)
        try:
            futures = []
            errors = []

            def submit_some(seed):
                try:
                    for i in range(2):
                        futures.append(engine.submit(
                            generators.rmat_graph(6, 4, seed=seed + i), 2))
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

            threads = [threading.Thread(target=submit_some, args=(10 * t,))
                       for t in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors
            for fut in futures:
                fut.result(timeout=300)
        finally:
            engine.shutdown(drain=True)
    # Matched-B/E validation per (pid, tid) is exactly the property the old
    # shared-stack timer raced on.
    summary = telemetry.validate_chrome_trace(json.loads(out.read_text()))
    assert summary["spans"] > 0
    assert "serve.batch" in summary["span_names"]
    assert "serve.queue" in summary["counter_names"]
    # The merged timer report stays renderable after the burst.
    assert isinstance(Timer.global_().render(), str)
    assert isinstance(Timer.global_().machine_readable(), str)


# -- logger JSON mode (satellite) -------------------------------------------


def test_logger_json_mode(monkeypatch, capsys):
    import sys

    from kaminpar_tpu.utils.logger import Logger, OutputLevel, log_result_line

    monkeypatch.setenv("KAMINPAR_TPU_LOG", "json")
    # Logger.stream binds sys.stdout at import; point it at capsys' capture.
    monkeypatch.setattr(Logger, "stream", sys.stdout)
    old_level = Logger.level
    Logger.level = OutputLevel.EXPERIMENT
    try:
        Logger.log("hello world")
        line = log_result_line(42, 0.015, True, 8, 1.25)
        Logger.warning("careful")
    finally:
        Logger.level = old_level
    assert line.startswith("RESULT cut=42 ")  # return value stays parseable
    captured = capsys.readouterr()
    records = [json.loads(row) for row in captured.out.splitlines()]
    assert records[0]["msg"] == "hello world"
    assert records[0]["level"] == "application"
    result = next(r for r in records if r.get("event") == "result")
    assert result["cut"] == 42 and result["k"] == 8 and result["feasible"] is True
    warn = json.loads(captured.err.splitlines()[-1])
    assert warn["level"] == "warning" and warn["msg"] == "careful"


def test_logger_plain_mode_unchanged(monkeypatch, capsys):
    import sys

    from kaminpar_tpu.utils.logger import Logger, OutputLevel, log_result_line

    monkeypatch.delenv("KAMINPAR_TPU_LOG", raising=False)
    monkeypatch.setattr(Logger, "stream", sys.stdout)
    old_level = Logger.level
    Logger.level = OutputLevel.EXPERIMENT
    try:
        log_result_line(7, 0.02, False, 2, 0.5)
    finally:
        Logger.level = old_level
    out = capsys.readouterr().out
    assert "RESULT cut=7 imbalance=0.02 feasible=0 k=2 time=0.5" in out


# -- phase registry drift (satellite) ---------------------------------------


_PHASE_LITERAL_PATTERNS = (
    re.compile(r'scoped_timer\(\s*"([a-z_]+)"'),
    re.compile(r'sync_stats\.scoped\(\s*"([a-z_]+)"'),
    re.compile(r'assert_phase_budget\(\s*"([a-z_]+)"'),
    re.compile(r'phase_count\(\s*"([a-z_]+)"'),
    re.compile(r'phase="([a-z_]+)"'),
)


def _library_phase_literals():
    pkg_root = Path(kaminpar_tpu.__file__).parent
    sources = list(pkg_root.rglob("*.py"))
    sources.append(pkg_root.parent / "bench.py")
    found = {}
    for path in sources:
        text = path.read_text()
        for pattern in _PHASE_LITERAL_PATTERNS:
            for name in pattern.findall(text):
                found.setdefault(name, set()).add(path.name)
    return found


def test_phase_registry_matches_source():
    """A misspelled phase in the library silently escaped the sync budget
    before the registry existed; now any drift — a source literal missing
    from the registry OR a registry entry no source uses — fails tier-1."""
    found = _library_phase_literals()
    unknown = {n: sorted(f) for n, f in found.items()
               if n not in phases.KNOWN_PHASES}
    assert not unknown, (
        f"phase names used in source but missing from the registry "
        f"(kaminpar_tpu/telemetry/phases.py): {unknown}"
    )
    # "untracked" is sync_stats' fallback phase, assigned, never a literal
    # at a scope site.
    stale = {n for n in phases.KNOWN_PHASES - {"untracked"} if n not in found}
    assert not stale, f"registry entries no source uses (remove or re-wire): {stale}"


def test_unknown_phase_warns_once():
    from kaminpar_tpu.utils.timer import scoped_timer

    phases._warned.discard("zz_not_a_phase")
    with pytest.warns(RuntimeWarning, match="phase registry"):
        with scoped_timer("zz_not_a_phase"):
            pass
    assert phases.is_known("coarsening")
    assert not phases.is_known("zz_not_a_phase")


# -- HBM watermark (satellite) ----------------------------------------------


def test_heap_watermark_report_shape():
    from kaminpar_tpu.utils import heap_profiler

    report = heap_profiler.watermark_report()
    assert report["budget_doc"] == "HBM_BUDGET.md"
    # Allocator stats are backend-dependent; when present they are ints and
    # the peak fraction is derived consistently.
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if key in report:
            assert isinstance(report[key], int)
    if "peak_frac_of_limit" in report:
        assert 0 <= report["peak_frac_of_limit"]
