"""Lane-stacked serve execution tests (ISSUE 6).

The hard contract: a lane-stacked batch result must be BIT-IDENTICAL to each
graph's own sequential ``KaMinPar.compute_partition`` run — across families,
shape buckets, k values, and lane counts (the tests/test_rng.py lane-count
invariance property extended to the full multilevel pipeline).  Fast tests
keep small graphs and reuse the scale-8 serve cells the rest of the tier
compiles anyway; the full family x bucket x k x lane-count sweep is @slow.
"""

import warnings

import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.presets import create_context_by_preset_name
from kaminpar_tpu.serve.engine import PartitionEngine
from kaminpar_tpu.serve.lanestack import (
    LaneStackUnsupported,
    check_eligibility,
    run_lanestacked,
)


def _rmat(seed, scale=8):
    return generators.rmat_graph(scale, edge_factor=4, seed=seed)


def _sequential(graphs, k, epsilon=0.03):
    out = []
    for g in graphs:
        solver = KaMinPar(ctx="serve")
        solver.set_graph(g)
        out.append(solver.compute_partition(k, epsilon))
    return out


def _assert_identical(graphs, k, epsilon=0.03):
    parts, report = run_lanestacked(
        create_context_by_preset_name("serve"), graphs, k, epsilon
    )
    expected = _sequential(graphs, k, epsilon)
    assert len(parts) == len(graphs)
    for i, (got, want) in enumerate(zip(parts, expected)):
        assert np.array_equal(got, want), (
            f"lane {i} differs from its sequential run "
            f"({int(np.sum(got != want))}/{got.size} labels)"
        )
    return report


# ---------------------------------------------------------------------------
# Runner-level bit-identity
# ---------------------------------------------------------------------------


def test_lanestack_identity_same_cell():
    """Four same-cell RMAT lanes, one stacked run == four sequential runs."""
    report = _assert_identical([_rmat(100 + s) for s in range(4)], k=4)
    assert report.lanes == 4
    assert report.stacked_pulls > 0


def test_lanestack_lane_count_invariance():
    """A lane's result is independent of how many other lanes ride the
    stack (the rng.lane_keys property at pipeline scale): the same graph
    through L=1, L=2, L=3 stacks equals its sequential run every time."""
    g = _rmat(7)
    solo = KaMinPar(ctx="serve")
    solo.set_graph(g)
    expected = solo.compute_partition(4, 0.03)
    for L in (1, 2, 3):
        graphs = [g] + [_rmat(100 + s) for s in range(L - 1)]
        parts, _ = run_lanestacked(
            create_context_by_preset_name("serve"), graphs, 4, 0.03
        )
        assert np.array_equal(parts[0], expected), f"lane 0 differs at L={L}"


def test_lanestack_census_counts_single_lane():
    """An L=1 stacked run (a single-request batch under lane_stack="on")
    records its stacked pulls in the sync census too, staying consistent
    with the engine's ``lanestacked_batches`` counter (regression: the
    old ``lanes > 1`` guard dropped them)."""
    from kaminpar_tpu.utils import sync_stats

    before = sync_stats.snapshot()
    _, report = run_lanestacked(
        create_context_by_preset_name("serve"), [_rmat(42)], 4, 0.03
    )
    after = sync_stats.snapshot()
    assert report.stacked_pulls > 0
    stacked = after["stacked_count"] - before["stacked_count"]
    assert stacked >= report.stacked_pulls
    # At L=1 each stacked pull carries exactly one logical lane pull.
    assert after["lane_pulls"] - before["lane_pulls"] == stacked


def test_lanestack_identity_ragged_mixed_sizes():
    """A ragged batch — lanes whose work graphs land in different shape
    buckets (a star's hub strip + two rmat sizes) — splits into cohorts and
    every lane still equals its sequential run."""
    graphs = [
        _rmat(3),
        generators.star_graph(255),
        _rmat(4, scale=7),
        _rmat(5),
    ]
    report = _assert_identical(graphs, k=4)
    assert report.cohorts >= 2  # mixed buckets cannot share one stack


def test_lanestack_identity_with_coarsening():
    """Scale 12 engages the multilevel hierarchy (contraction_limit 2000):
    lockstep coarsening levels, per-lane early-exit/convergence splits, and
    uncoarsen/refine all stay bit-identical; the per-level lane-accounted
    sync budget is asserted in-pipeline (sync_stats.assert_phase_budget)."""
    from kaminpar_tpu.utils import sync_stats

    # n = 4096 > 2 * contraction_limit with no isolated-node shrink (an
    # rmat at this scale strips below the threshold), so coarsening runs.
    graphs = [
        generators.grid2d_graph(64, 64),
        generators.grid2d_graph(32, 128),
    ]
    sync_stats.enable_budget_checks(True)
    try:
        report = _assert_identical(graphs, k=4)
    finally:
        sync_stats.enable_budget_checks(False)
    assert report.levels >= 1  # coarsening actually ran
    lane_pulls, stacked = sync_stats.lane_phase_count("lanestack_coarsening")
    assert stacked >= 1 and lane_pulls >= 2 * stacked


def test_lanestack_ineligibility():
    """Out-of-envelope configs raise :class:`LaneStackUnsupported` with the
    reason, before any device work."""
    from kaminpar_tpu.context import PartitioningMode

    ctx = create_context_by_preset_name("serve")
    ctx.mode = PartitioningMode.KWAY
    with pytest.raises(LaneStackUnsupported, match="mode"):
        check_eligibility(ctx, [_rmat(1)], 4)
    ctx = create_context_by_preset_name("serve")
    ctx.vcycles = 2
    with pytest.raises(LaneStackUnsupported, match="v-cycle"):
        check_eligibility(ctx, [_rmat(1)], 4)
    with pytest.raises(LaneStackUnsupported, match="k exceeds"):
        check_eligibility(
            create_context_by_preset_name("serve"), [_rmat(1)], 10**6
        )


# ---------------------------------------------------------------------------
# Engine integration: routing, stats, fallback, runtime isolation
# ---------------------------------------------------------------------------


def test_engine_lanestack_path_and_stats():
    """A burst of same-cell requests rides the lane-stacked path (counted
    in the stats census) and every result equals its sequential run."""
    eng = PartitionEngine("serve", warm_ladder=(), warm_ks=(),
                          max_batch=4, queue_bound=16, lane_stack="on")
    eng.pause()
    eng.start(warmup=False)
    try:
        futs = [eng.submit(_rmat(100 + s), 4) for s in range(4)]
        eng.resume()
        results = [f.result(timeout=600) for f in futs]
    finally:
        eng.shutdown(drain=True)
    expected = _sequential([_rmat(100 + s) for s in range(4)], 4)
    for res, want, g in zip(
        results, expected, [_rmat(100 + s) for s in range(4)]
    ):
        assert np.array_equal(res.partition, want)
        from kaminpar_tpu.graph import metrics

        assert res.cut == metrics.edge_cut(g, res.partition)
        assert res.feasible
    assert eng.stats_.counter("lanestacked_batches") >= 1
    assert eng.stats_.counter("lanestacked_lanes") >= 2
    snap = eng.stats()
    assert snap["lanestack_occupancy_mean"] >= 2


def test_engine_lanestack_fallback_loud_and_counted():
    """``lane_stack="on"`` with an out-of-envelope pipeline falls back to
    the per-graph loop with a RuntimeWarning and a counted fallback; the
    result is still correct."""
    ctx = create_context_by_preset_name("serve")
    ctx.vcycles = 1  # outside the lockstep envelope
    eng = PartitionEngine(ctx, warm_ladder=(), warm_ks=(),
                          max_batch=4, queue_bound=16, lane_stack="on")
    eng.pause()
    eng.start(warmup=False)
    try:
        # Same seed -> same shape cell -> exactly one micro-batch.
        futs = [eng.submit(_rmat(100), 4) for _ in range(2)]
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            eng.resume()
            parts = [f.result(timeout=600).partition for f in futs]
        assert any(
            issubclass(w.category, RuntimeWarning)
            and "lane-stack" in str(w.message)
            for w in caught
        )
    finally:
        eng.shutdown(drain=True)
    assert eng.stats_.counter("lanestack_fallbacks") == 1
    assert eng.stats_.counter("lanestacked_batches") == 0
    g = _rmat(100)
    for p in parts:
        assert p.shape == (g.n,) and p.max() < 4


def test_engine_lanestack_circuit_breaker(monkeypatch):
    """Three consecutive lane-stack *execution* failures latch the stacked
    path off for the engine: later batches skip the doomed attempt
    entirely (run_lanestacked is no longer invoked) while the per-graph
    loop keeps serving correct results, and the trip warns once."""
    from kaminpar_tpu.serve import lanestack as ls_mod

    calls = {"n": 0}

    def _boom(*args, **kwargs):
        calls["n"] += 1
        raise RuntimeError("injected lane-stack failure")

    monkeypatch.setattr(ls_mod, "run_lanestacked", _boom)
    eng = PartitionEngine("serve", warm_ladder=(), warm_ks=(),
                          max_batch=4, queue_bound=16, lane_stack="on")
    eng.start(warmup=False)
    g = _rmat(100)
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            # Single-request rounds: under lane_stack="on" even a 1-lane
            # batch attempts the stacked path, and one-at-a-time sync
            # submission makes the batch count deterministic (no
            # batch-window races on round boundaries).
            for _ in range(4):
                p = eng.partition(_rmat(100), 4)
                assert p.shape == (g.n,) and p.max() < 4
        assert any(
            issubclass(w.category, RuntimeWarning)
            and "disabling the stacked path" in str(w.message)
            for w in caught
        )
    finally:
        eng.shutdown(drain=True)
    assert calls["n"] == 3  # the 4th batch never attempted the stacked path
    assert eng.stats_.counter("lanestack_fallbacks") == 4
    assert eng.stats_.counter("lanestacked_batches") == 0


def test_engine_per_request_overrides_fall_back():
    """Explicit block-weight overrides are outside the stacked envelope —
    the batch silently (counted) takes the per-graph loop and honors them."""
    eng = PartitionEngine("serve", warm_ladder=(), warm_ks=(),
                          max_batch=4, queue_bound=16, lane_stack="auto")
    eng.pause()
    eng.start(warmup=False)
    try:
        g = _rmat(50)
        caps = [int(g.total_node_weight)] * 4
        futs = [
            eng.submit(_rmat(50), 4, max_block_weights=caps)
            for _ in range(2)
        ]
        eng.resume()
        for f in futs:
            f.result(timeout=600)
    finally:
        eng.shutdown(drain=True)
    assert eng.stats_.counter("lanestacked_batches") == 0
    assert eng.stats_.counter("lanestack_fallbacks") == 1


def test_lane_stack_mode_validated_and_normalized(monkeypatch):
    """An invalid configured ``lane_stack`` value raises at construction;
    env overrides are case-normalized and unknown env values disable the
    stacked path (a typo'd kill switch must never leave the feature on)."""
    with pytest.raises(ValueError, match="lane_stack"):
        PartitionEngine("serve", warm_ladder=(), warm_ks=(),
                        lane_stack="true")
    eng = PartitionEngine("serve", warm_ladder=(), warm_ks=(),
                          lane_stack="on")
    monkeypatch.setenv("KAMINPAR_TPU_LANE_STACK", "OFF")
    assert eng._lane_stack_mode() == "off"
    monkeypatch.setenv("KAMINPAR_TPU_LANE_STACK", "enabled")
    assert eng._lane_stack_mode() == "off"
    monkeypatch.delenv("KAMINPAR_TPU_LANE_STACK")
    assert eng._lane_stack_mode() == "on"


def test_two_engines_conflicting_configs_isolated():
    """ISSUE 6 satellite: two engines with conflicting layout/sync-timer
    configs coexist — no first-wins RuntimeWarning, independent behavior,
    both bit-identical to their own sequential references."""
    import copy

    ctx_a = create_context_by_preset_name("serve")
    ctx_a.parallel.device_layout_build = "host"
    ctx_a.parallel.sync_timers = False
    ctx_b = create_context_by_preset_name("serve")
    ctx_b.parallel.device_layout_build = "device"
    ctx_b.parallel.sync_timers = True

    g = _rmat(42)
    solo = KaMinPar(copy.deepcopy(ctx_a))
    solo.set_graph(g)
    expected = solo.compute_partition(4, 0.03)

    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        eng_a = PartitionEngine(ctx_a, warm_ladder=(), warm_ks=())
        eng_b = PartitionEngine(ctx_b, warm_ladder=(), warm_ks=())
        eng_a.start(warmup=False)
        eng_b.start(warmup=False)
        try:
            part_a = eng_a.partition(_rmat(42), 4)
            part_b = eng_b.partition(_rmat(42), 4)
        finally:
            eng_a.shutdown(drain=True)
            eng_b.shutdown(drain=True)
    assert eng_a.runtime.layout_build == "host"
    assert eng_b.runtime.layout_build == "device"
    assert eng_a.runtime.sync_timers is False
    assert eng_b.runtime.sync_timers is True
    # Identical results from both engines (the layout backends are
    # bit-identical by the PR 2 contract) and from the sequential run.
    assert np.array_equal(part_a, expected)
    assert np.array_equal(part_b, expected)


def test_retry_after_seeded_from_warmup():
    """ISSUE 6 satellite: after warmup the service-time EMA is seeded from
    the warmup report, so the first admission reject carries a real
    retry-after estimate before any completion."""
    eng = PartitionEngine(
        "serve", warm_ladder=(64,), warm_ks=(4,), max_batch=1, queue_bound=1
    )
    eng.start(warmup=True)
    try:
        assert eng.stats_.counter("completed") == 0
        assert eng.stats_.ema_service_s > 0.0
        est = eng.stats_.retry_after_estimate(queue_depth=4, max_batch=1)
        assert est >= 4 * eng.stats_.ema_service_s * 0.99
    finally:
        eng.shutdown(drain=True)


def test_retry_after_ema_unamortized_for_stacked_shares():
    """A lane-stacked request records execute_s = batch wall / occupancy
    for latency percentiles, but the retry-after EMA must take the
    UNAMORTIZED batch wall (``service_s``) — retry_after_estimate divides
    by the batch width itself, so feeding it the amortized share would
    double-count the occupancy and understate drain time by up to
    max_batch x."""
    from kaminpar_tpu.serve.stats import ServeStats

    stats = ServeStats()
    # 8-lane batch, 4 s wall: each request's latency share is 0.5 s but
    # the dispatch that serves a queue slot costs 4 s.
    for _ in range(8):
        stats.record_request(0.1, 0.5, service_s=4.0)
    assert stats.ema_service_s == pytest.approx(4.0)
    # depth 16, max_batch 8 -> two more stacked dispatches ~ 8 s of drain.
    est = stats.retry_after_estimate(queue_depth=16, max_batch=8)
    assert est == pytest.approx(8.0)
    # Per-graph path unchanged: service_s defaults to execute_s.
    plain = ServeStats()
    plain.record_request(0.1, 0.5)
    assert plain.ema_service_s == pytest.approx(0.5)


def test_warmup_report_lanestack_cells():
    """``warm_lanes`` warms the lane-stacked pipeline and records
    kind="lanestack" rows (printed by ``tools warmup``).  A k < 2 cell is
    outside the lane-stack envelope per-cell only: it must be skipped, not
    abort the warm pass for the remaining k (regression)."""
    eng = PartitionEngine(
        "serve", warm_ladder=(64,), warm_ks=(1, 4), warm_lanes=(2,),
        max_batch=4, queue_bound=8,
    )
    eng.start(warmup=True)
    try:
        rows = [r for r in eng.warmup_report if r.get("kind") == "lanestack"]
        assert len(rows) == 1 and rows[0]["k"] == 4
        assert rows[0]["lanes"] == 2 and rows[0]["wall_s"] > 0
    finally:
        eng.shutdown(drain=True)


# ---------------------------------------------------------------------------
# The full sweep (heavy): families x buckets x k x lane counts
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_lanestack_identity_sweep():
    families = {
        "rmat": lambda scale, seed: generators.rmat_graph(
            scale, edge_factor=4, seed=seed
        ),
        "grid": lambda scale, seed: generators.grid2d_graph(
            1 << (scale // 2), 1 << (scale - scale // 2)
        ),
        "star": lambda scale, seed: generators.star_graph((1 << scale) - 1),
    }
    for name, fn in families.items():
        for scale in (8, 10):  # two node buckets
            for k in (4, 8):
                for L in (2, 4):
                    graphs = [fn(scale, 300 + s) for s in range(L)]
                    parts, _ = run_lanestacked(
                        create_context_by_preset_name("serve"),
                        graphs, k, 0.03,
                    )
                    expected = _sequential(graphs, k)
                    for i, (got, want) in enumerate(zip(parts, expected)):
                        assert np.array_equal(got, want), (
                            name, scale, k, L, i
                        )
