"""Test configuration: CPU backend with a virtual 8-device mesh.

Mirrors the reference's KaTestrophe trick (oversubscribed single-machine MPI,
tests/cmake/KaTestrophe.cmake) with the JAX equivalent per SURVEY §4: force 8
host platform devices so distributed logic is tested on one box.  The forcing
recipe lives in ``kaminpar_tpu.utils.platform.force_cpu_devices`` (shared with
``__graft_entry__``); it works even when a site hook pre-imported jax because
backends initialize lazily.
"""

import os
import sys

# Exercise the persistent XLA cache in CI (VERDICT r3 weak #8: the cache
# path must not ship blind).  The round-3 CPU serializer crashes traced to
# AOT executable caching, which kaminpar_tpu/__init__.py keeps disabled
# (jax_persistent_cache_enable_xla_caches="none"); with that off the cache
# is stable on CPU and makes warm suite runs dramatically faster.  Must be
# set before kaminpar_tpu is first imported.
os.environ.setdefault(
    "KAMINPAR_TPU_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".xla_cache"),
)

_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _repo_root not in sys.path:
    sys.path.insert(0, _repo_root)

from kaminpar_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(8)

import time  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reseed():
    from kaminpar_tpu.utils import reseed

    reseed(42)
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# -- tier-1 wall watch (ISSUE 12 satellite) ----------------------------------
#
# Full suite runs append one kind="tier1" ledger entry (suite wall, pass/
# fail counts, top-20 slowest tests) so `tools regress` catches the creep
# toward the 870 s budget (ROADMAP operational item; PR 8 landed ~13.2 min).
# Gated on a minimum test count so `-k` subset runs never pollute the
# regress baseline window, and on KPTPU_LEDGER like every other writer.

_TIER1_MIN_TESTS = 150
_tier1 = {"t0": time.time(), "durations": [], "passed": 0,
          "failed_ids": set()}


def pytest_runtest_logreport(report):
    # Failures count from EVERY phase (a fixture that breaks during setup
    # must not let the suite log a green tier1 entry), deduped per test so
    # a call failure + teardown error is one failed test, not two.
    if report.failed:
        _tier1["failed_ids"].add(report.nodeid)
        return
    if report.when != "call":
        return
    _tier1["durations"].append((float(report.duration), report.nodeid))
    if report.passed:
        _tier1["passed"] += 1


def pytest_sessionfinish(session, exitstatus):
    failed = len(_tier1["failed_ids"])
    ran = _tier1["passed"] + failed
    if ran < _TIER1_MIN_TESTS or os.environ.get("KPTPU_LEDGER", "1") == "0":
        return
    try:
        from kaminpar_tpu.telemetry import ledger

        slowest = sorted(_tier1["durations"], reverse=True)[:20]
        # Per-module wall rollup (round 18): the fleet suite joined the
        # tier-1 budget — a per-file view catches a single suite creeping
        # toward the 870 s ceiling before the total does.
        module_walls: dict = {}
        for dur, nid in _tier1["durations"]:
            module_walls[nid.split("::")[0]] = (
                module_walls.get(nid.split("::")[0], 0.0) + dur
            )
        top_modules = sorted(
            module_walls.items(), key=lambda kv: kv[1], reverse=True
        )[:10]
        record = {
            "backend": "cpu",
            "tier1_wall_s": round(time.time() - _tier1["t0"], 1),
            "tier1_tests": ran,
            "tier1_failed": failed,
        }
        entry = ledger.build_entry(
            record, kind="tier1",
            extra={
                "slowest": [
                    {"nodeid": nid, "s": round(dur, 2)}
                    for dur, nid in slowest
                ],
                "module_walls": [
                    {"module": mod, "s": round(wall, 1)}
                    for mod, wall in top_modules
                ],
            },
        )
        ledger.append(entry)
    except Exception:  # noqa: BLE001 — the wall watch must never fail a run
        pass
