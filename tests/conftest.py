"""Test configuration: CPU backend with a virtual 8-device mesh.

Mirrors the reference's KaTestrophe trick (oversubscribed single-machine MPI,
tests/cmake/KaTestrophe.cmake) with the JAX equivalent per SURVEY §4: force 8
host platform devices so distributed logic is tested on one box.  The forcing
recipe lives in ``kaminpar_tpu.utils.platform.force_cpu_devices`` (shared with
``__graft_entry__``); it works even when a site hook pre-imported jax because
backends initialize lazily.
"""

import os
import sys

# Exercise the persistent XLA cache in CI (VERDICT r3 weak #8: the cache
# path must not ship blind).  The round-3 CPU serializer crashes traced to
# AOT executable caching, which kaminpar_tpu/__init__.py keeps disabled
# (jax_persistent_cache_enable_xla_caches="none"); with that off the cache
# is stable on CPU and makes warm suite runs dramatically faster.  Must be
# set before kaminpar_tpu is first imported.
os.environ.setdefault(
    "KAMINPAR_TPU_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".xla_cache"),
)

_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _repo_root not in sys.path:
    sys.path.insert(0, _repo_root)

from kaminpar_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reseed():
    from kaminpar_tpu.utils import reseed

    reseed(42)
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(0)
