"""Test configuration: CPU backend with a virtual 8-device mesh.

Mirrors the reference's KaTestrophe trick (oversubscribed single-machine MPI,
tests/cmake/KaTestrophe.cmake) with the JAX equivalent per SURVEY §4: force 8
host platform devices so distributed logic is tested on one box.  The forcing
recipe lives in ``kaminpar_tpu.utils.platform.force_cpu_devices`` (shared with
``__graft_entry__``); it works even when a site hook pre-imported jax because
backends initialize lazily.
"""

import os
import sys

# The persistent XLA cache must stay off under the CPU backend: jaxlib's
# executable serializer intermittently SIGSEGV/SIGABRTs in
# put_executable_and_time (kaminpar_tpu/__init__.py note).  Must be set
# before kaminpar_tpu is first imported.
os.environ.setdefault("KAMINPAR_TPU_NO_CACHE", "1")

_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _repo_root not in sys.path:
    sys.path.insert(0, _repo_root)

from kaminpar_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reseed():
    from kaminpar_tpu.utils import reseed

    reseed(42)
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(0)
