"""Test configuration: CPU backend with a virtual 8-device mesh.

Mirrors the reference's KaTestrophe trick (oversubscribed single-machine MPI,
tests/cmake/KaTestrophe.cmake) with the JAX equivalent per SURVEY §4: force 8
host platform devices so distributed logic is tested on one box.  Must run
before jax initializes, hence the env mutation at import time.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # override: tests never touch the TPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Avoid the axon TPU-tunnel site hook for CPU-only tests: it force-initializes
# the tunnel backend even under JAX_PLATFORMS=cpu.
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _repo_root not in sys.path:
    sys.path.insert(0, _repo_root)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reseed():
    from kaminpar_tpu.utils import reseed

    reseed(42)
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(0)
