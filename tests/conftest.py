"""Test configuration: CPU backend with a virtual 8-device mesh.

Mirrors the reference's KaTestrophe trick (oversubscribed single-machine MPI,
tests/cmake/KaTestrophe.cmake) with the JAX equivalent per SURVEY §4: force 8
host platform devices so distributed logic is tested on one box.  Must run
before jax initializes, hence the env mutation at import time.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # override: tests never touch the TPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# A site hook may import jax at interpreter startup, in which case jax has
# already read JAX_PLATFORMS from the ambient env (possibly a TPU tunnel) and
# the os.environ override above is a no-op.  jax.config.update still works at
# this point because backends initialize lazily on first use, not on import.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _repo_root not in sys.path:
    sys.path.insert(0, _repo_root)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reseed():
    from kaminpar_tpu.utils import reseed

    reseed(42)
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(0)
