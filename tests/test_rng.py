"""Per-lane counter-based RNG scheme (round 9, ISSUE 4 satellite).

The ROADMAP's lane-stacking item needs identity-preserving per-lane streams:
lane i's draws must depend only on (seed, i) — invariant to the number of
lanes launched beside it, to the execution order (vmap vs scan vs Python
loop), and to process restarts.  ``utils/rng.lane_key(s)`` delivers exactly
that via ``jax.random.fold_in``; these tests pin the properties down.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.rng import lane_key, lane_keys


def _draw(key):
    return jax.random.randint(key, (8,), 0, jnp.iinfo(jnp.int32).max,
                              dtype=jnp.int32)


def test_lane_keys_lane_count_invariant():
    """lane_keys(s, R)[i] == lane_key(s, i) for every R > i: adding lanes
    never perturbs existing lanes' streams."""
    small = jax.random.key_data(lane_keys(123, 4))
    big = jax.random.key_data(lane_keys(123, 16))
    np.testing.assert_array_equal(np.asarray(small), np.asarray(big)[:4])
    for i in range(4):
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(lane_key(123, i))),
            np.asarray(small)[i],
        )


def test_lane_draws_vmap_scan_loop_identical():
    """The same per-lane draws under all three execution orders — the
    property that makes vmapped pool lanes interchangeable with a
    sequential repetition loop."""
    R = 6
    keys = lane_keys(7, R)
    via_vmap = np.asarray(jax.vmap(_draw)(keys))
    _, via_scan = jax.lax.scan(lambda c, k: (c, _draw(k)), None, keys)
    via_loop = np.stack([np.asarray(_draw(lane_key(7, i))) for i in range(R)])
    np.testing.assert_array_equal(via_vmap, np.asarray(via_scan))
    np.testing.assert_array_equal(via_vmap, via_loop)


def test_lane_keys_distinct():
    data = np.asarray(jax.random.key_data(lane_keys(3, 32)))
    assert len({tuple(row) for row in data}) == 32


def test_lane_draws_stable_across_process_restart():
    """A fresh interpreter derives bit-identical lane streams from the same
    seed — the property that makes device-pool partitions reproducible
    across runs and across the serve engine's restarts."""
    code = (
        "import jax, numpy as np\n"
        "from kaminpar_tpu.utils.rng import lane_keys\n"
        "d = jax.random.randint(lane_keys(99, 3)[1], (4,), 0, 2**31 - 1,"
        " dtype='int32')\n"
        "print(','.join(str(int(x)) for x in np.asarray(d)))\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-500:]
    child = [int(x) for x in out.stdout.strip().splitlines()[-1].split(",")]
    here = jax.random.randint(
        lane_keys(99, 3)[1], (4,), 0, 2**31 - 1, dtype="int32"
    )
    assert child == [int(x) for x in np.asarray(here)]
