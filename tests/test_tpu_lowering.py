"""AOT TPU lowering of the full kernel set (VERDICT r3 next-steps #2).

The TPU tunnel can be down for a whole round; this test guarantees every
kernel — shm, 64-bit variants, and the shard_map distributed rounds on the
8-device mesh — lowers cleanly through ``jax.export`` for ``platforms=['tpu']``
so first silicon contact measures instead of debugging.  Lowering-rule
failures (unsupported primitives, int64 sorts, degenerate shapes, collectives)
surface here; Mosaic/XLA-TPU compile-time failures still need the chip.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from kaminpar_tpu.utils.aot import (
    AotExportError,
    export_kernel_suite,
    suite_total_bytes,
)


def test_kernel_suite_lowers_for_tpu():
    devs = jax.devices()
    mesh = Mesh(np.array(devs[:8]), ("nodes",)) if len(devs) >= 8 else None
    try:
        sizes = export_kernel_suite(
            platforms=("tpu",), include_dist=mesh is not None, mesh=mesh
        )
    except AotExportError as e:
        pytest.fail(str(e))
    # Full sweep: shm + x64 variants (+ dist rounds when the mesh exists).
    assert len(sizes) >= 32, sorted(sizes)
    assert all(n > 0 for n in sizes.values())
    # Spot-check the headline kernels are present.
    for name in (
        "lp_iterate_bucketed",
        "lp_round_bucketed_heavy",
        "contraction",
        "jet_move_round",
        "balance_round",
        "lp_iterate_bucketed_x64",
        "contraction_x64",
        # Serve batch kernels (ISSUE 3): engine warmup on silicon must not
        # be the first place they meet the TPU lowering rules.
        "serve_packed_metrics",
        # The lane-vmapped initial-bipartitioning pool (ISSUE 4), both
        # index widths — engine warmup compiles it per cell at startup.
        "ip_pool",
        "ip_pool_x64",
        # Decode-fused compressed kernels (ISSUE 10): both edge-stream
        # trace switches of the sweep loop, the flat decode, and
        # contraction-off-the-stream — the terapart device tier's cells,
        # counted in suite_total_bytes like every other family.
        "lp_iterate_compressed",
        "lp_iterate_compressed_uniform",
        "lp_two_hop_compressed",
        "decode_flat_padded",
        "contract_compressed",
    ):
        assert name in sizes
    # Cumulative serialized size is the suite's budget metric: a serialized
    # StableHLO module is never under ~1 KB, so a truncated/empty export
    # (the failure mode warmup would otherwise hit first on silicon) drags
    # the total below the per-kernel floor.
    assert suite_total_bytes(sizes) >= len(sizes) * 800
    if mesh is not None:
        for name in (
            "dist_lp_round",
            "dist_cluster_round",
            "dist_coloring",
            "dist_jet_round",
            "dist_contract_s1",
        ):
            assert name in sizes
