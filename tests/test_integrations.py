"""NetworKit adapter (reference: bindings/networkit).  NetworKit itself is
not bundled; a duck-typed stand-in exercises the same protocol surface the
real networkit.Graph exposes."""

import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.metrics import edge_cut, is_feasible
from kaminpar_tpu.integrations import KaMinParNetworKit
from kaminpar_tpu.integrations.networkit import networkit_to_csr


class FakeNkGraph:
    """Duck-typed networkit.Graph over one of our CSR graphs."""

    def __init__(self, g, weighted=False, directed=False):
        self.rp = np.asarray(g.row_ptr)
        self.col = np.asarray(g.col_idx)
        self.w = np.asarray(g.edge_w)
        self._weighted = weighted
        self._directed = directed

    def numberOfNodes(self):
        return len(self.rp) - 1

    def isWeighted(self):
        return self._weighted

    def isDirected(self):
        return self._directed

    def iterNeighbors(self, u):
        yield from self.col[self.rp[u]: self.rp[u + 1]]

    def iterNeighborsWeights(self, u):
        for e in range(self.rp[u], self.rp[u + 1]):
            yield self.col[e], float(self.w[e])


def test_networkit_roundtrip_and_partition():
    g = generators.grid2d_graph(16, 16)
    G = FakeNkGraph(g)
    csr = networkit_to_csr(G)
    assert csr.n == g.n and csr.m == g.m
    assert np.array_equal(np.asarray(csr.col_idx), np.asarray(g.col_idx))

    solver = KaMinParNetworKit(G, ctx="fast")
    part = solver.compute_partition_k(4)
    assert isinstance(part, list) and len(part) == g.n
    part = np.asarray(part)
    assert is_feasible(g, part, 4, solver.ctx.partition.max_block_weights)
    assert edge_cut(g, part) < 200  # grid 16x16 into quarters: far below random


def test_networkit_weighted_and_factors():
    g0 = generators.grid2d_graph(8, 8)
    G = FakeNkGraph(g0, weighted=True)
    csr = networkit_to_csr(G)
    assert int(np.asarray(csr.edge_w).sum()) == g0.total_edge_weight

    solver = KaMinParNetworKit(G, ctx="fast")
    part = solver.compute_partition_with_factors([0.6, 0.6])
    bw = np.bincount(part, minlength=2)
    assert bw.max() <= int(np.ceil(0.6 * 64))

    with pytest.raises(ValueError, match="undirected"):
        networkit_to_csr(FakeNkGraph(g0, directed=True))
