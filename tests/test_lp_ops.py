"""LP engine kernel tests (reference: the LP engine is exercised through
lp_clusterer/lp_refiner tests; here we test the jitted rounds directly)."""

import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.graph import generators
from kaminpar_tpu.ops import lp
from kaminpar_tpu.utils import next_key


def _run_rounds(g, max_w_scalar, rounds=5):
    pv = g.padded()
    idt = pv.row_ptr.dtype
    labels = jnp.concatenate(
        [jnp.arange(pv.n, dtype=idt), jnp.full(pv.n_pad - pv.n, pv.anchor, dtype=idt)]
    )
    state = lp.init_state(labels, pv.node_w, pv.n_pad)
    max_w = jnp.full(pv.n_pad, max_w_scalar, dtype=idt)
    for _ in range(rounds):
        state = lp.lp_round(
            state, next_key(), pv.edge_u, pv.col_idx, pv.edge_w, pv.node_w,
            max_w, num_labels=pv.n_pad,
        )
    return pv, state, max_w


def test_lp_clusters_respect_weight_limit():
    g = generators.rmat_graph(9, 8, seed=2)
    pv, state, max_w = _run_rounds(g, 30)
    lw = np.asarray(state.label_weights)
    assert lw.max() <= 30
    assert lw.sum() == g.total_node_weight


def test_lp_merges_connected_nodes():
    g = generators.complete_graph(8)
    pv, state, _ = _run_rounds(g, 100)
    labels = np.asarray(state.labels)[: g.n]
    # complete graph with no weight limit pressure: everything merges
    assert len(np.unique(labels)) < 8


def test_lp_weight_conservation_on_grid():
    g = generators.grid2d_graph(8, 8)
    pv, state, _ = _run_rounds(g, 10)
    lw = np.asarray(state.label_weights)
    assert lw.sum() == 64
    assert lw.max() <= 10


def test_isolated_nodes_clustering():
    # 5 isolated nodes + one edge
    import numpy as np

    from kaminpar_tpu.graph import from_edge_list

    g = from_edge_list(7, np.array([[5, 6]]))
    pv = g.padded()
    idt = pv.row_ptr.dtype
    labels = jnp.concatenate(
        [jnp.arange(pv.n, dtype=idt), jnp.full(pv.n_pad - pv.n, pv.anchor, dtype=idt)]
    )
    state = lp.init_state(labels, pv.node_w, pv.n_pad)
    max_w = jnp.full(pv.n_pad, 2, dtype=idt)
    state = lp.cluster_isolated_nodes(state, pv.row_ptr, pv.node_w, max_w, num_labels=pv.n_pad)
    labels = np.asarray(state.labels)
    # isolated nodes 0..4 grouped in pairs of weight <= 2
    lw = np.asarray(state.label_weights)
    assert lw.max() <= 2
    iso_labels = labels[:5]
    # grouped: fewer clusters than nodes
    assert len(np.unique(iso_labels)) <= 3
    # pad nodes untouched (all on anchor)
    assert (labels[pv.n:] == pv.anchor).all()


def test_two_hop_clustering_on_star():
    # star: leaves can't join the center if its cluster is weight-capped,
    # but two-hop matches leaves pairwise through their favored cluster
    g = generators.star_graph(8)
    pv, state, max_w = _run_rounds(g, 2, rounds=3)
    state2 = lp.cluster_two_hop_nodes(
        state, next_key(), pv.edge_u, pv.col_idx, pv.edge_w, pv.node_w,
        max_w, num_labels=pv.n_pad,
    )
    lw = np.asarray(state2.label_weights)
    assert lw.max() <= 2
    n_clusters_before = len(np.unique(np.asarray(state.labels)[: g.n]))
    n_clusters_after = len(np.unique(np.asarray(state2.labels)[: g.n]))
    assert n_clusters_after <= n_clusters_before


def test_lp_refinement_mode_small_k():
    """LP with num_labels=k (block mode) reduces the cut of a bad partition."""
    from kaminpar_tpu.graph import metrics

    g = generators.grid2d_graph(8, 8)
    pv = g.padded()
    rng = np.random.default_rng(3)
    part = rng.integers(0, 2, g.n).astype(np.int32)
    init_cut = metrics.edge_cut(g, part)
    labels = pv.pad_node_array(jnp.asarray(part), 0)
    state = lp.init_state(labels, pv.node_w, 2)
    max_w = jnp.full(2, 40, dtype=pv.node_w.dtype)
    for _ in range(8):
        state = lp.lp_round(
            state, next_key(), pv.edge_u, pv.col_idx, pv.edge_w, pv.node_w,
            max_w, num_labels=2,
        )
    final_cut = metrics.edge_cut(g, np.asarray(state.labels)[: g.n])
    assert final_cut < init_cut
    bw = np.asarray(state.label_weights)
    assert bw.max() <= 40 and bw.sum() == 64


def test_capacity_auction_strict_and_matches_oracle_uncontended():
    """The probabilistic auction must (a) never admit past a target's cap —
    the invariant the sorted-prefix oracle (capacity_auction_sorted) was
    built for — and (b) admit *everything* the oracle admits in the
    uncontended case (demand <= slack), so the common path loses nothing."""
    rng = np.random.default_rng(11)
    n, L = 512, 16
    movers = jnp.asarray(rng.random(n) < 0.7)
    target = jnp.asarray(rng.integers(0, L, n).astype(np.int32))
    node_w = jnp.asarray(rng.integers(1, 5, n).astype(np.int32))
    base = jnp.zeros(L, dtype=jnp.int32)

    # (a) contended: tight caps, strictness must hold for both variants.
    cap = jnp.asarray(np.full(L, 23, dtype=np.int32))
    for fn in (lp.capacity_auction, lp.capacity_auction_sorted):
        acc = fn(next_key(), movers, target, node_w, base, cap, L)
        w = np.where(np.asarray(movers & acc), np.asarray(node_w), 0)
        per = np.bincount(np.asarray(target), weights=w, minlength=L)
        assert (per <= 23).all(), fn.__name__

    # (b) uncontended: both admit every mover.
    wide = jnp.asarray(np.full(L, 10**6, dtype=np.int32))
    key = next_key()
    acc_p = lp.capacity_auction(key, movers, target, node_w, base, wide, L)
    acc_s = lp.capacity_auction_sorted(key, movers, target, node_w, base, wide, L)
    assert bool(jnp.all((movers & acc_p) == movers))
    assert bool(jnp.all((movers & acc_s) == movers))


def test_auction_radix_equals_bitwise_and_oracle():
    """The radix-32 threshold auction (r5 on-silicon rewrite) must admit
    EXACTLY the bitwise bisection's set, which is the maximal
    random-priority prefix per target (the sorted-oracle semantics)."""
    from kaminpar_tpu.ops.lp import _auction_bitwise, _auction_radix

    rng = np.random.default_rng(0)
    n, L = 2048, 24  # fixed shapes: one compile for all trials
    for trial in range(6):
        movers = rng.random(n) < 0.6
        target = rng.integers(0, L, n)
        node_w = rng.integers(1, 9, n)
        base = rng.integers(0, 40, L)
        maxw = rng.integers(10, 80, L)
        # unique priorities: collisions make the oracle order ambiguous
        prio = rng.choice(1 << 30, size=n, replace=False).astype(np.int32)
        args = (jnp.asarray(prio), jnp.asarray(movers), jnp.asarray(target),
                jnp.asarray(node_w), jnp.asarray(base), jnp.asarray(maxw), L)
        a = np.asarray(_auction_radix(*args))
        b = np.asarray(_auction_bitwise(*args))
        assert np.array_equal(a, b), f"trial {trial}"
        acc = np.zeros(n, bool)
        for t in range(L):
            idx = np.flatnonzero(movers & (target == t))
            idx = idx[np.argsort(prio[idx])]
            room = maxw[t] - base[t]
            for u in idx:
                if node_w[u] <= room:
                    acc[u] = True
                    room -= node_w[u]
                else:
                    break  # maximal prefix stops at the first non-fit
        assert np.array_equal(a, acc), f"trial {trial} vs oracle"
