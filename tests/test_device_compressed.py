"""Compressed-graph device pipeline tests (ISSUE 10).

The contract under test: the device-resident compressed view
(graph/device_compressed.py) and the decode-fused LP kernels produce
BIT-IDENTICAL results to the dense path on the decompressed graph — at
every layer (decoded bucket matrices, LP sweeps, two-hop, full deep
partitions) and for both kernel backends (XLA twin + Pallas interpret) —
while the finest level's sync budget stays unchanged.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.bucketed import build_bucketed_view
from kaminpar_tpu.graph.compressed import compress
from kaminpar_tpu.graph.device_compressed import (
    DeviceCompressedView,
    _decode_flat_padded_jit,
    decode_bucket,
    device_decode_eligible,
    resolve_device_decode,
)

FAMILIES = {
    "rmat": lambda scale=9: generators.rmat_graph(scale, 8, seed=1),
    "grid": lambda scale=9: generators.grid2d_graph(1 << (scale // 2), 1 << ((scale + 1) // 2)),
    "star": lambda scale=9: generators.star_graph(1 << scale),
}


def _view_pair(g):
    cg = compress(g)
    dg = cg.decompress()
    return cg, dg, DeviceCompressedView(cg)


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_decoded_buckets_match_dense_view(family):
    """Layout bit-identity: same bucket plan, same gather_idx, and the
    in-trace decoded (cols, wgts) equal the dense bucketed matrices."""
    cg, dg, cv = _view_pair(FAMILIES[family]())
    pv = dg.padded()
    assert (pv.n_pad, pv.m_pad) == (cv.n_pad, cv.m_pad)
    bv = build_bucketed_view(
        np.asarray(dg.row_ptr), np.asarray(dg.col_idx), np.asarray(dg.edge_w),
        dg.n, pv.anchor,
    )
    assert len(bv.buckets) == len(cv.buckets)
    np.testing.assert_array_equal(
        np.asarray(bv.gather_idx), np.asarray(cv.gather_idx)
    )
    dec = jax.jit(lambda s, cb: decode_bucket(s, cb, jnp.int32))
    for b, cb in zip(bv.buckets, cv.buckets):
        np.testing.assert_array_equal(np.asarray(b.nodes), np.asarray(cb.nodes))
        cols, wgts = dec(cv.stream, cb)
        np.testing.assert_array_equal(np.asarray(b.cols), np.asarray(cols))
        np.testing.assert_array_equal(np.asarray(b.wgts), np.asarray(wgts))
    for dense_arr, comp_arr in zip(bv.heavy, cv.heavy):
        np.testing.assert_array_equal(
            np.asarray(dense_arr), np.asarray(comp_arr)
        )


def test_flat_decode_matches_padded_view():
    """decode_flat_padded reproduces the dense PaddedView arrays exactly
    (the contraction and re-materialization substrate)."""
    for family in sorted(FAMILIES):
        _, dg, cv = _view_pair(FAMILIES[family]())
        pv = dg.padded()
        rp, col, ew, eu = _decode_flat_padded_jit(
            cv.stream, cv.wstart_pad, cv.width_pad, cv.degree_pad,
            m_pad=cv.m_pad,
        )
        np.testing.assert_array_equal(np.asarray(rp), np.asarray(pv.row_ptr))
        np.testing.assert_array_equal(np.asarray(col), np.asarray(pv.col_idx))
        np.testing.assert_array_equal(np.asarray(ew), np.asarray(pv.edge_w))
        np.testing.assert_array_equal(np.asarray(eu), np.asarray(pv.edge_u))


@pytest.mark.parametrize("family,scale", [
    ("rmat", 9), ("grid", 9), ("star", 12),  # star 2^12: exercises heavy rows
])
def test_lp_iterate_bit_identity_xla_and_pallas(family, scale):
    """The compressed LP sweep (XLA twin AND Pallas interpret) returns the
    exact labels of the dense sweep under the same key."""
    from kaminpar_tpu.ops import lp, pallas_lp

    _, dg, cv = _view_pair(FAMILIES[family](scale))
    pv = dg.padded()
    bv = dg.bucketed()
    n_pad = pv.n_pad
    idt = pv.row_ptr.dtype
    labels0 = jnp.concatenate(
        [jnp.arange(pv.n, dtype=idt),
         jnp.full(n_pad - pv.n, pv.anchor, dtype=idt)]
    )
    key = jax.random.key(7)
    max_w = jnp.asarray(1 << 20, dtype=idt)
    kw = dict(num_labels=n_pad, active_prob=0.5)
    dense = lp.lp_iterate_bucketed(
        lp.init_state(labels0, pv.node_w, n_pad), key, bv.buckets, bv.heavy,
        bv.gather_idx, pv.node_w, max_w, jnp.int32(1), jnp.int32(4), **kw,
    )
    comp = lp.lp_iterate_compressed(
        lp.init_state(labels0, cv.node_w_pad, n_pad), key, cv.buckets,
        cv.stream, cv.heavy, cv.gather_idx, cv.node_w_pad, max_w,
        jnp.int32(1), jnp.int32(4), **kw,
    )
    np.testing.assert_array_equal(
        np.asarray(dense.labels), np.asarray(comp.labels)
    )
    assert int(dense.num_moved) == int(comp.num_moved)
    fused = pallas_lp.lp_iterate_compressed(
        lp.init_state(labels0, cv.node_w_pad, n_pad), key, cv.buckets,
        cv.stream, cv.heavy, cv.gather_idx, cv.node_w_pad, max_w,
        jnp.int32(1), jnp.int32(4), **kw,
    )
    np.testing.assert_array_equal(
        np.asarray(dense.labels), np.asarray(fused.labels)
    )
    # two-hop favored pass decodes identically too
    th_dense = lp.cluster_two_hop_nodes_bucketed(
        dense, key, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w, max_w,
        num_labels=n_pad,
    )
    th_comp = lp.cluster_two_hop_nodes_compressed(
        comp, key, cv.buckets, cv.stream, cv.heavy, cv.gather_idx,
        cv.node_w_pad, max_w, num_labels=n_pad,
    )
    np.testing.assert_array_equal(
        np.asarray(th_dense.labels), np.asarray(th_comp.labels)
    )


def test_contract_compressed_matches_dense():
    from kaminpar_tpu.ops.contraction import (
        contract_clustering,
        contract_compressed,
    )

    _, dg, cv = _view_pair(FAMILIES["rmat"]())
    pv = dg.padded()
    rng = np.random.default_rng(3)
    lab = rng.integers(0, dg.n // 3, dg.n)
    lab_full = np.concatenate(
        [lab, np.full(pv.n_pad - pv.n, pv.anchor)]
    ).astype(np.int32)
    # fresh copies: the contraction kernels donate their labels buffer
    cd, of_d = contract_clustering(dg, jnp.asarray(lab_full))
    cc, of_c = contract_compressed(cv, jnp.asarray(lab_full))
    assert (cd.n, cd.m) == (cc.n, cc.m)
    np.testing.assert_array_equal(np.asarray(of_d), np.asarray(of_c))
    for attr in ("row_ptr", "col_idx", "node_w", "edge_w", "edge_u"):
        np.testing.assert_array_equal(
            np.asarray(getattr(cd, attr)), np.asarray(getattr(cc, attr))
        )


def test_materialize_csr_matches_host_decompress():
    cg, dg, cv = _view_pair(FAMILIES["grid"]())
    g = cv.materialize_csr()
    np.testing.assert_array_equal(np.asarray(g.row_ptr), np.asarray(dg.row_ptr))
    np.testing.assert_array_equal(np.asarray(g.col_idx), np.asarray(dg.col_idx))
    np.testing.assert_array_equal(np.asarray(g.edge_w), np.asarray(dg.edge_w))
    np.testing.assert_array_equal(np.asarray(g.node_w), np.asarray(dg.node_w))
    assert g._compressed_view is cv
    assert g._total_node_weight == dg.total_node_weight
    assert g._total_edge_weight == int(np.asarray(dg.edge_w).sum())


# -- end-to-end (the acceptance assertion) ----------------------------------


def _partition(g, k, mode, contraction_limit=48):
    from kaminpar_tpu.kaminpar import KaMinPar

    s = KaMinPar("terapart")
    # small graphs + a small contraction limit: >= 1 coarse level with a
    # shallow hierarchy, keeping the 12-cell matrix inside the tier-1 wall
    s.ctx.coarsening.contraction_limit = contraction_limit
    s.ctx.compression.device_decode = mode
    s.set_graph(g)
    return s.compute_partition(k=k)


@pytest.mark.parametrize("family", sorted(FAMILIES))
@pytest.mark.parametrize("scale", [8, 9])  # two padded shape buckets
@pytest.mark.parametrize("k", [3, 4])
def test_deep_pipeline_bit_identity_off_vs_finest(family, scale, k):
    """ISSUE 10 acceptance: device_decode=finest produces the IDENTICAL
    partition to the dense path across rmat/grid/star x 2 shape buckets x
    2 k, through the full deep pipeline (coarsening, IP, extension,
    refinement, finest re-materialization)."""
    g = FAMILIES[family](scale)
    off = _partition(g, k, "off")
    fin = _partition(g, k, "finest")
    np.testing.assert_array_equal(off, fin)


def test_sync_budget_unchanged_and_zero_new_transfers():
    """The compressed path adds ZERO blocking transfers: the coarsening
    phase keeps its one-readback-per-level contract (asserted in-pipeline
    by deep.py), and the compressed_build / compressed_decode phases pull
    nothing at all."""
    from kaminpar_tpu.utils import sync_stats

    g = FAMILIES["rmat"](9)
    sync_stats.reset()
    _partition(g, 4, "off")
    off_snap = sync_stats.snapshot()["phases"]
    sync_stats.reset()
    _partition(g, 4, "finest")
    fin_snap = sync_stats.snapshot()["phases"]
    # per-level contract: identical coarsening pull counts in both modes
    assert (
        fin_snap["coarsening"]["count"] == off_snap["coarsening"]["count"]
    )
    assert fin_snap.get("compressed_build", {"count": 0})["count"] == 0
    assert fin_snap.get("compressed_decode", {"count": 0})["count"] == 0
    # the compressed mode must not add transfers anywhere on the spine
    assert (
        sum(p["count"] for p in fin_snap.values())
        <= sum(p["count"] for p in off_snap.values())
    )


def test_terapart_device_decode_never_host_decompresses(monkeypatch):
    """The device-decode twin of test_compressed.py's release test: with
    routing on, the finest CSR is never host-decompressed — level-0 work
    and the final re-materialization both run off the device stream."""
    from kaminpar_tpu.graph.compressed import CompressedGraph

    calls = []
    orig = CompressedGraph.decompress

    def tracking(self):
        calls.append(1)
        return orig(self)

    monkeypatch.setattr(CompressedGraph, "decompress", tracking)
    g = FAMILIES["rmat"](9)
    part = _partition(g, 4, "finest")
    from kaminpar_tpu.graph import metrics

    assert metrics.is_feasible(g, part, 4, np.full(4, g.n, dtype=np.int64))
    assert not calls, f"host decompress ran {len(calls)}x under device decode"


def test_eligibility_gate_and_fallback():
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name("terapart")
    assert resolve_device_decode(ctx.compression) == "finest"  # auto -> on
    cg = compress(FAMILIES["grid"]())
    ok, _ = device_decode_eligible(ctx, cg)
    assert ok
    # 64-bit build falls outside the envelope
    ctx.use_64bit_ids = True
    ok, reason = device_decode_eligible(ctx, cg)
    assert not ok and "64-bit" in reason
    ctx.use_64bit_ids = False
    # v-cycle community restriction falls back dense
    ok, reason = device_decode_eligible(ctx, cg, communities=np.zeros(4))
    assert not ok
    # the full pipeline still works (dense fallback) when forced
    ctx.compression.device_decode = "off"
    assert resolve_device_decode(ctx.compression) == "off"


def test_resident_bytes_accounting():
    """The compressed tier is genuinely smaller on gap-friendly graphs,
    and the accounting matches the actually-allocated device arrays."""
    _, _, cv = _view_pair(generators.rgg2d_graph(4096, seed=1))
    total = cv.stream.words.nbytes + cv.stream.edge_w.nbytes
    total += sum(
        a.nbytes
        for a in (cv.node_w_pad, cv.degree_pad, cv.wstart_pad, cv.width_pad,
                  cv.gather_idx)
    )
    for cb in cv.buckets:
        total += (cb.nodes.nbytes + cb.wstart.nbytes + cb.width.nbytes
                  + cb.deg.nbytes + cb.estart.nbytes)
    total += sum(a.nbytes for a in cv.heavy)
    assert cv.resident_bytes() == total
    assert cv.dense_resident_bytes() > 2 * cv.resident_bytes()
