"""Checkpoint/resume tests (ISSUE 15 tentpole a): deterministic
level-boundary snapshots of the deep pipeline and BIT-IDENTICAL resume.

The fast tier proves the full property chain in-process — every boundary
of a multi-level run (coarsening AND uncoarsening stages) resumes to the
uninterrupted run's exact partition, the writer's pulls stay inside the
budget the pipeline asserts (and at ZERO when disarmed), fingerprints
reject foreign runs, and the atomic-rename format round-trips.  The
@slow tier adds the kill matrix the acceptance criteria name: a REAL
SIGTERM (the ``preempt`` injection point, resilience/faults.py) at every
level boundary of a scale-12 run across families x k, resumed from the
surviving checkpoint in a fresh process."""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import warnings

import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.presets import create_context_by_preset_name
from kaminpar_tpu.resilience import checkpoint as ckpt
from kaminpar_tpu.resilience.checkpoint import CheckpointMismatchError
from kaminpar_tpu.utils import sync_stats

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ctx(d=None, seed=7, every=1, keep_all=True, climit=60):
    ctx = create_context_by_preset_name("default")
    ctx.seed = seed
    # A small contraction limit produces several coarsening levels on a
    # small graph — the boundary matrix stays cheap while covering both
    # stages (the default C=2000 needs scale >= 13 for even one level).
    ctx.coarsening.contraction_limit = climit
    if d is not None:
        ctx.resilience.checkpoint_dir = str(d)
        ctx.resilience.checkpoint_every_levels = every
        ctx.resilience.checkpoint_keep_all = keep_all
    return ctx


def _solve(g, k=4, d=None, resume=None, **kw):
    solver = KaMinPar(_ctx(d, **kw))
    solver.set_graph(g)
    return solver.compute_partition(k, resume=resume)


def _files(d):
    return sorted(glob.glob(os.path.join(str(d), "ckpt_deep_b*.npz")))


def _meta(path):
    with np.load(path) as npz:
        return json.loads(str(npz["meta"][()]))


def _graph():
    return generators.rmat_graph(9, edge_factor=4, seed=3)


def test_disarmed_writes_nothing_and_pulls_nothing(tmp_path):
    """Without checkpoint_dir the pipeline performs ZERO checkpoint_write
    pulls — deep.py asserts the budget at 0 in-pipeline, so arming the
    budget checks makes the run itself the assertion."""
    g = _graph()
    sync_stats.enable_budget_checks(True)
    try:
        _solve(g)
    finally:
        sync_stats.enable_budget_checks(False)
    assert _files(tmp_path) == []


def test_every_boundary_resumes_bit_identical(tmp_path):
    """The core tentpole property: the armed run is bit-identical to the
    reference, writes a checkpoint at EVERY level boundary (both
    stages), and every one of those checkpoints resumes to the exact
    reference partition.  The armed run's writer pulls stay inside the
    exact entitlement deep.py asserts (budget checks armed)."""
    g = _graph()
    ref = _solve(g)
    sync_stats.enable_budget_checks(True)
    try:
        armed = _solve(g, d=tmp_path)
    finally:
        sync_stats.enable_budget_checks(False)
    assert np.array_equal(ref, armed)
    files = _files(tmp_path)
    assert len(files) >= 5
    stages = {_meta(f)["stage"] for f in files}
    assert stages == {"coarsening", "uncoarsening"}
    # Monotone RNG chain positions: later boundaries embody more draws.
    draws = [_meta(f)["rng"]["draws"] for f in files]
    assert draws == sorted(draws)
    for f in files:
        got = _solve(g, resume=f)
        assert np.array_equal(ref, got), f"resume from {f} diverged"


def test_resume_state_object_and_directory_latest(tmp_path):
    """resume= accepts a path, a directory (latest boundary wins), or a
    pre-loaded CheckpointState."""
    g = _graph()
    ref = _solve(g)
    _solve(g, d=tmp_path)
    files = _files(tmp_path)
    assert ckpt.latest(str(tmp_path)) == files[-1]
    state = ckpt.load(str(tmp_path))
    assert state.path == files[-1]
    assert np.array_equal(ref, _solve(g, resume=state))
    assert np.array_equal(ref, _solve(g, resume=str(tmp_path)))


def test_checkpoint_every_levels_thins_boundaries(tmp_path):
    g = _graph()
    d1 = tmp_path / "every1"
    d2 = tmp_path / "every2"
    _solve(g, d=d1, every=1)
    _solve(g, d=d2, every=2)
    assert 0 < len(_files(d2)) < len(_files(d1))
    # every=2 keeps exactly the even boundaries of the every=1 run.
    assert {_meta(f)["boundary"] for f in _files(d2)} == {
        b for b in (_meta(f)["boundary"] for f in _files(d1)) if b % 2 == 0
    }


def test_keep_latest_only_by_default(tmp_path):
    g = _graph()
    _solve(g, d=tmp_path, keep_all=False)
    files = _files(tmp_path)
    assert len(files) == 1
    assert _meta(files[0])["num_levels"] == 0  # the final boundary


def test_fingerprint_rejects_foreign_runs(tmp_path):
    g = _graph()
    _solve(g, d=tmp_path, keep_all=False)
    f = _files(tmp_path)[0]
    with pytest.raises(CheckpointMismatchError, match="seed"):
        _solve(g, resume=f, seed=99)
    with pytest.raises(CheckpointMismatchError, match="k="):
        _solve(g, k=8, resume=f)
    other = generators.rmat_graph(8, edge_factor=4, seed=4)
    with pytest.raises(CheckpointMismatchError, match="graph_"):
        _solve(other, resume=f)


def test_knob_digest_governs_not_preset_name(tmp_path):
    """A changed result-relevant knob (coarsening tree) must reject; the
    advisory fields (preset name/git head) only warn."""
    g = _graph()
    _solve(g, d=tmp_path, keep_all=False)
    f = _files(tmp_path)[0]
    with pytest.raises(CheckpointMismatchError, match="knobs_digest"):
        _solve(g, resume=f, climit=61)
    state = ckpt.load(f)
    state.fingerprint = dict(state.fingerprint, preset="renamed")
    with warnings.catch_warnings(record=True) as wrec:
        warnings.simplefilter("always")
        got = _solve(g, resume=state)
    assert any("preset" in str(w.message) for w in wrec)
    assert np.array_equal(_solve(g), got)


def test_env_arming_and_every_override(tmp_path, monkeypatch):
    g = _graph()
    d = tmp_path / "envdir"
    monkeypatch.setenv("KPTPU_CHECKPOINT", str(d))
    monkeypatch.setenv("KPTPU_CHECKPOINT_EVERY", "2")
    _solve(g)  # context itself is NOT armed: env alone arms
    files = _files(d)
    assert files
    assert all(_meta(f)["boundary"] % 2 == 0 for f in files)


def test_envelope_warns_once_and_disarms(tmp_path):
    """Armed outside the envelope (no dense graph / v-cycle communities /
    compressed source) the writer declines with one RuntimeWarning."""
    ctx = _ctx(tmp_path)
    ckpt._warned_envelope[0] = False
    with warnings.catch_warnings(record=True) as wrec:
        warnings.simplefilter("always")
        assert ckpt.writer_for(ctx, None) is None
        assert ckpt.writer_for(ctx, None) is None  # second call: silent
    assert sum("envelope" in str(w.message) for w in wrec) == 1
    ckpt._warned_envelope[0] = False


def test_atomic_format_tolerates_stray_tmp(tmp_path):
    """A torn write (kill mid-serialization) leaves only a .tmp file —
    latest() ignores it and the previous checkpoint stays loadable."""
    g = _graph()
    _solve(g, d=tmp_path, keep_all=False)
    f = _files(tmp_path)[0]
    (tmp_path / "ckpt_deep_b9999.npz.tmp12345").write_bytes(b"torn")
    assert ckpt.latest(str(tmp_path)) == f
    assert ckpt.load(str(tmp_path)).path == f


def test_armed_resume_does_not_rewrite_restored_boundary(tmp_path):
    """A resumed run that is ITSELF armed (preempted under
    KPTPU_CHECKPOINT, resumed under it too) continues the dead run's
    boundary numbering instead of re-writing the restored boundary —
    the write cadence (checkpoint_every_levels phase) must match the
    uninterrupted run's."""
    g = _graph()
    d1 = tmp_path / "first"
    _solve(g, d=d1)
    files = _files(d1)
    uncoarsen = [f for f in files if _meta(f)["stage"] == "uncoarsening"]
    state = ckpt.load(uncoarsen[0])
    d2 = tmp_path / "resumed"
    ref = _solve(g)
    got = _solve(g, d=d2, resume=state)
    assert np.array_equal(ref, got)
    resumed_bounds = [_meta(f)["boundary"] for f in _files(d2)]
    # Strictly AFTER the restored boundary (no duplicate write of it),
    # and exactly the uninterrupted run's remaining boundary numbers.
    all_bounds = [_meta(f)["boundary"] for f in files]
    assert resumed_bounds == [b for b in all_bounds if b > state.boundary]


def _run_preempt_child(spec, k, seed, boundary, ckpt_dir, climit=0,
                       timeout=900):
    """SIGTERM a checkpointing deep run at 1-based level boundary
    ``boundary`` in a fresh process (the tools chaos --preempt-child
    leg); returns the completed process."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        KPTPU_CHECKPOINT=str(ckpt_dir),
        KPTPU_CHECKPOINT_EVERY="1",
        KPTPU_FAULTS=f"preempt:execute-fault:after={boundary - 1}:n=1",
    )
    return subprocess.run(
        [sys.executable, "-m", "kaminpar_tpu.tools", "chaos",
         "--preempt-child", "--graph", spec, "-k", str(k),
         "--seed", str(seed), "--climit", str(climit)],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=_REPO,
    )


def test_sigterm_preemption_resumes_bit_identical(tmp_path):
    """One REAL kill in tier-1: a subprocess multi-level deep run dies
    to SIGTERM at a mid-run level boundary (checkpoint already durable —
    the preempt point fires after the write), and the resumed run
    matches the reference bit for bit.  The full scale-12 kill matrix
    is @slow below."""
    spec, k, seed = "rmat:9:4:3", 4, 7
    g = _graph()
    ref = _solve(g, k=k)
    child = _run_preempt_child(spec, k, seed, boundary=2,
                               ckpt_dir=tmp_path, climit=60)
    assert child.returncode == -signal.SIGTERM, child.stderr[-1000:]
    files = _files(tmp_path)
    assert files, "no checkpoint survived the kill"
    got = _solve(g, k=k, resume=str(tmp_path))
    assert np.array_equal(ref, got)


@pytest.mark.slow
@pytest.mark.parametrize("spec,factory", [
    ("rmat:12:8:3",
     lambda: generators.rmat_graph(12, edge_factor=8, seed=3)),
    ("grid:64x64", lambda: generators.grid2d_graph(64, 64)),
    ("star:4095", lambda: generators.star_graph(4095)),
])
@pytest.mark.parametrize("k", [4, 8])
def test_kill_anywhere_matrix_scale12(tmp_path, spec, factory, k):
    """Acceptance matrix: for EVERY level boundary of a scale-12 deep
    run (three families x two k), SIGTERM at that boundary + resume is
    bit-identical to the uninterrupted run."""
    g = factory()
    seed = 7
    ref = _solve(g, k=k, climit=2000)
    # Discover the boundary count from an uninterrupted armed run.
    probe_dir = tmp_path / "probe"
    _solve(g, k=k, d=probe_dir, climit=2000)
    boundaries = [_meta(f)["boundary"] for f in _files(probe_dir)]
    assert boundaries
    for b in boundaries:
        kill_dir = tmp_path / f"kill_b{b}"
        kill_dir.mkdir()
        child = _run_preempt_child(spec, k, seed, boundary=b,
                                   ckpt_dir=kill_dir)
        assert child.returncode == -signal.SIGTERM, (
            f"boundary {b}: rc={child.returncode}\n{child.stderr[-800:]}"
        )
        assert _files(kill_dir), f"boundary {b}: no checkpoint survived"
        got = _solve(g, k=k, resume=str(kill_dir), climit=2000)
        assert np.array_equal(ref, got), f"boundary {b} diverged"


def test_chaos_preemption_tool(tmp_path):
    """``tools chaos --preemption`` end-to-end: kill + resume + verdict
    + chaos_preempt_* record keys (ledger suppressed)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "kaminpar_tpu.tools", "chaos",
         "--preemption", "--graph", "rmat:9:4:3", "-k", "4",
         "--boundary", "1", "--no-ledger", "--json"],
        capture_output=True, text=True, timeout=900, env=env, cwd=_REPO,
    )
    assert out.returncode == 0, out.stderr[-1000:] + out.stdout[-500:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["chaos_preempt_killed"] == 1
    assert rec["chaos_preempt_identical"] == 1
    assert rec["chaos_preempt_recover_s"] >= 0.0
