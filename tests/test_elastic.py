"""Elastic fleet tests (ISSUE 15 tentpole c): ``scale_to`` under live
traffic with conserved resolutions, retired-slot revival vs fresh
spawning, autoscale watermarks with hysteresis, health-sweep
replacement, and the ``fleet_scale_*`` observability surface."""

from __future__ import annotations

import threading
import time
import warnings

import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.presets import create_context_by_preset_name
from kaminpar_tpu.resilience import breakers as rbreakers
from kaminpar_tpu.serve.fleet import PartitionFleet
from kaminpar_tpu.telemetry import prometheus


@pytest.fixture(autouse=True)
def _quiet_and_clean():
    rbreakers.reset_global_registry()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        yield
    rbreakers.reset_global_registry()


def _fleet(replicas=2, ctx=None, **kw):
    ctx = ctx or create_context_by_preset_name("serve")
    kw.setdefault("warm_ladder", ())
    kw.setdefault("warm_ks", ())
    kw.setdefault("queue_bound", 64)
    kw.setdefault("max_batch", 4)
    return PartitionFleet(ctx, replicas=replicas, **kw)


def _graphs(n, base=60):
    return [
        generators.rmat_graph(7, edge_factor=4, seed=base + i)
        for i in range(n)
    ]


def _wait_active(fleet, n, timeout=120):
    """Sweep-triggered scaling (autoscale/replacement) runs detached —
    poll the active count instead of asserting instantly."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fleet.active_replicas == n:
            bg = fleet._bg_scale
            if bg is None or not bg.is_alive():
                return True
        time.sleep(0.05)
    return False


class _Burst:
    """8-thread live traffic against a fleet; every submitted request is
    accounted as exactly one resolution or one typed rejection."""

    def __init__(self, fleet, graphs, threads=8):
        self.fleet = fleet
        self.graphs = graphs
        self.results: list = []
        self.errors: list = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._worker, args=(t,))
            for t in range(threads)
        ]

    def _worker(self, tid):
        i = 0
        while not self._stop.is_set():
            g = self.graphs[(tid + i) % len(self.graphs)]
            try:
                fut = self.fleet.submit(g, 4, graph_id=f"tenant{tid}")
                res = fut.result(timeout=300)
                with self._lock:
                    self.results.append((tid, res))
            except Exception as exc:  # noqa: BLE001 — typed rejects count
                with self._lock:
                    self.errors.append(type(exc).__name__)
            i += 1

    def __enter__(self):
        for t in self._threads:
            t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=600)

    @property
    def accounted(self):
        with self._lock:
            return len(self.results) + len(self.errors)


def test_scale_up_down_conserves_resolutions_under_live_burst():
    """The acceptance shape: scale 2 -> 3 -> 1 under an 8-thread live
    burst — zero lost (every submit resolves or rejects typed, none
    hangs), zero duplicated resolutions, and the router counters add
    up."""
    fleet = _fleet(replicas=2)
    fleet.start(warmup=False)
    try:
        with _Burst(fleet, _graphs(4)) as burst:
            time.sleep(2.0)
            up = fleet.scale_to(3)
            assert up["active"] == 3 and up["spawned"] == [2]
            time.sleep(2.0)
            down = fleet.scale_to(1)
            assert down["active"] == 1
            assert sorted(down["retired"], reverse=True) == down["retired"]
            time.sleep(2.0)
        stats = fleet.stats()
        # Conservation: every submitted request is accounted exactly once.
        assert stats["submitted"] == burst.accounted
        assert burst.results, "burst produced no resolutions"
        assert stats["fleet_scale_ups"] == 1
        assert stats["fleet_scale_downs"] == 1
        assert stats["fleet_scale_spawns"] == 1
        assert stats["fleet_scale_retires"] == 2
        assert stats["active_replicas"] == 1
    finally:
        fleet.shutdown(drain=True)


def test_scale_down_to_one_keeps_serving():
    fleet = _fleet(replicas=3)
    fleet.start(warmup=False)
    try:
        g = _graphs(1)[0]
        ref = fleet.submit(g, 4).result(timeout=300).partition
        fleet.scale_to(1)
        assert fleet.active_replicas == 1
        # The survivor is replica 0 and still serves bit-identically.
        res = fleet.submit(g, 4).result(timeout=300)
        assert (res.partition == ref).all()
        stats = fleet.stats()
        assert [r["retired"] for r in stats["per_replica"]] == [
            False, True, True,
        ]
    finally:
        fleet.shutdown(drain=True)


def test_scale_up_revives_retired_slot_before_spawning():
    """A retired slot's engine object survives retirement — scale-up
    revives it (warm state carries over, no fresh replica object) before
    any spawn."""
    fleet = _fleet(replicas=2)
    fleet.start(warmup=False)
    try:
        engines = list(fleet.replicas)
        fleet.scale_to(1)
        # The retire-drain runs detached (live traffic must not block on
        # it): wait for the slot's engine to stop.
        deadline = time.monotonic() + 60
        while fleet.replicas[1].running and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not fleet.replicas[1].running
        up = fleet.scale_to(2)
        assert up["revived"] == [1] and not up["spawned"]
        assert fleet.replicas[1] is engines[1]  # same object, revived
        assert fleet.replicas[1].running
        assert len(fleet.replicas) == 2
        stats = fleet.stats()
        assert stats["fleet_scale_revives"] == 1
        assert stats["fleet_scale_spawns"] == 0
        # The revived slot's fleet breaker is administratively closed —
        # it is routable immediately, no half-open probe spent.
        assert fleet.breakers.get("replica", (1,)).state == "closed"
    finally:
        fleet.shutdown(drain=True)


def test_retired_slot_is_not_probe_restorable():
    """Retirement is intentional: unlike a health drain, no half-open
    probe may bring the slot back — only scale_to revives it."""
    ctx = create_context_by_preset_name("serve")
    ctx.fleet.replica_cooldown_s = 0.05
    fleet = _fleet(replicas=2, ctx=ctx)
    fleet.start(warmup=False)
    try:
        fleet.scale_to(1)
        time.sleep(0.2)  # well past the breaker cooldown
        ok, is_probe = fleet._replica_available(1)
        assert not ok and not is_probe
        assert fleet.stats()["restores"] == 0
    finally:
        fleet.shutdown(drain=True)


def test_sticky_tenants_rehome_on_scale_down():
    fleet = _fleet(replicas=2)
    fleet.start(warmup=False)
    try:
        g = _graphs(1)[0]
        # Pin a tenant's first request onto replica 1, making it home.
        fut = fleet.submit(g, 4, graph_id="tenant-x", replica=1)
        fut.result(timeout=300)
        fleet._sticky["tenant-x"] = 1  # explicit-pin path does not bind
        fleet.scale_to(1)
        fut = fleet.submit(g, 4, graph_id="tenant-x")
        fut.result(timeout=300)
        assert fut.replica == 0
        stats = fleet.stats()
        assert stats["sticky_moves"] >= 1
        assert fleet._sticky["tenant-x"] == 0
    finally:
        fleet.shutdown(drain=True)


def test_autoscale_scales_up_on_sustained_pressure_with_hysteresis():
    ctx = create_context_by_preset_name("serve")
    ctx.fleet.autoscale = True
    ctx.fleet.autoscale_min_replicas = 1
    ctx.fleet.autoscale_max_replicas = 2
    ctx.fleet.autoscale_high_s = 0.0   # any queued work is "pressure"
    ctx.fleet.autoscale_low_s = -1.0   # never scale down here
    ctx.fleet.autoscale_hysteresis = 2
    fleet = _fleet(replicas=1, ctx=ctx, max_batch=2)
    fleet.start(warmup=False)
    # Only the EXPLICIT sweep calls below count toward hysteresis (the
    # submit-path sweep is throttled out of the way).
    fleet._health_interval_s = 1e9
    try:
        # Seed the service EMA (warmup would): the raw drain estimate is
        # depth x EMA / max_batch, so queued work now reads as pressure.
        fleet.replicas[0].stats_.seed_service_time(1.0)
        fleet.pause()  # queued work builds the drain estimate
        g = _graphs(1)[0]
        futs = [fleet.submit(g, 4)]
        # Sweep 1 counts toward hysteresis; no scaling yet.
        fleet._autoscale_sweep()
        assert fleet.active_replicas == 1
        # Sweep 2 crosses the hysteresis bar -> one replica added (the
        # action runs detached off the sweep thread).
        fleet._autoscale_sweep()
        assert _wait_active(fleet, 2)
        stats = fleet.stats()
        assert stats["fleet_scale_auto_ups"] == 1
        # Bounded: further pressure cannot exceed autoscale_max_replicas.
        fleet._autoscale_sweep()
        fleet._autoscale_sweep()
        time.sleep(0.2)
        assert fleet.active_replicas == 2
        fleet.resume()
        for f in futs:
            f.result(timeout=300)
    finally:
        fleet.shutdown(drain=True)


def test_autoscale_scales_down_when_idle_and_respects_min():
    ctx = create_context_by_preset_name("serve")
    ctx.fleet.autoscale = True
    ctx.fleet.autoscale_min_replicas = 1
    ctx.fleet.autoscale_max_replicas = 3
    ctx.fleet.autoscale_high_s = 1e9
    ctx.fleet.autoscale_low_s = 1e9   # everything is "idle"
    ctx.fleet.autoscale_hysteresis = 1
    fleet = _fleet(replicas=2, ctx=ctx)
    fleet.start(warmup=False)
    try:
        fleet._autoscale_sweep()
        assert _wait_active(fleet, 1)
        assert fleet.stats()["fleet_scale_auto_downs"] == 1
        # At the floor: no further scale-down.
        fleet._autoscale_sweep()
        time.sleep(0.2)
        assert fleet.active_replicas == 1
    finally:
        fleet.shutdown(drain=True)


def test_autoscale_hysteresis_resets_when_signal_leaves_band():
    ctx = create_context_by_preset_name("serve")
    ctx.fleet.autoscale = True
    ctx.fleet.autoscale_high_s = 0.0
    ctx.fleet.autoscale_low_s = -1.0
    ctx.fleet.autoscale_hysteresis = 3
    ctx.fleet.autoscale_max_replicas = 2
    fleet = _fleet(replicas=1, ctx=ctx, max_batch=2)
    fleet.start(warmup=False)
    fleet._health_interval_s = 1e9  # explicit sweeps only
    try:
        fleet.replicas[0].stats_.seed_service_time(1.0)
        fleet.pause()
        g = _graphs(1)[0]
        fut = fleet.submit(g, 4)
        fleet._autoscale_sweep()
        fleet._autoscale_sweep()
        assert fleet._above_high == 2
        # Pressure clears (drain the queue) -> the streak resets.
        fleet.resume()
        fut.result(timeout=300)
        fleet._autoscale_sweep()
        assert fleet._above_high == 0
        assert fleet.active_replicas == 1
    finally:
        fleet.shutdown(drain=True)


def test_health_sweep_replaces_watchdog_fired_replica():
    """A replica the health sweep condemns is REPLACED, not just
    drained: a fresh replica spawns at a new index, the sick slot is
    retired (never probe-revived into rotation), and active capacity is
    back to target immediately."""
    ctx = create_context_by_preset_name("serve")
    ctx.fleet.auto_drain = True
    ctx.fleet.replace_drained = True
    fleet = _fleet(replicas=2, ctx=ctx)
    fleet.start(warmup=False)
    fleet._health_interval_s = 0.0
    try:
        fleet.replicas[1].stats_.bump("watchdog_timeouts")
        g = _graphs(1)[0]
        fleet.submit(g, 4).result(timeout=300)  # submit runs the sweep
        assert _wait_active(fleet, 2)  # replacement spawns detached
        stats = fleet.stats()
        assert stats["fleet_scale_replacements"] == 1
        assert stats["fleet_scale_spawns"] == 1
        assert stats["replicas"] == 3
        assert stats["active_replicas"] == 2
        assert [r["retired"] for r in stats["per_replica"]] == [
            False, True, False,
        ]
        # The replacement serves traffic.
        fleet.submit(g, 4, replica=2).result(timeout=300)
    finally:
        fleet.shutdown(drain=True)


def test_spawned_replica_inherits_warm_state():
    """Scale-up spawning inherits the fleet's warm state (and journals
    nothing until started): the new replica's warmup raises ZERO compile
    events for inherited cells."""
    from kaminpar_tpu.utils import compile_stats

    fleet = _fleet(replicas=1, warm_ladder=(7,), warm_ks=(4,))
    fleet.start(warmup=True)
    try:
        before = compile_stats.compile_time_snapshot().get(
            "compile_events", 0
        )
        fleet.scale_to(2)
        delta = compile_stats.compile_time_snapshot().get(
            "compile_events", 0
        ) - before
        assert delta == 0, f"spawned replica compiled {delta} executables"
        cells = fleet.replicas[1].warmup_cell_counts()
        assert cells["inherited"] > 0 and cells["local"] == 0
    finally:
        fleet.shutdown(drain=True)


def test_scale_counters_exposed_in_prometheus():
    fleet = _fleet(replicas=2)
    fleet.start(warmup=False)
    try:
        fleet.scale_to(1)
        fleet.scale_to(2)
        text = fleet.metrics_text()
        prometheus.validate(text)
        assert 'kaminpar_fleet_scale_total{op="down"} 1' in text
        assert 'kaminpar_fleet_scale_total{op="up"} 1' in text
        assert 'kaminpar_fleet_scale_total{op="revive"} 1' in text
        assert 'kaminpar_fleet_scale_total{op="retire"} 1' in text
        assert "kaminpar_fleet_active_replicas 2" in text
    finally:
        fleet.shutdown(drain=True)


def test_scale_to_rejects_when_not_started():
    from kaminpar_tpu.serve.errors import EngineStoppedError

    fleet = _fleet(replicas=1)
    with pytest.raises(EngineStoppedError):
        fleet.scale_to(2)
