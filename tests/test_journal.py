"""Crash-safe serve journal tests (ISSUE 15 tentpole b): append-only
admit/resolve records, idempotent replay after a dead engine, warm-state
restoration with a ZERO warmup compile-event delta, and conservation —
every journaled admit ends with exactly ONE resolution, however the
process died.  The @slow tier SIGKILLs a real serve CLI process
mid-burst and replays its journal."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.presets import create_context_by_preset_name
from kaminpar_tpu.serve import journal as J
from kaminpar_tpu.serve.engine import PartitionEngine

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ctx(path, fsync_every=2):
    ctx = create_context_by_preset_name("serve")
    ctx.serve.journal_path = str(path)
    ctx.serve.journal_fsync_every = fsync_every
    return ctx


def _engine(path, **kw):
    kw.setdefault("warm_ladder", ())
    kw.setdefault("warm_ks", ())
    kw.setdefault("queue_bound", 16)
    kw.setdefault("max_batch", 4)
    return PartitionEngine(_ctx(path), **kw)


def _graphs(n, scale=7, base=50):
    return [
        generators.rmat_graph(scale, edge_factor=4, seed=base + i)
        for i in range(n)
    ]


def _wait_unresolved_empty(path, timeout=180):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not J.read_journal(str(path))["unresolved"]:
            return True
        time.sleep(0.2)
    return False


# -- file format -------------------------------------------------------------


def test_journal_append_and_batched_fsync(tmp_path):
    path = tmp_path / "j.jsonl"
    jr = J.ServeJournal(str(path), fsync_every=3)
    for i in range(7):
        jr.append({"t": "admit", "id": i + 1})
    snap = jr.snapshot()
    assert snap["appended"] == 7
    assert snap["fsyncs"] == 2  # batched: at appends 3 and 6
    jr.append({"t": "resolve", "id": 1, "ok": 1}, force_fsync=True)
    assert jr.snapshot()["fsyncs"] == 3
    jr.close()
    assert jr.snapshot()["fsyncs"] == 4  # close fsyncs the tail
    jr.append({"t": "admit", "id": 99})  # post-close: silently dropped
    view = J.read_journal(str(path))
    assert view["admits"] == 7
    assert view["max_id"] == 7


def test_read_journal_tolerates_torn_tail(tmp_path):
    path = tmp_path / "j.jsonl"
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps({"t": "admit", "id": 1, "k": 4}) + "\n")
        f.write(json.dumps({"t": "resolve", "id": 1, "ok": 1}) + "\n")
        f.write(json.dumps({"t": "admit", "id": 2, "k": 4}) + "\n")
        f.write('{"t": "adm')  # kill mid-append
    view = J.read_journal(str(path))
    assert view["torn"] == 1
    assert [r["id"] for r in view["unresolved"]] == [2]
    assert view["resolved"] == {1: 1}
    assert view["max_id"] == 2


def test_read_journal_missing_file(tmp_path):
    view = J.read_journal(str(tmp_path / "nope.jsonl"))
    assert view["unresolved"] == [] and view["max_id"] == 0


def test_compact_keeps_unresolved_and_latest_warm_state(tmp_path):
    path = tmp_path / "j.jsonl"
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps({"t": "warm_state", "warmup_report": []}) + "\n")
        f.write(json.dumps({"t": "admit", "id": 1, "k": 4}) + "\n")
        f.write(json.dumps({"t": "resolve", "id": 1, "ok": 1}) + "\n")
        f.write(json.dumps({"t": "admit", "id": 2, "k": 8}) + "\n")
        f.write(json.dumps({"t": "warm_state", "warmup_report": [],
                            "marker": "latest"}) + "\n")
        f.write('{"torn')
    dropped = J.compact(str(path))
    assert dropped == 4  # resolved pair + stale warm state + torn line
    view = J.read_journal(str(path))
    assert [r["id"] for r in view["unresolved"]] == [2]
    assert view["warm_state"]["marker"] == "latest"
    assert view["torn"] == 0
    assert view["max_id"] == 2
    # Idempotent: a second pass has nothing to drop.
    assert J.compact(str(path)) == 0


def test_graph_payload_round_trip():
    g = generators.rmat_graph(7, edge_factor=4, seed=9)
    payload = J.encode_graph(g)
    back = J.decode_graph(payload)
    assert back.n == g.n and back.m == g.m
    for attr in ("row_ptr", "col_idx", "node_w", "edge_w"):
        assert np.array_equal(
            np.asarray(getattr(back, attr))[: back.n + 1 if attr == "row_ptr"
                                            else back.m],
            np.asarray(getattr(g, attr))[: g.n + 1 if attr == "row_ptr"
                                         else g.m],
        )


# -- live engine -------------------------------------------------------------


def test_clean_burst_resolves_every_admit(tmp_path):
    path = tmp_path / "serve.jsonl"
    eng = _engine(path)
    eng.start(warmup=False)
    try:
        futs = [eng.submit(g, 4) for g in _graphs(5)]
        for f in futs:
            f.result(timeout=300)
        # Resolutions force an fsync, so the mid-run view is complete:
        # one admit + exactly one resolution each.
        view = J.read_journal(str(path))
        assert view["admits"] == 5
        assert not view["unresolved"]
        assert all(c == 1 for c in view["resolved"].values())
    finally:
        eng.shutdown(drain=True)
    # Clean shutdown compacts the history down to recovery needs:
    # nothing unresolved, just the final warm state.
    view = J.read_journal(str(path))
    assert view["admits"] == 0
    assert not view["unresolved"]
    assert view["warm_state"] is not None


def test_restart_replays_unresolved_idempotently(tmp_path):
    """The crash shape: an engine admits a burst it never dispatches
    (paused), dies hard — the restarted engine replays every unresolved
    admit exactly once: zero lost, zero duplicated resolutions."""
    path = tmp_path / "serve.jsonl"
    e1 = _engine(path)
    e1.start(warmup=False)
    e1.pause()
    for g in _graphs(6):
        e1.submit(g, 4)
    # Non-draining shutdown rejects queued work with EngineStoppedError —
    # the "engine gave it back" class the journal deliberately does NOT
    # record as a resolution, leaving the entries replayable.
    e1.shutdown(drain=False)
    view = J.read_journal(str(path))
    assert view["admits"] == 6
    assert len(view["unresolved"]) == 6

    e2 = _engine(path)
    e2.start(warmup=False)
    try:
        assert _wait_unresolved_empty(path)
        live = e2.stats()
        assert live["journal"]["path"] == str(path)
        # Pre-compaction view: the replay produced exactly ONE
        # resolution per admit (conservation).
        view = J.read_journal(str(path))
        assert not view["unresolved"]
        assert len(view["resolved"]) == 6
        assert all(c == 1 for c in view["resolved"].values())
    finally:
        e2.shutdown(drain=True)
    assert not J.read_journal(str(path))["unresolved"]
    stats = e2.stats()
    assert stats["journal_replayed"] == 6
    assert stats["journal_resolutions"] == 6


def test_restart_mid_burst_under_concurrent_load(tmp_path):
    """Crash mid-burst with SOME requests already resolved: the restart
    replays only the unresolved suffix, and the final journal carries
    exactly one resolution per admit (conservation under load)."""
    path = tmp_path / "serve.jsonl"
    e1 = _engine(path, max_batch=2)
    e1.start(warmup=False)
    graphs = _graphs(8)
    futs = [e1.submit(g, 4) for g in graphs[:4]]
    for f in futs:
        f.result(timeout=300)
    e1.pause()  # the second half stays queued = "in flight at the kill"
    for g in graphs[4:]:
        e1.submit(g, 4)
    e1.shutdown(drain=False)
    # The bounded shutdown compacts: the 4 delivered resolutions (and
    # their admits) are history, the 4 undelivered admits survive with
    # their ORIGINAL ids.
    view = J.read_journal(str(path))
    assert len(view["unresolved"]) == 4
    assert view["max_id"] == 8  # ids 5..8 kept: no fresh-id collision

    e2 = _engine(path, max_batch=2)
    e2.start(warmup=False)
    try:
        assert _wait_unresolved_empty(path)
        # New traffic lands on fresh ids PAST the dead run's (no replay
        # collision) and resolves normally alongside the replay.
        e2.submit(graphs[0], 4).result(timeout=300)
        view = J.read_journal(str(path))
        assert not view["unresolved"]
        # 4 replayed (resolving under their ORIGINAL ids 5..8) + 1 fresh
        # admission whose id lands PAST every id the engine handed out.
        assert view["admits"] == 5
        assert all(c == 1 for c in view["resolved"].values())
        assert len(view["resolved"]) == 5
        assert set(view["resolved"]) > {5, 6, 7, 8}
        assert max(view["resolved"]) > 8
    finally:
        e2.shutdown(drain=True)
    assert not J.read_journal(str(path))["unresolved"]


def test_failed_request_is_resolved_not_replayed(tmp_path):
    """A genuine per-request failure (not an engine give-back) writes an
    ok=0 resolution — the caller SAW the error, so a restart must not
    resurrect the request."""
    path = tmp_path / "serve.jsonl"
    eng = _engine(path)
    eng.start(warmup=False)
    try:
        g = _graphs(1)[0]
        fut = eng.submit(g, 4, deadline_ms=0.001)  # expires in-queue
        with pytest.raises(Exception):
            fut.result(timeout=60)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if not J.read_journal(str(path))["unresolved"]:
                break
            time.sleep(0.1)
        view = J.read_journal(str(path))
        assert view["admits"] == 1
        assert not view["unresolved"]
    finally:
        eng.shutdown(drain=True)
    assert not J.read_journal(str(path))["unresolved"]


def test_warm_state_restores_with_zero_compile_delta(tmp_path):
    """Engine restart restores the warmup report + warm cells through
    the journal's warm-state record (the PR 14 inheritance path): the
    restarted replica's warmup raises ZERO compile events."""
    from kaminpar_tpu.utils import compile_stats

    path = tmp_path / "serve.jsonl"
    e1 = _engine(path, warm_ladder=(7,), warm_ks=(4,))
    e1.start(warmup=True)
    report_rows = len(e1.warmup_report)
    assert report_rows > 0
    e1.shutdown(drain=True)

    before = compile_stats.compile_time_snapshot().get("compile_events", 0)
    e2 = _engine(path, warm_ladder=(7,), warm_ks=(4,))
    e2.start(warmup=True)
    delta = (
        compile_stats.compile_time_snapshot().get("compile_events", 0)
        - before
    )
    try:
        assert delta == 0, f"restarted warmup compiled {delta} executables"
        inherited = [r for r in e2.warmup_report if r.get("inherited")]
        assert len(inherited) == report_rows
        assert e2.stats()["warmup_cells"]["inherited"] == report_rows
    finally:
        e2.shutdown(drain=True)


def test_warm_state_restores_breaker_trips(tmp_path):
    path = tmp_path / "serve.jsonl"
    e1 = _engine(path)
    e1.start(warmup=False)
    e1.breakers.get("cell", (256, 1024, 4)).trip()
    e1.shutdown(drain=True)

    e2 = _engine(path)
    e2.start(warmup=False)
    try:
        assert e2.breakers.get("cell", (256, 1024, 4)).state == "open"
    finally:
        e2.shutdown(drain=True)


def test_env_override_arms_journal(tmp_path, monkeypatch):
    path = tmp_path / "env.jsonl"
    monkeypatch.setenv("KPTPU_SERVE_JOURNAL", str(path))
    ctx = create_context_by_preset_name("serve")  # context NOT armed
    eng = PartitionEngine(ctx, warm_ladder=(), warm_ks=(),
                          queue_bound=8, max_batch=2)
    eng.start(warmup=False)
    try:
        eng.submit(_graphs(1)[0], 4).result(timeout=300)
        # Pre-compaction view: the env-armed journal recorded the admit.
        assert J.read_journal(str(path))["admits"] == 1
    finally:
        eng.shutdown(drain=True)
    assert path.exists()
    assert not J.read_journal(str(path))["unresolved"]


def test_fleet_replicas_get_per_slot_journals(tmp_path):
    """One shared journal across N replicas would interleave colliding
    request ids — the fleet suffixes each replica's path."""
    import warnings

    from kaminpar_tpu.serve.fleet import PartitionFleet

    path = tmp_path / "fleet.jsonl"
    ctx = _ctx(path)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        fleet = PartitionFleet(ctx, replicas=2, warm_ladder=(),
                               warm_ks=(), queue_bound=8, max_batch=2)
        fleet.start(warmup=False)
        try:
            g = _graphs(1)[0]
            fleet.submit(g, 4, replica=0).result(timeout=300)
            fleet.submit(g, 4, replica=1).result(timeout=300)
            fleet.scale_to(3)
            fleet.submit(g, 4, replica=2).result(timeout=300)
            # Pre-compaction: each replica journaled exactly its own
            # request on its own file.
            for i in range(3):
                view = J.read_journal(str(path) + f".replica{i}")
                assert view["admits"] == 1, f"replica{i}"
                assert not view["unresolved"]
        finally:
            fleet.shutdown(drain=True)
    for i in range(3):
        assert not J.read_journal(str(path) + f".replica{i}")["unresolved"]
    assert not os.path.exists(path)  # nothing writes the bare path


def test_drain_resteer_resolves_drained_replicas_journal(tmp_path):
    """Work a fleet drain re-homes onto a sibling must be RESOLVED in
    the drained replica's journal ('resteered') — an unresolved entry
    there would replay already-completed work if the slot is revived."""
    import warnings

    from kaminpar_tpu.serve.fleet import PartitionFleet

    path = tmp_path / "fleet.jsonl"
    ctx = _ctx(path)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        fleet = PartitionFleet(ctx, replicas=2, warm_ladder=(),
                               warm_ks=(), queue_bound=8, max_batch=2)
        fleet.start(warmup=False)
        try:
            g = _graphs(1)[0]
            # Hold replica 0's queue, land work there, then drain it:
            # the eager drain leg resteers the queued request to
            # replica 1 where it completes.
            fleet.replicas[0].pause()
            fut = fleet.submit(g, 4, replica=0)
            fleet.drain_replica(0, reason="test")
            res = fut.result(timeout=300)
            assert res is not None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if not J.read_journal(
                    str(path) + ".replica0"
                )["unresolved"]:
                    break
                time.sleep(0.1)
            v0 = J.read_journal(str(path) + ".replica0")
            assert not v0["unresolved"], "resteered entry left replayable"
            v1 = J.read_journal(str(path) + ".replica1")
            assert v1["admits"] == 1  # the sibling's journal owns it now
        finally:
            fleet.shutdown(drain=True)


@pytest.mark.slow
def test_sigkill_serve_cli_replays_journal(tmp_path):
    """The real thing: SIGKILL (uncatchable) a serve CLI process
    mid-burst, then replay its journal in-process — zero accepted
    requests lost, zero duplicated resolutions."""
    path = tmp_path / "cli.jsonl"
    code = (
        "import time\n"
        "from kaminpar_tpu.graph import generators\n"
        "from kaminpar_tpu.presets import create_context_by_preset_name\n"
        "from kaminpar_tpu.serve.engine import PartitionEngine\n"
        "ctx = create_context_by_preset_name('serve')\n"
        "eng = PartitionEngine(ctx, warm_ladder=(), warm_ks=(),"
        " queue_bound=32, max_batch=2)\n"
        "eng.start(warmup=False)\n"
        "eng.pause()\n"  # admits journal; nothing dispatches before kill
        "for i in range(8):\n"
        "    eng.submit(generators.rmat_graph(7, edge_factor=4,"
        " seed=50 + i), 4)\n"
        "print('ADMITTED', flush=True)\n"
        "eng.resume()\n"
        "time.sleep(600)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               KPTPU_SERVE_JOURNAL=str(path))
    child = subprocess.Popen(
        [sys.executable, "-c", code], env=env, cwd=_REPO,
        stdout=subprocess.PIPE, text=True,
    )
    try:
        line = child.stdout.readline()
        assert "ADMITTED" in line
        time.sleep(0.5)  # a few dispatches start; most stay queued
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=60)
    finally:
        if child.poll() is None:
            child.kill()
    view = J.read_journal(str(path))
    assert view["admits"] == 8
    assert view["unresolved"]  # the kill landed mid-burst

    eng = _engine(path, queue_bound=32, max_batch=2)
    eng.start(warmup=False)
    try:
        assert _wait_unresolved_empty(path, timeout=600)
        view = J.read_journal(str(path))
        assert not view["unresolved"]
        assert len(view["resolved"]) == 8
        assert all(c == 1 for c in view["resolved"].values())
    finally:
        eng.shutdown(drain=True)
    assert not J.read_journal(str(path))["unresolved"]
