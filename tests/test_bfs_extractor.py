"""Distributed BFS extractor (reference: dist graphutils/bfs_extractor.cc)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from kaminpar_tpu.dist.graph import distribute_graph
from kaminpar_tpu.dist.lp import shard_arrays
from kaminpar_tpu.graph import generators


def _mesh(num=8):
    devs = jax.devices()
    if len(devs) < num:
        pytest.skip(f"need {num} devices, have {len(devs)}")
    return Mesh(np.array(devs[:num]), ("nodes",))


def _np_bfs_hops(g, seeds, radius):
    rp = np.asarray(g.row_ptr)
    col = np.asarray(g.col_idx)
    hops = np.full(g.n, 2**30, dtype=np.int64)
    hops[list(seeds)] = 0
    frontier = list(seeds)
    for h in range(radius):
        nxt = []
        for u in frontier:
            for e in range(rp[u], rp[u + 1]):
                v = col[e]
                if hops[v] > h + 1:
                    hops[v] = h + 1
                    nxt.append(v)
        frontier = nxt
    return hops


def test_dist_bfs_hops_match_host_bfs():
    from kaminpar_tpu.dist.bfs_extractor import dist_bfs_hops

    mesh = _mesh()
    g = generators.grid2d_graph(16, 16)
    dg = distribute_graph(g, mesh.size)
    lab = jnp.zeros(dg.N, dtype=jnp.int32)
    _, dgs = shard_arrays(mesh, dg, lab)
    seeds = [0, 255]
    radius = 5
    hops = dist_bfs_hops(mesh, dgs, seeds, radius=radius)
    ref = _np_bfs_hops(g, seeds, radius)
    # cross-shard propagation must match a host BFS exactly inside the ball
    assert np.array_equal(hops, np.minimum(ref, 2**30))


def test_bfs_extract_contract_exterior():
    from kaminpar_tpu.dist.bfs_extractor import dist_bfs_extract
    from kaminpar_tpu.graph.csr import CSRGraph

    mesh = _mesh()
    g = generators.grid2d_graph(16, 16)
    k = 4
    # blocks = quadrants
    part = np.zeros(g.n, dtype=np.int32)
    for u in range(g.n):
        r, c = divmod(u, 16)
        part[u] = (r >= 8) * 2 + (c >= 8)
    dg = distribute_graph(g, mesh.size)
    full = np.zeros(dg.N, dtype=np.int32)
    full[: g.n] = part
    lab, dgs = shard_arrays(mesh, dg, jnp.asarray(full))

    res = dist_bfs_extract(mesh, dgs, lab, [0], radius=4, k=k,
                           exterior="contract")
    ball = {u for u in range(g.n) if divmod(u, 16)[0] + divmod(u, 16)[1] <= 4}
    assert set(res.node_mapping.tolist()) == ball
    assert res.num_region_nodes == len(ball)
    assert res.graph.n == len(ball) + k
    # supernode weights carry the exterior block weights
    ext = res.graph
    nw = np.asarray(ext.node_w)
    for b in range(k):
        outside = sum(1 for u in range(g.n) if part[u] == b and u not in ball)
        assert nw[res.num_region_nodes + b] == max(outside, 1)
    # partition of region nodes matches the distributed labels; supernode b
    # sits in block b
    assert np.array_equal(res.partition[: res.num_region_nodes],
                          part[res.node_mapping])
    assert np.array_equal(res.partition[res.num_region_nodes:], np.arange(k))
    # the extracted graph is a valid symmetric CSR
    assert isinstance(ext, CSRGraph)
    rp = np.asarray(ext.row_ptr)
    col = np.asarray(ext.col_idx)
    ew = np.asarray(ext.edge_w)
    assert rp[-1] == col.shape[0]
    # symmetry with matching weights
    pairs = {}
    for u in range(ext.n):
        for e in range(rp[u], rp[u + 1]):
            pairs[(u, int(col[e]))] = int(ew[e])
    for (u, v), w in pairs.items():
        assert pairs.get((v, u)) == w, (u, v)
    # total edge weight: interior edges (both endpoints in ball) counted
    # once per direction + boundary edges twice (region->super + mirror)
    grp = np.asarray(g.row_ptr)
    gcol = np.asarray(g.col_idx)
    interior = boundary = 0
    for u in ball:
        for e in range(grp[u], grp[u + 1]):
            v = int(gcol[e])
            if v in ball:
                interior += 1
            else:
                boundary += 1
    assert int(ew.sum()) == interior + 2 * boundary


def test_bfs_extract_exclude_exterior():
    from kaminpar_tpu.dist.bfs_extractor import dist_bfs_extract

    mesh = _mesh()
    g = generators.grid2d_graph(12, 12)
    dg = distribute_graph(g, mesh.size)
    lab, dgs = shard_arrays(mesh, dg, jnp.zeros(dg.N, dtype=jnp.int32))
    res = dist_bfs_extract(mesh, dgs, lab, [0, 143], radius=3, k=1,
                           exterior="exclude")
    assert res.graph.n == res.num_region_nodes == len(res.node_mapping)
    # two disjoint balls of radius 3 around opposite corners
    assert res.graph.n == 2 * len(
        {u for u in range(144) if sum(divmod(u, 12)) <= 3}
    )
    with pytest.raises(ValueError):
        dist_bfs_extract(mesh, dgs, lab, [0], radius=1, k=1, exterior="bogus")
