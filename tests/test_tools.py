"""Tools + heap profiler + debug dump tests (reference: apps/tools/,
heap_profiler.h, partitioning/debug.cc)."""

import os
import subprocess
import sys

# Subprocesses must not try the (possibly hung) TPU tunnel backend; the
# axon site hook (PYTHONPATH) force-connects it even under JAX_PLATFORMS=cpu,
# so it must be stripped too.
_ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "/root/repo"}

import numpy as np
import pytest


@pytest.fixture(scope="module")
def metis_file(tmp_path_factory):
    """Self-generated 1024-node METIS fixture (the reference checkout's
    rgg2d.metis is not available in every container)."""
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.io.metis import write_metis

    g = generators.rgg2d_graph(1024, seed=1)
    path = tmp_path_factory.mktemp("tools") / "rgg2d.metis"
    write_metis(g, str(path))
    return str(path), int(g.n), int(g.m)


def _run_tool(*args):
    return subprocess.run(
        [sys.executable, "-m", "kaminpar_tpu.tools", *args],
        capture_output=True, text=True, timeout=300, env=_ENV,
    )


def test_graph_properties_tool(metis_file):
    path, n, m = metis_file
    out = _run_tool("graph-properties", path)
    assert out.returncode == 0, out.stderr
    assert f"n: {n}" in out.stdout
    assert f"m: {m // 2}" in out.stdout


def test_partition_properties_tool(metis_file, tmp_path):
    path, n, _ = metis_file
    part = np.zeros(n, dtype=np.int64)
    part[n // 2:] = 1
    pfile = tmp_path / "p.part"
    np.savetxt(pfile, part, fmt="%d")
    out = _run_tool("partition-properties", path, str(pfile))
    assert out.returncode == 0, out.stderr
    assert "k: 2" in out.stdout
    assert "cut:" in out.stdout


def test_connected_components_tool(metis_file):
    out = _run_tool("connected-components", metis_file[0])
    assert out.returncode == 0, out.stderr
    assert "Components:" in out.stdout


def test_rearrange_tool(metis_file, tmp_path):
    out_file = tmp_path / "rearranged.metis"
    out = _run_tool("rearrange", metis_file[0], str(out_file))
    assert out.returncode == 0, out.stderr
    from kaminpar_tpu.io.metis import read_metis

    g = read_metis(str(out_file))
    assert g.n == metis_file[1]


def test_heap_profiler_scopes():
    from kaminpar_tpu.utils.heap_profiler import HeapProfiler, memory_summary

    HeapProfiler.reset(enabled=True)
    with HeapProfiler.scope("outer"):
        with HeapProfiler.scope("inner"):
            import jax.numpy as jnp

            _ = jnp.ones(1000).sum()
    rep = HeapProfiler.report()
    assert "outer" in rep and "inner" in rep
    assert isinstance(memory_summary(), dict)


def test_debug_dumps(tmp_path):
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name("default")
    ctx.debug.dump_dir = str(tmp_path)
    ctx.debug.graph_name = "t"
    ctx.debug.dump_graph_hierarchy = True
    ctx.debug.dump_partition_hierarchy = True
    ctx.coarsening.contraction_limit = 100  # force >= 1 coarse level
    g = generators.rgg2d_graph(1024, seed=1)
    s = KaMinPar(ctx)
    s.set_graph(g)
    s.compute_partition(k=4)
    dumps = list(tmp_path.iterdir())
    assert any(p.suffix == ".metis" for p in dumps), dumps
    assert any(p.suffix == ".part" for p in dumps), dumps


def test_compression_tool(metis_file):
    out = _run_tool("compression", metis_file[0])
    assert out.returncode == 0, out.stderr
    assert "ratio:" in out.stdout


def test_warmup_tool():
    """`tools warmup` precompiles a (tiny) serving ladder and reports the
    per-bucket compile seconds from compile_stats (ISSUE 3 satellite);
    `--lanes` adds the lane-stacked pipeline cells (ISSUE 6 satellite)."""
    out = _run_tool("warmup", "--ladder", "64", "--ks", "4", "-P", "serve",
                    "--lanes", "2")
    assert out.returncode == 0, out.stderr
    assert "cell n_bucket=" in out.stdout
    assert "lanestack cell" in out.stdout and "lanes=2" in out.stdout
    assert "distinct kernel specializations" in out.stdout


# -- tools trace hardening (round 20 satellite) ------------------------------


def test_tools_trace_typed_error_exit_codes(tmp_path, capsys):
    """Malformed inputs get typed errors, not tracebacks: 2 unreadable
    file, 3 malformed/truncated JSON, 4 span-free capture."""
    import json

    from kaminpar_tpu.tools.__main__ import main as tools_main

    assert tools_main(["trace", str(tmp_path / "nope.json")]) == 2
    assert "cannot read trace" in capsys.readouterr().out

    truncated = tmp_path / "truncated.json"
    truncated.write_text('{"traceEvents": [{"name": "x", "ph"')
    assert tools_main(["trace", str(truncated)]) == 3
    assert "malformed trace JSON" in capsys.readouterr().out

    not_obj = tmp_path / "list.json"
    not_obj.write_text("[1, 2, 3]")
    assert tools_main(["trace", str(not_obj)]) == 3
    capsys.readouterr()

    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"traceEvents": [], "otherData": {}}))
    assert tools_main(["trace", str(empty)]) == 4
    assert "no spans" in capsys.readouterr().out


def test_tools_trace_shards_without_shard_lanes(tmp_path, capsys):
    """Regression guard: ``--shards`` on a valid trace with no shard
    lanes reports their absence and exits 0 (it used to be exercised
    only on mesh traces)."""
    from kaminpar_tpu.telemetry import trace as ttrace
    from kaminpar_tpu.tools.__main__ import main as tools_main

    rec = ttrace.TraceRecorder()
    rec.begin("partitioning")
    rec.end("partitioning")
    path = tmp_path / "single.json"
    rec.write(str(path))
    assert tools_main(["trace", str(path), "--shards"]) == 0
    assert "not a mesh trace" in capsys.readouterr().out
