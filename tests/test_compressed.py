"""Compressed graph tests (reference: graph_compression/ +
compressed_graph.h round-trip semantics)."""

import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.compressed import CompressedGraph, compress


def _sorted_csr(g):
    rp = np.asarray(g.row_ptr).astype(np.int64)
    col = np.asarray(g.col_idx).astype(np.int64)
    ew = np.asarray(g.edge_w)
    u = np.repeat(np.arange(g.n), np.diff(rp))
    order = np.lexsort((col, u))
    return rp, col[order], ew[order]


@pytest.mark.parametrize("gen", [
    lambda: generators.grid2d_graph(32, 32),
    lambda: generators.rmat_graph(10, 8, seed=1),
    lambda: generators.rgg2d_graph(2048, seed=2),
    lambda: generators.star_graph(50),
    lambda: generators.path_graph(1),
])
def test_roundtrip_exact(gen):
    g = gen()
    cg = compress(g)
    out = cg.decompress()
    rp, col, ew = _sorted_csr(g)
    np.testing.assert_array_equal(np.asarray(out.row_ptr).astype(np.int64), rp)
    np.testing.assert_array_equal(np.asarray(out.col_idx).astype(np.int64), col)
    np.testing.assert_array_equal(np.asarray(out.edge_w), ew)
    np.testing.assert_array_equal(np.asarray(out.node_w), np.asarray(g.node_w))


def test_roundtrip_weighted():
    rng = np.random.default_rng(0)
    g = generators.rgg2d_graph(1024, seed=3,
                               node_weights=rng.integers(1, 9, 1024))
    # give edges weights by symmetrized random
    from kaminpar_tpu.graph.csr import from_edge_list

    rp = np.asarray(g.row_ptr); col = np.asarray(g.col_idx)
    u = np.repeat(np.arange(g.n), np.diff(rp))
    key = np.minimum(u, col) * g.n + np.maximum(u, col)
    w = (key % 7 + 1).astype(np.int64)
    g2 = from_edge_list(g.n, np.stack([u, col], 1), edge_weights=w,
                        node_weights=np.asarray(g.node_w),
                        symmetrize=False, dedup=False)
    cg = compress(g2)
    out = cg.decompress()
    rp2, col2, ew2 = _sorted_csr(g2)
    np.testing.assert_array_equal(np.asarray(out.col_idx).astype(np.int64), col2)
    np.testing.assert_array_equal(np.asarray(out.edge_w), ew2)


def test_compression_ratio_on_local_graphs():
    """Geometric/mesh graphs have small gaps -> real compression."""
    g = generators.grid2d_graph(64, 64)
    cg = compress(g)
    assert cg.compression_ratio() > 1.3, cg.compression_ratio()
    g = generators.rgg2d_graph(4096, seed=1)
    cg = compress(g)
    assert cg.compression_ratio() > 2.0, cg.compression_ratio()


def test_terapart_preset_end_to_end():
    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.kaminpar import KaMinPar

    g = generators.rgg2d_graph(1024, seed=4)
    s = KaMinPar("terapart")
    s.set_graph(g)
    assert s.compressed_graph is not None
    part = s.compute_partition(k=4)
    assert metrics.is_feasible(g, part, 4, s.ctx.partition.max_block_weights)


def test_facade_accepts_compressed_graph():
    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.kaminpar import KaMinPar

    g = generators.rgg2d_graph(1024, seed=5)
    cg = compress(g)
    s = KaMinPar("default")
    s.set_graph(cg)
    part = s.compute_partition(k=4)
    assert metrics.is_feasible(g, part, 4, s.ctx.partition.max_block_weights)


def test_terapart_releases_finest_csr(monkeypatch):
    """TeraPart compute tier (VERDICT r2 next-steps #5): while the pipeline
    refines *coarse* levels, the finest CSR must be garbage — no m-sized
    array resident; it is re-decoded exactly once for final refinement."""
    import gc
    import weakref

    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.partitioning.deep import DeepMultilevelPartitioner

    # Big enough (relative to a tiny contraction limit) to guarantee >= 1
    # coarse level.
    g = generators.rgg2d_graph(4096, seed=6)

    refs = []
    orig_decompress = CompressedGraph.decompress

    def tracking(self):
        out = orig_decompress(self)
        refs.append(weakref.ref(out))
        return out

    monkeypatch.setattr(CompressedGraph, "decompress", tracking)

    coarse_checks = []
    orig_refine = DeepMultilevelPartitioner._refine

    def spy(self, graph, part, cur_k, coarse):
        if coarse and self.graph is None and refs:
            gc.collect()
            coarse_checks.append(refs[0]() is None)
        return orig_refine(self, graph, part, cur_k, coarse)

    monkeypatch.setattr(DeepMultilevelPartitioner, "_refine", spy)

    s = KaMinPar("terapart")
    s.ctx.coarsening.contraction_limit = 64  # force a deep hierarchy
    s.set_graph(g)
    part = s.compute_partition(k=4)

    assert metrics.is_feasible(g, part, 4, s.ctx.partition.max_block_weights)
    # The finest CSR was dead during every coarse-level refinement...
    assert coarse_checks and all(coarse_checks)
    # ...and was decoded exactly twice: level-0 work + final refinement.
    assert len(refs) == 2
