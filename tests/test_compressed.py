"""Compressed graph tests (reference: graph_compression/ +
compressed_graph.h round-trip semantics)."""

import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.compressed import CompressedGraph, compress


def _sorted_csr(g):
    rp = np.asarray(g.row_ptr).astype(np.int64)
    col = np.asarray(g.col_idx).astype(np.int64)
    ew = np.asarray(g.edge_w)
    u = np.repeat(np.arange(g.n), np.diff(rp))
    order = np.lexsort((col, u))
    return rp, col[order], ew[order]


@pytest.mark.parametrize("gen", [
    lambda: generators.grid2d_graph(32, 32),
    lambda: generators.rmat_graph(10, 8, seed=1),
    lambda: generators.rgg2d_graph(2048, seed=2),
    lambda: generators.star_graph(50),
    lambda: generators.path_graph(1),
])
def test_roundtrip_exact(gen):
    g = gen()
    cg = compress(g)
    out = cg.decompress()
    rp, col, ew = _sorted_csr(g)
    np.testing.assert_array_equal(np.asarray(out.row_ptr).astype(np.int64), rp)
    np.testing.assert_array_equal(np.asarray(out.col_idx).astype(np.int64), col)
    np.testing.assert_array_equal(np.asarray(out.edge_w), ew)
    np.testing.assert_array_equal(np.asarray(out.node_w), np.asarray(g.node_w))


def test_roundtrip_weighted():
    rng = np.random.default_rng(0)
    g = generators.rgg2d_graph(1024, seed=3,
                               node_weights=rng.integers(1, 9, 1024))
    # give edges weights by symmetrized random
    from kaminpar_tpu.graph.csr import from_edge_list

    rp = np.asarray(g.row_ptr); col = np.asarray(g.col_idx)
    u = np.repeat(np.arange(g.n), np.diff(rp))
    key = np.minimum(u, col) * g.n + np.maximum(u, col)
    w = (key % 7 + 1).astype(np.int64)
    g2 = from_edge_list(g.n, np.stack([u, col], 1), edge_weights=w,
                        node_weights=np.asarray(g.node_w),
                        symmetrize=False, dedup=False)
    cg = compress(g2)
    out = cg.decompress()
    rp2, col2, ew2 = _sorted_csr(g2)
    np.testing.assert_array_equal(np.asarray(out.col_idx).astype(np.int64), col2)
    np.testing.assert_array_equal(np.asarray(out.edge_w), ew2)


class _RawCSR:
    """Duck-typed CSR for codec property tests — lets us feed the codec
    streams a real generator cannot produce (max-width gaps, unsorted
    columns) without building a 2^31-node graph."""

    def __init__(self, row_ptr, col_idx, node_w=None, edge_w=None):
        self.row_ptr = np.asarray(row_ptr, dtype=np.int64)
        self.col_idx = np.asarray(col_idx, dtype=np.int64)
        self.n = len(self.row_ptr) - 1
        self.node_w = (
            np.ones(self.n, dtype=np.int64) if node_w is None
            else np.asarray(node_w, dtype=np.int64)
        )
        m = len(self.col_idx)
        self.edge_w = (
            np.ones(m, dtype=np.int64) if edge_w is None
            else np.asarray(edge_w, dtype=np.int64)
        )


def test_roundtrip_zero_degree_and_single_node():
    """Robustness (ISSUE 10 satellite): zero-degree nodes anywhere in the
    stream (leading, interior, trailing) and the 1-node graph."""
    g = _RawCSR([0, 0, 2, 2, 3, 3], [3, 4, 1])
    cg = compress(g)
    rp, col, nw, ew = cg.decompress_arrays()
    np.testing.assert_array_equal(rp, [0, 0, 2, 2, 3, 3])
    np.testing.assert_array_equal(col, [3, 4, 1])
    assert ew is None
    g1 = _RawCSR([0, 0], [])
    cg1 = compress(g1)
    rp1, col1, _, _ = cg1.decompress_arrays()
    np.testing.assert_array_equal(rp1, [0, 0])
    assert len(col1) == 0
    assert cg1.memory_bytes() > 0  # metadata still accounted


def test_roundtrip_max_gap_31bit():
    """A neighborhood whose zig-zag gap needs the full 31/32-bit width
    (column ids near 2^31 on a tiny node range) survives the fixed-width
    packer; one bit more raises the documented 64-bit-path error."""
    big = (1 << 30) + 12345
    g = _RawCSR([0, 2, 3], [1, big, big - 7])
    cg = compress(g)
    assert int(cg.width.max()) >= 31
    rp, col, _, _ = cg.decompress_arrays()
    np.testing.assert_array_equal(col, [1, big, big - 7])
    # gaps beyond 32 zig-zag bits must refuse, not corrupt
    g_over = _RawCSR([0, 1], [1 << 33])
    with pytest.raises(ValueError, match="32 bits"):
        compress(g_over)


def test_roundtrip_weighted_stream_and_unsorted_columns():
    """Non-sorted input columns re-sort with their weights still aligned;
    the weighted side stream round-trips exactly."""
    rng = np.random.default_rng(11)
    g = _RawCSR(
        [0, 3, 5, 8],
        [7, 2, 5, 9, 0, 4, 1, 6],  # deliberately unsorted per row
        node_w=rng.integers(1, 5, 3),
        edge_w=[10, 20, 30, 40, 50, 60, 70, 80],
    )
    cg = compress(g)
    rp, col, nw, ew = cg.decompress_arrays()
    np.testing.assert_array_equal(col, [2, 5, 7, 0, 9, 1, 4, 6])
    np.testing.assert_array_equal(ew, [20, 30, 10, 50, 40, 70, 60, 80])
    np.testing.assert_array_equal(nw, np.asarray(g.node_w, dtype=np.int32))


def test_memory_bytes_matches_allocated_arrays():
    """memory_bytes()/uncompressed_bytes() equal the actually-allocated
    array sizes (the compress_ab bench keys on these)."""
    g = generators.rgg2d_graph(2048, seed=9)
    cg = compress(g)
    expected = (
        cg.words.nbytes + cg.word_start.nbytes + cg.width.nbytes
        + cg.degree.nbytes + cg.node_w.nbytes
        + (0 if cg.edge_w is None else cg.edge_w.nbytes)
    )
    assert cg.memory_bytes() == expected
    rp, col, nw, ew = cg.decompress_arrays()
    dense = rp.nbytes + col.nbytes + nw.astype(np.int32).nbytes
    if ew is not None:
        dense += ew.astype(np.int32).nbytes
    assert cg.uncompressed_bytes() == dense


def test_compression_ratio_on_local_graphs():
    """Geometric/mesh graphs have small gaps -> real compression."""
    g = generators.grid2d_graph(64, 64)
    cg = compress(g)
    assert cg.compression_ratio() > 1.3, cg.compression_ratio()
    g = generators.rgg2d_graph(4096, seed=1)
    cg = compress(g)
    assert cg.compression_ratio() > 2.0, cg.compression_ratio()


def test_terapart_preset_end_to_end():
    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.kaminpar import KaMinPar

    g = generators.rgg2d_graph(1024, seed=4)
    s = KaMinPar("terapart")
    s.set_graph(g)
    assert s.compressed_graph is not None
    part = s.compute_partition(k=4)
    assert metrics.is_feasible(g, part, 4, s.ctx.partition.max_block_weights)


def test_facade_accepts_compressed_graph():
    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.kaminpar import KaMinPar

    g = generators.rgg2d_graph(1024, seed=5)
    cg = compress(g)
    s = KaMinPar("default")
    s.set_graph(cg)
    part = s.compute_partition(k=4)
    assert metrics.is_feasible(g, part, 4, s.ctx.partition.max_block_weights)


@pytest.mark.slow  # needs a graph big enough to observe the release (~20 s);
# compressed-path correctness stays tier-1 (round-20 tier-1 rebalance)
def test_terapart_releases_finest_csr(monkeypatch):
    """TeraPart compute tier (VERDICT r2 next-steps #5): while the pipeline
    refines *coarse* levels, the finest CSR must be garbage — no m-sized
    array resident; it is re-decoded exactly once for final refinement."""
    import gc
    import weakref

    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.partitioning.deep import DeepMultilevelPartitioner

    # Big enough (relative to a tiny contraction limit) to guarantee >= 1
    # coarse level.
    g = generators.rgg2d_graph(4096, seed=6)

    refs = []
    orig_decompress = CompressedGraph.decompress

    def tracking(self):
        out = orig_decompress(self)
        refs.append(weakref.ref(out))
        return out

    monkeypatch.setattr(CompressedGraph, "decompress", tracking)

    coarse_checks = []
    orig_refine = DeepMultilevelPartitioner._refine

    def spy(self, graph, part, cur_k, coarse):
        if coarse and self.graph is None and refs:
            gc.collect()
            coarse_checks.append(refs[0]() is None)
        return orig_refine(self, graph, part, cur_k, coarse)

    monkeypatch.setattr(DeepMultilevelPartitioner, "_refine", spy)

    s = KaMinPar("terapart")
    s.ctx.coarsening.contraction_limit = 64  # force a deep hierarchy
    # This test pins the HOST-decompress release accounting (the storage
    # tier); the device-decode routing (which never decompresses on host)
    # has its own release test in tests/test_device_compressed.py.
    s.ctx.compression.device_decode = "off"
    s.set_graph(g)
    part = s.compute_partition(k=4)

    assert metrics.is_feasible(g, part, 4, s.ctx.partition.max_block_weights)
    # The finest CSR was dead during every coarse-level refinement...
    assert coarse_checks and all(coarse_checks)
    # ...and was decoded exactly twice: level-0 work + final refinement.
    assert len(refs) == 2


def test_distributed_compressed_graph_roundtrip():
    """DistributedCompressedGraph (reference: distributed_compressed_graph
    .cc): per-shard gap streams rebuild exactly the distribute_graph
    layout (same edge multiset, ghosts, routing dims) at a real
    compression ratio."""
    from kaminpar_tpu.dist.compressed import compress_distributed
    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.graph import generators

    g = generators.rmat_graph(10, 8, seed=3)
    P = 8
    dcg = compress_distributed(g, P)
    # cross-shard columns make shard-relative gaps wide on a tiny graph;
    # ratios at real scale are ~2-3x (see test_compression_ratio above)
    assert dcg.compression_ratio() > 1.2, dcg.compression_ratio()
    assert dcg.total_node_weight == g.total_node_weight

    dg_c = dcg.to_dist_graph()
    dg_r = distribute_graph(g, P)
    assert dg_c.n == dg_r.n and dg_c.m == dg_r.m
    assert dg_c.n_loc == dg_r.n_loc and dg_c.m_loc == dg_r.m_loc
    assert dg_c.g_loc == dg_r.g_loc and dg_c.cap_g == dg_r.cap_g
    for s in range(P):
        assert np.array_equal(dg_c.ghost_global[s], dg_r.ghost_global[s])
    assert np.array_equal(np.asarray(dg_c.node_w), np.asarray(dg_r.node_w))
    # same edge multiset (neighborhood order may differ: the codec sorts)
    ec = np.stack(dg_c.edges_global_host(), axis=1)
    er = np.stack(dg_r.edges_global_host(), axis=1)
    assert np.array_equal(
        ec[np.lexsort(ec.T[::-1])], er[np.lexsort(er.T[::-1])]
    )


def test_to_dist_graph_decodes_each_shard_once():
    """Round-15 satellite: the staging path decodes every shard exactly ONCE
    (the original two-pass form decoded each shard twice — once for ghost
    routing, once for the device slices), and the single-pass layout is
    byte-identical to distribute_graph's."""
    import kaminpar_tpu.graph.compressed as gcomp
    from kaminpar_tpu.dist.compressed import compress_distributed
    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.graph import generators

    g = generators.rmat_graph(9, 8, seed=5)
    P = 8
    dcg = compress_distributed(g, P)
    calls = {"n": 0}
    orig = gcomp.CompressedGraph.decompress_arrays

    def counting(self):
        calls["n"] += 1
        return orig(self)

    gcomp.CompressedGraph.decompress_arrays = counting
    try:
        dg_c = dcg.to_dist_graph()
    finally:
        gcomp.CompressedGraph.decompress_arrays = orig
    assert calls["n"] == P, calls
    dg_r = distribute_graph(g, P)
    for f in ("node_w", "edge_u", "col_loc", "edge_w", "send_idx", "recv_map"):
        np.testing.assert_array_equal(
            np.asarray(getattr(dg_c, f)), np.asarray(getattr(dg_r, f)), f
        )


def test_distributed_compressed_pipeline():
    """Full dist pipeline over a compressed-built DistGraph."""
    import jax
    import jax.numpy as jnp
    import pytest
    from jax.sharding import Mesh

    from kaminpar_tpu.dist.compressed import compress_distributed
    from kaminpar_tpu.dist.metrics import dist_edge_cut
    from kaminpar_tpu.dist.lp import dist_lp_iterate, shard_arrays
    from kaminpar_tpu.graph import generators

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("need 8 devices")
    mesh = Mesh(np.array(devs[:8]), ("nodes",))
    g = generators.rgg2d_graph(512, seed=4)
    dg = compress_distributed(g, 8).to_dist_graph()
    k = 4
    rng = np.random.default_rng(0)
    full = np.zeros(dg.N, dtype=np.int32)
    full[: g.n] = rng.integers(0, k, g.n)
    part, dgs = shard_arrays(mesh, dg, jnp.asarray(full))
    W = int(np.asarray(g.node_w).sum())
    cap = jnp.full(k, int(np.ceil(W / k) * 1.1) + 1, dtype=dg.dtype)
    before = dist_edge_cut(mesh, part, dgs, k=k)
    out, moved = dist_lp_iterate(
        mesh, jax.random.PRNGKey(1), part, dgs, cap, num_labels=k,
        num_rounds=3, external_only=False,
    )
    assert int(moved) > 0
    assert dist_edge_cut(mesh, out, dgs, k=k) < before
