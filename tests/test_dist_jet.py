"""Distributed JET refiner tests (reference: dist jet_refiner.cc +
snapshooter.cc)."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def _mesh(num=8):
    devs = jax.devices()
    return Mesh(np.array(devs[:num]), ("nodes",))


def _setup(g, k, seed):
    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.dist.lp import shard_arrays

    rng = np.random.default_rng(seed)
    part = rng.integers(0, k, g.n).astype(np.int32)
    mesh = _mesh()
    dg = distribute_graph(g, mesh.size)
    full = np.zeros(dg.N, dtype=np.int32)
    full[: g.n] = part
    part_dev, dg = shard_arrays(mesh, dg, jnp.asarray(full))
    return mesh, dg, part_dev


def test_dist_jet_improves_and_stays_feasible():
    from kaminpar_tpu.dist.jet import dist_jet_iterate
    from kaminpar_tpu.dist.metrics import dist_block_weights, dist_edge_cut
    from kaminpar_tpu.graph import generators

    g = generators.rgg2d_graph(1024, seed=7)
    k = 4
    mesh, dg, part_dev = _setup(g, k, 7)
    W = int(np.asarray(g.node_w).sum())
    cap = jnp.full(k, int(np.ceil(W / k) * 1.1) + 1, dtype=dg.dtype)
    before = dist_edge_cut(mesh, part_dev, dg, k=k)
    out, best_cut = dist_jet_iterate(
        mesh, jax.random.PRNGKey(1), part_dev, dg, cap, num_labels=k,
        num_iterations=6,
    )
    after = dist_edge_cut(mesh, out, dg, k=k)
    assert after == best_cut
    assert after <= before, (after, before)
    bw = dist_block_weights(mesh, out, dg, k=k)
    assert (bw <= np.asarray(cap)).all(), bw


@pytest.mark.slow  # full-pipeline dist JET run (~20 s); kernel-level JET
# identity/feasibility stays tier-1 above (round-20 tier-1 rebalance)
def test_dist_jet_in_pipeline():
    from kaminpar_tpu.context import RefinementAlgorithm
    from kaminpar_tpu.dist.partitioner import DKaMinPar
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name("default")
    ctx.refinement.algorithms = ctx.refinement.algorithms + (
        RefinementAlgorithm.JET,
    )
    ctx.refinement.jet.num_iterations = 4
    ctx.coarsening.contraction_limit = 128
    g = generators.rgg2d_graph(2048, seed=8)
    k = 8
    solver = DKaMinPar(_mesh(), ctx)
    part = solver.compute_partition(g, k=k, epsilon=0.05)
    W = g.total_node_weight
    per = int(np.ceil(W / k) * 1.05) + int(np.asarray(g.node_w).max())
    bw = np.bincount(part, weights=np.asarray(g.node_w), minlength=k)
    assert (bw <= per).all()
    assert len(np.unique(part)) == k
