"""Graph datastructure tests (reference tier 2: tests/shm/ graph tests,
fixtures from tests/shm/graph_factories.h)."""

import numpy as np
import pytest

from kaminpar_tpu.graph import (
    CSRGraph,
    from_edge_list,
    generators,
    metrics,
    rearrange_by_degree_buckets,
    validate,
)


def test_path_graph():
    g = generators.path_graph(5)
    validate(g)
    assert g.n == 5 and g.m == 8  # 4 undirected edges, stored twice
    assert g.total_node_weight == 5


def test_star_graph():
    g = generators.star_graph(6)
    validate(g)
    assert g.n == 7 and g.m == 12
    deg = np.asarray(g.degrees())
    assert deg[0] == 6 and (deg[1:] == 1).all()


def test_complete_graph():
    g = generators.complete_graph(5)
    validate(g)
    assert g.m == 5 * 4


def test_grid_graph():
    g = generators.grid2d_graph(3, 4)
    validate(g)
    assert g.n == 12
    assert g.m == 2 * (3 * 3 + 2 * 4)


def test_from_edge_list_dedup_and_selfloops():
    edges = np.array([[0, 1], [0, 1], [1, 2], [2, 2]])
    g = from_edge_list(3, edges)
    validate(g)
    # duplicate (0,1) collapses with summed weight, self-loop dropped
    assert g.m == 4
    assert g.total_edge_weight == 6  # (0,1) w=2 both dirs + (1,2) w=1 both dirs


def test_weighted_graph():
    edges = np.array([[0, 1], [1, 2]])
    g = from_edge_list(3, edges, edge_weights=np.array([5, 7]),
                       node_weights=np.array([1, 2, 3]))
    validate(g)
    assert g.total_node_weight == 6
    assert g.max_node_weight == 3
    assert g.total_edge_weight == 24


def test_edge_u():
    g = generators.path_graph(4)
    u = np.asarray(g.edge_u)
    col = np.asarray(g.col_idx)
    row_ptr = np.asarray(g.row_ptr)
    expect = np.repeat(np.arange(4), np.diff(row_ptr))
    assert (u == expect).all()
    assert len(col) == g.m


def test_rmat_generator():
    g = generators.rmat_graph(8, 4, seed=1)
    validate(g)
    assert g.n == 256
    assert g.m > 0


def test_rgg2d_generator():
    g = generators.rgg2d_graph(200, seed=1)
    validate(g)
    assert g.n == 200


def test_degree_bucket_rearrange():
    g = generators.star_graph(8)
    rg, old_to_new = rearrange_by_degree_buckets(g)
    validate(rg)
    deg = np.asarray(rg.degrees())
    assert (np.diff(deg) >= 0).all()  # sorted by bucket
    # remap: partition of reordered graph maps back
    assert sorted(old_to_new.tolist()) == list(range(g.n))


def test_padded_view():
    g = generators.path_graph(5)
    pv = g.padded()
    assert pv.n == 5 and pv.m == 8
    assert pv.n_pad > pv.n and pv.m_pad > pv.m
    assert (pv.n_pad & (pv.n_pad - 1)) == 0  # power of two
    nw = np.asarray(pv.node_w)
    assert nw[: pv.n].sum() == 5 and nw[pv.n:].sum() == 0
    ew = np.asarray(pv.edge_w)
    assert ew[pv.m:].sum() == 0
    # pad edges are anchor self-loops
    col = np.asarray(pv.col_idx)
    eu = np.asarray(pv.edge_u)
    assert (col[pv.m:] == pv.anchor).all()
    assert (eu[pv.m:] == pv.anchor).all()


def test_metrics_edge_cut():
    g = generators.path_graph(4)  # 0-1-2-3
    part = np.array([0, 0, 1, 1])
    assert metrics.edge_cut(g, part) == 1
    part2 = np.array([0, 1, 0, 1])
    assert metrics.edge_cut(g, part2) == 3


def test_metrics_block_weights_imbalance():
    g = generators.path_graph(4)
    part = np.array([0, 0, 0, 1])
    bw = np.asarray(metrics.block_weights(g, part, 2))
    assert (bw == [3, 1]).all()
    assert metrics.imbalance(g, part, 2) == pytest.approx(0.5)
    assert metrics.is_feasible(g, part, 2, [3, 3])
    assert not metrics.is_feasible(g, part, 2, [2, 2])
    assert metrics.total_overload(g, part, 2, [2, 2]) == 1


def test_sparsify_threshold_keeps_heaviest_and_symmetry():
    """Threshold sparsifier (sparsification_cluster_coarsener.cc:175-228):
    ~target_m heaviest edges survive; both directions agree."""
    import numpy as np

    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.graph.csr import CSRGraph, from_edge_list
    from kaminpar_tpu.coarsening.sparsifier import sparsify_threshold

    g0 = generators.rgg2d_graph(512, seed=9)
    rp = np.asarray(g0.row_ptr); col = np.asarray(g0.col_idx)
    u = np.repeat(np.arange(g0.n), np.diff(rp))
    key = np.minimum(u, col) * g0.n + np.maximum(u, col)
    g = from_edge_list(
        g0.n, np.stack([u, col], 1), edge_weights=(key % 17 + 1),
        symmetrize=False, dedup=False,
    )
    target = g.m // 3
    s = sparsify_threshold(g, target)
    # tie edges are hash-sampled independently -> binomial deviation
    assert abs(s.m - target) <= max(0.1 * target, 4)
    # only edges were dropped, none invented; the heaviest all survive
    sw = np.asarray(s.edge_w)
    thresh_kept = sw.min()
    ew = np.asarray(g.edge_w)
    assert (np.sort(sw)[::-1][: (ew > thresh_kept).sum()] > thresh_kept).all()
    # symmetric: (u, v) kept iff (v, u) kept
    su = np.repeat(np.arange(s.n), np.diff(np.asarray(s.row_ptr)))
    scol = np.asarray(s.col_idx)
    pairs = set(zip(su.tolist(), scol.tolist()))
    assert all((v, w) in pairs for w, v in pairs)


def test_linear_time_kway_preset_end_to_end():
    from kaminpar_tpu.graph import generators, metrics
    from kaminpar_tpu.kaminpar import KaMinPar

    g = generators.rmat_graph(10, 8, seed=2)
    s = KaMinPar("linear-time-kway")
    s.set_graph(g)
    part = s.compute_partition(k=8)
    assert metrics.is_feasible(g, part, 8, s.ctx.partition.max_block_weights)
