"""Run ledger + regression sentinel (ISSUE 8): RUNS.jsonl append/read,
noise-aware baseline comparison, the tools ledger/regress CLI contract
(regress exits nonzero on an injected 2x phase regression and zero on an
identical replay), and the bench salvage compression satellite."""

import json

import pytest

from kaminpar_tpu.telemetry import ledger


def _record(**overrides):
    rec = {
        "value": 2.5e6,
        "vs_baseline": 0.003,
        "backend": "cpu-fallback",
        "partition_wall_s": 120.0,
        "partition_cut": 60000,
        "host_sync_count": 48,
        "host_sync_bytes": 12345,
        "phase_walls_s": {"partitioning": 110.0, "lp_bench_fence": 4.0},
        "collectives": {"count": 30, "logical_bytes": 4096,
                        "by_op": {"psum": {"count": 25, "logical_bytes": 1024},
                                  "all_to_all": {"count": 5,
                                                 "logical_bytes": 3072}}},
        "compiled_shape_count": {"total": 40},
        "lint": {"fresh": 0},
    }
    rec.update(overrides)
    return rec


def test_entry_build_append_read_roundtrip(tmp_path):
    path = str(tmp_path / "RUNS.jsonl")
    entry = ledger.build_entry(_record(), kind="bench", git_head="abc1234")
    assert entry["schema"] == ledger.SCHEMA
    assert entry["kind"] == "bench"
    assert entry["git_head"] == "abc1234"
    assert entry["backend"] == "cpu-fallback"
    assert entry["metrics"]["partition_wall_s"] == 120.0
    assert entry["metrics"]["partition_cut"] == 60000
    assert entry["sync"]["count"] == 48
    assert entry["collectives"]["count"] == 30
    assert entry["collectives"]["by_op"] == {"psum": 25, "all_to_all": 5}
    assert entry["compiled_shapes"] == 40
    assert entry["stale_vs_head"] is False

    ledger.append(entry, path)
    ledger.append(ledger.build_entry(_record(), kind="bench"), path)
    entries = ledger.read(path)
    assert len(entries) == 2
    assert entries[0]["git_head"] == "abc1234"
    assert ledger.tail(1, path) == entries[-1:]

    # a torn write must not poison the ledger
    with open(path, "a") as fh:
        fh.write('{"truncated": tru\n')
    assert len(ledger.read(path)) == 2


def test_metric_direction_classes():
    assert ledger.metric_direction("partition_wall_s") == "down"
    assert ledger.metric_direction("serve_p99_ms") == "down"
    assert ledger.metric_direction("partition_cut") == "down"
    assert ledger.metric_direction("host_sync_count") == "down"
    assert ledger.metric_direction("value") == "up"
    assert ledger.metric_direction("serve_throughput_gps") == "up"
    assert ledger.metric_direction("lanestack_vs_pergraph") == "up"
    assert ledger.metric_direction("vs_baseline") == "up"


def test_compare_quiet_on_identical_and_within_noise():
    base = [ledger.build_entry(_record(), kind="bench") for _ in range(3)]
    # identical replay: silent
    assert ledger.compare(ledger.build_entry(_record(), kind="bench"), base) == []
    # within the noise tolerance: silent
    near = ledger.build_entry(
        _record(partition_wall_s=140.0,
                phase_walls_s={"partitioning": 125.0}), kind="bench"
    )
    assert ledger.compare(near, base) == []


def test_compare_flags_wall_census_quality_and_throughput():
    base = [ledger.build_entry(_record(), kind="bench") for _ in range(3)]
    bad = ledger.build_entry(
        _record(
            partition_wall_s=240.0,       # 2x wall
            host_sync_count=49,           # one stray blocking transfer
            partition_cut=70000,          # ~17% worse cut
            value=1.0e6,                  # throughput collapse
            phase_walls_s={"partitioning": 110.0, "lp_bench_fence": 4.0},
            collectives={"count": 31, "logical_bytes": 4096, "by_op": {}},
        ),
        kind="bench",
    )
    regs = {r["metric"]: r for r in ledger.compare(bad, base)}
    assert "partition_wall_s" in regs and regs["partition_wall_s"]["class"] == "wall"
    assert "census.host_sync_count" in regs
    assert regs["census.host_sync_count"]["class"] == "census"
    assert "census.collective_count" in regs
    assert "partition_cut" in regs and regs["partition_cut"]["class"] == "quality"
    assert "value" in regs and regs["value"]["class"] == "throughput"


def test_baseline_window_filters_kind_backend_and_workload():
    entries = [
        ledger.build_entry(_record(backend="cpu-fallback"), kind="bench"),
        ledger.build_entry(_record(backend="tpu"), kind="bench"),
        ledger.build_entry(_record(backend="cpu-fallback"), kind="prober"),
        ledger.build_entry(_record(backend="cpu-fallback"), kind="bench"),
        # same kind/backend but a DIFFERENT workload scale: not a baseline
        ledger.build_entry(
            _record(backend="cpu-fallback", partition_scale=9), kind="bench"
        ),
    ]
    latest = ledger.build_entry(
        _record(backend="cpu-fallback", partition_scale=17), kind="bench"
    )
    window = ledger.baseline_window(entries, latest, window=5)
    # the two scale-free cpu-fallback bench entries match (absent config
    # keys are compatible); the scale-9 entry does not
    assert len(window) == 2
    assert all(e["backend"] == "cpu-fallback" and e["kind"] == "bench"
               for e in window)
    assert all(
        (e.get("metrics") or {}).get("partition_scale") is None
        for e in window
    )


def test_tools_regress_cli_exit_codes(tmp_path, capsys):
    """Acceptance (ISSUE 8): ``tools regress`` exits nonzero on a
    synthetically injected 2x phase regression and zero on a replayed
    identical entry."""
    from kaminpar_tpu.tools.__main__ import main as tools_main

    path = str(tmp_path / "RUNS.jsonl")
    for _ in range(3):
        ledger.append(ledger.build_entry(_record(), kind="bench"), path)
    # identical replay
    ledger.append(ledger.build_entry(_record(), kind="bench"), path)
    assert tools_main(["regress", "--runs", path]) == 0
    assert "no regressions" in capsys.readouterr().out

    # injected 2x regression in a phase wall
    ledger.append(
        ledger.build_entry(
            _record(phase_walls_s={"partitioning": 220.0,
                                   "lp_bench_fence": 4.0}),
            kind="bench",
        ),
        path,
    )
    assert tools_main(["regress", "--runs", path]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION phase.partitioning_s" in out

    # empty / no-baseline ledgers stay quiet (exit 0)
    empty = str(tmp_path / "EMPTY.jsonl")
    assert tools_main(["regress", "--runs", empty]) == 0
    lone = str(tmp_path / "LONE.jsonl")
    ledger.append(ledger.build_entry(_record(backend="tpu"), kind="bench"), lone)
    assert tools_main(["regress", "--runs", lone]) == 0
    capsys.readouterr()


def test_tools_ledger_cli(tmp_path, capsys):
    from kaminpar_tpu.tools.__main__ import main as tools_main

    path = str(tmp_path / "RUNS.jsonl")
    src = tmp_path / "record.json"
    src.write_text(json.dumps(_record()))
    assert tools_main(["ledger", "append", "--runs", path,
                       "--from-json", str(src), "--kind", "bench"]) == 0
    capsys.readouterr()
    assert tools_main(["ledger", "show", "--runs", path]) == 0
    out = capsys.readouterr().out
    assert "bench" in out and "partition_wall_s=120.0" in out
    assert tools_main(["ledger", "tail", "--runs", path, "-n", "1"]) == 0
    tail_out = capsys.readouterr().out
    assert json.loads(tail_out)["kind"] == "bench"
    # missing --from-json is an error, empty ledger is not
    assert tools_main(["ledger", "append", "--runs", path]) == 1
    capsys.readouterr()
    assert tools_main(["ledger", "show", "--runs",
                       str(tmp_path / "NONE.jsonl")]) == 0
    assert "no ledger entries" in capsys.readouterr().out


def test_record_run_kill_switch(tmp_path, monkeypatch):
    path = str(tmp_path / "RUNS.jsonl")
    monkeypatch.setenv("KPTPU_LEDGER", "0")
    assert ledger.record_run(_record(), kind="bench", path=path) is None
    assert ledger.read(path) == []
    monkeypatch.setenv("KPTPU_LEDGER", "1")
    assert ledger.record_run(_record(), kind="bench", path=path) == path
    assert len(ledger.read(path)) == 1


# -- bench salvage compression (satellite) -----------------------------------


def test_probe_telemetry_compresses_attempts(tmp_path, monkeypatch):
    """The prober summary embeds OUTCOME COUNTS (plus the 6h failure-window
    count the inline-probe decision needs) instead of the full per-attempt
    list that dominated BENCH_r05's tail."""
    import time as _time

    import bench

    log = tmp_path / "TPU_PROBE_LOG.jsonl"
    now = _time.time()
    rows = [{"event": "prober_start"}]
    for i in range(40):
        rows.append({"attempt": i + 1, "ts": now - 3600 * 10,
                     "iso": "old", "outcome": "init_hang_killed_after_1200s"})
    rows.append({"attempt": 41, "ts": now - 60, "iso": "new",
                 "outcome": "init_hang_killed_after_1201s"})
    rows.append({"attempt": 42, "ts": now - 30, "iso": "newer",
                 "outcome": "ambient_is_cpu"})
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    monkeypatch.setattr(bench, "TPU_PROBE_LOG", str(log))

    summary = bench.probe_telemetry()
    assert summary["attempts"] == 42
    assert summary["outcomes"]["init_hang_killed_after_1200s"] == 40
    assert "attempt_records" not in summary  # the compression satellite
    # only the two attempts inside the 6h window count as recent failures
    assert summary["recent_failed_6h"] == 2
    assert bench._recent_failures(summary) == 2
    assert bench._recent_failures(None) == 0
    assert summary["last_outcome"] == "ambient_is_cpu"
    # the summary is fixed-size: growing the log 10x must not grow it
    assert len(json.dumps(summary)) < 2000


# -- git head resolution (round 20 satellite) --------------------------------


def test_resolve_git_head_fallback_chain(monkeypatch):
    """Env override -> subprocess rev-parse -> ""; cached once resolved,
    and build_entry falls back to it so tier-1/bench entries written with
    no explicit head stop recording git_head=""."""
    monkeypatch.setenv("KPTPU_GIT_HEAD", "feedc0de")
    assert ledger.resolve_git_head(force=True) == "feedc0de"
    # cached: later env changes are invisible without force
    monkeypatch.setenv("KPTPU_GIT_HEAD", "other")
    assert ledger.resolve_git_head() == "feedc0de"
    monkeypatch.delenv("KPTPU_GIT_HEAD")
    head = ledger.resolve_git_head(force=True)
    assert head, "this repo is a git checkout: rev-parse must resolve"
    assert head != "feedc0de"

    entry = ledger.build_entry(_record(), kind="tier1")
    assert entry["git_head"] == head
    # an explicit head (or one carried by the record) still wins
    assert ledger.build_entry(
        _record(), kind="tier1", git_head="abc1234")["git_head"] == "abc1234"
    assert ledger.build_entry(
        _record(git_head="def5678"), kind="tier1")["git_head"] == "def5678"


# -- ledger analytics (round 20 tentpole c) ----------------------------------


def _series(n=6, regress_last=False):
    """n chronological same-workload entries; optionally the last one
    carries an injected 2.5x wall regression living in one phase."""
    entries = []
    for i in range(n):
        bad = regress_last and i == n - 1
        entries.append(ledger.build_entry(_record(
            partition_wall_s=300.0 if bad else 120.0,
            phase_walls_s={"partitioning": 290.0 if bad else 110.0,
                           "lp_bench_fence": 4.0},
        ), kind="bench"))
    return entries


def test_metric_trends_verdicts():
    trends = ledger.metric_trends(_series(regress_last=True))
    wall = trends["partition_wall_s"]
    assert wall["n"] == 6
    assert wall["prior_median"] == 120.0 and wall["last"] == 300.0
    assert wall["verdict"] == "regressed"
    assert trends["phase.partitioning_s"]["verdict"] == "regressed"
    assert trends["partition_cut"]["verdict"] == "flat"
    # an improving higher-better metric reads as improved
    up = [ledger.build_entry(_record(value=1e6), kind="bench")
          for _ in range(3)]
    up.append(ledger.build_entry(_record(value=2e6), kind="bench"))
    assert ledger.metric_trends(up)["value"]["verdict"] == "improved"
    # single-entry groups have no trend
    assert ledger.metric_trends(_series(n=1)) == {}


def test_attribute_names_co_moving_phase():
    entries = _series(regress_last=True)
    latest, base = entries[-1], entries[:-1]
    regs = ledger.compare(latest, base)
    assert any(r["metric"] == "partition_wall_s" for r in regs)
    attr = {a["metric"]: a["suspects"]
            for a in ledger.attribute(latest, base, regs)}
    suspects = [s["metric"] for s in attr["partition_wall_s"]]
    assert "phase.partitioning_s" in suspects
    # the stable phase is NOT a suspect (below the movement floor)
    assert "phase.lp_bench_fence_s" not in suspects
    top = attr["partition_wall_s"][0]
    assert top["metric"] == "phase.partitioning_s"
    assert top["latest"] == 290.0 and top["baseline_median"] == 110.0
    # a quiet series produces no attribution at all
    assert ledger.attribute(_series()[-1], _series()[:-1]) == []


def test_build_report_groups_and_markdown(tmp_path):
    path = str(tmp_path / "RUNS.jsonl")
    for entry in _series(regress_last=True):
        ledger.append(entry, path)
    # a second, quiet group with a different kind
    for _ in range(3):
        ledger.append(ledger.build_entry(_record(), kind="prober"), path)

    rep = ledger.build_report(path=path)
    assert rep["summary"]["entries"] == 9
    assert rep["summary"]["groups"] == 2
    assert rep["summary"]["regressed_groups"] == 1
    assert rep["summary"]["total_regressions"] >= 1
    bench_row = next(r for r in rep["groups"] if r["kind"] == "bench")
    assert bench_row["regressions"] and bench_row["attribution"]
    prober_row = next(r for r in rep["groups"] if r["kind"] == "prober")
    assert not prober_row["regressions"]

    md = ledger.render_report_markdown(rep)
    assert "# Ledger report" in md
    assert "## bench" in md and "## prober" in md
    assert "partition_wall_s" in md
    assert "suspect phase.partitioning_s" in md

    # kind filter narrows the report
    only = ledger.build_report(path=path, kinds=["prober"])
    assert only["summary"]["groups"] == 1
    assert only["groups"][0]["kind"] == "prober"


def test_tools_report_cli_and_regress_summary(tmp_path, capsys):
    """Acceptance (ISSUE 20c): ``tools report`` renders the ledger
    jax-free, attributes the injected regression fixture, and its
    summary keys ride the ``tools regress`` sentinel."""
    from kaminpar_tpu.tools.__main__ import main as tools_main

    path = str(tmp_path / "RUNS.jsonl")
    for entry in _series(regress_last=True):
        ledger.append(entry, path)

    assert tools_main(["report", "--runs", path]) == 0
    md = capsys.readouterr().out
    assert "# Ledger report" in md
    assert "suspect phase.partitioning_s" in md

    assert tools_main(["report", "--runs", path, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["summary"]["regressed_groups"] == 1
    suspects = [s["metric"]
                for a in rep["groups"][0]["attribution"]
                for s in a["suspects"]]
    assert "phase.partitioning_s" in suspects

    out = tmp_path / "report.md"
    assert tools_main(["report", "--runs", path, "--out", str(out)]) == 0
    capsys.readouterr()
    assert "suspect phase.partitioning_s" in out.read_text()

    # a missing ledger is a typed failure
    assert tools_main(["report", "--runs",
                       str(tmp_path / "NONE.jsonl")]) == 2
    capsys.readouterr()

    # the regress sentinel carries the report summary keys
    assert tools_main(["regress", "--runs", path, "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["regressions"]
    summ = payload["report_summary"]
    assert summ["groups"] == 1 and summ["regressed_groups"] == 1
    assert summ["trend_regressed_metrics"] >= 1
