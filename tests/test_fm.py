"""k-way FM refiner tests (reference: fm_refiner.cc exercised through
shm endtoend tests; here directly)."""

import numpy as np

from kaminpar_tpu.context import FMContext
from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.partitioned import PartitionedGraph
from kaminpar_tpu.refinement.fm_refiner import FMRefiner


def _pgraph(g, k, part, eps=0.1):
    W = int(np.asarray(g.node_w).sum())
    per = int(np.ceil(W / k) * (1 + eps)) + int(np.asarray(g.node_w).max())
    return PartitionedGraph.create(g, k, part, np.full(k, per, dtype=np.int64))


def test_fm_improves_noisy_grid():
    g = generators.grid2d_graph(16, 16)
    rng = np.random.default_rng(0)
    part = (np.arange(256) // 64).astype(np.int32)
    flip = rng.random(256) < 0.25
    part[flip] = rng.integers(0, 4, flip.sum())
    pg = _pgraph(g, 4, part)
    before = pg.edge_cut()
    out = FMRefiner(FMContext()).refine(pg)
    assert out.edge_cut() < before
    assert out.is_feasible()


def test_fm_improves_rmat_vs_lp_alone():
    """FM escapes local minima LP can't (negative-gain move chains)."""
    from kaminpar_tpu.context import LabelPropagationContext
    from kaminpar_tpu.refinement.lp_refiner import LPRefiner

    g = generators.rmat_graph(9, 8, seed=2)
    rng = np.random.default_rng(2)
    part = rng.integers(0, 4, g.n).astype(np.int32)
    pg = _pgraph(g, 4, part)
    lp_out = LPRefiner(LabelPropagationContext(num_iterations=8)).refine(pg)
    fm_out = FMRefiner(FMContext()).refine(lp_out)
    assert fm_out.edge_cut() <= lp_out.edge_cut()
    assert fm_out.is_feasible()


def test_fm_skips_large_graphs():
    g = generators.grid2d_graph(16, 16)
    part = (np.arange(256) // 64).astype(np.int32)
    pg = _pgraph(g, 4, part)
    out = FMRefiner(FMContext(max_n=100)).refine(pg)
    assert np.array_equal(np.asarray(out.partition), np.asarray(pg.partition))


def test_fm_respects_budgets_tight():
    g = generators.grid2d_graph(8, 8)
    part = (np.arange(64) // 16).astype(np.int32)
    pg = PartitionedGraph.create(g, 4, part, np.full(4, 17, dtype=np.int64))
    out = FMRefiner(FMContext()).refine(pg)
    bw = np.asarray(out.block_weights())
    assert (bw <= 17).all(), bw
