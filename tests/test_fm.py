"""k-way FM refiner tests (reference: fm_refiner.cc exercised through
shm endtoend tests; here directly)."""

import numpy as np

from kaminpar_tpu.context import FMContext
from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.partitioned import PartitionedGraph
from kaminpar_tpu.refinement.fm_refiner import FMRefiner


def _pgraph(g, k, part, eps=0.1):
    W = int(np.asarray(g.node_w).sum())
    per = int(np.ceil(W / k) * (1 + eps)) + int(np.asarray(g.node_w).max())
    return PartitionedGraph.create(g, k, part, np.full(k, per, dtype=np.int64))


def test_fm_improves_noisy_grid():
    g = generators.grid2d_graph(16, 16)
    rng = np.random.default_rng(0)
    part = (np.arange(256) // 64).astype(np.int32)
    flip = rng.random(256) < 0.25
    part[flip] = rng.integers(0, 4, flip.sum())
    pg = _pgraph(g, 4, part)
    before = pg.edge_cut()
    out = FMRefiner(FMContext()).refine(pg)
    assert out.edge_cut() < before
    assert out.is_feasible()


def test_fm_improves_rmat_vs_lp_alone():
    """FM escapes local minima LP can't (negative-gain move chains)."""
    from kaminpar_tpu.context import LabelPropagationContext
    from kaminpar_tpu.refinement.lp_refiner import LPRefiner

    g = generators.rmat_graph(9, 8, seed=2)
    rng = np.random.default_rng(2)
    part = rng.integers(0, 4, g.n).astype(np.int32)
    pg = _pgraph(g, 4, part)
    lp_out = LPRefiner(LabelPropagationContext(num_iterations=8)).refine(pg)
    fm_out = FMRefiner(FMContext()).refine(lp_out)
    assert fm_out.edge_cut() <= lp_out.edge_cut()
    assert fm_out.is_feasible()


def test_fm_skips_large_graphs():
    g = generators.grid2d_graph(16, 16)
    part = (np.arange(256) // 64).astype(np.int32)
    pg = _pgraph(g, 4, part)
    out = FMRefiner(FMContext(max_n=100)).refine(pg)
    assert np.array_equal(np.asarray(out.partition), np.asarray(pg.partition))


def test_fm_respects_budgets_tight():
    g = generators.grid2d_graph(8, 8)
    part = (np.arange(64) // 16).astype(np.int32)
    pg = PartitionedGraph.create(g, 4, part, np.full(4, 17, dtype=np.int64))
    out = FMRefiner(FMContext()).refine(pg)
    bw = np.asarray(out.block_weights())
    assert (bw <= 17).all(), bw


def test_fm_sparse_conn_matches_dense():
    """The lazily-materialized border-row table (sparse_gain_cache.h role)
    must produce bit-identical results to the dense matrix: same graph,
    same seed, dense_nk_threshold forced to 0 to select the sparse path."""
    from kaminpar_tpu.utils import RandomState

    g = generators.rmat_graph(10, 8, seed=3)
    rng = np.random.default_rng(5)
    part0 = rng.integers(0, 8, g.n).astype(np.int32)
    pg = _pgraph(g, 8, part0)

    RandomState.reseed(7)
    dense = FMRefiner(FMContext()).refine(pg)
    RandomState.reseed(7)
    sparse = FMRefiner(FMContext(dense_nk_threshold=0)).refine(pg)
    assert np.array_equal(np.asarray(dense.partition), np.asarray(sparse.partition))
    assert sparse.edge_cut() < pg.edge_cut()


def test_fm_sparse_runs_above_old_nk_gate():
    """n*k above the removed 2^26 gate must still run FM (VERDICT r3 #6);
    memory stays bounded by the touched set, which we check indirectly by
    the sparse table being selected and the result improving the cut."""
    g = generators.rmat_graph(12, 8, seed=4)
    rng = np.random.default_rng(6)
    k = 64
    part0 = rng.integers(0, k, g.n).astype(np.int32)
    pg = _pgraph(g, k, part0)
    ctx = FMContext(dense_nk_threshold=1)  # force sparse at any size
    out = FMRefiner(ctx).refine(pg)
    assert out.edge_cut() < pg.edge_cut()
    assert out.is_feasible()
