"""Sync accounting + the device-resident multilevel spine (ISSUE 2).

The contract under test: a coarsening level performs at most ONE blocking
device->host transfer (the batched stats readback in contract_clustering) on
both the LP/XLA and LP/Pallas paths, with zero implicit scalar pulls
(``int(x)`` / ``float(x)`` / ``bool(x)`` / ``.item()``) anywhere in the
level loop — asserted through utils/sync_stats' counters and its
dunder-patching tripwire (the CPU backend's zero-copy host arrays never
trigger jax's own transfer guard, so the tripwire is the CI-effective
detector).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from kaminpar_tpu.context import Context
from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.csr import set_layout_build_mode
from kaminpar_tpu.utils import sync_stats


@pytest.fixture(autouse=True)
def _clean_sync_state():
    sync_stats.reset()
    yield
    sync_stats.reset()
    sync_stats.enable_budget_checks(False)
    set_layout_build_mode("auto")


def test_pull_counts_per_phase():
    x = jnp.arange(16, dtype=jnp.int32)
    with sync_stats.scoped("alpha"):
        host = sync_stats.pull(x)
    assert isinstance(host, np.ndarray) and host.shape == (16,)
    with sync_stats.scoped("alpha"):
        a, b = sync_stats.pull(x, x * 2)
    assert b[3] == 6
    snap = sync_stats.snapshot()
    assert snap["phases"]["alpha"]["count"] == 3
    assert snap["phases"]["alpha"]["bytes"] == 3 * 16 * 4
    assert sync_stats.phase_count("alpha") == 3
    assert sync_stats.phase_count("beta") == 0


def test_tripwire_counts_implicit_scalar_pulls():
    x = jnp.int32(7)
    with sync_stats.scoped("phase_t"):
        with sync_stats.tripwire():
            assert int(x) == 7
            assert float(x) == 7.0
            assert bool(x > 0)
    snap = sync_stats.snapshot()["phases"]["phase_t"]
    assert snap["implicit"] >= 3
    assert snap["count"] == 0
    # uninstalled outside the context: no further counting
    int(jnp.int32(1))
    assert sync_stats.snapshot()["phases"]["phase_t"]["implicit"] == snap["implicit"]


def test_assert_phase_budget():
    sync_stats.enable_budget_checks(True)
    with sync_stats.scoped("budgeted"):
        sync_stats.pull(jnp.arange(4))
        sync_stats.pull(jnp.arange(4))
    sync_stats.assert_phase_budget("budgeted", 2)
    with pytest.raises(AssertionError, match="sync budget"):
        sync_stats.assert_phase_budget("budgeted", 1)
    sync_stats.enable_budget_checks(False)
    sync_stats.assert_phase_budget("budgeted", 0)  # disarmed: no-op


def test_shard_pull_accounting_and_per_shard_budget():
    """Round 13: a mesh-wide pull counts ONE blocking transfer (budget
    currency unchanged) while shard_pulls records the P logical reads a
    per-rank layout would have paid, and assert_phase_budget(shards=P)
    expresses budgets in that per-shard currency."""
    with sync_stats.scoped("meshy"):
        sync_stats.pull(jnp.arange(8), shards=4)
        sync_stats.pull(jnp.arange(8), jnp.arange(8), shards=4)
    snap = sync_stats.snapshot()["phases"]["meshy"]
    assert snap["count"] == 3           # one transfer per pulled array
    assert snap["shard_pulls"] == 12    # x4 shards each
    assert snap["sharded_count"] == 3
    assert sync_stats.shard_phase_count("meshy") == (12, 3)
    assert sync_stats.snapshot()["shard_pulls"] == 12

    sync_stats.enable_budget_checks(True)
    try:
        sync_stats.assert_phase_budget("meshy", 3, shards=4)  # 12 <= 12
        with pytest.raises(AssertionError, match="per-shard sync budget"):
            sync_stats.assert_phase_budget("meshy", 2, shards=4)  # 12 > 8
        # since= takes a shard_pulls snapshot in per-shard mode (and
        # count_since= the matching plain-count snapshot)
        since = sync_stats.shard_phase_count("meshy")[0]
        count_since = sync_stats.phase_count("meshy")
        with sync_stats.scoped("meshy"):
            sync_stats.pull(jnp.arange(4), shards=4)
        sync_stats.assert_phase_budget("meshy", 1, since=since, shards=4,
                                       count_since=count_since)
        # A stray pull that FORGOT its shards= tag is invisible to the
        # per-shard ledger but must still trip the plain-currency bound.
        with sync_stats.scoped("meshy"):
            sync_stats.pull(jnp.arange(4))  # untagged stray
        with pytest.raises(AssertionError, match="missing their shards"):
            sync_stats.assert_phase_budget("meshy", 1, since=since, shards=4,
                                           count_since=count_since)
    finally:
        sync_stats.enable_budget_checks(False)


def _coarsen_all(graph, ctx, target_n=128):
    from kaminpar_tpu.coarsening.cluster_coarsener import ClusterCoarsener

    coarsener = ClusterCoarsener(ctx, graph)
    coarsener.coarsen(ctx.partition.k, 0.03, target_n)
    return coarsener


@pytest.mark.slow  # heavy scale-12 x {xla,pallas} matrix (~55 s); the same
# one-readback-per-level budget is asserted at pipeline scale below in
# test_coarsening_budget_asserted_in_deep_pipeline (round-20 tier-1 rebalance)
@pytest.mark.parametrize("lp_kernel", ["xla", "pallas"])
def test_coarsening_level_single_readback_scale12(lp_kernel):
    """Acceptance (ISSUE 2 + ISSUE 5): blocking device->host transfers per
    coarsening level <= 1 on the LP/XLA and LP/Pallas paths at scale 12, and
    zero implicit scalar pulls inside the level loop — WITH telemetry armed,
    so the per-level quality probes are proven sync-budget neutral exactly
    where the budget is asserted."""
    from kaminpar_tpu import telemetry

    g = generators.rmat_graph(12, 8, seed=1)
    g.total_node_weight  # facade precomputes this before partitioning
    ctx = Context()
    ctx.partition.k = 4
    ctx.coarsening.lp.lp_kernel = lp_kernel
    ctx.coarsening.lp.num_iterations = 3 if lp_kernel == "pallas" else 5
    set_layout_build_mode("device")
    sync_stats.reset()
    with telemetry.run() as rec:
        with sync_stats.tripwire():
            coarsener = _coarsen_all(g, ctx)
    assert coarsener.contractions >= 2  # a real multi-level hierarchy
    snap = sync_stats.snapshot()["phases"]
    # one batched stats readback per contraction, nothing else — the armed
    # quality probes added zero transfers
    assert snap["coarsening"]["count"] == coarsener.contractions, snap
    assert snap["coarsening"]["implicit"] == 0, snap
    # the LP sweep loop is fully device-resident (lax.while_loop)
    lp_phase = snap.get("lp_clustering", {"count": 0, "implicit": 0})
    assert lp_phase["count"] == 0, snap
    assert lp_phase["implicit"] == 0, snap
    # ... and the probes did fire: one quality row per pushed level
    levels = [r for r in rec.quality if r["kind"] == "coarsening_level"]
    assert len(levels) == coarsener.contractions


def test_coarsening_budget_asserted_in_deep_pipeline():
    """deep.py's in-pipeline budget assertion (armed) holds on a full
    partition, and the pipeline runs under the implicit-sync tripwire
    without any stray scalar pull in the coarsening phases.  Telemetry runs
    armed (ISSUE 5): the per-level quality probes — including the packed
    extend-partition cut pull — must pass the same armed budgets."""
    from kaminpar_tpu import telemetry
    from kaminpar_tpu.graph.metrics import is_feasible
    from kaminpar_tpu.kaminpar import KaMinPar

    g = generators.rmat_graph(11, 8, seed=2)
    ctx = Context()
    from kaminpar_tpu.context import PartitioningMode

    ctx.mode = PartitioningMode.DEEP
    ctx.coarsening.contraction_limit = 200  # force a real hierarchy
    set_layout_build_mode("device")
    sync_stats.enable_budget_checks(True)
    try:
        with telemetry.run():
            with sync_stats.tripwire():
                s = KaMinPar(ctx=ctx)
                s.set_graph(g)
                part = s.compute_partition(4, epsilon=0.03)
    finally:
        sync_stats.enable_budget_checks(False)
    assert is_feasible(g, part, 4, s.ctx.partition.max_block_weights)
    snap = sync_stats.snapshot()["phases"]
    assert snap["coarsening"]["implicit"] == 0, snap
    assert snap.get("lp_clustering", {}).get("implicit", 0) == 0, snap
    assert snap.get("lp_refinement", {}).get("count", 0) == 0, snap


def test_full_partition_identical_across_layout_backends():
    """The device layout build is bit-inert end-to-end: the whole partition
    (same seed) is identical under host and device layout construction."""
    from kaminpar_tpu.kaminpar import KaMinPar

    outs = {}
    for mode in ("host", "device"):
        set_layout_build_mode(mode)
        g = generators.rmat_graph(10, 8, seed=3)
        ctx = Context()
        ctx.seed = 5
        s = KaMinPar(ctx=ctx)
        # KaMinPar() resets the layout mode per ctx.parallel; re-pin it.
        set_layout_build_mode(mode)
        s.set_graph(g)
        outs[mode] = np.asarray(s.compute_partition(8, epsilon=0.03))
    assert np.array_equal(outs["host"], outs["device"])


def test_scoped_timer_pushes_sync_phase():
    from kaminpar_tpu.utils.timer import scoped_timer

    with scoped_timer("outer_phase"):
        sync_stats.pull(jnp.arange(8))
        with scoped_timer("inner_phase"):
            sync_stats.pull(jnp.arange(8))
    snap = sync_stats.snapshot()["phases"]
    assert snap["outer_phase"]["count"] == 1
    assert snap["inner_phase"]["count"] == 1


def test_scoped_timer_sync_sentinel():
    from kaminpar_tpu.utils import timer
    from kaminpar_tpu.utils.timer import scoped_timer

    timer.set_sync_mode(True)
    try:
        with scoped_timer("synced", sync=True) as ts:
            ts.note(jnp.arange(4) * 2)
        with scoped_timer("synced", sync=True):
            pass  # no sentinel noted: must not raise
    finally:
        timer.set_sync_mode(False)
