"""C API (capi/): build libkaminpar_tpu.so + the C demo client, run it.

The reference ships a C interface (include/kaminpar-shm/ckaminpar.h); ours
is a C-linkable shared library embedding CPython (see
capi/include/kaminpar_tpu.h for the design).  This test is the analog of
compiling and running a ckaminpar client program.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI = os.path.join(REPO, "kaminpar_tpu", "capi")


@pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="native toolchain unavailable",
)
def test_c_api_demo_client():
    build = subprocess.run(
        ["make", "demo"], cwd=CAPI, capture_output=True, text=True
    )
    assert build.returncode == 0, build.stderr[-2000:]

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO  # strip any site hook, like conftest does
    env["KPTPU_PYTHON"] = sys.executable
    run = subprocess.run(
        [os.path.join(CAPI, "demo")], capture_output=True, text=True,
        env=env, timeout=900,
    )
    assert run.returncode == 0, (run.stdout[-500:], run.stderr[-2000:])
    assert "CAPI_OK cut=" in run.stdout
    cut = int(run.stdout.split("cut=")[1].split()[0])
    # 24x24 grid into 4 blocks: the ideal quarter-cut is 48; anything in
    # this range is a sane partition, anything far above means the C path
    # corrupted the graph.
    assert 40 <= cut <= 120, cut
