"""Chunked per-shard IO tests (reference: dist_metis_parser.cc)."""

import numpy as np

from kaminpar_tpu.io.dist_io import read_metis_chunked, read_metis_sharded
from kaminpar_tpu.io.metis import read_metis, write_metis


def test_chunked_matches_full_read():
    full = read_metis("/root/reference/misc/rgg2d.metis")
    assembled = read_metis_sharded("/root/reference/misc/rgg2d.metis", 8)
    np.testing.assert_array_equal(
        np.asarray(full.row_ptr), np.asarray(assembled.row_ptr)
    )
    np.testing.assert_array_equal(
        np.asarray(full.col_idx), np.asarray(assembled.col_idx)
    )
    np.testing.assert_array_equal(
        np.asarray(full.edge_w), np.asarray(assembled.edge_w)
    )


def test_chunked_ranges_partition_nodes():
    chunks = list(read_metis_chunked("/root/reference/misc/rgg2d.metis", 5))
    assert len(chunks) == 5
    covered = []
    for s, (lo, hi), ch in chunks:
        assert ch.lo == lo and ch.hi == hi
        assert len(ch.node_w) == hi - lo
        covered.extend(range(lo, hi))
    assert covered == list(range(1024))


def test_chunked_weighted_roundtrip(tmp_path):
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.graph.csr import from_edge_list

    g = generators.rgg2d_graph(512, seed=6)
    rp = np.asarray(g.row_ptr); col = np.asarray(g.col_idx)
    u = np.repeat(np.arange(g.n), np.diff(rp))
    key = np.minimum(u, col) * g.n + np.maximum(u, col)
    rng = np.random.default_rng(0)
    g2 = from_edge_list(
        g.n, np.stack([u, col], 1), edge_weights=(key % 5 + 1),
        node_weights=rng.integers(1, 7, g.n), symmetrize=False, dedup=False,
    )
    path = str(tmp_path / "w.metis")
    write_metis(g2, path)
    full = read_metis(path)
    assembled = read_metis_sharded(path, 4)
    np.testing.assert_array_equal(np.asarray(full.row_ptr), np.asarray(assembled.row_ptr))
    np.testing.assert_array_equal(np.asarray(full.col_idx), np.asarray(assembled.col_idx))
    np.testing.assert_array_equal(np.asarray(full.edge_w), np.asarray(assembled.edge_w))
    np.testing.assert_array_equal(np.asarray(full.node_w), np.asarray(assembled.node_w))
