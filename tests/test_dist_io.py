"""Chunked per-shard IO tests (reference: dist_metis_parser.cc)."""

import os

import numpy as np
import pytest

from kaminpar_tpu.io.dist_io import read_metis_chunked, read_metis_sharded
from kaminpar_tpu.io.metis import read_metis, write_metis

# The large-file fixture ships with the reference checkout, which is not
# present in every container; the chunked-reader logic itself is still
# covered below by the roundtrip tests on generated graphs.
_RGG = "/root/reference/misc/rgg2d.metis"
needs_reference_graph = pytest.mark.skipif(
    not os.path.exists(_RGG), reason="reference rgg2d.metis not available"
)


@needs_reference_graph
def test_chunked_matches_full_read():
    full = read_metis(_RGG)
    assembled = read_metis_sharded(_RGG, 8)
    np.testing.assert_array_equal(
        np.asarray(full.row_ptr), np.asarray(assembled.row_ptr)
    )
    np.testing.assert_array_equal(
        np.asarray(full.col_idx), np.asarray(assembled.col_idx)
    )
    np.testing.assert_array_equal(
        np.asarray(full.edge_w), np.asarray(assembled.edge_w)
    )


@needs_reference_graph
def test_chunked_ranges_partition_nodes():
    chunks = list(read_metis_chunked(_RGG, 5))
    assert len(chunks) == 5
    covered = []
    for s, (lo, hi), ch in chunks:
        assert ch.lo == lo and ch.hi == hi
        assert len(ch.node_w) == hi - lo
        covered.extend(range(lo, hi))
    assert covered == list(range(1024))


def test_chunked_weighted_roundtrip(tmp_path):
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.graph.csr import from_edge_list

    g = generators.rgg2d_graph(512, seed=6)
    rp = np.asarray(g.row_ptr); col = np.asarray(g.col_idx)
    u = np.repeat(np.arange(g.n), np.diff(rp))
    key = np.minimum(u, col) * g.n + np.maximum(u, col)
    rng = np.random.default_rng(0)
    g2 = from_edge_list(
        g.n, np.stack([u, col], 1), edge_weights=(key % 5 + 1),
        node_weights=rng.integers(1, 7, g.n), symmetrize=False, dedup=False,
    )
    path = str(tmp_path / "w.metis")
    write_metis(g2, path)
    full = read_metis(path)
    assembled = read_metis_sharded(path, 4)
    np.testing.assert_array_equal(np.asarray(full.row_ptr), np.asarray(assembled.row_ptr))
    np.testing.assert_array_equal(np.asarray(full.col_idx), np.asarray(assembled.col_idx))
    np.testing.assert_array_equal(np.asarray(full.edge_w), np.asarray(assembled.edge_w))
    np.testing.assert_array_equal(np.asarray(full.node_w), np.asarray(assembled.node_w))


def test_parhip_chunked_bitequal(tmp_path):
    """8-shard ParHIP parse assembles bit-equal to the monolithic reader
    (VERDICT r2 next-steps #8)."""
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.graph.csr import from_edge_list
    from kaminpar_tpu.io.dist_io import read_parhip_sharded
    from kaminpar_tpu.io.parhip import read_parhip, write_parhip

    g = generators.rgg2d_graph(700, seed=8)
    rp = np.asarray(g.row_ptr); col = np.asarray(g.col_idx)
    u = np.repeat(np.arange(g.n), np.diff(rp))
    key = np.minimum(u, col) * g.n + np.maximum(u, col)
    rng = np.random.default_rng(1)
    g2 = from_edge_list(
        g.n, np.stack([u, col], 1), edge_weights=(key % 7 + 1),
        node_weights=rng.integers(1, 5, g.n), symmetrize=False, dedup=False,
    )
    path = str(tmp_path / "g.parhip")
    write_parhip(g2, path)
    full = read_parhip(path)
    assembled = read_parhip_sharded(path, 8)
    for attr in ("row_ptr", "col_idx", "edge_w", "node_w"):
        np.testing.assert_array_equal(
            np.asarray(getattr(full, attr)), np.asarray(getattr(assembled, attr))
        )


def test_parhip_chunked_unweighted_64bit(tmp_path):
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.io.dist_io import read_parhip_sharded
    from kaminpar_tpu.io.parhip import read_parhip, write_parhip

    g = generators.rmat_graph(9, 6, seed=3)
    path = str(tmp_path / "g64.parhip")
    write_parhip(g, path, use_64bit=True)
    full = read_parhip(path)
    assembled = read_parhip_sharded(path, 3)
    np.testing.assert_array_equal(np.asarray(full.row_ptr), np.asarray(assembled.row_ptr))
    np.testing.assert_array_equal(np.asarray(full.col_idx), np.asarray(assembled.col_idx))


def _assemble(chunks):
    rps, cols = [], []
    base = 0
    for _s, (_lo, _hi), ch in chunks:
        rps.append(ch.row_ptr[:-1] + base)
        base += int(ch.row_ptr[-1])
        cols.append(ch.col_idx)
    return np.concatenate(rps + [[base]]), np.concatenate(cols)


def test_streaming_rmat_shard_invariant():
    """Sharded generation is independent of the shard count (the skagen
    analog, dist_skagen.cc:33-40): 8 shards assemble bit-equal to 1."""
    from kaminpar_tpu.io.dist_io import streaming_rmat_sharded

    rp1, col1 = _assemble(streaming_rmat_sharded(9, 4, 1, seed=5, chunk_edges=512))
    rp8, col8 = _assemble(streaming_rmat_sharded(9, 4, 8, seed=5, chunk_edges=512))
    np.testing.assert_array_equal(rp1, rp8)
    np.testing.assert_array_equal(col1, col8)
    # symmetric + no self-loops
    n = 1 << 9
    u = np.repeat(np.arange(n), np.diff(rp1))
    assert (u != col1).all()
    fwd = set(zip(u.tolist(), col1.tolist()))
    assert all((v, uu) in fwd for uu, v in fwd)


def test_streaming_rgg_shard_invariant_and_matches_generator():
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.io.dist_io import streaming_rgg2d_sharded

    n, radius, seed = 600, 0.06, 11
    rp1, col1 = _assemble(streaming_rgg2d_sharded(n, radius, 1, seed=seed))
    rp6, col6 = _assemble(streaming_rgg2d_sharded(n, radius, 6, seed=seed))
    np.testing.assert_array_equal(rp1, rp6)
    np.testing.assert_array_equal(col1, col6)
    # same undirected edge set as the monolithic generator at equal params
    g = generators.rgg2d_graph(n, radius=radius, seed=seed)
    u1 = np.repeat(np.arange(n), np.diff(rp1))
    ug = np.repeat(np.arange(n), np.diff(np.asarray(g.row_ptr)))
    ours = set(zip(u1.tolist(), col1.tolist()))
    theirs = set(zip(ug.tolist(), np.asarray(g.col_idx).tolist()))
    assert ours == theirs


def test_parhip_chunked_empty_trailing_shard(tmp_path):
    """Ceil-division shard ranges can leave a trailing shard empty; its
    chunk must be all-zero (regression: the global-xadj slice fallback
    double-counted m during assembly)."""
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.io.dist_io import read_parhip_chunked, read_parhip_sharded
    from kaminpar_tpu.io.parhip import read_parhip, write_parhip

    g = generators.cycle_graph(4)
    path = str(tmp_path / "tiny.parhip")
    write_parhip(g, path)
    chunks = list(read_parhip_chunked(path, 3))  # n_loc=2 -> shard 2 empty
    assert chunks[-1][1] == (4, 4)
    assert chunks[-1][2].row_ptr.tolist() == [0]
    full = read_parhip(path)
    assembled = read_parhip_sharded(path, 3)
    np.testing.assert_array_equal(np.asarray(full.row_ptr), np.asarray(assembled.row_ptr))
    np.testing.assert_array_equal(np.asarray(full.col_idx), np.asarray(assembled.col_idx))
