"""IO tests: METIS/ParHIP round-trips + compatibility with the reference's
checked-in sample graphs (read-only; skipped when unavailable)."""

import os

import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.csr import validate
from kaminpar_tpu.io import (
    GraphFileFormat,
    read_graph,
    read_partition,
    write_graph,
    write_partition,
)

REF_MISC = "/root/reference/misc"


def _assert_graph_equal(a, b):
    assert a.n == b.n and a.m == b.m
    np.testing.assert_array_equal(np.asarray(a.row_ptr), np.asarray(b.row_ptr))
    np.testing.assert_array_equal(np.asarray(a.col_idx), np.asarray(b.col_idx))
    np.testing.assert_array_equal(np.asarray(a.node_w), np.asarray(b.node_w))
    np.testing.assert_array_equal(np.asarray(a.edge_w), np.asarray(b.edge_w))


@pytest.mark.parametrize("fmt", ["metis", "parhip"])
@pytest.mark.parametrize("weighted", [False, True])
def test_roundtrip(tmp_path, rng, fmt, weighted):
    edges = rng.integers(0, 50, (120, 2))
    kw = {}
    if weighted:
        kw = dict(
            edge_weights=rng.integers(1, 9, 120),
            node_weights=rng.integers(1, 5, 50),
        )
    g = generators.from_edge_list(50, edges, **kw)
    path = str(tmp_path / f"g.{fmt}")
    write_graph(g, path, fmt)
    h = read_graph(path, fmt)
    _assert_graph_equal(g, h)


def test_format_autodetect(tmp_path, rng):
    g = generators.grid2d_graph(5, 5)
    p_metis = str(tmp_path / "a.graph")
    p_parhip = str(tmp_path / "a.bin")
    write_graph(g, p_metis, "metis")
    write_graph(g, p_parhip, "parhip")
    _assert_graph_equal(read_graph(p_metis), g)
    _assert_graph_equal(read_graph(p_parhip), g)


def test_metis_degree_zero_and_comments(tmp_path):
    path = str(tmp_path / "z.metis")
    with open(path, "w") as f:
        f.write("% a comment\n3 1\n2\n1\n\n")  # node 3 isolated, blank line
    g = read_graph(path, "metis")
    assert g.n == 3 and g.m == 2
    assert int(np.asarray(g.row_ptr)[-1]) == 2
    validate(g)


def test_partition_roundtrip(tmp_path, rng):
    part = rng.integers(0, 8, 100)
    path = str(tmp_path / "p.part")
    write_partition(path, part)
    np.testing.assert_array_equal(read_partition(path), part)


@pytest.mark.skipif(
    not os.path.exists(f"{REF_MISC}/rgg2d.metis"), reason="reference not mounted"
)
def test_reference_rgg2d_metis():
    g = read_graph(f"{REF_MISC}/rgg2d.metis", "metis")
    assert g.n == 1024 and g.m == 2 * 4113
    validate(g)


@pytest.mark.skipif(
    not os.path.exists(f"{REF_MISC}/rgg2d-32bit.parhip"), reason="reference not mounted"
)
def test_reference_rgg2d_parhip_matches_metis():
    gm = read_graph(f"{REF_MISC}/rgg2d.metis", "metis")
    for variant in ("rgg2d-32bit.parhip", "rgg2d-64bit.parhip"):
        gp = read_graph(f"{REF_MISC}/{variant}", "parhip")
        _assert_graph_equal(gm, gp)


def test_native_parser_matches_numpy(tmp_path, rng):
    """The C++ mmap tokenizer (io/_native/metis_native.cpp, the reference's
    metis_parser.cc analog) must agree exactly with the NumPy parser on
    weighted/unweighted graphs with comments and degree-0 nodes."""
    import kaminpar_tpu.io.native as nv
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.io import write_metis
    from kaminpar_tpu.io.metis import read_metis

    if not nv.native_available():
        pytest.skip("native toolchain unavailable")

    g = rmat_graph(8, 6, seed=4)
    # make it weighted both ways
    import numpy as _np

    nw = rng.integers(1, 9, g.n)
    # symmetric edge weights: hash of the unordered pair
    u = _np.asarray(g.edge_u)
    v = _np.asarray(g.col_idx)
    ew = 1 + (_np.minimum(u, v) * 31 + _np.maximum(u, v)) % 7
    from kaminpar_tpu.graph.csr import CSRGraph

    gw = CSRGraph(_np.asarray(g.row_ptr), v, nw, ew)
    path = tmp_path / "w.metis"
    write_metis(gw, str(path))
    # sprinkle a comment line after the header
    lines = path.read_text().split("\n")
    lines.insert(1, "% a comment")
    path.write_text("\n".join(lines))

    g_nat = read_metis(str(path))
    # Force the NumPy path: _load() short-circuits on a loaded _lib, so the
    # flag alone is not enough — the lib handle must be cleared too.
    saved_lib = nv._lib
    nv._lib, nv._lib_failed = None, True
    try:
        g_np = read_metis(str(path))
    finally:
        nv._lib, nv._lib_failed = saved_lib, False
    for attr in ("row_ptr", "col_idx", "node_w", "edge_w"):
        np.testing.assert_array_equal(
            np.asarray(getattr(g_nat, attr)), np.asarray(getattr(g_np, attr)),
            err_msg=attr,
        )


def test_native_parser_rejects_malformed(tmp_path):
    import kaminpar_tpu.io.native as nv

    if not nv.native_available():
        pytest.skip("native toolchain unavailable")
    bad = tmp_path / "bad.metis"
    bad.write_text("2 1\n2 x\n1\n")
    with pytest.raises(ValueError, match="non-negative"):
        nv.parse_metis_native(str(bad))
    wrong_count = tmp_path / "count.metis"
    wrong_count.write_text("2 2\n2\n1\n")
    with pytest.raises(ValueError, match="edge count"):
        nv.parse_metis_native(str(wrong_count))
    dangling = tmp_path / "dangling.metis"
    dangling.write_text("2 1 1\n2\n1 1\n")  # node 0 lists a neighbor, no weight
    with pytest.raises(ValueError, match="dangling"):
        nv.parse_metis_native(str(dangling))


def test_compressed_binary_roundtrip(tmp_path):
    """Compressed-graph binary (reference: graph_compression_binary.cc):
    write compressed, read back, decompress to the identical CSR; the
    facade partitions the loaded compressed graph directly."""
    from kaminpar_tpu.graph.compressed import compress
    from kaminpar_tpu.io import read_graph, write_graph

    g = generators.rgg2d_graph(512, radius=0.06, seed=3)
    path = str(tmp_path / "g.npz")
    write_graph(g, path, "compressed")
    cg = read_graph(path)  # auto-detected by extension
    from kaminpar_tpu.graph.compressed import CompressedGraph

    assert isinstance(cg, CompressedGraph)
    assert cg.compression_ratio() == compress(g).compression_ratio()
    h = cg.decompress()
    _assert_graph_equal(g, h)

    from kaminpar_tpu.graph import metrics
    from kaminpar_tpu.kaminpar import KaMinPar

    s = KaMinPar("default")
    s.set_graph(cg)
    part = s.compute_partition(4)
    assert metrics.is_feasible(g, part, 4, s.ctx.partition.max_block_weights)


def test_native_parser_hardening(tmp_path):
    """Parser-divergence and hardening cases found in review: one-token
    headers, huge header claims, oversized tokens, missing files must all
    behave identically to the NumPy path."""
    import kaminpar_tpu.io.native as nv

    if not nv.native_available():
        pytest.skip("native toolchain unavailable")
    one_token_header = tmp_path / "h1.metis"
    one_token_header.write_text("2\n1\n2\n1\n")
    with pytest.raises(ValueError):
        nv.parse_metis_native(str(one_token_header))
    huge = tmp_path / "huge.metis"
    huge.write_text("1 2305843009213693952\n\n")
    with pytest.raises(ValueError):
        nv.parse_metis_native(str(huge))
    big_tok = tmp_path / "big.metis"
    big_tok.write_text("2 1 1\n2 18446744073709551617\n1 1\n")
    with pytest.raises(ValueError, match="too large"):
        nv.parse_metis_native(str(big_tok))
    with pytest.raises(FileNotFoundError):
        nv.parse_metis_native(str(tmp_path / "missing.metis"))


def test_write_graph_npz_default_roundtrips(tmp_path):
    from kaminpar_tpu.io import read_graph, write_graph

    g = generators.grid2d_graph(6, 6)
    path = str(tmp_path / "g.npz")
    write_graph(g, path)  # extension decides: compressed container
    h = read_graph(path).decompress()
    _assert_graph_equal(g, h)
