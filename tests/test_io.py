"""IO tests: METIS/ParHIP round-trips + compatibility with the reference's
checked-in sample graphs (read-only; skipped when unavailable)."""

import os

import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.csr import validate
from kaminpar_tpu.io import (
    GraphFileFormat,
    read_graph,
    read_partition,
    write_graph,
    write_partition,
)

REF_MISC = "/root/reference/misc"


def _assert_graph_equal(a, b):
    assert a.n == b.n and a.m == b.m
    np.testing.assert_array_equal(np.asarray(a.row_ptr), np.asarray(b.row_ptr))
    np.testing.assert_array_equal(np.asarray(a.col_idx), np.asarray(b.col_idx))
    np.testing.assert_array_equal(np.asarray(a.node_w), np.asarray(b.node_w))
    np.testing.assert_array_equal(np.asarray(a.edge_w), np.asarray(b.edge_w))


@pytest.mark.parametrize("fmt", ["metis", "parhip"])
@pytest.mark.parametrize("weighted", [False, True])
def test_roundtrip(tmp_path, rng, fmt, weighted):
    edges = rng.integers(0, 50, (120, 2))
    kw = {}
    if weighted:
        kw = dict(
            edge_weights=rng.integers(1, 9, 120),
            node_weights=rng.integers(1, 5, 50),
        )
    g = generators.from_edge_list(50, edges, **kw)
    path = str(tmp_path / f"g.{fmt}")
    write_graph(g, path, fmt)
    h = read_graph(path, fmt)
    _assert_graph_equal(g, h)


def test_format_autodetect(tmp_path, rng):
    g = generators.grid2d_graph(5, 5)
    p_metis = str(tmp_path / "a.graph")
    p_parhip = str(tmp_path / "a.bin")
    write_graph(g, p_metis, "metis")
    write_graph(g, p_parhip, "parhip")
    _assert_graph_equal(read_graph(p_metis), g)
    _assert_graph_equal(read_graph(p_parhip), g)


def test_metis_degree_zero_and_comments(tmp_path):
    path = str(tmp_path / "z.metis")
    with open(path, "w") as f:
        f.write("% a comment\n3 1\n2\n1\n\n")  # node 3 isolated, blank line
    g = read_graph(path, "metis")
    assert g.n == 3 and g.m == 2
    assert int(np.asarray(g.row_ptr)[-1]) == 2
    validate(g)


def test_partition_roundtrip(tmp_path, rng):
    part = rng.integers(0, 8, 100)
    path = str(tmp_path / "p.part")
    write_partition(path, part)
    np.testing.assert_array_equal(read_partition(path), part)


@pytest.mark.skipif(
    not os.path.exists(f"{REF_MISC}/rgg2d.metis"), reason="reference not mounted"
)
def test_reference_rgg2d_metis():
    g = read_graph(f"{REF_MISC}/rgg2d.metis", "metis")
    assert g.n == 1024 and g.m == 2 * 4113
    validate(g)


@pytest.mark.skipif(
    not os.path.exists(f"{REF_MISC}/rgg2d-32bit.parhip"), reason="reference not mounted"
)
def test_reference_rgg2d_parhip_matches_metis():
    gm = read_graph(f"{REF_MISC}/rgg2d.metis", "metis")
    for variant in ("rgg2d-32bit.parhip", "rgg2d-64bit.parhip"):
        gp = read_graph(f"{REF_MISC}/{variant}", "parhip")
        _assert_graph_equal(gm, gp)
