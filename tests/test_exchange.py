"""Sparse ghost exchange + owner-routed primitives (8-device CPU mesh).

Verifies the static-routing exchange layer (kaminpar_tpu/dist/exchange.py)
against naive host computations — the TPU analog of the reference's
sparse-alltoall tests (tests/mpi/sparse_alltoall_test.cc)."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from kaminpar_tpu.dist import distribute_graph
from kaminpar_tpu.dist.exchange import (
    AXIS,
    ghost_exchange,
    owner_aggregate,
    owner_query,
)
from kaminpar_tpu.dist.lp import shard_arrays
from kaminpar_tpu.graph import generators


def _mesh(num=8):
    devs = jax.devices()
    if len(devs) < num:
        pytest.skip(f"need {num} devices, have {len(devs)}")
    return Mesh(np.array(devs[:num]), ("nodes",))


def test_ghost_exchange_delivers_owner_values():
    mesh = _mesh()
    g = generators.rmat_graph(9, 8, seed=2)
    dg = distribute_graph(g, mesh.size)
    # distinctive per-node values: value[global id] = 3*id + 7
    vals = (3 * np.arange(dg.N) + 7).astype(np.int32)
    vals_dev, dgs = shard_arrays(mesh, dg, jnp.asarray(vals))

    @jax.jit
    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS)), out_specs=P(AXIS),
    )
    def run(v, sidx, rmap):
        return ghost_exchange(v, sidx, rmap, fill=jnp.int32(-1))

    ghosts = np.asarray(run(vals_dev, dgs.send_idx, dgs.recv_map)).reshape(
        dg.num_shards, dg.g_loc
    )
    for s in range(dg.num_shards):
        gg = dg.ghost_global[s]
        np.testing.assert_array_equal(ghosts[s, : len(gg)], 3 * gg + 7)
        assert np.all(ghosts[s, len(gg):] == -1)


def test_col_loc_roundtrip_matches_global_edges():
    """Local-slot edge targets + ghost tables reproduce the original edges."""
    g = generators.grid2d_graph(12, 12)
    dg = distribute_graph(g, 4)
    cl = np.asarray(dg.col_loc).reshape(4, dg.m_loc)
    eu = np.asarray(dg.edge_u).reshape(4, dg.m_loc)
    w = np.asarray(dg.edge_w).reshape(4, dg.m_loc)
    edges = set()
    for s in range(4):
        real = w[s] > 0
        gg = dg.ghost_global[s]
        for u_l, slot in zip(eu[s][real], cl[s][real]):
            u = u_l + s * dg.n_loc
            v = slot + s * dg.n_loc if slot < dg.n_loc else gg[slot - dg.n_loc]
            edges.add((int(u), int(v)))
    rp = np.asarray(g.row_ptr)
    col = np.asarray(g.col_idx)
    want = {
        (u, int(col[e]))
        for u in range(g.n)
        for e in range(int(rp[u]), int(rp[u + 1]))
    }
    assert edges == want


@pytest.mark.parametrize("cap", [8, 64])
def test_owner_query_fetches_table_entries(cap):
    mesh = _mesh()
    Pn = mesh.size
    n_loc = 16
    N = Pn * n_loc
    table = np.arange(N, dtype=np.int32) * 5 + 1  # table[i] = 5i+1
    rng = np.random.default_rng(3)
    keys = rng.integers(0, N, size=N).astype(np.int32)
    drop = rng.random(N) < 0.2

    @partial(jax.jit, static_argnames=("cap_",))
    def run(t, k, d, *, cap_):
        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(AXIS), P(AXIS), P(AXIS)), out_specs=(P(AXIS), P()),
        )
        def body(t_loc, k_loc, d_loc):
            v, ovf = owner_query(
                k_loc, d_loc, t_loc, n_loc, cap_, fill=jnp.int32(-1)
            )
            return v, jax.lax.psum(ovf, AXIS)

        return body(t, k, d)

    vals, ovf = run(
        jnp.asarray(table), jnp.asarray(keys), jnp.asarray(drop), cap_=cap
    )
    vals = np.asarray(vals)
    if int(ovf) == 0:
        np.testing.assert_array_equal(vals[~drop], table[keys[~drop]])
    assert np.all(vals[drop] == -1)
    if cap == 64:  # cap ≥ per-shard query count: never overflows
        assert int(ovf) == 0


def test_owner_aggregate_matches_bincount():
    mesh = _mesh()
    Pn = mesh.size
    n_loc = 32
    N = Pn * n_loc
    rng = np.random.default_rng(7)
    keys = rng.integers(0, N, size=N).astype(np.int32)
    vals = rng.integers(1, 10, size=N).astype(np.int32)
    drop = rng.random(N) < 0.3

    @jax.jit
    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS)), out_specs=(P(AXIS), P()),
    )
    def run(k, v, d):
        out, ovf = owner_aggregate(k, v, d, n_loc, n_loc)
        return out, jax.lax.psum(ovf, AXIS)

    out, ovf = run(jnp.asarray(keys), jnp.asarray(vals), jnp.asarray(drop))
    assert int(ovf) == 0
    want = np.bincount(keys[~drop], weights=vals[~drop], minlength=N)
    np.testing.assert_array_equal(np.asarray(out), want.astype(np.int32))


def test_owner_query_overflow_reported():
    """Skewed key→owner distribution with a tiny cap must report overflow,
    never silently drop answers as successes."""
    mesh = _mesh()
    Pn = mesh.size
    n_loc = 32
    keys = np.zeros(Pn * n_loc, dtype=np.int32)  # every query hits owner 0
    drop = np.zeros(Pn * n_loc, dtype=bool)
    table = np.arange(Pn * n_loc, dtype=np.int32)

    @jax.jit
    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS)), out_specs=(P(AXIS), P()),
    )
    def run(t, k, d):
        v, ovf = owner_query(k, d, t, n_loc, 8, fill=jnp.int32(-1))
        return v, jax.lax.psum(ovf, AXIS)

    vals, ovf = run(jnp.asarray(table), jnp.asarray(keys), jnp.asarray(drop))
    assert int(ovf) > 0
    # answered slots are correct, overflowed slots return the fill value
    vals = np.asarray(vals)
    assert set(np.unique(vals)) <= {-1, 0}
