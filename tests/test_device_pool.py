"""Lane-vmapped device initial-bipartitioning pool (round 9, ISSUE 4).

Covers the acceptance criteria: seed-stable determinism on both backends,
lane-stream identity under vmap/scan/loop execution, host-pool oracle
parity (device best cut <= host-pool median over a seed sweep on
rmat/grid/star), the one-readback-per-bisection budget in-pipeline, and the
contraction-level edge cases (n <= 2, all-lanes-infeasible fallback).
"""

import dataclasses
from functools import partial

import jax
import numpy as np
import pytest

from kaminpar_tpu.context import Context, InitialPartitioningContext
from kaminpar_tpu.graph import generators
from kaminpar_tpu.initial.bipartitioner import (
    _block_weights,
    _cut,
    multilevel_bipartition,
    pool_bipartition,
    resolve_ip_backend,
)
from kaminpar_tpu.ops import bipartition as bip
from kaminpar_tpu.partitioning.kway import graph_to_host
from kaminpar_tpu.utils import sync_stats
from kaminpar_tpu.utils.rng import lane_key, lane_keys

IPC = InitialPartitioningContext()
DEVICE_IPC = dataclasses.replace(IPC, ip_backend="device")


def _budgets(host, frac=0.55):
    W = host.total_node_weight
    return np.array([int(frac * W), int(frac * W)], dtype=np.int64)


def _device_pool(host, mw, seed, final_k=2, ipc=IPC):
    return bip.pool_bipartition_device(
        host.row_ptr, host.col_idx, host.node_w, host.edge_w, mw, seed, ipc,
        final_k,
    )


def test_resolve_ip_backend_modes(monkeypatch):
    assert resolve_ip_backend(DEVICE_IPC) == "device"
    assert resolve_ip_backend(dataclasses.replace(IPC, ip_backend="host")) == "host"
    # "auto" on the CPU test backend = host.
    assert resolve_ip_backend(IPC) == "host"
    with pytest.raises(ValueError):
        resolve_ip_backend(dataclasses.replace(IPC, ip_backend="gpu"))
    # The env kill switch overrides the context knob (including bad ones).
    monkeypatch.setenv("KAMINPAR_TPU_IP_BACKEND", "device")
    assert resolve_ip_backend(IPC) == "device"
    assert resolve_ip_backend(dataclasses.replace(IPC, ip_backend="gpu")) == "device"


def test_device_pool_deterministic_and_feasible():
    host = graph_to_host(generators.grid2d_graph(12, 12))
    mw = _budgets(host)
    l1, s1 = _device_pool(host, mw, seed=5)
    l2, s2 = _device_pool(host, mw, seed=5)
    np.testing.assert_array_equal(l1, l2)
    assert s1 == s2
    assert s1["feasible"]
    bw = _block_weights(host, l1)
    assert (bw <= mw).all()
    assert s1["cut"] == _cut(host, l1)
    assert tuple(bw) == s1["block_weights"]
    # a different seed draws different lane streams
    l3, _ = _device_pool(host, mw, seed=6)
    assert not np.array_equal(l1, l3)


def test_lane_results_vmap_scan_loop_identical():
    """The single-lane kernel produces bit-identical partitions whether the
    lane stack executes as vmap, scan, or a Python loop — the ROADMAP's
    lane-stacking identity, on the real kernel rather than raw draws."""
    from kaminpar_tpu.graph.csr import from_numpy_csr

    host = graph_to_host(generators.rmat_graph(6, 8, seed=3))
    g = from_numpy_csr(host.row_ptr, host.col_idx, host.node_w, host.edge_w)
    pv = g.padded()
    idt = pv.node_w.dtype
    W = int(np.asarray(host.node_w).sum())
    lane = jax.jit(partial(
        bip._lane_bipartition,
        edge_u=pv.edge_u, col_idx=pv.col_idx, edge_w=pv.edge_w,
        node_w=pv.node_w, n=jax.numpy.asarray(pv.n, dtype=idt),
        target=jax.numpy.asarray(W // 2, dtype=idt),
        max_w0=jax.numpy.asarray(int(0.55 * W), dtype=idt),
        max_w1=jax.numpy.asarray(int(0.55 * W), dtype=idt),
        method="ggg", grow_trips=16, fm_rounds=8,
    ))
    R = 4
    keys = lane_keys(11, R)
    via_vmap = np.asarray(jax.vmap(lane)(keys))
    _, via_scan = jax.lax.scan(lambda c, k: (c, lane(k)), None, keys)
    via_loop = np.stack([np.asarray(lane(lane_key(11, i))) for i in range(R)])
    np.testing.assert_array_equal(via_vmap, np.asarray(via_scan))
    np.testing.assert_array_equal(via_vmap, via_loop)
    # lane-count invariance on the kernel: the first R lanes of a bigger
    # stack are the same partitions
    bigger = np.asarray(jax.vmap(lane)(lane_keys(11, 2 * R)))
    np.testing.assert_array_equal(via_vmap, bigger[:R])


@pytest.mark.parametrize("make", [
    lambda: generators.rmat_graph(7, 8, seed=1),
    lambda: generators.grid2d_graph(12, 12),
    lambda: generators.star_graph(48),
], ids=["rmat", "grid", "star"])
def test_device_pool_beats_host_pool_median(make):
    """Oracle parity (acceptance): device-pool best cut <= host-pool median
    cut over a 10-seed sweep."""
    host = graph_to_host(make())
    mw = _budgets(host)
    host_cuts = sorted(
        _cut(host, pool_bipartition(host, mw, np.random.default_rng(s), IPC))
        for s in range(10)
    )
    dev_best = min(
        s["cut"] for s in
        (_device_pool(host, mw, seed=s)[1] for s in range(10))
    )
    assert dev_best <= host_cuts[5], (dev_best, host_cuts)


def test_multilevel_bipartition_device_backend_routes_and_falls_back():
    host = graph_to_host(generators.grid2d_graph(8, 8))
    mw = _budgets(host)
    part = multilevel_bipartition(
        host, mw, np.random.default_rng(0), DEVICE_IPC
    )
    assert set(np.unique(part)) <= {0, 1}
    assert (_block_weights(host, part) <= mw).all()
    # n <= 2 contraction-level edge case: falls through to the host pool
    # (no device dispatch), stays deterministic and feasible.
    for n in (1, 2):
        tiny = graph_to_host(generators.path_graph(n))
        mw2 = np.array([1, 1], dtype=np.int64)
        p1 = multilevel_bipartition(tiny, mw2, np.random.default_rng(0), DEVICE_IPC)
        p2 = multilevel_bipartition(tiny, mw2, np.random.default_rng(0), DEVICE_IPC)
        np.testing.assert_array_equal(p1, p2)
        assert (_block_weights(tiny, p1) <= mw2).all()


def test_method_lane_keys_stable_across_bucket_growth():
    """Each method keys its lanes from a disjoint counter window: growing
    the shared lane bucket (more repetitions) must not shift any existing
    lane's stream in any method."""
    small = jax.random.key_data(
        bip.method_lane_keys(5, (("bfs", 4), ("ggg", 4), ("random", 4)))
    )
    big = jax.random.key_data(
        bip.method_lane_keys(5, (("bfs", 8), ("ggg", 8), ("random", 8)))
    )
    small_np, big_np = np.asarray(small), np.asarray(big)
    for m in range(3):
        np.testing.assert_array_equal(
            small_np[m * 4 : (m + 1) * 4], big_np[m * 8 : m * 8 + 4]
        )


def test_rebalance_skips_unmovable_heavy_node():
    """A max-gain candidate heavier than the receiver's room must not block
    lighter candidates behind it from repairing the overload (the host
    pool's queues skip unmovable nodes and continue)."""
    import jax.numpy as jnp

    from kaminpar_tpu.graph.csr import from_numpy_csr

    # Path 0-1-2 with node 0 heavy; block 0 = {0, 1} is overweight by 1 and
    # only moving node 1 (not the heavy node 0) can repair it.
    row_ptr = np.array([0, 1, 3, 4], dtype=np.int64)
    col = np.array([1, 0, 2, 1], dtype=np.int64)
    nw = np.array([100, 1, 1], dtype=np.int64)
    g = from_numpy_csr(row_ptr, col, nw, np.ones(4, dtype=np.int64))
    pv = g.padded()
    idt = pv.node_w.dtype
    in0 = jnp.zeros(pv.n_pad, dtype=bool).at[0].set(True).at[1].set(True)
    out = bip._rebalance_side(
        lane_key(0, 0), in0, pv.edge_u, pv.col_idx, pv.edge_w, pv.node_w,
        jnp.asarray(100, dtype=idt), jnp.asarray(50, dtype=idt), side=0,
    )
    out = np.asarray(out)
    assert out[0] and not out[1]  # heavy node stayed, light node moved
    bw0 = int(np.sum(np.where(out[: 3], nw, 0)))
    assert bw0 == 100  # overload repaired


def test_device_pool_tight_budgets_rebalance():
    """Near-perfect balance budgets: grown lanes overshoot and the forced
    balance pass must repair them — every lane, not just the winner."""
    host = graph_to_host(generators.grid2d_graph(8, 8))
    W = host.total_node_weight
    mw = np.array([W // 2 + 1, W // 2 + 1], dtype=np.int64)
    labels, stats = _device_pool(host, mw, seed=0)
    assert stats["feasible"]
    assert (_block_weights(host, labels) <= mw).all()
    assert stats["num_feasible"] == stats["lanes"]


def test_device_pool_all_lanes_infeasible_fallback():
    """Budgets no bipartition can satisfy: the pool still returns a valid
    labeling and reports infeasibility (minimum-overload lane) instead of
    crashing — the caller's refinement/balancing layers take it from there."""
    host = graph_to_host(generators.star_graph(16))
    W = host.total_node_weight
    mw = np.array([W // 3, W // 3], dtype=np.int64)  # sum < W: unsatisfiable
    labels, stats = _device_pool(host, mw, seed=1)
    assert not stats["feasible"]
    assert stats["num_feasible"] == 0
    assert set(np.unique(labels)) <= {0, 1}
    assert len(labels) == host.n


def test_device_pool_rejects_unsafe_weights():
    host = graph_to_host(generators.path_graph(4))
    big = host._replace(node_w=np.full(4, 2**30, dtype=np.int64))
    with pytest.raises(ValueError):
        _device_pool(big, np.array([2**33, 2**33], dtype=np.int64), seed=0)


def test_deep_pipeline_device_backend_deterministic_and_budgeted():
    """End-to-end acceptance: ip_backend=device through the deep pipeline is
    seed-deterministic, feasible, and holds the <= 1-readback-per-bisection
    budget (asserted in-pipeline via enable_budget_checks)."""
    from kaminpar_tpu.graph.metrics import edge_cut, is_feasible
    from kaminpar_tpu.kaminpar import KaMinPar

    g = generators.grid2d_graph(24, 24)
    sync_stats.enable_budget_checks(True)
    try:
        parts = []
        for _ in range(2):
            ctx = Context()
            ctx.initial_partitioning.ip_backend = "device"
            solver = KaMinPar(ctx=ctx)
            solver.set_graph(g)
            parts.append(solver.compute_partition(4, 0.03))
        caps = ctx.partition.max_block_weights
    finally:
        sync_stats.enable_budget_checks(False)
    np.testing.assert_array_equal(parts[0], parts[1])
    assert bool(is_feasible(g, parts[0], 4, caps))
    assert int(edge_cut(g, parts[0])) > 0


def test_engine_warmup_reports_ip_pool_cells():
    """PartitionEngine warmup precompiles the pool per (bucket, lane-count,
    k=2) cell on the device backend and reports each cell's compile cost."""
    from kaminpar_tpu.serve.engine import PartitionEngine

    ctx = Context()
    ctx.initial_partitioning.ip_backend = "device"
    engine = PartitionEngine(ctx, warm_ladder=(64,), warm_ks=(4,))
    # warmup's pool pass, without the full ladder; the rung generator
    # mirrors _warmup's (scale 6 for the 64-rung, same edge factor/seed).
    from kaminpar_tpu.graph.generators import rmat_graph

    engine._warm_ip_pool(lambda n: (6, rmat_graph(
        6, edge_factor=engine.serve.warm_edge_factor, seed=1)))
    rows = [r for r in engine.warmup_report if r.get("kind") == "ip_pool"]
    assert rows, engine.warmup_report
    for row in rows:
        assert row["k"] == 2
        assert row["lanes"] > 0
        assert row["wall_s"] >= 0
        assert row["n_bucket"] > 64
    # host backend: nothing to compile, no rows
    ctx2 = Context()
    ctx2.initial_partitioning.ip_backend = "host"
    engine2 = PartitionEngine(ctx2, warm_ladder=(64,), warm_ks=(4,))
    engine2._warm_ip_pool(lambda n: (6, rmat_graph(
        6, edge_factor=engine2.serve.warm_edge_factor, seed=1)))
    assert not [r for r in engine2.warmup_report if r.get("kind") == "ip_pool"]
