"""Initial partitioning tests (reference: initial bipartitioner pool + FM,
tests exercised through shm endtoend tests; here directly)."""

import numpy as np
import pytest

from kaminpar_tpu.context import InitialPartitioningContext
from kaminpar_tpu.graph import generators
from kaminpar_tpu.initial.bipartitioner import (
    _bfs_bipartition,
    _fm_refine_2way,
    _ggg_bipartition,
    _random_bipartition,
    extract_subgraph,
    pool_bipartition,
    recursive_bipartition,
)
from kaminpar_tpu.partitioning.kway import graph_to_host


@pytest.fixture
def grid_host():
    return graph_to_host(generators.grid2d_graph(8, 8))


def _balanced_budgets(host, parts=2, eps=0.1):
    per = int(np.ceil(host.total_node_weight / parts) * (1 + eps)) + 1
    return np.full(parts, per, dtype=np.int64)


@pytest.mark.parametrize("fn", [_bfs_bipartition, _ggg_bipartition, _random_bipartition])
def test_flat_bipartitioners_feasible(grid_host, rng, fn):
    mw = _balanced_budgets(grid_host)
    part = fn(grid_host, mw, rng)
    assert set(np.unique(part)) <= {0, 1}
    bw = np.bincount(part, weights=grid_host.node_w, minlength=2)
    assert bw[0] <= mw[0]


def test_fm_improves_cut(grid_host, rng):
    from kaminpar_tpu.initial.bipartitioner import _cut

    mw = _balanced_budgets(grid_host)
    part = _random_bipartition(grid_host, mw, rng)
    before = _cut(grid_host, part)
    refined = _fm_refine_2way(grid_host, part, mw, rng)
    after = _cut(grid_host, refined)
    assert after <= before
    bw = np.bincount(refined, weights=grid_host.node_w, minlength=2)
    assert (bw <= mw).all()


def test_pool_bipartition_quality(grid_host, rng):
    from kaminpar_tpu.initial.bipartitioner import _cut

    mw = _balanced_budgets(grid_host)
    part = pool_bipartition(grid_host, mw, rng, InitialPartitioningContext())
    # an 8x8 grid has a bisection of width 8; pool+FM should get close
    assert _cut(grid_host, part) <= 16


def test_extract_subgraph(grid_host):
    part = np.zeros(64, dtype=np.int32)
    part[32:] = 1
    sub, nodes = extract_subgraph(grid_host, part, 0)
    assert sub.n == 32
    assert (nodes == np.arange(32)).all()
    # induced 4x8 grid: edges = 2*(3*8 + 4*7) = 104
    assert len(sub.col_idx) == 104


def test_recursive_bipartition_k4(grid_host, rng):
    mw = _balanced_budgets(grid_host, 4)
    part = recursive_bipartition(grid_host, 4, mw, rng, InitialPartitioningContext())
    assert set(np.unique(part)) == {0, 1, 2, 3}
    bw = np.bincount(part, weights=grid_host.node_w, minlength=4)
    assert (bw <= mw).all()


def test_recursive_bipartition_odd_k(grid_host, rng):
    mw = np.full(3, 30, dtype=np.int64)
    part = recursive_bipartition(grid_host, 3, mw, rng, InitialPartitioningContext())
    assert set(np.unique(part)) == {0, 1, 2}
    bw = np.bincount(part, weights=grid_host.node_w, minlength=3)
    assert (bw <= mw).all()


def test_graph_to_host_packed_single_pull():
    """graph_to_host materializes all four CSR arrays through ONE counted
    blocking transfer (round 9: the initial-partitioning phase budget counts
    pulls, so the bulk graph pull must cost exactly one)."""
    from kaminpar_tpu.utils import sync_stats

    g = generators.rmat_graph(6, 4, seed=2)
    pre = sync_stats.phase_count("ip_pull_test")
    with sync_stats.scoped("ip_pull_test"):
        host = graph_to_host(g)
    assert sync_stats.phase_count("ip_pull_test") - pre == 1
    np.testing.assert_array_equal(host.row_ptr, np.asarray(g.row_ptr))
    np.testing.assert_array_equal(host.col_idx, np.asarray(g.col_idx))
    np.testing.assert_array_equal(host.node_w, np.asarray(g.node_w))
    np.testing.assert_array_equal(host.edge_w, np.asarray(g.edge_w))


def _to_host(g):
    from kaminpar_tpu.initial.bipartitioner import HostCSR

    return HostCSR(
        np.asarray(g.row_ptr), np.asarray(g.col_idx),
        np.asarray(g.node_w), np.asarray(g.edge_w),
    )


def test_multilevel_bipartition_beats_flat_pool_on_structured():
    """VERDICT r1 missing #8 done-criterion: the sequential mini-multilevel
    must measurably improve coarsest-graph bipartition cuts vs the flat
    pool on non-trivial graphs (reference:
    initial_multilevel_bipartitioner.cc:67-74).  Measured behavior: ML wins
    clearly on geometric/mesh-like graphs (the hierarchy preserves their
    structure); on expanders (RMAT) coarsening creates heavy nodes and flat
    pool+FM wins — covered by the best-of guard tested below."""
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.initial.bipartitioner import (
        _cut,
        multilevel_bipartition,
        pool_bipartition,
    )

    wins = 0
    total_flat = 0
    total_ml = 0
    for seed in range(3):
        host = _to_host(generators.rgg2d_graph(4096, seed=seed))
        W = host.total_node_weight
        mw = np.array([int(0.55 * W), int(0.55 * W)], dtype=np.int64)
        cut_flat = _cut(host, pool_bipartition(host, mw, np.random.default_rng(seed)))
        cut_ml = _cut(host, multilevel_bipartition(host, mw, np.random.default_rng(seed)))
        total_flat += cut_flat
        total_ml += cut_ml
        if cut_ml <= cut_flat:
            wins += 1
    assert wins >= 2, f"multilevel won only {wins}/3"
    assert total_ml < total_flat, (total_ml, total_flat)


def test_multilevel_bipartition_no_regression_on_expander():
    """The best-of flat-pool guard keeps ML ≥ flat quality on expander-like
    graphs where the projected hierarchy partition is a bad FM basin."""
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.initial.bipartitioner import (
        _cut,
        multilevel_bipartition,
        pool_bipartition,
    )

    total_flat = 0
    total_ml = 0
    for seed in range(3):
        host = _to_host(generators.rmat_graph(10, 8, seed=seed))
        W = host.total_node_weight
        mw = np.array([int(0.55 * W), int(0.55 * W)], dtype=np.int64)
        total_flat += _cut(host, pool_bipartition(host, mw, np.random.default_rng(seed)))
        total_ml += _cut(host, multilevel_bipartition(host, mw, np.random.default_rng(seed)))
    # same candidate family via the fallback; allow rng-stream slack
    assert total_ml <= 1.10 * total_flat, (total_ml, total_flat)
