"""kptlint tests (ISSUE 7): per-rule fixtures, suppression, baseline
round-trip, the package-wide self-clean gate, and the mutation gates the
acceptance criteria name (deleting the PR 6 ``_nested_partition``
layout-mode pin, re-introducing an un-pulled ``np.asarray`` in dist/).

Everything here is pure-AST — no jax import, no device work — so this file
adds milliseconds to tier-1.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from kaminpar_tpu.analysis import ALL_RULES, Analyzer, default_config
from kaminpar_tpu.analysis.baseline import Baseline
from kaminpar_tpu.analysis.core import summarize

REPO = Path(__file__).resolve().parent.parent


def analyze(source: str, rel: str = "kaminpar_tpu/dist/_snippet.py"):
    """Findings (non-suppressed) of a snippet placed at ``rel``."""
    analyzer = Analyzer(ALL_RULES, default_config())
    return [
        f for f in analyzer.check_source(textwrap.dedent(source), rel=rel)
        if not f.suppressed
    ]


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# sync-discipline
# ---------------------------------------------------------------------------


def test_sync_rule_fires_on_unpulled_asarray_in_dist():
    findings = analyze(
        """
        import numpy as np

        def leak(graph):
            return np.asarray(graph.node_w)
        """
    )
    assert "sync-discipline" in rules_of(findings)


def test_sync_rule_fires_on_device_get_and_item_and_coercion():
    findings = analyze(
        """
        import jax
        import jax.numpy as jnp

        def leak(x):
            a = jax.device_get(x)
            b = jnp.sum(x).item()
            c = int(jnp.max(x))
            return a, b, c
        """
    )
    assert sum(f.rule == "sync-discipline" for f in findings) == 3


def test_sync_rule_clean_on_pull_and_host_data():
    findings = analyze(
        """
        import numpy as np
        from ..utils import sync_stats

        def fine(graph, budgets: np.ndarray):
            host = sync_stats.pull(graph.node_w, phase="dist_metrics")
            caps = np.asarray(budgets, dtype=np.int64)
            meta = graph.node_w.dtype
            hist = np.asarray([1, 2, 3])
            return host.sum() + caps.sum(), meta, hist
        """
    )
    assert findings == []


def test_sync_rule_tracks_host_assignments():
    findings = analyze(
        """
        import numpy as np
        from ..utils import sync_stats

        def fine(graph):
            lab = sync_stats.pull(graph.partition)
            again = np.asarray(lab)  # host already: no finding
            return again
        """
    )
    assert findings == []


def test_sync_rule_ignores_io_boundary_modules():
    findings = analyze(
        """
        import numpy as np

        def boundary(graph):
            return np.asarray(graph.node_w)
        """,
        rel="kaminpar_tpu/io/_snippet.py",
    )
    assert "sync-discipline" not in rules_of(findings)


def test_sync_rule_suppression_honored():
    findings = analyze(
        """
        import numpy as np

        def fine(graph):
            return np.asarray(graph.node_w)  # kpt: ignore[sync-discipline]
        """
    )
    assert findings == []


# ---------------------------------------------------------------------------
# runtime-isolation
# ---------------------------------------------------------------------------


def test_runtime_rule_fires_without_layout_pin_and_accepts_pin():
    bad = analyze(
        """
        from ..graph.csr import from_numpy_csr

        def build(sub, ctx):
            g = from_numpy_csr(sub.row_ptr, sub.col_idx, sub.node_w, sub.edge_w)
            return g
        """,
        rel="kaminpar_tpu/partitioning/_snippet.py",
    )
    assert "runtime-isolation" in rules_of(bad)
    good = analyze(
        """
        from ..graph.csr import from_numpy_csr

        def build(sub, ctx):
            g = from_numpy_csr(sub.row_ptr, sub.col_idx, sub.node_w, sub.edge_w)
            g._layout_mode = ctx.parallel.device_layout_build
            return g
        """,
        rel="kaminpar_tpu/partitioning/_snippet.py",
    )
    assert "runtime-isolation" not in rules_of(good)


def test_runtime_rule_bans_process_default_mutators():
    findings = analyze(
        """
        from ..graph.csr import set_layout_build_mode
        from ..context import configure_compilation_cache

        def misconfigure(ctx):
            set_layout_build_mode("device")
            configure_compilation_cache(ctx.parallel)
        """,
        rel="kaminpar_tpu/serve/_snippet.py",
    )
    assert sum(f.rule == "runtime-isolation" for f in findings) == 2


def test_runtime_rule_bans_direct_cache_config():
    findings = analyze(
        """
        import jax

        def sneaky():
            jax.config.update("jax_compilation_cache_dir", "/tmp/x")
        """,
        rel="kaminpar_tpu/ops/_snippet.py",
    )
    assert "runtime-isolation" in rules_of(findings)


def test_mutation_gate_deleting_pr6_layout_pin_fails_lint():
    """Acceptance: deleting the PR 6 _nested_partition layout-mode pin must
    make the lint gate fail.  Run the analyzer over the REAL deep.py source
    and over a mutated copy with the pin line removed."""
    deep_src = (REPO / "kaminpar_tpu/partitioning/deep.py").read_text()
    pin = "g._layout_mode = sub_ctx.parallel.device_layout_build"
    assert pin in deep_src, "the PR 6 pin disappeared from deep.py"

    analyzer = Analyzer(ALL_RULES, default_config())
    rel = "kaminpar_tpu/partitioning/deep.py"
    clean = [
        f for f in analyzer.check_source(deep_src, rel=rel,
                                         modname="kaminpar_tpu.partitioning.deep")
        if not f.suppressed and f.rule == "runtime-isolation"
    ]
    assert clean == [], [f.render() for f in clean]

    mutated = "\n".join(
        line for line in deep_src.splitlines() if pin not in line
    )
    broken = [
        f for f in analyzer.check_source(mutated, rel=rel,
                                         modname="kaminpar_tpu.partitioning.deep")
        if not f.suppressed and f.rule == "runtime-isolation"
    ]
    assert broken, "deleting the layout pin must trip runtime-isolation"
    assert any("'g'" in f.message for f in broken)


def test_mutation_gate_unpulled_asarray_in_dist_fails_lint():
    """Acceptance: re-introducing an un-pulled np.asarray in dist/ must make
    the lint gate fail — mutate the real dist/metrics.py back to the
    pre-fix spelling."""
    src = (REPO / "kaminpar_tpu/dist/metrics.py").read_text()
    fixed = ("return sync_stats.pull(bw, phase=\"dist_metrics\", "
             "shards=graph.num_shards)")
    assert fixed in src
    analyzer = Analyzer(ALL_RULES, default_config())
    rel = "kaminpar_tpu/dist/metrics.py"
    clean = [
        f for f in analyzer.check_source(src, rel=rel,
                                         modname="kaminpar_tpu.dist.metrics")
        if not f.suppressed and f.rule == "sync-discipline"
    ]
    assert clean == [], [f.render() for f in clean]
    mutated = src.replace(fixed, "return np.asarray(bw)")
    broken = [
        f for f in analyzer.check_source(mutated, rel=rel,
                                         modname="kaminpar_tpu.dist.metrics")
        if not f.suppressed and f.rule == "sync-discipline"
    ]
    assert broken, "an un-pulled np.asarray in dist/ must trip sync-discipline"


def test_runtime_rule_accepts_attribute_and_annotated_targets():
    """Review fix: `self.g = CSRGraph(...)` / `g: CSRGraph = ...` with a
    matching pin must not be misreported as an un-pinnable inline
    construction."""
    good = analyze(
        """
        from ..graph.csr import CSRGraph, from_numpy_csr

        class Holder:
            def build(self, s, ctx):
                self.g = CSRGraph(s.a, s.b)
                self.g._layout_mode = ctx.parallel.device_layout_build
                h: CSRGraph = from_numpy_csr(s.a, s.b, s.c, s.d)
                h._layout_mode = ctx.parallel.device_layout_build
                return h
        """,
        rel="kaminpar_tpu/serve/_snippet.py",
    )
    assert "runtime-isolation" not in rules_of(good)
    bad = analyze(
        """
        from ..graph.csr import CSRGraph

        class Holder:
            def build(self, s):
                self.g = CSRGraph(s.a, s.b)
        """,
        rel="kaminpar_tpu/serve/_snippet.py",
    )
    msgs = [f.message for f in bad if f.rule == "runtime-isolation"]
    assert len(msgs) == 1 and "'self.g'" in msgs[0]


def test_sync_rule_sees_through_container_annotations():
    """Review fix: `Sequence[CSRGraph]` must not launder device fields
    through the host-container annotation, while `Sequence[float]` stays
    host."""
    findings = analyze(
        """
        import numpy as np
        from typing import Sequence
        from ..graph.csr import CSRGraph

        def leak(graphs: Sequence[CSRGraph]):
            return [np.asarray(g.node_w) for g in graphs]

        def fine(values: Sequence[float]):
            return np.asarray(values)
        """
    )
    sync = [f for f in findings if f.rule == "sync-discipline"]
    assert len(sync) == 1 and sync[0].line == 7


def test_sync_rule_sees_into_lambda_bodies():
    """Review fix: a materialization inside a lambda must not escape the
    scope-based scan."""
    findings = analyze(
        """
        import numpy as np
        import jax.numpy as jnp

        def leak(vals):
            x = jnp.asarray(vals)
            f = lambda: np.asarray(x)
            return f
        """
    )
    assert "sync-discipline" in rules_of(findings)


def test_ignore_file_past_header_does_not_suppress_line():
    """Review fix: an ignore-file directive after line 10 is inert — it
    neither grants a file-wide exemption nor silently suppresses every rule
    on its own line."""
    src = (
        "import numpy as np\n" + "\n" * 10 +
        "def leak(graph):\n"
        "    return np.asarray(graph.node_w)  # kpt: ignore-file[sync-discipline]\n"
    )
    analyzer = Analyzer(ALL_RULES, default_config())
    findings = [f for f in analyzer.check_source(src) if not f.suppressed]
    assert "sync-discipline" in rules_of(findings)


def test_importmap_resolves_relative_imports_in_package_init():
    """Review fix: level-1 relative imports inside an __init__.py resolve
    against the package itself, not its parent."""
    from kaminpar_tpu.analysis.core import SourceModule

    mod = SourceModule.load(
        REPO / "kaminpar_tpu/serve/__init__.py",
        "kaminpar_tpu/serve/__init__.py",
        "kaminpar_tpu.serve",
    )
    assert (
        mod.imports.names.get("pack_graphs")
        == "kaminpar_tpu.serve.batching.pack_graphs"
    )


# ---------------------------------------------------------------------------
# phase-registry
# ---------------------------------------------------------------------------


def test_phase_rule_fires_on_unregistered_literal():
    findings = analyze(
        """
        from ..utils.timer import scoped_timer

        def work():
            with scoped_timer("coarsning"):  # typo
                pass
        """
    )
    assert "phase-registry" in rules_of(findings)


def test_phase_rule_checks_pull_phase_kwarg():
    findings = analyze(
        """
        from ..utils import sync_stats

        def work(x):
            return sync_stats.pull(x, phase="not_a_phase")
        """
    )
    assert "phase-registry" in rules_of(findings)


def test_phase_rule_accepts_registered_names():
    findings = analyze(
        """
        from ..utils.timer import scoped_timer
        from ..utils import sync_stats

        def work(x):
            with scoped_timer("coarsening"):
                return sync_stats.pull(x, phase="dist_metrics")
        """
    )
    assert "phase-registry" not in rules_of(findings)


def test_phase_rule_reverse_direction_flags_stale_registry():
    """finalize(): a registered phase never referenced anywhere is flagged
    on the registry module."""
    from kaminpar_tpu.analysis.core import SourceModule
    from kaminpar_tpu.analysis.rules import PhaseRegistryRule

    registry = SourceModule.from_source(
        "KNOWN_PHASES = ()\n",
        rel="kaminpar_tpu/telemetry/phases.py",
        modname="kaminpar_tpu.telemetry.phases",
    )
    user = SourceModule.from_source(
        'from ..utils.timer import scoped_timer\n'
        'def f():\n'
        '    with scoped_timer("coarsening"):\n'
        '        pass\n',
        rel="kaminpar_tpu/dist/_snippet.py",
        modname="kaminpar_tpu.dist._snippet",
    )
    rule = PhaseRegistryRule()
    stale = rule.finalize([registry, user], default_config())
    # every KNOWN_PHASES entry except "untracked" and the ones the snippet
    # uses shows up as stale against this tiny module set
    from kaminpar_tpu.telemetry.phases import KNOWN_PHASES

    expect = len(KNOWN_PHASES) - 2  # "untracked" + "coarsening"
    assert len(stale) == expect


# ---------------------------------------------------------------------------
# rng-discipline
# ---------------------------------------------------------------------------


def test_rng_rule_fires_on_np_random_and_stdlib_random():
    findings = analyze(
        """
        import random
        import numpy as np

        def draw():
            rng = np.random.default_rng(0)
            return rng.integers(10) + random.random()
        """
    )
    assert sum(f.rule == "rng-discipline" for f in findings) == 2


def test_rng_rule_fires_on_raw_key_construction():
    findings = analyze(
        """
        import jax

        def key():
            return jax.random.key(0)
        """
    )
    assert "rng-discipline" in rules_of(findings)


def test_rng_rule_accepts_facade():
    findings = analyze(
        """
        from ..utils import RandomState, rng

        def draw():
            host = RandomState.numpy_rng()
            return rng.seed_key(0), rng.lane_key(1, 3), host.integers(10)
        """
    )
    assert "rng-discipline" not in rules_of(findings)


def test_rng_rule_exempts_io_and_generators():
    findings = analyze(
        """
        import numpy as np

        def gen(seed):
            return np.random.default_rng(seed)
        """,
        rel="kaminpar_tpu/graph/generators.py",
    )
    assert "rng-discipline" not in rules_of(findings)


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

_DONATING_DEF = """
    from functools import partial
    import jax

    @partial(jax.jit, donate_argnums=(0,))
    def step(state, x):
        return state + x
"""


def test_donation_rule_fires_on_use_after_donate():
    findings = analyze(
        _DONATING_DEF + """
    def caller(state, x):
        out = step(state, x)
        return out + state.sum()
        """
    )
    assert "donation-safety" in rules_of(findings)


def test_donation_rule_accepts_rebinding_idiom():
    findings = analyze(
        _DONATING_DEF + """
    def caller(state, x):
        for _ in range(3):
            state = step(state, x)
        return state
        """
    )
    assert "donation-safety" not in rules_of(findings)


def test_donation_rule_revives_after_rebind():
    findings = analyze(
        _DONATING_DEF + """
    def caller(state, x, fresh):
        out = step(state, x)
        state = fresh
        return out + state.sum()
        """
    )
    assert "donation-safety" not in rules_of(findings)


# ---------------------------------------------------------------------------
# suppressions / baseline machinery
# ---------------------------------------------------------------------------


def test_file_wide_suppression():
    findings = analyze(
        """
        # kpt: ignore-file[sync-discipline]
        import numpy as np

        def leak(graph):
            return np.asarray(graph.node_w)
        """
    )
    assert "sync-discipline" not in rules_of(findings)


def test_baseline_round_trip(tmp_path):
    """run -> baseline-update -> rerun shows zero fresh; removing the
    violation makes the entry stale; an unrelated edit above the site does
    NOT invalidate the entry (line-independent fingerprints)."""
    src = textwrap.dedent(
        """
        import numpy as np

        def leak(graph):
            return np.asarray(graph.node_w)
        """
    )
    analyzer = Analyzer(ALL_RULES, default_config())
    first = [f for f in analyzer.check_source(src) if not f.suppressed]
    assert first
    bl = Baseline.from_findings(first, notes="test")
    path = tmp_path / "baseline.json"
    bl.save(path)
    loaded = Baseline.load(path)
    assert len(loaded) == len(first)

    # same source: everything baselined, nothing fresh
    again = analyzer.check_source(src)
    for f in again:
        if not f.suppressed and loaded.contains(f):
            f.baselined = True
    assert analyzer.fresh(again) == []

    # unrelated edit above the site: fingerprints survive
    shifted = src.replace(
        "import numpy as np", "import numpy as np\nUNRELATED = 1"
    )
    moved = analyzer.check_source(shifted)
    live = [f for f in moved if not f.suppressed]
    assert all(loaded.contains(f) for f in live)

    # fixing the violation leaves a stale entry
    fixed = src.replace("np.asarray(graph.node_w)", "graph.node_w")
    clean = analyzer.check_source(fixed)
    assert loaded.stale_entries(clean) == loaded.entries


def test_summarize_shape():
    src = "import numpy as np\ndef f(g):\n    return np.asarray(g.node_w)\n"
    analyzer = Analyzer(ALL_RULES, default_config())
    findings = analyzer.check_source(src)
    s = summarize(findings)
    assert set(s) == {"fresh", "suppressed", "baselined", "per_rule"}
    assert s["fresh"] == s["per_rule"].get("sync-discipline", 0) > 0


# ---------------------------------------------------------------------------
# package-wide gates
# ---------------------------------------------------------------------------


def test_package_self_clean():
    """The whole package carries zero non-baselined violations — the tier-1
    lint gate (same analysis `tools lint` runs)."""
    config = default_config()
    baseline = Baseline.load(REPO / "kptlint_baseline.json")
    analyzer = Analyzer(ALL_RULES, config)
    findings = analyzer.run(baseline=baseline)
    fresh = analyzer.fresh(findings)
    assert fresh == [], "fresh kptlint violations:\n" + "\n".join(
        f.render() for f in fresh
    )


def test_lint_cli_json_and_exit_code():
    out = subprocess.run(
        [sys.executable, "-m", "kaminpar_tpu.tools", "lint", "--json"],
        capture_output=True, text=True, timeout=120,
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["summary"]["fresh"] == 0
    assert "baseline_size" in payload["summary"]


def test_lint_cli_list_rules():
    out = subprocess.run(
        [sys.executable, "-m", "kaminpar_tpu.tools", "lint", "--list-rules"],
        capture_output=True, text=True, timeout=120, cwd=str(REPO),
    )
    assert out.returncode == 0
    for rule in ("sync-discipline", "runtime-isolation", "phase-registry",
                 "rng-discipline", "donation-safety"):
        assert rule in out.stdout


# ---------------------------------------------------------------------------
# error-discipline (round 17, ISSUE 13)
# ---------------------------------------------------------------------------


def test_error_rule_fires_on_bare_runtimeerror():
    findings = analyze(
        """
        def f(x):
            raise RuntimeError("device pool failed")
        """,
        rel="kaminpar_tpu/serve/_snippet.py",
    )
    assert "error-discipline" in rules_of(findings)


def test_error_rule_fires_on_unclassified_dispatch_handler():
    """The pre-round-17 engine pattern: a broad except around a dispatch
    site wrapping the failure in an untyped ServeError."""
    findings = analyze(
        """
        class ServeError(RuntimeError):
            pass

        def f(solver, reqs):
            try:
                solver.compute_partition(4, 0.03)
            except Exception as exc:
                for r in reqs:
                    r.future._reject(ServeError(f"batch failed: {exc!r}"))
        """,
        rel="kaminpar_tpu/serve/_snippet.py",
    )
    assert "error-discipline" in rules_of(findings)


def test_error_rule_fires_on_laundered_valueerror():
    findings = analyze(
        """
        def f(g):
            try:
                return g.dispatch()
            except Exception as exc:
                raise ValueError(str(exc))
        """,
        rel="kaminpar_tpu/ops/_snippet.py",
    )
    assert "error-discipline" in rules_of(findings)


def test_error_rule_clean_on_classify_and_validation():
    """classify-routed handlers, typed raises, bare re-raises, narrow
    handlers, and plain argument validation all pass."""
    findings = analyze(
        """
        from ..resilience.errors import ExecuteFault, classify

        def f(solver, k):
            if k <= 0:
                raise ValueError("k must be positive")
            try:
                return solver.compute_partition(k, 0.03)
            except KeyError:
                return None
            except Exception as exc:
                raise classify(exc, site="test")

        def g(solver):
            try:
                return solver.compute_partition(2, 0.03)
            except Exception:
                raise ExecuteFault("typed", site="test")

        def h(solver):
            try:
                return solver.compute_partition(2, 0.03)
            except Exception:
                raise
        """,
        rel="kaminpar_tpu/serve/_snippet.py",
    )
    assert "error-discipline" not in rules_of(findings)


def test_error_rule_mutation_gate_engine_loop():
    """Deleting the classify routing from the real engine dispatcher
    handler trips error-discipline on the real source."""
    engine_src = (REPO / "kaminpar_tpu" / "serve" / "engine.py").read_text()
    rel = "kaminpar_tpu/serve/engine.py"
    analyzer = Analyzer(ALL_RULES, default_config())
    clean = [
        f for f in analyzer.check_source(
            engine_src, rel=rel, modname="kaminpar_tpu.serve.engine"
        )
        if not f.suppressed and f.rule == "error-discipline"
    ]
    assert clean == []
    assert "err = classify(exc, site=\"dispatch\")" in engine_src
    mutated = engine_src.replace(
        "err = classify(exc, site=\"dispatch\")",
        "err = ServeError(f\"batch failed: {exc!r}\")",
    ).replace(
        "from ..resilience.errors import classify\n\n                err",
        "err",
    )
    fired = [
        f for f in analyzer.check_source(
            mutated, rel=rel, modname="kaminpar_tpu.serve.engine"
        )
        if not f.suppressed and f.rule == "error-discipline"
    ]
    assert fired, "mutated dispatcher handler must trip error-discipline"


def test_every_shipped_rule_has_fire_and_suppress_coverage():
    """Meta-gate: each shipped rule fires on at least one fixture above AND
    honors suppression (spot-checked here for the remaining rules)."""
    fixtures = {
        "sync-discipline": "import numpy as np\ndef f(g):\n"
                           "    return np.asarray(g.node_w)\n",
        "runtime-isolation": "from ..graph.csr import from_numpy_csr\n"
                             "def f(s):\n"
                             "    g = from_numpy_csr(s.a, s.b, s.c, s.d)\n"
                             "    return g\n",
        "phase-registry": "from ..utils.timer import scoped_timer\n"
                          "def f():\n"
                          "    with scoped_timer(\"zz_bogus\"):\n"
                          "        pass\n",
        "rng-discipline": "import random\n",
        "donation-safety": (
            "from functools import partial\nimport jax\n"
            "@partial(jax.jit, donate_argnums=(0,))\n"
            "def step(s):\n    return s\n"
            "def f(s):\n    out = step(s)\n    return out, s\n"
        ),
        "error-discipline": (
            "def f(solver):\n"
            "    try:\n"
            "        return solver.compute_partition(2, 0.03)\n"
            "    except Exception as exc:\n"
            "        raise RuntimeError(str(exc))\n"
        ),
    }
    analyzer = Analyzer(ALL_RULES, default_config())
    for rule, src in fixtures.items():
        fired = analyzer.check_source(src)
        assert any(
            f.rule == rule and not f.suppressed for f in fired
        ), f"{rule} fixture did not fire"
        lines = src.splitlines()
        suppressed_src = "\n".join(
            [f"# kpt: ignore-file[{rule}]"] + lines
        ) + "\n"
        silent = analyzer.check_source(suppressed_src)
        assert not any(
            f.rule == rule and not f.suppressed for f in silent
        ), f"{rule} suppression not honored"
