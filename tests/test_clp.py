"""Colored LP refiner tests (reference: clp_refiner.cc +
greedy_node_coloring.h)."""

import jax
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.context import ColoredLPContext
from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.partitioned import PartitionedGraph
from kaminpar_tpu.ops.coloring import color_graph, num_colors
from kaminpar_tpu.refinement.clp_refiner import CLPRefiner


def test_coloring_is_proper():
    for g in (generators.grid2d_graph(16, 16), generators.rmat_graph(9, 8, seed=1)):
        pv = g.padded()
        mask = jnp.arange(pv.n_pad) < pv.n
        colors = np.asarray(
            color_graph(jax.random.PRNGKey(0), pv.edge_u, pv.col_idx, mask, n=pv.n_pad)
        )
        eu, cv, w = np.asarray(pv.edge_u), np.asarray(pv.col_idx), np.asarray(pv.edge_w)
        real = (w > 0) & (eu != cv)
        assert (colors[eu[real]] != colors[cv[real]]).all()


def _pgraph(g, k, part, eps=0.1):
    W = int(np.asarray(g.node_w).sum())
    per = int(np.ceil(W / k) * (1 + eps)) + int(np.asarray(g.node_w).max())
    return PartitionedGraph.create(g, k, part, np.full(k, per, dtype=np.int64))


def test_clp_improves_noisy_grid():
    g = generators.grid2d_graph(16, 16)
    rng = np.random.default_rng(0)
    part = (np.arange(256) // 64).astype(np.int32)
    flip = rng.random(256) < 0.2
    part[flip] = rng.integers(0, 4, flip.sum())
    pg = _pgraph(g, 4, part)
    out = CLPRefiner(ColoredLPContext()).refine(pg)
    assert out.edge_cut() < pg.edge_cut()
    assert out.is_feasible()


def test_clp_straightens_boundaries_beyond_lp():
    """Exact gains + safe tie diffusion should at least match strict LP."""
    from kaminpar_tpu.context import LabelPropagationContext
    from kaminpar_tpu.refinement.lp_refiner import LPRefiner

    g = generators.rgg2d_graph(2048, seed=4)
    rng = np.random.default_rng(4)
    part = rng.integers(0, 8, g.n).astype(np.int32)
    pg = _pgraph(g, 8, part)
    lp_out = LPRefiner(LabelPropagationContext(num_iterations=8)).refine(pg)
    clp_out = CLPRefiner(ColoredLPContext()).refine(lp_out)
    assert clp_out.edge_cut() <= lp_out.edge_cut()
    assert clp_out.is_feasible()


def test_clp_never_worsens():
    g = generators.rmat_graph(9, 8, seed=2)
    rng = np.random.default_rng(2)
    part = rng.integers(0, 4, g.n).astype(np.int32)
    pg = _pgraph(g, 4, part)
    out = CLPRefiner(ColoredLPContext()).refine(pg)
    assert out.edge_cut() <= pg.edge_cut()


def test_clp_fused_supersteps_bit_identical_to_host_loop():
    """The device-resident CLP iteration (one fori_loop over color classes,
    one batched moved-count readback) is bit-identical to the
    dispatch-per-superstep host loop it replaced (ISSUE 2): same key draws
    in the same order, same rounds, same early break."""
    from kaminpar_tpu.ops.coloring import num_colors_device
    from kaminpar_tpu.utils import next_key, reseed, sync_stats

    def host_loop_clp(p_graph, ctx):
        from kaminpar_tpu.ops import lp

        pv = p_graph.graph.padded()
        bv = p_graph.graph.bucketed()
        k = p_graph.k
        k_pad = lp.num_labels_bucket(k)
        max_w = jnp.asarray(p_graph.max_block_weights, dtype=pv.node_w.dtype)
        if k_pad > k:
            max_w = jnp.concatenate([max_w, jnp.zeros(k_pad - k, dtype=max_w.dtype)])
        part = pv.pad_node_array(p_graph.partition, 0)
        mask = jnp.arange(pv.n_pad) < pv.n
        colors = color_graph(next_key(), pv.edge_u, pv.col_idx, mask, n=pv.n_pad)
        nc = num_colors(colors, mask)
        state = lp.init_state(part, pv.node_w, k_pad)
        before = p_graph.edge_cut()
        for _ in range(ctx.num_iterations):
            moved = 0
            for c in range(nc):
                state = lp.lp_round_colored(
                    state, next_key(), bv.buckets, bv.heavy, bv.gather_idx,
                    pv.node_w, max_w, colors == c, num_labels=k_pad,
                    allow_tie_moves=ctx.allow_tie_moves,
                )
                moved += int(state.num_moved)
            if moved == 0:
                break
        out = p_graph.with_partition(state.labels[: pv.n])
        return p_graph if out.edge_cut() > before else out

    for g in (generators.grid2d_graph(16, 16), generators.rmat_graph(9, 8, seed=3)):
        rng = np.random.default_rng(9)
        part = rng.integers(0, 4, g.n).astype(np.int32)
        reseed(31)
        ref = host_loop_clp(_pgraph(g, 4, part), ColoredLPContext())
        reseed(31)
        sync_stats.reset()
        fused = CLPRefiner(ColoredLPContext()).refine(_pgraph(g, 4, part))
        assert np.array_equal(np.asarray(ref.partition), np.asarray(fused.partition))
        # fused path: 1 color-count pull + 1 moved-count pull per iteration
        phases = sync_stats.snapshot()["phases"]
        assert phases["clp_refinement"]["count"] <= 1 + ColoredLPContext().num_iterations


def test_num_colors_device_matches_host():
    from kaminpar_tpu.ops.coloring import num_colors_device

    for g in (generators.grid2d_graph(12, 12), generators.rmat_graph(8, 8, seed=4)):
        pv = g.padded()
        mask = jnp.arange(pv.n_pad) < pv.n
        colors = color_graph(jax.random.PRNGKey(3), pv.edge_u, pv.col_idx, mask, n=pv.n_pad)
        assert int(num_colors_device(colors, mask)) == num_colors(colors, mask)
