"""Colored LP refiner tests (reference: clp_refiner.cc +
greedy_node_coloring.h)."""

import jax
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.context import ColoredLPContext
from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.partitioned import PartitionedGraph
from kaminpar_tpu.ops.coloring import color_graph, num_colors
from kaminpar_tpu.refinement.clp_refiner import CLPRefiner


def test_coloring_is_proper():
    for g in (generators.grid2d_graph(16, 16), generators.rmat_graph(9, 8, seed=1)):
        pv = g.padded()
        mask = jnp.arange(pv.n_pad) < pv.n
        colors = np.asarray(
            color_graph(jax.random.PRNGKey(0), pv.edge_u, pv.col_idx, mask, n=pv.n_pad)
        )
        eu, cv, w = np.asarray(pv.edge_u), np.asarray(pv.col_idx), np.asarray(pv.edge_w)
        real = (w > 0) & (eu != cv)
        assert (colors[eu[real]] != colors[cv[real]]).all()


def _pgraph(g, k, part, eps=0.1):
    W = int(np.asarray(g.node_w).sum())
    per = int(np.ceil(W / k) * (1 + eps)) + int(np.asarray(g.node_w).max())
    return PartitionedGraph.create(g, k, part, np.full(k, per, dtype=np.int64))


def test_clp_improves_noisy_grid():
    g = generators.grid2d_graph(16, 16)
    rng = np.random.default_rng(0)
    part = (np.arange(256) // 64).astype(np.int32)
    flip = rng.random(256) < 0.2
    part[flip] = rng.integers(0, 4, flip.sum())
    pg = _pgraph(g, 4, part)
    out = CLPRefiner(ColoredLPContext()).refine(pg)
    assert out.edge_cut() < pg.edge_cut()
    assert out.is_feasible()


def test_clp_straightens_boundaries_beyond_lp():
    """Exact gains + safe tie diffusion should at least match strict LP."""
    from kaminpar_tpu.context import LabelPropagationContext
    from kaminpar_tpu.refinement.lp_refiner import LPRefiner

    g = generators.rgg2d_graph(2048, seed=4)
    rng = np.random.default_rng(4)
    part = rng.integers(0, 8, g.n).astype(np.int32)
    pg = _pgraph(g, 8, part)
    lp_out = LPRefiner(LabelPropagationContext(num_iterations=8)).refine(pg)
    clp_out = CLPRefiner(ColoredLPContext()).refine(lp_out)
    assert clp_out.edge_cut() <= lp_out.edge_cut()
    assert clp_out.is_feasible()


def test_clp_never_worsens():
    g = generators.rmat_graph(9, 8, seed=2)
    rng = np.random.default_rng(2)
    part = rng.integers(0, 4, g.n).astype(np.int32)
    pg = _pgraph(g, 4, part)
    out = CLPRefiner(ColoredLPContext()).refine(pg)
    assert out.edge_cut() <= pg.edge_cut()
