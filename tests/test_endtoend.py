"""End-to-end facade tests (reference tier 3:
tests/endtoend/shm_endtoend_test.cc — drives the public API, asserts
feasibility and sane cuts without golden numbers)."""

import numpy as np
import pytest

from kaminpar_tpu.graph import generators, metrics
from kaminpar_tpu.kaminpar import KaMinPar


def _check(graph, part, k, max_bw):
    assert part.shape == (graph.n,)
    assert part.min() >= 0 and part.max() < k
    assert metrics.is_feasible(graph, part, k, max_bw)


@pytest.mark.parametrize("preset", ["default", "fast", "noref"])
def test_presets_grid(preset):
    g = generators.grid2d_graph(12, 12)
    solver = KaMinPar(preset)
    solver.set_graph(g)
    part = solver.compute_partition(k=4)
    _check(g, part, 4, solver.ctx.partition.max_block_weights)


def test_kway_mode():
    g = generators.grid2d_graph(10, 10)
    solver = KaMinPar("kway")
    solver.set_graph(g)
    part = solver.compute_partition(k=5)
    _check(g, part, 5, solver.ctx.partition.max_block_weights)


def test_weighted_graph():
    rng = np.random.default_rng(0)
    from kaminpar_tpu.graph import from_edge_list

    edges = []
    for i in range(49):
        edges.append([i, i + 1])
    g = from_edge_list(
        50, np.array(edges), node_weights=rng.integers(1, 5, 50)
    )
    solver = KaMinPar("default")
    solver.set_graph(g)
    part = solver.compute_partition(k=3, epsilon=0.1)
    _check(g, part, 3, solver.ctx.partition.max_block_weights)


def test_k16_rmat():
    g = generators.rmat_graph(9, 6, seed=11)
    solver = KaMinPar("fast")
    solver.set_graph(g)
    part = solver.compute_partition(k=16)
    _check(g, part, 16, solver.ctx.partition.max_block_weights)
    assert len(np.unique(part)) == 16


def test_quality_vs_random():
    """No golden numbers (reference asserts only feasibility), but the
    multilevel cut must beat a random partition by a wide margin."""
    g = generators.grid2d_graph(16, 16)
    solver = KaMinPar("default")
    solver.set_graph(g)
    part = solver.compute_partition(k=4)
    cut = metrics.edge_cut(g, part)
    rng = np.random.default_rng(0)
    rand_cut = metrics.edge_cut(g, rng.integers(0, 4, g.n))
    assert cut < rand_cut / 3


def test_empty_and_tiny_graphs():
    # an empty block can be feasible under the +max_node_weight slack (as in
    # the reference's block-weight setup), so assert feasibility, not shape
    from kaminpar_tpu.graph import from_edge_list

    g = from_edge_list(2, np.array([[0, 1]]))
    solver = KaMinPar("fast")
    solver.set_graph(g)
    part = solver.compute_partition(k=2)
    _check(g, part, 2, solver.ctx.partition.max_block_weights)


def test_determinism_same_seed():
    g = generators.grid2d_graph(8, 8)
    parts = []
    for _ in range(2):
        solver = KaMinPar("fast")
        solver.ctx.seed = 7
        solver.set_graph(g)
        parts.append(solver.compute_partition(k=2))
    assert np.array_equal(parts[0], parts[1])


def test_strong_not_worse_than_fast():
    g = generators.rmat_graph(9, 8, seed=4)
    cuts = {}
    for preset in ("fast", "strong"):
        solver = KaMinPar(preset)
        solver.set_graph(g)
        part = solver.compute_partition(k=4)
        cuts[preset] = metrics.edge_cut(g, part)
    assert cuts["strong"] <= cuts["fast"] * 1.1


def test_isolated_nodes_stripped_and_reintegrated():
    """Reference: kaminpar.cc:388-429 — isolated nodes are removed before
    partitioning and bin-packed into the lightest blocks afterwards."""
    import numpy as np

    from kaminpar_tpu.graph import generators, metrics
    from kaminpar_tpu.graph.csr import from_numpy_csr
    from kaminpar_tpu.kaminpar import KaMinPar

    base = generators.rgg2d_graph(512, seed=12)
    # append 128 isolated nodes with varied weights
    rp = np.asarray(base.row_ptr)
    n_iso = 128
    rng = np.random.default_rng(0)
    rp2 = np.concatenate([rp, np.full(n_iso, rp[-1])])
    nw = np.concatenate([np.asarray(base.node_w), rng.integers(1, 5, n_iso)])
    g = from_numpy_csr(rp2, np.asarray(base.col_idx), nw, np.asarray(base.edge_w))
    k = 4
    s = KaMinPar("default")
    s.set_graph(g)
    part = s.compute_partition(k=k)
    assert len(part) == g.n
    assert metrics.is_feasible(g, part, k, s.ctx.partition.max_block_weights)
    # all isolated nodes got assigned to real blocks
    assert set(np.unique(part[512:])) <= set(range(k))
