"""Distributed LP on a virtual 8-device CPU mesh (SURVEY §4: the JAX analog
of the reference's oversubscribed-MPI KaTestrophe testing)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from kaminpar_tpu.dist import (
    dist_cluster_iterate,
    dist_lp_iterate,
    dist_lp_round,
    distribute_graph,
)
from kaminpar_tpu.dist.lp import shard_arrays
from kaminpar_tpu.graph import generators, metrics


def _mesh(num=8):
    devs = jax.devices()
    if len(devs) < num:
        pytest.skip(f"need {num} devices, have {len(devs)}")
    return Mesh(np.array(devs[:num]), ("nodes",))


def test_distribute_graph_layout():
    g = generators.grid2d_graph(10, 10)
    dg = distribute_graph(g, 4)
    assert dg.N > g.n and dg.N == 4 * dg.n_loc
    # Per-shard edge realness: weights of pads are 0; real edge weights sum
    # matches the original.
    assert int(np.asarray(dg.edge_w).sum()) == g.total_edge_weight
    assert int(np.asarray(dg.node_w).sum()) == g.total_node_weight
    # real edge targets are valid local or ghost slots, pads point at the
    # pad slot
    eu = np.asarray(dg.edge_u).reshape(4, dg.m_loc)
    ew = np.asarray(dg.edge_w).reshape(4, dg.m_loc)
    cl = np.asarray(dg.col_loc).reshape(4, dg.m_loc)
    for s in range(4):
        real = ew[s] > 0
        assert np.all(cl[s][real] < dg.n_loc + len(dg.ghost_global[s]))
        assert np.all(cl[s][~real] == dg.n_loc + dg.g_loc)
        assert np.all(eu[s][real] < dg.n_loc)


def test_distribute_graph_int64():
    # 64-bit ids/weights (the reference's KAMINPAR_64BIT_* switches) require
    # jax x64 mode, the runtime analog of the build flag.
    with jax.enable_x64(True):
        g = generators.grid2d_graph(6, 6)
        dg = distribute_graph(g, 4, dtype=np.int64)
        assert str(dg.node_w.dtype) == "int64"
        assert str(dg.col_loc.dtype) == "int64"
        assert int(np.asarray(dg.edge_w).sum()) == g.total_edge_weight


def test_dist_cluster_round():
    mesh = _mesh()
    g = generators.grid2d_graph(16, 16)
    dg = distribute_graph(g, mesh.size)
    N = dg.N
    labels = jnp.arange(N, dtype=jnp.int32)
    labels, dg = shard_arrays(mesh, dg, labels)

    out, moved = dist_cluster_iterate(
        mesh, jax.random.key(0), labels, dg, jnp.int32(8), num_rounds=1
    )
    out = np.asarray(out)
    assert int(moved) > 0
    # cluster weights respect the cap
    w = np.bincount(out[: g.n], minlength=N)
    assert w.max() <= 8
    # pads never move
    assert np.all(out[g.n :] == np.arange(g.n, N))


def test_dist_cluster_iterate_coarsens():
    mesh = _mesh()
    g = generators.rmat_graph(10, 8, seed=3)
    dg = distribute_graph(g, mesh.size)
    N = dg.N
    labels = jnp.arange(N, dtype=jnp.int32)
    labels, dg = shard_arrays(mesh, dg, labels)
    out, total = dist_cluster_iterate(
        mesh, jax.random.key(1), labels, dg, jnp.int32(64), num_rounds=5
    )
    out = np.asarray(out)[: g.n]
    clusters = len(np.unique(out))
    assert clusters < 0.6 * g.n  # real coarsening happened
    w = np.bincount(np.asarray(out), minlength=N, weights=np.ones(g.n))
    assert w.max() <= 64


def test_dist_local_cluster_stays_shard_local():
    """LOCAL_LP clusterer (reference: local_lp_clusterer.cc): clusters never
    span shards, rounds are exchange-free, caps hold."""
    from kaminpar_tpu.dist.lp import dist_local_cluster_iterate

    mesh = _mesh()
    g = generators.rmat_graph(10, 8, seed=3)
    dg = distribute_graph(g, mesh.size)
    N = dg.N
    labels = jnp.arange(N, dtype=jnp.int32)
    labels, dgs = shard_arrays(mesh, dg, labels)
    out, total = dist_local_cluster_iterate(
        mesh, jax.random.key(4), labels, dgs, jnp.int32(32), num_rounds=4
    )
    out = np.asarray(out)
    assert int(total) > 0
    # every node's cluster id is owned by the node's own shard
    shard_of_node = np.arange(N) // dg.n_loc
    shard_of_label = out // dg.n_loc
    assert np.all(shard_of_label == shard_of_node)
    # caps hold and real coarsening happened
    w = np.bincount(out[: g.n], minlength=N)
    assert w.max() <= 32
    assert len(np.unique(out[: g.n])) < 0.8 * g.n
    # pads never move
    assert np.all(out[g.n :] == np.arange(g.n, N))


def test_dist_hem_matches_pairs_across_shards():
    """Dist HEM (reference: hem_clusterer.cc): clusters are mutual pairs
    (size <= 2), weight caps hold, matching crosses shard boundaries."""
    from kaminpar_tpu.dist.hem import dist_hem_cluster

    mesh = _mesh()
    g = generators.grid2d_graph(16, 16)
    dg = distribute_graph(g, mesh.size)
    labels, matched = dist_hem_cluster(
        mesh, jax.random.key(7), dg, 8, num_rounds=5
    )
    out = np.asarray(labels)[: g.n]
    assert matched > 0
    sizes = np.bincount(out, minlength=dg.N)
    assert sizes.max() <= 2  # matching, not merging
    # pairs are mutual: every size-2 cluster's label is one of its members
    labs, counts = np.unique(out, return_counts=True)
    paired = labs[counts == 2]
    assert len(paired) == matched
    # at least one pair spans a shard boundary on a grid this size
    shard_of = np.arange(g.n) // dg.n_loc
    cross = 0
    for lab in paired[:200]:
        members = np.flatnonzero(out == lab)
        if shard_of[members[0]] != shard_of[members[1]]:
            cross += 1
    assert cross > 0, "no cross-shard pair matched"
    # full pipeline sanity through contraction
    from kaminpar_tpu.dist.contraction import contract_dist_clustering
    from kaminpar_tpu.dist.lp import shard_arrays

    lab_dev, dgs = shard_arrays(mesh, dg, jnp.asarray(labels))
    coarse, coarse_of, n_c = contract_dist_clustering(mesh, dgs, lab_dev)
    assert n_c == g.n - matched


def test_dist_hem_respects_weight_cap():
    """HEM eligibility must reject pairs whose combined weight exceeds the
    cluster cap (weighted nodes, tight cap)."""
    from kaminpar_tpu.dist.hem import dist_hem_cluster
    from kaminpar_tpu.graph.csr import CSRGraph

    mesh = _mesh()
    g0 = generators.grid2d_graph(12, 12)
    rng = np.random.default_rng(5)
    nw = rng.integers(1, 6, g0.n)  # weights 1..5, cap 6
    g = CSRGraph(np.asarray(g0.row_ptr), np.asarray(g0.col_idx), nw,
                 np.asarray(g0.edge_w))
    dg = distribute_graph(g, mesh.size)
    labels, matched = dist_hem_cluster(
        mesh, jax.random.key(9), dg, 6, num_rounds=5
    )
    out = np.asarray(labels)[: g.n]
    assert matched > 0
    cw = np.bincount(out, weights=nw.astype(float), minlength=dg.N)
    labs, counts = np.unique(out, return_counts=True)
    paired = labs[counts == 2]
    assert (cw[paired] <= 6).all(), cw[paired].max()


def test_cluster_auction_keeps_feasibility():
    """The owner-side capacity auction must never admit weight beyond the
    cluster cap, across seeds (the reference's growt weight-rollback
    protocol analog, global_lp_clusterer.cc:437-525)."""
    mesh = _mesh()
    g = generators.rmat_graph(9, 6, seed=11)
    dg = distribute_graph(g, mesh.size)
    N = dg.N
    cap = 3
    for seed in range(20):
        labels = jnp.arange(N, dtype=jnp.int32)
        labels, dgs = shard_arrays(mesh, dg, labels)
        out, _ = dist_cluster_iterate(
            mesh, jax.random.key(seed), labels, dgs, jnp.int32(cap),
            num_rounds=3,
        )
        w = np.bincount(np.asarray(out)[: g.n], minlength=N)
        assert w.max() <= cap, f"seed {seed}: cluster weight {w.max()} > {cap}"


def test_dist_lp_refinement_improves_cut():
    mesh = _mesh()
    g = generators.grid2d_graph(20, 20)
    dg = distribute_graph(g, mesh.size)
    N = dg.N
    k = 4
    rng = np.random.default_rng(0)
    part = rng.integers(0, k, N).astype(np.int32)
    part[g.n :] = 0
    cut0 = metrics.edge_cut(g, part[: g.n])
    labels, dg = shard_arrays(mesh, dg, jnp.asarray(part))
    cap = jnp.full(k, int(1.1 * g.total_node_weight / k) + 8, dtype=jnp.int32)
    out, _ = dist_lp_iterate(
        mesh, jax.random.key(2), labels, dg, cap, num_labels=k,
        num_rounds=8, external_only=False,
    )
    out = np.asarray(out)[: g.n]
    cut1 = metrics.edge_cut(g, out)
    assert cut1 < cut0  # refinement reduces the cut
    w = np.bincount(out, weights=np.ones(g.n), minlength=k)
    assert w.max() <= int(1.1 * g.total_node_weight / k) + 8


def test_per_shard_memory_stays_local():
    """Weak-scaling witness (VERDICT r1 weak #3): per-shard arrays are
    O(n_loc + m_loc + ghosts), never O(N).  On an rmat scale-14 graph over 8
    shards no per-shard device array may exceed ~2*(n_loc + m_loc)."""
    mesh = _mesh()
    g = generators.rmat_graph(14, 8, seed=5)
    dg = distribute_graph(g, mesh.size)
    bound = 2 * (dg.n_loc + dg.m_loc)
    assert dg.max_per_shard_array <= bound, (
        f"per-shard array {dg.max_per_shard_array} exceeds 2*(n_loc+m_loc)="
        f"{bound}"
    )
    # and the ghost/exchange structures specifically
    assert dg.g_loc <= dg.m_loc
    assert dg.num_shards * dg.cap_g <= bound

    # one clustering round runs without the owner buffers blowing past the
    # bound either (cap_q * P <= 2*(n_loc+m_loc))
    labels = jnp.arange(dg.N, dtype=jnp.int32)
    labels, dgs = shard_arrays(mesh, dg, labels)
    from kaminpar_tpu.utils.intmath import next_pow2

    cap_q = min(next_pow2(max(64, 2 * dg.n_loc // dg.num_shards), 8), dg.n_loc)
    assert dg.num_shards * cap_q <= bound
    out, moved = dist_cluster_iterate(
        mesh, jax.random.key(0), labels, dgs, jnp.int32(64), num_rounds=2,
        cap_q=cap_q,
    )
    assert int(moved) > 0


def test_dist_coloring_is_proper():
    """dist CLP prerequisite: the sharded Jones-Plassmann coloring must be
    proper across shard boundaries (reference: greedy_node_coloring.h)."""
    import numpy as np

    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.dist.lp import dist_color, shard_arrays
    from kaminpar_tpu.graph import generators

    mesh = _mesh()
    g = generators.rmat_graph(10, 8, seed=2)
    dg = distribute_graph(g, mesh.size)
    import jax.numpy as jnp

    lab, dg = shard_arrays(mesh, dg, jnp.arange(dg.N, dtype=dg.dtype))
    colors = np.asarray(dist_color(mesh, dg))
    # reconstruct global edges and check properness (in the contiguous
    # block layout, global id == flat sharded slot id)
    deg = np.diff(np.asarray(g.row_ptr))
    u = np.repeat(np.arange(g.n), deg)
    v = np.asarray(g.col_idx)
    cu, cv = colors[u], colors[v]
    mask = u != v
    assert (cu[mask] != cv[mask]).all(), int((cu[mask] == cv[mask]).sum())


def test_dist_clp_refines():
    import numpy as np

    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.dist.lp import dist_clp_iterate, shard_arrays
    from kaminpar_tpu.dist.metrics import dist_edge_cut
    from kaminpar_tpu.graph import generators

    mesh = _mesh()
    g = generators.rgg2d_graph(1024, seed=5)
    k = 4
    rng = np.random.default_rng(5)
    part = rng.integers(0, k, g.n).astype(np.int32)
    dg = distribute_graph(g, mesh.size)
    import jax.numpy as jnp

    full = np.zeros(dg.N, dtype=np.int32)
    full[: g.n] = part
    part_dev, dg = shard_arrays(mesh, dg, jnp.asarray(full))
    W = int(np.asarray(g.node_w).sum())
    cap = jnp.full(k, int(np.ceil(W / k) * 1.1) + 1, dtype=dg.dtype)
    before = dist_edge_cut(mesh, part_dev, dg, k=k)
    out, moved = dist_clp_iterate(
        mesh, jax.random.PRNGKey(0), part_dev, dg, cap, num_labels=k
    )
    after = dist_edge_cut(mesh, out, dg, k=k)
    assert after <= before, (after, before)
    assert moved > 0
    bw = np.bincount(np.asarray(out)[np.asarray(dg.node_w) > 0], minlength=k,
                     weights=np.asarray(dg.node_w)[np.asarray(dg.node_w) > 0])
    assert (bw <= np.asarray(cap)).all()


def test_dist_best_moves_round():
    """BEST_MOVES strategy (dkaminpar.h:116-120): globally best movers per
    block, never exceeding caps."""
    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.dist.lp import dist_lp_round_best, shard_arrays
    from kaminpar_tpu.dist.metrics import dist_block_weights, dist_edge_cut
    from kaminpar_tpu.graph import generators

    mesh = _mesh()
    g = generators.rgg2d_graph(1024, seed=13)
    k = 4
    rng = np.random.default_rng(13)
    part = rng.integers(0, k, g.n).astype(np.int32)
    dg = distribute_graph(g, mesh.size)
    full = np.zeros(dg.N, dtype=np.int32)
    full[: g.n] = part
    part_dev, dg = shard_arrays(mesh, dg, jnp.asarray(full))
    W = int(np.asarray(g.node_w).sum())
    cap = jnp.full(k, int(np.ceil(W / k) * 1.1) + 1, dtype=dg.dtype)
    before = dist_edge_cut(mesh, part_dev, dg, k=k)
    bw0 = dist_block_weights(mesh, part_dev, dg, k=k)
    assert (bw0 <= np.asarray(cap)).all()
    out, moved = dist_lp_round_best(
        mesh, jax.random.PRNGKey(2), part_dev, dg, cap, num_labels=k
    )
    after = dist_edge_cut(mesh, out, dg, k=k)
    assert int(moved) > 0
    assert after < before, (after, before)
    bw = dist_block_weights(mesh, out, dg, k=k)
    assert (bw <= np.asarray(cap)).all(), bw


def test_dist_local_moves_round():
    """LOCAL_MOVES strategy (dkaminpar.h:116-120): eager commit of every
    positive-gain mover, caps restored by the rollback fixpoint."""
    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.dist.lp import dist_lp_round_local, shard_arrays
    from kaminpar_tpu.dist.metrics import dist_block_weights, dist_edge_cut
    from kaminpar_tpu.graph import generators

    mesh = _mesh()
    g = generators.rgg2d_graph(1024, seed=13)
    k = 4
    rng = np.random.default_rng(13)
    part = rng.integers(0, k, g.n).astype(np.int32)
    dg = distribute_graph(g, mesh.size)
    full = np.zeros(dg.N, dtype=np.int32)
    full[: g.n] = part
    part_dev, dg = shard_arrays(mesh, dg, jnp.asarray(full))
    W = int(np.asarray(g.node_w).sum())
    cap = jnp.full(k, int(np.ceil(W / k) * 1.1) + 1, dtype=dg.dtype)
    before = dist_edge_cut(mesh, part_dev, dg, k=k)
    out, moved = dist_lp_round_local(
        mesh, jax.random.PRNGKey(2), part_dev, dg, cap, num_labels=k
    )
    after = dist_edge_cut(mesh, out, dg, k=k)
    assert int(moved) > 0
    assert after < before, (after, before)
    bw = dist_block_weights(mesh, out, dg, k=k)
    assert (bw <= np.asarray(cap)).all(), bw


def test_shard_stats_aggregation():
    """Per-shard min/mean/max load table — the dist timer-aggregation analog
    (kaminpar-dist/timer.cc:106-173); totals must match the real graph."""
    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.dist.shard_stats import ShardStats, collect_graph_stats

    g = generators.rgg2d_graph(512, seed=3)
    P = 8
    dg = distribute_graph(g, P)
    st = collect_graph_stats(dg)
    assert int(np.sum(st._rows["owned_nodes"])) == g.n
    assert int(np.sum(st._rows["owned_edges"])) == g.m
    s = st.stats("owned_nodes")
    assert s["min"] <= s["mean"] <= s["max"]
    assert s["imb"] >= 1.0
    # ghosts/interface are bounded by what exists
    assert st.stats("ghost_nodes")["max"] <= g.n
    assert st.stats("interface_nodes")["max"] <= dg.n_loc
    txt = st.render()
    assert "owned_edges" in txt and "imb" in txt
    mr = st.machine_readable()
    # 4 per-row lines + the round-13 aggregate skew line
    assert mr.count("SHARDSTAT ") == 4
    assert mr.count("SHARDSTAT_SUMMARY") == 1

    # repeated record() accumulates (per-round phase counters)
    acc = ShardStats(2)
    acc.record("moves", [1, 2])
    acc.record("moves", [3, 4])
    assert acc.stats("moves") == {"min": 4.0, "mean": 5.0, "max": 6.0,
                                  "imb": 1.2}
    with pytest.raises(ValueError):
        acc.record("bad", [1, 2, 3])


def test_local_moves_swaps_between_at_cap_blocks():
    """The point of LOCAL_MOVES' eager semantics: freed capacity stays
    proposable, so two blocks at exact cap can still exchange nodes —
    BEST_MOVES (cap-respecting proposals) commits nothing here."""
    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.dist.lp import (
        dist_lp_round_best, dist_lp_round_local, shard_arrays,
    )
    from kaminpar_tpu.graph.csr import CSRGraph

    mesh = _mesh()
    # Two nodes joined by one edge, one per block, caps exactly 1.
    g = CSRGraph(np.array([0, 1, 2]), np.array([1, 0]))
    k = 2
    dg = distribute_graph(g, mesh.size)
    full = np.zeros(dg.N, dtype=np.int32)
    full[0], full[1] = 0, 1
    cap = jnp.ones(k, dtype=dg.dtype)

    part_dev, dgs = shard_arrays(mesh, dg, jnp.asarray(full))
    _, moved_best = dist_lp_round_best(
        mesh, jax.random.PRNGKey(0), part_dev, dgs, cap, num_labels=k
    )
    assert int(moved_best) == 0

    out, moved_local = dist_lp_round_local(
        mesh, jax.random.PRNGKey(0), part_dev, dgs, cap, num_labels=k
    )
    assert int(moved_local) > 0
    out = np.asarray(out)[:2]
    bw = np.bincount(out, minlength=k)
    assert (bw <= 1).all(), bw
