"""Distributed LP on a virtual 8-device CPU mesh (SURVEY §4: the JAX analog
of the reference's oversubscribed-MPI KaTestrophe testing)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from kaminpar_tpu.dist import distribute_graph, dist_lp_iterate, dist_lp_round
from kaminpar_tpu.dist.lp import shard_arrays
from kaminpar_tpu.graph import generators, metrics


def _mesh(num=8):
    devs = jax.devices()
    if len(devs) < num:
        pytest.skip(f"need {num} devices, have {len(devs)}")
    return Mesh(np.array(devs[:num]), ("nodes",))


def test_distribute_graph_layout():
    g = generators.grid2d_graph(10, 10)
    dg = distribute_graph(g, 4)
    assert dg.N > g.n and dg.N == 4 * dg.n_loc
    # Per-shard edge realness: weights of pads are 0; real edge weights sum
    # matches the original.
    assert int(np.asarray(dg.edge_w).sum()) == g.total_edge_weight
    assert int(np.asarray(dg.node_w).sum()) == g.total_node_weight
    # reconstruct global sources and check endpoints are real nodes
    eu = np.asarray(dg.edge_u).reshape(4, dg.m_loc)
    ew = np.asarray(dg.edge_w).reshape(4, dg.m_loc)
    ci = np.asarray(dg.col_idx).reshape(4, dg.m_loc)
    for s in range(4):
        real = ew[s] > 0
        assert np.all(ci[s][real] < g.n)
        assert np.all(eu[s][real] < dg.n_loc)


def test_dist_lp_clustering_round():
    mesh = _mesh()
    g = generators.grid2d_graph(16, 16)
    dg = distribute_graph(g, mesh.size)
    N = dg.N
    labels = jnp.arange(N, dtype=jnp.int32)
    labels, dg = shard_arrays(mesh, dg, labels)
    max_w = jnp.int32(8)

    out, moved = dist_lp_round(
        mesh, jax.random.key(0), labels, dg, max_w, num_labels=N
    )
    out = np.asarray(out)
    assert int(moved) > 0
    # cluster weights respect the cap
    w = np.bincount(out[: g.n], minlength=N)
    assert w.max() <= 8
    # pads never move
    assert np.all(out[g.n :] == np.arange(g.n, N))


def test_dist_lp_iterate_coarsens():
    mesh = _mesh()
    g = generators.rmat_graph(10, 8, seed=3)
    dg = distribute_graph(g, mesh.size)
    N = dg.N
    labels = jnp.arange(N, dtype=jnp.int32)
    labels, dg = shard_arrays(mesh, dg, labels)
    out, total = dist_lp_iterate(
        mesh, jax.random.key(1), labels, dg, jnp.int32(64), num_labels=N,
        num_rounds=5,
    )
    out = np.asarray(out)[: g.n]
    clusters = len(np.unique(out))
    assert clusters < 0.6 * g.n  # real coarsening happened
    w = np.bincount(np.asarray(out), minlength=N, weights=np.ones(g.n))
    assert w.max() <= 64


def test_rollback_cascade_keeps_feasibility():
    """A rolled-back out-move returns weight to its source cluster, which may
    itself tip overweight — the rollback must iterate to a fixpoint (review
    finding: single-pass rollback violated the cap on ~3% of seeds)."""
    mesh = _mesh()
    g = generators.rmat_graph(9, 6, seed=11)
    dg = distribute_graph(g, mesh.size)
    N = dg.N
    cap = 3
    for seed in range(20):
        labels = jnp.arange(N, dtype=jnp.int32)
        labels, dgs = shard_arrays(mesh, dg, labels)
        out, _ = dist_lp_iterate(
            mesh, jax.random.key(seed), labels, dgs, jnp.int32(cap),
            num_labels=N, num_rounds=3,
        )
        w = np.bincount(np.asarray(out)[: g.n], minlength=N)
        assert w.max() <= cap, f"seed {seed}: cluster weight {w.max()} > {cap}"


def test_dist_lp_refinement_improves_cut():
    mesh = _mesh()
    g = generators.grid2d_graph(20, 20)
    dg = distribute_graph(g, mesh.size)
    N = dg.N
    k = 4
    rng = np.random.default_rng(0)
    part = rng.integers(0, k, N).astype(np.int32)
    part[g.n :] = 0
    cut0 = metrics.edge_cut(g, part[: g.n])
    labels, dg = shard_arrays(mesh, dg, jnp.asarray(part))
    cap = jnp.full(k, int(1.1 * g.total_node_weight / k) + 8, dtype=jnp.int32)
    out, _ = dist_lp_iterate(
        mesh, jax.random.key(2), labels, dg, cap, num_labels=k,
        num_rounds=8, external_only=False,
    )
    out = np.asarray(out)[: g.n]
    cut1 = metrics.edge_cut(g, out)
    assert cut1 < cut0  # refinement reduces the cut
    w = np.bincount(out, weights=np.ones(g.n), minlength=k)
    assert w.max() <= int(1.1 * g.total_node_weight / k) + 8
