"""Unified resilience layer tests (ISSUE 13): the typed taxonomy + the
ONE classifier, the seed-keyed fault-injection harness, the circuit
breaker state machine, and the chaos matrix the acceptance criteria
name — for every fault class x injection point, the engine recovers
without wedging (drain completes), deterministic demotions are
BIT-IDENTICAL to the healthy fallback path, breaker/demotion/injection
counters match the armed plan exactly, and a post-cooldown half-open
probe restores the primary path.

Plus the round-17 satellites: bounded shutdown drain with a hung worker,
facade-boundary CSR validation rejections, and queue admission under
concurrent overload.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.presets import create_context_by_preset_name
from kaminpar_tpu.resilience import breakers as rbreakers
from kaminpar_tpu.resilience import faults as rfaults
from kaminpar_tpu.resilience.breakers import BreakerRegistry, CircuitBreaker
from kaminpar_tpu.resilience.errors import (
    BackendUnavailable,
    CapacityExceeded,
    CompileTimeout,
    ExecuteFault,
    GraphValidationError,
    PoisonedCell,
    ResilienceError,
    WorkerHung,
    classify,
    is_control_flow,
)
from kaminpar_tpu.resilience.faults import FaultPlan, injected_faults
from kaminpar_tpu.serve.engine import PartitionEngine
from kaminpar_tpu.serve.errors import QueueFullError


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    """Every test starts with a disarmed harness and fresh registries —
    the process-global breaker registry must not leak trips between
    tests (the same reason sync_stats budgets reset per pipeline)."""
    rfaults.reset()
    rbreakers.reset_global_registry()
    yield
    rfaults.reset()
    rbreakers.reset_global_registry()


def _rmat(seed, scale=7):
    return generators.rmat_graph(scale, edge_factor=4, seed=seed)


def _engine(threshold=3, cooldown=30.0, execute_timeout=0.0, **serve):
    ctx = create_context_by_preset_name("serve")
    ctx.resilience.breaker_threshold = threshold
    ctx.resilience.breaker_cooldown_s = cooldown
    ctx.resilience.execute_timeout_s = execute_timeout
    serve.setdefault("warm_ladder", ())
    serve.setdefault("warm_ks", ())
    serve.setdefault("max_batch", 4)
    serve.setdefault("queue_bound", 16)
    return PartitionEngine(ctx, **serve)


# ---------------------------------------------------------------------------
# Taxonomy + classifier
# ---------------------------------------------------------------------------


def test_classify_maps_adhoc_exceptions_to_failure_classes():
    assert isinstance(classify(MemoryError("oom")), CapacityExceeded)
    assert isinstance(
        classify(RuntimeError("RESOURCE_EXHAUSTED: out of memory")),
        CapacityExceeded,
    )
    assert isinstance(
        classify(RuntimeError("UNAVAILABLE: failed to initialize backend")),
        BackendUnavailable,
    )
    assert isinstance(
        classify(TimeoutError("x"), site="warmup_compile"), CompileTimeout
    )
    assert isinstance(classify(TimeoutError("x"), site="engine"), ExecuteFault)
    generic = classify(ZeroDivisionError("kernel bug"), site="engine")
    assert isinstance(generic, ExecuteFault)
    assert generic.__cause__.__class__ is ZeroDivisionError
    assert generic.failure_class == "execute-fault"


def test_classify_idempotent_and_control_flow_passthrough():
    typed = ExecuteFault("already typed", site="x")
    assert classify(typed) is typed
    full = QueueFullError(0.5)
    assert is_control_flow(full)
    assert not is_control_flow(RuntimeError("boom"))
    # The serve CapacityError (round 16 preflight) wraps into the taxonomy.
    from kaminpar_tpu.serve.errors import CapacityError

    wrapped = classify(CapacityError(100, 10))
    assert isinstance(wrapped, CapacityExceeded)


def test_graph_validation_error_is_valueerror():
    err = GraphValidationError("bad input")
    assert isinstance(err, ValueError) and isinstance(err, ResilienceError)
    assert err.failure_class == "graph-validation"


# ---------------------------------------------------------------------------
# Fault plan: parsing + seed-keyed replayability
# ---------------------------------------------------------------------------


def test_fault_plan_parse():
    plan = FaultPlan.parse(
        "execute@lanestack:execute-fault:n=2,"
        "queue-admit:capacity-exceeded:after=1,"
        "readback:execute-fault:p=0.5:delay=0.1",
        seed=7,
    )
    assert len(plan.specs) == 3
    a, b, c = plan.specs
    assert (a.point, a.site, a.error, a.count) == (
        "execute", "lanestack", "execute-fault", 2
    )
    assert (b.point, b.after, b.count) == ("queue-admit", 1, 1)
    assert (c.p, c.delay_s) == (0.5, 0.1)
    with pytest.raises(ValueError, match="injection point"):
        FaultPlan.parse("bogus:execute-fault")
    with pytest.raises(ValueError, match="failure class"):
        FaultPlan.parse("execute:bogus-class")


def test_fault_plan_parse_rejects_malformed_values():
    """Round-19 satellite: malformed plans raise a typed ValueError
    NAMING the offending spec at arm time — silent partial arming would
    let a chaos run claim coverage its plan never delivered."""
    # Non-numeric values, each naming the key and the spec.
    with pytest.raises(ValueError, match=r"malformed n=.*'abc'"):
        FaultPlan.parse("execute:execute-fault:n=abc")
    with pytest.raises(ValueError, match=r"malformed after="):
        FaultPlan.parse("execute:execute-fault:after=1.5x")
    with pytest.raises(ValueError, match=r"malformed p="):
        FaultPlan.parse("execute:execute-fault:p=lots")
    with pytest.raises(ValueError, match=r"malformed delay="):
        FaultPlan.parse("execute:execute-fault:delay=soon")
    # Out-of-range values.
    with pytest.raises(ValueError, match=r"p=1\.5 outside"):
        FaultPlan.parse("execute:execute-fault:p=1.5")
    with pytest.raises(ValueError, match=r"n=-1 must be >= 0"):
        FaultPlan.parse("execute:execute-fault:n=-1")
    with pytest.raises(ValueError, match=r"after=-2 must be >= 0"):
        FaultPlan.parse("execute:execute-fault:after=-2")
    with pytest.raises(ValueError, match="unknown fault-spec key"):
        FaultPlan.parse("execute:execute-fault:bogus=1")
    # The offending SPEC rides the message (a multi-spec plan must name
    # which entry is broken).
    with pytest.raises(ValueError, match=r"execute:execute-fault:n=zz"):
        FaultPlan.parse(
            "readback:execute-fault:n=1,execute:execute-fault:n=zz"
        )


def test_fault_plan_parse_rejects_duplicate_specs():
    """An EXACT copy of a spec could never add a firing — rejected at
    arm time, not silently carried.  Same-(point, site, error) specs
    with different firing parameters are legal STAGED plans (the
    matcher falls through exhausted/after-gated specs)."""
    with pytest.raises(ValueError, match="duplicate fault spec"):
        FaultPlan.parse(
            "execute:execute-fault:n=1,execute:execute-fault:n=1"
        )
    with pytest.raises(ValueError, match="duplicate fault spec"):
        FaultPlan.parse(
            "execute@site:execute-fault,execute@site:execute-fault"
        )
    # Staged plan: fire at hit 1 and again at hit 11 — NOT a duplicate.
    plan = FaultPlan.parse(
        "execute:execute-fault:n=1,execute:execute-fault:after=10:n=1"
    )
    assert len(plan.specs) == 2
    # Different site or error class: NOT duplicates either.
    plan = FaultPlan.parse(
        "execute@a:execute-fault,execute@b:execute-fault,"
        "execute@a:capacity-exceeded"
    )
    assert len(plan.specs) == 3


def test_fault_injection_counts_and_site_filter():
    with injected_faults("execute@right:execute-fault:n=2") as plan:
        rfaults.maybe_inject("execute", site="wrong-site")  # filtered
        with pytest.raises(ExecuteFault) as ei:
            rfaults.maybe_inject("execute", site="right-site")
        assert ei.value.injected and ei.value.site == "right-site"
        with pytest.raises(ExecuteFault):
            rfaults.maybe_inject("execute", site="right-site")
        rfaults.maybe_inject("execute", site="right-site")  # n=2 exhausted
        assert plan.specs[0].injected == 2
    snap = rfaults.snapshot()
    assert snap["points"]["execute"] == {"hits": 4, "injected": 2}


def test_seeded_coin_is_replayable():
    def decisions(seed):
        plan = FaultPlan.parse("readback:execute-fault:p=0.4:n=0", seed=seed)
        out = []
        with injected_faults(plan):
            for _ in range(64):
                try:
                    rfaults.maybe_inject("readback")
                    out.append(0)
                except ExecuteFault:
                    out.append(1)
        return out

    a, b = decisions(7), decisions(7)
    assert a == b, "same seed must replay the same injection sequence"
    c = decisions(8)
    assert a != c, "a different seed must reshuffle the sequence"
    assert 5 < sum(a) < 60  # the coin is actually probabilistic


# ---------------------------------------------------------------------------
# Circuit breaker state machine
# ---------------------------------------------------------------------------


def test_breaker_trip_cooldown_halfopen_close():
    br = CircuitBreaker(("x", ()), threshold=2, cooldown_s=0.15)
    assert br.allow() and br.state == "closed"
    assert not br.record_failure()
    assert br.record_failure(), "threshold-th failure must trip"
    assert br.state == "open" and not br.allow()
    assert br.retry_after_s() > 0
    time.sleep(0.16)
    assert br.allow(), "post-cooldown: the half-open probe is admitted"
    assert br.state == "half-open"
    assert not br.allow(), "only ONE probe while half-open"
    assert br.record_success(), "probe success closes (reports restoration)"
    assert br.state == "closed" and br.allow()


def test_breaker_halfopen_failure_reopens():
    br = CircuitBreaker(("x", ()), threshold=1, cooldown_s=0.1)
    br.record_failure()
    time.sleep(0.11)
    assert br.allow()
    assert br.record_failure(), "probe failure re-trips"
    assert br.state == "open" and not br.allow()


def test_breaker_retry_after_in_half_open():
    """While a half-open probe is in flight, retry_after_s hints the
    probe deadline instead of 0 — a 0 would make rejected clients
    hot-spin against repeated rejections until the probe resolves."""
    br = CircuitBreaker(("x", ()), threshold=1, cooldown_s=0.2)
    br.record_failure()
    time.sleep(0.21)
    assert br.allow()  # the probe
    assert br.state == "half-open"
    assert br.retry_after_s() > 0


def test_breaker_stale_probe_renewal():
    """A probe whose caller never reports back must not pin the path
    demoted forever — a new probe is granted after one more cooldown."""
    br = CircuitBreaker(("x", ()), threshold=1, cooldown_s=0.1)
    br.record_failure()
    time.sleep(0.11)
    assert br.allow()  # probe 1, never reported
    assert not br.allow()
    time.sleep(0.11)
    assert br.allow()  # stale -> probe 2
    assert br.probes == 2


def test_breaker_halfopen_probe_race_burns_one_slot():
    """Round-19 satellite: N threads racing a cooled-down breaker must
    burn exactly ONE probe slot — the open->half-open transition and the
    probe claim are one locked step (a barrier lines the threads up on
    the same instant)."""
    br = CircuitBreaker(("x", ()), threshold=1, cooldown_s=0.05)
    br.record_failure()
    time.sleep(0.06)  # cooldown elapsed: the next allow() opens the race
    n = 8
    barrier = threading.Barrier(n)
    grants: list = []
    lock = threading.Lock()

    def racer():
        barrier.wait()
        ok = br.allow()
        with lock:
            grants.append(ok)

    threads = [threading.Thread(target=racer) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert grants.count(True) == 1, grants
    assert br.probes == 1
    # The claimed probe stays exclusive until an outcome is recorded...
    assert not br.allow()
    assert not br.would_allow()
    # ...and its success releases the claim by closing the breaker.
    assert br.record_success()
    assert br.allow()


def test_breaker_would_allow_peek_vs_claim():
    """would_allow() must stay a pure peek while a claimed probe is in
    flight (False — the slot is taken), and would_allow(claim=True) is
    the consuming twin of allow()."""
    br = CircuitBreaker(("x", ()), threshold=1, cooldown_s=0.05)
    br.record_failure()
    time.sleep(0.06)
    # Peek does not consume: repeated peeks all say "available".
    assert br.would_allow() and br.would_allow()
    assert br.probes == 0
    # The claiming form consumes the one slot.
    assert br.would_allow(claim=True)
    assert br.probes == 1
    assert not br.would_allow()
    assert not br.would_allow(claim=True)
    # A recorded outcome (failure) re-opens; after cooldown the cycle
    # restarts with a fresh slot.
    br.record_failure()
    time.sleep(0.06)
    assert br.would_allow()
    assert br.allow()
    assert br.probes == 2


def test_lp_pallas_probe_reserved_for_guarded_callers():
    """Only probe=True callers (the clusterer's guarded dispatch) may
    consume the lp_pallas half-open probe: an unguarded refiner handed a
    still-broken pallas kernel would crash the whole partition with
    nobody reporting the probe outcome back."""
    from kaminpar_tpu.ops import lp as lp_ops
    from kaminpar_tpu.ops.pallas_lp import select_lp_ops

    reg = rbreakers.global_registry()
    br = reg.get("lp_pallas")
    br.threshold = 1
    br.cooldown_s = 0.1
    br.record_failure()
    time.sleep(0.11)
    # Unguarded selection (refiners): demoted to XLA, probe NOT consumed.
    ops = select_lp_ops("pallas")
    assert ops[0] is lp_ops.lp_iterate_bucketed
    assert br.state == "open" and br.probes == 0
    # Guarded selection (clusterer): granted the probe.
    ops = select_lp_ops("pallas", probe=True)
    assert ops[0] is not lp_ops.lp_iterate_bucketed
    assert br.state == "half-open" and br.probes == 1


def test_engine_shutdown_disarms_its_fault_plan():
    """A fault plan armed from the engine's context must not outlive the
    engine — injections leaking into unrelated pipelines in the process
    would be a chaos harness attacking production."""
    ctx = create_context_by_preset_name("serve")
    ctx.resilience.fault_plan = "queue-admit:capacity-exceeded:n=0"
    eng = PartitionEngine(ctx, warm_ladder=(), warm_ks=(), queue_bound=8)
    eng.start(warmup=False)
    assert rfaults.active_plan() is not None
    try:
        with pytest.raises(CapacityExceeded):
            eng.submit(_rmat(seed=1), 4)
    finally:
        eng.shutdown(drain=True)
    assert rfaults.active_plan() is None
    rfaults.maybe_inject("queue-admit", site="post-shutdown")  # no raise


def test_registry_demotion_warns_once():
    reg = BreakerRegistry(threshold=1, cooldown_s=0.1)
    import warnings

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        reg.record_demotion("lanestack", "test")
        reg.record_demotion("lanestack", "test")
    assert len([w for w in caught if "degrading" in str(w.message)]) == 1
    assert reg.demotions() == {"lanestack": 2}


# ---------------------------------------------------------------------------
# Chaos matrix: engine recovery per fault class x injection point
# ---------------------------------------------------------------------------


def test_chaos_execute_fault_typed_rejection_and_recovery():
    """Injected execute faults reject exactly the planned requests with
    the typed error; the engine keeps serving, drain completes, and the
    injection/breaker counters match the plan exactly."""
    eng = _engine().start(warmup=False)
    outcomes = []
    try:
        with injected_faults("execute@engine_request:execute-fault:n=2"):
            for i in range(4):
                try:
                    eng.partition(_rmat(seed=10 + i), 4)
                    outcomes.append("ok")
                except ExecuteFault as exc:
                    assert exc.injected
                    outcomes.append("fault")
            snap = rfaults.snapshot()
    finally:
        eng.shutdown(drain=True)
    assert outcomes == ["fault", "fault", "ok", "ok"]
    assert snap["points"]["execute"]["injected"] == 2
    stats = eng.stats()
    assert stats["failed"] == 2 and stats["completed"] == 2
    cell = [
        br for name, br in
        stats["resilience"]["engine"]["breakers"].items()
        if name.startswith("cell|")
    ]
    assert len(cell) == 1
    assert cell[0]["failures"] == 2 and cell[0]["state"] == "closed"


def test_chaos_poisoned_cell_fast_fail_and_halfopen_restore():
    """Enough execute faults in one cell open its breaker: new submits
    fast-fail with typed PoisonedCell (+ retry_after) instead of wedging
    the queue, and the post-cooldown half-open probe restores the cell."""
    eng = _engine(threshold=2, cooldown=0.3).start(warmup=False)
    try:
        with injected_faults("execute@engine_request:execute-fault:n=2"):
            for i in range(2):
                with pytest.raises(ExecuteFault):
                    eng.partition(_rmat(seed=20 + i), 4)
        with pytest.raises(PoisonedCell) as ei:
            eng.partition(_rmat(seed=30), 4)
        assert ei.value.retry_after_s > 0
        assert eng.stats_.counter("rejected_poisoned") == 1
        time.sleep(0.35)
        # Half-open probe (injection plan exhausted): succeeds, restores.
        p = eng.partition(_rmat(seed=31), 4)
        assert p.size > 0
        breakers = eng.stats()["resilience"]["engine"]["breakers"]
        cell = next(v for k, v in breakers.items() if k.startswith("cell|"))
        assert cell["state"] == "closed" and cell["trips"] == 1
        assert cell["probes"] == 1
        # And the cell serves normally again.
        eng.partition(_rmat(seed=32), 4)
    finally:
        eng.shutdown(drain=True)


def test_chaos_lanestack_demotion_bit_identical_and_restore():
    """A lanestack execute fault demotes the batch to the per-graph loop
    — BIT-IDENTICAL to sequential runs (the deterministic-demotion
    acceptance bar) — trips the per-cell breaker at threshold 1, skips
    the doomed stacked attempt while open, and the post-cooldown
    half-open probe restores the stacked path."""
    import warnings

    # Cooldown far above the test's wall so the open window is actually
    # observable; the restore round rewinds _open_until instead of
    # sleeping (CPU solves take seconds — real time is not controllable).
    eng = _engine(threshold=1, cooldown=300.0, lane_stack="on")
    eng.pause()
    eng.start(warmup=False)
    try:
        # Same seed -> same shape cell (the test_lanestack idiom): every
        # round below must land in the SAME cell as the tripped breaker.
        solver = KaMinPar(ctx="serve")
        solver.set_graph(_rmat(100, scale=8))
        seq = solver.compute_partition(4, 0.03)
        with injected_faults("execute@lanestack:execute-fault:n=1"):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                futs = [eng.submit(_rmat(100, scale=8), 4) for _ in range(2)]
                eng.resume()
                parts = [f.result(timeout=600).partition for f in futs]
        # Demoted batch == healthy per-graph path == sequential runs.
        for part in parts:
            assert np.array_equal(part, seq)
        # Batch formation may split a round into 1-request batches (the
        # 2 ms batch window races submit timing under load), so batch-
        # granular counters are lower-bounded; breaker STATE transitions
        # are the deterministic contract.
        stats = eng.stats()
        assert stats["lanestacked_batches"] == 0
        assert stats["lanestack_fallbacks"] >= 1
        ls = next(
            v for k, v in
            stats["resilience"]["engine"]["breakers"].items()
            if k.startswith("lanestack|")
        )
        assert ls["state"] == "open" and ls["trips"] == 1
        assert stats["resilience"]["engine"]["demotions"]["lanestack"] >= 1
        fallbacks_after_trip = stats["lanestack_fallbacks"]

        # While open: the stacked attempt is skipped (demotion, no probe).
        eng.pause()
        futs = [eng.submit(_rmat(100, scale=8), 4) for _ in range(2)]
        eng.resume()
        for f in futs:
            f.result(timeout=600)
        assert eng.stats_.counter("lanestacked_batches") == 0
        assert eng.stats_.counter("lanestack_fallbacks") > fallbacks_after_trip

        # "Post-cooldown": rewind the open window, then the half-open
        # probe runs stacked and restores the primary path.
        br_obj = next(
            v for k, v in eng.breakers._breakers.items()
            if k[0] == "lanestack"
        )
        with br_obj._lock:
            br_obj._open_until = time.monotonic() - 1.0
        eng.pause()
        futs = [eng.submit(_rmat(100, scale=8), 4) for _ in range(2)]
        eng.resume()
        for f in futs:
            f.result(timeout=600)
        stats = eng.stats()
        assert stats["lanestacked_batches"] >= 1
        ls = next(
            v for k, v in
            stats["resilience"]["engine"]["breakers"].items()
            if k.startswith("lanestack|")
        )
        assert ls["state"] == "closed"
        assert stats["resilience"]["engine"]["restorations"][
            "lanestack"
        ] == 1
    finally:
        eng.shutdown(drain=True)


def test_chaos_ip_device_demotion_bit_identical():
    """With every device-pool dispatch faulted, the run demotes to the
    host pool — bit-identical to a run configured ip_backend="host"
    from the start (the injection fires before the device path draws
    from the host RNG stream), and counted on the global registry."""
    import warnings

    def run(backend, inject):
        ctx = create_context_by_preset_name("default")
        ctx.initial_partitioning.ip_backend = backend
        solver = KaMinPar(ctx)
        solver.set_graph(_rmat(seed=5, scale=7))
        if inject:
            with injected_faults("execute@ip_device:execute-fault:n=0"):
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    return solver.compute_partition(4, 0.03)
        return solver.compute_partition(4, 0.03)

    host = run("host", inject=False)
    demoted = run("device", inject=True)
    assert np.array_equal(host, demoted)
    demos = rbreakers.global_registry().snapshot()["demotions"]
    assert demos.get("ip_device", 0) >= 1


def test_chaos_device_decode_demotion_bit_identical():
    """A faulted compressed-view build demotes the run to the dense
    path — bit-identical by the round-14 contract — and opens the
    device_decode breaker after enough repeats."""
    import warnings

    def run(device_decode, inject):
        ctx = create_context_by_preset_name("default")
        ctx.compression.enabled = True
        ctx.compression.device_decode = device_decode
        solver = KaMinPar(ctx)
        solver.set_graph(_rmat(seed=6, scale=7))
        if inject:
            with injected_faults("execute@device_decode:execute-fault:n=0"):
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    return solver.compute_partition(4, 0.03)
        return solver.compute_partition(4, 0.03)

    dense = run("off", inject=False)
    demoted = run("finest", inject=True)
    assert np.array_equal(dense, demoted)
    demos = rbreakers.global_registry().snapshot()["demotions"]
    assert demos.get("device_decode", 0) >= 1


def test_chaos_pallas_demotion_bit_identical():
    """A faulted Pallas LP dispatch retries in-flight on the XLA twin
    (bit-identical by the round-5 contract) and records the failure on
    the lp_pallas breaker; with the breaker tripped, later selections
    demote at the dispatch point."""
    import warnings

    def run(kernel, inject):
        ctx = create_context_by_preset_name("default")
        ctx.coarsening.lp.lp_kernel = kernel
        ctx.refinement.lp.lp_kernel = kernel
        # Engage coarsening at small n (the clusterer owns the pallas
        # dispatch + in-flight retry); the default C=2000 would skip LP
        # clustering entirely at this scale.
        ctx.coarsening.contraction_limit = 10
        solver = KaMinPar(ctx)
        solver.set_graph(_rmat(seed=8, scale=6))
        if inject:
            with injected_faults("execute@lp_pallas:execute-fault:n=1"):
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    return solver.compute_partition(2, 0.03)
        return solver.compute_partition(2, 0.03)

    xla = run("xla", inject=False)
    demoted = run("pallas", inject=True)
    assert np.array_equal(xla, demoted)
    reg = rbreakers.global_registry()
    br = reg.get("lp_pallas").snapshot()
    assert br["failures"] == 1
    assert reg.snapshot()["demotions"].get("lp_pallas", 0) >= 1


def test_pallas_retry_survives_donated_state():
    """The iterate twins donate their state carry: a pallas failure AFTER
    dispatch has consumed the buffer, so the in-flight XLA retry must run
    from a pre-attempt copy — re-passing the donated state would die on
    'Array has been deleted' instead of recovering."""
    import jax.numpy as jnp

    from kaminpar_tpu.coarsening.lp_clusterer import LPClustering
    from kaminpar_tpu.context import LabelPropagationContext

    clus = LPClustering(LabelPropagationContext(lp_kernel="pallas"))

    def xla_it(state, inc):
        return state + inc

    def pallas_it(state, inc):
        state.delete()  # emulate donation consuming the buffer...
        raise RuntimeError("pallas died after dispatch")

    out = clus._run_iterate(
        pallas_it, xla_it, jnp.arange(4), jnp.int32(1)
    )
    assert np.array_equal(np.asarray(out), np.arange(4) + 1)
    br = rbreakers.global_registry().get("lp_pallas").snapshot()
    assert br["failures"] == 1


def test_halfopen_cell_probe_served_stacked_closes_breaker():
    """A half-open cell probe served by the lane-stacked path must close
    the cell breaker — otherwise a healthy cell whose probes always
    succeed stays pinned at one request per cooldown."""
    eng = _engine(threshold=1, cooldown=300.0, lane_stack="on")
    eng.start(warmup=False)
    try:
        cell_key = None
        cbr = None
        # Trip the cell breaker directly (the state machine is unit-tested
        # above; this test is about WHO reports the probe outcome).
        from kaminpar_tpu.serve.batching import shape_cell

        g = _rmat(100, scale=8)
        cell = shape_cell(g, 4)
        cell_key = (cell.n_bucket, cell.m_bucket, cell.k)
        cbr = eng.breakers.get("cell", cell_key)
        cbr.record_failure()
        assert cbr.state == "open"
        with cbr._lock:
            cbr._open_until = time.monotonic() - 1.0
        p = eng.partition(g, 4)  # the half-open probe, served stacked
        assert p.size > 0
        assert eng.stats_.counter("lanestacked_batches") == 1
        assert cbr.state == "closed"
    finally:
        eng.shutdown(drain=True)


def test_fault_plan_disarmed_when_start_fails(monkeypatch):
    """start() failing after arming the context's fault plan must disarm
    it — shutdown's disarm is unreachable for a never-running engine."""
    ctx = create_context_by_preset_name("serve")
    ctx.resilience.fault_plan = "queue-admit:capacity-exceeded:n=0"
    eng = PartitionEngine(ctx, warm_ladder=(), warm_ks=(), queue_bound=8)
    monkeypatch.setattr(
        eng, "_resolve_capacity_ceiling",
        lambda: (_ for _ in ()).throw(RuntimeError("init died")),
    )
    with pytest.raises(RuntimeError, match="init died"):
        eng.start(warmup=False)
    assert rfaults.active_plan() is None
    rfaults.maybe_inject("queue-admit", site="post-failed-start")  # no raise


def test_chaos_queue_admit_fault_typed():
    eng = _engine().start(warmup=False)
    try:
        with injected_faults("queue-admit:capacity-exceeded:n=1"):
            with pytest.raises(CapacityExceeded) as ei:
                eng.submit(_rmat(seed=40), 4)
            assert ei.value.injected
            fut = eng.submit(_rmat(seed=41), 4)
            assert fut.result(timeout=600).partition.size > 0
    finally:
        eng.shutdown(drain=True)


def test_chaos_warmup_fault_contained():
    """A warmup-point fault degrades the engine to cold-start serving —
    start() completes, the fault is counted, requests still serve."""
    import warnings

    eng = _engine(warm_ladder=(64,), warm_ks=(2,))
    with injected_faults("warmup:backend-unavailable:n=1"):
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            eng.start(warmup=True)
    try:
        assert eng.running
        assert eng.stats_.counter("warmup_faults") == 1
        assert any("warmup" in str(w.message) for w in caught)
        p = eng.partition(_rmat(seed=50), 4)
        assert p.size > 0
    finally:
        eng.shutdown(drain=True)


def test_chaos_readback_fault_classified():
    """A readback-point fault inside the pipeline surfaces as the typed
    error through the engine's classifier and does not wedge drain."""
    eng = _engine().start(warmup=False)
    try:
        with injected_faults("readback:execute-fault:n=1:after=2"):
            with pytest.raises(ResilienceError):
                eng.partition(_rmat(seed=60), 4)
        p = eng.partition(_rmat(seed=61), 4)
        assert p.size > 0
    finally:
        eng.shutdown(drain=True)


def test_watchdog_times_out_hung_execute():
    """An execute overrunning the watchdog deadline has its future
    force-resolved with a typed ExecuteFault naming the watchdog, its
    cell breaker records the failure, and a dossier with the stack tail
    is captured; the engine keeps serving afterwards."""
    eng = _engine(execute_timeout=0.15).start(warmup=False)
    try:
        with injected_faults(
            "execute@engine_request:execute-fault:n=1:delay=0.8"
        ):
            fut = eng.submit(_rmat(seed=70), 4)
            with pytest.raises(ExecuteFault, match="watchdog"):
                fut.result(timeout=600)
        deadline = time.monotonic() + 5
        while eng.stats_.counter("watchdog_timeouts") == 0:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert eng.stats_.counter("watchdog_timeouts") == 1
        wd = eng.watchdog.snapshot()
        assert wd["fired"] == 1
        assert eng.watchdog.dossiers[0]["stack_tail"]
        # One observed hang TRIPS the cell breaker outright (each further
        # probe would wedge the dispatcher for a full deadline): the next
        # same-cell submit fast-fails with PoisonedCell.
        cbr = next(
            v for k, v in eng.breakers._breakers.items() if k[0] == "cell"
        )
        assert cbr.state == "open" and cbr.trips == 1
        with pytest.raises(PoisonedCell):
            eng.submit(_rmat(seed=70), 4)
        # Recovery: rewind the cooldown and serve the half-open probe.
        # The 0.15s deadline exists to catch the injected 0.8s hang
        # deterministically; a real CPU solve is slower than that, so
        # disarm it for the probe (deployments tune above their p99).
        eng.resilience.execute_timeout_s = 0.0
        with cbr._lock:
            cbr._open_until = time.monotonic() - 1.0
        p = eng.partition(_rmat(seed=70), 4)
        assert p.size > 0
        assert cbr.state == "closed"
    finally:
        eng.shutdown(drain=True)


def test_quality_fast_tier_and_capacity_demotion():
    """quality="fast" serves from the trimmed solver; capacity-class
    execute failures trip the per-cell quality breaker and demote later
    strong requests to the fast tier (counted + reversible)."""
    eng = _engine(threshold=2, cooldown=30.0).start(warmup=False)
    try:
        p = eng.partition(_rmat(seed=80), 4, quality="fast")
        assert p.size > 0
        with injected_faults("execute@engine_request:capacity-exceeded:n=2"):
            for i in range(2):
                with pytest.raises(CapacityExceeded):
                    eng.partition(_rmat(seed=81 + i), 4)
        import warnings

        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            p = eng.partition(_rmat(seed=83), 4)  # strong -> demoted
        assert p.size > 0
        assert eng.stats_.counter("demoted_quality") == 1
        assert any("quality_strong" in str(w.message) for w in caught)
        stats = eng.stats()
        assert stats["resilience"]["engine"]["demotions"][
            "quality_strong"
        ] == 1
        with pytest.raises(ValueError, match="quality"):
            eng.submit(_rmat(seed=84), 4, quality="bogus")
    finally:
        eng.shutdown(drain=True)


# ---------------------------------------------------------------------------
# Satellite: bounded shutdown drain with a dead/hung worker
# ---------------------------------------------------------------------------


def test_shutdown_bounded_drain_force_resolves_hung_worker():
    eng = _engine().start(warmup=False)
    release = threading.Event()
    started = threading.Event()
    original = eng._solver.compute_partition

    def _hang(*args, **kwargs):
        started.set()
        release.wait(30.0)
        return original(*args, **kwargs)

    eng._solver.compute_partition = _hang
    try:
        fut = eng.submit(_rmat(seed=90), 4)
        fut2 = eng.submit(_rmat(seed=91), 8)  # different cell: stays queued
        assert started.wait(30.0)
        t0 = time.monotonic()
        eng.shutdown(drain=True, timeout_s=0.3)
        assert time.monotonic() - t0 < 5.0, "drain must be bounded"
        with pytest.raises(WorkerHung):
            fut.result(timeout=1.0)
        with pytest.raises(WorkerHung):
            fut2.result(timeout=1.0)
        assert eng.stats_.counter("worker_hung") == 2
        assert not eng.running
    finally:
        release.set()


# ---------------------------------------------------------------------------
# Satellite: CSR ingestion hardening at the facade boundary
# ---------------------------------------------------------------------------


class TestGraphValidation:
    def _solver(self):
        return KaMinPar(ctx="default")

    def test_valid_graph_accepted(self):
        s = self._solver()
        s.copy_graph(
            np.array([0, 1, 2]), np.array([1, 0]),
            np.array([1, 1]), np.array([1, 1]),
        )
        assert s.graph is not None and s.graph.n == 2

    def test_rejects_nonmonotone_row_ptr(self):
        with pytest.raises(GraphValidationError, match="non-monotone"):
            self._solver().copy_graph(np.array([0, 2, 1, 4]),
                                      np.array([1, 2, 0, 0]))

    def test_rejects_bad_row_ptr_origin(self):
        with pytest.raises(GraphValidationError, match=r"row_ptr\[0\]"):
            self._solver().copy_graph(np.array([1, 2]), np.array([0]))

    def test_rejects_row_ptr_tail_mismatch(self):
        with pytest.raises(GraphValidationError, match=r"row_ptr\[-1\]"):
            self._solver().copy_graph(np.array([0, 1, 3]), np.array([1, 0]))

    def test_rejects_out_of_range_columns(self):
        with pytest.raises(GraphValidationError, match="out of range"):
            self._solver().copy_graph(np.array([0, 1, 2]), np.array([1, 9]))
        with pytest.raises(GraphValidationError, match="out of range"):
            self._solver().copy_graph(np.array([0, 1, 2]), np.array([-1, 0]))

    def test_rejects_negative_weights(self):
        with pytest.raises(GraphValidationError, match="negative edge"):
            self._solver().copy_graph(
                np.array([0, 1, 2]), np.array([1, 0]),
                None, np.array([1, -3]),
            )
        with pytest.raises(GraphValidationError, match="negative node"):
            self._solver().copy_graph(
                np.array([0, 1, 2]), np.array([1, 0]),
                np.array([-1, 1]), None,
            )

    def test_rejects_weight_shape_mismatch(self):
        with pytest.raises(GraphValidationError, match="shape"):
            self._solver().copy_graph(
                np.array([0, 1, 2]), np.array([1, 0]), np.array([1, 1, 1]),
            )

    def test_rejects_overflowing_total_weight(self):
        big = np.array([np.iinfo(np.int32).max, 2], dtype=np.int64)
        with pytest.raises(GraphValidationError, match="overflows int32"):
            self._solver().copy_graph(
                np.array([0, 1, 2]), np.array([1, 0]), big, None,
            )

    def test_rejects_overflow_on_64bit_build_exactly(self):
        """The total-weight sum must be exact: an int64 accumulator wraps
        modulo 2**64 and can NEVER exceed the 64-bit id_max, making the
        check dead for 64-bit builds (and wrapped totals pass 32-bit)."""
        from kaminpar_tpu.graph.csr import validate_csr_input

        huge = np.array([1 << 62, 1 << 62, 1 << 62, 1 << 62],
                        dtype=np.int64)
        with pytest.raises(GraphValidationError, match="overflows int64"):
            validate_csr_input(
                np.array([0, 1, 2, 3, 4]), np.array([1, 0, 3, 2]),
                huge, None, use_64bit=True,
            )

    def test_rejects_float_weights(self):
        """Float weights would be silently truncated by the index-typed
        cast — a different weighted problem, not a rounding detail."""
        with pytest.raises(GraphValidationError, match="integer"):
            self._solver().copy_graph(
                np.array([0, 1, 2]), np.array([1, 0]),
                np.array([1.9, 2.9]), None,
            )

    def test_rejects_nonmonotone_unsigned_row_ptr(self):
        """np.diff on an unsigned row_ptr WRAPS instead of going negative
        — the validation must diff in a signed dtype or the exact
        malformed input it exists for passes."""
        with pytest.raises(GraphValidationError, match="non-monotone"):
            self._solver().copy_graph(
                np.array([0, 2, 1, 4], dtype=np.uint32),
                np.array([1, 2, 0, 0]),
            )

    def test_rejects_float_indices(self):
        with pytest.raises(GraphValidationError, match="integer"):
            self._solver().copy_graph(
                np.array([0.0, 1.0, 2.0]), np.array([1, 0]),
            )

    def test_internal_construction_not_taxed(self):
        """from_numpy_csr without validate_input skips the checks —
        coarse-level construction inside the pipeline pays nothing."""
        from kaminpar_tpu.graph.csr import from_numpy_csr

        g = from_numpy_csr(np.array([0, 1, 2]), np.array([1, 0]))
        assert g.n == 2


# ---------------------------------------------------------------------------
# Satellite: queue admission under concurrent overload
# ---------------------------------------------------------------------------


def test_queue_admission_concurrent_overload():
    """N threads submitting past capacity: every submit either yields a
    future that resolves exactly once or a QueueFullError with a
    positive, sane retry_after estimate; nothing is lost or duplicated."""
    eng = _engine(queue_bound=4, max_batch=2)
    eng.pause()  # hold dispatch so the bound actually fills
    eng.start(warmup=False)
    graphs = [_rmat(seed=200 + i) for i in range(4)]
    futures, rejects, errors = [], [], []
    lock = threading.Lock()
    barrier = threading.Barrier(8)

    def submit(i):
        barrier.wait()
        try:
            fut = eng.submit(graphs[i % 4], 4)
            with lock:
                futures.append(fut)
        except QueueFullError as exc:
            with lock:
                rejects.append(exc.retry_after_s)
        except Exception as exc:  # noqa: BLE001 — the test records strays
            with lock:
                errors.append(exc)

    threads = [
        threading.Thread(target=submit, args=(i,)) for i in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        assert not errors, f"unexpected submit errors: {errors}"
        assert len(futures) + len(rejects) == 8, "no submission lost"
        assert len(futures) == 4, "admissions must respect the bound"
        assert len(rejects) == 4
        for retry in rejects:
            assert 0.0 < retry < 60.0, f"insane retry_after {retry}"
        eng.resume()
        ids = [f.result(timeout=600).request_id for f in futures]
        assert len(set(ids)) == len(ids), "duplicated resolution"
        stats = eng.stats()
        assert stats["submitted"] == 8
        assert stats["admitted"] == 4
        assert stats["rejected_full"] == 4
        assert stats["completed"] == 4
    finally:
        eng.shutdown(drain=True)


# ---------------------------------------------------------------------------
# tools chaos smoke (the soak the CI/tooling satellite wires)
# ---------------------------------------------------------------------------


def test_tools_chaos_soak(tmp_path):
    from kaminpar_tpu.tools.tools import chaos

    runs = tmp_path / "RUNS.jsonl"
    rc = chaos([
        "--plan", "execute@engine_request:execute-fault:n=1",
        "--requests", "3", "--scale", "6", "-k", "2",
        "--runs", str(runs), "--json",
    ])
    assert rc == 0
    import json

    lines = runs.read_text().strip().splitlines()
    assert len(lines) == 1
    entry = json.loads(lines[0])
    assert entry["kind"] == "chaos"
    metrics = entry["metrics"]
    assert metrics["chaos_injected_count"] == 1
    assert metrics["chaos_faulted"] == 1
    assert metrics["chaos_recovered"] == 1
    assert "chaos_recover_s" in metrics
