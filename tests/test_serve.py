"""Partition-serving runtime tests (ISSUE 3): engine lifecycle, bounded
queue + admission control, deadlines, micro-batch packing, and the
bit-identity contract — batched serve results must equal sequential
``KaMinPar.compute_partition`` runs exactly.

Tier-1 keeps small graphs (n ~ 256, the "serve" preset's fast pipeline);
the heavy rmat/grid/star x two-buckets x two-k sweep is @slow.
"""

import time

import numpy as np
import pytest

from kaminpar_tpu.graph import generators, metrics
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.serve import (
    BoundedServeQueue,
    DeadlineExceededError,
    EngineStoppedError,
    PartitionEngine,
    QueueFullError,
    batched_metrics,
    form_batches,
    pack_graphs,
    shape_cell,
    unpack_partition,
)

SMALL = dict(warm_ladder=(), warm_ks=(), max_batch=4, queue_bound=8)


def _rmat(seed, scale=8):
    return generators.rmat_graph(scale, edge_factor=4, seed=seed)


class _Item:
    def __init__(self, cell):
        self.cell = cell


# ---------------------------------------------------------------------------
# Packing + batched metrics (single-dispatch over the union buffer)
# ---------------------------------------------------------------------------


def test_pack_unpack_roundtrip():
    graphs = [_rmat(1), generators.grid2d_graph(16, 16), generators.star_graph(99)]
    packed = pack_graphs(graphs)
    assert packed.num_graphs == 3
    assert packed.union.n == sum(g.n for g in graphs)
    assert packed.union.m == sum(g.m for g in graphs)
    # The union is a structurally valid disjoint graph.
    from kaminpar_tpu.graph.csr import validate

    validate(packed.union)
    # Labels round-trip through the union node space.
    labels = np.concatenate(
        [np.full(g.n, i, dtype=np.int32) for i, g in enumerate(graphs)]
    )
    parts = unpack_partition(labels, packed.node_offsets)
    for i, (g, p) in enumerate(zip(graphs, parts)):
        assert p.shape == (g.n,)
        assert np.all(p == i)


def test_batched_metrics_match_per_graph():
    graphs = [_rmat(1), _rmat(2), generators.grid2d_graph(16, 16)]
    k = 4
    rng = np.random.default_rng(0)
    parts = [rng.integers(0, k, g.n).astype(np.int32) for g in graphs]
    cuts, bws = batched_metrics(pack_graphs(graphs), parts, k)
    for i, g in enumerate(graphs):
        assert int(cuts[i]) == metrics.edge_cut(g, parts[i])
        assert np.array_equal(
            np.asarray(bws[i]), np.asarray(metrics.block_weights(g, parts[i], k))
        )


def test_shape_cell_and_form_batches():
    g = _rmat(1)
    cell = shape_cell(g, 4)
    assert cell.n_bucket > g.n and cell.m_bucket > g.m and cell.k == 4
    # Same graph, same k -> same cell; different k -> different cell.
    assert shape_cell(g, 4) == cell
    assert shape_cell(g, 8) != cell

    a, b = _Item(("x",)), _Item(("y",))
    batches = form_batches([a, b, _Item(("x",)), _Item(("x",))], max_batch=2)
    # FIFO-fair: head seeds the first batch, max_batch respected, the
    # leftover same-cell item forms its own batch, order preserved.
    assert [len(x) for x in batches] == [2, 1, 1]
    assert batches[0][0] is a and batches[1][0] is b


# ---------------------------------------------------------------------------
# Bounded queue
# ---------------------------------------------------------------------------


def test_queue_admission_and_rejection():
    q = BoundedServeQueue(bound=2)
    q.put(_Item(("a",)))
    q.put(_Item(("b",)))
    with pytest.raises(QueueFullError):
        q.put(_Item(("c",)))
    batch = q.pop_batch(max_batch=4, window_s=0.0)
    assert [i.cell for i in batch] == [("a",)]
    q.close()
    with pytest.raises(EngineStoppedError):
        q.put(_Item(("d",)))
    assert q.pop_batch(4)[0].cell == ("b",)
    assert q.pop_batch(4) is None  # closed + drained


def test_queue_same_cell_batch_extraction_preserves_order():
    q = BoundedServeQueue(bound=8)
    items = [_Item(("a",)), _Item(("b",)), _Item(("a",)), _Item(("c",))]
    for it in items:
        q.put(it)
    batch = q.pop_batch(max_batch=4, window_s=0.0)
    assert batch == [items[0], items[2]]
    # Other cells keep FIFO order.
    assert q.pop_batch(4, 0.0) == [items[1]]
    assert q.pop_batch(4, 0.0) == [items[3]]


# ---------------------------------------------------------------------------
# Engine lifecycle: start -> warmup -> submit -> drain -> shutdown
# ---------------------------------------------------------------------------


def test_engine_lifecycle_and_stats():
    # lane_stack="off": this test pins the PER-GRAPH path's warm-hit
    # accounting; under lane-stacking a cold compile cache would demote
    # the submit-time warm hits when the stacked program compiles (that
    # path and its stats have their own tests in test_lanestack.py).
    eng = PartitionEngine(
        "serve", warm_ladder=(256,), warm_ks=(4,), max_batch=4,
        queue_bound=8, lane_stack="off",
    )
    eng.start(warmup=True)
    try:
        assert eng.running
        assert len(eng.warmup_report) == 1
        row = eng.warmup_report[0]
        assert row["k"] == 4 and row["wall_s"] > 0
        futs = [eng.submit(_rmat(10 + i), 4) for i in range(3)]
        results = [f.result(timeout=300) for f in futs]
        for g, res in zip([_rmat(10 + i) for i in range(3)], results):
            part = res.partition
            assert part.shape == (g.n,)
            assert part.min() >= 0 and part.max() < 4
            assert res.cut == metrics.edge_cut(g, part)
            assert res.feasible
        snap = eng.stats()
        assert snap["submitted"] == 3 and snap["completed"] == 3
        assert snap["queue_depth"] == 0
        assert snap["warm_cells"] >= 1
        assert snap["latency_ms"]["total_ms"]["count"] == 3
        # Warmup covered the (n_bucket, k) of these requests.
        assert snap["warm_hits"] == 3, snap
    finally:
        eng.shutdown(drain=True)
    assert not eng.running
    with pytest.raises(EngineStoppedError):
        eng.submit(_rmat(1), 4)


def test_engine_restart_after_shutdown():
    """start() (including the partition() auto-start) must fully revive a
    shut-down engine: fresh queue, live dispatcher, warm state retained."""
    eng = PartitionEngine("serve", **SMALL)
    eng.start(warmup=False)
    g = _rmat(60)
    first = eng.partition(_rmat(60), 4)
    eng.shutdown(drain=True)
    assert not eng.running
    # Auto-start path (what facade delegation hits after a shutdown).
    again = eng.partition(_rmat(60), 4)
    assert eng.running
    assert np.array_equal(first, again)
    assert g.n == again.shape[0]
    eng.shutdown(drain=True)


def test_engine_shutdown_without_drain_rejects_queued():
    eng = PartitionEngine("serve", **SMALL)
    eng.pause()  # engaged before start: the dispatcher never pops
    eng.start(warmup=False)
    futs = [eng.submit(_rmat(20 + i), 4) for i in range(2)]
    eng.shutdown(drain=False, timeout_s=30)
    for f in futs:
        with pytest.raises(EngineStoppedError):
            f.result(timeout=30)


def test_engine_queue_full_rejection_with_retry_after():
    eng = PartitionEngine("serve", warm_ladder=(), warm_ks=(),
                          max_batch=1, queue_bound=2)
    eng.pause()
    eng.start(warmup=False)
    try:
        eng.submit(_rmat(30), 4)
        eng.submit(_rmat(31), 4)
        with pytest.raises(QueueFullError) as exc:
            eng.submit(_rmat(32), 4)
        assert exc.value.retry_after_s > 0
        assert eng.stats_.counter("rejected_full") == 1
    finally:
        eng.resume()
        eng.shutdown(drain=True)


def test_engine_deadline_timeout_in_queue():
    eng = PartitionEngine("serve", **SMALL)
    eng.pause()
    eng.start(warmup=False)
    try:
        fut = eng.submit(_rmat(40), 4, deadline_ms=10)
        time.sleep(0.05)
        eng.resume()
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=60)
        assert eng.stats_.counter("timed_out") == 1
    finally:
        eng.shutdown(drain=True)


def test_engine_cancel_before_execution():
    eng = PartitionEngine("serve", **SMALL)
    eng.pause()
    eng.start(warmup=False)
    try:
        fut = eng.submit(_rmat(41), 4)
        assert fut.cancel()
        eng.resume()
        from kaminpar_tpu.serve import RequestCancelledError

        with pytest.raises(RequestCancelledError):
            fut.result(timeout=60)
        assert eng.stats_.counter("cancelled") == 1
    finally:
        eng.shutdown(drain=True)


# ---------------------------------------------------------------------------
# Bit-identity: batched serve == sequential facade (the PR 1/2 discipline)
# ---------------------------------------------------------------------------


def _assert_batched_equals_sequential(graph_fns, k, max_batch=8):
    """Burst-submit all graphs (paused engine -> deterministic batches),
    then compare every result against a fresh sequential facade run."""
    eng = PartitionEngine("serve", warm_ladder=(), warm_ks=(),
                          max_batch=max_batch, queue_bound=64)
    eng.pause()
    eng.start(warmup=False)
    try:
        futs = [eng.submit(fn(), k) for fn in graph_fns]
        eng.resume()
        results = [f.result(timeout=600) for f in futs]
    finally:
        eng.shutdown(drain=True)
    occupancies = []
    for fn, res in zip(graph_fns, results):
        solo = KaMinPar(ctx="serve")
        solo.set_graph(fn())
        expected = solo.compute_partition(k, 0.03)
        assert np.array_equal(res.partition, expected), (
            f"batched result (batch={res.batch_size}) differs from the "
            f"sequential facade run for k={k}"
        )
        occupancies.append(res.batch_size)
    return occupancies


def test_batched_bit_identity_same_cell():
    # Four same-scale RMAT graphs; same-cell ones are micro-batched and
    # every result must equal its solo sequential run bit-for-bit.
    occ = _assert_batched_equals_sequential(
        [lambda s=s: _rmat(100 + s) for s in range(4)], k=4
    )
    assert max(occ) >= 2, f"expected some batching, got occupancies {occ}"


def test_batched_bit_identity_mixed_cells():
    # Mixed families and two k values: cells differ, batches split, and
    # identity still holds for every request.
    fns = [
        lambda: _rmat(7),
        lambda: generators.grid2d_graph(16, 16),
        lambda: _rmat(8),
    ]
    _assert_batched_equals_sequential(fns, k=4)
    _assert_batched_equals_sequential([lambda: _rmat(9)], k=8)


def test_facade_delegates_to_engine():
    g = _rmat(50)
    solo = KaMinPar(ctx="serve")
    solo.set_graph(g)
    expected = solo.compute_partition(4, 0.03)
    with PartitionEngine("serve", **SMALL) as eng:
        # Sync convenience wrapper...
        direct = eng.partition(_rmat(50), 4)
        # ...and facade delegation.
        facade = KaMinPar(ctx="serve", engine=eng)
        facade.set_graph(_rmat(50))
        delegated = facade.compute_partition(4, 0.03)
    assert np.array_equal(direct, expected)
    assert np.array_equal(delegated, expected)


@pytest.mark.slow
def test_batched_bit_identity_sweep():
    """The full ISSUE-3 sweep: rmat/grid/star at two buckets and two k
    values, batched-vs-sequential identity for every combination."""
    families = {
        "rmat": lambda scale, seed: generators.rmat_graph(
            scale, edge_factor=4, seed=seed
        ),
        "grid": lambda scale, seed: generators.grid2d_graph(
            1 << (scale // 2), 1 << (scale - scale // 2)
        ),
        "star": lambda scale, seed: generators.star_graph((1 << scale) - 1),
    }
    for name, fn in families.items():
        for scale in (8, 10):  # two node buckets
            for k in (4, 8):
                occ = _assert_batched_equals_sequential(
                    [lambda s=s: fn(scale, 200 + s) for s in range(3)], k=k
                )
                if name == "rmat":
                    assert max(occ) >= 1, (name, scale, k, occ)


# -- serve CLI metrics/health endpoints (round 20 satellite) ------------------


def test_metrics_server_serves_metrics_and_healthz():
    """One HTTP server, two endpoints: /metrics stays the Prometheus
    exposition, /healthz answers 200 with queue/dispatcher liveness and
    the SLO burn summary while the engine lives — and 503 once it stops."""
    import json as _json
    import urllib.error
    import urllib.request

    from kaminpar_tpu.serve.__main__ import _start_metrics_server

    eng = PartitionEngine("serve", slo_strong_ms=250.0, **SMALL)
    eng.start(warmup=False)
    server = _start_metrics_server(eng, 0)  # port 0: ephemeral
    port = server.server_address[1]
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            assert resp.status == 200
            body = resp.read().decode()
        assert "kaminpar_serve_queue_depth" in body

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            assert resp.status == 200
            health = _json.loads(resp.read())
        assert health["healthy"] is True
        (row,) = health["replicas"]
        assert row["queue_open"] and row["dispatcher_alive"]
        assert row["slo"]["armed"] is True
        assert "worst_burn" in row["slo"]

        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10)
        assert exc_info.value.code == 404

        eng.shutdown(drain=True)
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert exc_info.value.code == 503
        assert _json.loads(exc_info.value.read())["healthy"] is False
    finally:
        eng.shutdown(drain=False)
        server.shutdown()
