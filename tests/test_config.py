"""TOML config round-trip tests (reference: the CLI11 --dump-config/-C
machinery used by apps/KaMinPar.cc)."""

import os
import subprocess
import sys

# Subprocesses must not try the (possibly hung) TPU tunnel backend; the
# axon site hook (PYTHONPATH) force-connects it even under JAX_PLATFORMS=cpu,
# so it must be stripped too.
_ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "/root/repo"}

from kaminpar_tpu.config import dump_toml, load_toml
from kaminpar_tpu.context import RefinementAlgorithm
from kaminpar_tpu.presets import create_context_by_preset_name, get_preset_names


def test_dump_load_roundtrip_all_presets():
    for name in get_preset_names():
        ctx = create_context_by_preset_name(name)
        text = dump_toml(ctx)
        ctx2 = load_toml(text)
        assert ctx2.to_dict() == ctx.to_dict(), name


def test_load_overrides():
    ctx = load_toml(
        """
preset_name = "fast"
seed = 7

[coarsening.lp]
num_iterations = 3

[refinement]
algorithms = ["jet"]
"""
    )
    assert ctx.preset_name == "fast"
    assert ctx.seed == 7
    assert ctx.coarsening.lp.num_iterations == 3
    assert ctx.refinement.algorithms == (RefinementAlgorithm.JET,)


def test_load_rejects_unknown_key():
    import pytest

    with pytest.raises(ValueError, match="unknown config key"):
        load_toml("[coarsening]\nnot_a_field = 1\n")


def test_cli_dump_config():
    out = subprocess.run(
        [sys.executable, "-m", "kaminpar_tpu", "-P", "eco", "--dump-config"],
        capture_output=True, text=True, timeout=120, env=_ENV,
    )
    assert out.returncode == 0, out.stderr
    assert 'preset_name = "eco"' in out.stdout
    assert "[refinement]" in out.stdout
