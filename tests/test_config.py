"""TOML config round-trip tests (reference: the CLI11 --dump-config/-C
machinery used by apps/KaMinPar.cc)."""

import os
import subprocess
import sys

# Subprocesses must not try the (possibly hung) TPU tunnel backend; the
# axon site hook (PYTHONPATH) force-connects it even under JAX_PLATFORMS=cpu,
# so it must be stripped too.
_ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "/root/repo"}

from kaminpar_tpu.config import dump_toml, load_toml
from kaminpar_tpu.context import RefinementAlgorithm
from kaminpar_tpu.presets import create_context_by_preset_name, get_preset_names


def test_dump_load_roundtrip_all_presets():
    for name in get_preset_names():
        ctx = create_context_by_preset_name(name)
        text = dump_toml(ctx)
        ctx2 = load_toml(text)
        assert ctx2.to_dict() == ctx.to_dict(), name


def test_load_overrides():
    ctx = load_toml(
        """
preset_name = "fast"
seed = 7

[coarsening.lp]
num_iterations = 3

[refinement]
algorithms = ["jet"]
"""
    )
    assert ctx.preset_name == "fast"
    assert ctx.seed == 7
    assert ctx.coarsening.lp.num_iterations == 3
    assert ctx.refinement.algorithms == (RefinementAlgorithm.JET,)


def test_load_rejects_unknown_key():
    import pytest

    with pytest.raises(ValueError, match="unknown config key"):
        load_toml("[coarsening]\nnot_a_field = 1\n")


def test_cli_dump_config():
    out = subprocess.run(
        [sys.executable, "-m", "kaminpar_tpu", "-P", "eco", "--dump-config"],
        capture_output=True, text=True, timeout=120, env=_ENV,
    )
    assert out.returncode == 0, out.stderr
    assert 'preset_name = "eco"' in out.stdout
    assert "[refinement]" in out.stdout


def test_assertion_ladder():
    """KASSERT ladder (reference: kaminpar-common/assert.h:40-50): checks
    above the active level are skipped; callables defer evaluation."""
    import pytest

    from kaminpar_tpu.utils.assertions import (
        HEAVY,
        LIGHT,
        assertion_level,
        kassert,
        set_assertion_level,
    )

    prev = assertion_level()
    try:
        set_assertion_level("always")
        kassert(False, "inactive at always", LIGHT)  # no raise
        exploded = []
        kassert(lambda: exploded.append(1) or True, "", HEAVY)
        assert not exploded  # heavy callable never evaluated
        set_assertion_level("heavy")
        with pytest.raises(AssertionError, match="boom"):
            kassert(lambda: False, "boom", HEAVY)
    finally:
        set_assertion_level(
            {1: "always", 2: "light", 3: "normal", 4: "heavy", 0: "none"}[prev]
        )


def test_dist_preset_ladder():
    """dist preset ladder (reference: dist presets.cc:18-286)."""
    from kaminpar_tpu.context import (
        DistClusteringAlgorithm,
        RefinementAlgorithm,
    )
    from kaminpar_tpu.presets import create_context_by_preset_name

    fast = create_context_by_preset_name("dist-fast")
    assert fast.coarsening.dist_clustering == DistClusteringAlgorithm.LOCAL_GLOBAL_LP
    strong = create_context_by_preset_name("dist-strong")
    assert RefinementAlgorithm.CLP in strong.refinement.algorithms
    assert RefinementAlgorithm.JET in strong.refinement.algorithms
    largek = create_context_by_preset_name("dist-largek")
    assert largek.initial_partitioning.device_extension


def test_configure_globals_first_wins_and_warns():
    """ISSUE 3 satellite: configure_* is idempotent and re-entrancy-safe —
    a second facade/engine instance must not clobber the first's global
    config; conflicting settings warn instead."""
    import warnings

    import pytest

    from kaminpar_tpu import context as ctx_mod
    from kaminpar_tpu.context import ParallelContext, configure_sync_timers
    from kaminpar_tpu.utils import timer

    prev_mode = timer.sync_mode()
    ctx_mod.reset_global_configuration()
    try:
        configure_sync_timers(ParallelContext(sync_timers=False))
        # Identical settings: silent no-op (the common second-instance case).
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            configure_sync_timers(ParallelContext(sync_timers=False))
        # Conflicting settings: warn, keep the first application.
        with pytest.warns(RuntimeWarning, match="first-wins"):
            configure_sync_timers(ParallelContext(sync_timers=True))
        assert timer.sync_mode() is False
    finally:
        ctx_mod.reset_global_configuration()
        timer.set_sync_mode(prev_mode)


def test_serve_context_roundtrips_and_preset():
    from kaminpar_tpu.config import dump_toml as _dump, load_toml as _load
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name("serve")
    ctx.serve.warm_ladder = (64, 128)
    ctx.serve.default_deadline_ms = 250.0
    ctx2 = _load(_dump(ctx))
    assert ctx2.serve.warm_ladder == (64, 128)
    assert ctx2.serve.default_deadline_ms == 250.0
    assert ctx2.serve.max_batch == ctx.serve.max_batch
