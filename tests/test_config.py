"""TOML config round-trip tests (reference: the CLI11 --dump-config/-C
machinery used by apps/KaMinPar.cc)."""

import os
import subprocess
import sys

# Subprocesses must not try the (possibly hung) TPU tunnel backend; the
# axon site hook (PYTHONPATH) force-connects it even under JAX_PLATFORMS=cpu,
# so it must be stripped too.
_ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "/root/repo"}

from kaminpar_tpu.config import dump_toml, load_toml
from kaminpar_tpu.context import RefinementAlgorithm
from kaminpar_tpu.presets import create_context_by_preset_name, get_preset_names


def test_dump_load_roundtrip_all_presets():
    for name in get_preset_names():
        ctx = create_context_by_preset_name(name)
        text = dump_toml(ctx)
        ctx2 = load_toml(text)
        assert ctx2.to_dict() == ctx.to_dict(), name


def test_load_overrides():
    ctx = load_toml(
        """
preset_name = "fast"
seed = 7

[coarsening.lp]
num_iterations = 3

[refinement]
algorithms = ["jet"]
"""
    )
    assert ctx.preset_name == "fast"
    assert ctx.seed == 7
    assert ctx.coarsening.lp.num_iterations == 3
    assert ctx.refinement.algorithms == (RefinementAlgorithm.JET,)


def test_load_rejects_unknown_key():
    import pytest

    with pytest.raises(ValueError, match="unknown config key"):
        load_toml("[coarsening]\nnot_a_field = 1\n")


def test_cli_dump_config():
    out = subprocess.run(
        [sys.executable, "-m", "kaminpar_tpu", "-P", "eco", "--dump-config"],
        capture_output=True, text=True, timeout=120, env=_ENV,
    )
    assert out.returncode == 0, out.stderr
    assert 'preset_name = "eco"' in out.stdout
    assert "[refinement]" in out.stdout


def test_assertion_ladder():
    """KASSERT ladder (reference: kaminpar-common/assert.h:40-50): checks
    above the active level are skipped; callables defer evaluation."""
    import pytest

    from kaminpar_tpu.utils.assertions import (
        HEAVY,
        LIGHT,
        assertion_level,
        kassert,
        set_assertion_level,
    )

    prev = assertion_level()
    try:
        set_assertion_level("always")
        kassert(False, "inactive at always", LIGHT)  # no raise
        exploded = []
        kassert(lambda: exploded.append(1) or True, "", HEAVY)
        assert not exploded  # heavy callable never evaluated
        set_assertion_level("heavy")
        with pytest.raises(AssertionError, match="boom"):
            kassert(lambda: False, "boom", HEAVY)
    finally:
        set_assertion_level(
            {1: "always", 2: "light", 3: "normal", 4: "heavy", 0: "none"}[prev]
        )


def test_dist_preset_ladder():
    """dist preset ladder (reference: dist presets.cc:18-286)."""
    from kaminpar_tpu.context import (
        DistClusteringAlgorithm,
        RefinementAlgorithm,
    )
    from kaminpar_tpu.presets import create_context_by_preset_name

    fast = create_context_by_preset_name("dist-fast")
    assert fast.coarsening.dist_clustering == DistClusteringAlgorithm.LOCAL_GLOBAL_LP
    strong = create_context_by_preset_name("dist-strong")
    assert RefinementAlgorithm.CLP in strong.refinement.algorithms
    assert RefinementAlgorithm.JET in strong.refinement.algorithms
    largek = create_context_by_preset_name("dist-largek")
    assert largek.initial_partitioning.device_extension


def test_engine_runtime_ownership_no_first_wins():
    """ISSUE 6 unlocking refactor: the first-wins configure_* records are
    gone — each facade/engine owns an :class:`EngineRuntime` and activates
    it thread-locally, so two conflicting configs coexist in one process
    with no RuntimeWarning and *independent* behavior inside each
    activation."""
    import warnings

    from kaminpar_tpu import context as ctx_mod
    from kaminpar_tpu.context import EngineRuntime, ParallelContext
    from kaminpar_tpu.graph.csr import resolve_layout_build_mode
    from kaminpar_tpu.utils import timer

    prev_mode = timer.sync_mode()
    try:
        rt_a = EngineRuntime.from_parallel(
            ParallelContext(sync_timers=False, device_layout_build="host")
        )
        rt_b = EngineRuntime.from_parallel(
            ParallelContext(sync_timers=True, device_layout_build="device")
        )
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # conflicting configs: no warning
            with rt_a.activate():
                assert timer.sync_mode() is False
                assert resolve_layout_build_mode() == "host"
                # Nested activation (engine dispatch inside a facade run):
                # the inner runtime wins, the outer is restored after.
                with rt_b.activate():
                    assert timer.sync_mode() is True
                    assert resolve_layout_build_mode() == "device"
                assert timer.sync_mode() is False
                assert resolve_layout_build_mode() == "host"
        assert ctx_mod.current_runtime() is None
    finally:
        timer.set_sync_mode(prev_mode)


def test_engine_runtime_cache_isolation(tmp_path):
    """Two runtimes with different cache dirs: each activation applies its
    own dir to the live jax config at entry (last-activation-wins on the
    process-global jax config — concurrent engines may interleave, which
    costs cache locality but never correctness)."""
    import jax

    from kaminpar_tpu import context as ctx_mod
    from kaminpar_tpu.context import EngineRuntime, ParallelContext

    prev = jax.config.jax_compilation_cache_dir
    ctx_mod.reset_global_configuration()
    try:
        dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
        rt_a = EngineRuntime.from_parallel(
            ParallelContext(compilation_cache_dir=dir_a)
        )
        rt_b = EngineRuntime.from_parallel(
            ParallelContext(compilation_cache_dir=dir_b)
        )
        with rt_a.activate():
            assert jax.config.jax_compilation_cache_dir == dir_a
            with rt_b.activate():
                assert jax.config.jax_compilation_cache_dir == dir_b
            # Restored to the enclosing engine's setting on exit.
            assert jax.config.jax_compilation_cache_dir == dir_a
    finally:
        ctx_mod.reset_global_configuration()
        try:
            jax.config.update("jax_compilation_cache_dir", prev)
        except Exception:
            pass


def test_engine_runtime_restores_process_default_cache(tmp_path):
    """The outermost activation restores whatever cache settings were
    applied before it (the ``configure_compilation_cache`` process
    default), so one facade run doesn't permanently clobber them for
    compiles outside any activation (regression)."""
    import jax

    from kaminpar_tpu import context as ctx_mod
    from kaminpar_tpu.context import (
        EngineRuntime,
        ParallelContext,
        configure_compilation_cache,
    )

    prev = jax.config.jax_compilation_cache_dir
    ctx_mod.reset_global_configuration()
    try:
        default_dir = str(tmp_path / "default")
        configure_compilation_cache(
            ParallelContext(compilation_cache_dir=default_dir)
        )
        assert jax.config.jax_compilation_cache_dir == default_dir
        rt = EngineRuntime.from_parallel(
            ParallelContext(persistent_compilation_cache=False)
        )
        with rt.activate():
            assert jax.config.jax_compilation_cache_dir is None
        assert jax.config.jax_compilation_cache_dir == default_dir

        # Also when the default was applied with raw jax.config updates
        # (the import-time setup in kaminpar_tpu/__init__.py) and nothing
        # is recorded in the module's memo: activate() captures the live
        # config as the default instead.
        raw_dir = str(tmp_path / "raw")
        jax.config.update("jax_compilation_cache_dir", raw_dir)
        ctx_mod.reset_global_configuration()
        with rt.activate():
            assert jax.config.jax_compilation_cache_dir is None
        assert jax.config.jax_compilation_cache_dir == raw_dir

        # Overlapping activations on different threads (two engines' dispatch
        # threads mid-run) still restore the true process default once the
        # last one exits — never a snapshot of the other engine's settings.
        import threading

        default_dir2 = str(tmp_path / "default2")
        configure_compilation_cache(
            ParallelContext(compilation_cache_dir=default_dir2)
        )
        rt_a = EngineRuntime.from_parallel(
            ParallelContext(compilation_cache_dir=str(tmp_path / "ov_a"))
        )
        rt_b = EngineRuntime.from_parallel(
            ParallelContext(compilation_cache_dir=str(tmp_path / "ov_b"))
        )
        a_in, b_in, a_out = (threading.Event() for _ in range(3))

        def thread_a():
            with rt_a.activate():
                a_in.set()
                b_in.wait(10)
            a_out.set()

        def thread_b():
            a_in.wait(10)
            with rt_b.activate():  # enters while A is still active
                b_in.set()
                a_out.wait(10)  # exits after A

        ta = threading.Thread(target=thread_a)
        tb = threading.Thread(target=thread_b)
        ta.start(); tb.start(); ta.join(15); tb.join(15)
        assert jax.config.jax_compilation_cache_dir == default_dir2
    finally:
        ctx_mod.reset_global_configuration()
        try:
            jax.config.update("jax_compilation_cache_dir", prev)
        except Exception:
            pass


def test_serve_context_roundtrips_and_preset():
    from kaminpar_tpu.config import dump_toml as _dump, load_toml as _load
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name("serve")
    ctx.serve.warm_ladder = (64, 128)
    ctx.serve.default_deadline_ms = 250.0
    ctx2 = _load(_dump(ctx))
    assert ctx2.serve.warm_ladder == (64, 128)
    assert ctx2.serve.default_deadline_ms == 250.0
    assert ctx2.serve.max_batch == ctx.serve.max_batch
