"""Request-scoped distributed tracing + SLO burn accounting (ISSUE 20).

Tentpole contracts under test:

* every submit mints a trace id whose event chain reads steer → admit →
  dispatch → resolve as one CONNECTED dossier (``engine.explain`` /
  ``fleet.explain``), even across a replica kill (resteer) or an engine
  crash + journal replay — zero orphan spans;
* tracing is host-only: arming it adds ZERO blocking transfers;
* SLO burn-rate pressure is a control input only — partitions are
  bit-identical with the SLO layer armed or off;
* terminal events export the request's life onto a per-request lane of
  the active Chrome trace.
"""

import threading
import time

import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.serve import journal as J
from kaminpar_tpu.serve.engine import PartitionEngine
from kaminpar_tpu.serve.fleet import PartitionFleet
from kaminpar_tpu.telemetry import trace as ttrace
from kaminpar_tpu.telemetry.reqtrace import ReqTrace
from kaminpar_tpu.telemetry.slo import BurnTracker, prometheus_families

SMALL = dict(warm_ladder=(), warm_ks=(), max_batch=4, queue_bound=16)


def _rmat(seed, scale=7):
    return generators.rmat_graph(scale, edge_factor=4, seed=seed)


def _events(dossier):
    return [ev["event"] for ev in dossier["events"]]


# -- registry unit tests ------------------------------------------------------


def test_mint_bind_and_bounds():
    rt = ReqTrace(capacity=4, max_events=3)
    assert len({rt.mint() for _ in range(16)}) == 16
    tid = rt.mint()
    rt.bind(7, tid)
    rt.bind_fleet(70, tid)
    assert rt.trace_for_request(7) == tid
    assert rt.trace_for_fleet(70) == tid
    for i in range(5):
        rt.record(tid, "admit", request_id=7, seq=i)
    assert len(rt.events(tid)) == 3  # max_events cap
    assert rt.dropped_events == 2
    for i in range(10):
        rt.record(f"stray-{i}", "admit")
    snap = rt.snapshot()
    assert snap["traces"] <= 4  # capacity eviction
    assert snap["evicted_traces"] > 0
    rt.record("", "admit")  # empty trace id is a no-op
    assert rt.dossier("no-such-trace") is None
    assert rt.explain_request(12345) is None


def test_dossier_connectivity_and_orphans():
    rt = ReqTrace()
    tid = rt.mint()
    rt.record(tid, "steer", fleet_id=1)
    rt.record(tid, "admit", request_id=11, engine="replica0")
    rt.record(tid, "dispatch", request_id=11, engine="replica0")
    rt.record(tid, "resolve", request_id=11, final=True, engine="replica0")
    d = rt.dossier(tid)
    assert _events(d) == ["steer", "admit", "dispatch", "resolve"]
    s = d["summary"]
    assert s["connected"] and s["resolved"] and s["outcome"] == "resolve"
    assert s["roots"] == 2 and s["orphan_events"] == 0
    assert s["engines"] == ["replica0"]

    # a request-scoped event with no matching admit in the trace is an
    # orphan and breaks connectivity — the replay/resteer tripwire
    tid2 = rt.mint()
    rt.record(tid2, "steer")
    rt.record(tid2, "resolve", request_id=99, final=True)
    s2 = rt.dossier(tid2)["summary"]
    assert s2["orphan_events"] == 1 and not s2["connected"]

    # a non-final (resteerable) error is NOT a terminal resolution
    tid3 = rt.mint()
    rt.record(tid3, "admit", request_id=5)
    rt.record(tid3, "error", request_id=5, final=False,
              failure_class="worker-hung")
    s3 = rt.dossier(tid3)["summary"]
    assert not s3["resolved"] and s3["outcome"] is None
    rt.record(tid3, "admit", request_id=6, engine="replica1")
    rt.record(tid3, "resolve", request_id=6, final=True)
    s3 = rt.dossier(tid3)["summary"]
    assert s3["resolved"] and s3["connected"] and s3["outcome"] == "resolve"


def test_reqtrace_is_host_only():
    """Arming request tracing must add ZERO blocking transfers: every
    ReqTrace operation is dict bookkeeping under a lock."""
    from kaminpar_tpu.utils import sync_stats

    sync_stats.reset()
    rt = ReqTrace()
    with sync_stats.scoped("reqtrace_export"):
        tid = rt.mint()
        rt.bind(1, tid)
        rt.record(tid, "admit", request_id=1)
        rt.record(tid, "resolve", request_id=1, final=True, cut=42)
        rt.dossier(tid)
        rec = ttrace.TraceRecorder()
        rt.export_chrome(rec, tid)
    sync_stats.enable_budget_checks(True)
    try:
        sync_stats.assert_phase_budget("reqtrace_export", 0)
    finally:
        sync_stats.enable_budget_checks(False)
        sync_stats.reset()


def test_new_phases_registered():
    from kaminpar_tpu.telemetry import phases

    assert "reqtrace_export" in phases.KNOWN_PHASES
    assert "slo_eval" in phases.KNOWN_PHASES


# -- SLO burn accounting ------------------------------------------------------


def test_burn_tracker_math_and_pressure():
    bt = BurnTracker(strong_ms=100.0, availability=0.9,
                     capacity_reject_rate=0.5, windows_s=(60.0,))
    for _ in range(8):
        bt.record_request("strong", 0.01, ok=True)
    bt.record_request("strong", 0.5, ok=True)   # misses the 100 ms target
    bt.record_request("strong", 0.01, ok=False)  # availability failure
    bt.record_reject(capacity=True)
    bt.record_reject(capacity=False)  # queue-full: NOT a capacity reject
    s = bt.summary()
    assert s["armed"]
    burns = s["windows"][0]["burn"]
    # 1 of 9 ok-requests missed latency, against a 10% budget (1 - 0.9)
    assert burns["latency_strong"] == pytest.approx((1 / 9) / 0.1)
    # 1 of 10 finished failed, against the same 10% budget
    assert burns["availability"] == pytest.approx(0.1 / 0.1)
    # 1 capacity reject of 11 submitted, against a 50% reject budget
    assert burns["capacity_reject"] == pytest.approx((1 / 11) / 0.5)
    assert s["worst_burn"] == pytest.approx(max(burns.values()))
    assert s["pressure"] == pytest.approx(max(0.0, s["worst_burn"] - 1.0))
    assert bt.pressure() == pytest.approx(s["pressure"], abs=1e-6)
    fams = {f[0] for f in prometheus_families(bt)}
    assert {"kaminpar_slo_burn_rate", "kaminpar_slo_worst_burn",
            "kaminpar_slo_pressure"} <= fams


def test_burn_tracker_disarmed_is_none():
    class Serve:
        slo_strong_ms = 0.0
        slo_fast_ms = 0.0
        slo_availability = 0.0
        slo_capacity_reject_rate = 0.0

    assert BurnTracker.from_serve(Serve()) is None
    assert prometheus_families(None) == []


# -- engine integration -------------------------------------------------------


def test_engine_explain_request_lifecycle_and_chrome_lane():
    # One engine drive covers the explain() lifecycle AND the Chrome
    # per-request lane export (the trace is armed for the whole run).
    rec = ttrace.start()
    try:
        eng = PartitionEngine("serve", **SMALL)
        eng.start(warmup=False)
        try:
            fut = eng.submit(_rmat(1), 4)
            fut.result(timeout=300)
            d = eng.explain(fut.request_id)
            assert d is not None
            evs = _events(d)
            assert evs[0] == "admit" and evs[-1] == "resolve"
            assert "dispatch" in evs
            s = d["summary"]
            assert s["connected"] and s["resolved"]
            assert s["outcome"] == "resolve"
            assert s["orphan_events"] == 0
            admit = d["events"][0]
            assert admit["request_id"] == fut.request_id
            assert admit["queue_position"] >= 1
            resolve = d["events"][-1]
            assert resolve["final"] is True and "cut" in resolve

            # a caller-supplied trace id extends the SAME chain
            rt_tid = eng.reqtrace.mint()
            eng.reqtrace.record(rt_tid, "steer", fleet_id=123)
            fut2 = eng.submit(_rmat(2), 4, trace_id=rt_tid)
            fut2.result(timeout=300)
            d2 = eng.reqtrace.dossier(rt_tid)
            assert _events(d2)[0] == "steer"
            assert _events(d2)[-1] == "resolve"
            assert d2["summary"]["connected"]

            snap = eng.stats()
            assert snap["reqtrace"]["minted"] >= 2
            assert snap["reqtrace"]["recorded_events"] >= 6
            assert snap["slo"] == {"armed": False}
        finally:
            eng.shutdown(drain=True)
    finally:
        ttrace.stop()
    chrome = rec.chrome_trace()
    req_spans = [ev for ev in chrome["traceEvents"]
                 if str(ev.get("name", "")).startswith("req.")
                 and ev.get("ph") == "B"]
    assert req_spans, "terminal resolve must export a per-request lane"
    assert any(ev["name"] == "req.admit" for ev in req_spans)
    assert all("trace_id" in ev.get("args", {}) for ev in req_spans)
    # the exported lane validates as part of the whole chrome trace
    from kaminpar_tpu.telemetry.trace import validate_chrome_trace

    validate_chrome_trace(chrome)


def test_slo_armed_bit_identical_partitions():
    """The bit-identity acceptance gate: burn-rate feedback is a control
    input only — an engine with objectives armed must produce the exact
    same partition as one with the SLO layer off."""
    g = _rmat(4)

    def run(**slo):
        eng = PartitionEngine("serve", **SMALL, **slo)
        eng.start(warmup=False)
        try:
            return np.asarray(
                eng.submit(g, 4).result(timeout=300).partition
            )
        finally:
            eng.shutdown(drain=True)

    off = run()
    armed = run(slo_strong_ms=0.001, slo_availability=0.999,
                slo_capacity_reject_rate=0.01)
    assert np.array_equal(off, armed)


def test_engine_slo_summary_and_metrics():
    eng = PartitionEngine("serve", slo_strong_ms=0.001, **SMALL)
    eng.start(warmup=False)
    try:
        eng.submit(_rmat(5), 4).result(timeout=300)
        slo = eng.stats()["slo"]
        assert slo["armed"]
        # a sub-millisecond target cannot be met: the burn saturates
        assert slo["worst_burn"] > 1.0 and slo["pressure"] > 0.0
        assert eng.steer_signals()["slo_pressure"] > 0.0
        text = eng.metrics_text()
        assert "kaminpar_slo_burn_rate" in text
        assert "kaminpar_slo_pressure" in text
    finally:
        eng.shutdown(drain=True)


# -- crash / resteer continuity (the satellite-4 acceptance tests) -----------


def test_journal_replay_trace_continuity(tmp_path):
    """Kill an engine with admitted-but-unserved work; the restarted
    engine replays the journal and every replayed request's dossier
    reads admit → journal_replay → resolve under the ORIGINAL trace id,
    connected with zero orphan spans."""
    path = tmp_path / "serve.jsonl"

    def engine():
        from kaminpar_tpu.presets import create_context_by_preset_name

        ctx = create_context_by_preset_name("serve")
        ctx.serve.journal_path = str(path)
        ctx.serve.journal_fsync_every = 1
        return PartitionEngine(ctx, **SMALL)

    e1 = engine()
    e1.start(warmup=False)
    e1.pause()
    futs = [e1.submit(_rmat(10 + i, scale=7), 4) for i in range(3)]
    tids = [e1.reqtrace.trace_for_request(f.request_id) for f in futs]
    assert all(tids)
    e1.shutdown(drain=False)  # dies with 3 unresolved admits

    e2 = engine()
    e2.start(warmup=False)
    try:
        deadline = time.monotonic() + 180
        while (J.read_journal(str(path))["unresolved"]
               and time.monotonic() < deadline):
            time.sleep(0.1)
        assert not J.read_journal(str(path))["unresolved"]
        for tid in tids:
            d = e2.reqtrace.dossier(tid)
            assert d is not None, "replay must rebind the journaled id"
            evs = _events(d)
            assert "admit" in evs and "journal_replay" in evs
            s = d["summary"]
            assert s["replays"] == 1
            assert s["resolved"] and s["outcome"] == "resolve"
            assert s["connected"] and s["orphan_events"] == 0
            admit = next(ev for ev in d["events"]
                         if ev["event"] == "admit")
            assert admit.get("replayed") is True
    finally:
        e2.shutdown(drain=True)


def test_resteer_trace_continuity():
    """Kill (drain) the replica holding a queued burst: every resteered
    request's dossier shows the steer root, the first admit, the resteer
    hop, the second admit on the surviving replica, and the final
    resolve — one connected span tree, zero orphans."""
    fleet = PartitionFleet("serve", replicas=2, **SMALL)
    fleet.pause()
    fleet.start(warmup=False)
    try:
        graphs = [_rmat(20, scale=7)] * 4  # same cell: one home replica
        futs = [fleet.submit(g, 4) for g in graphs]
        routed = [f.replica for f in futs]
        victim = max(set(routed), key=routed.count)
        fleet.drain_replica(victim, reason="trace continuity test")
        deadline = time.monotonic() + 60
        while (fleet.replicas[victim].running
               and time.monotonic() < deadline):
            time.sleep(0.02)
        fleet.resume()
        for f in futs:
            f.result(timeout=600)
        moved = [f for f in futs if routed[futs.index(f)] == victim]
        assert moved, "the drain must have resteered at least one request"
        for f in futs:
            d = fleet.explain(f)
            assert d is not None
            s = d["summary"]
            assert s["connected"], f"orphans: {d['orphans']}"
            assert s["orphan_events"] == 0
            assert s["resolved"] and s["outcome"] == "resolve"
            assert _events(d)[0] == "steer"
        for f in moved:
            d = fleet.explain(f)
            s = d["summary"]
            assert s["resteers"] >= 1
            assert s["admits"] >= 2  # one per replica the request visited
            assert len(s["engines"]) >= 1
            resteer = next(ev for ev in d["events"]
                           if ev["event"] == "resteer")
            assert resteer["from_replica"] == victim
    finally:
        fleet.shutdown(drain=True)


# -- fleet integration --------------------------------------------------------


def test_fleet_steer_event_and_explain():
    fleet = PartitionFleet("serve", replicas=2, **SMALL)
    fleet.start(warmup=False)
    try:
        fut = fleet.submit(_rmat(30, scale=7), 4)
        fut.result(timeout=300)
        # explain by future, by fleet id, and by raw trace id agree
        d = fleet.explain(fut)
        assert d == fleet.explain(fut.fleet_id)
        assert d == fleet.explain(d["trace_id"])
        steer = d["events"][0]
        assert steer["event"] == "steer"
        assert len(steer["candidates"]) >= 1
        # the per-replica score inputs that chose the winner are recorded
        assert {s["replica"] for s in steer["scores"]} \
            == set(steer["candidates"])
        assert sum(1 for ev in d["events"] if ev["event"] == "steer") == 1
        s = d["summary"]
        assert s["connected"] and s["resolved"]
        assert s["engines"], "the admit event names the landing replica"
        snap = fleet.stats()
        assert snap["reqtrace"]["minted"] >= 1
        assert "slo_pressure" in snap
        assert "kaminpar_slo_fleet_pressure" in fleet.metrics_text()
    finally:
        fleet.shutdown(drain=True)


@pytest.mark.slow
def test_fleet_trace_matrix_burst():
    """Heavy fleet-trace matrix: a concurrent multi-cell burst across 2
    replicas under SLO steering with an active Chrome trace — every
    request's dossier stays connected, lanes are budgeted, and the
    combined trace still validates."""
    rec = ttrace.start()
    try:
        fleet = PartitionFleet(
            "serve", replicas=2, slo_strong_ms=250.0,
            slo_availability=0.99, **SMALL,
        )
        fleet.start(warmup=False)
        try:
            graphs = [_rmat(40 + i, scale=7 + (i % 2)) for i in range(12)]
            futs, lock = [], threading.Lock()

            def submit(g):
                fut = fleet.submit(g, 4)
                with lock:
                    futs.append(fut)

            threads = [threading.Thread(target=submit, args=(g,))
                       for g in graphs]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for f in futs:
                f.result(timeout=600)
            for f in futs:
                s = fleet.explain(f)["summary"]
                assert s["connected"] and s["resolved"]
                assert s["orphan_events"] == 0
            snap = fleet.reqtrace.snapshot()
            assert snap["minted"] >= len(graphs)
            assert snap["chrome_lanes_exported"] <= 64
        finally:
            fleet.shutdown(drain=True)
    finally:
        ttrace.stop()
    from kaminpar_tpu.telemetry.trace import validate_chrome_trace

    validate_chrome_trace(rec.chrome_trace())
