"""Distributed contraction + end-to-end dKaMinPar-equivalent pipeline on the
virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from kaminpar_tpu.dist import distribute_graph
from kaminpar_tpu.dist.contraction import contract_dist_clustering, project_partition_up
from kaminpar_tpu.dist.lp import shard_arrays
from kaminpar_tpu.dist.partitioner import DKaMinPar
from kaminpar_tpu.graph import generators, metrics
from kaminpar_tpu.ops.contraction import contract_clustering


def _mesh(num=8):
    devs = jax.devices()
    if len(devs) < num:
        pytest.skip(f"need {num} devices, have {len(devs)}")
    return Mesh(np.array(devs[:num]), ("nodes",))


def _host_contract(graph, labels_global):
    """Single-chip reference contraction for comparison."""
    pv = graph.padded()
    lab_pad = np.full(pv.n_pad, pv.anchor, dtype=np.int32)
    lab_pad[: graph.n] = labels_global
    coarse, coarse_of = contract_clustering(graph, jnp.asarray(lab_pad))
    return coarse, np.asarray(coarse_of)


def test_dist_contraction_matches_host():
    mesh = _mesh()
    g = generators.rmat_graph(9, 8, seed=5)
    dg = distribute_graph(g, mesh.size)
    rng = np.random.default_rng(0)
    # a clustering over global node ids: group id = node id // 3 (valid label
    # choice: labels must be *node ids* of representatives — use min member)
    group = np.arange(dg.N, dtype=np.int32)
    group[: g.n] = (np.arange(g.n) // 3 * 3).astype(np.int32)

    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(group))
    coarse, coarse_of, n_c = contract_dist_clustering(mesh, dgs, labels)

    host_coarse, host_of = _host_contract(g, group[: g.n])
    assert n_c == host_coarse.n
    assert coarse.m == host_coarse.m
    # same total coarse edge weight and node weight
    assert int(np.asarray(coarse.edge_w).sum()) == host_coarse.total_edge_weight
    assert int(np.asarray(coarse.node_w).sum()) == host_coarse.total_node_weight
    # same coarse node weights per compact id (both relabel by first-seen
    # order of cluster representatives = ascending representative id)
    np.testing.assert_array_equal(
        np.asarray(coarse.node_w)[: n_c], np.asarray(host_coarse.node_w)
    )
    # projection consistency: fine nodes in the same cluster share an id
    c_of = np.asarray(coarse_of)[: g.n]
    np.testing.assert_array_equal(c_of, host_of)

    # exact coarse edge set: reconstruct (cu, cv, w) from the dist layout
    # (edge_u is shard-local, col_loc is a local/ghost slot) and compare
    # with the host coarse CSR triples
    eu = np.asarray(coarse.edge_u).reshape(coarse.num_shards, coarse.m_loc)
    cl = np.asarray(coarse.col_loc).reshape(coarse.num_shards, coarse.m_loc)
    w = np.asarray(coarse.edge_w).reshape(coarse.num_shards, coarse.m_loc)
    got = set()
    for s in range(coarse.num_shards):
        real = w[s] > 0
        gg = coarse.ghost_global[s]
        for u_l, slot, ew in zip(eu[s][real], cl[s][real], w[s][real]):
            u = int(u_l) + s * coarse.n_loc
            v = (
                int(slot) + s * coarse.n_loc
                if slot < coarse.n_loc
                else int(gg[slot - coarse.n_loc])
            )
            got.add((u, v, int(ew)))
    rp = np.asarray(host_coarse.row_ptr)
    hc = np.asarray(host_coarse.col_idx)
    hw = np.asarray(host_coarse.edge_w)
    want = {
        (u, int(hc[e]), int(hw[e]))
        for u in range(host_coarse.n)
        for e in range(int(rp[u]), int(rp[u + 1]))
    }
    assert got == want


def test_project_partition_up():
    mesh = _mesh()
    g = generators.grid2d_graph(12, 12)
    dg = distribute_graph(g, mesh.size)
    group = np.arange(dg.N, dtype=np.int32)
    group[: g.n] = (np.arange(g.n) // 4 * 4).astype(np.int32)
    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(group))
    coarse, coarse_of, n_c = contract_dist_clustering(mesh, dgs, labels)

    rng = np.random.default_rng(1)
    cpart = rng.integers(0, 4, coarse.N).astype(np.int32)
    cpart_dev, _ = shard_arrays(mesh, coarse, jnp.asarray(cpart))
    fine = np.asarray(
        project_partition_up(mesh, coarse_of, cpart_dev, n_loc_c=coarse.n_loc)
    )
    c_of = np.asarray(coarse_of)
    np.testing.assert_array_equal(fine[: g.n], cpart[c_of[: g.n]])


@pytest.mark.parametrize("gen,k", [
    (lambda: generators.grid2d_graph(24, 24), 4),
    (lambda: generators.rmat_graph(10, 8, seed=9), 8),
])
@pytest.mark.slow  # full dist pipeline on the virtual mesh: tier-2 (pytest -m slow)
def test_dkaminpar_endtoend(gen, k):
    mesh = _mesh()
    g = gen()
    solver = DKaMinPar(mesh)
    part = solver.compute_partition(g, k=k)
    assert part.shape == (g.n,)
    assert part.min() >= 0 and part.max() < k
    # balanced-ish and better than random
    w = np.bincount(part, weights=np.asarray(g.node_w), minlength=k)
    limit = (1.03 * g.total_node_weight + k - 1) // k + g.max_node_weight
    assert w.max() <= limit
    rng = np.random.default_rng(0)
    rand_cut = metrics.edge_cut(g, rng.integers(0, k, g.n))
    assert metrics.edge_cut(g, part) < rand_cut


@pytest.mark.slow  # full dist pipeline on the virtual mesh: tier-2 (pytest -m slow)
def test_dkaminpar_cli_entry(tmp_path):
    """dKaMinPar binary analog (apps/dKaMinPar.cc:546): parse, mesh, read,
    partition, write."""
    from kaminpar_tpu.dist.__main__ import main as dist_main
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.io import write_metis

    g = generators.grid2d_graph(16, 16)
    gpath = tmp_path / "g.metis"
    opath = tmp_path / "part.txt"
    write_metis(g, str(gpath))
    rc = dist_main([str(gpath), "4", "--shards", "4", "-s", "1", "-q",
                    "-o", str(opath)])
    assert rc == 0
    part = np.loadtxt(opath, dtype=np.int64)
    assert part.shape == (g.n,)
    assert set(np.unique(part)) <= set(range(4))


@pytest.mark.slow  # full dist pipeline on the virtual mesh: tier-2 (pytest -m slow)
def test_dist_kway_scheme():
    """dist k-way scheme (reference: kway_multilevel.cc): coarsen to C*k,
    direct k-way IP on the replicated coarsest, refine up — no extension."""
    from kaminpar_tpu.context import PartitioningMode
    from kaminpar_tpu.presets import create_context_by_preset_name

    mesh = _mesh()
    ctx = create_context_by_preset_name("default")
    ctx.mode = PartitioningMode.KWAY
    ctx.coarsening.contraction_limit = 32
    g = generators.rmat_graph(11, 8, seed=4)
    k = 8
    solver = DKaMinPar(mesh, ctx)
    part = solver.compute_partition(g, k=k)
    assert part.shape == (g.n,)
    assert len(np.unique(part)) == k
    w = np.bincount(part, weights=np.asarray(g.node_w), minlength=k)
    limit = (1.03 * g.total_node_weight + k - 1) // k + g.max_node_weight
    assert w.max() <= limit
    rng = np.random.default_rng(0)
    assert metrics.edge_cut(g, part) < metrics.edge_cut(g, rng.integers(0, k, g.n))


@pytest.mark.parametrize("algo", ["local-lp", "local-global-lp",
                                  "global-hem-lp"])
@pytest.mark.slow  # full dist pipeline on the virtual mesh: tier-2 (pytest -m slow)
def test_dist_alternative_clusterers_pipeline(algo):
    """LOCAL_LP (pure shard-local clustering -> exchange-free local
    contraction, local_contraction.cc role), LOCAL_GLOBAL_LP (LOCAL_LP
    paired with global rounds) and GLOBAL_HEM_LP (handshake matching + LP
    growth) through the full dist pipeline (reference: dist
    ClusteringAlgorithm, dkaminpar.h:73-78)."""
    from kaminpar_tpu.context import DistClusteringAlgorithm
    from kaminpar_tpu.presets import create_context_by_preset_name

    mesh = _mesh()
    ctx = create_context_by_preset_name("default")
    ctx.coarsening.dist_clustering = DistClusteringAlgorithm(algo)
    g = generators.rmat_graph(10, 8, seed=9)
    k = 8
    solver = DKaMinPar(mesh, ctx)
    part = solver.compute_partition(g, k=k)
    assert part.shape == (g.n,)
    w = np.bincount(part, weights=np.asarray(g.node_w), minlength=k)
    limit = (1.03 * g.total_node_weight + k - 1) // k + g.max_node_weight
    assert w.max() <= limit
    rng = np.random.default_rng(0)
    assert metrics.edge_cut(g, part) < metrics.edge_cut(g, rng.integers(0, k, g.n))


@pytest.mark.slow  # full dist pipeline on the virtual mesh: tier-2 (pytest -m slow)
def test_dist_sharded_extension_pipeline():
    """Sharded extension path (dist/extension.py): the full dist pipeline
    with device_extension engaged at test sizes — no per-level full
    replication — still yields a valid balanced partition."""
    from kaminpar_tpu.presets import create_context_by_preset_name

    mesh = _mesh()
    ctx = create_context_by_preset_name("default")
    ctx.coarsening.contraction_limit = 64
    ctx.initial_partitioning.device_extension = True
    ctx.initial_partitioning.device_extension_n = 512
    ctx.initial_partitioning.device_extension_cpb = 16
    k = 16
    g = generators.rmat_graph(12, 8, seed=3)
    solver = DKaMinPar(mesh, ctx)
    part = solver.compute_partition(g, k=k, epsilon=0.05)
    assert part.shape == (g.n,)
    assert len(np.unique(part)) == k
    W = g.total_node_weight
    per = int(np.ceil(W / k) * 1.05) + int(np.asarray(g.node_w).max())
    bw = np.bincount(part, weights=np.asarray(g.node_w), minlength=k)
    assert (bw <= per).all(), bw
    rng = np.random.default_rng(0)
    assert metrics.edge_cut(g, part) < metrics.edge_cut(g, rng.integers(0, k, g.n))


@pytest.mark.slow  # full dist pipeline on the virtual mesh: tier-2 (pytest -m slow)
def test_mesh_split_replica_refinement():
    """Mesh splitting (deep_multilevel.cc:80-96): R=2 replica groups refine
    two candidates concurrently on disjoint sub-meshes; the returned winner
    matches the reported per-replica cuts."""
    from kaminpar_tpu.dist.replicate import refine_replicated, split_mesh

    mesh = _mesh()
    g = generators.grid2d_graph(20, 20)
    k = 4
    mesh2 = split_mesh(mesh, 2)
    assert mesh2.devices.shape == (2, 4)
    assert mesh2.axis_names == ("rep", "nodes")

    rng = np.random.default_rng(3)
    # replica 0: random garbage; replica 1: a sane-ish stripes partition —
    # selection must prefer the better refined cut
    parts_R = np.stack([
        rng.integers(0, k, g.n).astype(np.int32),
        (np.arange(g.n) * k // g.n).astype(np.int32),
    ])
    cap = jnp.full(k, int(1.2 * g.total_node_weight / k) + 4, dtype=jnp.int32)
    best, cuts = refine_replicated(
        mesh, jax.random.key(0), parts_R, g, cap, k=k, num_rounds=3
    )
    assert best.shape == (g.n,)
    assert len(cuts) == 2
    # the winner's actual cut equals the reported minimum
    assert metrics.edge_cut(g, best) == int(cuts.min())
    # refinement improved on both starts
    assert int(cuts.min()) < metrics.edge_cut(g, parts_R[0])


@pytest.mark.slow  # full dist pipeline on the virtual mesh: tier-2 (pytest -m slow)
def test_dist_nontoy_rmat14_full_partition():
    """Non-toy dist e2e (VERDICT r4 next-steps #6): RMAT scale-14 on the
    8-device mesh — (a) cut within a factor of the shm pipeline's, (b) the
    exchange overflow-doubling path fires at least once under a forced small
    cap, (c) validate_partition passes.  Match:
    tests/endtoend/dist_endtoend_test.cc (the oversubscribed-MPI e2e)."""
    import kaminpar_tpu.dist.lp as dlp
    from kaminpar_tpu.dist.debug import validate_partition
    from kaminpar_tpu.dist.lp import dist_cluster_iterate, shard_arrays
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name

    mesh = _mesh()
    g = generators.rmat_graph(14, 14, seed=5)
    k = 16

    # (b) overflow-doubling witness: iterate with a deliberately tiny owner
    # buffer; record the cap_q escalation through the factory.
    caps_used = []
    orig_factory = dlp.make_dist_cluster_round

    def recording_factory(mesh_, *, cap_q):
        caps_used.append(cap_q)
        return orig_factory(mesh_, cap_q=cap_q)

    dg = distribute_graph(g, mesh.size)
    labels = jnp.arange(dg.N, dtype=jnp.int32)
    labels, dgs = shard_arrays(mesh, dg, labels)
    dlp.make_dist_cluster_round = recording_factory
    try:
        out, _ = dist_cluster_iterate(
            mesh, jax.random.key(3), labels, dgs, jnp.int32(64),
            num_rounds=2, cap_q=64,
        )
    finally:
        dlp.make_dist_cluster_round = orig_factory
    assert len(caps_used) >= 2 and max(caps_used) > 64, (
        f"overflow-doubling never fired: caps {caps_used}"
    )
    # the escalated rounds still respect the cluster cap
    w = np.bincount(np.asarray(out)[: g.n], minlength=dg.N)
    assert w.max() <= 64

    # (a)+(c) full pipeline at scale 14
    ctx = create_context_by_preset_name("fast")
    ctx.seed = 1
    solver = DKaMinPar(mesh, ctx)
    part = solver.compute_partition(g, k=k, epsilon=0.03)
    dist_cut = metrics.edge_cut(g, part)

    shm_ctx = create_context_by_preset_name("fast")
    shm_ctx.seed = 1
    s = KaMinPar(shm_ctx)
    s.set_graph(g)
    shm_cut = metrics.edge_cut(g, s.compute_partition(k, epsilon=0.03))
    assert dist_cut <= 1.5 * shm_cut, (dist_cut, shm_cut)

    # (c) validate on a re-sharded finest graph + partition
    dgf = distribute_graph(g, mesh.size)
    pfull = np.zeros(dgf.N, dtype=np.int32)
    pfull[: g.n] = part
    plab, dgs2 = shard_arrays(mesh, dgf, jnp.asarray(pfull))
    W = g.total_node_weight
    cap = np.full(k, int(np.ceil(W / k) * 1.03) + int(g.max_node_weight),
                  dtype=np.int64)
    ok, problems = validate_partition(mesh, plab, dgs2, k, cap)
    assert ok, problems


@pytest.mark.slow  # full dist pipeline on the virtual mesh: tier-2 (pytest -m slow)
def test_dist_deep_extends_partition():
    """VERDICT r1 #7 done-criterion: dist deep must produce k > k0 through
    extension during uncoarsening (reference: dist deep_multilevel.cc
    extend_partition), not by partitioning the coarsest straight to k."""
    import numpy as np

    from kaminpar_tpu.context import Context
    from kaminpar_tpu.dist.partitioner import DKaMinPar
    from kaminpar_tpu.graph import generators, metrics
    from kaminpar_tpu.partitioning.partition_utils import compute_k_for_n
    from kaminpar_tpu.presets import create_context_by_preset_name

    mesh8 = _mesh()
    ctx = create_context_by_preset_name("default")
    ctx.coarsening.contraction_limit = 64
    k = 16
    g = generators.rmat_graph(12, 8, seed=3)
    solver = DKaMinPar(mesh8, ctx)
    part = solver.compute_partition(g, k=k, epsilon=0.05)
    # the coarsest could not have carried k blocks
    target_n = max(2 * 64, mesh8.size * 64 // k, 2 * k)
    assert compute_k_for_n(target_n, 64, k) < k
    assert len(np.unique(part)) == k
    W = g.total_node_weight
    per = int(np.ceil(W / k) * 1.05) + int(np.asarray(g.node_w).max())
    bw = np.bincount(part, weights=np.asarray(g.node_w), minlength=k)
    assert (bw <= per).all(), bw


# -- sharded compressed tier (round 15, ISSUE 11) ----------------------------


def _compress_ctx(device_decode, cl=40, seed=3):
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name("default")
    ctx.coarsening.contraction_limit = cl
    ctx.seed = seed
    ctx.compression.enabled = device_decode is not None
    if device_decode is not None:
        ctx.compression.device_decode = device_decode
    return ctx


def test_dist_compressed_view_layout_matches_dense():
    """Layer-1 identity: the staged dense DistGraph (to_dist_graph), the
    plain distribute_graph layout, and the device view's one-dispatch
    materialization agree array for array — pad conventions, ghost slot
    numbering, routing, and the layout scalars."""
    from kaminpar_tpu.dist.compressed import compress_distributed
    from kaminpar_tpu.dist.device_compressed import (
        build_dist_device_view,
        materialize_dist_graph,
    )

    mesh = _mesh()
    g = generators.rmat_graph(9, 8, seed=5)
    dg = distribute_graph(g, mesh.size)
    dcg = compress_distributed(g, mesh.size)
    staged = dcg.to_dist_graph()
    view = build_dist_device_view(dcg)
    dense = materialize_dist_graph(mesh, view)
    assert (view.n_loc, view.m_loc, view.g_loc, view.cap_g) == (
        dg.n_loc, dg.m_loc, dg.g_loc, dg.cap_g
    )
    for other in (staged, dense):
        for f in ("node_w", "edge_u", "col_loc", "edge_w", "send_idx",
                  "recv_map"):
            np.testing.assert_array_equal(
                np.asarray(getattr(dg, f)), np.asarray(getattr(other, f)), f
            )
    assert view.shard_work == dg.shard_work
    # the compressed tier actually shrinks the resident adjacency
    assert view.resident_bytes() < view.dense_resident_bytes()


@pytest.mark.parametrize("P", [8])
def test_dist_compressed_pipeline_bit_identity(P):
    """Acceptance (ISSUE 11): the full sharded deep pipeline off the
    device-resident per-shard compressed streams is bit-identical to the
    dense dist path at the same config — with per-shard budgets + the
    implicit-sync tripwire ARMED, the new dist_compressed_* phases pulling
    ZERO transfers, and ``decompress_arrays`` never called past the view
    build (the no-host-decompress contract).

    Only the full 8-device mesh runs in-process: the P=1/2 legs each
    compile a full extra set of dist shard_map specializations (programs
    key on the mesh), and on a box still at the default
    ``vm.max_map_count`` (65530) the extra JIT mappings push the suite
    process over the limit — a later compile (a serve engine thread ~70
    tests downstream) then segfaults in LLVM (the round-5 box gotcha,
    .claude/skills/verify; bisected to exactly these legs, confirmed by
    the suite passing with the sysctl raised).  The P ∈ {1, 2} coverage
    lives in ``test_dist_compressed_bit_identity_small_meshes`` below,
    which gives each sub-mesh a fresh process — correct under either
    sysctl setting."""
    import kaminpar_tpu.graph.compressed as gcomp
    from kaminpar_tpu.utils import sync_stats

    devs = jax.devices()
    if len(devs) < P:
        pytest.skip(f"need {P} devices")
    mesh = Mesh(np.array(devs[:P]), ("nodes",))
    g = generators.rmat_graph(9, 8, seed=7)
    k = 4

    part_dense = DKaMinPar(mesh, _compress_ctx(None)).compute_partition(g, k=k)

    calls = {"n": 0}
    orig = gcomp.CompressedGraph.decompress_arrays

    def counting(self):
        calls["n"] += 1
        return orig(self)

    from kaminpar_tpu.utils.timer import Timer

    Timer.reset_global()
    sync_stats.reset()
    sync_stats.enable_budget_checks(True)
    gcomp.CompressedGraph.decompress_arrays = counting
    try:
        with sync_stats.tripwire():
            part_comp = DKaMinPar(
                mesh, _compress_ctx("finest")
            ).compute_partition(g, k=k)
    finally:
        gcomp.CompressedGraph.decompress_arrays = orig
        sync_stats.enable_budget_checks(False)
    np.testing.assert_array_equal(part_dense, part_comp)
    # one decode per shard at view build (ghost routing), none afterwards
    assert calls["n"] == P, calls
    # both compressed phases OPENED (timer tree) yet pulled ZERO transfers
    # (a phase with no pulls never enters the sync snapshot — that absence
    # IS the zero-transfer contract, witnessed against the open scope)
    timer = Timer.global_()
    assert timer.phase_seconds("dist_compressed_build") is not None
    assert timer.phase_seconds(
        "dist_uncoarsening", "dist_compressed_decode"
    ) is not None or timer.phase_seconds("dist_compressed_decode") is not None
    phases = sync_stats.snapshot()["phases"]
    for phase in ("dist_compressed_build", "dist_compressed_decode"):
        assert phases.get(phase, {"count": 0})["count"] == 0, (phase, phases)


@pytest.mark.parametrize("P", [1, 2])
def test_dist_compressed_bit_identity_small_meshes(P):
    """The P ∈ {1, 2} legs of the bit-identity acceptance matrix, each in a
    FRESH subprocess: their per-mesh shard_map specializations would spend
    the suite process's memory-map budget (see the P=8 test's docstring),
    and a process boundary keeps tier-1 immune to the box's
    ``vm.max_map_count`` setting.  The child re-runs the exact in-process
    check: dense == compressed, one decompress per shard, budgets +
    tripwire armed."""
    import os
    import subprocess
    import sys

    code = f"""
from kaminpar_tpu.utils.platform import force_cpu_devices
force_cpu_devices(8)
import jax, numpy as np
from jax.sharding import Mesh
import kaminpar_tpu.graph.compressed as gcomp
from kaminpar_tpu.dist.partitioner import DKaMinPar
from kaminpar_tpu.graph import generators
from kaminpar_tpu.presets import create_context_by_preset_name
from kaminpar_tpu.utils import sync_stats

P = {P}
mesh = Mesh(np.array(jax.devices()[:P]), ("nodes",))
g = generators.rmat_graph(9, 8, seed=7)

def ctx(compress, mode):
    c = create_context_by_preset_name("default")
    c.coarsening.contraction_limit = 40
    c.seed = 3
    c.compression.enabled = compress
    c.compression.device_decode = mode
    return c

part_dense = DKaMinPar(mesh, ctx(False, "off")).compute_partition(g, k=4)
calls = dict(n=0)
orig = gcomp.CompressedGraph.decompress_arrays
def counting(self):
    calls["n"] += 1
    return orig(self)
gcomp.CompressedGraph.decompress_arrays = counting
sync_stats.enable_budget_checks(True)
with sync_stats.tripwire():
    part_comp = DKaMinPar(mesh, ctx(True, "finest")).compute_partition(g, k=4)
assert np.array_equal(part_dense, part_comp), "partition diverged"
assert calls["n"] == P, calls
print("IDENTICAL", P)
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-1000:]
    assert f"IDENTICAL {P}" in out.stdout


def test_dist_compressed_vs_single_device_deep():
    """The sharded compressed path's quality tracks the single-device deep
    pipeline at matching config (cut within the dist tier's usual 1.5x
    envelope of the shm pipeline — the test_dist_nontoy bound)."""
    from kaminpar_tpu.kaminpar import KaMinPar

    mesh = _mesh()
    g = generators.rmat_graph(9, 8, seed=7)
    k = 4
    part = DKaMinPar(mesh, _compress_ctx("finest")).compute_partition(g, k=k)
    shm_ctx = _compress_ctx(None)
    shm = KaMinPar(shm_ctx)
    shm.set_graph(g)
    shm_cut = metrics.edge_cut(g, shm.compute_partition(k, epsilon=0.03))
    dist_cut = metrics.edge_cut(g, part)
    assert dist_cut <= 1.5 * max(shm_cut, 1), (dist_cut, shm_cut)
    w = np.bincount(part, weights=np.asarray(g.node_w), minlength=k)
    limit = (1.03 * g.total_node_weight + k - 1) // k + g.max_node_weight
    assert w.max() <= limit


@pytest.mark.slow  # out-of-envelope fallback sweep (~17 s); in-envelope
# bit-identity stays tier-1 across P in {1,2,8} (round-20 tier-1 rebalance)
def test_dist_compressed_fallback_outside_envelope(capsys):
    """Outside the envelope (HEM clustering) the view gate falls back to the
    dense staging path — loudly under device_decode=finest — and the
    pipeline still produces a valid partition off the staged graph."""
    from kaminpar_tpu.context import DistClusteringAlgorithm

    mesh = _mesh()
    g = generators.rmat_graph(9, 8, seed=7)
    ctx = _compress_ctx("finest")
    ctx.coarsening.dist_clustering = DistClusteringAlgorithm.GLOBAL_HEM_LP
    part = DKaMinPar(mesh, ctx).compute_partition(g, k=4)
    assert "dense staging" in capsys.readouterr().err
    assert part.shape == (g.n,)
    assert part.min() >= 0 and part.max() < 4


@pytest.mark.slow  # full dist pipeline x weighted input: tier-2
def test_dist_compressed_weighted_bit_identity():
    """Weighted graphs (non-uniform edge weights ride the uncompressed side
    stream): compressed-vs-dense bit identity holds with the weight stream
    engaged."""
    mesh = _mesh()
    g = generators.rmat_graph(10, 8, seed=11)  # rmat dedup sums weights > 1
    assert int(np.asarray(g.edge_w).max()) > 1, "fixture lost its weights"
    k = 8
    part_dense = DKaMinPar(mesh, _compress_ctx(None)).compute_partition(g, k=k)
    part_comp = DKaMinPar(
        mesh, _compress_ctx("finest")
    ).compute_partition(g, k=k)
    np.testing.assert_array_equal(part_dense, part_comp)


def test_dist_metrics_match_host():
    import numpy as np

    from kaminpar_tpu.dist.lp import shard_arrays
    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.dist.metrics import dist_block_weights, dist_edge_cut
    from kaminpar_tpu.graph import generators, metrics

    mesh8 = _mesh()
    g = generators.rmat_graph(10, 8, seed=1)
    k = 8
    rng = np.random.default_rng(0)
    part = rng.integers(0, k, g.n).astype(np.int32)
    dg = distribute_graph(g, mesh8.size)
    full = np.zeros(dg.N, dtype=np.int32)
    full[: g.n] = part
    import jax.numpy as jnp

    part_dev, dg = shard_arrays(mesh8, dg, jnp.asarray(full))
    assert dist_edge_cut(mesh8, part_dev, dg, k=k) == metrics.edge_cut(g, part)
    np.testing.assert_array_equal(
        dist_block_weights(mesh8, part_dev, dg, k=k),
        np.asarray(metrics.block_weights(g, part, k)),
    )


@pytest.mark.slow  # full dist pipeline on the virtual mesh: tier-2 (pytest -m slow)
def test_dist_pipeline_int64():
    """64-bit dist mode end-to-end (reference: KAMINPAR_64BIT_* switches;
    VERDICT r1 minor: dist tier previously hardcoded int32)."""
    import jax
    import numpy as np

    from kaminpar_tpu.dist.partitioner import DKaMinPar
    from kaminpar_tpu.graph import generators, metrics
    from kaminpar_tpu.presets import create_context_by_preset_name

    with jax.enable_x64(True):
        ctx = create_context_by_preset_name("default")
        ctx.use_64bit_ids = True
        ctx.coarsening.contraction_limit = 128
        g = generators.rgg2d_graph(1024, seed=11)
        k = 4
        solver = DKaMinPar(_mesh(), ctx)
        part = solver.compute_partition(g, k=k, epsilon=0.05)
        W = g.total_node_weight
        per = int(np.ceil(W / k) * 1.05) + int(np.asarray(g.node_w).max())
        bw = np.bincount(part, weights=np.asarray(g.node_w), minlength=k)
        assert (bw <= per).all()
        assert len(np.unique(part)) == k


def test_dist_validate_partition():
    """Reference: dist debug.cc:122 validate_partition analog."""
    import jax.numpy as jnp
    import numpy as np

    from kaminpar_tpu.dist.debug import validate_partition
    from kaminpar_tpu.dist.graph import distribute_graph
    from kaminpar_tpu.dist.lp import shard_arrays
    from kaminpar_tpu.graph import generators

    mesh = _mesh()
    g = generators.rgg2d_graph(1024, seed=14)
    k = 4
    rng = np.random.default_rng(14)
    part = rng.integers(0, k, g.n).astype(np.int32)
    dg = distribute_graph(g, mesh.size)
    full = np.zeros(dg.N, dtype=np.int32)
    full[: g.n] = part
    part_dev, dg = shard_arrays(mesh, dg, jnp.asarray(full))
    ok, problems = validate_partition(mesh, part_dev, dg, k)
    assert ok, problems

    # an out-of-range label must be caught
    bad = np.array(full)
    bad[0] = k + 3
    part_bad, dg = shard_arrays(mesh, dg, jnp.asarray(bad))
    ok, problems = validate_partition(mesh, part_bad, dg, k)
    assert not ok and any("range" in p for p in problems), problems


@pytest.mark.parametrize("strategy", ["best-moves", "local-moves"])
@pytest.mark.slow  # full dist pipeline on the virtual mesh: tier-2 (pytest -m slow)
def test_dist_pipeline_move_execution_strategies(strategy):
    import numpy as np

    from kaminpar_tpu.context import MoveExecutionStrategy
    from kaminpar_tpu.dist.partitioner import DKaMinPar
    from kaminpar_tpu.graph import generators
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name("default")
    ctx.refinement.dist_move_execution = MoveExecutionStrategy(strategy)
    ctx.coarsening.contraction_limit = 128
    g = generators.rgg2d_graph(1024, seed=15)
    k = 4
    part = DKaMinPar(_mesh(), ctx).compute_partition(g, k=k, epsilon=0.05)
    W = g.total_node_weight
    per = int(np.ceil(W / k) * 1.05) + int(np.asarray(g.node_w).max())
    bw = np.bincount(part, weights=np.asarray(g.node_w), minlength=k)
    assert (bw <= per).all()
