"""Distributed contraction + end-to-end dKaMinPar-equivalent pipeline on the
virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from kaminpar_tpu.dist import distribute_graph
from kaminpar_tpu.dist.contraction import contract_dist_clustering, project_partition_up
from kaminpar_tpu.dist.lp import shard_arrays
from kaminpar_tpu.dist.partitioner import DKaMinPar
from kaminpar_tpu.graph import generators, metrics
from kaminpar_tpu.ops.contraction import contract_clustering


def _mesh(num=8):
    devs = jax.devices()
    if len(devs) < num:
        pytest.skip(f"need {num} devices, have {len(devs)}")
    return Mesh(np.array(devs[:num]), ("nodes",))


def _host_contract(graph, labels_global):
    """Single-chip reference contraction for comparison."""
    pv = graph.padded()
    lab_pad = np.full(pv.n_pad, pv.anchor, dtype=np.int32)
    lab_pad[: graph.n] = labels_global
    coarse, coarse_of = contract_clustering(graph, jnp.asarray(lab_pad))
    return coarse, np.asarray(coarse_of)


def test_dist_contraction_matches_host():
    mesh = _mesh()
    g = generators.rmat_graph(9, 8, seed=5)
    dg = distribute_graph(g, mesh.size)
    rng = np.random.default_rng(0)
    # a clustering over global node ids: group id = node id // 3 (valid label
    # choice: labels must be *node ids* of representatives — use min member)
    group = np.arange(dg.N, dtype=np.int32)
    group[: g.n] = (np.arange(g.n) // 3 * 3).astype(np.int32)

    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(group))
    coarse, coarse_of, n_c = contract_dist_clustering(mesh, dgs, labels)

    host_coarse, host_of = _host_contract(g, group[: g.n])
    assert n_c == host_coarse.n
    assert coarse.m == host_coarse.m
    # same total coarse edge weight and node weight
    assert int(np.asarray(coarse.edge_w).sum()) == host_coarse.total_edge_weight
    assert int(np.asarray(coarse.node_w).sum()) == host_coarse.total_node_weight
    # same coarse node weights per compact id (both relabel by first-seen
    # order of cluster representatives = ascending representative id)
    np.testing.assert_array_equal(
        np.asarray(coarse.node_w)[: n_c], np.asarray(host_coarse.node_w)
    )
    # projection consistency: fine nodes in the same cluster share an id
    c_of = np.asarray(coarse_of)[: g.n]
    np.testing.assert_array_equal(c_of, host_of)

    # exact coarse edge set: reconstruct (cu, cv, w) from the dist layout
    # (edge_u is shard-local, col_loc is a local/ghost slot) and compare
    # with the host coarse CSR triples
    eu = np.asarray(coarse.edge_u).reshape(coarse.num_shards, coarse.m_loc)
    cl = np.asarray(coarse.col_loc).reshape(coarse.num_shards, coarse.m_loc)
    w = np.asarray(coarse.edge_w).reshape(coarse.num_shards, coarse.m_loc)
    got = set()
    for s in range(coarse.num_shards):
        real = w[s] > 0
        gg = coarse.ghost_global[s]
        for u_l, slot, ew in zip(eu[s][real], cl[s][real], w[s][real]):
            u = int(u_l) + s * coarse.n_loc
            v = (
                int(slot) + s * coarse.n_loc
                if slot < coarse.n_loc
                else int(gg[slot - coarse.n_loc])
            )
            got.add((u, v, int(ew)))
    rp = np.asarray(host_coarse.row_ptr)
    hc = np.asarray(host_coarse.col_idx)
    hw = np.asarray(host_coarse.edge_w)
    want = {
        (u, int(hc[e]), int(hw[e]))
        for u in range(host_coarse.n)
        for e in range(int(rp[u]), int(rp[u + 1]))
    }
    assert got == want


def test_project_partition_up():
    mesh = _mesh()
    g = generators.grid2d_graph(12, 12)
    dg = distribute_graph(g, mesh.size)
    group = np.arange(dg.N, dtype=np.int32)
    group[: g.n] = (np.arange(g.n) // 4 * 4).astype(np.int32)
    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(group))
    coarse, coarse_of, n_c = contract_dist_clustering(mesh, dgs, labels)

    rng = np.random.default_rng(1)
    cpart = rng.integers(0, 4, coarse.N).astype(np.int32)
    cpart_dev, _ = shard_arrays(mesh, coarse, jnp.asarray(cpart))
    fine = np.asarray(
        project_partition_up(mesh, coarse_of, cpart_dev, n_loc_c=coarse.n_loc)
    )
    c_of = np.asarray(coarse_of)
    np.testing.assert_array_equal(fine[: g.n], cpart[c_of[: g.n]])


@pytest.mark.parametrize("gen,k", [
    (lambda: generators.grid2d_graph(24, 24), 4),
    (lambda: generators.rmat_graph(10, 8, seed=9), 8),
])
def test_dkaminpar_endtoend(gen, k):
    mesh = _mesh()
    g = gen()
    solver = DKaMinPar(mesh)
    part = solver.compute_partition(g, k=k)
    assert part.shape == (g.n,)
    assert part.min() >= 0 and part.max() < k
    # balanced-ish and better than random
    w = np.bincount(part, weights=np.asarray(g.node_w), minlength=k)
    limit = (1.03 * g.total_node_weight + k - 1) // k + g.max_node_weight
    assert w.max() <= limit
    rng = np.random.default_rng(0)
    rand_cut = metrics.edge_cut(g, rng.integers(0, k, g.n))
    assert metrics.edge_cut(g, part) < rand_cut
