"""Mesh-replicated serve fleet tests (ISSUE 14): SLO-aware shape-cell
steering over per-device engine replicas, lane x device 2D fill, warm-cache
inheritance (compile-delta asserted), graph-id stickiness, fleet-level
backpressure (least-loaded retry-after), and the drain + cross-replica
resteer path under concurrent overload — zero lost/duplicated resolutions
(extending the PR 13 queue-admission test to the fleet tier).

Determinism is the acceptance witness: the same (graph, seed, k) request
returns a bit-identical partition regardless of which replica serves it,
asserted across cells x replicas against sequential facade runs.

Tier-1 keeps small graphs and warmup-free engines; the 8-replica x
8-lane aggregate-occupancy sweep is @slow.
"""

import threading
import time

import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.serve import (
    PartitionFleet,
    QueueFullError,
)
from kaminpar_tpu.serve.batching import shape_cell
from kaminpar_tpu.serve.stats import ServeStats

SMALL = dict(warm_ladder=(), warm_ks=(), max_batch=4, queue_bound=16,
             lane_stack="off")


def _rmat(seed, scale=8):
    return generators.rmat_graph(scale, edge_factor=4, seed=seed)


def _fleet(replicas=2, **overrides):
    kw = dict(SMALL)
    kw.update(overrides)
    return PartitionFleet("serve", replicas=replicas, **kw)


def _same_cell_graphs(n, k, scale=8):
    pool = [_rmat(seed=50 + i, scale=scale) for i in range(3 * n)]
    cells = [shape_cell(g, k) for g in pool]
    head = max(set(cells), key=cells.count)
    graphs = [g for g, c in zip(pool, cells) if c == head][:n]
    assert len(graphs) == n, "could not build a same-cell workload"
    return graphs


# ---------------------------------------------------------------------------
# Steering: lane-axis fill before device-axis spill, poisoned-cell avoidance
# ---------------------------------------------------------------------------


def test_steering_fills_lanes_then_spills_to_next_device():
    fleet = _fleet(replicas=2, max_batch=4)
    fleet.pause()  # before start: hold dispatch until the burst is queued
    fleet.start(warmup=False)
    try:
        graphs = _same_cell_graphs(8, k=4)
        futs = [fleet.submit(g, 4) for g in graphs]
        routed = [f.replica for f in futs]
        # Batch-join fill policy: the first max_batch requests land on one
        # replica (the lane axis fills), the rest spill to the sibling.
        assert sorted(routed) == [0, 0, 0, 0, 1, 1, 1, 1]
        assert routed[:4] == [routed[0]] * 4
        fleet.resume()
        for f in futs:
            f.result(timeout=600)
        snap = fleet.stats()
        occ = [r["batch_occupancy_max"] for r in snap["per_replica"]]
        assert sorted(occ) == [4, 4]
        assert snap["aggregate_occupancy"] == 8.0
        assert snap["resteers"] == 0
    finally:
        fleet.shutdown(drain=True)


def test_steering_avoids_replica_with_open_cell_breaker():
    fleet = _fleet(replicas=2).start(warmup=False)
    try:
        g = _rmat(seed=1)
        cell = shape_cell(g, 4)
        key = (cell.n_bucket, cell.m_bucket, cell.k)
        fleet.replicas[0].breakers.get("cell", key).trip()
        futs = [fleet.submit(g, 4) for _ in range(3)]
        assert all(f.replica == 1 for f in futs)
        for f in futs:
            f.result(timeout=600)
    finally:
        fleet.shutdown(drain=True)


def test_sticky_routing_hits_and_moves_on_drain():
    fleet = _fleet(replicas=2).start(warmup=False)
    try:
        g = _rmat(seed=2)
        home = fleet.submit(g, 4, graph_id="tenant-a").replica
        # Load the OTHER replica lightly so pure load-based steering would
        # prefer it; stickiness must keep tenant-a on its home replica.
        futs = [fleet.submit(g, 4, graph_id="tenant-a") for _ in range(3)]
        assert all(f.replica == home for f in futs)
        assert fleet.stats()["sticky_hits"] == 3
        for f in futs:
            f.result(timeout=600)
        fleet.drain_replica(home, reason="test")
        fut = fleet.submit(g, 4, graph_id="tenant-a")
        assert fut.replica != home
        fut.result(timeout=600)
        assert fleet.stats()["sticky_moves"] >= 1
    finally:
        fleet.shutdown(drain=True)


# ---------------------------------------------------------------------------
# Determinism: bit-identity across cells x replicas (acceptance witness)
# ---------------------------------------------------------------------------


def test_partition_bit_identical_across_replicas_and_cells():
    fleet = _fleet(replicas=2).start(warmup=False)
    try:
        for scale, k in ((7, 2), (9, 4)):  # two distinct shape cells
            g = _rmat(seed=3, scale=scale)
            solver = KaMinPar("serve")
            solver.set_graph(g)
            ref = solver.compute_partition(k, 0.03)
            for replica in range(2):
                part = fleet.submit(
                    g, k, replica=replica
                ).result(timeout=600).partition
                assert np.array_equal(part, ref), (
                    f"replica {replica} diverged at scale={scale} k={k}"
                )
    finally:
        fleet.shutdown(drain=True)


# ---------------------------------------------------------------------------
# Warm-cache inheritance: replica N+1 skips every cell already traced
# ---------------------------------------------------------------------------


def test_warm_inheritance_zero_compile_delta():
    from kaminpar_tpu.utils import compile_stats

    fleet = _fleet(
        replicas=2, warm_ladder=(256,), warm_ks=(4,),
    )
    try:
        compile_stats.enable_compile_time_tracking()
        fleet.replicas[0].start(warmup=True)
        assert fleet.replicas[0].warmup_cell_counts()["local"] >= 1
        before = compile_stats.compile_time_snapshot()["compile_events"]
        fleet.replicas[1].inherit_warmup(fleet.replicas[0])
        fleet.replicas[1].start(warmup=True)
        after = compile_stats.compile_time_snapshot()["compile_events"]
        # The inheriting replica skips every cell already traced: its
        # start raises ZERO compile events (the acceptance delta).
        assert after - before == 0
        counts = fleet.replicas[1].warmup_cell_counts()
        assert counts["inherited"] >= 1
        assert counts["local"] == 0
        assert all(
            row.get("inherited") for row in fleet.replicas[1].warmup_report
        )
        # The warm EMA seed carries over so retry-after estimates are real
        # from the first reject on the new replica too.
        assert fleet.replicas[1].stats_.service_time_estimate() > 0.0
        # Inherited-vs-local counts ride the engine Prometheus exposition.
        text = fleet.replicas[1].metrics_text()
        assert 'kaminpar_serve_warmup_cells_total{source="inherited"}' in text
        # The warm-hit accounting inherited too: a request in the
        # inherited cell reports warm at submit time.
        fleet._started = True
        g = generators.rmat_graph(8, edge_factor=8, seed=1)
        fut = fleet.submit(g, 4, replica=1)
        res = fut.result(timeout=600)
        assert res.warm_hit
    finally:
        fleet._started = True
        fleet.shutdown(drain=True)


def test_fleet_start_inherits_and_shares_cache_dir():
    fleet = _fleet(replicas=3, warm_ladder=(256,), warm_ks=(4,))
    try:
        fleet.start(warmup=True)
        dirs = {eng.runtime.cache_dir for eng in fleet.replicas}
        assert len(dirs) == 1, "fleet replicas must share one cache dir"
        devices = [
            eng.runtime.device_index for eng in fleet.replicas
        ]
        assert devices == [0, 1, 2], "one replica per mesh device"
        counts = [r.warmup_cell_counts() for r in fleet.replicas]
        assert counts[0]["local"] >= 1 and counts[0]["inherited"] == 0
        for c in counts[1:]:
            assert c["inherited"] >= 1 and c["local"] == 0
    finally:
        fleet.shutdown(drain=True)


# ---------------------------------------------------------------------------
# Fleet-level backpressure: least-loaded drain estimate (ISSUE 14 satellite)
# ---------------------------------------------------------------------------


def test_queue_full_retry_after_from_least_loaded_replica():
    fleet = _fleet(replicas=2, queue_bound=2, max_batch=4).start(warmup=False)
    try:
        fleet.pause()
        # Distinct smoothed service times: replica 0 slow, replica 1 fast.
        fleet.replicas[0].stats_.seed_service_time(10.0)
        fleet.replicas[1].stats_.seed_service_time(0.2)
        g = _rmat(seed=4)
        for _ in range(4):  # fill both bounded queues (2 + 2)
            fleet.submit(g, 4)
        with pytest.raises(QueueFullError) as exc:
            fleet.submit(g, 4)
        # The hint must be the LEAST-LOADED replica's drain estimate
        # (depth x EMA / max_batch = 2 x 0.2 / 4), not the rejecting (or
        # slowest) replica's 2 x 10 / 4 = 5 s.
        expected = fleet.replicas[1].stats_.retry_after_estimate(2, 4)
        assert abs(exc.value.retry_after_s - expected) < 1e-9
        assert exc.value.retry_after_s < 1.0
        assert fleet.stats()["rejected_full"] == 1
    finally:
        fleet.resume()
        fleet.shutdown(drain=True)


def test_retry_after_stays_unamortized_for_lanestacked_batches():
    # The PR 6 rule feeding the fleet estimate: the EMA takes the
    # UNAMORTIZED batch wall (service_s), not the per-lane execute share,
    # because retry_after_estimate divides by the batch width itself.
    stats = ServeStats()
    stats.record_request(0.0, 0.1, service_s=0.8)  # share 0.1s of a 0.8s stack
    assert abs(stats.service_time_estimate() - 0.8) < 1e-9
    assert abs(stats.retry_after_estimate(4, 8) - 4 * 0.8 / 8) < 1e-9


def test_unroutable_fleet_rejects_with_retry_hint():
    fleet = _fleet(replicas=2).start(warmup=False)
    try:
        fleet.drain_replica(0, reason="test")
        fleet.drain_replica(1, reason="test")
        with pytest.raises(QueueFullError) as exc:
            fleet.submit(_rmat(seed=5), 4)
        assert exc.value.retry_after_s > 0.0
        assert fleet.stats()["rejected_unroutable"] == 1
    finally:
        fleet.shutdown(drain=True)


# ---------------------------------------------------------------------------
# Drain + cross-replica resteer (extends the PR 13 queue-admission test)
# ---------------------------------------------------------------------------


def test_drain_resteer_concurrent_overload_no_lost_no_duplicated():
    """8 threads submit past one replica's batch capacity while that
    replica is drained mid-burst: every admitted request resolves exactly
    once (on a healthy replica), every reject carries a sane retry_after,
    and fleet ids stay unique — the PR 13 force-resolve machinery extended
    to cross-replica requeue."""
    fleet = _fleet(replicas=2, queue_bound=8, max_batch=2)
    # Pause BEFORE the dispatchers start: a post-start pause only takes
    # effect before the *next* batch (the dispatcher may already be inside
    # pop_batch), which would let the victim serve a batch pre-drain.
    fleet.pause()
    fleet.start(warmup=False)
    graphs = _same_cell_graphs(4, k=4)
    futures, rejects, errors = [], [], []
    lock = threading.Lock()
    barrier = threading.Barrier(8)

    def submit(i):
        barrier.wait()
        try:
            fut = fleet.submit(graphs[i % 4], 4)
            with lock:
                futures.append(fut)
        except QueueFullError as exc:
            with lock:
                rejects.append(exc.retry_after_s)
        except Exception as exc:  # noqa: BLE001 — the test records strays
            with lock:
                errors.append(exc)

    try:
        threads = [
            threading.Thread(target=submit, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, f"unexpected submit errors: {errors}"
        assert len(futures) + len(rejects) == 8, "no submission lost"
        for retry in rejects:
            assert 0.0 < retry < 60.0, f"insane retry_after {retry}"
        # Drain the replica holding the most queued work while every
        # request is still queued (dispatch is held) — the eager leg
        # requeues all of them on the sibling, honoring its bound.
        routed = [f.replica for f in futures]
        victim = max(set(routed), key=routed.count)
        fleet.drain_replica(victim, reason="test overload drain")
        deadline = time.monotonic() + 60
        while fleet.replicas[victim].running and time.monotonic() < deadline:
            time.sleep(0.02)
        fleet.resume()
        results = [f.result(timeout=600) for f in futures]
        ids = [f.fleet_id for f in futures]
        assert len(set(ids)) == len(ids), "duplicated resolution"
        assert all(r.partition is not None for r in results)
        # Every drained request moved off the victim.
        assert all(f.replica != victim for f in futures)
        snap = fleet.stats()
        assert snap["drains"] == 1
        assert snap["resteers"] >= routed.count(victim)
        # A second result() call returns the SAME resolution (first-wins
        # finalization).
        again = futures[0].result(timeout=5)
        assert again is results[0]
    finally:
        fleet.shutdown(drain=True)


def test_drained_replica_restored_by_half_open_probe():
    fleet = _fleet(replicas=2).start(warmup=False)
    fleet.fleet_ctx.replica_cooldown_s = 0.2
    fleet.breakers.cooldown_s = 0.2
    # Score on queue depth alone: both replicas carry noisy warm-up p99
    # samples, and this test is about probe admission, not tail steering.
    fleet.fleet_ctx.steer_p99_weight = 0.0
    try:
        g = _rmat(seed=6)
        fleet.submit(g, 4, replica=0).result(timeout=600)
        fleet.drain_replica(0, reason="test")
        # Tripped breaker: replica 0 is out of rotation.
        assert fleet.submit(g, 4).replica == 1
        # Recreate the breaker with the short cooldown (the registry's
        # default cooldown applied when the breaker was first created).
        br = fleet.breakers.get("replica", (0,))
        br.cooldown_s = 0.2
        br.trip()
        time.sleep(0.3)
        # Load replica 1 so the score prefers the probe-restored replica 0.
        fleet.pause()
        futs = [fleet.submit(g, 4) for _ in range(6)]
        fleet.resume()
        for f in futs:
            f.result(timeout=600)
        assert any(f.replica == 0 for f in futs), (
            "half-open probe should have restored + used replica 0"
        )
        assert fleet.stats()["restores"] >= 1
        assert fleet.replicas[0].running
    finally:
        fleet.shutdown(drain=True)


def test_sticky_home_capacity_reject_steers_to_bigger_sibling():
    """Sticky/pinned candidates bypass the scan's capacity filter, so a
    request oversize for its home replica must fall through to a sibling
    with a larger ceiling instead of surfacing CapacityError — ceilings
    are per-replica (heterogeneous fleets)."""
    from kaminpar_tpu.serve.errors import CapacityError

    fleet = _fleet(replicas=2).start(warmup=False)
    try:
        g = _rmat(seed=9)
        home = fleet.submit(g, 4, graph_id="tenant-c").replica
        # Shrink the home replica's ceiling so its admission preflight
        # now rejects this cell; the sibling keeps the real ceiling.
        fleet.replicas[home]._capacity_ceiling = 1
        fut = fleet.submit(g, 4, graph_id="tenant-c")
        assert fut.replica != home
        fut.result(timeout=600)
        # When EVERY replica is too small the typed error surfaces (with
        # the router counter bumped), not a retry-forever hint.
        for eng in fleet.replicas:
            eng._capacity_ceiling = 1
        with pytest.raises(CapacityError):
            fleet.submit(g, 4)
        assert fleet.stats()["rejected_capacity"] >= 1
    finally:
        fleet.shutdown(drain=True)


def test_inflight_success_during_drain_keeps_breaker_open():
    """A success delivered by a DRAINING replica (in-flight work finishing
    inside the bounded drain) must NOT close its tripped fleet breaker:
    closed + draining is unroutable forever — only the half-open probe
    path clears the draining flag, and it requires a non-closed breaker."""
    fleet = _fleet(replicas=2)
    fleet.breakers.cooldown_s = 0.2
    fleet.start(warmup=False)
    try:
        g = _rmat(seed=8)
        fleet.submit(g, 4, replica=0).result(timeout=600)
        fleet.drain_replica(0, reason="test")
        t = fleet._drain_threads[0]
        assert t is not None
        t.join(60)
        assert not t.is_alive()
        # The in-flight success arrives after the trip: the waiter-side
        # hook must leave the tripped breaker open while draining.
        from kaminpar_tpu.serve.fleet import _FleetRecord

        rec = _FleetRecord(999, g, 4, 0.03, {}, None)
        rec.replica = 0
        fleet._note_success(rec)
        br = fleet.breakers.get("replica", (0,))
        assert br.state == "open", (
            "success during drain must not close the replica breaker"
        )
        # The half-open probe still restores the replica afterwards.
        time.sleep(0.3)
        ok, is_probe = fleet._replica_available(0)
        assert ok and is_probe
        assert fleet.replicas[0].running
        assert not fleet._draining[0]
    finally:
        fleet.shutdown(drain=True)


# ---------------------------------------------------------------------------
# Observability: Prometheus exposition, phase registry, trace instants
# ---------------------------------------------------------------------------


def test_fleet_prometheus_exposition_validates():
    from kaminpar_tpu.telemetry import prometheus

    fleet = _fleet(replicas=2).start(warmup=False)
    try:
        fleet.submit(_rmat(seed=7), 4).result(timeout=600)
        text = fleet.metrics_text()
        prometheus.validate(text)
        assert "kaminpar_fleet_replicas 2" in text
        assert "kaminpar_fleet_steered_total" in text
        assert "kaminpar_fleet_warmup_cells_total" in text
        assert 'scope="fleet"' in text
        snap = fleet.stats()
        assert snap["breakers"]["scope"] == "fleet"
    finally:
        fleet.shutdown(drain=True)


def test_fleet_steer_phase_registered():
    from kaminpar_tpu.telemetry import phases

    assert phases.is_known("fleet_steer")


def test_replica_rung_in_ladder():
    from kaminpar_tpu.resilience.breakers import LADDER

    assert LADDER["replica"] == "resteer"


# ---------------------------------------------------------------------------
# Lane x device 2D: per-replica lane-stacked batches
# ---------------------------------------------------------------------------


def test_lanestacked_batches_across_replicas():
    fleet = _fleet(replicas=2, max_batch=2, lane_stack="auto")
    fleet.pause()  # before start: hold dispatch until the burst is queued
    fleet.start(warmup=False)
    try:
        graphs = _same_cell_graphs(4, k=2, scale=7)
        futs = [fleet.submit(g, 2) for g in graphs]
        assert sorted(f.replica for f in futs) == [0, 0, 1, 1]
        fleet.resume()
        for f in futs:
            f.result(timeout=600)
        snap = fleet.stats()
        stacked = [r["lanestacked_batches"] for r in snap["per_replica"]]
        lanes = [r["lanestacked_lanes"] for r in snap["per_replica"]]
        # Each replica ran its micro-batch as ONE vmapped stack (the lane
        # axis) on its own device (the device axis).
        assert all(s >= 1 for s in stacked)
        assert snap["aggregate_lanestacked_lanes"] == sum(lanes) == 4
    finally:
        fleet.shutdown(drain=True)


@pytest.mark.slow
def test_aggregate_occupancy_64_on_8_replica_mesh():
    """The ROADMAP "millions of users" configuration on the CPU dryrun:
    64 same-cell requests over 8 replicas x max_batch 8 fill the full
    lane x device plane (aggregate occupancy >= 64), with per-replica
    results bit-identical to a sequential facade run."""
    fleet = _fleet(replicas=8, max_batch=8, queue_bound=64)
    fleet.pause()  # before start: hold dispatch until the burst is queued
    fleet.start(warmup=False)
    try:
        graphs = _same_cell_graphs(64, k=4)
        solver = KaMinPar("serve")
        solver.set_graph(graphs[0])
        ref = solver.compute_partition(4, 0.03)
        futs = [fleet.submit(g, 4) for g in graphs]
        fleet.resume()
        results = [f.result(timeout=1800) for f in futs]
        assert np.array_equal(results[0].partition, ref)
        snap = fleet.stats()
        assert snap["aggregate_occupancy"] >= 64.0
        used = {f.replica for f in futs}
        assert used == set(range(8))
    finally:
        fleet.shutdown(drain=True)
