"""Mesh-scale telemetry (ISSUE 8): per-shard sync accounting, the
collective-traffic census, and merged multi-rank traces on the virtual
8-device CPU mesh.

The contracts under test:

- the dist pipeline's per-shard sync budgets hold in-pipeline with
  telemetry ARMED (armed probes add zero blocking transfers — asserted via
  the unchanged ``assert_phase_budget(shards=P)`` checks AND an explicit
  per-phase pull-count equality between armed and off runs);
- the collective census counts match a **hand-counted** expectation for
  one LP refinement round and one balancer round (the census is trace-time
  accounting, so one traced round body has a fixed, structurally derivable
  op count);
- arming telemetry is bit-inert on the dist tier (same partition);
- the merged trace validates as Chrome trace JSON and carries one lane per
  shard whose span walls ``tools trace --shards`` summarizes.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from kaminpar_tpu import telemetry
from kaminpar_tpu.dist import distribute_graph
from kaminpar_tpu.dist.partitioner import DKaMinPar
from kaminpar_tpu.graph import generators
from kaminpar_tpu.telemetry import trace as ttrace
from kaminpar_tpu.utils import collective_stats, sync_stats


def _mesh(num=8):
    devs = jax.devices()
    if len(devs) < num:
        pytest.skip(f"need {num} devices, have {len(devs)}")
    return Mesh(np.array(devs[:num]), ("nodes",))


@pytest.fixture(autouse=True)
def _clean_state():
    ttrace.stop()
    sync_stats.reset()
    collective_stats.reset()
    yield
    ttrace.stop()
    sync_stats.reset()
    collective_stats.reset()
    sync_stats.enable_budget_checks(False)


def _dist_ctx(cl=40, seed=3):
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name("default")
    ctx.coarsening.contraction_limit = cl  # force a real dist hierarchy
    ctx.seed = seed
    return ctx


# -- collective census --------------------------------------------------------


def test_collective_census_matches_hand_count():
    """Acceptance (ISSUE 8): census counts for ONE traced LP refinement
    round and ONE traced balancer round equal the hand count of their round
    bodies.  The census is trace-time accounting (utils/collective_stats),
    so the expectation is structural, not statistical."""
    import kaminpar_tpu.dist.lp as dlp
    from kaminpar_tpu.dist.balancer import make_dist_balance_round
    from kaminpar_tpu.dist.lp import shard_arrays

    mesh = _mesh()
    g = generators.grid2d_graph(16, 16)
    dg = distribute_graph(g, mesh.size)
    k = 4
    part = jnp.asarray(
        np.random.default_rng(0).integers(0, k, dg.N).astype(np.int32)
    )
    part, dgs = shard_arrays(mesh, dg, part)
    cap = jnp.full(k, int(1.2 * g.total_node_weight / k) + 4, dtype=jnp.int32)

    # Force a fresh trace: the factories are lru_cached and the census
    # counts per TRACED program, so a previously traced round contributes
    # nothing (by design — that is the zero-per-execution-cost property).
    dlp.make_dist_lp_round.cache_clear()
    make_dist_balance_round.cache_clear()

    collective_stats.reset()
    with sync_stats.scoped("dist_refinement"):
        dlp.dist_lp_round(
            mesh, jax.random.key(0), part, dgs, cap, num_labels=k
        )
    ops = collective_stats.phase_ops("dist_refinement")
    # Hand count of _refine_round_body (external_only=False, 1 chunk):
    #   ghost_exchange ............................ 1 all_to_all
    #   _global_block_weights ..................... 1 psum
    #   _probabilistic_commit demand .............. 1 psum
    #   _overweight_rollback: overweight_fixable is
    #     traced TWICE (loop init + while body), 2 psums each ... 4 psums
    #   num_moved ................................. 1 psum
    assert ops == {"all_to_all": 1, "psum": 7}, ops
    # Logical bytes of the exchange: per-shard (P, cap_g) int32 operand
    # times the P participating shards.
    snap = collective_stats.snapshot()["phases"]["dist_refinement"]
    P = mesh.size
    assert snap["ops"]["all_to_all"]["logical_bytes"] == (
        P * dgs.cap_g * 4 * P
    )

    collective_stats.reset()
    fn = make_dist_balance_round(mesh, k=k)
    with sync_stats.scoped("dist_refinement"):
        fn(jax.random.key(1), part, dgs.node_w, dgs.edge_u, dgs.col_loc,
           dgs.edge_w, cap, dgs.send_idx, dgs.recv_map)
    ops = collective_stats.phase_ops("dist_refinement")
    # Hand count of _balance_round_body:
    #   ghost_exchange 1 all_to_all; block_w, cand_w, demand psums (3);
    #   rollback fixable traced twice (4); new_bw + moved psums (2).
    assert ops == {"all_to_all": 1, "psum": 9}, ops

    # Re-executing the SAME compiled round adds nothing: the census is
    # per-specialization, like the compiled-shape census.
    before = collective_stats.snapshot()["count"]
    fn(jax.random.key(2), part, dgs.node_w, dgs.edge_u, dgs.col_loc,
       dgs.edge_w, cap, dgs.send_idx, dgs.recv_map)
    assert collective_stats.snapshot()["count"] == before


# -- mesh dryrun: armed budgets + merged trace + bit identity ----------------


def test_mesh_dryrun_budgets_trace_and_probe_neutrality(tmp_path):
    """Acceptance (ISSUE 8): the 8-device dryrun runs with telemetry ARMED
    and per-shard budgets asserted in-pipeline, produces ONE merged Chrome
    trace with a lane per shard, and arming changes neither the partition
    nor any dist phase's blocking-transfer count."""
    mesh = _mesh()
    P = mesh.size
    g = generators.rmat_graph(9, 8, seed=7)
    out = tmp_path / "mesh_trace.json"

    # Off run FIRST: same seed, telemetry disarmed.  Besides providing the
    # bit-identity/neutrality reference, it traces every program of this
    # configuration — so the armed run below can additionally prove that
    # arming telemetry adds ZERO collectives (trace-time census delta 0).
    sync_stats.reset()
    part_off = DKaMinPar(mesh, _dist_ctx()).compute_partition(g, k=4)
    off_phases = sync_stats.snapshot()["phases"]
    coll_before = collective_stats.snapshot()["count"]

    # Armed run: budgets + tripwire + telemetry, all at once — the probes
    # must pass the SAME armed checks the bare pipeline passes.
    sync_stats.reset()
    sync_stats.enable_budget_checks(True)
    try:
        with telemetry.run(trace_out=str(out)) as rec:
            with sync_stats.tripwire():
                part_armed = DKaMinPar(mesh, _dist_ctx()).compute_partition(
                    g, k=4
                )
    finally:
        sync_stats.enable_budget_checks(False)
    # Zero added collectives with telemetry armed (everything was already
    # traced by the off run, so any delta would be telemetry's own).
    assert collective_stats.snapshot()["count"] == coll_before
    armed_phases = sync_stats.snapshot()["phases"]
    dist_phases = [p for p in armed_phases if p.startswith("dist_")]
    assert "dist_coarsening" in dist_phases  # the hierarchy actually formed
    for phase in dist_phases:
        assert armed_phases[phase]["implicit"] == 0, (phase, armed_phases)
        # per-shard accounting engaged: mesh-wide pulls carry shards=P
        if phase in ("dist_coarsening", "dist_refinement"):
            row = armed_phases[phase]
            assert row["shard_pulls"] == row["sharded_count"] * P

    # Quality rows for both dist level kinds rode existing pulls.
    kinds = {r["kind"] for r in rec.quality}
    assert "dist_coarsening_level" in kinds
    assert "dist_uncoarsening_level" in kinds

    # One merged Chrome trace: validates, carries a lane per shard, and
    # the shard lanes expose per-level spans.
    obj = json.loads(out.read_text())
    summary = telemetry.validate_chrome_trace(obj)
    assert "dist_coarsening_level" in summary["span_names"]
    lanes = {
        (e.get("args") or {}).get("name")
        for e in obj["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert {f"shard{s}" for s in range(P)} <= lanes
    rows = ttrace.shard_lane_summary(obj)
    assert rows and all(len(r["walls_ms"]) == P for r in rows)
    assert all(r["imb"] >= 1.0 for r in rows)

    # Probe neutrality, PR 5 style: bit-identical partition and per-phase
    # pull-count equality between the armed and off runs.
    assert np.array_equal(part_armed, part_off)
    for phase in dist_phases:
        assert (
            armed_phases[phase]["count"]
            == off_phases.get(phase, {"count": 0})["count"]
        ), (phase, armed_phases[phase], off_phases.get(phase))
        assert (
            armed_phases[phase]["shard_pulls"]
            == off_phases.get(phase, {"shard_pulls": 0})["shard_pulls"]
        )


def test_tools_trace_shards_summary(tmp_path, capsys):
    """``tools trace --shards`` prints the per-shard imbalance table from a
    mesh trace's lane spans (and stays quiet on a non-mesh trace)."""
    from kaminpar_tpu.tools.__main__ import main as tools_main

    rec = ttrace.TraceRecorder()
    rec.begin("dist_coarsening")
    # Two shard lanes, 3:1 work skew across two levels.
    for level, t0 in ((0, 0.0), (1, 1000.0)):
        rec.lane_span("shard0", "dist_coarsening_level", t0, t0 + 900.0,
                      level=level)
        rec.lane_span("shard1", "dist_coarsening_level", t0, t0 + 300.0,
                      level=level)
    rec.end("dist_coarsening")
    path = tmp_path / "t.json"
    rec.write(str(path))

    rows = ttrace.shard_lane_summary(json.loads(path.read_text()))
    assert len(rows) == 1
    row = rows[0]
    assert row["name"] == "dist_coarsening_level"
    assert row["walls_ms"] == [1.8, 0.6]  # 2 x 900us / 2 x 300us
    assert row["imb"] == pytest.approx(1.5)

    assert tools_main(["trace", str(path), "--shards"]) == 0
    out = capsys.readouterr().out
    assert "imb 1.50" in out
    assert "shard-lane walls over 2 shards" in out

    # A trace without shard lanes reports none instead of failing.
    rec2 = ttrace.TraceRecorder()
    rec2.begin("partitioning")
    rec2.end("partitioning")
    path2 = tmp_path / "t2.json"
    rec2.write(str(path2))
    assert tools_main(["trace", str(path2), "--shards"]) == 0
    assert "shard lanes: (none" in capsys.readouterr().out


# -- sharded compressed tier (round 15) ---------------------------------------


def test_dist_compressed_phases_budgets_and_zero_collectives(tmp_path):
    """Round-15 contracts for the new dist_compressed_* phases, checked on
    the armed 8-device dryrun: (a) both phases record ZERO blocking
    transfers and ZERO collectives (the view build is host packing +
    device puts; the materialization is one local sharded decode — no
    psum/all_to_all anywhere in either); (b) the armed compressed run
    passes the same in-pipeline per-shard budgets as the dense pipeline
    with the implicit-sync tripwire up; (c) re-running the already-traced
    compressed programs adds nothing to the collective census."""
    from kaminpar_tpu.presets import create_context_by_preset_name

    mesh = _mesh()
    g = generators.rmat_graph(9, 8, seed=7)

    def ctx():
        c = create_context_by_preset_name("default")
        c.coarsening.contraction_limit = 40
        c.seed = 3
        c.compression.enabled = True
        c.compression.device_decode = "finest"
        return c

    sync_stats.reset()
    collective_stats.reset()
    sync_stats.enable_budget_checks(True)
    try:
        with telemetry.run(trace_out=str(tmp_path / "t.json")):
            with sync_stats.tripwire():
                part1 = DKaMinPar(mesh, ctx()).compute_partition(g, k=4)
    finally:
        sync_stats.enable_budget_checks(False)
    phases = sync_stats.snapshot()["phases"]
    for phase in ("dist_compressed_build", "dist_compressed_decode"):
        # a zero-pull phase never enters the snapshot — its absence (or an
        # all-zero row) is the contract; any transfer would materialize a row
        row = phases.get(phase, {"count": 0, "implicit": 0})
        assert row["count"] == 0 and row["implicit"] == 0, (phase, row)
        assert collective_stats.phase_ops(phase) == {}, phase

    # (c) a second identical run re-executes the same compiled programs:
    # the trace-time census must not move, and the partition is stable.
    before = collective_stats.snapshot()["count"]
    part2 = DKaMinPar(mesh, ctx()).compute_partition(g, k=4)
    assert collective_stats.snapshot()["count"] == before
    np.testing.assert_array_equal(part1, part2)


# -- shard work table ---------------------------------------------------------


def test_shard_work_table_zero_pull_stats():
    """distribute_graph populates the host-computed per-shard work table;
    collect_graph_stats consumes it WITHOUT any device readback, and the
    render/machine_readable outputs carry the skew summary column."""
    from kaminpar_tpu.dist.shard_stats import collect_graph_stats

    g = generators.rmat_graph(9, 8, seed=5)
    dg = distribute_graph(g, 8)
    assert len(dg.shard_work) == 8
    assert sum(w["owned_nodes"] for w in dg.shard_work) == g.n
    assert sum(w["owned_edges"] for w in dg.shard_work) == g.m
    for w, gg in zip(dg.shard_work, dg.ghost_global):
        assert w["ghost_nodes"] == len(gg)

    sync_stats.reset()
    st = collect_graph_stats(dg)
    assert sync_stats.snapshot()["count"] == 0  # zero readbacks
    assert st.stats("owned_nodes")["imb"] >= 1.0
    agg = st.imbalance_summary()
    assert agg["max_imb"] >= agg["mean_imb"] >= 1.0
    assert agg["worst"] in ("owned_nodes", "owned_edges", "ghost_nodes",
                            "interface_nodes")
    assert "SHARDSTAT_SUMMARY" in st.machine_readable()
    assert "imbalance" in st.render()


def test_coarse_graph_carries_shard_work():
    """The contraction assembly populates shard_work for coarse levels too
    (from its own host-resident assembly arrays)."""
    from kaminpar_tpu.dist.contraction import contract_dist_clustering
    from kaminpar_tpu.dist.lp import shard_arrays

    mesh = _mesh()
    g = generators.rmat_graph(9, 8, seed=5)
    dg = distribute_graph(g, mesh.size)
    group = np.arange(dg.N, dtype=np.int32)
    group[: g.n] = (np.arange(g.n) // 3 * 3).astype(np.int32)
    labels, dgs = shard_arrays(mesh, dg, jnp.asarray(group))
    coarse, _, n_c = contract_dist_clustering(mesh, dgs, labels)
    assert len(coarse.shard_work) == mesh.size
    assert sum(w["owned_nodes"] for w in coarse.shard_work) == n_c
    assert sum(w["owned_edges"] for w in coarse.shard_work) == coarse.m
