"""Bucketed fast-path vs flat reference-path equivalence.

The flat sort-reduce ops (ops/gains.py, ops/lp.py lp_round) are the semantic
reference; the degree-bucketed kernels (ops/bucketed_gains.py) must compute
identical ratings/feasibility (targets may differ only within random
tie-breaks, so we compare tie-break-independent quantities)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kaminpar_tpu.graph import generators
from kaminpar_tpu.graph.bucketed import build_bucketed_view
from kaminpar_tpu.ops import lp
from kaminpar_tpu.ops.bucketed_gains import bucketed_best_moves
from kaminpar_tpu.ops.gains import best_moves
from kaminpar_tpu.utils import next_key


def _random_graph(rng, n=200, extra_edges=400, weighted=True):
    edges = rng.integers(0, n, (extra_edges, 2))
    w = rng.integers(1, 5, extra_edges) if weighted else None
    return generators.from_edge_list(n, edges, edge_weights=w)


def _views(graph, min_width=8, max_width=32, min_rows=4):
    """Small bucket params so tests exercise multiple buckets + heavy path."""
    pv = graph.padded()
    bv = build_bucketed_view(
        np.asarray(graph.row_ptr), np.asarray(graph.col_idx),
        np.asarray(graph.edge_w), graph.n, pv.anchor,
        min_width=min_width, max_width=max_width, min_rows=min_rows,
    )
    return pv, bv


@pytest.mark.parametrize("external_only,respect_caps", [
    (False, True), (True, True), (False, False), (True, False),
])
def test_best_moves_equivalence(rng, external_only, respect_caps):
    graph = _random_graph(rng)
    pv, bv = _views(graph)
    n_pad = pv.n_pad
    num_labels = n_pad
    labels = jnp.asarray(rng.integers(0, graph.n, n_pad).astype(np.int32))
    label_weights = jax.ops.segment_sum(pv.node_w, labels, num_segments=num_labels)
    max_w = jnp.full(num_labels, 6, dtype=jnp.int32)

    key = next_key()
    t_f, c_f, o_f, h_f = best_moves(
        key, labels, pv.edge_u, pv.col_idx, pv.edge_w, pv.node_w,
        label_weights, max_w, num_labels=num_labels,
        external_only=external_only, respect_caps=respect_caps,
    )
    t_b, c_b, o_b, h_b = bucketed_best_moves(
        key, labels, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w,
        label_weights, max_w,
        external_only=external_only, respect_caps=respect_caps,
    )
    n = graph.n
    # Tie-break independent quantities must match exactly on real nodes.
    np.testing.assert_array_equal(np.asarray(o_f)[:n], np.asarray(o_b)[:n])
    np.testing.assert_array_equal(np.asarray(h_f)[:n], np.asarray(h_b)[:n])
    np.testing.assert_array_equal(np.asarray(c_f)[:n], np.asarray(c_b)[:n])
    # The chosen target must be a best-rated feasible candidate: its rating
    # equals the flat best rating (tconn), even if the tie-broken label differs.
    tf, tb = np.asarray(t_f)[:n], np.asarray(t_b)[:n]
    hf = np.asarray(h_f)[:n]
    lab = np.asarray(labels)[:n]
    assert np.array_equal(tf[~hf], lab[~hf])
    assert np.array_equal(tb[~hf], lab[~hf])


def test_no_pathological_merge_inflation(rng):
    """Undersized width classes must merge to the largest *naturally occupied*
    class, not cascade to MAX_WIDTH (a 2000-node graph must not become a
    (rows, 4096) monster)."""
    graph = _random_graph(rng, n=2000, extra_edges=8000)
    pv = graph.padded()
    bv = build_bucketed_view(
        np.asarray(graph.row_ptr), np.asarray(graph.col_idx),
        np.asarray(graph.edge_w), graph.n, pv.anchor,
    )  # default (production) merge parameters
    max_deg = int(np.max(np.diff(np.asarray(graph.row_ptr))))
    for b in bv.buckets:
        assert b.cols.shape[1] <= max(8, 1 << (max_deg - 1).bit_length())
    slots = sum(int(b.cols.shape[0]) * int(b.cols.shape[1]) for b in bv.buckets)
    assert slots <= 8 * graph.m + 8 * 4096  # padding bounded, no 500x blowup


def test_heavy_path_exercised(rng):
    graph = generators.star_graph(100)
    pv, bv = _views(graph, max_width=16)
    assert bv.heavy.nodes.shape[0] > 0  # hub has degree 100 > 16
    num_labels = pv.n_pad
    labels = jnp.arange(pv.n_pad, dtype=jnp.int32)
    label_weights = jax.ops.segment_sum(pv.node_w, labels, num_segments=num_labels)
    max_w = jnp.full(num_labels, 1000, dtype=jnp.int32)
    key = next_key()
    t_b, c_b, o_b, h_b = bucketed_best_moves(
        key, labels, bv.buckets, bv.heavy, bv.gather_idx, pv.node_w,
        label_weights, max_w, external_only=False, respect_caps=True,
    )
    # Hub (node 0, heavy) sees 100 singleton neighbors, each rating 1.
    assert bool(h_b[0])
    assert int(c_b[0]) == 1
    # Every leaf's best candidate is the hub's cluster with rating 1.
    leaves = np.arange(1, 101)
    np.testing.assert_array_equal(np.asarray(t_b)[leaves], 0)
    np.testing.assert_array_equal(np.asarray(c_b)[leaves], 1)


def test_lp_round_bucketed_matches_flat_cut_quality(rng):
    graph = generators.grid2d_graph(20, 20)
    pv, bv = _views(graph)
    n_pad = pv.n_pad
    idt = pv.row_ptr.dtype
    labels = jnp.concatenate([
        jnp.arange(pv.n, dtype=idt),
        jnp.full(n_pad - pv.n, pv.anchor, dtype=idt),
    ])
    max_w = jnp.full(n_pad, 16, dtype=jnp.int32)

    state_f = lp.init_state(labels, pv.node_w, n_pad)
    state_b = lp.init_state(labels, pv.node_w, n_pad)
    # active_prob < 1: the documented oscillation guard for symmetric grids
    # (ops/lp.py:_commit_moves) — with full activation, strict-improvement
    # synchronous LP barely merges on a grid and the internal-edge counts
    # below are single-digit tie-draw noise rather than a quality signal.
    for _ in range(5):
        state_f = lp.lp_round(
            state_f, next_key(), pv.edge_u, pv.col_idx, pv.edge_w, pv.node_w,
            max_w, num_labels=n_pad, active_prob=0.5,
        )
        state_b = lp.lp_round_bucketed(
            state_b, next_key(), bv.buckets, bv.heavy, bv.gather_idx,
            pv.node_w, max_w, num_labels=n_pad, active_prob=0.5,
        )

    def quality(state):
        lab = np.asarray(state.labels)
        u, v = np.asarray(pv.edge_u), np.asarray(pv.col_idx)
        clusters = len(np.unique(lab[: graph.n]))
        internal = int(np.sum((lab[u] == lab[v]) & (np.asarray(pv.edge_w) > 0)))
        return clusters, internal

    cl_f, in_f = quality(state_f)
    cl_b, in_b = quality(state_b)
    # Both paths should coarsen comparably (same algorithm, different layout).
    assert abs(cl_f - cl_b) <= max(5, 0.2 * cl_f)
    assert in_b >= 0.7 * in_f

    # Weight invariant: cluster weights respect the cap on both paths.
    for state in (state_f, state_b):
        w = np.asarray(state.label_weights)
        assert w.max() <= 16


def test_lp_iterate_bucketed(rng):
    graph = generators.grid2d_graph(16, 16)
    pv, bv = _views(graph)
    n_pad = pv.n_pad
    idt = pv.row_ptr.dtype
    labels = jnp.concatenate([
        jnp.arange(pv.n, dtype=idt),
        jnp.full(n_pad - pv.n, pv.anchor, dtype=idt),
    ])
    max_w = jnp.full(n_pad, 12, dtype=jnp.int32)
    state = lp.init_state(labels, pv.node_w, n_pad)
    out = lp.lp_iterate_bucketed(
        state, next_key(), bv.buckets, bv.heavy, bv.gather_idx,
        pv.node_w, max_w, jnp.int32(0), jnp.int32(5), num_labels=n_pad,
    )
    lab = np.asarray(out.labels)[: graph.n]
    assert len(np.unique(lab)) < graph.n  # clustering actually happened
    assert np.asarray(out.label_weights).max() <= 12


# ---------------------------------------------------------------------------
# Device-side layout build (ISSUE 2): bit-identical to the host builder.
# ---------------------------------------------------------------------------


def _assert_views_equal(a, b):
    assert len(a.buckets) == len(b.buckets), (len(a.buckets), len(b.buckets))
    for i, (ba, bb) in enumerate(zip(a.buckets, b.buckets)):
        for name in ("nodes", "cols", "wgts"):
            xa, xb = np.asarray(getattr(ba, name)), np.asarray(getattr(bb, name))
            assert xa.shape == xb.shape, (i, name, xa.shape, xb.shape)
            assert np.array_equal(xa, xb), (i, name)
    for name in ("nodes", "row", "cols", "wgts"):
        assert np.array_equal(
            np.asarray(getattr(a.heavy, name)), np.asarray(getattr(b.heavy, name))
        ), name
    assert np.array_equal(np.asarray(a.gather_idx), np.asarray(b.gather_idx))
    assert a.n == b.n


@pytest.mark.parametrize("gname", ["rmat", "grid", "star", "heavy_star"])
def test_device_layout_build_matches_host(gname):
    from kaminpar_tpu.graph.bucketed import build_bucketed_view_device

    graphs = {
        "rmat": lambda: generators.rmat_graph(10, 8, seed=5),
        "grid": lambda: generators.grid2d_graph(40, 40),
        "star": lambda: generators.star_graph(200),
        # center degree 4999 > MAX_WIDTH: exercises the heavy part
        "heavy_star": lambda: generators.star_graph(5000),
    }
    g = graphs[gname]()
    pv = g.padded()
    host = build_bucketed_view(
        np.asarray(g.row_ptr), np.asarray(g.col_idx), np.asarray(g.edge_w),
        g.n, pv.anchor,
    )
    dev = build_bucketed_view_device(pv, g.n, g.deg_histogram())
    _assert_views_equal(host, dev)


def test_deg_histogram_host_device_agree():
    from kaminpar_tpu.graph.bucketed import (
        device_deg_histogram, host_deg_histogram,
    )

    for g in (generators.rmat_graph(10, 8, seed=6), generators.star_graph(5000)):
        pv = g.padded()
        deg = pv.row_ptr[1:] - pv.row_ptr[:-1]
        real = jnp.arange(pv.n_pad) < pv.n
        dev = np.asarray(jax.jit(device_deg_histogram)(deg, real))
        host = host_deg_histogram(np.asarray(g.row_ptr), g.n)
        assert np.array_equal(dev.astype(np.int64), host), (dev, host)


def test_lp_round_identical_on_device_layout():
    """An LP round over the device-built layout commits exactly the same
    labels as over the host-built layout (the layouts are bit-identical,
    so the kernel results must be too)."""
    from kaminpar_tpu.graph.bucketed import build_bucketed_view_device
    from kaminpar_tpu.utils import reseed

    g = generators.rmat_graph(10, 8, seed=8)
    pv = g.padded()
    host = build_bucketed_view(
        np.asarray(g.row_ptr), np.asarray(g.col_idx), np.asarray(g.edge_w),
        g.n, pv.anchor,
    )
    dev = build_bucketed_view_device(pv, g.n, g.deg_histogram())
    idt = pv.row_ptr.dtype
    labels = jnp.concatenate(
        [jnp.arange(pv.n, dtype=idt), jnp.full(pv.n_pad - pv.n, pv.anchor, dtype=idt)]
    )
    max_w = jnp.asarray(30, dtype=idt)
    outs = {}
    for name, bv in (("host", host), ("device", dev)):
        reseed(21)
        state = lp.init_state(labels, pv.node_w, pv.n_pad)
        state = lp.lp_round_bucketed(
            state, next_key(), bv.buckets, bv.heavy, bv.gather_idx,
            pv.node_w, max_w, num_labels=pv.n_pad,
        )
        outs[name] = np.asarray(state.labels)
    assert np.array_equal(outs["host"], outs["device"])
