#!/usr/bin/env python
"""Driver benchmark: LP coarsening throughput (edges/sec) on an RMAT graph.

Mirrors the reference's north-star microbenchmark
(``apps/benchmarks/shm_label_propagation_benchmark.cc``): build a graph, run
the LP clustering hot loop, report throughput.  BASELINE config 2 is RMAT
scale-22 / k=16; the scale is tunable via ``KPTPU_BENCH_SCALE`` so CI boxes
without a TPU can run a smaller instance.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` divides by a documented estimate of the reference's
shared-memory LP throughput (~250 M edges/s on a modern multicore; the repo
publishes no in-tree numbers, BASELINE.json ``published: {}``), so >1.0 means
faster than the CPU baseline estimate.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from kaminpar_tpu.coarsening.max_cluster_weights import compute_max_cluster_weight
from kaminpar_tpu.context import Context
from kaminpar_tpu.graph.generators import rmat_graph
from kaminpar_tpu.ops import lp
from kaminpar_tpu.utils import RandomState, next_key

# Estimated TBB LP throughput of the reference on a modern multicore (no
# published in-tree number exists; see BASELINE.md).
CPU_BASELINE_EDGES_PER_SEC = 250e6


def main() -> None:
    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    default_scale = 22 if on_tpu else 16
    scale = int(os.environ.get("KPTPU_BENCH_SCALE", default_scale))
    rounds = int(os.environ.get("KPTPU_BENCH_ROUNDS", 5))
    k = int(os.environ.get("KPTPU_BENCH_K", 16))

    RandomState.reseed(0)
    graph = rmat_graph(scale, edge_factor=16, seed=1)
    pv = graph.padded()
    n_pad = pv.n_pad

    bv = graph.bucketed()
    ctx = Context()
    max_cw = compute_max_cluster_weight(
        ctx.coarsening, graph.n, graph.total_node_weight, k, 0.03
    )
    idt = pv.row_ptr.dtype
    labels = jnp.concatenate(
        [jnp.arange(pv.n, dtype=idt), jnp.full(n_pad - pv.n, pv.anchor, dtype=idt)]
    )
    state = lp.init_state(labels, pv.node_w, n_pad)
    max_w = jnp.asarray(max_cw, dtype=idt)

    def one_round(state):
        return lp.lp_round_bucketed(
            state, next_key(), bv.buckets, bv.heavy, bv.gather_idx, pv.node_w,
            max_w, num_labels=n_pad,
        )

    # Warmup: compile + one real round.  Sync via scalar readback: on the
    # tunneled TPU backend block_until_ready can return before execution
    # completes, so a device->host transfer is the only reliable fence.
    state = one_round(state)
    int(state.num_moved)

    start = time.perf_counter()
    for _ in range(rounds):
        state = one_round(state)
    int(state.num_moved)
    elapsed = time.perf_counter() - start

    edges_per_sec = graph.m * rounds / elapsed
    print(
        json.dumps(
            {
                "metric": f"lp_clustering_throughput_rmat{scale}",
                "value": round(edges_per_sec, 1),
                "unit": "edges/sec",
                "vs_baseline": round(edges_per_sec / CPU_BASELINE_EDGES_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
