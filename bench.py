#!/usr/bin/env python
"""Driver benchmark: LP coarsening throughput + full-partition wall-clock.

Mirrors the reference's north-star microbenchmark
(``apps/benchmarks/shm_label_propagation_benchmark.cc:29-80``): build a graph,
run the LP clustering hot loop, report throughput.  BASELINE config 2 is RMAT
scale-22 / k=16; the scale is tunable via ``KPTPU_BENCH_SCALE`` so CI boxes
without a TPU can run a smaller instance.

Structure (round-3 redesign, VERDICT r2 missing #1): the *probed* backend is
the *measured* backend.  The parent spawns one child subprocess; the child
initializes the ambient backend (possibly a tunneled TPU plugin that can hang
rather than fail — no in-process try/except can catch that) and runs the whole
benchmark there, streaming JSON lines to stdout.  The parent enforces a
deadline (default 540 s, ``KPTPU_TPU_PROBE_TIMEOUT``), and on timeout salvages
the last JSON line the child already flushed (the LP-throughput line is
printed the moment it exists, before the slower full-partition phase).  Only
if the child produced nothing does the parent fall back to an in-process CPU
run, recording the child's stderr tail.

The final stdout line is always the headline JSON record:
{"metric", "value", "unit", "vs_baseline", "backend", ...extras}.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

# Measured reference anchor (VERDICT r1 weak #6: the previous 250e6 was a
# guess).  Measured 2026-07-30 on this box with the reference binary built
# from /root/reference (Release, -t 1, sparsehash/kassert off):
#   rgg64k (n=65k, m=1.63M directed): coarsening 0.079 s -> 20.6M edges/s
#   rmat14 (n=16k, m=0.22M directed): coarsening 0.016 s -> 13.6M edges/s
# Single-core LP-coarsening throughput ~= 17e6 edges/s.  The BASELINE.md
# north star compares against the 96-core TBB configuration; assuming 50%
# parallel efficiency (LP scales well but not linearly) gives the multicore
# anchor below.  This provenance is surfaced in the JSON as "baseline".
CPU_BASELINE_1CORE_EDGES_PER_SEC = 17e6
CPU_BASELINE_EDGES_PER_SEC = CPU_BASELINE_1CORE_EDGES_PER_SEC * 96 * 0.5
BASELINE_PROVENANCE = "estimated-96core (17e6 e/s measured single-core x96 x0.5 eff)"

# Peak HBM bandwidth (GB/s) by device_kind substring, for the interpretability
# estimate requested by VERDICT r2 next-steps #1.  Sources: public TPU specs.
_HBM_GBPS = [
    ("v6e", 1638.0),
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
]


def _hbm_peak(device_kind: str) -> float | None:
    dk = device_kind.lower()
    for key, gbps in _HBM_GBPS:
        if key in dk:
            return gbps
    return None


def run_benchmark() -> None:
    """The actual measurement; runs on whatever backend JAX initializes in
    *this* process.  Prints >=1 flushed JSON lines; the last is the headline."""
    import jax
    import jax.numpy as jnp

    from kaminpar_tpu.coarsening.max_cluster_weights import compute_max_cluster_weight
    from kaminpar_tpu.context import Context
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.ops import lp
    from kaminpar_tpu.utils import RandomState, next_key

    dev = jax.devices()[0]
    backend = dev.platform
    device_kind = getattr(dev, "device_kind", backend)
    on_accel = backend != "cpu"

    default_scale = 22 if on_accel else 16
    scale = int(os.environ.get("KPTPU_BENCH_SCALE", default_scale))
    rounds = int(os.environ.get("KPTPU_BENCH_ROUNDS", 5))
    k = int(os.environ.get("KPTPU_BENCH_K", 16))

    RandomState.reseed(0)
    graph = rmat_graph(scale, edge_factor=16, seed=1)
    pv = graph.padded()
    n_pad = pv.n_pad

    bv = graph.bucketed()
    ctx = Context()
    max_cw = compute_max_cluster_weight(
        ctx.coarsening, graph.n, graph.total_node_weight, k, 0.03
    )
    idt = pv.row_ptr.dtype
    labels = jnp.concatenate(
        [jnp.arange(pv.n, dtype=idt), jnp.full(n_pad - pv.n, pv.anchor, dtype=idt)]
    )
    state = lp.init_state(labels, pv.node_w, n_pad)
    max_w = jnp.asarray(max_cw, dtype=idt)

    def one_round(state):
        return lp.lp_round_bucketed(
            state, next_key(), bv.buckets, bv.heavy, bv.gather_idx, pv.node_w,
            max_w, num_labels=n_pad,
        )

    # Warmup: compile + one real round.  Sync via scalar readback: on the
    # tunneled TPU backend block_until_ready can return before execution
    # completes, so a device->host transfer is the only reliable fence.
    state = one_round(state)
    int(state.num_moved)

    start = time.perf_counter()
    for _ in range(rounds):
        state = one_round(state)
    int(state.num_moved)
    elapsed = time.perf_counter() - start

    edges_per_sec = graph.m * rounds / elapsed
    # Lower-bound HBM traffic per LP round: per directed edge one adjacency
    # index read (4 B) + one neighbor-label gather (4 B) + one edge weight
    # (4 B); per node ~6 int32 reads/writes of label/weight/moved state.
    # Sort/scan traffic inside the bucketed kernels is NOT counted, so the
    # bandwidth figure is a floor on achieved DRAM throughput.
    bytes_lb = graph.m * 12 + graph.n * 24
    est_gbps = bytes_lb * rounds / elapsed / 1e9
    hbm_peak = _hbm_peak(str(device_kind)) if on_accel else None

    record = {
        "metric": f"lp_clustering_throughput_rmat{scale}",
        "value": round(edges_per_sec, 1),
        "unit": "edges/sec",
        "vs_baseline": round(edges_per_sec / CPU_BASELINE_EDGES_PER_SEC, 4),
        "backend": backend,
        "device_kind": str(device_kind),
        "baseline": BASELINE_PROVENANCE,
        "est_hbm_gbps_lb": round(est_gbps, 1),
    }
    if hbm_peak:
        record["hbm_frac_of_peak_lb"] = round(est_gbps / hbm_peak, 4)
    # Flush the headline immediately: if the slower full-partition phase below
    # blows the parent's deadline, this line is salvaged as the result.
    print(json.dumps(record), flush=True)

    if os.environ.get("KPTPU_BENCH_FULL", "1") != "1":
        return
    # Phase 2: end-to-end compute_partition wall-clock at the same scale
    # (VERDICT r2 next-steps #1: "full compute_partition wall-clock at scale
    # 22/k=16" so the microbenchmark number is interpretable).
    from kaminpar_tpu.graph.metrics import edge_cut
    from kaminpar_tpu.kaminpar import KaMinPar

    full_scale = int(os.environ.get("KPTPU_BENCH_FULL_SCALE", scale))
    fgraph = graph if full_scale == scale else rmat_graph(full_scale, edge_factor=16, seed=1)
    shm = KaMinPar(ctx=Context())
    shm.set_graph(fgraph)
    t0 = time.perf_counter()
    part = shm.compute_partition(k, epsilon=0.03)
    wall = time.perf_counter() - t0
    cut = int(edge_cut(fgraph, part))
    record["partition_wall_s"] = round(wall, 2)
    record["partition_cut"] = cut
    record["partition_scale"] = full_scale
    record["partition_k"] = k
    record["partition_edges_per_sec"] = round(fgraph.m / wall, 1)
    print(json.dumps(record), flush=True)


def _salvage(stdout: str) -> dict | None:
    """Last complete JSON object the child flushed, if any."""
    best = None
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                best = json.loads(line)
            except ValueError:
                pass
    return best


def _run_child(timeout_s: float) -> tuple[dict | None, str]:
    """Run the benchmark in a killable subprocess on the ambient backend.

    Own process group so a timeout kill reaches any helper the plugin forked
    (ssh/grpc proxies inherit the pipes; killing only the direct child would
    leave communicate() blocked on pipe EOF forever).  Returns the salvaged
    headline record (or None) and an error string ('' = clean)."""
    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            start_new_session=True,
        )
    except Exception as exc:  # noqa: BLE001
        return None, f"{type(exc).__name__}: {exc}"[:500]
    try:
        out, errout = proc.communicate(timeout=timeout_s)
        err = ""
        if proc.returncode != 0:
            err = (errout.strip().splitlines() or ["child failed"])[-1][:500]
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        out, errout = proc.communicate()
        err = f"benchmark child killed after {timeout_s:.0f}s"
    rec = _salvage(out or "")
    if rec is not None and err:
        rec["note"] = err  # partial result: headline phase finished, later phase cut off
        err = ""
    return rec, err


def main() -> None:
    if "--child" in sys.argv:
        run_benchmark()
        return
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # Explicitly CPU-pinned environment (tests/CI): measure in-process.
        # force_cpu_devices, not the env var alone: the axon site hook sets
        # jax.config jax_platforms=axon at interpreter start, which beats
        # the env var — only an explicit config update wins it back.
        from kaminpar_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(1)
        run_benchmark()
        return
    timeout_s = float(os.environ.get("KPTPU_TPU_PROBE_TIMEOUT", 540))
    rec, err = _run_child(timeout_s)
    if rec is not None:
        print(json.dumps(rec))
        return
    # Child produced nothing: the backend is unreachable.  Fall back to CPU
    # in-process so the driver still gets a number, with the failure recorded.
    from kaminpar_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)
    os.environ["KPTPU_BENCH_FULL"] = os.environ.get("KPTPU_BENCH_FULL", "0")

    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        run_benchmark()
    rec = _salvage(buf.getvalue()) or {"metric": "lp_clustering_throughput", "value": 0.0,
                                       "unit": "edges/sec", "vs_baseline": 0.0}
    rec["backend"] = "cpu-fallback"
    rec["error"] = err or "backend init failed"
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
