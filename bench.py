#!/usr/bin/env python
"""Driver benchmark: LP coarsening throughput + full-partition wall-clock.

Mirrors the reference's north-star microbenchmark
(``apps/benchmarks/shm_label_propagation_benchmark.cc:29-80``): build a graph,
run the LP clustering hot loop, report throughput.  BASELINE config 2 is RMAT
scale-22 / k=16; the scale is tunable via ``KPTPU_BENCH_SCALE`` so CI boxes
without a TPU can run a smaller instance.

Round-5 structure (VERDICT r4 missing #1 + weak #2 — availability
engineering):

  * A round-long prober daemon (``scripts/tpu_prober.py``) retries TPU
    backend init all round and, on success, measures immediately and writes
    ``TPU_RESULT.json`` plus per-attempt telemetry in ``TPU_PROBE_LOG.jsonl``.
    This script *prefers* that artifact: if the tunnel was up at any point in
    the round, the number captured in that window is the headline.
  * Absent a prober result, the probe log decides whether another in-line
    probe is worth its budget: repeated recent init hangs mean "tunnel down
    all round" is already evidenced, and we go straight to the CPU fallback
    instead of burning the driver's deadline on another >560 s hang.
  * The CPU fallback now records end-to-end ``partition_wall_s`` +
    ``partition_cut`` (never captured before r5): phase 2 runs in its own
    child with its own deadline at a scale that finishes on CPU.
  * Probe-attempt telemetry is embedded in the final JSON either way, so
    "no TPU number" is evidenced, not asserted.

The final stdout line is always the headline JSON record:
{"metric", "value", "unit", "vs_baseline", "backend", ...extras}.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
TPU_RESULT_PATH = os.path.join(REPO, "TPU_RESULT.json")
TPU_PROBE_LOG = os.path.join(REPO, "TPU_PROBE_LOG.jsonl")

# Measured reference anchor (VERDICT r1 weak #6: the previous 250e6 was a
# guess).  Measured 2026-07-30 on this box with the reference binary built
# from /root/reference (Release, -t 1, sparsehash/kassert off):
#   rgg64k (n=65k, m=1.63M directed): coarsening 0.079 s -> 20.6M edges/s
#   rmat14 (n=16k, m=0.22M directed): coarsening 0.016 s -> 13.6M edges/s
# Single-core LP-coarsening throughput ~= 17e6 edges/s.  The BASELINE.md
# north star compares against the 96-core TBB configuration; assuming 50%
# parallel efficiency (LP scales well but not linearly) gives the multicore
# anchor below.  This provenance is surfaced in the JSON as "baseline".
CPU_BASELINE_1CORE_EDGES_PER_SEC = 17e6
CPU_BASELINE_EDGES_PER_SEC = CPU_BASELINE_1CORE_EDGES_PER_SEC * 96 * 0.5
BASELINE_PROVENANCE = "estimated-96core (17e6 e/s measured single-core x96 x0.5 eff)"

# Peak HBM bandwidth (GB/s) by device_kind substring, for the interpretability
# estimate requested by VERDICT r2 next-steps #1.  Sources: public TPU specs.
_HBM_GBPS = [
    ("v6e", 1638.0),
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
]


def _hbm_peak(device_kind: str) -> float | None:
    dk = device_kind.lower()
    for key, gbps in _HBM_GBPS:
        if key in dk:
            return gbps
    return None


def run_lp_phase() -> dict:
    """LP-clustering throughput on whatever backend JAX initializes in *this*
    process.  Prints the headline record the moment it exists and returns it."""
    import jax
    import jax.numpy as jnp

    from kaminpar_tpu.coarsening.max_cluster_weights import compute_max_cluster_weight
    from kaminpar_tpu.context import Context
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.ops import lp, pallas_lp
    from kaminpar_tpu.utils import RandomState, next_key
    from kaminpar_tpu.utils import compile_stats, sync_stats

    compile_stats.enable_compile_time_tracking()
    compile_stats.reset()
    sync_stats.reset()

    dev = jax.devices()[0]
    backend = dev.platform
    device_kind = getattr(dev, "device_kind", backend)
    on_accel = backend != "cpu"

    default_scale = 22 if on_accel else 16
    scale = int(os.environ.get("KPTPU_BENCH_SCALE", default_scale))
    rounds = int(os.environ.get("KPTPU_BENCH_ROUNDS", 5))
    k = int(os.environ.get("KPTPU_BENCH_K", 16))
    # LP round kernel backend for the microbench: "xla" | "pallas" | "auto".
    # The prober measures both so every TPU window yields an A/B number.
    lp_kernel = pallas_lp.resolve_lp_kernel(
        os.environ.get("KPTPU_BENCH_LP_KERNEL", "xla")
    )
    round_mod = pallas_lp if lp_kernel == "pallas" else lp

    RandomState.reseed(0)
    graph = rmat_graph(scale, edge_factor=16, seed=1)
    pv = graph.padded()
    n_pad = pv.n_pad

    bv = graph.bucketed()
    ctx = Context()
    max_cw = compute_max_cluster_weight(
        ctx.coarsening, graph.n, graph.total_node_weight, k, 0.03
    )
    idt = pv.row_ptr.dtype
    labels = jnp.concatenate(
        [jnp.arange(pv.n, dtype=idt), jnp.full(n_pad - pv.n, pv.anchor, dtype=idt)]
    )
    state = lp.init_state(labels, pv.node_w, n_pad)
    max_w = jnp.asarray(max_cw, dtype=idt)

    def one_round(state):
        return round_mod.lp_round_bucketed(
            state, next_key(), bv.buckets, bv.heavy, bv.gather_idx, pv.node_w,
            max_w, num_labels=n_pad,
        )

    # Warmup: compile + one real round.  Sync via scalar readback: on the
    # tunneled TPU backend block_until_ready can return before execution
    # completes, so a device->host transfer is the only reliable fence.
    # Routed through sync_stats so the fences show up in the host_sync
    # report rather than hiding from it.
    with sync_stats.scoped("lp_bench_fence"):
        state = one_round(state)
        sync_stats.pull(state.num_moved)

        start = time.perf_counter()
        for _ in range(rounds):
            state = one_round(state)
        sync_stats.pull(state.num_moved)
        elapsed = time.perf_counter() - start

    edges_per_sec = graph.m * rounds / elapsed
    # Lower-bound HBM traffic per LP round: per directed edge one adjacency
    # index read (4 B) + one neighbor-label gather (4 B) + one edge weight
    # (4 B); per node ~6 int32 reads/writes of label/weight/moved state.
    # Sort/scan traffic inside the bucketed kernels is NOT counted, so the
    # bandwidth figure is a floor on achieved DRAM throughput.
    bytes_lb = graph.m * 12 + graph.n * 24
    est_gbps = bytes_lb * rounds / elapsed / 1e9
    hbm_peak = _hbm_peak(str(device_kind)) if on_accel else None

    sync_snap = sync_stats.snapshot()
    record = {
        "metric": f"lp_clustering_throughput_rmat{scale}",
        "value": round(edges_per_sec, 1),
        "unit": "edges/sec",
        "vs_baseline": round(edges_per_sec / CPU_BASELINE_EDGES_PER_SEC, 4),
        "backend": backend,
        "device_kind": str(device_kind),
        "baseline": BASELINE_PROVENANCE,
        "est_hbm_gbps_lb": round(est_gbps, 1),
        "lp_kernel": lp_kernel,
        "lp_compile": compile_stats.compile_time_snapshot(),
        # Blocking device->host transfer census of the microbench window
        # (utils/sync_stats.py): count + bytes per timer phase.
        "host_sync_count": sync_snap["count"],
        "host_sync": sync_snap["phases"],
    }
    if hbm_peak:
        record["hbm_frac_of_peak_lb"] = round(est_gbps / hbm_peak, 4)
    # Flush the headline immediately: if the slower full-partition phase below
    # blows the parent's deadline, this line is salvaged as the result.
    print(json.dumps(record), flush=True)
    return record


def _timer_phase_seconds(*path: str) -> float | None:
    """Elapsed seconds of a timer-tree scope by path (e.g. "partitioning",
    "initial_partitioning"); None when the scope never ran.  Reads the
    merged (all-threads) tree via the public Timer API."""
    from kaminpar_tpu.utils import Timer

    return Timer.global_().phase_seconds(*path)


def _run_ip_ab(k: int) -> dict:
    """Initial-partitioning A/B (ISSUE 4 acceptance): wall of the same
    k-way recursive bisection on the host pool vs the lane-vmapped device
    pool, on a coarsest-graph-sized instance.  The device number is
    reported cold (first call pays per-cell compiles; the persistent cache
    keeps them paid) and warm (the steady-state cost every level of a real
    run pays)."""
    import dataclasses

    import numpy as np

    from kaminpar_tpu.context import InitialPartitioningContext
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.initial.bipartitioner import _cut, recursive_bipartition
    from kaminpar_tpu.partitioning.kway import graph_to_host

    scale = int(os.environ.get("KPTPU_BENCH_IP_SCALE", 12))
    host = graph_to_host(rmat_graph(scale, edge_factor=8, seed=2))
    W = host.total_node_weight
    per = int(np.ceil(W / k) * 1.03) + 1
    budgets = np.full(k, per, dtype=np.int64)
    out: dict = {"scale": scale, "k": k}
    # The KAMINPAR_TPU_IP_BACKEND kill switch overrides the context knob,
    # which would make both A/B arms silently run the same pool; the A/B
    # pins each arm explicitly, so lift the override for its duration.
    env_override = os.environ.pop("KAMINPAR_TPU_IP_BACKEND", None)
    try:
        for backend in ("host", "device"):
            ipc = dataclasses.replace(
                InitialPartitioningContext(), ip_backend=backend
            )
            walls = []
            for rep in range(2):
                t0 = time.perf_counter()
                part = recursive_bipartition(
                    host, k, budgets, np.random.default_rng(1), ipc
                )
                walls.append(time.perf_counter() - t0)
            out[f"{backend}_cold_s"] = round(walls[0], 3)
            out[f"{backend}_warm_s"] = round(walls[1], 3)
            out[f"{backend}_cut"] = _cut(host, part)
    finally:
        if env_override is not None:
            os.environ["KAMINPAR_TPU_IP_BACKEND"] = env_override
    if out["device_warm_s"]:
        out["device_vs_host_warm"] = round(
            out["host_warm_s"] / out["device_warm_s"], 2
        )
    return out


def run_full_phase(record: dict | None = None) -> dict:
    """Phase 2: end-to-end compute_partition wall-clock (VERDICT r4 weak #2 —
    never recorded by any BENCH artifact before r5).  Scale defaults to one
    that finishes on CPU inside its own deadline; the persistent XLA
    compilation cache makes repeat runs warm."""
    import jax

    from kaminpar_tpu.context import Context
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.graph.metrics import edge_cut
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.utils import RandomState

    from kaminpar_tpu.utils import compile_stats, sync_stats

    compile_stats.enable_compile_time_tracking()
    compile_stats.reset()
    sync_stats.reset()

    record = dict(record or {})
    backend = jax.devices()[0].platform
    on_accel = backend != "cpu"
    k = int(os.environ.get("KPTPU_BENCH_K", 16))
    # CPU default 17: scale 16 measured 134 s warm on this box (r5); one
    # doubling keeps a safe margin inside the 900 s phase-2 deadline.
    default_full = 20 if on_accel else 17
    full_scale = int(os.environ.get("KPTPU_BENCH_FULL_SCALE", default_full))

    from kaminpar_tpu.initial.bipartitioner import resolve_ip_backend
    from kaminpar_tpu.ops import bipartition as ip_pool
    from kaminpar_tpu.telemetry import trace as ttrace
    from kaminpar_tpu.utils import heap_profiler
    from kaminpar_tpu.utils.heap_profiler import HeapProfiler

    # Executable census (ISSUE 12): armed for the bench so compile events
    # attribute to their phases and warmup/AOT harvest sites populate —
    # strictly host-side (zero transfers; tests assert neutrality).
    if os.environ.get("KPTPU_BENCH_CENSUS", "1") == "1":
        compile_stats.arm_executable_census()
    ip_pool.reset_pool_stats()
    RandomState.reseed(0)
    fgraph = rmat_graph(full_scale, edge_factor=16, seed=1)
    shm = KaMinPar(ctx=Context())
    shm.set_graph(fgraph)
    # Run telemetry (ISSUE 5): the full-partition phase records the unified
    # trace — spans, per-level quality rows, sync/compile/HBM counter
    # samples — and the artifact carries its summary + the trace path.
    trace_out = os.environ.get(
        "KPTPU_BENCH_TRACE_OUT", os.path.join(REPO, "BENCH_trace.json")
    )
    trace_rec = None if ttrace.active() is not None else ttrace.start()
    HeapProfiler.reset(enabled=True)
    t0 = time.perf_counter()
    try:
        part = shm.compute_partition(k, epsilon=0.03)
    finally:
        wall = time.perf_counter() - t0
        if trace_rec is not None:
            ttrace.stop()
    cut = int(edge_cut(fgraph, part))
    # Initial-partitioning share of the partition wall + device-pool lane
    # census (ISSUE 4): occupancy = requested repetitions / bucketed lanes
    # launched; zero calls on the host backend is the honest CPU reading.
    ip_wall = _timer_phase_seconds("partitioning", "initial_partitioning")
    part_wall = _timer_phase_seconds("partitioning")
    # Distinct kernel specializations + actual compile wall-time of the
    # full-partition phase — the cold-compile tax the geometric shape
    # buckets bound (ISSUE 1; one ~35-48 s compile per shape on a tunneled
    # TPU, TPU_NOTES.md).
    shape_counts = compile_stats.snapshot()
    sync_snap = sync_stats.snapshot()
    record.update({
        "backend": record.get("backend", backend),
        "partition_wall_s": round(wall, 2),
        "partition_cut": cut,
        "partition_scale": full_scale,
        "partition_k": k,
        "partition_edges_per_sec": round(fgraph.m / wall, 1),
        "compiled_shape_count": shape_counts,
        "partition_compile": compile_stats.compile_time_snapshot(),
        "ip_backend": resolve_ip_backend(shm.ctx.initial_partitioning),
        "initial_partitioning_wall_s": round(ip_wall, 3)
        if ip_wall is not None else None,
        "initial_partitioning_share": round(ip_wall / part_wall, 4)
        if ip_wall is not None and part_wall else None,
        "ip_pool": ip_pool.pool_stats_snapshot(),
        # Blocking-transfer census of the full-partition phase: total count
        # + per-phase {count, bytes} keyed by the timer tree's scope names
        # (the one-batched-readback-per-coarsening-level contract shows up
        # as host_sync.coarsening.count == hierarchy depth).
        "host_sync_count": sync_snap["count"],
        "host_sync_bytes": sync_snap["bytes"],
        "host_sync": sync_snap["phases"],
        # Executable census + per-phase compile attribution (ISSUE 12):
        # what the compiled programs WOULD do (XLA cost/memory analyses)
        # and which phases paid the cold compiles.
        "executable_census": compile_stats.executable_census_snapshot(),
        "compile_by_phase": compile_stats.compile_by_phase_snapshot(),
    })
    # Telemetry summary (ISSUE 5): trace path + per-level quality rows +
    # the HBM watermark, embedded so BENCH_*.json / TPU_PROBE_LOG.jsonl
    # carry the run's structured record.
    if trace_rec is not None:
        try:
            trace_rec.meta.update(
                {"scale": full_scale, "k": k, "backend": backend}
            )
            trace_rec.write(trace_out)
            record["telemetry"] = {
                "trace_path": trace_out,
                **trace_rec.summary(),
                # Cap the embedded rows so a deep hierarchy cannot bloat the
                # one-line artifact; the full set lives in the trace file.
                "levels": trace_rec.quality[:48],
                "hbm": heap_profiler.watermark_report(),
            }
        except Exception as exc:  # noqa: BLE001 — telemetry must not void the record
            record["telemetry_error"] = f"{type(exc).__name__}: {exc}"[:300]
    # kptlint summary (ISSUE 7): rule counts + baseline size ride the
    # artifact so static-contract violation drift is visible in the perf
    # trajectory alongside the runtime sync census above.
    try:
        from kaminpar_tpu.analysis.cli import lint_summary

        record["lint"] = lint_summary()
    except Exception as exc:  # noqa: BLE001 — lint must not void the record
        record["lint_error"] = f"{type(exc).__name__}: {exc}"[:300]
    # Resilience census (round 17, ISSUE 13): any PIPELINE-rung
    # degradation (ip_device->host, device_decode->dense, lp_pallas->xla)
    # or breaker trips during the measured run ride the artifact — a
    # benchmark that silently served its numbers from a demoted path
    # must say so next to the headline.  Scope is the process-global
    # registry only: serve-tier rungs (lanestack/cell/quality) live on
    # each engine's private registry and surface through the serve
    # phase's own stats snapshot (lanestack_fallbacks etc.), not here.
    try:
        from kaminpar_tpu.resilience import breakers as _rbreakers
        from kaminpar_tpu.resilience import faults as _rfaults

        rsnap = _rbreakers.global_registry().snapshot()
        record["resilience"] = {
            "scope": "pipeline_rungs",
            "demotions": rsnap["demotions"],
            "breaker_trips": sum(
                b["trips"] for b in rsnap["breakers"].values()
            ),
            "faults_injected": _rfaults.injected_total(),
        }
    except Exception as exc:  # noqa: BLE001 — census must not void the record
        record["resilience_error"] = f"{type(exc).__name__}: {exc}"[:300]
    # Run-ledger inputs (round 13): top-level phase walls + the collective
    # census ride the record so the ledger entry (and the salvage path,
    # which runs in the parent process) sees the measuring process's state.
    try:
        from kaminpar_tpu.telemetry import ledger as _ledger
        from kaminpar_tpu.utils import collective_stats

        record["phase_walls_s"] = _ledger.phase_walls()
        record["collectives"] = collective_stats.snapshot()
    except Exception as exc:  # noqa: BLE001
        record["ledger_inputs_error"] = f"{type(exc).__name__}: {exc}"[:300]
    # Watermark captured — disarm the profiler so the serve phase's measured
    # request path does not pay per-scope allocator queries or accumulate
    # unbounded per-request heap-tree nodes.
    HeapProfiler.reset(enabled=False)
    # Measured host-vs-device pool speedup (ISSUE 4 acceptance); an A/B
    # failure must not void the partition record above.
    if os.environ.get("KPTPU_BENCH_IP_AB", "1") == "1":
        try:
            record["ip_ab"] = _run_ip_ab(k=min(k, 8))
        except Exception as exc:  # noqa: BLE001
            record["ip_ab_error"] = f"{type(exc).__name__}: {exc}"[:300]
    print(json.dumps(record), flush=True)
    return record


def _run_lanestack_ab(scale: int, k: int, occupancy: int = 8,
                      reps: int = 3) -> dict:
    """Execute-phase wall of one full-occupancy same-cell batch: warm
    per-graph loop vs ONE lane-stacked vmapped program, both measured over
    ``reps`` warm passes (first pass unmeasured on each arm)."""
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name
    from kaminpar_tpu.serve.batching import shape_cell
    from kaminpar_tpu.serve.lanestack import run_lanestacked

    # Distinct seeds from one RMAT family, filtered to the dominant shape
    # cell — the batch the serve queue would actually form.
    pool = [rmat_graph(scale, edge_factor=8, seed=200 + i) for i in range(24)]
    cells = [shape_cell(g, k) for g in pool]
    head = max(set(cells), key=cells.count)
    graphs = [g for g, c in zip(pool, cells) if c == head][:occupancy]

    solver = KaMinPar(ctx="serve")

    def pergraph_once() -> None:
        for g in graphs:
            solver.set_graph(g)
            solver.compute_partition(k, 0.03)

    ctx = create_context_by_preset_name("serve")

    def lanestack_once():
        return run_lanestacked(ctx, graphs, k, 0.03)

    pergraph_once()  # warm (traces + compiles)
    t0 = time.perf_counter()
    for _ in range(reps):
        pergraph_once()
    pergraph_s = (time.perf_counter() - t0) / reps

    _, report = lanestack_once()  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        _, report = lanestack_once()
    lanestack_s = (time.perf_counter() - t0) / reps

    return {
        "scale": scale,
        "k": k,
        "occupancy": len(graphs),
        "reps": reps,
        "pergraph_s": round(pergraph_s, 4),
        "lanestack_s": round(lanestack_s, 4),
        "lanestack_vs_pergraph": round(pergraph_s / lanestack_s, 2)
        if lanestack_s else None,
        "cohorts": report.cohorts,
        "splits": report.splits,
        "stacked_pulls": report.stacked_pulls,
    }


def run_serve_phase(record: dict | None = None) -> dict:
    """Phase 3 (ISSUE 3): serving throughput under the warm engine vs the
    status-quo single-request pattern, over an offered-load sweep.

    ``single_request`` is the pattern the serve runtime replaces — a cold,
    single-graph, synchronous invocation: fresh facade per request with the
    in-process executable caches cleared (``jax.clear_caches()``), so every
    call pays the per-call rebuild (trace + cache load; the persistent disk
    cache stays, so XLA compiles are warm — this measures orchestration
    rebuild, not compiler time).  ``warm_single`` is the honest same-process
    lower bound (warm caches, still one request at a time).  The serve side
    warms the ladder once (reported as ``serve_warmup_s``, excluded from
    steady-state throughput) and then takes the same workload at two offered
    loads: a burst (maximum batchability) and a paced trickle (occupancy 1).
    """
    import jax

    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.serve import PartitionEngine
    from kaminpar_tpu.utils import RandomState

    record = dict(record or {})
    backend = jax.devices()[0].platform
    n_req = int(os.environ.get("KPTPU_BENCH_SERVE_REQS", 24))
    scales = tuple(
        int(s) for s in os.environ.get("KPTPU_BENCH_SERVE_SCALES", "8,9").split(",")
    )
    k = int(os.environ.get("KPTPU_BENCH_SERVE_K", 8))
    base_n = min(int(os.environ.get("KPTPU_BENCH_SERVE_BASE_REQS", 6)), n_req)

    from kaminpar_tpu.utils import compile_stats

    if os.environ.get("KPTPU_BENCH_CENSUS", "1") == "1":
        # Engine warmup harvests per-cell executable censuses when armed
        # (ISSUE 12) — the serve record carries them below.
        compile_stats.arm_executable_census()

    RandomState.reseed(0)
    graphs = [
        rmat_graph(scales[i % len(scales)], edge_factor=8, seed=100 + i)
        for i in range(n_req)
    ]

    def single_sweep(n: int, cold: bool) -> float:
        t0 = time.perf_counter()
        for g in graphs[:n]:
            if cold:
                jax.clear_caches()
            solver = KaMinPar(ctx="serve")
            solver.set_graph(g)
            solver.compute_partition(k, 0.03)
        return n / (time.perf_counter() - t0)

    engine = PartitionEngine(
        "serve", warm_ladder=tuple(1 << s for s in scales), warm_ks=(k,)
    )
    t0 = time.perf_counter()
    engine.start(warmup=True)
    warmup_s = time.perf_counter() - t0

    from kaminpar_tpu.serve import QueueFullError

    def submit_backpressured(g):
        # An offered load beyond the queue bound is the backpressure path
        # working as designed — honor the retry-after hint instead of
        # letting the sweep crash on its own admission control.
        while True:
            try:
                return engine.submit(g, k)
            except QueueFullError as e:
                time.sleep(e.retry_after_s)

    sweep = []
    try:
        # Preflight (unmeasured): steady-state serving throughput is the
        # quantity of interest, and the warmup ladder cannot predict every
        # shape cell of the workload (edge buckets vary with the graphs),
        # so run the workload once to pay first-touch traces before the
        # measured windows.  Its wall is reported — it is the cold tax a
        # real deployment pays exactly once per cell per process.
        t0 = time.perf_counter()
        for fut in [submit_backpressured(g) for g in graphs]:
            fut.result()
        preflight_s = time.perf_counter() - t0

        for load, gap_s in (("burst", 0.0), ("paced", None)):
            engine.stats_.reset()
            t0 = time.perf_counter()
            if gap_s is None:
                # Paced = closed-loop, one in flight: the no-batching floor.
                for g in graphs:
                    engine.submit(g, k).result()
            else:
                futures = [submit_backpressured(g) for g in graphs]
                for fut in futures:
                    fut.result()
            wall = time.perf_counter() - t0
            snap = engine.stats_.snapshot()
            sweep.append({
                "offered_load": load,
                "throughput_gps": round(n_req / wall, 2),
                "batch_occupancy_mean": snap["batch_occupancy_mean"],
                "batch_occupancy_max": snap["batch_occupancy_max"],
                "p50_ms": snap["latency_ms"]["total_ms"].get("p50"),
                "p99_ms": snap["latency_ms"]["total_ms"].get("p99"),
                "timed_out": snap["timed_out"],
                # Lane-stack census (ISSUE 6): how many batches ran as one
                # vmapped stack, at what realized lane occupancy, and how
                # many fell back to the per-graph loop.
                "lanestack_batches": snap["lanestacked_batches"],
                "lanestack_occupancy_mean": snap["lanestack_occupancy_mean"],
                "lanestack_fallbacks": snap["lanestack_fallbacks"],
            })
    finally:
        engine.shutdown(drain=True)

    # Lane-stack execute-phase A/B (ISSUE 6): the same-cell batch the serve
    # queue forms at full occupancy, executed (a) once per graph on the warm
    # facade — the PR 3 pattern — and (b) as ONE lane-stacked vmapped
    # program (serve/lanestack.py).  Both arms run once unmeasured (warm
    # tax paid identically) and are then timed over `reps` passes; results
    # are bit-identical by the lane-stack contract, so this isolates pure
    # execute-phase wall.  Distinct seeds, honest workload: cohort splits
    # (hierarchy divergence) are reported, not hidden.
    try:
        record["lanestack_ab"] = _run_lanestack_ab(
            scale=max(scales), k=k,
            occupancy=int(os.environ.get("KPTPU_BENCH_LANESTACK_OCC", 8)),
        )
    except Exception as exc:  # noqa: BLE001
        record["lanestack_ab_error"] = f"{type(exc).__name__}: {exc}"[:300]

    # Baselines AFTER the engine phases so ordering cannot skew them:
    # warm_single shares the process's now-warm caches (the honest
    # same-process floor), and the cold-call pattern runs last because
    # jax.clear_caches() would throw away everyone else's warm state.
    warm_single_gps = single_sweep(base_n, cold=False)
    single_gps = single_sweep(base_n, cold=True)

    burst = sweep[0]
    record.update({
        "backend": record.get("backend", backend),
        "serve_requests": n_req,
        "serve_k": k,
        "serve_warmup_s": round(warmup_s, 2),
        "serve_preflight_s": round(preflight_s, 2),
        "serve_throughput_gps": burst["throughput_gps"],
        "serve_batch_occupancy": burst["batch_occupancy_mean"],
        "serve_p50_ms": burst["p50_ms"],
        "serve_p99_ms": burst["p99_ms"],
        "single_request_gps": round(single_gps, 3),
        "warm_single_gps": round(warm_single_gps, 3),
        "serve_vs_single_request": round(burst["throughput_gps"] / single_gps, 2)
        if single_gps else None,
        "serve_vs_warm_single": round(
            burst["throughput_gps"] / warm_single_gps, 2
        ) if warm_single_gps else None,
        "lanestack_vs_pergraph": (record.get("lanestack_ab") or {}).get(
            "lanestack_vs_pergraph"
        ),
        "serve_sweep": sweep,
        "executable_census": compile_stats.executable_census_snapshot(),
    })
    print(json.dumps(record), flush=True)
    return record


def run_compress_phase(record: dict | None = None) -> dict:
    """Phase 4 (ISSUE 10): compressed-graph device-pipeline A/B — the same
    terapart run with ``device_decode`` off (host decompress + dense
    kernels) vs ``finest`` (decode fused into the LP kernels), recording
    wall per level (the run-trace quality rows ride the existing per-level
    readbacks), resident bytes/edge of both adjacency tiers, the
    compression ratio, and the HBM watermark delta.  Keys ride the
    RUNS.jsonl ledger flat (``compress_ab_*``) so ``tools regress``
    baseline windows cover them; tpu_prober carries the phase on-silicon
    through run_benchmark."""
    import jax
    import numpy as np

    from kaminpar_tpu.graph.compressed import compress
    from kaminpar_tpu.graph.device_compressed import DeviceCompressedView
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.telemetry import trace as ttrace
    from kaminpar_tpu.utils import RandomState, Timer, heap_profiler
    from kaminpar_tpu.utils.heap_profiler import HeapProfiler

    record = dict(record or {})
    backend = jax.devices()[0].platform
    k = int(os.environ.get("KPTPU_BENCH_K", 16))
    # Scale 16 is the acceptance floor for the resident-bytes claim; warm
    # CPU runs finish each arm in ~2 min (the full phase's scale-17 single
    # run is the reference point).
    scale = int(os.environ.get("KPTPU_BENCH_COMPRESS_SCALE", 16))
    g = rmat_graph(scale, edge_factor=16, seed=1)
    cg = compress(g)
    cv = DeviceCompressedView(cg)
    dense_bytes = cv.dense_resident_bytes()
    comp_bytes = cv.resident_bytes()
    ab: dict = {
        "backend": backend,
        "scale": scale,
        "k": k,
        "compression_ratio": round(cg.compression_ratio(), 3),
        # Device-resident adjacency bytes of the finest level: what the
        # dense path keeps in HBM between dispatches vs the compressed
        # stream + decode metadata (graph/device_compressed.py).
        "resident_bytes_dense": dense_bytes,
        "resident_bytes_compressed": comp_bytes,
        "bytes_per_edge_dense": round(dense_bytes / max(g.m, 1), 2),
        "bytes_per_edge_compressed": round(comp_bytes / max(g.m, 1), 2),
        "resident_reduction": round(dense_bytes / max(comp_bytes, 1), 3),
    }
    del cv  # the finest arm rebuilds its own; keep the A honest
    # The env override beats the per-arm ctx knob (resolve_device_decode);
    # a leftover KAMINPAR_TPU_DEVICE_DECODE would silently run both arms in
    # the same mode and record a meaningless A/B into the ledger.
    env_override = os.environ.pop("KAMINPAR_TPU_DEVICE_DECODE", None)
    if env_override is not None:
        ab["env_override_cleared"] = env_override
    parts: dict = {}
    for mode, tag in (("off", "dense"), ("finest", "decode")):
        RandomState.reseed(0)
        Timer.reset_global()
        solver = KaMinPar("terapart")
        solver.ctx.compression.device_decode = mode
        trace_rec = None if ttrace.active() is not None else ttrace.start()
        HeapProfiler.reset(enabled=True)
        t0 = time.perf_counter()
        try:
            solver.set_graph(g)
            parts[mode] = solver.compute_partition(k, epsilon=0.03)
        finally:
            wall = time.perf_counter() - t0
            if trace_rec is not None:
                ttrace.stop()
        arm = {
            "wall_s": round(wall, 2),
            "coarsening_wall_s": _timer_phase_seconds(
                "partitioning", "coarsening"
            ),
            # Allocator truth (empty on backends without stats — the
            # honest CPU reading; the static resident_bytes_* above are
            # exact either way).
            "hbm": heap_profiler.watermark_report(),
        }
        if trace_rec is not None:
            # Per-level rows (n, m, wall between level readbacks) — they
            # rode the levels' existing single pulls, zero added transfers.
            arm["levels"] = trace_rec.quality[:24]
        ab[tag] = arm
        HeapProfiler.reset(enabled=False)
    if env_override is not None:
        os.environ["KAMINPAR_TPU_DEVICE_DECODE"] = env_override
    ab["identical_partition"] = bool(
        np.array_equal(parts["off"], parts["finest"])
    )
    peaks = [
        ab[tag].get("hbm", {}).get("peak_bytes_in_use")
        for tag in ("dense", "decode")
    ]
    if all(isinstance(p, int) for p in peaks):
        ab["hbm_peak_delta_bytes"] = peaks[0] - peaks[1]
    record["compress_ab"] = ab
    # Flat ledger keys (telemetry/ledger._numeric_metrics reads top-level
    # numerics; *_ratio/*_reduction are higher-better, *_s/_bytes lower).
    record.update({
        "compress_ab_dense_wall_s": ab["dense"]["wall_s"],
        "compress_ab_decode_wall_s": ab["decode"]["wall_s"],
        "compress_ab_resident_bytes_dense": dense_bytes,
        "compress_ab_resident_bytes_compressed": comp_bytes,
        "compress_ab_compression_ratio": ab["compression_ratio"],
        "compress_ab_resident_reduction": ab["resident_reduction"],
        "compress_ab_identical": int(ab["identical_partition"]),
    })
    print(json.dumps(record), flush=True)
    return record


def run_shard_phase(record: dict | None = None) -> dict:
    """Phase 5 (ISSUE 11): sharded deep-multilevel A/B on the P-device mesh.

    Three arms at one (scale, k, seed) workload: the single-device shm deep
    pipeline, the P-shard dist pipeline on the dense staging path, and the
    P-shard dist pipeline off the device-resident per-shard compressed
    streams (``device_decode``).  Per arm: end-to-end wall, per-level trace
    rows (they ride the levels' existing counted pulls), the per-shard pull
    census (``shard_pulls`` over the dist phases), the trace-time collective
    census, and the HBM watermark (allocator stats exist on TPU; the static
    resident-bytes figures are exact on every backend).  The dense-vs-
    compressed identical-partition check is the acceptance witness; flat
    ``shard_ab_*`` keys ride RUNS.jsonl so ``tools regress`` covers them,
    and tpu_prober carries the phase on-silicon through run_benchmark.

    Runs on whatever mesh this process has; the ``--child`` entry forces
    ``KPTPU_BENCH_SHARD_P`` virtual CPU devices (the dryrun) unless
    ``KPTPU_BENCH_SHARD_NATIVE=1`` keeps the ambient multi-chip mesh.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from kaminpar_tpu.dist.compressed import compress_distributed
    from kaminpar_tpu.dist.device_compressed import build_dist_device_view
    from kaminpar_tpu.dist.partitioner import DKaMinPar
    from kaminpar_tpu.graph import metrics as gmetrics
    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name
    from kaminpar_tpu.telemetry import trace as ttrace
    from kaminpar_tpu.utils import (
        RandomState, Timer, collective_stats, heap_profiler, sync_stats,
    )
    from kaminpar_tpu.utils.heap_profiler import HeapProfiler

    record = dict(record or {})
    P = int(os.environ.get("KPTPU_BENCH_SHARD_P", 8))
    scale = int(os.environ.get("KPTPU_BENCH_SHARD_SCALE", 12))
    k = int(os.environ.get("KPTPU_BENCH_SHARD_K", 8))
    # Contraction limit for the mesh arms: the default C=2000 stops dryrun-
    # sized graphs before any dist level forms; 256 gives a real hierarchy
    # (several coarsen/uncoarsen levels) at scale 12 so the per-level rows
    # and the coarsening pull census measure something.
    cl = int(os.environ.get("KPTPU_BENCH_SHARD_CL", 256))
    devs = jax.devices()
    backend = devs[0].platform
    if len(devs) < P:
        raise RuntimeError(
            f"shard_ab needs {P} devices, have {len(devs)} (the --child "
            "entry forces virtual CPU devices; in-process callers must)"
        )
    mesh = Mesh(np.array(devs[:P]), ("nodes",))
    g = rmat_graph(scale, edge_factor=8, seed=1)

    # Static resident-adjacency accounting straight from the view layout
    # (exact on every backend): dense = the three (P*m_loc,) structural
    # arrays, compressed = words + decode metadata + ghost table.
    dcg = compress_distributed(g, P)
    view = build_dist_device_view(dcg)
    dense_bytes = view.dense_resident_bytes()
    comp_bytes = view.resident_bytes()
    del view, dcg  # the measured arms rebuild their own

    ab: dict = {
        "backend": backend,
        "shards": P,
        "scale": scale,
        "k": k,
        "contraction_limit": cl,
        "resident_bytes_dense": dense_bytes,
        "resident_bytes_compressed": comp_bytes,
        "bytes_per_edge_dense": round(dense_bytes / max(g.m, 1), 2),
        "bytes_per_edge_compressed": round(comp_bytes / max(g.m, 1), 2),
        "resident_reduction": round(dense_bytes / max(comp_bytes, 1), 3),
    }
    # The env override beats the per-arm ctx knob (resolve_device_decode);
    # clear it so both mesh arms measure what they claim.
    env_override = os.environ.pop("KAMINPAR_TPU_DEVICE_DECODE", None)
    if env_override is not None:
        ab["env_override_cleared"] = env_override

    def _arm_record(wall: float, part, trace_rec) -> dict:
        arm = {
            "wall_s": round(wall, 2),
            "cut": int(gmetrics.edge_cut(g, part)),
            "hbm": heap_profiler.watermark_report(),
        }
        snap = sync_stats.snapshot()["phases"]
        arm["pull_census"] = {
            phase: {
                "count": row["count"],
                "shard_pulls": row.get("shard_pulls", 0),
            }
            for phase, row in sorted(snap.items())
            if phase.startswith("dist_")
        }
        coll = collective_stats.snapshot()
        arm["collectives_traced"] = {
            "count": coll.get("count", 0),
            "logical_bytes": coll.get("logical_bytes", coll.get("bytes", 0)),
        }
        if trace_rec is not None:
            # Per-level rows (n, m, shrink / k per level) — they rode the
            # levels' existing counted pulls, zero added transfers.
            arm["levels"] = [
                r for r in trace_rec.quality
                if str(r.get("kind", "")).startswith("dist_")
            ][:24]
        return arm

    # Arm 0: single-device shm deep at the same workload (the wall anchor
    # for the dryrun; on a CPU mesh the P-shard arms pay collective overhead
    # for no real parallelism — the honest reading is in TPU_NOTES r15).
    RandomState.reseed(0)
    Timer.reset_global()
    HeapProfiler.reset(enabled=True)
    t0 = time.perf_counter()
    solver = KaMinPar("default")
    solver.ctx.seed = 1
    solver.set_graph(g)
    part_single = solver.compute_partition(k, epsilon=0.03)
    ab["single"] = {
        "wall_s": round(time.perf_counter() - t0, 2),
        "cut": int(gmetrics.edge_cut(g, part_single)),
        "hbm": heap_profiler.watermark_report(),
    }
    HeapProfiler.reset(enabled=False)

    def _mesh_ctx(compress: bool, mode: str):
        ctx = create_context_by_preset_name("default")
        ctx.seed = 1
        ctx.coarsening.contraction_limit = cl
        ctx.compression.enabled = compress
        ctx.compression.device_decode = mode
        return ctx

    parts: dict = {}
    for tag, compress, mode in (
        ("dense", False, "off"), ("compressed", True, "finest")
    ):
        RandomState.reseed(0)
        Timer.reset_global()
        sync_stats.reset()
        collective_stats.reset()
        trace_rec = None if ttrace.active() is not None else ttrace.start()
        HeapProfiler.reset(enabled=True)
        t0 = time.perf_counter()
        try:
            parts[tag] = DKaMinPar(mesh, _mesh_ctx(compress, mode)).compute_partition(
                g, k=k, epsilon=0.03
            )
        finally:
            wall = time.perf_counter() - t0
            if trace_rec is not None:
                ttrace.stop()
        ab[tag] = _arm_record(wall, parts[tag], trace_rec)
        HeapProfiler.reset(enabled=False)
    if env_override is not None:
        os.environ["KAMINPAR_TPU_DEVICE_DECODE"] = env_override

    # Acceptance witness: the compressed mesh arm is bit-identical to the
    # dense mesh arm (same seed, same mesh, decode-fused kernels).
    ab["identical_partition"] = bool(
        np.array_equal(parts["dense"], parts["compressed"])
    )
    peaks = [
        ab[tag].get("hbm", {}).get("peak_bytes_in_use")
        for tag in ("dense", "compressed")
    ]
    if all(isinstance(p, int) for p in peaks):
        ab["hbm_peak_delta_bytes"] = peaks[0] - peaks[1]
    record["shard_ab"] = ab
    # Flat ledger keys (telemetry/ledger: *_wall_s/_bytes/count lower-better,
    # *_reduction higher-better; covered by the tools regress windows).
    comp_pulls = sum(
        row["shard_pulls"] for row in ab["compressed"]["pull_census"].values()
    )
    record.update({
        "shard_ab_single_wall_s": ab["single"]["wall_s"],
        "shard_ab_dense_wall_s": ab["dense"]["wall_s"],
        "shard_ab_compressed_wall_s": ab["compressed"]["wall_s"],
        "shard_ab_resident_bytes_dense": dense_bytes,
        "shard_ab_resident_bytes_compressed": comp_bytes,
        "shard_ab_resident_reduction": ab["resident_reduction"],
        "shard_ab_identical": int(ab["identical_partition"]),
        "shard_ab_shard_pull_count": comp_pulls,
        "shard_ab_collective_bytes":
            ab["compressed"]["collectives_traced"]["logical_bytes"],
    })
    print(json.dumps(record), flush=True)
    return record


def run_fleet_phase(record: dict | None = None) -> dict:
    """Phase 6 (ISSUE 14): single-engine vs N-replica fleet A/B on the
    P-device mesh (CPU dryrun: forced virtual host devices, like shard_ab).

    One same-cell burst workload served two ways: (a) ONE warm
    PartitionEngine — the PR 3/6 pattern, lane axis only — and (b) a
    :class:`~kaminpar_tpu.serve.fleet.PartitionFleet` of P per-device
    replicas behind the SLO-aware shape-cell router (lane x device).  Per
    arm: aggregate graphs/s, per-replica batch occupancy, p50/p99 total
    latency (computed fleet-wide from the request results themselves),
    steer/resteer counts, warm-cache inheritance counts (replica 0 pays
    the ladder, replicas 1..N-1 import it — the inherit ratio is a ledger
    metric), and a per-replica bit-identity probe against a sequential
    facade run.  Flat ``fleet_*`` keys ride RUNS.jsonl under the ``tools
    regress`` sentinel; tpu_prober carries the phase on-silicon.

    CPU-dryrun honesty (TPU_NOTES round 18): virtual host devices
    SERIALIZE — the aggregate-throughput ratio is a *device* claim; on CPU
    this phase proves routing, occupancy, inheritance, and bit-identity,
    not speedup.
    """
    import jax
    import numpy as np

    from kaminpar_tpu.graph.generators import rmat_graph
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.serve import PartitionEngine, PartitionFleet, QueueFullError
    from kaminpar_tpu.serve.batching import shape_cell
    from kaminpar_tpu.utils import RandomState

    record = dict(record or {})
    P = int(os.environ.get("KPTPU_BENCH_FLEET_P", 8))
    scale = int(os.environ.get("KPTPU_BENCH_FLEET_SCALE", 8))
    k = int(os.environ.get("KPTPU_BENCH_FLEET_K", 8))
    n_req = int(os.environ.get("KPTPU_BENCH_FLEET_REQS", 64))
    max_batch = int(os.environ.get("KPTPU_BENCH_FLEET_MAX_BATCH", 8))
    devs = jax.devices()
    backend = devs[0].platform
    if len(devs) < P:
        raise RuntimeError(
            f"fleet phase needs {P} devices, have {len(devs)} (the --child "
            "entry forces virtual CPU devices; in-process callers must)"
        )

    # Same-cell burst workload: distinct seeds from one RMAT family,
    # filtered to the dominant shape cell (the batch population the serve
    # queue and the router actually see).
    pool = [rmat_graph(scale, edge_factor=8, seed=300 + i)
            for i in range(2 * n_req)]
    cells = [shape_cell(g, k) for g in pool]
    head = max(set(cells), key=cells.count)
    graphs = [g for g, c in zip(pool, cells) if c == head][:n_req]
    n_req = len(graphs)

    serve_cfg = dict(
        warm_ladder=(1 << scale,), warm_ks=(k,), max_batch=max_batch,
        queue_bound=max(n_req, 8),
    )

    def _submit_backpressured(target, g):
        while True:
            try:
                return target.submit(g, k)
            except QueueFullError as e:
                time.sleep(e.retry_after_s)

    def _measure_burst(target) -> dict:
        # Burst with a held dispatcher so the queues (and the router's
        # batch-join fill) see the whole offered load, then release.
        target.pause()
        t0 = time.perf_counter()
        futures = [_submit_backpressured(target, g) for g in graphs]
        target.resume()
        results = [f.result() for f in futures]
        wall = time.perf_counter() - t0
        totals = [
            (r.queue_wait_s + r.execute_s) * 1e3 for r in results
        ]
        return {
            "wall_s": round(wall, 2),
            "throughput_gps": round(n_req / wall, 2),
            "p50_ms": round(float(np.percentile(totals, 50)), 1),
            "p99_ms": round(float(np.percentile(totals, 99)), 1),
            "results": results,
        }

    ab: dict = {"backend": backend, "replicas": P, "scale": scale, "k": k,
                "requests": n_req, "max_batch": max_batch}

    # Sequential reference for the bit-identity probe (the engine contract:
    # warm serve results == cold facade runs).
    RandomState.reseed(0)
    ref_solver = KaMinPar(ctx="serve")
    ref_solver.set_graph(graphs[0])
    ref_part = ref_solver.compute_partition(k, 0.03)

    # Arm A: one warm engine (lane axis only).
    RandomState.reseed(0)
    engine = PartitionEngine("serve", **serve_cfg)
    t0 = time.perf_counter()
    engine.start(warmup=True)
    single_warm_s = time.perf_counter() - t0
    try:
        for fut in [_submit_backpressured(engine, g) for g in graphs]:
            fut.result()  # preflight: pay first-touch traces unmeasured
        engine.stats_.reset()
        burst = _measure_burst(engine)
        snap = engine.stats_.snapshot()
        ab["single"] = {
            "warmup_s": round(single_warm_s, 2),
            "wall_s": burst["wall_s"],
            "throughput_gps": burst["throughput_gps"],
            "p50_ms": burst["p50_ms"],
            "p99_ms": burst["p99_ms"],
            "batch_occupancy_mean": snap["batch_occupancy_mean"],
            "batch_occupancy_max": snap["batch_occupancy_max"],
            "lanestacked_batches": snap["lanestacked_batches"],
        }
    finally:
        engine.shutdown(drain=True)

    # Arm B: the P-replica fleet (lane x device).
    RandomState.reseed(0)
    fleet = PartitionFleet("serve", replicas=P, **serve_cfg)
    t0 = time.perf_counter()
    fleet.start(warmup=True)
    fleet_warm_s = time.perf_counter() - t0
    try:
        inherit = [r.warmup_cell_counts() for r in fleet.replicas]
        inherited_total = sum(c["inherited"] for c in inherit[1:])
        report_total = sum(
            c["inherited"] + c["local"] for c in inherit[1:]
        )
        # Per-replica bit-identity probe: the same (graph, seed, k) request
        # pinned to the first and last replica must equal the sequential
        # facade run exactly (the acceptance witness).
        probes = [
            fleet.submit(graphs[0], k, replica=r).result().partition
            for r in (0, P - 1)
        ]
        ab["identical_partition"] = bool(all(
            np.array_equal(p, ref_part) for p in probes
        ))
        # Preflight (unmeasured): pay first-touch traces on every replica,
        # then zero the measured window.
        for fut in [_submit_backpressured(fleet, g) for g in graphs]:
            fut.result()
        for r in fleet.replicas:
            r.stats_.reset()
        # Router counters are cumulative (probes + preflight + every
        # backpressure retry re-entering submit): snapshot here so the
        # ledger reports the measured burst's DELTA, not process totals.
        pre = fleet.stats()
        burst = _measure_burst(fleet)
        per_replica = []
        agg_occupancy = 0.0
        for i, r in enumerate(fleet.replicas):
            snap = r.stats_.snapshot()
            agg_occupancy += snap["batch_occupancy_max"]
            per_replica.append({
                "replica": i,
                "completed": snap["completed"],
                "batch_occupancy_mean": snap["batch_occupancy_mean"],
                "batch_occupancy_max": snap["batch_occupancy_max"],
                "lanestacked_batches": snap["lanestacked_batches"],
                "lanestacked_lanes": snap["lanestacked_lanes"],
                "inherited_cells": inherit[i]["inherited"],
                "local_cells": inherit[i]["local"],
            })
        fstats = fleet.stats()
        ab["fleet"] = {
            "warmup_s": round(fleet_warm_s, 2),
            "wall_s": burst["wall_s"],
            "throughput_gps": burst["throughput_gps"],
            "p50_ms": burst["p50_ms"],
            "p99_ms": burst["p99_ms"],
            "aggregate_occupancy": agg_occupancy,
            "steered": (
                sum(r["steered"] for r in fstats["per_replica"])
                - sum(r["steered"] for r in pre["per_replica"])
            ),
            "resteers": fstats["resteers"] - pre["resteers"],
            "sticky_hits": fstats["sticky_hits"] - pre["sticky_hits"],
            "rejected_full": fstats["rejected_full"] - pre["rejected_full"],
            "inherited_cells": inherited_total,
            "per_replica": per_replica,
        }
        # Elastic exercise (ISSUE 15): one scale-down/scale-up cycle on
        # the live fleet — the drained replica's work resteers, the
        # revived slot restarts warm — so the fleet_scale_* ledger keys
        # measure a REAL drain/revive, not untouched zeros.
        if P >= 2:
            t0 = time.perf_counter()
            fleet.scale_to(P - 1, reason="bench elastic cycle")
            down_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            fleet.scale_to(P, reason="bench elastic cycle")
            up_s = time.perf_counter() - t0
            sstats = fleet.stats()
            ab["fleet"]["scale"] = {
                "down_s": round(down_s, 3),
                "up_s": round(up_s, 3),
                "active_after": sstats["active_replicas"],
                **{key: val for key, val in sstats.items()
                   if key.startswith("fleet_scale_")},
            }
    finally:
        fleet.shutdown(drain=True)

    record["fleet_ab"] = ab
    # Standalone child runs feed the ledger directly (tools ledger
    # append): tag the backend so baseline windows stay comparable.
    record.setdefault("backend", backend)
    # Flat ledger keys under the regress sentinel (telemetry/ledger
    # direction markers: _gps/_vs_/_ratio up, _ms/_s/count down).
    record.update({
        "fleet_single_gps": ab["single"]["throughput_gps"],
        "fleet_agg_gps": ab["fleet"]["throughput_gps"],
        "fleet_vs_single": round(
            ab["fleet"]["throughput_gps"]
            / max(ab["single"]["throughput_gps"], 1e-9), 2
        ),
        "fleet_p50_ms": ab["fleet"]["p50_ms"],
        "fleet_p99_ms": ab["fleet"]["p99_ms"],
        "fleet_aggregate_occupancy": ab["fleet"]["aggregate_occupancy"],
        "fleet_resteer_count": ab["fleet"]["resteers"],
        "fleet_identical": int(ab["identical_partition"]),
        "fleet_inherit_ratio": round(
            inherited_total / max(report_total, 1), 3
        ),
        "fleet_warmup_s": ab["fleet"]["warmup_s"],
    })
    # Elastic-cycle ledger keys (ISSUE 15): scale walls + the census the
    # cycle produced (retire/revive counts; zero lost resolutions is
    # asserted by the test matrix, the bench records the cost).
    if "scale" in ab["fleet"]:
        record.update({
            "fleet_scale_down_s": ab["fleet"]["scale"]["down_s"],
            "fleet_scale_up_s": ab["fleet"]["scale"]["up_s"],
            "fleet_scale_retires": ab["fleet"]["scale"]["fleet_scale_retires"],
            "fleet_scale_revives": ab["fleet"]["scale"]["fleet_scale_revives"],
        })
    print(json.dumps(record, default=str), flush=True)
    return record


def _merge_child_phase(rec: dict, phase: str, sentinel: str, prefix: str,
                       *, echo: bool = False) -> None:
    """Run one bench phase in its own child process and merge its
    ``prefix``-keyed results into ``rec`` — shard_ab and fleet_ab both
    need their own device topology (P virtual CPU devices for the dryrun,
    KPTPU_BENCH_*_NATIVE=1 keeps a real mesh), so they never run in this
    process.  ``sentinel`` gates success and names the error key; the
    timeout rides KPTPU_BENCH_<PHASE>_TIMEOUT."""
    timeout = float(
        os.environ.get(f"KPTPU_BENCH_{phase.upper()}_TIMEOUT", 900)
    )
    child_rec, child_err = _run_child(timeout, extra_env={
        "KPTPU_BENCH_PHASE": phase,
    })
    if child_rec and sentinel in child_rec:
        for key, val in child_rec.items():
            if key.startswith(prefix):
                rec[key] = val
        if echo:
            print(json.dumps(rec), flush=True)
    else:
        rec[f"{sentinel}_error"] = (
            child_err or f"{phase} phase produced no record"
        )


def run_benchmark() -> dict:
    """All phases in-process (used by the prober child and --child mode).
    Returns the final headline record (the ledger entry's source)."""
    record = run_lp_phase()
    if os.environ.get("KPTPU_BENCH_FULL", "1") == "1":
        record = run_full_phase(record)
    if os.environ.get("KPTPU_BENCH_SERVE", "1") == "1":
        record = run_serve_phase(record)
    if os.environ.get("KPTPU_BENCH_COMPRESS", "1") == "1":
        try:
            record = run_compress_phase(record)
        except Exception as exc:  # noqa: BLE001 — A/B must not void phases 1-3
            record["compress_ab_error"] = f"{type(exc).__name__}: {exc}"[:300]
    if os.environ.get("KPTPU_BENCH_SHARD", "1") == "1":
        _merge_child_phase(record, "shard", "shard_ab", "shard_ab",
                           echo=True)
    if os.environ.get("KPTPU_BENCH_FLEET", "1") == "1":
        _merge_child_phase(record, "fleet", "fleet_ab", "fleet_",
                           echo=True)
    return record


def _ledger_record(rec: dict | None, kind: str = "bench") -> None:
    """Append the run's compact summary to RUNS.jsonl (round 13; see
    telemetry/ledger.py).  Called only at the parent's terminal points so
    child re-runs cannot double-append; failures never void the record."""
    if not rec:
        return
    try:
        from kaminpar_tpu.telemetry import ledger

        ledger.record_run(
            rec, kind=kind, git_head=rec.get("git_head") or _git_head()
        )
    except Exception:  # noqa: BLE001
        pass


def probe_telemetry() -> dict | None:
    """Summarize TPU_PROBE_LOG.jsonl for embedding in the artifact.

    Round 13: the attempt history is compressed into OUTCOME COUNTS —
    BENCH_r05's tail was dominated by dozens of identical
    ``init_hang_killed_after_1200s`` records; the count plus the first/last
    timestamps carries the same evidence in a fixed-size summary.  The 6h
    failure window the inline-probe decision needs is computed here from
    the raw per-attempt timestamps (``recent_failed_6h``) instead of
    shipping the records themselves."""
    if not os.path.exists(TPU_PROBE_LOG):
        return None
    attempts = []
    events = []
    with open(TPU_PROBE_LOG) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if "attempt" in rec:
                attempts.append(rec)
            elif "event" in rec:
                events.append(rec.get("event"))
    if not attempts and not events:
        return None
    outcomes: dict[str, int] = {}
    for a in attempts:
        out = a.get("outcome", "?")
        outcomes[out] = outcomes.get(out, 0) + 1
    cutoff = time.time() - 6.0 * 3600
    summary = {
        "attempts": len(attempts),
        "outcomes": outcomes,
        "events": events,
        "recent_failed_6h": sum(
            1 for a in attempts
            if a.get("outcome") != "measured" and a.get("ts", 0) >= cutoff
        ),
    }
    if attempts:
        summary["first_attempt_iso"] = attempts[0].get("iso")
        summary["last_attempt_iso"] = attempts[-1].get("iso")
        summary["last_outcome"] = attempts[-1].get("outcome")
    return summary


def _recent_failures(telemetry: dict | None) -> int:
    """Failed probe attempts within the summary's 6 h window — a stale
    log from a previous round must not permanently disable the inline
    probe."""
    if not telemetry:
        return 0
    return int(telemetry.get("recent_failed_6h", 0))


def _git_head() -> str:
    # Round 20: one resolver (env override -> rev-parse -> ""), cached
    # per process, shared with every ledger entry writer.
    from kaminpar_tpu.telemetry.ledger import resolve_git_head

    return resolve_git_head()


def _salvage(stdout: str) -> dict | None:
    """Last complete JSON object the child flushed, if any."""
    best = None
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                best = json.loads(line)
            except ValueError:
                pass
    return best


def _run_child(timeout_s: float, extra_env: dict | None = None) -> tuple[dict | None, str]:
    """Run the benchmark in a killable subprocess on the ambient backend.

    Own process group so a timeout kill reaches any helper the plugin forked
    (ssh/grpc proxies inherit the pipes; killing only the direct child would
    leave communicate() blocked on pipe EOF forever).  Returns the salvaged
    headline record (or None) and an error string ('' = clean)."""
    env = dict(os.environ)
    env.update(extra_env or {})
    # Flight recorder (ISSUE 12): every killable bench child heartbeats to
    # its own sidecar with a stack dump armed just under the kill timeout,
    # so a timeout kill yields a dossier (phase + stack tail) instead of a
    # bare "killed after N s".  The sidecar env contract is single-sourced
    # in telemetry/flight_recorder.child_sidecar_env (shared with the
    # prober's run_attempt).
    from kaminpar_tpu.telemetry import flight_recorder

    phase_tag = (extra_env or {}).get("KPTPU_BENCH_PHASE", "bench")
    fr_env, hb_path, stack_path = flight_recorder.child_sidecar_env(
        os.path.join(REPO, f".bench_child_{phase_tag}"), timeout_s
    )
    env.update(fr_env)
    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            start_new_session=True,
            env=env,
        )
    except Exception as exc:  # noqa: BLE001
        return None, f"{type(exc).__name__}: {exc}"[:500]
    dossier = None
    try:
        out, errout = proc.communicate(timeout=timeout_s)
        err = ""
        if proc.returncode != 0:
            err = (errout.strip().splitlines() or ["child failed"])[-1][:500]
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        out, errout = proc.communicate()
        err = f"benchmark child killed after {timeout_s:.0f}s"
        try:
            dossier = flight_recorder.read_dossier(hb_path, stack_path)
        except Exception:  # noqa: BLE001 — forensics must not mask the kill
            dossier = None
        if dossier is not None:
            err += (f" (phase={dossier.get('phase')} "
                    f"class={dossier.get('phase_class')})")
    flight_recorder.cleanup_sidecars(hb_path, stack_path)
    rec = _salvage(out or "")
    if rec is not None and err:
        rec["note"] = err  # partial result: headline phase finished, later phase cut off
        if dossier is not None:
            rec["kill_dossier"] = dossier
        err = ""
    return rec, err


def _cpu_fallback(err: str, telemetry: dict | None) -> None:
    """In-process CPU LP phase + own-deadline CPU child for phase 2."""
    from kaminpar_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(1)

    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rec = run_lp_phase()
    rec = rec or {"metric": "lp_clustering_throughput", "value": 0.0,
                  "unit": "edges/sec", "vs_baseline": 0.0}
    rec["backend"] = "cpu-fallback"
    rec["error"] = err or "backend init failed"
    if telemetry:
        rec["tpu_probe"] = telemetry
    # Flush the phase-1 headline NOW: if an outer deadline kills us during
    # the phase-2 child below, the salvage convention (last JSON line wins)
    # still finds this record.
    print(json.dumps(rec), flush=True)

    # Phase 2 in a CPU child with its own deadline (VERDICT r4 weak #2):
    # losing phase 2 must not cost the phase-1 number, and vice versa.
    full_timeout = float(os.environ.get("KPTPU_BENCH_FULL_TIMEOUT", 900))
    if os.environ.get("KPTPU_BENCH_FULL", "1") == "1":
        full_rec, full_err = _run_child(full_timeout, extra_env={
            "KPTPU_CHILD_FORCE_CPU": "1",
            "KPTPU_BENCH_PHASE": "full",
        })
        if full_rec and "partition_wall_s" in full_rec:
            for key in ("partition_wall_s", "partition_cut", "partition_scale",
                        "partition_k", "partition_edges_per_sec",
                        "compiled_shape_count", "partition_compile",
                        "host_sync_count", "host_sync_bytes", "host_sync",
                        "ip_backend", "initial_partitioning_wall_s",
                        "initial_partitioning_share", "ip_pool", "ip_ab",
                        "ip_ab_error", "telemetry", "telemetry_error",
                        "phase_walls_s", "collectives", "lint",
                        "resilience", "resilience_error"):
                if key in full_rec:
                    rec[key] = full_rec[key]
        else:
            rec["partition_error"] = full_err or "phase 2 produced no record"
    # Phase 3 (serve-mode, ISSUE 3) in its own CPU child: the offered-load
    # sweep must not cost the phase-1/2 records, and vice versa.
    if os.environ.get("KPTPU_BENCH_SERVE", "1") == "1":
        serve_timeout = float(os.environ.get("KPTPU_BENCH_SERVE_TIMEOUT", 900))
        serve_rec, serve_err = _run_child(serve_timeout, extra_env={
            "KPTPU_CHILD_FORCE_CPU": "1",
            "KPTPU_BENCH_PHASE": "serve",
        })
        if serve_rec and "serve_throughput_gps" in serve_rec:
            for key, val in serve_rec.items():
                if key.startswith(("serve_", "single_request", "warm_single",
                                   "lanestack_")):
                    rec[key] = val
        else:
            rec["serve_error"] = serve_err or "serve phase produced no record"
    # Phases 5/6 (shard_ab / fleet_ab) in their own children: each forces
    # its own virtual P-device CPU mesh regardless of this process's
    # 1-device pin.
    if os.environ.get("KPTPU_BENCH_SHARD", "1") == "1":
        _merge_child_phase(rec, "shard", "shard_ab", "shard_ab")
    if os.environ.get("KPTPU_BENCH_FLEET", "1") == "1":
        _merge_child_phase(rec, "fleet", "fleet_ab", "fleet_")
    rec.setdefault("git_head", _git_head())
    rec.setdefault("stale_vs_head", False)  # fallback measured at head
    print(json.dumps(rec))
    _ledger_record(rec)


def main() -> None:
    if "--child" in sys.argv:
        # Flight recorder (ISSUE 12): heartbeat + armed stack dump when the
        # parent configured sidecars (bench _run_child and the prober do).
        try:
            from kaminpar_tpu.telemetry import flight_recorder

            flight_recorder.arm_from_env()
        except Exception:  # noqa: BLE001 — forensics must not break the child
            pass
        phase = os.environ.get("KPTPU_BENCH_PHASE")
        if phase == "shard":
            # The 8-device CPU-mesh dryrun (ISSUE 11): force the virtual
            # mesh BEFORE the backend initializes, unless the caller pinned
            # the ambient multi-chip mesh (KPTPU_BENCH_SHARD_NATIVE=1).
            if os.environ.get("KPTPU_BENCH_SHARD_NATIVE") != "1":
                from kaminpar_tpu.utils.platform import force_cpu_devices

                force_cpu_devices(int(os.environ.get("KPTPU_BENCH_SHARD_P", 8)))
            run_shard_phase()
            return
        if phase == "fleet":
            # The P-replica fleet dryrun (ISSUE 14): same virtual-mesh
            # forcing contract as the shard phase.
            if os.environ.get("KPTPU_BENCH_FLEET_NATIVE") != "1":
                from kaminpar_tpu.utils.platform import force_cpu_devices

                force_cpu_devices(int(os.environ.get("KPTPU_BENCH_FLEET_P", 8)))
            run_fleet_phase()
            return
        if os.environ.get("KPTPU_CHILD_FORCE_CPU") == "1":
            from kaminpar_tpu.utils.platform import force_cpu_devices

            force_cpu_devices(1)
        if phase == "full":
            run_full_phase()
        elif phase == "serve":
            run_serve_phase()
        elif phase == "compress":
            run_compress_phase()
        else:
            run_benchmark()
        return
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # Explicitly CPU-pinned environment (tests/CI): measure in-process —
        # this regression signal for the current commit must never be
        # shadowed by a cached TPU artifact.  force_cpu_devices, not the env
        # var alone: the axon site hook sets jax.config jax_platforms=axon at
        # interpreter start, which beats the env var — only an explicit
        # config update wins it back.
        from kaminpar_tpu.utils.platform import force_cpu_devices

        force_cpu_devices(1)
        rec = run_benchmark()
        rec.setdefault("git_head", _git_head())
        rec.setdefault("stale_vs_head", False)  # measured at head, in-process
        _ledger_record(rec)
        return
    telemetry = probe_telemetry()
    # A prober-captured silicon result from any point in the round beats
    # re-probing a tunnel that may have closed again — but only a *fresh*
    # one (default 24 h ~ one round): a stale artifact from an older build
    # must not masquerade as a measurement of current code.
    max_age_h = float(os.environ.get("KPTPU_TPU_RESULT_MAX_AGE_H", 24))
    if os.path.exists(TPU_RESULT_PATH):
        age_h = (time.time() - os.path.getmtime(TPU_RESULT_PATH)) / 3600
        try:
            with open(TPU_RESULT_PATH) as fh:
                rec = json.load(fh)
        except ValueError:
            rec = None
        if (
            rec is not None
            and age_h <= max_age_h
            and rec.get("backend") not in (None, "cpu", "cpu-fallback")
        ):
            if telemetry:
                rec["tpu_probe"] = telemetry
            rec["source"] = "tpu_prober"
            rec["result_age_h"] = round(age_h, 2)
            head = _git_head()
            # stale_vs_head is ALWAYS recorded explicitly (round 13): its
            # absence used to be ambiguous between "fresh" and "not checked".
            stale = bool(
                head and rec.get("git_head") and rec["git_head"] != head
            )
            rec["stale_vs_head"] = stale
            if stale:
                # still a real silicon number, but flag that the code moved
                rec["git_head_now"] = head
            print(json.dumps(rec))
            # NO ledger append here: the prober already recorded this
            # measurement (kind="prober") at capture time, and this branch
            # can re-serve the same artifact for 24h — appending per
            # invocation would fill the regress baseline window with
            # clones of one run.
            return
    # No prober success.  If the round-long log already shows repeated init
    # failures, the "tunnel down" claim is evidenced — skip another >560 s
    # hang and spend the budget on the CPU fallback's phase 2 instead.
    recent_failed = _recent_failures(telemetry)
    if recent_failed >= 2:
        _cpu_fallback(
            f"tpu backend unreachable: {recent_failed} prober attempts "
            f"failed in the last 6h (see TPU_PROBE_LOG.jsonl)", telemetry)
        return
    # Observed init hang exceeds 560 s; the probe budget must exceed it
    # (VERDICT r4 missing #1).
    timeout_s = float(os.environ.get("KPTPU_TPU_PROBE_TIMEOUT", 900))
    rec, err = _run_child(timeout_s)
    if rec is not None:
        if telemetry:
            rec["tpu_probe"] = telemetry
        rec.setdefault("git_head", _git_head())
        rec.setdefault("stale_vs_head", False)  # child measured at head
        print(json.dumps(rec))
        _ledger_record(rec)
        return
    _cpu_fallback(err, telemetry)


if __name__ == "__main__":
    main()
