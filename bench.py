#!/usr/bin/env python
"""Driver benchmark: LP coarsening throughput (edges/sec) on an RMAT graph.

Mirrors the reference's north-star microbenchmark
(``apps/benchmarks/shm_label_propagation_benchmark.cc``): build a graph, run
the LP clustering hot loop, report throughput.  BASELINE config 2 is RMAT
scale-22 / k=16; the scale is tunable via ``KPTPU_BENCH_SCALE`` so CI boxes
without a TPU can run a smaller instance.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` divides by a documented estimate of the reference's
shared-memory LP throughput (~250 M edges/s on a modern multicore; the repo
publishes no in-tree numbers, BASELINE.json ``published: {}``), so >1.0 means
faster than the CPU baseline estimate.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp


from kaminpar_tpu.coarsening.max_cluster_weights import compute_max_cluster_weight
from kaminpar_tpu.utils.platform import force_cpu_devices
from kaminpar_tpu.context import Context
from kaminpar_tpu.graph.generators import rmat_graph
from kaminpar_tpu.ops import lp
from kaminpar_tpu.utils import RandomState, next_key

# Measured reference anchor (VERDICT r1 weak #6: the previous 250e6 was a
# guess).  Measured 2026-07-30 on this box with the reference binary built
# from /root/reference (Release, -t 1, sparsehash/kassert off):
#   rgg64k (n=65k, m=1.63M directed): coarsening 0.079 s -> 20.6M edges/s
#   rmat14 (n=16k, m=0.22M directed): coarsening 0.016 s -> 13.6M edges/s
# Single-core LP-coarsening throughput ~= 17e6 edges/s.  The BASELINE.md
# north star compares against the 96-core TBB configuration; assuming 50%
# parallel efficiency (LP scales well but not linearly) gives the
# multicore anchor below.
CPU_BASELINE_1CORE_EDGES_PER_SEC = 17e6
CPU_BASELINE_EDGES_PER_SEC = CPU_BASELINE_1CORE_EDGES_PER_SEC * 96 * 0.5


def _probe_backend(timeout_s: float) -> tuple[str | None, str | None]:
    """Probe the ambient JAX backend in a subprocess.

    BENCH_r01 died with an unguarded ``jax.devices()``; worse, the tunneled
    TPU plugin can *hang* (not fail) during backend init, which no try/except
    in-process can catch.  A killable subprocess running device enumeration
    plus a tiny compile is the only reliable test.  The reference's benchmark
    harness always produces a number (shm_label_propagation_benchmark.cc:29-80);
    so must we.  Returns (platform_name | None, error | None); any platform
    name other than "cpu" counts as an accelerator (tunneled plugins may
    register under a non-"tpu" name).
    """
    code = (
        "import jax, jax.numpy as jnp\n"
        "plats = sorted({d.platform for d in jax.devices()})\n"
        "jnp.zeros(8).sum().block_until_ready()\n"
        "print('PROBE_OK', ','.join(plats))\n"
    )
    try:
        # Own process group so a timeout kill reaches any helper the plugin
        # forked (ssh/grpc proxies inherit the pipes; killing only the direct
        # child would leave communicate() blocked on pipe EOF forever).
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            start_new_session=True,
        )
        try:
            out, errout = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.communicate()
            return None, f"backend init timed out after {timeout_s:.0f}s"
    except Exception as exc:  # noqa: BLE001
        return None, f"{type(exc).__name__}: {exc}"[:500]
    if proc.returncode == 0:
        for line in out.splitlines():
            if line.startswith("PROBE_OK"):
                plats = line.split(None, 1)[1].split(",") if " " in line else []
                accel = [p for p in plats if p != "cpu"]
                return (accel[0] if accel else "cpu"), None
    return None, (errout.strip().splitlines() or ["probe failed"])[-1][:500]


def _init_backend() -> tuple[str, str | None]:
    """Pick a backend that is guaranteed to work: the ambient accelerator if
    the probe passes, else CPU with the probe's error recorded.  Returns
    (name, error|None); name "cpu" = no accelerator configured (clean),
    "cpu-fallback" = accelerator configured but broken."""
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return "cpu", None
    timeout_s = float(os.environ.get("KPTPU_TPU_PROBE_TIMEOUT", 90))
    platform, err = _probe_backend(timeout_s)
    if platform is not None:
        # Residual risk: the parent re-initializes the backend after the
        # probe, so a tunnel that wedges *between* probe and init still
        # hangs; the driver's outer timeout is the backstop for that.
        return platform, None
    force_cpu_devices(1)
    return "cpu-fallback", err


def main() -> None:
    backend, backend_err = _init_backend()
    on_tpu = backend not in ("cpu", "cpu-fallback")
    if not on_tpu:
        # CPU path: the persistent-cache executable serializer is the known
        # crasher (see kaminpar_tpu/__init__); a benchmark must never die
        # writing a cache.
        jax.config.update("jax_compilation_cache_dir", None)
    default_scale = 22 if on_tpu else 16
    scale = int(os.environ.get("KPTPU_BENCH_SCALE", default_scale))
    rounds = int(os.environ.get("KPTPU_BENCH_ROUNDS", 5))
    k = int(os.environ.get("KPTPU_BENCH_K", 16))

    RandomState.reseed(0)
    graph = rmat_graph(scale, edge_factor=16, seed=1)
    pv = graph.padded()
    n_pad = pv.n_pad

    bv = graph.bucketed()
    ctx = Context()
    max_cw = compute_max_cluster_weight(
        ctx.coarsening, graph.n, graph.total_node_weight, k, 0.03
    )
    idt = pv.row_ptr.dtype
    labels = jnp.concatenate(
        [jnp.arange(pv.n, dtype=idt), jnp.full(n_pad - pv.n, pv.anchor, dtype=idt)]
    )
    state = lp.init_state(labels, pv.node_w, n_pad)
    max_w = jnp.asarray(max_cw, dtype=idt)

    def one_round(state):
        return lp.lp_round_bucketed(
            state, next_key(), bv.buckets, bv.heavy, bv.gather_idx, pv.node_w,
            max_w, num_labels=n_pad,
        )

    # Warmup: compile + one real round.  Sync via scalar readback: on the
    # tunneled TPU backend block_until_ready can return before execution
    # completes, so a device->host transfer is the only reliable fence.
    state = one_round(state)
    int(state.num_moved)

    start = time.perf_counter()
    for _ in range(rounds):
        state = one_round(state)
    int(state.num_moved)
    elapsed = time.perf_counter() - start

    edges_per_sec = graph.m * rounds / elapsed
    record = {
        "metric": f"lp_clustering_throughput_rmat{scale}",
        "value": round(edges_per_sec, 1),
        "unit": "edges/sec",
        "vs_baseline": round(edges_per_sec / CPU_BASELINE_EDGES_PER_SEC, 4),
        "backend": backend,
    }
    if backend_err:
        record["error"] = backend_err
    print(json.dumps(record))


if __name__ == "__main__":
    main()
