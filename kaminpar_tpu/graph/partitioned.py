"""Partition state over a CSR graph.

Counterpart of the reference's ``GenericPartitionedGraph``
(``kaminpar-shm/datastructures/partitioned_graph.h:50``): a partition array
plus replicated block weights.  Where the reference uses atomic ``move_node``
updates, the TPU version is functional — refiners produce new ``partition``
arrays and block weights are recomputed by one ``segment_sum`` (cheap relative
to the O(m) rating kernels).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from . import metrics
from .csr import CSRGraph


@dataclass
class PartitionedGraph:
    graph: CSRGraph
    k: int
    partition: object  # (n,) int array of block ids
    max_block_weights: object  # (k,) int64 host array
    # Optional minimum block weights (reference: PartitionContext min block
    # weights, enforced by the underload balancer; None = unconstrained).
    min_block_weights: object = None

    @classmethod
    def create(
        cls, graph: CSRGraph, k: int, partition, max_block_weights, min_block_weights=None
    ) -> "PartitionedGraph":
        return cls(
            graph=graph,
            k=int(k),
            partition=jnp.asarray(partition),
            max_block_weights=np.asarray(max_block_weights, dtype=np.int64),
            min_block_weights=(
                None
                if min_block_weights is None
                else np.asarray(min_block_weights, dtype=np.int64)
            ),
        )

    def block_weights(self):
        return metrics.block_weights(self.graph, self.partition, self.k)

    def edge_cut(self) -> int:
        return metrics.edge_cut(self.graph, self.partition)

    def imbalance(self) -> float:
        return metrics.imbalance(self.graph, self.partition, self.k)

    def is_feasible(self) -> bool:
        return metrics.is_feasible(self.graph, self.partition, self.k, self.max_block_weights)

    def is_min_feasible(self) -> bool:
        if self.min_block_weights is None:
            return True
        return metrics.is_min_feasible(
            self.graph, self.partition, self.k, self.min_block_weights
        )

    def with_partition(self, partition) -> "PartitionedGraph":
        return PartitionedGraph(
            self.graph,
            self.k,
            jnp.asarray(partition),
            self.max_block_weights,
            self.min_block_weights,
        )
