"""Degree-bucketed adjacency view — the TPU-fast neighborhood layout.

The reference handles power-law degree distributions with degree buckets and a
two-phase LP (``kaminpar-shm/label_propagation.h:571-601,640-815``: low-degree
nodes node-parallel, huge-degree nodes edge-parallel).  The TPU analog
(SURVEY §7 hard part (a)): group nodes by degree into power-of-two width
buckets and lay each bucket out as a dense ``(rows, width)`` matrix.  Row-local
kernels (batched sort + cumulative ops along the width axis) then replace the
global edge sort — XLA maps them onto the VPU with full parallelism over rows,
which is ~20x faster than a flat ``m``-element sort per LP round.

Nodes with degree > ``MAX_WIDTH`` go to the *heavy* flat path (edge-parallel
sort-reduce over just their slots), mirroring the reference's second phase.

Layout invariants (all host-built once per graph, then device-resident):
- pad slots inside a row: ``col = the row's own node id`` with edge weight 0 —
  inert in ratings (a zero-weight run of the node's own label); in the heavy
  part, pad slots use ``col = anchor``;
- pad rows: ``node = anchor``; their results are never gathered;
- ``gather_idx[u]`` = position of node u's row in the concatenation of all
  bucket rows (buckets in order, then heavy rows), so per-node results are
  assembled with one gather and no scatter.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

MIN_WIDTH = 8
MAX_WIDTH = 4096  # batched row sorts stay cheap even at this width
# Buckets with fewer rows merge upward to bound the per-level kernel-shape
# count.  4096 was far too aggressive: on power-law graphs it cascaded every
# mid-degree class into one max-width bucket (rmat14: 73x slot inflation,
# 15x slower LP rounds on TPU).  Bucket *count* barely affects XLA compile
# time (row sorts are cheap to compile; measured 19 s for 2 buckets vs 19 s
# for 6); padding waste dominates runtime, so keep classes fine-grained.
MIN_ROWS = 256


class Bucket(NamedTuple):
    nodes: jax.Array  # (R,)   node id per row (pad rows -> anchor)
    cols: jax.Array  # (R, w) neighbor ids (pad slots -> anchor)
    wgts: jax.Array  # (R, w) edge weights (pad slots -> 0)


class HeavyPart(NamedTuple):
    nodes: jax.Array  # (Hr,)  heavy node id per dense row (pads -> anchor)
    row: jax.Array  # (Hs,)  dense row index per slot, ascending (pads -> Hr-1)
    cols: jax.Array  # (Hs,)  neighbor ids (pads -> anchor)
    wgts: jax.Array  # (Hs,)  edge weights (pads -> 0)


class BucketedView(NamedTuple):
    buckets: Tuple[Bucket, ...]
    heavy: HeavyPart  # zero-row part when no heavy nodes
    gather_idx: jax.Array  # (n,) row position of node u in concat(results)
    n: int

    @property
    def num_rows(self) -> int:
        """Total rows across buckets + heavy (the concat result length)."""
        r = sum(int(b.nodes.shape[0]) for b in self.buckets)
        return r + int(self.heavy.nodes.shape[0])


from ..utils.intmath import next_pow2 as _next_pow2


def build_bucketed_view(
    row_ptr: np.ndarray,
    col_idx: np.ndarray,
    edge_w: np.ndarray,
    n: int,
    anchor: int,
    *,
    min_width: int = MIN_WIDTH,
    max_width: int = MAX_WIDTH,
    min_rows: int = MIN_ROWS,
) -> BucketedView:
    rp = np.asarray(row_ptr)
    col = np.asarray(col_idx)
    ew = np.asarray(edge_w)
    idt = col.dtype
    m = col.shape[0]
    deg = np.diff(rp[: n + 1]).astype(np.int64)

    # Per-node bucket width: next power of two >= degree, clamped.
    width = np.maximum(min_width, 2 ** np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64))
    heavy_mask = deg > max_width
    width = np.minimum(width, max_width)

    # Merge sparse width classes upward so small graphs use few kernel shapes.
    # An undersized class merges into the next *naturally occupied* class, so
    # the cascade ends at next_pow2(max degree) — never at max_width — and a
    # coarse graph cannot be inflated past its own degree range.
    natural = set(int(x) for x in np.unique(width[~heavy_mask]))
    for w in sorted(natural)[:-1]:
        sel = (~heavy_mask) & (width == w)
        cnt = int(sel.sum())
        if 0 < cnt < min_rows:
            bigger = min(x for x in natural if x > w)
            width[sel] = bigger

    buckets = []
    offsets = np.zeros(n, dtype=np.int64)
    offset = 0
    for w in sorted(int(x) for x in np.unique(width[~heavy_mask])):
        nodes = np.nonzero((~heavy_mask) & (width == w))[0]
        R = len(nodes)
        R_pad = _next_pow2(R, 8)
        slot = np.arange(w, dtype=np.int64)
        idx = rp[nodes][:, None] + slot[None, :]
        valid = slot[None, :] < deg[nodes][:, None]
        safe = np.minimum(idx, max(m - 1, 0))
        cols_b = np.where(valid, col[safe] if m else 0, nodes[:, None]).astype(idt)
        wgts_b = np.where(valid, ew[safe] if m else 0, 0).astype(idt)
        nodes_b = np.full(R_pad, anchor, dtype=idt)
        nodes_b[:R] = nodes
        cols_full = np.full((R_pad, w), anchor, dtype=idt)
        cols_full[:R] = cols_b
        wgts_full = np.zeros((R_pad, w), dtype=idt)
        wgts_full[:R] = wgts_b
        buckets.append(
            Bucket(jnp.asarray(nodes_b), jnp.asarray(cols_full), jnp.asarray(wgts_full))
        )
        offsets[nodes] = offset + np.arange(R)
        offset += R_pad

    # Heavy part: flat slots of all heavy rows, padded to a power of two.
    hn = np.nonzero(heavy_mask)[0]
    Hr = len(hn)
    if Hr:
        hdeg = deg[hn]
        Hs = int(hdeg.sum())
        Hr_pad = _next_pow2(Hr + 1, 8)  # strictly > Hr so the last row is a pad
        Hs_pad = _next_pow2(Hs, 8)
        hrow = np.repeat(np.arange(Hr, dtype=idt), hdeg)
        starts = rp[hn]
        base = np.repeat(starts - np.concatenate([[0], np.cumsum(hdeg)[:-1]]), hdeg)
        hslots = base + np.arange(Hs, dtype=np.int64)
        hcols = np.full(Hs_pad, anchor, dtype=idt)
        hw = np.zeros(Hs_pad, dtype=idt)
        hrow_full = np.full(Hs_pad, Hr_pad - 1, dtype=idt)
        hcols[:Hs] = col[hslots]
        hw[:Hs] = ew[hslots]
        hrow_full[:Hs] = hrow
        hnodes = np.full(Hr_pad, anchor, dtype=idt)
        hnodes[:Hr] = hn
        heavy = HeavyPart(
            jnp.asarray(hnodes), jnp.asarray(hrow_full), jnp.asarray(hcols), jnp.asarray(hw)
        )
        offsets[hn] = offset + np.arange(Hr)
    else:
        heavy = HeavyPart(
            jnp.zeros(0, dtype=idt),
            jnp.zeros(0, dtype=idt),
            jnp.zeros(0, dtype=idt),
            jnp.zeros(0, dtype=idt),
        )

    return BucketedView(
        buckets=tuple(buckets),
        heavy=heavy,
        gather_idx=jnp.asarray(offsets.astype(idt)),
        n=n,
    )
