"""Degree-bucketed adjacency view — the TPU-fast neighborhood layout.

The reference handles power-law degree distributions with degree buckets and a
two-phase LP (``kaminpar-shm/label_propagation.h:571-601,640-815``: low-degree
nodes node-parallel, huge-degree nodes edge-parallel).  The TPU analog
(SURVEY §7 hard part (a)): group nodes by degree into power-of-two width
buckets and lay each bucket out as a dense ``(rows, width)`` matrix.  Row-local
kernels (batched sort + cumulative ops along the width axis) then replace the
global edge sort — XLA maps them onto the VPU with full parallelism over rows,
which is ~20x faster than a flat ``m``-element sort per LP round.

Nodes with degree > ``MAX_WIDTH`` go to the *heavy* flat path (edge-parallel
sort-reduce over just their slots), mirroring the reference's second phase.

Layout invariants (all host-built once per graph, then device-resident):
- pad slots inside a row: ``col = the row's own node id`` with edge weight 0 —
  inert in ratings (a zero-weight run of the node's own label); in the heavy
  part, pad slots use ``col = anchor``;
- pad rows: ``node = anchor``; their results are never gathered;
- ``gather_idx[u]`` = position of node u's row in the concatenation of all
  bucket rows (buckets in order, then heavy rows), so per-node results are
  assembled with one gather and no scatter.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

MIN_WIDTH = 8
MAX_WIDTH = 4096  # batched row sorts stay cheap even at this width
# Buckets with fewer rows merge upward to bound the per-level kernel-shape
# count.  4096 was far too aggressive: on power-law graphs it cascaded every
# mid-degree class into one max-width bucket (rmat14: 73x slot inflation,
# 15x slower LP rounds on TPU).  Bucket *count* barely affects XLA compile
# time (row sorts are cheap to compile; measured 19 s for 2 buckets vs 19 s
# for 6); padding waste dominates runtime, so keep classes fine-grained.
MIN_ROWS = 256


class Bucket(NamedTuple):
    nodes: jax.Array  # (R,)   node id per row (pad rows -> anchor)
    cols: jax.Array  # (R, w) neighbor ids (pad slots -> anchor)
    wgts: jax.Array  # (R, w) edge weights (pad slots -> 0)


class HeavyPart(NamedTuple):
    nodes: jax.Array  # (Hr,)  heavy node id per dense row (pads -> anchor)
    row: jax.Array  # (Hs,)  dense row index per slot, ascending (pads -> Hr-1)
    cols: jax.Array  # (Hs,)  neighbor ids (pads -> anchor)
    wgts: jax.Array  # (Hs,)  edge weights (pads -> 0)


class BucketedView(NamedTuple):
    buckets: Tuple[Bucket, ...]
    heavy: HeavyPart  # zero-row part when no heavy nodes
    gather_idx: jax.Array  # (n,) row position of node u in concat(results)
    n: int

    @property
    def num_rows(self) -> int:
        """Total rows across buckets + heavy (the concat result length)."""
        r = sum(int(b.nodes.shape[0]) for b in self.buckets)
        return r + int(self.heavy.nodes.shape[0])


from ..utils.intmath import next_pow2 as _next_pow2

# Width classes of the degree histogram that rides the contraction level's
# batched readback (ops/contraction.py stats layout): class i holds nodes of
# bucket width 2^(3+i); two trailing ints carry heavy row / slot counts.
WIDTH_CLASSES = tuple(1 << (3 + i) for i in range(10))


def degree_classes(deg, real):
    """Device (width-class index 0..9, heavy mask) per node via integer
    threshold counts — bit-identical to the host builder's float
    ``pow2ceil`` width computation for every degree (exact comparisons, no
    rounding)."""
    import jax.numpy as _jnp

    cls = _jnp.zeros_like(deg)
    for t in WIDTH_CLASSES[:-1]:
        cls = cls + (deg > t).astype(deg.dtype)
    heavy = real & (deg > WIDTH_CLASSES[-1])
    return cls, heavy


def device_deg_histogram(deg, real):
    """(12,) device ints: per-class real non-heavy node counts, heavy row
    count, heavy slot count.  Trace-safe (called inside the contraction
    kernel so the histogram ships in the level's single readback)."""
    cls, heavy = degree_classes(deg, real)
    ok = real & ~heavy
    seg = jnp.where(ok, cls, len(WIDTH_CLASSES)).astype(jnp.int32)
    hist = jax.ops.segment_sum(
        jnp.ones_like(seg), seg, num_segments=len(WIDTH_CLASSES) + 1
    )[:-1]
    hr = jnp.sum(heavy.astype(jnp.int32))
    hs = jnp.sum(jnp.where(heavy, deg, 0)).astype(jnp.int32)
    return jnp.concatenate(
        [hist.astype(deg.dtype), jnp.stack([hr, hs]).astype(deg.dtype)]
    )


def host_deg_histogram(row_ptr: np.ndarray, n: int) -> np.ndarray:
    """Host twin of :func:`device_deg_histogram` for graphs built from
    numpy (the finest level) — no device readback needed."""
    deg = np.diff(np.asarray(row_ptr)[: n + 1]).astype(np.int64)
    heavy = deg > WIDTH_CLASSES[-1]
    cls = np.zeros(n, dtype=np.int64)
    for t in WIDTH_CLASSES[:-1]:
        cls += deg > t
    counts = np.bincount(cls[~heavy], minlength=len(WIDTH_CLASSES))
    return np.concatenate(
        [counts[: len(WIDTH_CLASSES)],
         [int(heavy.sum()), int(deg[heavy].sum())]]
    ).astype(np.int64)


def node_width_plan(
    deg: np.ndarray,
    *,
    min_width: int = MIN_WIDTH,
    max_width: int = MAX_WIDTH,
    min_rows: int = MIN_ROWS,
):
    """(per-node bucket width, heavy mask) — the host-side bucket plan.

    Per-node width = next power of two >= degree, clamped; then sparse
    width classes merge upward so small graphs use few kernel shapes.  An
    undersized class merges into the next *naturally occupied* class, so
    the cascade ends at next_pow2(max degree) — never at max_width — and a
    coarse graph cannot be inflated past its own degree range.

    Shared by :func:`build_bucketed_view` and the compressed layout builder
    (graph/device_compressed.py), whose bit-identity contract requires the
    two plans to be the SAME function — do not fork this logic.
    """
    deg = np.asarray(deg, dtype=np.int64)
    width = np.maximum(
        min_width, 2 ** np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64)
    )
    heavy_mask = deg > max_width
    width = np.minimum(width, max_width)
    natural = set(int(x) for x in np.unique(width[~heavy_mask]))
    for w in sorted(natural)[:-1]:
        sel = (~heavy_mask) & (width == w)
        cnt = int(sel.sum())
        if 0 < cnt < min_rows:
            bigger = min(x for x in natural if x > w)
            width[sel] = bigger
    return width, heavy_mask


def build_bucketed_view(
    row_ptr: np.ndarray,
    col_idx: np.ndarray,
    edge_w: np.ndarray,
    n: int,
    anchor: int,
    *,
    min_width: int = MIN_WIDTH,
    max_width: int = MAX_WIDTH,
    min_rows: int = MIN_ROWS,
) -> BucketedView:
    rp = np.asarray(row_ptr)
    col = np.asarray(col_idx)
    ew = np.asarray(edge_w)
    idt = col.dtype
    m = col.shape[0]
    deg = np.diff(rp[: n + 1]).astype(np.int64)

    width, heavy_mask = node_width_plan(
        deg, min_width=min_width, max_width=max_width, min_rows=min_rows
    )

    buckets = []
    offsets = np.zeros(n, dtype=np.int64)
    offset = 0
    for w in sorted(int(x) for x in np.unique(width[~heavy_mask])):
        nodes = np.nonzero((~heavy_mask) & (width == w))[0]
        R = len(nodes)
        R_pad = _next_pow2(R, 8)
        slot = np.arange(w, dtype=np.int64)
        idx = rp[nodes][:, None] + slot[None, :]
        valid = slot[None, :] < deg[nodes][:, None]
        safe = np.minimum(idx, max(m - 1, 0))
        cols_b = np.where(valid, col[safe] if m else 0, nodes[:, None]).astype(idt)
        wgts_b = np.where(valid, ew[safe] if m else 0, 0).astype(idt)
        nodes_b = np.full(R_pad, anchor, dtype=idt)
        nodes_b[:R] = nodes
        cols_full = np.full((R_pad, w), anchor, dtype=idt)
        cols_full[:R] = cols_b
        wgts_full = np.zeros((R_pad, w), dtype=idt)
        wgts_full[:R] = wgts_b
        buckets.append(
            Bucket(jnp.asarray(nodes_b), jnp.asarray(cols_full), jnp.asarray(wgts_full))
        )
        offsets[nodes] = offset + np.arange(R)
        offset += R_pad

    # Heavy part: flat slots of all heavy rows, padded to a power of two.
    hn = np.nonzero(heavy_mask)[0]
    Hr = len(hn)
    if Hr:
        hdeg = deg[hn]
        Hs = int(hdeg.sum())
        Hr_pad = _next_pow2(Hr + 1, 8)  # strictly > Hr so the last row is a pad
        Hs_pad = _next_pow2(Hs, 8)
        hrow = np.repeat(np.arange(Hr, dtype=idt), hdeg)
        starts = rp[hn]
        base = np.repeat(starts - np.concatenate([[0], np.cumsum(hdeg)[:-1]]), hdeg)
        hslots = base + np.arange(Hs, dtype=np.int64)
        hcols = np.full(Hs_pad, anchor, dtype=idt)
        hw = np.zeros(Hs_pad, dtype=idt)
        hrow_full = np.full(Hs_pad, Hr_pad - 1, dtype=idt)
        hcols[:Hs] = col[hslots]
        hw[:Hs] = ew[hslots]
        hrow_full[:Hs] = hrow
        hnodes = np.full(Hr_pad, anchor, dtype=idt)
        hnodes[:Hr] = hn
        heavy = HeavyPart(
            jnp.asarray(hnodes), jnp.asarray(hrow_full), jnp.asarray(hcols), jnp.asarray(hw)
        )
        offsets[hn] = offset + np.arange(Hr)
    else:
        heavy = HeavyPart(
            jnp.zeros(0, dtype=idt),
            jnp.zeros(0, dtype=idt),
            jnp.zeros(0, dtype=idt),
            jnp.zeros(0, dtype=idt),
        )

    return BucketedView(
        buckets=tuple(buckets),
        heavy=heavy,
        gather_idx=jnp.asarray(offsets.astype(idt)),
        n=n,
    )


# ---------------------------------------------------------------------------
# Device-side builder: the layout is computed with jitted gathers on the
# padded (shape-ladder) arrays; the ONLY host-side input is the 12-int degree
# histogram, which for coarse graphs rides the contraction level's single
# batched readback — so a coarsening level performs zero bulk device->host
# transfers for layout construction.  Bit-identical to the host builder
# (same class structure, same ascending node order, same pad conventions;
# asserted in tests/test_bucketed.py).
# ---------------------------------------------------------------------------


def _merge_plan(hist, min_rows: int):
    """Histogram twin of the host builder's width-class merge cascade.

    Returns (plan, merged_to): ``plan`` is [(width, R, R_pad)] ascending for
    every final occupied class; ``merged_to[i]`` is the final width of
    original class i (0 for empty classes)."""
    counts = {
        w: int(hist[i]) for i, w in enumerate(WIDTH_CLASSES) if int(hist[i]) > 0
    }
    natural = sorted(counts)
    groups = {w: [w] for w in natural}
    for w in natural[:-1]:
        cnt = counts.get(w, 0)
        if 0 < cnt < min_rows:
            bigger = min(x for x in natural if x > w)
            counts[bigger] = counts.get(bigger, 0) + cnt
            counts[w] = 0
            groups.setdefault(bigger, [bigger]).extend(groups.pop(w))
    merged_to = np.zeros(len(WIDTH_CLASSES), dtype=np.int32)
    plan = []
    for w in sorted(counts):
        if counts[w] <= 0:
            continue
        for member in groups.get(w, [w]):
            merged_to[WIDTH_CLASSES.index(member)] = w
        plan.append((w, counts[w], _next_pow2(counts[w], 8)))
    return plan, merged_to


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("w", "R_pad"), donate_argnums=(3,))
def _device_bucket(row_ptr, col, ew, gather_idx, n, merged_to, base, R, *,
                   w: int, R_pad: int):
    idt = col.dtype
    n_pad = row_ptr.shape[0] - 1
    m_pad = col.shape[0]
    anchor = n_pad - 1
    deg = row_ptr[1:] - row_ptr[:-1]
    real = jnp.arange(n_pad) < n
    cls, heavy = degree_classes(deg, real)
    mask = real & ~heavy & (merged_to[cls.astype(jnp.int32)] == w)
    nodes = jnp.nonzero(mask, size=R_pad, fill_value=anchor)[0].astype(idt)
    rows_ok = jnp.arange(R_pad) < R
    degn = jnp.where(rows_ok, deg[nodes], 0)
    slot = jnp.arange(w)
    idx = row_ptr[nodes][:, None] + slot[None, :]
    valid = slot[None, :] < degn[:, None]
    safe = jnp.minimum(idx, m_pad - 1)
    cols_b = jnp.where(valid, col[safe], nodes[:, None])
    wgts_b = jnp.where(valid, ew[safe], 0)
    rank = (jnp.cumsum(mask) - 1).astype(idt)
    gi = jnp.where(mask, base.astype(idt) + rank, gather_idx)
    return nodes, cols_b, wgts_b, gi


@_partial(jax.jit, static_argnames=("Hr_pad", "Hs_pad"), donate_argnums=(4,))
def _device_heavy(row_ptr, col, ew, edge_u, gather_idx, n, base, Hs, *,
                  Hr_pad: int, Hs_pad: int):
    idt = col.dtype
    n_pad = row_ptr.shape[0] - 1
    m_pad = col.shape[0]
    anchor = n_pad - 1
    deg = row_ptr[1:] - row_ptr[:-1]
    real = jnp.arange(n_pad) < n
    _, heavy = degree_classes(deg, real)
    hnodes = jnp.nonzero(heavy, size=Hr_pad, fill_value=anchor)[0].astype(idt)
    hrank = (jnp.cumsum(heavy) - 1).astype(idt)
    # Heavy CSR slots ascending == host's per-node slot enumeration (pad
    # edges belong to the anchor, which real-mask excludes from heavy).
    edge_sel = heavy[edge_u]
    hslots = jnp.nonzero(edge_sel, size=Hs_pad, fill_value=0)[0]
    slot_ok = jnp.arange(Hs_pad) < Hs
    safe = jnp.minimum(hslots, m_pad - 1)
    hcols = jnp.where(slot_ok, col[safe], anchor).astype(idt)
    hw = jnp.where(slot_ok, ew[safe], 0).astype(idt)
    hrow = jnp.where(
        slot_ok, hrank[edge_u[safe]], Hr_pad - 1
    ).astype(idt)
    gi = jnp.where(heavy, base.astype(idt) + hrank, gather_idx)
    return hnodes, hrow, hcols, hw, gi


def build_bucketed_view_device(pv, n: int, hist) -> BucketedView:
    """Device-resident layout build over a :class:`PaddedView`.

    ``hist``: the 12-int degree histogram (see :func:`device_deg_histogram`)
    — the only host-side shape input.  Uses the default width configuration
    (the histogram classes are fixed); the host builder remains the
    configurable reference implementation."""
    plan, merged_to = _merge_plan(hist, MIN_ROWS)
    Hr, Hs = int(hist[len(WIDTH_CLASSES)]), int(hist[len(WIDTH_CLASSES) + 1])
    idt = pv.col_idx.dtype
    n_dev = jnp.asarray(n)
    m2 = jnp.asarray(merged_to)
    gather_idx = jnp.zeros(pv.n_pad, dtype=idt)
    buckets = []
    base = 0
    for w, R, R_pad in plan:
        nodes, cols_b, wgts_b, gather_idx = _device_bucket(
            pv.row_ptr, pv.col_idx, pv.edge_w, gather_idx, n_dev, m2,
            jnp.asarray(base), jnp.asarray(R), w=w, R_pad=R_pad,
        )
        buckets.append(Bucket(nodes, cols_b, wgts_b))
        base += R_pad
    if Hr:
        Hr_pad = _next_pow2(Hr + 1, 8)  # strictly > Hr: last row is a pad
        Hs_pad = _next_pow2(Hs, 8)
        hnodes, hrow, hcols, hw, gather_idx = _device_heavy(
            pv.row_ptr, pv.col_idx, pv.edge_w, pv.edge_u, gather_idx, n_dev,
            jnp.asarray(base), jnp.asarray(Hs), Hr_pad=Hr_pad, Hs_pad=Hs_pad,
        )
        heavy = HeavyPart(hnodes, hrow, hcols, hw)
    else:
        z = jnp.zeros(0, dtype=idt)
        heavy = HeavyPart(z, z, z, z)
    return BucketedView(
        buckets=tuple(buckets), heavy=heavy, gather_idx=gather_idx[:n], n=n
    )
