"""Device-resident compressed graph view — the TeraPart *compute* tier.

Reference: ``kaminpar-shm/datastructures/compressed_graph.h:409`` — the
reference's kernels iterate neighborhoods straight off the compressed
stream (``adjacent_nodes`` decodes varint gaps in-loop), so the dense CSR
never exists at the finest levels.  Our fixed-bit-width gap encoding
(graph/compressed.py) was designed for exactly this on TPU: decoding one
edge is ONE word-gather (two words when the gap straddles a word boundary)
plus shifts/masks — no data-dependent control flow — so the decode fuses
into the vectorized LP kernels.

This module owns the device half:

- :class:`DeviceCompressedView` — the packed word stream plus per-node
  ``(word_start, width, degree, node_w)`` resident in HBM, node arrays
  padded on the PR 1 geometric shape ladder (``n_pad`` matches what the
  dense ``PaddedView`` of the same graph would use, so labels / LP states
  share kernel shapes with the dense path) and the word stream padded on
  its own bucket dimension.  Non-uniform edge weights stay an uncompressed
  (m-sized) side stream, exactly like the reference's weighted graphs —
  the structural arrays (col_idx + edge_u + the bucketed neighbor
  matrices, 2/3 of the dense bytes) are still never materialized.
- a *compressed bucketed layout* mirroring graph/bucketed.py: nodes are
  grouped into the identical degree buckets (same merge cascade, same
  ascending order, same ``R_pad``/``gather_idx``), but each bucket row
  stores only ``(word_start, width, degree, edge_start)`` — the (R, w)
  neighbor matrix is materialized *inside* the consuming kernel by
  :func:`decode_rows`.  Heavy rows (degree > MAX_WIDTH) stay dense (they
  are rare and already take the flat edge-parallel path, mirroring the
  reference's two-phase LP split).
- in-trace decode helpers shared by the XLA oracle twin (ops/lp.py), the
  fused Pallas rate kernel (ops/pallas_lp.py), and the contraction /
  re-materialization paths (:func:`decode_flat_padded`).

Envelope: the 32-bit build with LP clustering (v-cycle community
restriction needs per-edge masking the stream does not carry; HEM walks
matchings host-side).  ``GraphCompressionContext.device_decode`` gates the
routing with the dense path as fallback (see :func:`resolve_device_decode`).

Bit-identity contract (asserted in tests/test_device_compressed.py): the
decoded bucket matrices equal the dense bucketed view of the decompressed
graph bit for bit — same cols, weights, pad conventions, gather_idx — so
every downstream kernel (rating, auction, commit) is byte-compatible and
``device_decode=finest`` partitions are identical to the dense path.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.intmath import next_pow2, next_shape_bucket
from .bucketed import HeavyPart
from .compressed import CompressedGraph


class CompressedStream(NamedTuple):
    """The device-resident byte streams: packed gap words plus the
    (uncompressed) edge-weight side stream.  ``edge_w`` is a (1,) zero
    dummy when the graph's weights are uniform all-1 — its *shape* is the
    trace-time weighted/unweighted switch, so no extra static argument
    threads through the kernel entry points."""

    words: jax.Array  # (W_pad,) uint32 packed zig-zag gaps
    edge_w: jax.Array  # (m_pad,) weights in decode order, or (1,) dummy

    @property
    def weighted(self) -> bool:
        return int(self.edge_w.shape[0]) > 1


class CompressedBucket(NamedTuple):
    """One degree bucket of the compressed layout: per-row decode metadata
    instead of the dense (R, w) neighbor matrix.  ``slot`` is a (w,) iota
    whose *shape* carries the bucket width into jitted consumers (its
    contents are never read)."""

    nodes: jax.Array  # (R_pad,) node id per row (pad rows -> anchor)
    wstart: jax.Array  # (R_pad,) first word of the row's gap stream
    width: jax.Array  # (R_pad,) bits per gap (pad rows -> 1)
    deg: jax.Array  # (R_pad,) degree (pad rows -> 0)
    estart: jax.Array  # (R_pad,) first edge slot (weight-stream gather base)
    slot: jax.Array  # (w,) static width carrier


# -- in-trace decode --------------------------------------------------------


def _funnel_unpack(words, w0, bit_in_word, wd):
    """Extract the ``wd``-bit zig-zag value starting at ``bit_in_word`` of
    word ``w0`` and return the signed gap — the per-edge shift/mask core.
    32-bit only (no uint64), so the math lowers identically with and
    without jax x64.  Shared with the dist tier's per-shard in-trace
    decode (dist/device_compressed.decode_shard_adjacency, round 15) —
    any change here must keep the signed shard-relative-gap case exact."""
    s0 = jnp.clip(w0, 0, words.shape[0] - 2)
    sh = bit_in_word.astype(jnp.uint32)
    lo = words[s0]
    hi = words[s0 + 1]
    lo_part = jnp.right_shift(lo, sh)
    hi_part = jnp.where(
        sh == jnp.uint32(0),
        jnp.uint32(0),
        jnp.left_shift(hi, (jnp.uint32(32) - sh) & jnp.uint32(31)),
    )
    mask = jnp.right_shift(
        jnp.uint32(0xFFFFFFFF), jnp.uint32(32) - wd.astype(jnp.uint32)
    )
    z = (lo_part | hi_part) & mask
    return jnp.right_shift(z, jnp.uint32(1)).astype(jnp.int32) ^ -(
        (z & jnp.uint32(1)).astype(jnp.int32)
    )


def decode_rows(stream: CompressedStream, nodes, wstart, width, deg, estart,
                w: int, wdtype):
    """Materialize the (R, w) neighbor matrix of one bucket from the packed
    word stream — pure jnp, traced inside the consuming jit / Pallas kernel.

    Per slot: one gather of two consecutive words + shift/mask (the gap
    straddles at most one word boundary because widths are <= 32), zig-zag
    decode, then a row cumsum turns gaps into absolute neighbor ids (the
    first gap is relative to the node id).  Weights come from the
    uncompressed side stream (one more gather) or are the constant 1.  Pad
    slots reproduce the dense bucket conventions exactly: ``col = the
    row's own node id`` with weight 0 (pad rows decode to all-anchor rows).
    """
    R = nodes.shape[0]
    slot = jax.lax.broadcasted_iota(jnp.int32, (R, w), 1)
    wd = width[:, None].astype(jnp.int32)
    bit = slot * wd
    w0 = wstart[:, None].astype(jnp.int32) + (bit >> 5)
    gap = _funnel_unpack(stream.words, w0, bit & 31, wd)
    valid = slot < deg[:, None]
    base = jnp.where(slot == 0, nodes.astype(jnp.int32)[:, None], 0)
    vals = jnp.where(valid, base + gap, 0)
    cols = jnp.cumsum(vals, axis=1)
    cols = jnp.where(valid, cols, nodes.astype(jnp.int32)[:, None]).astype(
        nodes.dtype
    )
    if stream.weighted:
        eidx = jnp.clip(
            estart[:, None].astype(jnp.int32) + slot,
            0, stream.edge_w.shape[0] - 1,
        )
        wgts = jnp.where(valid, stream.edge_w[eidx], 0).astype(wdtype)
    else:
        wgts = valid.astype(wdtype)
    return cols, wgts


def decode_bucket(stream: CompressedStream, cb: CompressedBucket, wdtype):
    """(cols, wgts) of one :class:`CompressedBucket` (see decode_rows)."""
    return decode_rows(
        stream, cb.nodes, cb.wstart, cb.width, cb.deg, cb.estart,
        int(cb.slot.shape[0]), wdtype,
    )


def decode_flat_padded(stream: CompressedStream, wstart, width, deg, *,
                       m_pad: int):
    """Flat in-trace decode to PaddedView-convention arrays.

    Returns ``(row_ptr, col_idx, edge_w, edge_u)`` padded exactly like the
    dense ``CSRGraph.padded()`` of the decompressed graph: pad edges are
    weight-0 anchor self-loops, pad rows are empty except the anchor (the
    last node), whose row_ptr entry closes at ``m_pad``.  Used by the
    compressed contraction wrapper (the finest level's coarse graph is
    built without ever holding a resident dense CSR) and by the finest
    re-materialization at final uncoarsening (a device decode kernel, no
    host round trip).
    """
    idt = deg.dtype
    n_pad = deg.shape[0]
    rp = jnp.concatenate(
        [jnp.zeros(1, dtype=idt), jnp.cumsum(deg).astype(idt)]
    )
    m = rp[-1]
    # edge_u via the scatter-of-row-starts cumsum trick: rows with start <=
    # slot accumulate, so each slot lands on its owning row; all pad slots
    # (>= m) accumulate every trailing empty row and land on the anchor —
    # exactly the dense pad convention.
    marks = jnp.zeros(m_pad, dtype=jnp.int32).at[rp[:-1]].add(1, mode="drop")
    eu = (jnp.cumsum(marks) - 1).astype(idt)
    pos = jnp.arange(m_pad, dtype=jnp.int32) - rp[eu].astype(jnp.int32)
    wd = width[eu].astype(jnp.int32)
    bit = pos * wd
    w0 = wstart[eu].astype(jnp.int32) + (bit >> 5)
    gap = _funnel_unpack(stream.words, w0, bit & 31, wd)
    valid = jnp.arange(m_pad, dtype=jnp.int32) < m.astype(jnp.int32)
    firsts = pos == 0
    vals = jnp.where(
        valid, jnp.where(firsts, eu.astype(jnp.int32) + gap, gap), 0
    )
    c = jnp.cumsum(vals)
    row_base = jnp.concatenate([jnp.zeros(1, c.dtype), c])[rp[:-1]]
    col = c - row_base[eu]
    anchor = jnp.asarray(n_pad - 1, dtype=idt)
    col = jnp.where(valid, col.astype(idt), anchor)
    if stream.weighted:
        eidx = jnp.clip(
            jnp.arange(m_pad, dtype=jnp.int32), 0, stream.edge_w.shape[0] - 1
        )
        ew = jnp.where(valid, stream.edge_w[eidx], 0).astype(idt)
    else:
        ew = valid.astype(idt)
    eu = jnp.where(valid, eu, anchor)
    rp = rp.at[-1].set(jnp.asarray(m_pad, dtype=idt))
    return rp, col, ew, eu


_decode_flat_padded_jit = jax.jit(
    decode_flat_padded, static_argnames=("m_pad",)
)


# -- host-side heavy-row decode (view construction only) --------------------


def _decode_neighbors_host(
    cg: CompressedGraph, nodes: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Decode the concatenated (sorted-ascending) neighbor lists of the
    given nodes on host, plus their flat edge-slot indices (for the weight
    side stream) — used once at view build for the rare heavy rows."""
    deg_all = cg.degree.astype(np.int64)
    rp_all = np.concatenate([[0], np.cumsum(deg_all)])
    deg = deg_all[nodes]
    total = int(deg.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64)
    u_arr = np.repeat(nodes.astype(np.int64), deg)
    starts = np.concatenate([[0], np.cumsum(deg)[:-1]])
    pos = np.arange(total) - np.repeat(starts, deg)
    slots = np.repeat(rp_all[nodes], deg) + pos
    w = cg.width[u_arr].astype(np.int64)
    bit = pos * w
    word0 = cg.word_start[u_arr].astype(np.int64) + (bit >> 5)
    shift = bit & 31
    lo = cg.words[word0].astype(np.uint64)
    hi = cg.words[np.minimum(word0 + 1, len(cg.words) - 1)].astype(np.uint64)
    both = lo | (hi << np.uint64(32))
    mask = (np.uint64(1) << w.astype(np.uint64)) - np.uint64(1)
    z = (both >> shift.astype(np.uint64)) & mask
    gaps = (z.astype(np.int64) >> 1) ^ -(z.astype(np.int64) & 1)
    firsts = pos == 0
    vals = np.where(firsts, u_arr + gaps, gaps)
    c = np.cumsum(vals)
    c_ext = np.concatenate([np.zeros(1, c.dtype), c])
    return c - np.repeat(c_ext[starts], deg), slots


# -- the view ---------------------------------------------------------------


class DeviceCompressedView:
    """Device-resident compressed graph + compressed bucketed layout.

    Resident arrays (everything the finest-level LP pass touches): the
    :class:`CompressedStream` (word-stream bucket + the edge-weight side
    stream when non-uniform), per-node ``word_start/width/degree/node_w``
    (node ladder ``n_pad`` — the same bucket the dense PaddedView would
    use, so LP states share kernel shapes), the per-bucket row metadata,
    the dense heavy part, and ``gather_idx``.  The structural m-sized
    arrays (col_idx, edge_u, the bucketed neighbor matrices) exist only as
    kernel transients.
    """

    def __init__(self, cg: CompressedGraph, *, layout_mode: Optional[str] = None):
        from .csr import _next_bucket

        self._cg = cg
        self.n = int(cg.n)
        self.m = int(cg.m)
        self.n_pad = _next_bucket(self.n)
        self.m_pad = _next_bucket(self.m)
        self.layout_mode = layout_mode
        idt = np.int32

        deg = cg.degree.astype(np.int64)
        erp = np.concatenate([[0], np.cumsum(deg)])  # decode-order row_ptr
        node_w = np.asarray(cg.node_w).astype(idt)
        wstart = cg.word_start[: self.n].astype(np.int64)
        width = cg.width.astype(np.int64)

        # Word stream: its own shape-bucket dimension (strictly > len so the
        # straddle read at +1 stays in bounds even at the last real word).
        w_bucket = next_shape_bucket(len(cg.words) + 1, 256)
        words_pad = np.zeros(w_bucket, dtype=np.uint32)
        words_pad[: len(cg.words)] = cg.words
        if cg.edge_w is None:
            ew_pad = np.zeros(1, dtype=idt)
        else:
            ew_pad = np.zeros(self.m_pad, dtype=idt)
            ew_pad[: self.m] = np.asarray(cg.edge_w, dtype=idt)
        self.stream = CompressedStream(jnp.asarray(words_pad), jnp.asarray(ew_pad))

        n_fill = self.n_pad - self.n
        self.node_w_pad = jnp.asarray(
            np.concatenate([node_w, np.zeros(n_fill, dtype=idt)])
        )
        self.degree_pad = jnp.asarray(
            np.concatenate([deg.astype(idt), np.zeros(n_fill, dtype=idt)])
        )
        self.wstart_pad = jnp.asarray(
            np.concatenate([wstart.astype(idt), np.zeros(n_fill, dtype=idt)])
        )
        self.width_pad = jnp.asarray(
            np.concatenate([width.astype(idt), np.ones(n_fill, dtype=idt)])
        )

        self.buckets, self.heavy, self.gather_idx = self._build_buckets(
            cg, deg, erp, wstart, width, idt
        )
        self._row_ptr = None
        self._total_node_weight = int(node_w.astype(np.int64).sum())
        self._max_node_weight = int(node_w.max(initial=0))
        self._total_edge_weight = (
            self.m if cg.edge_w is None
            else int(np.asarray(cg.edge_w).astype(np.int64).sum())
        )
        from ..utils import compile_stats

        compile_stats.record(
            "compressed_bucket", statics=(self.n_pad, int(w_bucket))
        )

    @property
    def anchor(self) -> int:
        return self.n_pad - 1

    @property
    def total_node_weight(self) -> int:
        return self._total_node_weight

    @property
    def max_node_weight(self) -> int:
        return self._max_node_weight

    def _build_buckets(self, cg, deg, erp, wstart, width, idt):
        """The dense host builder's exact bucket structure (same width
        classes, same merge cascade — literally the shared
        :func:`~kaminpar_tpu.graph.bucketed.node_width_plan` — same
        ascending node order, same ``R_pad`` and ``gather_idx``) with
        per-row decode metadata instead of materialized neighbor
        matrices."""
        from .bucketed import node_width_plan

        n = self.n
        anchor = self.anchor
        bwidth, heavy_mask = node_width_plan(deg)

        buckets = []
        offsets = np.zeros(n, dtype=np.int64)
        offset = 0
        for w in sorted(int(x) for x in np.unique(bwidth[~heavy_mask])):
            nodes = np.nonzero((~heavy_mask) & (bwidth == w))[0]
            R = len(nodes)
            R_pad = next_pow2(R, 8)
            nodes_b = np.full(R_pad, anchor, dtype=idt)
            ws_b = np.zeros(R_pad, dtype=idt)
            wd_b = np.ones(R_pad, dtype=idt)
            dg_b = np.zeros(R_pad, dtype=idt)
            es_b = np.zeros(R_pad, dtype=idt)
            nodes_b[:R] = nodes
            ws_b[:R] = wstart[nodes]
            wd_b[:R] = width[nodes]
            dg_b[:R] = deg[nodes]
            es_b[:R] = erp[nodes]
            buckets.append(
                CompressedBucket(
                    jnp.asarray(nodes_b), jnp.asarray(ws_b),
                    jnp.asarray(wd_b), jnp.asarray(dg_b), jnp.asarray(es_b),
                    jnp.arange(w, dtype=jnp.int32),
                )
            )
            offsets[nodes] = offset + np.arange(R)
            offset += R_pad

        hn = np.nonzero(heavy_mask)[0]
        Hr = len(hn)
        if Hr:
            hdeg = deg[hn]
            Hs = int(hdeg.sum())
            Hr_pad = next_pow2(Hr + 1, 8)  # strictly > Hr: last row is a pad
            Hs_pad = next_pow2(Hs, 8)
            hcols = np.full(Hs_pad, anchor, dtype=idt)
            hw = np.zeros(Hs_pad, dtype=idt)
            hrow_full = np.full(Hs_pad, Hr_pad - 1, dtype=idt)
            cols, slots = _decode_neighbors_host(cg, hn)
            hcols[:Hs] = cols
            hw[:Hs] = 1 if cg.edge_w is None else cg.edge_w[slots]
            hrow_full[:Hs] = np.repeat(np.arange(Hr, dtype=idt), hdeg)
            hnodes = np.full(Hr_pad, anchor, dtype=idt)
            hnodes[:Hr] = hn
            heavy = HeavyPart(
                jnp.asarray(hnodes), jnp.asarray(hrow_full),
                jnp.asarray(hcols), jnp.asarray(hw),
            )
            offsets[hn] = offset + np.arange(Hr)
        else:
            z = jnp.zeros(0, dtype=idt)
            heavy = HeavyPart(z, z, z, z)
        return tuple(buckets), heavy, jnp.asarray(offsets.astype(idt))

    def row_ptr_like(self):
        """(n_pad + 1,) row-pointer twin of the dense PaddedView's (cached
        device array; feeds ``lp.cluster_isolated_nodes`` unchanged)."""
        if self._row_ptr is None:
            idt = self.degree_pad.dtype
            rp = jnp.concatenate(
                [jnp.zeros(1, dtype=idt), jnp.cumsum(self.degree_pad)]
            )
            self._row_ptr = rp.at[-1].set(
                jnp.asarray(self.m_pad, dtype=idt)
            )
        return self._row_ptr

    # -- memory accounting (bench compress_ab) -----------------------------

    def resident_bytes(self) -> int:
        """Device-resident bytes of the compressed adjacency tier (the
        steady-state finest-level footprint under device decode)."""
        b = self.stream.words.nbytes + self.stream.edge_w.nbytes
        for arr in (
            self.node_w_pad, self.degree_pad, self.wstart_pad,
            self.width_pad, self.gather_idx,
        ):
            b += arr.nbytes
        for cb in self.buckets:
            b += cb.nodes.nbytes + cb.wstart.nbytes + cb.width.nbytes
            b += cb.deg.nbytes + cb.estart.nbytes
        for arr in self.heavy:
            b += arr.nbytes
        return b

    def dense_resident_bytes(self) -> int:
        """Padded dense-CSR footprint of the same level (what the dense
        path keeps resident: row_ptr/col/edge_w/edge_u/node_w on the shape
        ladder, plus the dense bucketed layout's neighbor matrices)."""
        itemsize = 4
        csr = (self.n_pad + 1 + self.n_pad + 3 * self.m_pad) * itemsize
        slots = 0
        for cb in self.buckets:
            slots += int(cb.nodes.shape[0]) * int(cb.slot.shape[0])
        bucketed = (2 * slots + self.n_pad) * itemsize  # cols + wgts + gather
        bucketed += sum(int(a.shape[0]) for a in self.heavy) * itemsize
        return csr + bucketed

    # -- finest re-materialization (device decode, no host round trip) -----

    def materialize_csr(self):
        """Decode the full CSR into device arrays (ONE jit dispatch, zero
        blocking transfers — every scalar a later phase needs is seeded
        from host-side compressed metadata).  The returned graph carries
        ``_compressed_view = self`` so the finest-level LP refinement pass
        routes through the decode-fused kernels."""
        from .bucketed import host_deg_histogram
        from .csr import CSRGraph, PaddedView

        rp, col, ew, eu = _decode_flat_padded_jit(
            self.stream, self.wstart_pad, self.width_pad, self.degree_pad,
            m_pad=self.m_pad,
        )
        g = CSRGraph(
            rp[: self.n + 1], col[: self.m], self.node_w_pad[: self.n],
            ew[: self.m], edge_u=eu[: self.m],
        )
        g._padded = PaddedView(rp, col, self.node_w_pad, ew, eu, self.n, self.m)
        from ..utils import compile_stats

        compile_stats.record("padded_bucket", statics=(self.n_pad, self.m_pad))
        rp_host = np.zeros(self.n + 1, dtype=np.int64)
        np.cumsum(self._cg.degree.astype(np.int64), out=rp_host[1:])
        g._deg_hist = host_deg_histogram(rp_host, self.n)
        g._total_node_weight = self._total_node_weight
        g._max_node_weight = self._max_node_weight
        g._total_edge_weight = self._total_edge_weight
        g._layout_mode = self.layout_mode
        g._compressed_view = self
        return g


# -- routing ----------------------------------------------------------------


def resolve_device_decode(compression_ctx) -> str:
    """Map ``GraphCompressionContext.device_decode`` to a concrete mode
    ("off" | "finest"); ``KAMINPAR_TPU_DEVICE_DECODE`` overrides."""
    mode = os.environ.get("KAMINPAR_TPU_DEVICE_DECODE", "") or getattr(
        compression_ctx, "device_decode", "off"
    )
    if mode not in ("off", "finest", "auto"):
        raise ValueError(
            f"device_decode must be 'off', 'finest' or 'auto', got {mode!r}"
        )
    return "finest" if mode == "auto" else mode


def device_decode_eligible(ctx, cg: CompressedGraph, communities=None) -> Tuple[bool, str]:
    """(eligible, reason) for routing the finest level through the device
    view.  The envelope: 32-bit build, LP clustering, no v-cycle community
    restriction (community masking needs per-edge weight masking, which
    the compressed stream does not carry)."""
    from ..context import ClusteringAlgorithm

    if cg is None or cg.n == 0:
        return False, "empty graph"
    if ctx.use_64bit_ids:
        return False, "64-bit build"
    if ctx.coarsening.algorithm != ClusteringAlgorithm.LP:
        return False, f"clusterer {ctx.coarsening.algorithm.value}"
    if communities is not None:
        return False, "v-cycle community restriction"
    return True, ""


def build_device_view_if_eligible(ctx, cg: CompressedGraph, communities=None):
    """The deep partitioner's gate: a :class:`DeviceCompressedView` when the
    knob + envelope allow it, else None (dense fallback).  ``finest`` warns
    on fallback; ``auto`` falls back silently."""
    mode = resolve_device_decode(ctx.compression)
    if mode == "off":
        return None
    ok, reason = device_decode_eligible(ctx, cg, communities)
    if not ok:
        # Warn iff "finest" was what the caller *requested* — via the env
        # override or the ctx knob (an "auto" that resolved to finest falls
        # back silently; that is its contract).
        requested = os.environ.get(
            "KAMINPAR_TPU_DEVICE_DECODE", ""
        ) or getattr(ctx.compression, "device_decode", "off")
        if requested == "finest":
            from ..utils.logger import Logger

            Logger.warning(
                f"compression.device_decode=finest requested but {reason}; "
                "falling back to the dense decode path"
            )
        return None
    from ..resilience.breakers import global_registry

    reg = global_registry()
    breaker = reg.get("device_decode")
    if not breaker.allow():
        # Round 17: the decode-fused path failed its way past the breaker
        # threshold — run this level dense (bit-identical by the round-14
        # contract) instead of paying another doomed build; the half-open
        # probe after the cooldown re-admits the compressed path.
        reg.record_demotion("device_decode", "circuit breaker open")
        return None
    try:
        from ..resilience.faults import maybe_inject

        maybe_inject("execute", site="device_decode")
        view = DeviceCompressedView(
            cg, layout_mode=ctx.parallel.device_layout_build
        )
    except Exception as exc:  # noqa: BLE001 — the dense path is the
        # bit-identical fallback for every view-build failure class
        from ..resilience.errors import classify

        err = classify(exc, site="device_decode")
        breaker.record_failure()
        reg.record_demotion("device_decode", err.failure_class)
        return None
    if breaker.record_success():
        reg.record_restoration("device_decode")
    return view
