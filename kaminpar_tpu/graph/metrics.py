"""Partition quality metrics as jitted segment reductions.

Reference: ``kaminpar-shm/metrics.{h,cc}`` — ``edge_cut`` (metrics.h:19),
``imbalance``, ``total_overload``, ``is_feasible`` (metrics.h:19-60).  On TPU
the edge cut is a single masked reduction over the edge list and block weights
are one ``segment_sum`` — these are the "trivially TPU-native" metrics of
SURVEY §7 stage 1.  All kernels run on the graph's shape-bucketed
:class:`PaddedView` (weight-0 padding is inert) so they compile once per
bucket, not once per hierarchy level.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .csr import CSRGraph


def _pad_partition(graph: CSRGraph, partition):
    pv = graph.padded()
    return pv, pv.pad_node_array(jnp.asarray(partition), 0)


@partial(jax.jit, static_argnames=("k",))
def _block_weights(labels, node_w, k: int):
    return jax.ops.segment_sum(node_w, labels, num_segments=k)


def block_weights(graph: CSRGraph, partition, k: int):
    """Weight of every block (reference: PartitionedGraph::block_weights)."""
    pv, part = _pad_partition(graph, partition)
    return _block_weights(part, pv.node_w, k)


@jax.jit
def _edge_cut(edge_u, col_idx, edge_w, labels):
    cut = labels[edge_u] != labels[col_idx]
    return jnp.sum(jnp.where(cut, edge_w, 0)) // 2


def edge_cut(graph: CSRGraph, partition) -> int:
    """Total weight of cut edges (each undirected edge counted once).
    Reference: ``metrics::edge_cut`` (metrics.cc)."""
    pv, part = _pad_partition(graph, partition)
    return int(_edge_cut(pv.edge_u, pv.col_idx, pv.edge_w, part))


def edge_cut_device(pv, padded_labels):
    """Device edge-cut scalar over a :class:`PaddedView` (no readback —
    telemetry probes pack this into an existing pull)."""
    return _edge_cut(pv.edge_u, pv.col_idx, pv.edge_w, padded_labels)


def quality_scalars_device(pv, padded_labels, k: int):
    """Device ``(cut, max_block_weight)`` pair for the per-level quality
    probes (telemetry/probes.py).  Both stay on device so they can ride an
    existing batched readback instead of costing their own transfers."""
    cut = _edge_cut(pv.edge_u, pv.col_idx, pv.edge_w, padded_labels)
    bw = _block_weights(padded_labels, pv.node_w, int(k))
    return cut, jnp.max(bw)


def imbalance(graph: CSRGraph, partition, k: int) -> float:
    """max_b w(b) / ceil(W/k) - 1 (reference: ``metrics::imbalance``)."""
    bw = np.asarray(block_weights(graph, partition, k))
    perfect = -(graph.total_node_weight // -k)  # ceil(W/k), as in the reference
    return float(bw.max() / perfect - 1.0) if perfect > 0 else 0.0


def total_overload(graph: CSRGraph, partition, k: int, max_block_weights) -> int:
    """Sum of overweight above the per-block limits (metrics.h)."""
    bw = np.asarray(block_weights(graph, partition, k))
    return int(np.maximum(bw - np.asarray(max_block_weights, dtype=np.int64), 0).sum())


def is_feasible(graph: CSRGraph, partition, k: int, max_block_weights) -> bool:
    """All block weights within limits (reference: ``metrics::is_feasible``)."""
    return total_overload(graph, partition, k, max_block_weights) == 0


def total_underload(graph: CSRGraph, partition, k: int, min_block_weights) -> int:
    """Sum of weight missing below the per-block minimums (metrics.h)."""
    bw = np.asarray(block_weights(graph, partition, k))
    return int(np.maximum(np.asarray(min_block_weights, dtype=np.int64) - bw, 0).sum())


def is_min_feasible(graph: CSRGraph, partition, k: int, min_block_weights) -> bool:
    """All block weights at or above the minimums (reference:
    ``metrics::is_min_balanced``, metrics.h:74)."""
    return total_underload(graph, partition, k, min_block_weights) == 0
