"""CSR graph held in device memory.

TPU-native counterpart of the reference's ``CSRGraph``
(``kaminpar-shm/datastructures/csr_graph.h:35``): adjacency as four flat
arrays ``(row_ptr, col_idx, edge_w, node_w)`` in HBM, int32 indices by default
with an int64 mode mirroring the reference's 64-bit build switches
(CMakeLists.txt:71-79).  Each undirected edge is stored twice (forward +
backward), exactly like the reference / METIS convention.

Additions over the reference layout, both load-bearing for TPU kernels:

- ``edge_u``: the source endpoint of every CSR slot, precomputed once so the
  hot LP/contraction kernels are *edge-parallel* (flat ``m``-sized ops) rather
  than row-parallel — rows have power-law lengths and would defeat XLA tiling.
- all arrays have static shapes; variable-size results (coarse graphs) are
  produced by the contraction kernel with host-side compaction per level.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


from ..utils.intmath import next_shape_bucket


def _next_bucket(x: int, minimum: int = 256) -> int:
    """Next geometric shape bucket (strictly > x, reserving pad slots).

    Powers of sqrt(2) on n and m (utils/intmath.next_shape_bucket): every
    multilevel level — including the coarse graphs the cluster coarsener
    produces — pads onto this ladder, so a full v-cycle touches O(log n)
    distinct padded shapes while wasting at most ~41% slots per level
    (pure powers of two waste up to ~100%)."""
    return next_shape_bucket(x, minimum)


# Degree-bucketed layout construction backend: "host" (numpy over pulled
# CSR arrays — zero-copy on the CPU backend, a full-graph device->host
# round trip per hierarchy level on an accelerator), "device" (jitted
# gathers fed by the 12-int degree histogram that rides the contraction
# level's single batched readback — no bulk transfer), or "auto" (device
# on accelerator backends).  Owned per facade/engine by the active
# EngineRuntime (ParallelContext.device_layout_build); set_layout_build_mode
# sets the process default (offline entry points only — kptlint's
# runtime-isolation rule bans it from pipeline code), and
# KAMINPAR_TPU_LAYOUT_BUILD overrides everything.
_layout_build_mode = "auto"


def set_layout_build_mode(mode: str) -> None:
    if mode not in ("host", "device", "auto"):
        raise ValueError(
            f"layout build mode must be 'host', 'device' or 'auto', got {mode!r}"
        )
    global _layout_build_mode
    _layout_build_mode = mode


def resolve_layout_build_mode(override: Optional[str] = None) -> str:
    """Env kill switch > per-graph override (CSRGraph._layout_mode, pinned
    by the facade and inherited through contraction — two KaMinPar
    instances with different settings must not reconfigure each other's
    graphs) > the active EngineRuntime (context.current_runtime(), so two
    engines with different layout configs coexist in one process) >
    process default."""
    import os

    from ..context import current_runtime

    rt = current_runtime()
    mode = (
        os.environ.get("KAMINPAR_TPU_LAYOUT_BUILD", "")
        or override
        or (rt.layout_build if rt is not None else "")
        or _layout_build_mode
    )
    if mode == "auto":
        return "device" if jax.default_backend() != "cpu" else "host"
    return mode


class PaddedView(NamedTuple):
    """Shape-bucketed view of a CSRGraph for jitted kernels.

    All arrays are padded to power-of-2 buckets so that every multilevel
    level hits a small set of compile shapes (SURVEY §7 hard part (c)):
    - pad *nodes* have weight 0 and degree 0, except the last node (the
      "anchor"), which owns all pad edges;
    - pad *edges* are weight-0 self-loops on the anchor, so they contribute
      nothing to ratings, cuts, or contraction (self-loops are dropped).
    Kernels therefore need no real-size masking: zero weights make padding
    inert.  ``n``/``m`` are the real sizes; ``n_pad = len(row_ptr) - 1 > n``
    always holds, so the anchor is never a real node.
    """

    row_ptr: jax.Array
    col_idx: jax.Array
    node_w: jax.Array
    edge_w: jax.Array
    edge_u: jax.Array
    n: int
    m: int

    @property
    def n_pad(self) -> int:
        return int(self.row_ptr.shape[0]) - 1

    @property
    def m_pad(self) -> int:
        return int(self.col_idx.shape[0])

    @property
    def anchor(self) -> int:
        return self.n_pad - 1

    def pad_node_array(self, arr, fill):
        """Pad an (n,)-array to (n_pad,) with `fill`."""
        pad = self.n_pad - self.n
        return jnp.concatenate(
            [jnp.asarray(arr), jnp.full(pad, fill, dtype=jnp.asarray(arr).dtype)]
        )


class CSRGraph:
    """Immutable CSR graph; arrays may live on device or host (jnp/np)."""

    def __init__(
        self,
        row_ptr,
        col_idx,
        node_w=None,
        edge_w=None,
        *,
        sorted_by_degree: bool = False,
        edge_u=None,
    ):
        self.row_ptr = jnp.asarray(row_ptr)
        self.col_idx = jnp.asarray(col_idx)
        n = int(self.row_ptr.shape[0]) - 1
        m = int(self.col_idx.shape[0])
        idt = self.row_ptr.dtype
        self.node_w = (
            jnp.ones(n, dtype=idt) if node_w is None else jnp.asarray(node_w)
        )
        self.edge_w = (
            jnp.ones(m, dtype=idt) if edge_w is None else jnp.asarray(edge_w)
        )
        self.n = n
        self.m = m
        self.sorted_by_degree = sorted_by_degree
        # Host copy of row_ptr when construction started from numpy — lets
        # edge_u / the degree histogram come for free instead of via a pull.
        self._host_row_ptr = (
            np.asarray(row_ptr) if isinstance(row_ptr, np.ndarray) else None
        )
        # Source endpoint per CSR slot: edge_u[e] = u for e in [row_ptr[u], row_ptr[u+1]).
        # Callers sharing structure with another graph can pass its edge_u
        # (contraction passes the coarse sources it already has on device).
        self.edge_u = (
            _compute_edge_u(
                self.row_ptr if self._host_row_ptr is None else self._host_row_ptr,
                m,
            )
            if edge_u is None
            else jnp.asarray(edge_u)
        )
        self._total_node_weight: Optional[int] = None
        self._max_node_weight: Optional[int] = None
        self._total_edge_weight: Optional[int] = None
        self._padded: Optional[PaddedView] = None
        self._bucketed = None
        # (12,) host ints: per-width-class node counts + heavy row/slot
        # counts (ops/contraction.py stats layout).  Seeded by contraction
        # for coarse graphs so the device layout build needs no readback.
        self._deg_hist = None
        # Per-graph layout-build mode override (None = process default);
        # pinned by the owning facade, inherited by coarse/masked graphs.
        self._layout_mode: Optional[str] = None

    def padded(self) -> PaddedView:
        """Shape-bucketed view (cached); see :class:`PaddedView`."""
        if self._padded is None:
            idt = self.row_ptr.dtype
            n_pad = _next_bucket(self.n)
            m_pad = _next_bucket(self.m)
            n_fill = n_pad - self.n
            m_fill = m_pad - self.m
            row_ptr = jnp.concatenate(
                [
                    self.row_ptr,
                    jnp.full(n_fill - 1, self.m, dtype=idt),
                    jnp.full(1, m_pad, dtype=idt),
                ]
            )
            col_idx = jnp.concatenate(
                [self.col_idx, jnp.full(m_fill, n_pad - 1, dtype=idt)]
            )
            node_w = jnp.concatenate([self.node_w, jnp.zeros(n_fill, dtype=idt)])
            edge_w = jnp.concatenate([self.edge_w, jnp.zeros(m_fill, dtype=idt)])
            # All pad edges belong to the anchor (the pad rows before it are
            # empty), so the padded sources extend edge_u in place — no
            # host-side recomputation, no device->host transfer.
            edge_u = jnp.concatenate(
                [self.edge_u, jnp.full(m_fill, n_pad - 1, dtype=idt)]
            )
            from ..resilience.faults import maybe_inject
            from ..utils import compile_stats

            # Named "compile" injection point (round 17): a fresh padded
            # bucket is what triggers fresh XLA specializations — the
            # chaos harness arms compile-class faults here.
            maybe_inject("compile", site=f"padded_bucket:{n_pad}x{m_pad}")
            # Census of (n_pad, m_pad) shape buckets actually materialized —
            # the quantity the geometric ladder bounds to O(log n) per run.
            compile_stats.record("padded_bucket", statics=(n_pad, m_pad))
            self._padded = PaddedView(
                row_ptr, col_idx, node_w, edge_w, edge_u, self.n, self.m
            )
        return self._padded

    def bucketed(self):
        """Degree-bucketed adjacency view (cached); see graph/bucketed.py.
        Indexed against the PaddedView's node space (labels arrays are
        (n_pad,), pad cols point at the anchor).

        Built on device (gathers fed by the degree histogram, no bulk
        device->host transfer) or on host per the layout-build mode; the
        two builders produce bit-identical views (asserted in
        tests/test_bucketed.py)."""
        if self._bucketed is None:
            pv = self.padded()
            if resolve_layout_build_mode(self._layout_mode) == "device":
                from .bucketed import build_bucketed_view_device

                self._bucketed = build_bucketed_view_device(
                    pv, self.n, self.deg_histogram()
                )
            else:
                from ..utils import sync_stats
                from .bucketed import build_bucketed_view

                host_arrays = sync_stats.pull(
                    self.row_ptr, self.col_idx, self.edge_w
                )
                self._bucketed = build_bucketed_view(
                    *host_arrays, self.n, pv.anchor
                )
        return self._bucketed

    def deg_histogram(self):
        """(12,) host ints: width-class node counts + heavy row/slot counts
        (the device layout build's only host-side input).  Seeded by
        contraction for coarse graphs; otherwise derived from the host
        row_ptr when available, else via one 12-int readback."""
        if self._deg_hist is None:
            if self._host_row_ptr is not None:
                from .bucketed import host_deg_histogram

                self._deg_hist = host_deg_histogram(self._host_row_ptr, self.n)
            else:
                from ..utils import sync_stats
                from .bucketed import device_deg_histogram

                pv = self.padded()
                deg = pv.row_ptr[1:] - pv.row_ptr[:-1]
                real = jnp.arange(pv.n_pad) < pv.n
                self._deg_hist = sync_stats.pull(
                    jax.jit(device_deg_histogram)(deg, real)
                ).astype(int)
        return self._deg_hist

    # -- scalar properties (host) -----------------------------------------

    @property
    def total_node_weight(self) -> int:
        if self._total_node_weight is None:
            from ..utils import sync_stats

            self._total_node_weight = int(
                sync_stats.pull(self.node_w).astype(np.int64).sum()
            )
        return self._total_node_weight

    @property
    def max_node_weight(self) -> int:
        if self._max_node_weight is None:
            from ..utils import sync_stats

            self._max_node_weight = (
                int(sync_stats.pull(jnp.max(self.node_w))) if self.n > 0 else 0
            )
        return self._max_node_weight

    @property
    def total_edge_weight(self) -> int:
        if self._total_edge_weight is None:
            from ..utils import sync_stats

            self._total_edge_weight = int(
                sync_stats.pull(self.edge_w).astype(np.int64).sum()
            )
        return self._total_edge_weight

    def degrees(self):
        return self.row_ptr[1:] - self.row_ptr[:-1]

    def is_unweighted(self) -> bool:
        return bool(jnp.all(self.node_w == 1)) and bool(jnp.all(self.edge_w == 1))

    def has_uniform_edge_weights(self) -> bool:
        """All edge weights equal (device-side reduce; only a scalar reaches
        the host, as a counted pull).  Gates the weighted clustering mode
        (lp_clusterer.py)."""
        if self.m == 0:
            return True
        from ..utils import sync_stats

        return bool(
            sync_stats.pull(jnp.min(self.edge_w) == jnp.max(self.edge_w))
        )

    def device_put(self, device=None) -> "CSRGraph":
        g = CSRGraph.__new__(CSRGraph)
        for attr in ("row_ptr", "col_idx", "node_w", "edge_w", "edge_u"):
            setattr(g, attr, jax.device_put(getattr(self, attr), device))
        g.n, g.m = self.n, self.m
        g.sorted_by_degree = self.sorted_by_degree
        g._total_node_weight = self._total_node_weight
        g._max_node_weight = self._max_node_weight
        g._total_edge_weight = self._total_edge_weight
        g._padded = None
        g._bucketed = None
        g._deg_hist = self._deg_hist
        g._host_row_ptr = self._host_row_ptr
        g._layout_mode = self._layout_mode
        return g

    def __repr__(self):
        return f"CSRGraph(n={self.n}, m={self.m}, dtype={self.row_ptr.dtype})"


def _compute_edge_u(row_ptr, m: int):
    """edge_u[e] = source node of CSR slot e.

    Computed host-side with ``np.repeat`` — graph construction is host
    orchestration, and a device expression of this (scatter + max-scan) costs
    a fresh XLA compile per hierarchy-level shape for zero benefit.  Coarse
    graphs never reach here: contraction hands the sources it already has on
    device to the constructor.
    """
    if isinstance(row_ptr, np.ndarray):
        rp = row_ptr
    else:
        from ..utils import sync_stats

        rp = sync_stats.pull(row_ptr)
    dtype = rp.dtype
    if m == 0:
        return jnp.zeros(0, dtype=dtype)
    n = rp.shape[0] - 1
    deg = np.diff(rp)
    return jnp.asarray(np.repeat(np.arange(n, dtype=dtype), deg))


def validate_csr_input(
    row_ptr: np.ndarray,
    col_idx: np.ndarray,
    node_w: Optional[np.ndarray] = None,
    edge_w: Optional[np.ndarray] = None,
    *,
    use_64bit: bool = False,
) -> None:
    """Facade-boundary ingestion guard (round 17 satellite): reject
    malformed CSR input with a typed
    :class:`~kaminpar_tpu.resilience.errors.GraphValidationError` instead
    of letting a non-monotone row_ptr or an out-of-range column turn into
    downstream kernel garbage (a negative degree silently corrupts
    edge_u; an overflowing weight wraps inside int32 segment sums).

    Cheap vectorized O(n + m) numpy checks — structural only; the full
    symmetry sweep stays in :func:`validate` (the heavy assertion tier).
    """
    from ..resilience.errors import GraphValidationError

    def _reject(msg: str):
        raise GraphValidationError(f"rejected graph input: {msg}",
                                   site="csr_ingest")

    rp = np.asarray(row_ptr)
    col = np.asarray(col_idx)
    if rp.ndim != 1 or rp.size < 1:
        _reject(f"row_ptr must be 1-D with n+1 entries, got shape {rp.shape}")
    if col.ndim != 1:
        _reject(f"col_idx must be 1-D, got shape {col.shape}")
    if not np.issubdtype(rp.dtype, np.integer) or not np.issubdtype(
        col.dtype, np.integer
    ):
        _reject(
            f"row_ptr/col_idx must be integer arrays, got "
            f"{rp.dtype}/{col.dtype}"
        )
    n, m = rp.size - 1, col.size
    if rp[0] != 0:
        _reject(f"row_ptr[0] must be 0, got {int(rp[0])}")
    if int(rp[-1]) != m:
        _reject(
            f"row_ptr[-1] ({int(rp[-1])}) must equal len(col_idx) ({m})"
        )
    # Signed diff: on an unsigned row_ptr a descending step WRAPS instead
    # of going negative, and the exact malformed input this guard exists
    # for would pass.
    drp = np.diff(rp.astype(np.int64))
    if n > 0 and np.any(drp < 0):
        bad = int(np.argmax(drp < 0))
        _reject(
            f"row_ptr is non-monotone at node {bad} "
            f"({int(rp[bad])} -> {int(rp[bad + 1])})"
        )
    if m > 0:
        cmin, cmax = int(col.min()), int(col.max())
        if cmin < 0 or cmax >= n:
            _reject(
                f"col_idx out of range: [{cmin}, {cmax}] vs n={n}"
            )
    idt = np.int64 if use_64bit else np.int32
    id_max = np.iinfo(idt).max
    if m > id_max or n > id_max:
        _reject(
            f"n={n}/m={m} exceed the {np.dtype(idt).name} index space — "
            "build with use_64bit_ids"
        )
    for name, w, count in (("node", node_w, n), ("edge", edge_w, m)):
        if w is None:
            continue
        w = np.asarray(w)
        if w.shape != (count,):
            _reject(
                f"{name}_weights must have shape ({count},), got {w.shape}"
            )
        if not np.issubdtype(w.dtype, np.integer):
            # Float weights would be silently truncated by the index-typed
            # cast below the facade — a different weighted problem, not a
            # rounding detail.
            _reject(
                f"{name}_weights must be an integer array, got {w.dtype}"
            )
        if w.size and int(w.min()) < 0:
            _reject(
                f"negative {name} weight {int(w.min())} at index "
                f"{int(np.argmin(w))}"
            )
        # Totals drive block caps / cluster-weight limits as index-typed
        # device scalars: a total that wraps in the build's dtype corrupts
        # every balance decision downstream.  Tiered for scale: the
        # count*max bound clears healthy graphs with one reduction; only
        # when it is inconclusive is the total computed — int64 where
        # provably wrap-free, else an exact arbitrary-precision sum (an
        # int64 accumulator alone would itself wrap, leaving the check
        # dead for 64-bit builds).
        if w.size:
            wmax = int(w.max())
            if wmax > id_max:
                _reject(
                    f"{name} weight {wmax} overflows "
                    f"{np.dtype(idt).name} — build with use_64bit_ids"
                )
            if count * wmax > id_max:
                if count * wmax <= np.iinfo(np.int64).max:
                    total = int(w.astype(np.int64).sum())
                else:
                    total = int(np.add.reduce(w.astype(object)))
                if total > id_max:
                    _reject(
                        f"total {name} weight {total} overflows "
                        f"{np.dtype(idt).name} — build with use_64bit_ids"
                    )


def from_numpy_csr(
    row_ptr: np.ndarray,
    col_idx: np.ndarray,
    node_w: Optional[np.ndarray] = None,
    edge_w: Optional[np.ndarray] = None,
    *,
    use_64bit: bool = False,
    validate_input: bool = False,
) -> CSRGraph:
    if validate_input:
        validate_csr_input(
            row_ptr, col_idx, node_w, edge_w, use_64bit=use_64bit
        )
    idt = np.int64 if use_64bit else np.int32
    return CSRGraph(
        np.asarray(row_ptr, dtype=idt),
        np.asarray(col_idx, dtype=idt),
        None if node_w is None else np.asarray(node_w, dtype=idt),
        None if edge_w is None else np.asarray(edge_w, dtype=idt),
    )


def from_edge_list(
    n: int,
    edges: np.ndarray,
    edge_weights: Optional[np.ndarray] = None,
    node_weights: Optional[np.ndarray] = None,
    *,
    symmetrize: bool = True,
    dedup: bool = True,
    use_64bit: bool = False,
) -> CSRGraph:
    """Build a CSR graph from an (E, 2) undirected edge array (host-side).

    Removes self-loops; duplicate edges have their weights summed when
    ``dedup`` (matching the reference graph validator's expectations,
    kaminpar-shm/graphutils/graph_validator.cc).
    """
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    w = (
        np.ones(len(edges), dtype=np.int64)
        if edge_weights is None
        else np.asarray(edge_weights, dtype=np.int64)
    )
    mask = edges[:, 0] != edges[:, 1]
    edges, w = edges[mask], w[mask]
    if symmetrize:
        edges = np.concatenate([edges, edges[:, ::-1]], axis=0)
        w = np.concatenate([w, w])
    if dedup and len(edges):
        key = edges[:, 0] * n + edges[:, 1]
        order = np.argsort(key, kind="stable")
        key, edges, w = key[order], edges[order], w[order]
        first = np.ones(len(key), dtype=bool)
        first[1:] = key[1:] != key[:-1]
        seg = np.cumsum(first) - 1
        w = np.bincount(seg, weights=w, minlength=int(seg[-1]) + 1).astype(np.int64)
        edges = edges[first]
    deg = np.bincount(edges[:, 0], minlength=n)
    row_ptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(deg, out=row_ptr[1:])
    order = np.lexsort((edges[:, 1], edges[:, 0]))
    col_idx = edges[order, 1]
    edge_w = w[order]
    return from_numpy_csr(row_ptr, col_idx, node_weights, edge_w, use_64bit=use_64bit)


def validate(graph: CSRGraph) -> None:
    """Check structural invariants (reference: graphutils/graph_validator.cc):
    sorted row_ptr, in-range col_idx, no self loops, symmetric adjacency with
    matching weights.  Host-side; intended for tests, the debug flag, and the
    heavy assertion tier.  Raises ``ValueError`` (not bare asserts, which
    ``python -O`` would strip out from under the KASSERT ladder)."""

    def _check(cond, msg):
        if not cond:
            raise ValueError(f"invalid graph: {msg}")

    row_ptr = np.asarray(graph.row_ptr)
    col = np.asarray(graph.col_idx)
    ew = np.asarray(graph.edge_w)
    n, m = graph.n, graph.m
    _check(row_ptr[0] == 0 and row_ptr[-1] == m, "row_ptr range")
    _check(np.all(np.diff(row_ptr) >= 0), "row_ptr monotone")
    if m == 0:
        return
    _check(col.min() >= 0 and col.max() < n, "col_idx in range")
    u = np.asarray(graph.edge_u)
    _check(not np.any(u == col), "self loops present")
    fwd = {}
    for a, b, w in zip(u.tolist(), col.tolist(), ew.tolist()):
        fwd[(a, b)] = fwd.get((a, b), 0) + w
    for (a, b), w in fwd.items():
        _check(fwd.get((b, a)) == w, f"asymmetric edge {(a, b)}")


def rearrange_by_degree_buckets(graph: CSRGraph) -> Tuple[CSRGraph, np.ndarray]:
    """Reorder nodes into exponentially-spaced degree buckets.

    Reference: ``graph::rearrange_by_degree_buckets``
    (kaminpar-shm/graphutils/permutator.h:227, invoked at kaminpar.cc:376).
    Returns (reordered graph, old_to_new permutation) so callers can remap the
    output partition back (kaminpar.cc:434-446).  On TPU this layout is what
    lets per-bucket kernels run on near-uniform row lengths.
    """
    deg = np.asarray(graph.degrees())
    bucket = np.zeros(graph.n, dtype=np.int64)
    nz = deg > 0
    bucket[nz] = np.floor(np.log2(deg[nz])).astype(np.int64) + 1
    new_to_old = np.argsort(bucket, kind="stable")
    old_to_new = np.empty_like(new_to_old)
    old_to_new[new_to_old] = np.arange(graph.n)
    return permute_nodes(graph, old_to_new), old_to_new


def permute_nodes(graph: CSRGraph, old_to_new: np.ndarray) -> CSRGraph:
    """Apply a node permutation on host (used by rearrangement + tests)."""
    old_to_new = np.asarray(old_to_new)
    new_to_old = np.empty_like(old_to_new)
    new_to_old[old_to_new] = np.arange(graph.n)
    row_ptr = np.asarray(graph.row_ptr)
    col = np.asarray(graph.col_idx)
    ew = np.asarray(graph.edge_w)
    nw = np.asarray(graph.node_w)
    deg = np.diff(row_ptr)
    new_deg = deg[new_to_old]
    new_row_ptr = np.zeros(graph.n + 1, dtype=row_ptr.dtype)
    np.cumsum(new_deg, out=new_row_ptr[1:])
    # One vectorized lexsort over (new_u, new_v) rebuilds the adjacency: the
    # sort groups slots by new source row (matching new_row_ptr, which counts
    # the same degrees) with neighbor ids ascending within each row.
    u_new = old_to_new[np.asarray(graph.edge_u)]
    v_new = old_to_new[col]
    order = np.lexsort((v_new, u_new))
    return CSRGraph(
        new_row_ptr, v_new[order], nw[new_to_old], ew[order], sorted_by_degree=True
    )
