from . import generators, metrics
from .csr import (
    CSRGraph,
    from_edge_list,
    from_numpy_csr,
    permute_nodes,
    rearrange_by_degree_buckets,
    validate,
)
from .partitioned import PartitionedGraph

__all__ = [
    "CSRGraph",
    "PartitionedGraph",
    "from_edge_list",
    "from_numpy_csr",
    "permute_nodes",
    "rearrange_by_degree_buckets",
    "validate",
    "generators",
    "metrics",
]
