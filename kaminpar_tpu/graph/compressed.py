"""Compressed graph representation (TeraPart analog).

Reference: ``kaminpar-common/graph_compression/`` (varint + interval
encoded neighborhoods, ~2 941 LoC) and
``kaminpar-shm/datastructures/compressed_graph.h:409`` — the memory tier
that lets billion-edge graphs fit in RAM.

The reference's byte-aligned varint streams are hostile to TPU decoding
(data-dependent lengths serialize).  The TPU-native scheme keeps the same
information-theoretic win — neighborhood *gaps* are small — but packs them
at a **fixed bit width per node** chosen from the node's largest gap:

- neighbors sorted ascending; first stored as a signed delta from the
  node id (locality makes it small), the rest as consecutive gaps,
- per-node width w(u) = bits(max zig-zag gap); all gaps of u packed
  back-to-back into a shared uint32 word stream at word-aligned start,
- decoding is one gather of (up to two) words + shifts/masks per edge —
  fully vectorized, no data-dependent control flow, XLA/TPU friendly.

Edge weights, when not all-1, are stored uncompressed (the reference does
the same for its weighted streams).  ``decompress()`` reproduces the
original CSRGraph bit-exactly (neighbors re-sorted ascending).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .csr import CSRGraph


def _zigzag(x: np.ndarray) -> np.ndarray:
    return (x << 1) ^ (x >> 63)


def _unzigzag(z: np.ndarray) -> np.ndarray:
    return (z >> 1) ^ -(z & 1)


@dataclass
class CompressedGraph:
    n: int
    m: int
    words: np.ndarray  # uint32 packed gap stream
    word_start: np.ndarray  # (n+1,) uint32 word offset per node
    width: np.ndarray  # (n,) uint8 bits per gap
    degree: np.ndarray  # (n,) node degrees
    node_w: np.ndarray
    edge_w: object  # None when all-1, else (m,) aligned with decompressed order

    @property
    def total_node_weight(self) -> int:
        return int(self.node_w.sum())

    def memory_bytes(self) -> int:
        b = self.words.nbytes + self.word_start.nbytes + self.width.nbytes
        b += self.degree.nbytes + self.node_w.nbytes
        if self.edge_w is not None:
            b += self.edge_w.nbytes
        return b

    def uncompressed_bytes(self) -> int:
        """CSR(int32) footprint of the same graph."""
        b = 4 * (self.n + 1) + 4 * self.m + 4 * self.n
        if self.edge_w is not None:
            b += 4 * self.m
        return b

    def compression_ratio(self) -> float:
        return self.uncompressed_bytes() / max(self.memory_bytes(), 1)

    # -- decoding ----------------------------------------------------------

    def decompress(self) -> CSRGraph:
        """Rebuild the CSRGraph (vectorized; the same arithmetic runs under
        jit for on-device decoding)."""
        row_ptr, col, node_w, edge_w = self.decompress_arrays()
        return CSRGraph(row_ptr, col, node_w, edge_w)

    def decompress_arrays(self):
        """Decode to plain numpy (row_ptr, col, node_w, edge_w-or-None) —
        no CSRGraph wrapper, so no device transfer and no edge_u kernel.
        The distributed staging path (dist/compressed.py) depends on this
        staying host-only."""
        deg = self.degree.astype(np.int64)
        row_ptr = np.zeros(self.n + 1, dtype=np.int64)
        np.cumsum(deg, out=row_ptr[1:])
        m = int(row_ptr[-1])
        u_arr = np.repeat(np.arange(self.n), deg)
        pos = np.arange(m) - row_ptr[u_arr]  # gap index within the node

        w = self.width[u_arr].astype(np.int64)
        bit = pos * w
        word0 = self.word_start[u_arr].astype(np.int64) + (bit >> 5)
        shift = bit & 31
        lo = self.words[word0].astype(np.uint64)
        hi = self.words[np.minimum(word0 + 1, len(self.words) - 1)].astype(np.uint64)
        both = lo | (hi << np.uint64(32))
        mask = (np.uint64(1) << w.astype(np.uint64)) - np.uint64(1)
        z = (both >> shift.astype(np.uint64)) & mask
        gaps = _unzigzag(z.astype(np.int64))

        # first gap is relative to u; the rest accumulate
        firsts = pos == 0
        base = np.where(firsts, u_arr, 0)
        vals = base + gaps
        # segmented prefix sum: global cumsum minus the value just before
        # each row's start.  (An earlier max.accumulate trick silently
        # required non-negative columns; shard-relative columns in the
        # distributed compressed graph are signed.)
        c = np.cumsum(vals)
        c_ext = np.concatenate([np.zeros(1, c.dtype), c])
        col = c - np.repeat(c_ext[row_ptr[:-1]], deg)

        if m >= 2**31:
            raise ValueError("edge count exceeds int32; use the 64-bit path")
        return (
            row_ptr.astype(np.int32),
            col.astype(np.int32),
            np.asarray(self.node_w),
            None if self.edge_w is None else np.asarray(self.edge_w),
        )


def compress(graph) -> CompressedGraph:
    """Compress a CSRGraph (host numpy; one sort + vectorized packing)."""
    row_ptr = np.asarray(graph.row_ptr).astype(np.int64)
    col = np.asarray(graph.col_idx).astype(np.int64)
    n = graph.n
    deg = np.diff(row_ptr)
    u_arr = np.repeat(np.arange(n), deg)
    ew = np.asarray(graph.edge_w)

    # sort each neighborhood ascending (stable by (u, v)), keeping weights
    order = np.lexsort((col, u_arr))
    col = col[order]
    ew = ew[order]
    if bool((ew == 1).all()):
        ew_out = None
    else:
        if int(ew.max(initial=0)) >= 2**31:
            raise ValueError("edge weight exceeds int32; use the 64-bit path")
        ew_out = ew.astype(np.int32)

    # gaps: first neighbor relative to u (zig-zag for the sign), then
    # consecutive differences (non-negative, zig-zag is cheap anyway)
    firsts = np.zeros(len(col), dtype=bool)
    firsts[row_ptr[:-1][deg > 0]] = True
    prev = np.concatenate([[0], col[:-1]])
    gaps = np.where(firsts, col - u_arr, col - prev)
    z = _zigzag(gaps)

    # per-node width = bits of the largest zig-zag gap (min 1)
    width = np.ones(n, dtype=np.int64)
    if len(z):
        zmax = np.zeros(n, dtype=np.int64)
        np.maximum.at(zmax, u_arr, z)
        width = np.maximum(
            np.ceil(np.log2(np.maximum(zmax, 1) + 1)).astype(np.int64), 1
        )
    if int(width.max(initial=1)) > 32:
        raise ValueError(
            "neighborhood gap exceeds 32 bits (node ids >= 2^31); the "
            "compressed representation is 32-bit — partition with the "
            "uncompressed 64-bit path instead"
        )

    bits_per_node = width * deg
    words_per_node = (bits_per_node + 31) // 32
    word_start = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(words_per_node, out=word_start[1:])
    total_words = int(word_start[-1]) + 1  # +1 sentinel for straddle reads

    # scatter-pack: each gap contributes to one or two words
    w_e = width[u_arr]
    pos = np.arange(len(z)) - row_ptr[u_arr]
    bit = pos * w_e
    word0 = word_start[u_arr] + (bit >> 5)
    shift = bit & 31
    lo_part = (z << shift) & 0xFFFFFFFF
    hi_part = z >> np.maximum(32 - shift, 0)
    # hi_part only valid when the value straddles (shift + w > 32)
    straddle = shift + w_e > 32
    words = np.zeros(total_words, dtype=np.uint64)
    np.bitwise_or.at(words, word0, lo_part.astype(np.uint64))
    if straddle.any():
        np.bitwise_or.at(
            words, word0[straddle] + 1, hi_part[straddle].astype(np.uint64)
        )

    return CompressedGraph(
        n=n,
        m=int(deg.sum()),
        words=words.astype(np.uint32),
        word_start=word_start.astype(np.uint32),
        width=width.astype(np.uint8),
        degree=deg.astype(np.int32),
        node_w=np.asarray(graph.node_w).astype(np.int32),
        edge_w=ew_out,
    )
