"""Synthetic graph generators (host-side, NumPy).

Counterparts of the reference's KaGen streaming generators
(``kaminpar-io/dist_skagen.cc:33-40``, used by apps/dKaMinPar.cc:295) and the
test graph factories (``tests/shm/graph_factories.h``: path / star / grid /
complete builders).  RMAT is the benchmark workload of BASELINE.md.
"""

from __future__ import annotations

import numpy as np

from .csr import CSRGraph, from_edge_list


def path_graph(n: int, **kw) -> CSRGraph:
    e = np.stack([np.arange(n - 1), np.arange(1, n)], axis=1)
    return from_edge_list(n, e, **kw)


def cycle_graph(n: int, **kw) -> CSRGraph:
    e = np.stack([np.arange(n), (np.arange(n) + 1) % n], axis=1)
    return from_edge_list(n, e, **kw)


def star_graph(n_leaves: int, **kw) -> CSRGraph:
    e = np.stack([np.zeros(n_leaves, dtype=np.int64), np.arange(1, n_leaves + 1)], axis=1)
    return from_edge_list(n_leaves + 1, e, **kw)


def complete_graph(n: int, **kw) -> CSRGraph:
    idx = np.arange(n)
    a, b = np.meshgrid(idx, idx, indexing="ij")
    mask = a < b
    e = np.stack([a[mask], b[mask]], axis=1)
    return from_edge_list(n, e, **kw)


def grid2d_graph(rows: int, cols: int, **kw) -> CSRGraph:
    """4-neighbor grid — the structured benchmark graph (rgg2d stand-in)."""
    ids = np.arange(rows * cols).reshape(rows, cols)
    right = np.stack([ids[:, :-1].ravel(), ids[:, 1:].ravel()], axis=1)
    down = np.stack([ids[:-1, :].ravel(), ids[1:, :].ravel()], axis=1)
    return from_edge_list(rows * cols, np.concatenate([right, down]), **kw)


def rmat_graph(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    **kw,
) -> CSRGraph:
    """Graph500-style RMAT: 2**scale nodes, ~edge_factor*2**scale undirected
    edges (pre-dedup).  The benchmark graph family of BASELINE.md configs 2/4."""
    n = 1 << scale
    num_edges = edge_factor * n
    rng = np.random.default_rng(seed)
    u = np.zeros(num_edges, dtype=np.int64)
    v = np.zeros(num_edges, dtype=np.int64)
    ab, abc = a + b, a + b + c
    for _ in range(scale):
        r = rng.random(num_edges)
        right = r >= ab  # bottom half of the adjacency matrix
        down = (r >= a) & (r < ab) | (r >= abc)
        u = (u << 1) | right.astype(np.int64)
        v = (v << 1) | down.astype(np.int64)
    # permute node ids to break the power-law ordering correlation
    perm = rng.permutation(n)
    edges = np.stack([perm[u], perm[v]], axis=1)
    return from_edge_list(n, edges, **kw)


def rgg2d_graph(n: int, radius: float | None = None, seed: int = 0, **kw) -> CSRGraph:
    """Random geometric graph in the unit square (KaGen ``rgg2d``; the
    reference checks one into ``misc/rgg2d.metis``).  O(n) cell grid."""
    rng = np.random.default_rng(seed)
    if radius is None:
        radius = float(np.sqrt(8.0 / n))  # ~ avg degree 8*pi/... small constant
    pts = rng.random((n, 2))
    ncell = max(1, int(1.0 / radius))
    cell = (pts * ncell).astype(np.int64)
    cell_id = cell[:, 0] * ncell + cell[:, 1]
    order = np.argsort(cell_id, kind="stable")
    pts_s, cid_s = pts[order], cell_id[order]
    starts = np.searchsorted(cid_s, np.arange(ncell * ncell))
    ends = np.searchsorted(cid_s, np.arange(ncell * ncell), side="right")
    out_u, out_v = [], []
    r2 = radius * radius
    for cx in range(ncell):
        for cy in range(ncell):
            me = slice(starts[cx * ncell + cy], ends[cx * ncell + cy])
            if me.start == me.stop:
                continue
            for dx, dy in ((0, 0), (0, 1), (1, -1), (1, 0), (1, 1)):
                ox, oy = cx + dx, cy + dy
                if not (0 <= ox < ncell and 0 <= oy < ncell):
                    continue
                other = slice(starts[ox * ncell + oy], ends[ox * ncell + oy])
                if other.start == other.stop:
                    continue
                d = pts_s[me, None, :] - pts_s[None, other, :]
                close = (d * d).sum(-1) <= r2
                if dx == 0 and dy == 0:
                    # Same-cell pairs: keep only i<j, or symmetrization would
                    # double each pair's weight relative to cross-cell edges.
                    close = np.triu(close, k=1)
                ii, jj = np.nonzero(close)
                out_u.append(order[np.arange(me.start, me.stop)[ii]])
                out_v.append(order[np.arange(other.start, other.stop)[jj]])
    u = np.concatenate(out_u) if out_u else np.zeros(0, dtype=np.int64)
    v = np.concatenate(out_v) if out_v else np.zeros(0, dtype=np.int64)
    mask = u != v
    return from_edge_list(n, np.stack([u[mask], v[mask]], axis=1), **kw)
