"""Isolated-node strip + re-integration, shared by the facade and the
lane-stacked serve runner.

Isolated nodes never affect the cut but dilute coarsening and refinement
(reference: kaminpar.cc:388-429), so the facade strips them before
partitioning and bin-packs them into the lightest blocks afterwards
(reference: ``graph::assign_isolated_nodes``).  The lane-stacked runner
(serve/lanestack.py) replicates the facade per lane and its bit-identity
contract requires the replica to match the facade EXACTLY — these helpers
are that single copy, so the two paths cannot drift.
"""

from __future__ import annotations

import heapq
from typing import Optional, Tuple

import numpy as np


def strip_isolated_csr(
    row_ptr: np.ndarray,
    col_idx,
    node_w,
    n: int,
    k: int,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Strip zero-degree nodes from a host CSR.

    Returns ``(keep, isolated, new_row_ptr, new_col_idx, new_node_w)``
    (``new_row_ptr`` int64, ``new_col_idx`` remapped to the stripped id
    space), or None when stripping does not apply — no isolated nodes,
    nothing BUT isolated nodes, or too few survivors for ``k`` blocks.
    Edge weights pass through unchanged (isolated nodes carry no edges).

    ``col_idx`` / ``node_w`` may be zero-arg callables, resolved only when
    stripping applies — the common no-isolated-nodes case then reads
    ``row_ptr`` alone (no O(m) host materialization of a device graph).
    """
    deg = row_ptr[1:] - row_ptr[:-1]
    isolated = np.flatnonzero(deg == 0)
    if not (0 < len(isolated) < n and k <= n - len(isolated)):
        return None
    col_idx = np.asarray(col_idx() if callable(col_idx) else col_idx)
    node_w = np.asarray(node_w() if callable(node_w) else node_w)
    keep = np.flatnonzero(deg > 0)
    remap = np.full(n, -1, dtype=np.int64)
    remap[keep] = np.arange(len(keep))
    new_rp = np.zeros(len(keep) + 1, dtype=np.int64)
    np.cumsum(deg[keep], out=new_rp[1:])
    return keep, isolated, new_rp, remap[col_idx], node_w[keep]


def assign_isolated_nodes(
    full_n: int,
    k: int,
    keep: np.ndarray,
    isolated: np.ndarray,
    work_part: np.ndarray,
    work_node_w: np.ndarray,
    node_w: np.ndarray,
    caps: np.ndarray,
) -> np.ndarray:
    """Re-integrate stripped isolated nodes: greedy lightest-block
    assignment respecting the caps.  A k-entry heap keeps this
    O(n_iso log k) — RMAT graphs can have millions of isolated nodes.
    Returns the full (``full_n``,) partition."""
    full_part = np.zeros(full_n, dtype=work_part.dtype)
    full_part[keep] = work_part
    bw = np.bincount(work_part, weights=work_node_w, minlength=k).astype(np.int64)
    iso_w = node_w[isolated]
    order = np.argsort(-iso_w)  # heaviest first packs tightest
    heap = [(int(bw[b]), b) for b in range(k)]
    heapq.heapify(heap)
    for u, w in zip(isolated[order], iso_w[order]):
        w = int(w)
        popped = []
        while heap and heap[0][0] + w > caps[heap[0][1]]:
            popped.append(heapq.heappop(heap))
        if heap:
            wt, b = heapq.heappop(heap)
        else:  # nothing fits: overload the lightest block
            popped.sort()
            wt, b = popped.pop(0)
        full_part[u] = b
        heapq.heappush(heap, (wt + w, b))
        for item in popped:
            heapq.heappush(heap, item)
    return full_part
