"""Distributed partition metrics as on-device psum reductions.

Reference: ``kaminpar-dist/metrics.cc:100`` — cut/imbalance are
``MPI_Allreduce`` sums of per-PE local contributions; here each shard
reduces its local edges/nodes inside ``shard_map`` and one ``psum`` rides
the mesh (VERDICT r1 row 51: previously the cut was computed on host
after a full gather).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from .exchange import ghost_exchange, psum
from .lp import _neighbor_labels

AXIS = "nodes"


_CACHE: dict = {}


def make_dist_metrics(mesh: Mesh, *, k: int):
    """Build the jitted (cut, block_weights) reducer for a mesh (cached
    per (mesh, k) so repeated metric calls reuse the compiled program)."""
    key = (id(mesh), k)
    if key in _CACHE:
        return _CACHE[key]

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(), P()),
    )
    def metrics_fn(labels, node_w, edge_u, col_loc, edge_w, send_idx, recv_map):
        ghost_labels = ghost_exchange(
            labels, send_idx, recv_map, fill=jnp.asarray(0, labels.dtype)
        )
        nbr = _neighbor_labels(labels, ghost_labels, col_loc, 0)
        own = labels[edge_u]
        # Pad edges have weight 0, so no masking is needed.  Every
        # undirected edge is stored twice (once per endpoint), so the
        # psum double-counts and we halve outside.
        local_cut = jnp.sum(jnp.where(own != nbr, edge_w, 0))
        cut2 = psum(local_cut, AXIS)
        bw = psum(
            jax.ops.segment_sum(node_w, labels.astype(jnp.int32), num_segments=k),
            AXIS,
        )
        return cut2, bw

    fn = jax.jit(metrics_fn)
    _CACHE[key] = fn
    return fn


def dist_edge_cut(mesh: Mesh, labels, graph, *, k: int) -> int:
    """Global edge cut of a sharded partition (one device program)."""
    from ..utils import sync_stats

    cut2, _ = make_dist_metrics(mesh, k=k)(
        labels, graph.node_w, graph.edge_u, graph.col_loc, graph.edge_w,
        graph.send_idx, graph.recv_map,
    )
    # int(cut2) was an un-counted implicit scalar pull (round 12).
    return int(
        sync_stats.pull(cut2, phase="dist_metrics", shards=graph.num_shards)
    ) // 2


def dist_block_weights(mesh: Mesh, labels, graph, *, k: int) -> np.ndarray:
    from ..utils import sync_stats

    _, bw = make_dist_metrics(mesh, k=k)(
        labels, graph.node_w, graph.edge_u, graph.col_loc, graph.edge_w,
        graph.send_idx, graph.recv_map,
    )
    # Counted readback (round 12): the (k,) weight table leaves the device
    # exactly once per metrics call.
    return sync_stats.pull(bw, phase="dist_metrics", shards=graph.num_shards)


def dist_imbalance(mesh: Mesh, labels, graph, *, k: int) -> float:
    bw = dist_block_weights(mesh, labels, graph, k=k)
    total = int(bw.sum())
    perfect = -(total // -k) if k else 1
    return float(bw.max() / perfect - 1.0) if perfect > 0 else 0.0


def dist_is_feasible(mesh: Mesh, labels, graph, max_block_weights, *, k: int) -> bool:
    bw = dist_block_weights(mesh, labels, graph, k=k)
    return bool((bw <= np.asarray(max_block_weights)).all())  # kpt: ignore[sync-discipline] — caps are host np
