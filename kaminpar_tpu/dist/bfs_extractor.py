"""Distributed BFS region extractor.

Role counterpart: kaminpar-dist/graphutils/bfs_extractor.{h,cc} (~764 LoC)
— grow a bounded-radius region around seed nodes of a distributed graph
and materialize it as a *shared-memory* graph + partition + node mapping,
optionally representing everything outside the region as one contracted
supernode per block (ExteriorStrategy::CONTRACT), so a local refiner can
improve the region while seeing the exterior's block weights.

TPU redesign: the reference runs a per-PE parallel BFS with explored-node
sets and ships subtrees over MPI.  Here hop propagation is SPMD: each
round is one ghost exchange + a gather + segment-min by edge source — a
node's new hop is ``min(hop, min over incident edges of hop[neighbor]+1)``
— run ``radius`` times inside one jitted shard_map (same round shape as
dist LP).  Extraction then happens host-side from the final hop labels,
like the reference's materialized shm::Graph result.

High-degree strategies (IGNORE/SAMPLE/CUT, bfs_extractor.h:37-42) are not
needed: the frontier is bounded by radius * max-degree and the extractor
is a tooling path, not the hot path (TAKE_ALL semantics).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..utils import sync_stats
from .exchange import AXIS, ghost_exchange

_INF = np.int32(2**30)


@dataclass
class BfsResult:
    """Mirrors BfsExtractor::Result (graph, p_graph, node_mapping)."""

    graph: object  # CSRGraph of the region (+ one supernode per block if contracted)
    partition: np.ndarray  # (n_region [+ k],) block ids
    node_mapping: np.ndarray  # (n_region,) global ids of region nodes
    num_region_nodes: int  # region nodes (excludes supernodes)


@lru_cache(maxsize=None)
def _make_bfs_hops(mesh: Mesh, *, radius: int):
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
        out_specs=P(AXIS),
    )
    def hops_fn(hop0, edge_u, col_loc, send_idx, recv_map):
        def body(_, hop):
            ghost_hop = ghost_exchange(
                hop, send_idx, recv_map, fill=jnp.asarray(_INF, hop.dtype)
            )
            ext = jnp.concatenate(
                [hop, ghost_hop, jnp.full((1,), _INF, hop.dtype)]
            )
            cand = ext[col_loc] + 1  # hop via each incident edge
            best = jax.ops.segment_min(
                cand, edge_u.astype(jnp.int32), num_segments=hop.shape[0]
            )
            return jnp.minimum(hop, best)

        return jax.lax.fori_loop(0, radius, body, hop0)

    return jax.jit(hops_fn)


def dist_bfs_hops(mesh, dgraph, seeds: np.ndarray, *, radius: int) -> np.ndarray:
    """(n,) BFS hop distance from the seed set (INF where unreached within
    ``radius``)."""
    hop0 = np.full(dgraph.N, _INF, dtype=np.int32)
    hop0[np.asarray(seeds, dtype=np.int64)] = 0
    fn = _make_bfs_hops(mesh, radius=int(radius))
    # edge pads point at the fill slot (col == n_loc + g_loc) whose value is
    # INF, so they never win the min.
    hops = fn(jnp.asarray(hop0), dgraph.edge_u.astype(jnp.int32),
              dgraph.col_loc.astype(jnp.int32), dgraph.send_idx,
              dgraph.recv_map)
    return sync_stats.pull(
        hops, phase="dist_extract", shards=dgraph.num_shards
    )[: dgraph.n]


def dist_bfs_extract(mesh, dgraph, labels, seeds, *, radius: int, k: int,
                     exterior: str = "contract") -> BfsResult:
    """Extract the radius-ball around ``seeds`` as a host CSRGraph.

    exterior: 'exclude' drops edges leaving the region; 'contract' routes
    them into one supernode per block carrying the block's exterior weight
    (ExteriorStrategy::{EXCLUDE,CONTRACT}; INCLUDE is EXCLUDE plus the
    boundary ring, which radius+1 already gives).
    """
    if exterior not in ("exclude", "contract"):
        raise ValueError(f"unknown exterior strategy {exterior!r}")
    hops = dist_bfs_hops(mesh, dgraph, seeds, radius=radius)
    # One counted readback for the label/weight inputs of the host
    # extraction (round 12, kptlint sync-discipline).
    labels_host, node_w = sync_stats.pull(
        labels, dgraph.node_w, phase="dist_extract",
        shards=dgraph.num_shards,
    )
    labels_host = labels_host[: dgraph.n].astype(np.int64)
    # An out-of-range label would make the np.bincount below return more
    # than k supernode weights, desynchronizing the weight vector from the
    # partition array and only failing much later inside from_edge_list.
    if labels_host.size:
        lo, hi = int(labels_host.min()), int(labels_host.max())
        if lo < 0 or hi >= k:
            raise ValueError(
                f"partition labels must lie in [0, {k}); got range [{lo}, {hi}]"
            )
    node_w = node_w[: dgraph.n].astype(np.int64)

    reached = hops < _INF
    mapping = np.flatnonzero(reached).astype(np.int64)  # region -> global
    n_sub = len(mapping)
    local_of = np.full(dgraph.n, -1, dtype=np.int64)
    local_of[mapping] = np.arange(n_sub)

    src, dst, w = dgraph.edges_global_host()
    src_in = reached[src]
    dst_in = reached[dst]

    keep = src_in & dst_in
    e_src = [local_of[src[keep]]]
    e_dst = [local_of[dst[keep]]]
    e_w = [w[keep]]

    n_total = n_sub
    part = labels_host[mapping]
    nw_sub = [node_w[mapping]]

    if exterior == "contract":
        n_total = n_sub + k
        # region -> exterior edges, rerouted to the exterior block supernode
        # (and mirrored, keeping the CSR symmetric).
        bound = src_in & ~dst_in
        bs = local_of[src[bound]]
        bb = n_sub + labels_host[dst[bound]]
        e_src += [bs, bb]
        e_dst += [bb, bs]
        e_w += [w[bound], w[bound]]
        # supernode weight = block weight outside the region
        ext_w = np.bincount(
            labels_host[~reached], weights=node_w[~reached].astype(float),
            minlength=k,
        ).astype(np.int64)
        nw_sub.append(np.maximum(ext_w, 1))  # zero-weight nodes break caps
        part = np.concatenate([part, np.arange(k, dtype=np.int64)])

    # from_edge_list merges the parallel edges that contracting many
    # boundary edges into one supernode creates (weights summed), and
    # handles the edgeless radius-0 region; edges are already symmetric
    # here and self-loops cannot occur (cu != cv by construction).
    from ..graph.csr import from_edge_list

    edges = np.stack([np.concatenate(e_src), np.concatenate(e_dst)], axis=1)
    graph = from_edge_list(
        n_total, edges, edge_weights=np.concatenate(e_w),
        node_weights=np.concatenate(nw_sub), symmetrize=False,
    )
    return BfsResult(
        graph=graph,
        partition=part.astype(np.int64),
        node_mapping=mapping,
        num_region_nodes=n_sub,
    )
