"""Distributed partition validation.

Reference: ``kaminpar-dist/debug.cc:122`` (``validate_partition``) — after
every phase, assert the partition is structurally sound across PEs: block
ids in range, replicated block weights consistent with the actual node
weights, ghost copies consistent with their owners.  Used by tests and
(optionally) by the pipeline between phases; one shard_map program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from functools import lru_cache, partial

from jax.sharding import PartitionSpec as P

from ..utils import sync_stats
from .exchange import ghost_exchange
from .metrics import dist_block_weights


@lru_cache(maxsize=None)
def _make_ghost_reader(mesh: Mesh):
    """Jitted ghost-label reader, cached per mesh (same pattern as the
    make_dist_* round factories — a fresh closure per call would recompile
    every phase-boundary validation)."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("nodes"), P("nodes"), P("nodes")),
        out_specs=P("nodes"),
    )
    def ghosts(lab_loc, send_idx, recv_map):
        return ghost_exchange(
            lab_loc, send_idx, recv_map, fill=jnp.asarray(-1, lab_loc.dtype)
        )

    return jax.jit(ghosts)


def validate_partition(mesh: Mesh, labels, graph, k: int, max_block_weights=None):
    """Returns (ok, problems: list[str]).  Checks:

    1. every real node's label is in [0, k),
    2. ghost label copies equal their owners' values (the exchange is the
       single source of truth — this catches routing corruption),
    3. block weights match a direct recount, and respect the caps when
       given (reference debug.cc:122 checks the replicated tables).
    """
    problems = []
    # One counted readback for the label + weight sweep (round 12, kptlint
    # sync-discipline: these were un-counted np.asarray transfers).
    lab, node_w = sync_stats.pull(
        labels, graph.node_w, phase="dist_validation",
        shards=graph.num_shards,
    )
    real = node_w > 0

    if real.any():
        lr = lab[real]
        if lr.min() < 0 or lr.max() >= k:
            problems.append(
                f"labels out of range [0,{k}): min={lr.min()} max={lr.max()}"
            )

    # ghost consistency through the actual exchange program
    gl = sync_stats.pull(
        _make_ghost_reader(mesh)(labels, graph.send_idx, graph.recv_map),
        phase="dist_validation", shards=graph.num_shards,
    )
    gl = gl.reshape(graph.num_shards, graph.g_loc)
    for s in range(graph.num_shards):
        gg = graph.ghost_global[s]
        if len(gg) == 0:
            continue
        got = gl[s, : len(gg)]
        want = lab[gg]
        bad = got != want
        if bad.any():
            problems.append(
                f"shard {s}: {int(bad.sum())} ghost labels diverge from owners"
            )

    # dist_block_weights already returns a pulled host array.
    bw = dist_block_weights(mesh, labels, graph, k=k)
    direct = np.bincount(lab[real], weights=node_w[real], minlength=k)
    if not np.array_equal(bw, direct.astype(bw.dtype)):
        problems.append("device block weights diverge from direct recount")
    if max_block_weights is not None:
        over = np.flatnonzero(bw > np.asarray(max_block_weights))  # kpt: ignore[sync-discipline] — caps are host np
        if len(over):
            problems.append(f"blocks over cap: {over.tolist()}")

    return len(problems) == 0, problems
