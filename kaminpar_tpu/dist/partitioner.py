"""dKaMinPar facade: distributed deep multilevel partitioning over a mesh.

Reference: ``kaminpar-dist/dkaminpar.cc:496`` (facade) +
``kaminpar-dist/partitioning/deep_multilevel.cc`` — coarsen globally until
the graph is small, **replicate the coarsest graph everywhere and run the
shared-memory partitioner as initial partitioner**
(replicate_graph_everywhere → shm KaMinPar, deep_multilevel.cc:132 +
initial_partitioning/kaminpar_initial_partitioner.cc:63), then uncoarsen with
distributed refinement.  Here "replicate to shm" = all-gather the coarse
graph to host (the mesh-wide analog) and run the single-chip pipeline; the
uncoarsening path projects partitions up across shards (owner-routed
queries, no O(N) gather) and refines with distributed LP rounds.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..context import Context
from ..graph.csr import CSRGraph, from_edge_list
from ..graph import metrics
from ..telemetry import probes
from ..telemetry import trace as ttrace
from ..utils import RandomState, sync_stats
from ..utils.logger import Logger, OutputLevel
from ..utils.timer import scoped_timer
from .balancer import dist_balance
from .contraction import contract_dist_clustering, project_partition_up
from .graph import DistGraph, distribute_graph
from .lp import dist_cluster_iterate, dist_lp_iterate, shard_arrays


@dataclass
class _Level:
    graph: DistGraph
    coarse_of: object  # sharded fine->coarse map (global coarse ids)
    coarse_n_loc: int


@dataclass
class DKaMinPar:
    """Distributed facade.  Usage::

        mesh = Mesh(np.array(jax.devices()), ("nodes",))
        solver = DKaMinPar(mesh, ctx)          # ctx optional (default preset)
        part = solver.compute_partition(graph, k=16, epsilon=0.03)
    """

    mesh: Mesh
    ctx: Optional[Context] = None
    hierarchy: List[_Level] = field(default_factory=list)

    def __post_init__(self):
        if self.ctx is None:
            from ..presets import create_context_by_preset_name

            self.ctx = create_context_by_preset_name("default")

    # -- mesh telemetry (round 13) -----------------------------------------

    @staticmethod
    def _shard_level_spans(rec, name, t0_us, dgraph, **args) -> None:
        """Emit one span per shard lane for the level that just finished.

        SPMD has one host program and one fused XLA program per step, so a
        *measured* per-shard wall does not exist (dist/shard_stats.py) —
        the lanes carry an explicit work-proportional ESTIMATE instead:
        shard s's span is the level wall scaled by its owned-edge share of
        the maximum (the max-work shard bounds the bulk-synchronous step,
        so it gets the full measured wall).  The work quantities come from
        the DistGraph's host-computed ``shard_work`` table — zero device
        readbacks — and ride each span's args so ``tools trace --shards``
        can summarize skew from span walls."""
        if rec is None or not dgraph.shard_work:
            return
        t1 = rec._now_us()
        edges = [max(int(w["owned_edges"]), 0) for w in dgraph.shard_work]
        wmax = max(max(edges), 1)
        for s, w in enumerate(dgraph.shard_work):
            rec.lane_span(
                f"shard{s}", name, t0_us, t0_us + (t1 - t0_us) * (edges[s] / wmax),
                estimated="work-proportional", shard=s, **w, **args,
            )

    def _coarsen_level_budget(self) -> int:
        """Blocking-transfer budget of ONE dist coarsening level (per-shard
        currency via assert_phase_budget(shards=P)).  Every term is a
        counted pull the level's drive loops perform; pathological
        overflow-cap escalation re-pulls beyond the slack term can exceed
        this, which is why budget checks are an armed test harness, not an
        always-on assert (see utils/sync_stats.enable_budget_checks)."""
        from ..context import DistClusteringAlgorithm as DCA

        rounds = self.ctx.coarsening.lp.num_iterations
        algo = self.ctx.coarsening.dist_clustering
        cluster = 0
        if algo in (DCA.GLOBAL_HEM, DCA.GLOBAL_HEM_LP):
            cluster += rounds + 1  # per-round matched + final total pull
        if algo in (DCA.LOCAL_LP, DCA.LOCAL_GLOBAL_LP):
            cluster += rounds  # per-round moved pull
        if algo in (DCA.GLOBAL_LP, DCA.LOCAL_GLOBAL_LP, DCA.GLOBAL_HEM_LP):
            cluster += rounds  # per-round overflow pull
        # contraction: packed (n_c, ovf) + s2 ovf + counts x2 + m_c +
        # assembly x3 (global path; the local path uses one fewer)
        contraction = 8
        # The three overflow-adaptive cap loops (cluster rounds, _s1, _s2)
        # each re-pull once per doubling; routine escalation on skewed
        # graphs is a handful per level (measured 7 at scale 11/P=8), and
        # the slack covers that without absorbing a per-round stray pull.
        escalation_slack = 12
        return cluster + contraction + escalation_slack

    def _refine_call_budget(self) -> int:
        """Blocking-transfer budget of ONE ``_refine`` call (the
        ``dist_refinement`` phase): balancer round pulls + the per-round
        convergence pulls of whichever refiners the context engages
        (dist_edge_cut pulls attribute to ``dist_metrics``, not here)."""
        from ..context import MoveExecutionStrategy, RefinementAlgorithm

        r = self.ctx.refinement
        budget = 16 + 8  # node-balancer rounds + cluster-balance escalation
        if r.dist_move_execution in (
            MoveExecutionStrategy.BEST_MOVES, MoveExecutionStrategy.LOCAL_MOVES
        ):
            budget += r.lp.num_iterations
        if RefinementAlgorithm.CLP in r.algorithms:
            # forced-count + num-colors pulls + per-superstep fences on the
            # CPU backend (<= 97 colors under the 96-round JP cap) + one
            # packed fence per iteration elsewhere
            budget += 2 + r.clp.num_iterations * 98
        if RefinementAlgorithm.JET in r.algorithms:
            budget += (r.jet.num_iterations + 1) * (16 + 8)
        return budget

    # -- pipeline ----------------------------------------------------------

    def compute_partition(
        self, graph: CSRGraph, k: int, epsilon: float = 0.03
    ) -> np.ndarray:
        from ..resilience.faults import maybe_inject

        # Named "execute" injection point of the sharded tier (round 17):
        # chaos plans target the dist dispatch with site filter "dist".
        maybe_inject("execute", site="dist_partition")
        P = self.mesh.size
        ctx = self.ctx
        RandomState.reseed(ctx.seed)
        total_w = graph.total_node_weight
        # Balance cap matches the shm/reference convention (kaminpar.py:96-99):
        # max((1+eps)*ceil(W/k), ceil(W/k) + max_node_weight).
        ceil_wk = (total_w + k - 1) // k
        max_bw_val = max(
            int((1.0 + epsilon) * ceil_wk), ceil_wk + graph.max_node_weight
        )
        C = ctx.coarsening.contraction_limit
        from ..context import PartitioningMode

        kway = ctx.mode == PartitioningMode.KWAY
        if kway:
            # dist k-way scheme (reference: kaminpar-dist/partitioning/
            # kway_multilevel.cc): coarsen until n <= C*k, partition the
            # replicated coarsest STRAIGHT to k, uncoarsen with refinement
            # only — no extension levels.
            target_n = max(C * k, 2 * k)
        else:
            target_n = max(2 * C, P * C // max(k, 1), 2 * k)

        # 64-bit ids/weights mirror the reference's KAMINPAR_64BIT_* build
        # switches (CMakeLists.txt:71-79); requires jax x64 (without it the
        # device arrays silently downcast to int32 — exactly the workloads
        # this flag exists for would be corrupted).
        if ctx.use_64bit_ids and not jax.config.jax_enable_x64:
            from ..resilience.errors import BackendUnavailable

            raise BackendUnavailable(
                "use_64bit_ids requires jax x64 mode "
                "(jax.config.update('jax_enable_x64', True))",
                site="dist_partition",
            )
        dtype = np.int64 if ctx.use_64bit_ids else np.int32

        # Compressed staging + device residency (round 15): with
        # ``compression.enabled`` the input is gap-packed per shard before
        # anything m-sized exists host-side, and under ``device_decode``
        # (same knob as the shm tier — terapart presets engage both) the
        # per-shard streams become the *resident* finest-level adjacency on
        # the mesh: LP clustering, contraction S2, and the finest LP
        # refinement pass decode in-kernel inside shard_map, and
        # ``decompress_arrays`` is never called past the view build.
        from .compressed import DistributedCompressedGraph, compress_distributed
        from .device_compressed import build_dist_view_if_eligible

        dcg = None
        if isinstance(graph, DistributedCompressedGraph):
            dcg = graph
        elif ctx.compression.enabled and not ctx.use_64bit_ids:
            dcg = compress_distributed(graph, P)
        if dcg is not None:
            cb_since = sync_stats.phase_count("dist_compressed_build")
            with scoped_timer("dist_compressed_build"):
                view = build_dist_view_if_eligible(ctx, dcg)
            # View build = one host decode per shard for the ghost routing
            # + host packing + device puts: ZERO blocking device->host
            # transfers (the memory win must not be bought with hidden
            # syncs).  No-op unless enable_budget_checks armed it.
            sync_stats.assert_phase_budget(
                "dist_compressed_build", 0, since=cb_since
            )
            dg = view if view is not None else dcg.to_dist_graph(dtype=dtype)
        else:
            dg = distribute_graph(graph, P, dtype=dtype)

        # Per-shard load table — the reference's aggregated dist timer rows
        # (kaminpar-dist/timer.cc:106-173); see dist/shard_stats.py for why
        # the SPMD analog aggregates work quantities, not wall time.
        # Collected here, before shard_arrays, while the arrays are still
        # host-resident (afterwards it would be a full device->host gather),
        # and only when the table will actually be shown.
        self.shard_stats = None
        if Logger.level >= OutputLevel.DEBUG:
            from .shard_stats import collect_graph_stats

            self.shard_stats = collect_graph_stats(dg)
            Logger.log(self.shard_stats.render(), OutputLevel.DEBUG)

        labels = jnp.arange(dg.N, dtype=dg.dtype)
        labels, dg = shard_arrays(self.mesh, dg, labels)

        # -- distributed coarsening ---------------------------------------
        # Mesh telemetry (round 13): per-level shard-lane spans + quality
        # rows ride the level's existing counted pulls (zero extra
        # transfers), and the per-shard sync budget is asserted in-pipeline
        # when enable_budget_checks armed it.
        rec = ttrace.active()
        if rec is not None:
            rec.meta.setdefault("mesh_shards", P)
        self._refine_calls = 0
        self._refine_since = sync_stats.shard_phase_count("dist_refinement")[0]
        self._refine_count_since = sync_stats.phase_count("dist_refinement")
        coarsen_since = sync_stats.shard_phase_count("dist_coarsening")[0]
        coarsen_count_since = sync_stats.phase_count("dist_coarsening")
        coarsen_levels = 0
        self.hierarchy = []
        cur = dg
        with scoped_timer("dist_coarsening"):
            while cur.n > target_n:
                t_lvl = rec._now_us() if rec is not None else 0.0
                max_cw = max(
                    int(epsilon * total_w / max(min(cur.n // max(C, 1), k), 2)), 1
                )
                lab = jnp.arange(cur.N, dtype=cur.dtype)
                lab, cur = shard_arrays(self.mesh, cur, lab)
                from ..context import DistClusteringAlgorithm as DCA

                algo = ctx.coarsening.dist_clustering
                rounds = ctx.coarsening.lp.num_iterations
                if algo in (DCA.GLOBAL_HEM, DCA.GLOBAL_HEM_LP):
                    from .hem import dist_hem_cluster

                    lab, _ = dist_hem_cluster(
                        self.mesh, RandomState.next_key(), cur, max_cw,
                        num_rounds=rounds,
                    )
                if algo in (DCA.LOCAL_LP, DCA.LOCAL_GLOBAL_LP):
                    from .lp import dist_local_cluster_iterate

                    lab, _ = dist_local_cluster_iterate(
                        self.mesh, RandomState.next_key(), lab, cur,
                        jnp.asarray(max_cw, cur.dtype), num_rounds=rounds,
                    )
                if algo in (DCA.GLOBAL_LP, DCA.LOCAL_GLOBAL_LP,
                            DCA.GLOBAL_HEM_LP):
                    if getattr(cur, "is_compressed_view", False):
                        # Decode-fused clustering off the resident per-shard
                        # gap streams (round 15).  The view only exists
                        # under the GLOBAL_LP envelope, the drive consumes
                        # the same key and the decoded adjacency is
                        # bit-identical to the dense slices — so this
                        # branch and the dense one produce identical labels.
                        from .device_compressed import (
                            dist_cluster_iterate_compressed,
                        )

                        lab, _ = dist_cluster_iterate_compressed(
                            self.mesh, RandomState.next_key(), lab, cur,
                            jnp.asarray(max_cw, cur.dtype), num_rounds=rounds,
                        )
                    else:
                        lab, _ = dist_cluster_iterate(
                            self.mesh, RandomState.next_key(), lab, cur,
                            jnp.asarray(max_cw, cur.dtype), num_rounds=rounds,
                        )
                if algo == DCA.LOCAL_LP:
                    # shard-local clusters never migrate: the exchange-free
                    # local contraction (local_contraction.cc role) applies
                    from .contraction import contract_local_clustering

                    coarse, coarse_of, n_c = contract_local_clustering(
                        self.mesh, cur, lab
                    )
                else:
                    coarse, coarse_of, n_c = contract_dist_clustering(
                        self.mesh, cur, lab
                    )
                coarsen_levels += 1
                probes.dist_coarsening_level(
                    level=coarsen_levels - 1, n=cur.n, m=cur.m, n_c=n_c,
                    m_c=coarse.m, shards=P, max_cluster_weight=max_cw,
                )
                self._shard_level_spans(
                    rec, "dist_coarsening_level", t_lvl, cur,
                    level=coarsen_levels - 1,
                )
                if n_c < k:
                    # contraction overshot below k blocks — keep the finer
                    # graph so initial partitioning can still produce k
                    Logger.log(
                        f"  dist coarsening stopped: n_c={n_c} < k={k}",
                        OutputLevel.DEBUG,
                    )
                    break
                shrink = 1.0 - n_c / max(cur.n, 1)
                Logger.log(
                    f"  dist coarsening: n={cur.n} -> {n_c} (m={cur.m} -> {coarse.m})",
                    OutputLevel.DEBUG,
                )
                if shrink < ctx.coarsening.convergence_threshold:
                    break
                self.hierarchy.append(_Level(cur, coarse_of, coarse.n_loc))
                cur = coarse
        # Per-shard sync budget, asserted in-pipeline (round 13): every
        # level's drive loops stay within the statically derived per-level
        # pull allowance — a stray per-round readback regresses this
        # immediately.  No-op unless sync_stats.enable_budget_checks armed.
        sync_stats.assert_phase_budget(
            "dist_coarsening",
            self._coarsen_level_budget() * max(coarsen_levels, 1),
            since=coarsen_since, shards=P,
            count_since=coarsen_count_since,
        )
        # The coarsest may still be the compressed view (tiny inputs /
        # early convergence): replicate-to-host and the dense refiners need
        # the dense DistGraph — ONE sharded decode dispatch, zero pulls.
        cur, cur_view = self._materialize_if_view(cur)

        # -- initial partitioning: replicate coarsest -> shm pipeline ------
        # Deep scheme (else-branch below): the coarsest carries only
        # compute_k_for_n blocks; extension toward k happens during
        # uncoarsening (dist deep_multilevel.cc extend_partition :208-311).
        # The kway scheme DELIBERATELY partitions straight to k on its
        # C*k-sized coarsest (kway_multilevel.cc) — that is its design, not
        # the r1 regression (which was deep-mode doing the same on a far
        # smaller coarsest).
        from ..partitioning.partition_utils import compute_k_for_n

        ip_since = sync_stats.phase_count("dist_initial_partitioning")
        with scoped_timer("dist_initial_partitioning"):
            coarse_host = self._replicate_to_host(cur)
            if kway:
                k0 = max(min(k, coarse_host.n), 1)  # direct k-way IP
            else:
                k0 = max(
                    min(k, compute_k_for_n(coarse_host.n, C, k), coarse_host.n), 1
                )
            # PE-splitting analog (deep_multilevel.cc:80-96): the reference
            # splits PEs into ceil(P/k0) groups, each replicating the coarse
            # graph and partitioning independently; the best result wins.
            # With the coarsest replicated to one host, the parallelism is
            # moot but the quality benefit is R independent attempts.
            reps = max(1, min(P // max(k0, 1), 4))
            part_host, best_cut = None, None
            import copy as _copy
            from concurrent.futures import ThreadPoolExecutor

            from ..factories import create_partitioner
            from ..utils.timer import Timer

            # Construct partitioners directly, NOT through the KaMinPar
            # facade: the facade reseeds the RNG and resets the timer tree
            # (kaminpar.py) — side effects the enclosing dist pipeline (open
            # scoped_timer scopes, its own RNG stream) must not see.  Same
            # pattern as partitioning/deep._nested_partition (ADVICE r2 #1).
            # Intentionally also skips the facade's isolated-node strip +
            # bin-pack: contracted coarse graphs may contain isolated nodes
            # (zero-cut either way) and stripping would perturb the replica
            # RNG streams; refinement rebalances any placement slack.
            def one_rep(r: int):
                # Worker-thread RNG stream: deterministic in (seed, rep)
                # regardless of scheduling (RandomState is thread-local).
                RandomState.reseed(self.ctx.seed * 4099 + r * 7919)
                rep_ctx = _copy.deepcopy(self.ctx)
                rep_ctx.compression.enabled = False
                rep_ctx.partition.setup(
                    int(coarse_host.total_node_weight), k0, epsilon
                )
                # weighted-node strictness adjustment (kaminpar.cc setup)
                perfect = (int(coarse_host.total_node_weight) + k0 - 1) // k0
                rep_ctx.partition.max_block_weights = np.maximum(
                    rep_ctx.partition.max_block_weights,
                    perfect + int(coarse_host.max_node_weight),
                )
                cand = sync_stats.pull(
                    create_partitioner(rep_ctx, coarse_host).partition().partition,
                    phase="dist_initial_partitioning",
                ).astype(np.int32)
                return cand, metrics.edge_cut(coarse_host, cand)

            # Concurrent replicas (VERDICT r2 next-steps #7): the reference
            # splits PE groups so the R attempts run in parallel
            # (deep_multilevel.cc:80-96) and disables timers inside the
            # parallel section (its deep_multilevel.cc:213); thread workers
            # overlap the reps' device dispatches and GIL-releasing numpy.
            timer = Timer.global_()
            timer.disable()
            # The nested shm replicas run their own armed budget asserts
            # against process-global counters — concurrent replica threads
            # alias each other's phases (utils/sync_stats.py docstring), so
            # disarm for the pool's duration and re-arm after.
            budget_armed = sync_stats.budget_checks_enabled()
            if budget_armed:
                sync_stats.enable_budget_checks(False)
            try:
                import os as _os

                # Always run reps in worker threads — even reps == 1 —
                # so the reseed never touches the main thread's stream.
                workers = min(reps, max(_os.cpu_count() or 1, 1))
                from ..context import propagate_runtime

                with ThreadPoolExecutor(max_workers=workers) as pool:
                    results = list(
                        pool.map(propagate_runtime(one_rep), range(reps))
                    )
            finally:
                timer.enable()
                if budget_armed:
                    sync_stats.enable_budget_checks(True)
            # Mesh splitting (deep_multilevel.cc:80-96 / replicator.cc):
            # with R candidates and P divisible by R, refine + select on R
            # disjoint sub-meshes in one device program — the replica
            # groups work concurrently, no host-side selection loop.
            if reps >= 2 and P % reps == 0:
                from .replicate import refine_replicated

                parts_R = np.stack([c for c, _ in results])
                perfect = (int(coarse_host.total_node_weight) + k0 - 1) // k0
                cap0 = np.full(
                    k0,
                    max(int((1.0 + epsilon) * perfect),
                        perfect + int(coarse_host.max_node_weight)),
                    dtype=np.int64,
                )
                part_host, rep_cuts = refine_replicated(
                    self.mesh, RandomState.next_key(), parts_R, coarse_host,
                    jnp.asarray(cap0, dtype=dtype), k=k0,
                    num_rounds=ctx.refinement.lp.num_iterations, dtype=dtype,
                )
                best_cut = int(rep_cuts.min())
                Logger.log(
                    f"  dist IP mesh-split: {reps} replica groups x "
                    f"{P // reps} shards, cuts {rep_cuts.tolist()}",
                    OutputLevel.DEBUG,
                )
            else:
                for cand, cand_cut in results:
                    if best_cut is None or cand_cut < best_cut:
                        part_host, best_cut = cand, cand_cut
            Logger.log(
                f"  dist IP: coarsest n={coarse_host.n} k0={k0} reps={reps} "
                f"cut={best_cut}",
                OutputLevel.DEBUG,
            )
            part = np.zeros(cur.N, dtype=np.int32)
            part[: cur.n] = part_host
            cur_k = k0
        # Replicated-IP budget in plain transfer currency: one counted rep
        # pull per replica + the mesh-split selection's cut-vector + winner
        # pulls (the nested shm pipelines run under their OWN phase names).
        sync_stats.assert_phase_budget(
            "dist_initial_partitioning", reps + 4, since=ip_since,
        )

        # -- uncoarsening: extend toward k + distributed refinement --------
        final_bw = np.full(k, max_bw_val, dtype=np.int64)
        uncoarsen_since = sync_stats.shard_phase_count("dist_uncoarsening")[0]
        uncoarsen_count_since = sync_stats.phase_count("dist_uncoarsening")
        uncoarsen_levels = 0
        with scoped_timer("dist_uncoarsening"):
            t_lvl = rec._now_us() if rec is not None else 0.0
            part_dev, cur_shard = shard_arrays(self.mesh, cur, jnp.asarray(part))
            part_dev, cur_k = self._extend_and_refine(
                part_dev, cur_shard, cur_k, k, final_bw, view=cur_view
            )
            uncoarsen_levels += 1
            probes.dist_uncoarsening_level(
                level=len(self.hierarchy), n=cur_shard.n, m=cur_shard.m,
                k=cur_k, shards=P,
            )
            self._shard_level_spans(
                rec, "dist_uncoarsening_level", t_lvl, cur_shard,
                level=len(self.hierarchy),
            )
            while self.hierarchy:
                level = self.hierarchy.pop()
                t_lvl = rec._now_us() if rec is not None else 0.0
                # A compressed finest level stores only the view in the
                # hierarchy; the dense graph the balancer/CLP/JET refiners
                # need is decoded here in one sharded dispatch (zero
                # pulls), while the LP refinement pass below runs straight
                # off the view's streams.
                level_graph, lvl_view = self._materialize_if_view(level.graph)
                part_dev = project_partition_up(
                    self.mesh, level.coarse_of, part_dev,
                    n_loc_c=level.coarse_n_loc,
                )
                part_dev, cur_k = self._extend_and_refine(
                    part_dev, level_graph, cur_k, k, final_bw, view=lvl_view
                )
                uncoarsen_levels += 1
                probes.dist_uncoarsening_level(
                    level=len(self.hierarchy), n=level_graph.n,
                    m=level_graph.m, k=cur_k, shards=P,
                )
                self._shard_level_spans(
                    rec, "dist_uncoarsening_level", t_lvl, level_graph,
                    level=len(self.hierarchy),
                )

        out = sync_stats.pull(
            part_dev, phase="dist_uncoarsening", shards=P
        )[: graph.n]
        # Uncoarsening-phase budget (per-shard currency): per level at most
        # the extension part pull + projection overflow pulls, plus the
        # final partition readback.  The sharded device-extension path
        # nests whole coarsening pipelines under this phase with
        # data-dependent depth, so its budget is not asserted here.
        if not self.ctx.initial_partitioning.device_extension:
            sync_stats.assert_phase_budget(
                "dist_uncoarsening", 4 * uncoarsen_levels + 1,
                since=uncoarsen_since, shards=P,
                count_since=uncoarsen_count_since,
            )
        sync_stats.assert_phase_budget(
            "dist_refinement",
            self._refine_call_budget() * max(self._refine_calls, 1),
            since=getattr(self, "_refine_since", 0), shards=P,
            count_since=getattr(self, "_refine_count_since", 0),
        )
        if Logger.level.value >= OutputLevel.EXPERIMENT.value and isinstance(
            graph, CSRGraph
        ):
            # (dist_edge_cut computes the identical value on device — used
            # when the graph only exists sharded; here the host copy is free.
            # Compressed inputs skip the host cut: decompressing the whole
            # graph just for a log line would defeat the staging tier.)
            cut = metrics.edge_cut(graph, out)
            Logger.log(
                f"dist RESULT cut={cut} k={k} n={graph.n} shards={P}",
                OutputLevel.EXPERIMENT,
            )
        return out

    def _materialize_if_view(self, g):
        """(dense graph, view-or-None) for a hierarchy level: a compressed
        view is decoded into the dense DistGraph in ONE sharded device
        dispatch under its own ``dist_compressed_decode`` phase with a
        ZERO blocking-transfer budget asserted in-pipeline (round 15) —
        no host decompress, no readbacks.  Dense levels pass through."""
        if not getattr(g, "is_compressed_view", False):
            return g, None
        from .device_compressed import materialize_dist_graph

        cd_since = sync_stats.phase_count("dist_compressed_decode")
        with scoped_timer("dist_compressed_decode"):
            dense = materialize_dist_graph(self.mesh, g)
        sync_stats.assert_phase_budget(
            "dist_compressed_decode", 0, since=cd_since
        )
        return dense, g

    def _extend_and_refine(self, part_dev, dgraph: DistGraph, cur_k: int, k: int,
                           final_bw: np.ndarray, view=None):
        """Extend the partition toward k for this level's size, then refine.

        Reference: dist deep_multilevel.cc extend_partition (:208-311) —
        block-induced subgraphs are extracted and partitioned by the shm
        initial partitioner.  Extension levels have n bounded by ~k*C (for
        larger n, compute_k_for_n already returns k), so gathering the
        level graph to host for extension is O(k*C) work independent of
        the input size; only a toplevel extension (input graph still below
        k*C nodes) gathers the full graph.
        """
        from ..partitioning.partition_utils import (
            compute_k_for_n,
            intermediate_block_weights,
        )

        C = self.ctx.coarsening.contraction_limit
        is_finest = not self.hierarchy
        target_k = k if is_finest else min(k, compute_k_for_n(dgraph.n, C, k))
        if cur_k < target_k:
            ipc = self.ctx.initial_partitioning
            if ipc.device_extension and dgraph.n >= ipc.device_extension_n:
                # Sharded extension (dist/extension.py): no per-level full
                # replication — only the nested coarsest (O(target_n)) is
                # gathered (VERDICT r4 missing #4).
                from .extension import dist_extend_partition

                part_dev = dist_extend_partition(
                    self.mesh, part_dev, dgraph, cur_k, target_k, self.ctx,
                    final_bw, self._replicate_to_host,
                )
                cur_k = target_k
            else:
                from ..partitioning.deep import extend_partition

                host = self._replicate_to_host(dgraph)
                part_host = sync_stats.pull(
                    part_dev, shards=dgraph.num_shards
                )[: dgraph.n].astype(np.int32)
                import copy as _copy

                ext_ctx = _copy.deepcopy(self.ctx)
                ext_ctx.partition.k = k
                ext_ctx.partition.max_block_weights = final_bw
                part_host = extend_partition(host, part_host, cur_k, target_k, ext_ctx)
                if Logger.level.value >= OutputLevel.DEBUG.value:
                    Logger.log(
                        f"  dist extend: n={dgraph.n} k {cur_k} -> {target_k}, "
                        f"cut {metrics.edge_cut(host, part_host)}",
                        OutputLevel.DEBUG,
                    )
                cur_k = target_k
                full = np.zeros(dgraph.N, dtype=np.int32)
                full[: dgraph.n] = part_host
                part_dev = jnp.asarray(full)

        cap = jnp.asarray(
            intermediate_block_weights(np.asarray(final_bw, dtype=np.int64), cur_k),  # kpt: ignore[sync-discipline] — final_bw is host np
            dtype=dgraph.dtype,
        )
        part_dev = self._refine(part_dev, dgraph, cap, cur_k, view=view)
        return part_dev, cur_k

    def _refine(self, part, dgraph: DistGraph, cap, k: int, view=None):
        """Balance → LP, the reference's refiner pipeline order
        (dist factories.cc:95-131: NodeBalancer runs before LP/CLP/JET).
        Runs under its own ``dist_refinement`` phase so the balancer/LP
        convergence pulls budget separately from the uncoarsening spine."""
        self._refine_calls = getattr(self, "_refine_calls", 0) + 1
        with scoped_timer("dist_refinement"):
            return self._refine_body(part, dgraph, cap, k, view=view)

    def _refine_body(self, part, dgraph: DistGraph, cap, k: int, view=None):
        # Round carries are donated throughout (round 15, the SNIPPETS
        # [1]-[3] donation pattern): every drive below rebinds its labels
        # output (`x = fn(x, ...)`), so each round's input buffer is
        # released to XLA the moment its output exists — across level
        # boundaries the previous level's projected partition is freed as
        # this level's refinement proceeds, instead of accumulating one
        # (P*n_loc,) buffer per round per level.
        part, dgraph = shard_arrays(self.mesh, dgraph, part)
        part, feasible = dist_balance(
            self.mesh, RandomState.next_key(), part, dgraph, cap, k=k,
            donate=True,
        )
        if not feasible:
            Logger.warning(
                "dist balancer exhausted its round budget without restoring "
                "feasibility; the returned partition may exceed block caps"
            )
        from ..context import MoveExecutionStrategy, RefinementAlgorithm

        if self.ctx.refinement.dist_move_execution in (
            MoveExecutionStrategy.BEST_MOVES,
            MoveExecutionStrategy.LOCAL_MOVES,
        ):
            from .lp import make_dist_lp_round_best

            fn = make_dist_lp_round_best(
                self.mesh, num_labels=k,
                eager=self.ctx.refinement.dist_move_execution
                == MoveExecutionStrategy.LOCAL_MOVES,
                donate=True,
            )
            out = part
            for _ in range(self.ctx.refinement.lp.num_iterations):
                out, moved = fn(
                    RandomState.next_key(), out, dgraph.node_w, dgraph.edge_u,
                    dgraph.col_loc, dgraph.edge_w, cap, dgraph.send_idx,
                    dgraph.recv_map,
                )
                # Counted per-round convergence readback (round 13).
                if int(sync_stats.pull(moved, shards=dgraph.num_shards)) == 0:
                    break
        elif view is not None:
            # Finest compressed level (round 15): the LP refinement pass
            # decodes the adjacency in-kernel off the view's resident
            # streams — bit-identical to the dense rounds (the decode
            # reproduces the dense slices exactly and the shared round body
            # does the rest), same key consumption, same pull structure.
            from .device_compressed import dist_lp_iterate_compressed

            out, _ = dist_lp_iterate_compressed(
                self.mesh, RandomState.next_key(), part, view, cap,
                num_labels=k, num_rounds=self.ctx.refinement.lp.num_iterations,
                external_only=False,
                num_chunks=max(self.ctx.refinement.dist_num_chunks, 1),
                donate=True,
            )
        else:
            out, _ = dist_lp_iterate(
                self.mesh, RandomState.next_key(), part, dgraph, cap,
                num_labels=k, num_rounds=self.ctx.refinement.lp.num_iterations,
                external_only=False,
                num_chunks=max(self.ctx.refinement.dist_num_chunks, 1),
                donate=True,
            )

        if RefinementAlgorithm.CLP in self.ctx.refinement.algorithms:
            from .lp import dist_clp_iterate

            out, _ = dist_clp_iterate(
                self.mesh, RandomState.next_key(), out, dgraph, cap,
                num_labels=k,
                num_iterations=self.ctx.refinement.clp.num_iterations,
                allow_tie_moves=self.ctx.refinement.clp.allow_tie_moves,
                donate=True,
            )
        if RefinementAlgorithm.JET in self.ctx.refinement.algorithms:
            from .jet import dist_jet_iterate

            jc = self.ctx.refinement.jet
            # coarse levels = everything still carrying hierarchy below it
            coarse = bool(self.hierarchy)
            t0 = (
                jc.initial_gain_temp_on_coarse_level
                if coarse
                else jc.initial_gain_temp_on_fine_level
            )
            t1 = (
                jc.final_gain_temp_on_coarse_level
                if coarse
                else jc.final_gain_temp_on_fine_level
            )
            out, _ = dist_jet_iterate(
                self.mesh, RandomState.next_key(), out, dgraph, cap,
                num_labels=k, num_iterations=jc.num_iterations,
                num_fruitless=jc.num_fruitless_iterations, temp0=t0, temp1=t1,
            )
        return out

    def _replicate_to_host(self, dg: DistGraph) -> CSRGraph:
        """replicate_graph_everywhere analog: gather the coarse graph off the
        mesh and rebuild a host CSRGraph (reference: replicator.h:26)."""
        node_w = sync_stats.pull(
            dg.node_w, phase="dist_extract", shards=dg.num_shards
        )[: dg.n]
        src, dst, ww = dg.edges_global_host()
        edges = np.stack([src, dst], axis=1)
        return from_edge_list(
            dg.n, edges, edge_weights=ww, node_weights=node_w,
            symmetrize=False, dedup=False,
        )
