"""Distributed CSR graph: 1D node-range sharding with ghost nodes.

TPU-native counterpart of ``DistributedCSRGraph``
(kaminpar-dist/datastructures/distributed_csr_graph.h:39-100): node ranges
are contiguous per shard (the reference's ``node_distribution[]`` prefix
array); edges live with the owner of their source endpoint; off-shard
neighbors are **ghost nodes** with per-shard local slots — the analog of
``ghost_to_global[]``/``global_to_ghost`` (:39-100), built host-side instead
of with growt hash maps.

Edge targets are stored as *local slots* ``col_loc`` in
``[0, n_loc + g_loc]``: ``< n_loc`` = local node, ``< n_loc + g_loc`` =
ghost slot, ``== n_loc + g_loc`` = inert pad.  Per-round ghost values
(labels, partitions) arrive via the static-routing sparse exchange in
``exchange.py``, so per-device state is O(n_loc + m_loc + ghosts) — never
O(N).

Static-shape layout (SURVEY §7 hard part (d)):
- ``n_loc = next_pow2(ceil((n+1)/P))`` nodes per shard; padded global node
  space ``N = P * n_loc`` (> n always);
- ``m_loc = next_pow2(max shard edge count)`` edge slots per shard;
- ``g_loc = next_pow2(max shard ghost count)`` ghost slots per shard;
- flat ``(P * per_shard,)`` arrays so ``PartitionSpec('nodes')`` splits them
  into per-shard blocks;
- pad edge slots: ``u_local = 0``, ``col_loc = n_loc + g_loc``, ``w = 0``
  (zero-rating runs are never move candidates).

``dtype`` selects 32- vs 64-bit ids/weights (the reference's
KAMINPAR_64BIT_* switches, CMakeLists.txt:71-79).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import numpy as np

from functools import partial

from ..graph.csr import CSRGraph
from ..utils.intmath import next_pow2
from .exchange import build_ghost_exchange, localize_columns

_next_pow2 = partial(next_pow2, minimum=8)


class DistGraph(NamedTuple):
    """Sharded device arrays + host metadata.  Device placement happens when
    the arrays enter a pjit/shard_map computation with a 'nodes' spec; the
    NamedTuple itself is never traced."""

    node_w: jax.Array  # (P * n_loc,) node weights, pads 0
    edge_u: jax.Array  # (P * m_loc,) LOCAL row index of the source
    col_loc: jax.Array  # (P * m_loc,) LOCAL target slot (node/ghost/pad)
    edge_w: jax.Array  # (P * m_loc,) weights, pads 0
    send_idx: jax.Array  # (P * P, cap_g) ghost-exchange routing (owner side)
    recv_map: jax.Array  # (P * g_loc,) ghost-exchange routing (ghost side)
    ghost_global: tuple  # host: per-shard np arrays of ghost global ids
    n: int  # real node count
    m: int  # real (directed) edge count
    n_loc: int
    m_loc: int
    g_loc: int
    cap_g: int
    num_shards: int
    #: Per-shard static work table (round 13): tuple of P dicts with
    #: owned_nodes / owned_edges / ghost_nodes / interface_nodes, computed
    #: HOST-SIDE at build time (distribute_graph / _assemble_coarse already
    #: hold every input as numpy) — so the mesh telemetry's shard lanes and
    #: ShardStats cost ZERO device readbacks.  Empty tuple when a build
    #: path does not populate it (consumers fall back or skip).
    shard_work: tuple = ()

    @property
    def N(self) -> int:
        """Padded global node count (= P * n_loc)."""
        return self.num_shards * self.n_loc

    @property
    def dtype(self):
        return self.node_w.dtype

    def edges_global_host(self):
        """Host view of all real edges as (src_global, dst_global, weight)
        numpy arrays — gathers the device shards, localizes ghost slots via
        ghost_global.  Shared by replicate-to-host and the BFS extractor
        (keep the subtle slot->global localization in ONE place)."""
        from ..utils import sync_stats

        srcs, dsts, ws = [], [], []
        # One counted readback for the full-edge gather (round 12, kptlint
        # sync-discipline): the replicate/BFS paths pay this knowingly.
        eu, cl, ew = sync_stats.pull(
            self.edge_u, self.col_loc, self.edge_w, phase="dist_extract",
            shards=self.num_shards,
        )
        eu = eu.reshape(self.num_shards, self.m_loc)
        cl = cl.reshape(self.num_shards, self.m_loc)
        ew = ew.reshape(self.num_shards, self.m_loc)
        for s in range(self.num_shards):
            real = ew[s] > 0
            srcs.append(
                eu[s][real].astype(np.int64) + s * self.n_loc
            )
            slots = cl[s][real].astype(np.int64)
            gg = self.ghost_global[s]
            is_local = slots < self.n_loc
            # Layout invariant: every non-local slot must resolve to a ghost
            # entry.  Fail fast instead of silently clipping to the last
            # ghost (or global node 0), which would corrupt edges.
            nonlocal_slots = slots[~is_local]
            if len(gg) == 0:
                if nonlocal_slots.size:
                    raise ValueError(
                        f"shard {s}: {nonlocal_slots.size} non-local edge "
                        "slots but the shard has no ghost entries"
                    )
            elif nonlocal_slots.size and int(nonlocal_slots.max()) - self.n_loc >= len(gg):
                raise ValueError(
                    f"shard {s}: ghost slot {int(nonlocal_slots.max())} out of "
                    f"range (n_loc={self.n_loc}, ghosts={len(gg)})"
                )
            dst = np.where(
                is_local,
                slots + s * self.n_loc,
                gg[np.clip(slots - self.n_loc, 0, max(len(gg) - 1, 0))]
                if len(gg) else 0,
            )
            dsts.append(dst)
            ws.append(ew[s][real].astype(np.int64))
        return (
            np.concatenate(srcs) if srcs else np.zeros(0, np.int64),
            np.concatenate(dsts) if dsts else np.zeros(0, np.int64),
            np.concatenate(ws) if ws else np.zeros(0, np.int64),
        )

    @property
    def max_per_shard_array(self) -> int:
        """Largest per-shard device array the layout allocates — the
        weak-scaling witness asserted in tests (must stay
        O(n_loc + m_loc + ghosts), never O(N))."""
        return max(
            self.n_loc,
            self.m_loc,
            self.g_loc,
            self.num_shards * self.cap_g,  # exchange buffers / routing
        )


def compute_shard_work(
    send_idx: np.ndarray,
    ghost_global,
    owned_nodes,
    owned_edges,
    n_loc: int,
    num_shards: int,
) -> tuple:
    """Host-side per-shard work table (round 13) from build-time arrays:
    the quantities per-rank wall time proxies in the reference's dist timer
    rows (see dist/shard_stats.py for the SPMD argument).  ``send_idx`` is
    the HOST routing array (rows t*P+s hold the local slots shard t sends
    shard s; pads hold n_loc)."""
    P = num_shards
    rows = send_idx.reshape(P, P, -1)
    work = []
    for s in range(P):
        sent = rows[s][rows[s] < n_loc]
        work.append({
            "owned_nodes": int(owned_nodes[s]),
            "owned_edges": int(owned_edges[s]),
            "ghost_nodes": int(len(ghost_global[s])),
            "interface_nodes": int(len(np.unique(sent))),
        })
    return tuple(work)


def distribute_graph(
    graph: CSRGraph, num_shards: int, dtype=np.int32
) -> DistGraph:
    """Split a host CSRGraph into ``num_shards`` contiguous node ranges.

    The reference distributes by node ranges too (dkaminpar.cc ``copy_graph``
    vtxdist); balanced *edge* distribution would permute by degree first —
    callers can pre-permute with graph.csr.rearrange_by_degree_buckets.
    """
    from ..utils import sync_stats

    P = num_shards
    # The staging split reads the whole CSR once; counted as one batched
    # readback (zero-copy on the CPU backend, a real transfer on devices).
    rp, col, ew, nw = sync_stats.pull(
        graph.row_ptr, graph.col_idx, graph.edge_w, graph.node_w,
        phase="dist_build",
    )
    col = col.astype(dtype)
    ew = ew.astype(dtype)
    nw = nw.astype(dtype)
    n, m = graph.n, graph.m

    n_loc = _next_pow2((n + P) // P)  # ceil((n+1)/P) so N > n
    N = P * n_loc

    counts = [
        int(rp[min((s + 1) * n_loc, n)] - rp[min(s * n_loc, n)]) for s in range(P)
    ]
    m_loc = _next_pow2(max(max(counts), 1))

    node_w = np.zeros(N, dtype=dtype)
    node_w[:n] = nw
    edge_u = np.zeros(P * m_loc, dtype=dtype)
    edge_w = np.zeros(P * m_loc, dtype=dtype)

    deg = np.diff(rp)
    src_global = np.repeat(np.arange(n, dtype=np.int64), deg)
    col_global_per_shard, valid_per_shard = [], []
    for s in range(P):
        lo_node, hi_node = s * n_loc, min((s + 1) * n_loc, n)
        shard_col = np.zeros(m_loc, dtype=dtype)
        shard_valid = np.zeros(m_loc, dtype=bool)
        if lo_node < n:
            lo_e, hi_e = int(rp[lo_node]), int(rp[hi_node])
            cnt = hi_e - lo_e
            base = s * m_loc
            edge_u[base : base + cnt] = (src_global[lo_e:hi_e] - lo_node).astype(
                dtype
            )
            edge_w[base : base + cnt] = ew[lo_e:hi_e]
            shard_col[:cnt] = col[lo_e:hi_e]
            shard_valid[:cnt] = ew[lo_e:hi_e] > 0
        col_global_per_shard.append(shard_col)
        valid_per_shard.append(shard_valid)

    send_idx, recv_map, ghost_global, cap_g, g_loc = build_ghost_exchange(
        col_global_per_shard, valid_per_shard, n_loc, P, dtype=dtype
    )

    # Rewrite edge targets to local slots.
    col_loc = np.concatenate(
        [
            localize_columns(
                col_global_per_shard[s], valid_per_shard[s], ghost_global[s],
                s, n_loc, g_loc, dtype,
            )
            for s in range(P)
        ]
    )

    shard_work = compute_shard_work(
        send_idx, ghost_global,
        owned_nodes=[
            max(0, min((s + 1) * n_loc, n) - s * n_loc) for s in range(P)
        ],
        owned_edges=[
            int((edge_w[s * m_loc:(s + 1) * m_loc] > 0).sum()) for s in range(P)
        ],
        n_loc=n_loc, num_shards=P,
    )

    jnp = jax.numpy
    return DistGraph(
        node_w=jnp.asarray(node_w),
        edge_u=jnp.asarray(edge_u),
        col_loc=jnp.asarray(col_loc),
        edge_w=jnp.asarray(edge_w),
        send_idx=jnp.asarray(send_idx),
        recv_map=jnp.asarray(recv_map),
        ghost_global=tuple(ghost_global),
        n=n,
        m=m,
        n_loc=n_loc,
        m_loc=m_loc,
        g_loc=g_loc,
        cap_g=cap_g,
        num_shards=P,
        shard_work=shard_work,
    )
