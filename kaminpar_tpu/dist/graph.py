"""Distributed CSR graph: 1D node-range sharding over a mesh axis.

TPU-native counterpart of ``DistributedCSRGraph``
(kaminpar-dist/datastructures/distributed_csr_graph.h:39-100): node ranges are
contiguous per shard (the reference's ``node_distribution[]`` prefix array);
edges live with the owner of their source endpoint.  Instead of ghost-node
remapping + growt hash maps, neighbor ids stay *global* and per-round label
lookups read an all-gathered label table — the dense-exchange trade that fits
XLA collectives (SURVEY §5 "Distributed communication backend").

Static-shape layout (SURVEY §7 hard part (d)):
- ``n_loc = next_pow2(ceil((n+1)/P))`` nodes per shard; total padded node
  space ``N = P * n_loc`` (> n always, so ``N-1`` is a global pad "anchor");
- ``m_loc = next_pow2(max shard edge count)`` edge slots per shard;
- all arrays are flat ``(P * per_shard,)`` so ``PartitionSpec('nodes')``
  splits them into per-shard blocks;
- pad edge slots: ``u_local = 0``, ``col = N-1`` (anchor), ``w = 0`` (inert:
  zero-rating runs are never candidates);
- pad nodes: weight 0, no edges.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import numpy as np

from functools import partial

from ..graph.csr import CSRGraph
from ..utils.intmath import next_pow2

_next_pow2 = partial(next_pow2, minimum=8)


class DistGraph(NamedTuple):
    """Host container of the sharded arrays (device placement happens when
    the arrays enter a pjit/shard_map computation with a 'nodes' spec)."""

    node_w: jax.Array  # (P * n_loc,) node weights, pads 0
    edge_u: jax.Array  # (P * m_loc,) LOCAL row index of the source
    col_idx: jax.Array  # (P * m_loc,) GLOBAL neighbor id
    edge_w: jax.Array  # (P * m_loc,) weights, pads 0
    n: int  # real node count
    m: int  # real (directed) edge count
    n_loc: int
    m_loc: int
    num_shards: int

    @property
    def N(self) -> int:
        """Padded global node count (= P * n_loc)."""
        return self.num_shards * self.n_loc

    @property
    def anchor(self) -> int:
        return self.N - 1


def distribute_graph(graph: CSRGraph, num_shards: int) -> DistGraph:
    """Split a host CSRGraph into ``num_shards`` contiguous node ranges.

    The reference distributes by node ranges too (dkaminpar.cc ``copy_graph``
    vtxdist); balanced *edge* distribution would permute by degree first —
    callers can pre-permute with graph.csr.rearrange_by_degree_buckets.
    """
    P = num_shards
    rp = np.asarray(graph.row_ptr)
    col = np.asarray(graph.col_idx).astype(np.int32)
    ew = np.asarray(graph.edge_w).astype(np.int32)
    nw = np.asarray(graph.node_w).astype(np.int32)
    n, m = graph.n, graph.m

    n_loc = _next_pow2((n + P) // P)  # ceil((n+1)/P) so N > n (global anchor)
    N = P * n_loc
    anchor = N - 1

    counts = [
        int(rp[min((s + 1) * n_loc, n)] - rp[min(s * n_loc, n)]) for s in range(P)
    ]
    m_loc = _next_pow2(max(max(counts), 1))

    node_w = np.zeros(N, dtype=np.int32)
    node_w[:n] = nw
    edge_u = np.zeros(P * m_loc, dtype=np.int32)
    col_idx = np.full(P * m_loc, anchor, dtype=np.int32)
    edge_w = np.zeros(P * m_loc, dtype=np.int32)

    deg = np.diff(rp)
    src_global = np.repeat(np.arange(n, dtype=np.int64), deg)
    for s in range(P):
        lo_node, hi_node = s * n_loc, min((s + 1) * n_loc, n)
        if lo_node >= n:
            continue
        lo_e, hi_e = int(rp[lo_node]), int(rp[hi_node])
        cnt = hi_e - lo_e
        base = s * m_loc
        edge_u[base : base + cnt] = (src_global[lo_e:hi_e] - lo_node).astype(np.int32)
        col_idx[base : base + cnt] = col[lo_e:hi_e]
        edge_w[base : base + cnt] = ew[lo_e:hi_e]

    return DistGraph(
        node_w=jax.numpy.asarray(node_w),
        edge_u=jax.numpy.asarray(edge_u),
        col_idx=jax.numpy.asarray(col_idx),
        edge_w=jax.numpy.asarray(edge_w),
        n=n,
        m=m,
        n_loc=n_loc,
        m_loc=m_loc,
        num_shards=P,
    )
