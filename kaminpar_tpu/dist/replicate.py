"""Mesh splitting: concurrent best-of-R replica refinement on sub-meshes.

Reference: dist deep multilevel PE-splitting
(``kaminpar-dist/partitioning/deep_multilevel.cc:80-96`` +
``graphutils/replicator.cc``): when the coarse graph is small relative to the
PE count, the communicator is split into R groups, each group replicates the
graph and partitions independently, and the best result wins
(``distribute_best_partition``).

TPU redesign: the 1D ``('nodes',)`` mesh of P devices reshapes to a
``('rep', 'nodes')`` mesh of (R, P//R); graph arrays are *replicated* across
``rep`` and sharded across ``nodes``; candidate partitions carry a leading
replica dimension.  The existing per-shard LP refinement round body runs
unchanged inside the 2D shard_map — its collectives name only the ``nodes``
axis, so every psum/all_to_all stays inside one replica group by
construction.  Per-replica cuts psum over ``nodes`` and selection is an
argmin over the replica dimension: R independent refinement+selection runs
in ONE device program, no host threads.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .exchange import AXIS, ghost_exchange, psum
from .lp import _neighbor_labels, _refine_round_body

REP_AXIS = "rep"


def split_mesh(mesh: Mesh, R: int) -> Mesh:
    """Reshape a 1D ('nodes',) mesh into ('rep', 'nodes') = (R, P//R)."""
    devs = mesh.devices.reshape(-1)
    S = len(devs) // R
    if S < 1:
        raise ValueError(f"cannot split {len(devs)} devices into {R} groups")
    return Mesh(devs[: R * S].reshape(R, S), (REP_AXIS, AXIS))


@lru_cache(maxsize=None)
def make_replicated_refine(mesh2: Mesh, *, num_labels: int, num_rounds: int):
    """R replica groups refine their own candidate labels concurrently and
    report per-replica cuts; one jitted program."""

    @partial(
        jax.shard_map,
        mesh=mesh2,
        in_specs=(P(), P(REP_AXIS, AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                  P(), P(AXIS), P(AXIS)),
        out_specs=(P(REP_AXIS, AXIS), P(REP_AXIS)),
    )
    def fn(key, labels2, node_w, edge_u, col_loc, edge_w, max_w, send_idx,
           recv_map):
        rep = jax.lax.axis_index(REP_AXIS)
        lab = labels2[0]  # (n_loc,) — this group's replica
        krep = jax.random.fold_in(key, rep)

        def body(i, lab):
            lab, _ = _refine_round_body(
                jax.random.fold_in(krep, i), lab, node_w, edge_u, col_loc,
                edge_w, max_w, send_idx, recv_map, jnp.int32(0), jnp.int32(i),
                num_labels=num_labels, external_only=False,
            )
            return lab

        lab = jax.lax.fori_loop(0, num_rounds, body, lab)
        # Per-replica cut (double-counted; halved by the caller), psum'd only
        # over this group's 'nodes' axis.
        ghosts = ghost_exchange(
            lab, send_idx, recv_map, fill=jnp.asarray(0, lab.dtype)
        )
        nbr = _neighbor_labels(lab, ghosts, col_loc, 0)
        own = lab[edge_u]
        cut2 = psum(
            jnp.sum(jnp.where(own != nbr, edge_w, 0)), AXIS
        )
        return lab[None, :], cut2[None]

    return jax.jit(fn)


def refine_replicated(mesh: Mesh, key, parts_R: np.ndarray, coarse_host,
                      max_w, *, k: int, num_rounds: int, dtype=np.int32):
    """Refine R candidate partitions of ``coarse_host`` concurrently on R
    disjoint sub-meshes of ``mesh``; return (best_part, per_replica_cuts).

    ``parts_R`` is (R, n) host labels.  The graph is re-sharded over the
    P//R 'nodes' shards of each group (replicated across groups); ``dtype``
    must match the pipeline's id/weight width (int64 under use_64bit_ids —
    silent int32 wrapping of accumulated coarse weights would corrupt the
    balance decisions and cuts)."""
    from .graph import distribute_graph

    R = parts_R.shape[0]
    mesh2 = split_mesh(mesh, R)
    S = mesh2.devices.shape[1]
    dg = distribute_graph(coarse_host, S, dtype=dtype)
    labels2 = np.zeros((R, dg.N), dtype=np.int32)
    labels2[:, : coarse_host.n] = parts_R[:, : coarse_host.n]

    rep_sh = NamedSharding(mesh2, P(REP_AXIS, AXIS))
    node_sh = NamedSharding(mesh2, P(AXIS))
    labels_dev = jax.device_put(jnp.asarray(labels2), rep_sh)
    args = [
        jax.device_put(a, node_sh)
        for a in (dg.node_w, dg.edge_u, dg.col_loc, dg.edge_w, dg.send_idx,
                  dg.recv_map)
    ]
    fn = make_replicated_refine(mesh2, num_labels=k, num_rounds=num_rounds)
    out_labels, cuts2 = fn(
        key, labels_dev, args[0], args[1], args[2], args[3],
        jnp.asarray(max_w), args[4], args[5],
    )
    from ..utils import sync_stats

    # Two counted readbacks: the tiny (R,) cut vector first, then ONLY the
    # winning label row — pulling the whole (R, N) stack would be an R-fold
    # bandwidth regression on the best-of-R path.
    cuts = sync_stats.pull(cuts2, shards=mesh.size) // 2
    best = int(np.argmin(cuts))
    return sync_stats.pull(out_labels[best], shards=S)[: coarse_host.n], cuts
