"""Distributed JET refiner.

Reference: ``kaminpar-dist/refinement/jet/jet_refiner.cc`` (503 LoC) +
``snapshooter.cc`` — the shm JET loop (find / filter / execute / rebalance
/ best-snapshot, see refinement/jet.py) run bulk-synchronously over the
sharded graph: per iteration each shard computes its candidates against
ghost labels, the filter's pessimistic gains need the *neighbors'*
(gain, target) pairs, which ride one extra ghost exchange, moves execute
unconditionally, the node balancer repairs balance, and the best feasible
partition snapshot is kept (snapshooter.cc's role).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ..ops.bucketed_gains import flat_best_moves, lookup
from .balancer import dist_balance
from .exchange import AXIS, ghost_exchange, psum
from .lp import _neighbor_labels
from .metrics import dist_edge_cut


def _jet_round_body(
    key, labels_loc, locked_loc, node_w_loc, edge_u, col_loc, edge_w, max_w,
    send_idx, recv_map, temp, *, num_labels: int
):
    idx = jax.lax.axis_index(AXIS)
    kr = jax.random.fold_in(jax.random.fold_in(key, 1), idx)
    n_loc = labels_loc.shape[0]

    ghost_labels = ghost_exchange(
        labels_loc, send_idx, recv_map, fill=jnp.asarray(0, labels_loc.dtype)
    )
    cand = _neighbor_labels(labels_loc, ghost_labels, col_loc, 0)

    cluster_w = psum(
        jax.ops.segment_sum(
            node_w_loc, labels_loc.astype(jnp.int32), num_segments=num_labels
        ),
        AXIS,
    )

    # --- find: best external block, caps ignored (jet_refiner.cc:104-132)
    target, tconn, own_conn, has = flat_best_moves(
        kr, edge_u, cand, edge_w, labels_loc, node_w_loc,
        cluster_w, max_w, num_rows=n_loc,
        external_only=True, respect_caps=False,
    )
    gain = tconn - own_conn
    threshold = -jnp.floor(temp * own_conn.astype(jnp.float32)).astype(gain.dtype)
    cand_mask = has & ~locked_loc & (gain > threshold)

    # --- filter: pessimistic gain assuming higher-priority neighbors move.
    # Neighbors' (gain, candidacy, target) ride the ghost exchange; the
    # priority rule (gain_v > gain_u, ties by global id) is computable from
    # exchanged values + known slot ordering.
    gid_loc = (idx * n_loc + jnp.arange(n_loc)).astype(jnp.int32)
    fill_i = jnp.asarray(-(2**31) + 1, jnp.int32)
    nbr_gain = _neighbor_labels(
        gain, ghost_exchange(gain, send_idx, recv_map, fill=fill_i), col_loc, fill_i
    )
    nbr_cand = _neighbor_labels(
        cand_mask,
        ghost_exchange(cand_mask, send_idx, recv_map, fill=jnp.asarray(False)),
        col_loc, False,
    )
    nbr_target = _neighbor_labels(
        target,
        ghost_exchange(target, send_idx, recv_map, fill=jnp.asarray(0, target.dtype)),
        col_loc, 0,
    )
    nbr_gid = _neighbor_labels(
        gid_loc,
        ghost_exchange(gid_loc, send_idx, recv_map, fill=jnp.asarray(-1, jnp.int32)),
        col_loc, -1,
    )

    u_gain = gain[edge_u]
    u_gid = gid_loc[edge_u]
    v_first = nbr_cand & (
        (nbr_gain > u_gain) | ((nbr_gain == u_gain) & (nbr_gid < u_gid))
    )
    eff_v = jnp.where(v_first, nbr_target, cand)  # cand == current nbr label view
    contrib = jnp.where(eff_v == target[edge_u], edge_w, 0) - jnp.where(
        eff_v == labels_loc[edge_u], edge_w, 0
    )
    gain2 = jax.ops.segment_sum(contrib, edge_u, num_segments=n_loc)
    move = cand_mask & (gain2 > 0)

    new_labels = jnp.where(move, target, labels_loc)
    return new_labels, move


@lru_cache(maxsize=None)
def make_dist_jet_round(mesh: Mesh, *, num_labels: int):
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                  P(), P(AXIS), P(AXIS), P()),
        out_specs=(P(AXIS), P(AXIS)),
    )
    def round_fn(key, labels, locked, node_w, edge_u, col_loc, edge_w,
                 max_w, send_idx, recv_map, temp):
        return _jet_round_body(
            key, labels, locked, node_w, edge_u, col_loc, edge_w, max_w,
            send_idx, recv_map, temp, num_labels=num_labels,
        )

    return jax.jit(round_fn)


def dist_jet_iterate(mesh, key, labels, graph, max_w, *, num_labels: int,
                     num_iterations: int = 12, num_fruitless: int = 12,
                     temp0: float = 0.25, temp1: float = 0.25):
    """Full dist JET loop with balancing + best-feasible snapshot.

    Snapshot rule (snapshooter.cc): a feasible partition always beats an
    infeasible one; among feasible ones, lower cut wins — so an infeasible
    seed can never shadow later feasible candidates."""
    fn = make_dist_jet_round(mesh, num_labels=num_labels)

    labels, feas0 = dist_balance(mesh, key, labels, graph, max_w, k=num_labels)
    best = labels
    best_cut = dist_edge_cut(mesh, labels, graph, k=num_labels)
    best_feasible = bool(feas0)
    locked = jnp.zeros(labels.shape, dtype=bool)
    fruitless = 0
    for it in range(num_iterations):
        frac = it / max(num_iterations - 1, 1)
        temp = jnp.float32(temp0 + (temp1 - temp0) * frac)
        labels, moved = fn(
            jax.random.fold_in(key, it), labels, locked, graph.node_w,
            graph.edge_u, graph.col_loc, graph.edge_w, max_w,
            graph.send_idx, graph.recv_map, temp,
        )
        locked = moved
        labels, feas = dist_balance(
            mesh, jax.random.fold_in(key, 1000 + it), labels, graph, max_w,
            k=num_labels,
        )
        feas = bool(feas)
        cut = dist_edge_cut(mesh, labels, graph, k=num_labels)
        accept = (feas and not best_feasible) or (
            feas == best_feasible and cut <= best_cut
        )
        if accept:
            if best_cut - cut <= 0.001 * max(best_cut, 1) and feas == best_feasible:
                fruitless += 1
            else:
                fruitless = 0
            best, best_cut, best_feasible = labels, cut, feas
        else:
            fruitless += 1
        if fruitless >= num_fruitless:
            break
    return best, best_cut
