"""Sharded partition extension — the dist half of the device-side redesign.

Reference: dist ``extend partition`` gathers block-induced subgraphs
(``kaminpar-dist/graphutils/subgraph_extractor.cc``) and partitions them with
the shm initial partitioner.  Until round 5 our dist pipeline replicated the
WHOLE level graph to host per extension level
(``dist/partitioner.py _replicate_to_host`` — the biggest host-residency
violation, VERDICT r4 missing #4).  This module keeps extension sharded:

1. **Restricted sharded coarsening**: cluster with cross-block edge weights
   masked to 0 (blocks = the current cur_k partition), so clusters never
   span blocks — the sharded analog of shm v-cycle community masking.
   Clustering runs on the masked weights; contraction uses the true ones.
   Coarse-node block ids derive from two ``owner_aggregate`` rounds
   (sum + count of per-cluster-equal values).
2. **Host extension of the nested coarsest only**: O(target_n) gather,
   independent of the level size, through the existing host pool machinery.
3. **Restricted sharded uncoarsening**: project up; per level, refine with
   the dist LP rounds over the masked weights and the intermediate new-k
   budgets — candidates can never leave the parent block because masked
   ratings are 0 and the engine requires rating > 0.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..utils import RandomState, sync_stats
from ..utils.intmath import next_pow2
from ..utils.logger import Logger, OutputLevel
from .contraction import contract_dist_clustering, project_partition_up
from .exchange import AXIS, ghost_exchange, owner_aggregate
from .lp import _neighbor_labels, dist_cluster_iterate, dist_lp_iterate


@lru_cache(maxsize=None)
def make_edge_mask(mesh: Mesh):
    """Per-shard cross-block edge-weight mask: w -> 0 where the endpoints'
    blocks differ (ghost blocks via the static exchange)."""

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
        out_specs=P(AXIS),
    )
    def fn(comm, edge_u, col_loc, edge_w, send_idx, recv_map):
        ghosts = ghost_exchange(
            comm, send_idx, recv_map, fill=jnp.asarray(-1, comm.dtype)
        )
        nbr = _neighbor_labels(comm, ghosts, col_loc, -1)
        return jnp.where(comm[edge_u] == nbr, edge_w, 0)

    return jax.jit(fn)


@lru_cache(maxsize=None)
def make_comm_down(mesh: Mesh, *, n_loc_c: int, cap_q: int):
    """Coarse-node block ids from fine ones: clusters never span blocks, so
    sum/count of (equal) member values at the coarse owner recovers the
    value exactly."""

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P()),
    )
    def fn(coarse_of_loc, comm_loc, node_w_loc):
        drop = node_w_loc <= 0  # pads (coarse_of is -1 there)
        # +1 biases comm 0 away from the empty-sum 0
        s, ovf1 = owner_aggregate(
            jnp.where(drop, 0, coarse_of_loc),
            jnp.where(drop, 0, comm_loc + 1), drop, n_loc_c, cap_q,
        )
        c, ovf2 = owner_aggregate(
            jnp.where(drop, 0, coarse_of_loc),
            jnp.where(drop, 0, jnp.ones_like(comm_loc)), drop, n_loc_c, cap_q,
        )
        comm_c = jnp.where(c > 0, s // jnp.maximum(c, 1) - 1, 0)
        return comm_c.astype(comm_loc.dtype), ovf1 + ovf2

    return jax.jit(fn)


def _comm_down(mesh, coarse_of, comm, node_w, *, n_loc_c: int, n_loc: int,
               num_shards: int):
    cap_q = min(next_pow2(max(64, 2 * n_loc // max(num_shards, 1)), 8), n_loc)
    while True:
        comm_c, ovf = make_comm_down(mesh, n_loc_c=n_loc_c, cap_q=cap_q)(
            coarse_of, comm, node_w
        )
        # Counted overflow readback (round 13; was an implicit int() pull).
        if int(sync_stats.pull(ovf, shards=num_shards)) == 0 or cap_q >= n_loc:
            return comm_c
        cap_q = min(cap_q * 2, n_loc)


def dist_extend_partition(mesh, part_dev, dgraph, cur_k: int, target_k: int,
                          ctx, final_bw, replicate_to_host):
    """Extend a sharded cur_k partition to target_k without gathering the
    level graph; returns the sharded (N,) new-k partition."""
    from ..partitioning.deep import _extend_partition_host
    from ..partitioning.partition_utils import intermediate_block_weights

    ipc = ctx.initial_partitioning
    C = ctx.coarsening.contraction_limit
    target_n = max(target_k * ipc.device_extension_cpb, 2 * C)
    eps = ctx.partition.epsilon

    mask_fn = make_edge_mask(mesh)
    levels = []  # (fine graph, coarse_of, coarse n_loc, fine comm)
    cur = dgraph
    comm = jnp.asarray(part_dev, dtype=jnp.int32)
    total_w = None
    while cur.n > target_n:
        masked = mask_fn(comm, cur.edge_u, cur.col_loc, cur.edge_w,
                         cur.send_idx, cur.recv_map)
        mg = cur._replace(edge_w=masked)
        if total_w is None:
            total_w = int(
                sync_stats.pull(jnp.sum(cur.node_w), shards=cur.num_shards)
            )
        max_cw = max(
            int(eps * total_w / max(min(cur.n // max(C, 1), target_k), 2)), 1
        )
        lab = jnp.arange(cur.N, dtype=cur.dtype)
        from .lp import shard_arrays

        lab, mg = shard_arrays(mesh, mg, lab)
        lab, _ = dist_cluster_iterate(
            mesh, RandomState.next_key(), lab, mg,
            jnp.asarray(max_cw, cur.dtype),
            num_rounds=ctx.coarsening.lp.num_iterations,
        )
        coarse, coarse_of, n_c = contract_dist_clustering(mesh, cur, lab)
        if n_c < target_k or 1.0 - n_c / max(cur.n, 1) < \
                ctx.coarsening.convergence_threshold:
            break
        comm_c = _comm_down(
            mesh, coarse_of, comm, cur.node_w, n_loc_c=coarse.n_loc,
            n_loc=cur.n_loc, num_shards=cur.num_shards,
        )
        levels.append((cur, coarse_of, coarse.n_loc, comm))
        cur, comm = coarse, comm_c
        Logger.log(
            f"  dist device-ext: coarsened to n={cur.n} "
            f"(level {len(levels)})", OutputLevel.DEBUG,
        )

    # Host extension of the nested coarsest only (O(target_n) gather).
    import copy as _copy

    host = replicate_to_host(cur)
    comm_host = np.asarray(comm)[: cur.n].astype(np.int32)
    ext_ctx = _copy.deepcopy(ctx)
    ext_ctx.partition.k = len(final_bw)
    ext_ctx.partition.max_block_weights = np.asarray(final_bw, dtype=np.int64)  # kpt: ignore[sync-discipline] — final_bw is host np
    part_host = _extend_partition_host(
        host, comm_host, cur_k, target_k, ext_ctx
    )
    full = np.zeros(cur.N, dtype=np.int32)
    full[: cur.n] = part_host
    part = jnp.asarray(full)

    cap = jnp.asarray(
        intermediate_block_weights(
            np.asarray(final_bw, dtype=np.int64), target_k  # kpt: ignore[sync-discipline] — final_bw is host np
        ),
        dtype=dgraph.dtype,
    )
    from .lp import shard_arrays

    while True:
        part, curg = shard_arrays(mesh, cur, part)
        # restricted refinement: masked weights keep moves inside parents
        masked = mask_fn(
            comm, curg.edge_u, curg.col_loc, curg.edge_w, curg.send_idx,
            curg.recv_map,
        )
        part, _ = dist_lp_iterate(
            mesh, RandomState.next_key(), part, curg._replace(edge_w=masked),
            cap, num_labels=target_k,
            num_rounds=ctx.refinement.lp.num_iterations, external_only=False,
            num_chunks=max(ctx.refinement.dist_num_chunks, 1),
        )
        if not levels:
            break
        fine, coarse_of, n_loc_c, comm = levels.pop()
        part = project_partition_up(mesh, coarse_of, part, n_loc_c=n_loc_c)
        cur = fine
    return part
