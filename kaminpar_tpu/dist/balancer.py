"""Distributed node balancer: repair infeasible partitions across shards.

Reference: ``kaminpar-dist/refinement/balancer/node_balancer.cc`` (829 LoC) —
per-PE candidate PQs of relative-gain moves out of overloaded blocks, a
binary-reduction-tree combine, probabilistic move application.  TPU
re-design as bulk-synchronous mesh rounds (block weights are a replicated
``(k,)`` table, like the reference's replicated block weights):

1. every node in an overloaded block picks its best *feasible* external
   target (highest connection via the shared flat kernel; fallback: the
   globally lightest block with room),
2. **source admission** is probabilistic with p = overload_b / global
   candidate weight of block b (the reference's probabilistic commitment,
   node_balancer.cc's ``perform_moves`` — a psum replaces the reduction
   tree),
3. **target admission** re-uses the refinement rollback fixpoint so no
   receiver block ends overweight.

Rounds repeat (host loop) until feasible or the round budget is exhausted;
each round is one XLA dispatch.  Unlike LP refinement this accepts
negative-gain moves — it exists to restore feasibility, which capacity-
respecting LP can never do (VERDICT r1 weak #4).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from functools import lru_cache

from ..ops.bucketed_gains import flat_best_moves
from .exchange import AXIS, ghost_exchange
from .lp import _neighbor_labels


def _balance_round_body(
    key, labels_loc, node_w_loc, edge_u, col_loc, edge_w, max_bw, send_idx,
    recv_map, *, k: int
):
    idx = jax.lax.axis_index(AXIS)
    kshard = jax.random.fold_in(key, idx)
    kr, kp, kf, kt = jax.random.split(kshard, 4)
    n_loc = labels_loc.shape[0]
    real = node_w_loc > 0

    ghost_labels = ghost_exchange(
        labels_loc, send_idx, recv_map, fill=jnp.asarray(0, labels_loc.dtype)
    )
    cand = _neighbor_labels(labels_loc, ghost_labels, col_loc, 0)

    block_w = jax.lax.psum(
        jax.ops.segment_sum(
            node_w_loc, labels_loc.astype(jnp.int32), num_segments=k
        ),
        AXIS,
    )
    overload = jnp.maximum(block_w - max_bw, 0)
    over_b = overload > 0

    target, tconn, oconn, has = flat_best_moves(
        kr, edge_u, cand, edge_w, labels_loc, node_w_loc, block_w, max_bw,
        num_rows=n_loc, external_only=True, respect_caps=True,
    )
    mover = over_b[labels_loc] & real

    # Fallback for movers with no adjacent feasible target: a random
    # underloaded block sampled ∝ remaining capacity, so a flood out of one
    # giant block spreads over all receivers instead of drowning the single
    # lightest one.
    remaining = jnp.maximum(max_bw - block_w, 0)
    cdf = jnp.cumsum(remaining.astype(jnp.float32))
    r = jax.random.uniform(kf, (n_loc,)) * jnp.maximum(cdf[-1], 1e-9)
    fb = jnp.searchsorted(cdf, r).astype(labels_loc.dtype)
    fb = jnp.clip(fb, 0, k - 1)
    fallback_ok = (remaining[fb] >= node_w_loc) & (fb != labels_loc)
    use_fb = mover & ~has & fallback_ok
    target = jnp.where(use_fb, fb, target)
    eligible = mover & (has | use_fb) & (target != labels_loc)

    # Probabilistic source release: p_b = overload_b / global candidate
    # weight of b (candidates above the needed weight are thinned out).
    cand_w = jax.lax.psum(
        jax.ops.segment_sum(
            jnp.where(eligible, node_w_loc, 0),
            labels_loc.astype(jnp.int32),
            num_segments=k,
        ),
        AXIS,
    )
    p_src = jnp.where(
        cand_w > 0, overload.astype(jnp.float32) / jnp.maximum(cand_w, 1), 0.0
    )
    u = jax.random.uniform(kp, (n_loc,))
    picked = eligible & (u < jnp.clip(p_src[labels_loc] * 1.5, 0.0, 1.0))

    # Target-side probabilistic thinning: accept ∝ remaining capacity /
    # global demand, so receivers are not flooded past their cap before the
    # rollback fixpoint (which is all-or-nothing per block) runs.
    demand = jax.lax.psum(
        jax.ops.segment_sum(
            jnp.where(picked, node_w_loc, 0),
            target.astype(jnp.int32),
            num_segments=k,
        ),
        AXIS,
    )
    p_tgt = jnp.where(
        demand > 0, remaining.astype(jnp.float32) / jnp.maximum(demand, 1), 1.0
    )
    u2 = jax.random.uniform(kt, (n_loc,))
    commit = picked & (u2 < jnp.clip(p_tgt[target], 0.0, 1.0))

    # Target admission: rollback fixpoint so no receiver ends overweight —
    # but blocks that were *already* overweight without arrivals are the
    # next round's problem, not a reason to spin.
    def overweight_fixable(kept):
        w = jax.lax.psum(
            jax.ops.segment_sum(
                node_w_loc,
                jnp.where(kept, target, labels_loc).astype(jnp.int32),
                num_segments=k,
            ),
            AXIS,
        )
        arrivals = jax.lax.psum(
            jax.ops.segment_sum(
                kept.astype(jnp.int32),
                target.astype(jnp.int32),
                num_segments=k,
            ),
            AXIS,
        )
        return (w > max_bw) & (arrivals > 0)

    def cond(carry):
        _, ow = carry
        return jnp.any(ow)

    def body(carry):
        kept, ow = carry
        kept = kept & ~ow[target]
        return kept, overweight_fixable(kept)

    kept, _ = jax.lax.while_loop(cond, body, (commit, overweight_fixable(commit)))
    new_labels = jnp.where(kept, target, labels_loc)
    new_bw = jax.lax.psum(
        jax.ops.segment_sum(
            node_w_loc, new_labels.astype(jnp.int32), num_segments=k
        ),
        AXIS,
    )
    moved = jax.lax.psum(jnp.sum(kept).astype(jnp.int32), AXIS)
    still = jnp.any(new_bw > max_bw)
    return new_labels, moved, still


@lru_cache(maxsize=None)
def make_dist_balance_round(mesh: Mesh, *, k: int):
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(),
                  P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(), P()),
    )
    def round_fn(key, labels, node_w, edge_u, col_loc, edge_w, max_bw,
                 send_idx, recv_map):
        return _balance_round_body(
            key, labels, node_w, edge_u, col_loc, edge_w, max_bw,
            send_idx, recv_map, k=k,
        )

    return jax.jit(round_fn)


def dist_balance(mesh, key, labels, graph, max_bw, *, k: int,
                 max_rounds: int = 16):
    """Drive balance rounds until feasible or the budget is exhausted.

    Returns (labels, feasible).  ``max_bw`` is a (k,) block-weight cap."""
    fn = make_dist_balance_round(mesh, k=k)
    feasible = False
    dry = 0
    for i in range(max_rounds):
        labels, moved, still = fn(
            jax.random.fold_in(key, i), labels, graph.node_w, graph.edge_u,
            graph.col_loc, graph.edge_w, max_bw, graph.send_idx,
            graph.recv_map,
        )
        if not bool(still):
            feasible = True
            break
        # A probabilistic round can legitimately move nothing once; only
        # consecutive dry rounds mean stuck (cluster-balancer territory in
        # the reference).
        dry = dry + 1 if int(moved) == 0 else 0
        if dry >= 3:
            break
    return labels, feasible
