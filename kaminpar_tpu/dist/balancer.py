"""Distributed node balancer: repair infeasible partitions across shards.

Reference: ``kaminpar-dist/refinement/balancer/node_balancer.cc`` (829 LoC) —
per-PE candidate PQs of relative-gain moves out of overloaded blocks, a
binary-reduction-tree combine, probabilistic move application.  TPU
re-design as bulk-synchronous mesh rounds (block weights are a replicated
``(k,)`` table, like the reference's replicated block weights):

1. every node in an overloaded block picks its best *feasible* external
   target (highest connection via the shared flat kernel; fallback: the
   globally lightest block with room),
2. **source admission** is probabilistic with p = overload_b / global
   candidate weight of block b (the reference's probabilistic commitment,
   node_balancer.cc's ``perform_moves`` — a psum replaces the reduction
   tree),
3. **target admission** re-uses the refinement rollback fixpoint so no
   receiver block ends overweight.

Rounds repeat (host loop) until feasible or the round budget is exhausted;
each round is one XLA dispatch.  Unlike LP refinement this accepts
negative-gain moves — it exists to restore feasibility, which capacity-
respecting LP can never do (VERDICT r1 weak #4).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from functools import lru_cache

from ..ops.bucketed_gains import flat_best_moves
from .exchange import AXIS, ghost_exchange, pmax, psum
from .lp import _neighbor_labels


def _balance_round_body(
    key, labels_loc, node_w_loc, edge_u, col_loc, edge_w, max_bw, send_idx,
    recv_map, *, k: int
):
    idx = jax.lax.axis_index(AXIS)
    kshard = jax.random.fold_in(key, idx)
    kr, kp, kf, kt = jax.random.split(kshard, 4)
    n_loc = labels_loc.shape[0]
    real = node_w_loc > 0

    ghost_labels = ghost_exchange(
        labels_loc, send_idx, recv_map, fill=jnp.asarray(0, labels_loc.dtype)
    )
    cand = _neighbor_labels(labels_loc, ghost_labels, col_loc, 0)

    block_w = psum(
        jax.ops.segment_sum(
            node_w_loc, labels_loc.astype(jnp.int32), num_segments=k
        ),
        AXIS,
    )
    overload = jnp.maximum(block_w - max_bw, 0)
    over_b = overload > 0

    target, tconn, oconn, has = flat_best_moves(
        kr, edge_u, cand, edge_w, labels_loc, node_w_loc, block_w, max_bw,
        num_rows=n_loc, external_only=True, respect_caps=True,
    )
    mover = over_b[labels_loc] & real

    # Fallback for movers with no adjacent feasible target: a random
    # underloaded block sampled ∝ remaining capacity, so a flood out of one
    # giant block spreads over all receivers instead of drowning the single
    # lightest one.
    remaining = jnp.maximum(max_bw - block_w, 0)
    cdf = jnp.cumsum(remaining.astype(jnp.float32))
    r = jax.random.uniform(kf, (n_loc,)) * jnp.maximum(cdf[-1], 1e-9)
    fb = jnp.searchsorted(cdf, r).astype(labels_loc.dtype)
    fb = jnp.clip(fb, 0, k - 1)
    fallback_ok = (remaining[fb] >= node_w_loc) & (fb != labels_loc)
    use_fb = mover & ~has & fallback_ok
    target = jnp.where(use_fb, fb, target)
    eligible = mover & (has | use_fb) & (target != labels_loc)

    # Probabilistic source release: p_b = overload_b / global candidate
    # weight of b (candidates above the needed weight are thinned out).
    cand_w = psum(
        jax.ops.segment_sum(
            jnp.where(eligible, node_w_loc, 0),
            labels_loc.astype(jnp.int32),
            num_segments=k,
        ),
        AXIS,
    )
    p_src = jnp.where(
        cand_w > 0, overload.astype(jnp.float32) / jnp.maximum(cand_w, 1), 0.0
    )
    u = jax.random.uniform(kp, (n_loc,))
    picked = eligible & (u < jnp.clip(p_src[labels_loc] * 1.5, 0.0, 1.0))

    # Target-side probabilistic thinning: accept ∝ remaining capacity /
    # global demand, so receivers are not flooded past their cap before the
    # rollback fixpoint (which is all-or-nothing per block) runs.
    demand = psum(
        jax.ops.segment_sum(
            jnp.where(picked, node_w_loc, 0),
            target.astype(jnp.int32),
            num_segments=k,
        ),
        AXIS,
    )
    p_tgt = jnp.where(
        demand > 0, remaining.astype(jnp.float32) / jnp.maximum(demand, 1), 1.0
    )
    u2 = jax.random.uniform(kt, (n_loc,))
    commit = picked & (u2 < jnp.clip(p_tgt[target], 0.0, 1.0))

    # Target admission: rollback fixpoint so no receiver ends overweight —
    # but blocks that were *already* overweight without arrivals are the
    # next round's problem, not a reason to spin.
    def overweight_fixable(kept):
        w = psum(
            jax.ops.segment_sum(
                node_w_loc,
                jnp.where(kept, target, labels_loc).astype(jnp.int32),
                num_segments=k,
            ),
            AXIS,
        )
        arrivals = psum(
            jax.ops.segment_sum(
                kept.astype(jnp.int32),
                target.astype(jnp.int32),
                num_segments=k,
            ),
            AXIS,
        )
        return (w > max_bw) & (arrivals > 0)

    def cond(carry):
        _, ow = carry
        return jnp.any(ow)

    def body(carry):
        kept, ow = carry
        kept = kept & ~ow[target]
        return kept, overweight_fixable(kept)

    kept, _ = jax.lax.while_loop(cond, body, (commit, overweight_fixable(commit)))
    new_labels = jnp.where(kept, target, labels_loc)
    new_bw = psum(
        jax.ops.segment_sum(
            node_w_loc, new_labels.astype(jnp.int32), num_segments=k
        ),
        AXIS,
    )
    moved = psum(jnp.sum(kept).astype(jnp.int32), AXIS)
    still = jnp.any(new_bw > max_bw)
    # Packed (moved, still) round stats: the drive loop reads both in ONE
    # counted mesh-wide pull per round (round 13; the shm balancer has
    # packed its round stats since PR 2).
    return new_labels, jnp.stack([moved, still.astype(jnp.int32)])


def _cluster_balance_round_body(
    key, labels_loc, node_w_loc, edge_u, col_loc, edge_w, max_bw, send_idx,
    recv_map, *, k: int, grow_rounds: int = 3
):
    """One cluster-balance round (the node balancer's stuck escalation).

    Reference: ``cluster_balancer.cc`` (1 075 LoC) + ``clusters.cc`` (627):
    grow weight-bounded clusters from nodes of overloaded blocks (the
    reference builds them PE-locally too), rate each cluster's best target
    block, and move whole clusters.  Where the node balancer commits
    probabilistically (and can thrash when receivers only have room for
    specific weight combinations — its dry-round stuck case), this phase is
    deterministic-greedy like the reference's *sequential* rounds
    (ClusterBalancer::Statistics::num_seq_rounds): per overloaded block,
    the single best-relative-gain fitting cluster moves per round, so every
    round makes progress or proves none is possible.

    Shard-local clusters, global block weights via psum; the receiver-side
    rollback fixpoint is shared with the node round.
    """
    idx = jax.lax.axis_index(AXIS)
    kshard = jax.random.fold_in(key, idx)
    kg, kc = jax.random.split(kshard)
    n_loc = labels_loc.shape[0]
    real = node_w_loc > 0

    block_w = psum(
        jax.ops.segment_sum(
            node_w_loc, labels_loc.astype(jnp.int32), num_segments=k
        ),
        AXIS,
    )
    overload = jnp.maximum(block_w - max_bw, 0)
    over_b = overload > 0
    remaining = jnp.maximum(max_bw - block_w, 0)
    in_over = over_b[labels_loc] & real

    # -- grow clusters among same-block local nodes of overloaded blocks --
    # Weight cap: a cluster must fit the roomiest receiver and should not
    # overshoot its own block's overload (clusters.cc bounds growth by the
    # per-block overload as well).
    cap = jnp.maximum(
        jnp.minimum(jnp.max(remaining), jnp.max(jnp.where(over_b, overload, 0))),
        1,
    ).astype(node_w_loc.dtype)
    local_nbr = col_loc < n_loc
    src_block = labels_loc[edge_u]
    nbr_local = jnp.clip(col_loc, 0, n_loc - 1)
    same_block = local_nbr & (labels_loc[nbr_local] == src_block)
    grow_w = jnp.where(same_block & in_over[edge_u], edge_w, 0)

    clabels = jnp.arange(n_loc, dtype=labels_loc.dtype)
    for g in range(grow_rounds):
        cw = jax.ops.segment_sum(node_w_loc, clabels, num_segments=n_loc)
        cand_cl = clabels[nbr_local]
        target_cl, tconn, _, has = flat_best_moves(
            jax.random.fold_in(kg, g), edge_u, cand_cl, grow_w, clabels,
            node_w_loc, cw, cap, num_rows=n_loc,
            external_only=True, respect_caps=True,
        )
        # Only singleton clusters join (LP-style adoption); the auction
        # keeps merged weights under the cap even for simultaneous joiners.
        from ..ops.lp import capacity_auction

        singleton = cw[clabels] == node_w_loc
        mover = in_over & has & singleton & (target_cl != clabels)
        accept = capacity_auction(
            jax.random.fold_in(kg, 100 + g), mover, target_cl, node_w_loc,
            cw, cap, n_loc,
        )
        clabels = jnp.where(mover & accept, target_cl, clabels)

    # -- rate clusters: best external block by connection ------------------
    cw = jax.ops.segment_sum(node_w_loc, clabels, num_segments=n_loc)
    cl_block = jax.ops.segment_max(
        jnp.where(real, labels_loc, 0), clabels, num_segments=n_loc
    ).astype(labels_loc.dtype)
    ghost_labels = ghost_exchange(
        labels_loc, send_idx, recv_map, fill=jnp.asarray(0, labels_loc.dtype)
    )
    nbr_block = _neighbor_labels(labels_loc, ghost_labels, col_loc, 0)
    ext_w = jnp.where(in_over[edge_u], edge_w, 0)  # rated edges only
    row_cl = clabels[edge_u]
    target, tconn, _own, has = flat_best_moves(
        kc, row_cl, nbr_block, ext_w, cl_block, cw, block_w, max_bw,
        num_rows=n_loc, external_only=True, respect_caps=True,
    )
    # Fallback mirror of the node round: clusters with no *adjacent*
    # feasible target go to the roomiest block that fits them (interior
    # clusters of a deeply overloaded block have no external edges at all).
    roomiest = jnp.argmax(remaining).astype(target.dtype)
    fb_ok = (~has) & (cw <= remaining[roomiest]) & (roomiest != cl_block)
    target = jnp.where(fb_ok, roomiest, target)
    tconn = jnp.where(fb_ok, 0, tconn)
    has = has | fb_ok

    # -- deterministic greedy: best cluster per overloaded block ----------
    # relative gain = conn / weight (clusters.h relative_gain).  Selection
    # uses a globally UNIQUE sortable key — float32 rel in the high bits
    # (non-negative floats bit-cast to int32 are order-preserving), global
    # cluster id in the low 31 bits — so exactly one cluster wins per
    # source block and per receiver across all shards; equal-gain ties
    # cannot make two shards dump on the same receiver and bounce off the
    # all-or-nothing rollback (every round makes deterministic progress).
    is_cluster = (cw > 0) & over_b[cl_block] & has
    rel = tconn.astype(jnp.float32) / jnp.maximum(cw, 1).astype(jnp.float32)
    # int64 is unavailable without jax x64, so the (rel, gid) lexicographic
    # max runs as two chained int32 reductions.
    rel_bits = jax.lax.bitcast_convert_type(rel, jnp.int32)
    gid = idx * n_loc + jnp.arange(n_loc, dtype=jnp.int32)

    def _lex_best(mask, seg):
        segi = seg.astype(jnp.int32)
        b1 = pmax(
            jax.ops.segment_max(
                jnp.where(mask, rel_bits, jnp.int32(-1)), segi, num_segments=k
            ),
            AXIS,
        )
        m2 = mask & (rel_bits == b1[segi])
        b2 = pmax(
            jax.ops.segment_max(
                jnp.where(m2, gid, jnp.int32(-1)), segi, num_segments=k
            ),
            AXIS,
        )
        return m2 & (gid == b2[segi])

    chosen = _lex_best(is_cluster, cl_block)
    # One arrival per *receiver* as well: each chosen cluster was verified
    # to fit the receiver's current weight, so a single arrival can never
    # trip the rollback fixpoint.
    chosen = _lex_best(chosen, target)

    # -- receiver-side rollback fixpoint at cluster granularity -----------
    def overweight_fixable(kept):
        move_w = jnp.where(kept, cw, 0)
        arrivals = psum(
            jax.ops.segment_sum(
                move_w, target.astype(jnp.int32), num_segments=k
            ),
            AXIS,
        )
        w = block_w + arrivals - psum(
            jax.ops.segment_sum(
                move_w, cl_block.astype(jnp.int32), num_segments=k
            ),
            AXIS,
        )
        return (w > max_bw) & (arrivals > 0)

    def cond(carry):
        _, ow = carry
        return jnp.any(ow)

    def body(carry):
        kept, ow = carry
        kept = kept & ~ow[target]
        return kept, overweight_fixable(kept)

    kept, _ = jax.lax.while_loop(
        cond, body, (chosen, overweight_fixable(chosen))
    )
    move_cl = kept[clabels]
    new_labels = jnp.where(move_cl, target[clabels], labels_loc)
    new_bw = psum(
        jax.ops.segment_sum(
            node_w_loc, new_labels.astype(jnp.int32), num_segments=k
        ),
        AXIS,
    )
    moved = psum(jnp.sum(move_cl & real).astype(jnp.int32), AXIS)
    still = jnp.any(new_bw > max_bw)
    return new_labels, jnp.stack([moved, still.astype(jnp.int32)])


@lru_cache(maxsize=None)
def make_dist_cluster_balance_round(mesh: Mesh, *, k: int,
                                    donate: bool = False):
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(),
                  P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P()),
    )
    def round_fn(key, labels, node_w, edge_u, col_loc, edge_w, max_bw,
                 send_idx, recv_map):
        return _cluster_balance_round_body(
            key, labels, node_w, edge_u, col_loc, edge_w, max_bw,
            send_idx, recv_map, k=k,
        )

    return jax.jit(round_fn, donate_argnums=(1,) if donate else ())


@lru_cache(maxsize=None)
def make_dist_balance_round(mesh: Mesh, *, k: int, donate: bool = False):
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(),
                  P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P()),
    )
    def round_fn(key, labels, node_w, edge_u, col_loc, edge_w, max_bw,
                 send_idx, recv_map):
        return _balance_round_body(
            key, labels, node_w, edge_u, col_loc, edge_w, max_bw,
            send_idx, recv_map, k=k,
        )

    return jax.jit(round_fn, donate_argnums=(1,) if donate else ())


def dist_cluster_balance(mesh, key, labels, graph, max_bw, *, k: int,
                         max_rounds: int = 8, donate: bool = False):
    """Drive deterministic cluster-balance rounds (reference:
    cluster_balancer.cc).  Returns (labels, feasible)."""
    from ..utils import sync_stats

    fn = make_dist_cluster_balance_round(mesh, k=k, donate=donate)
    for i in range(max_rounds):
        labels, stats = fn(
            jax.random.fold_in(key, i), labels, graph.node_w, graph.edge_u,
            graph.col_loc, graph.edge_w, max_bw, graph.send_idx,
            graph.recv_map,
        )
        # ONE counted mesh-wide readback per round: packed (moved, still)
        # (round 13; was two implicit int()/bool() pulls).
        stats_h = sync_stats.pull(stats, shards=graph.num_shards)
        if not bool(stats_h[1]):
            return labels, True
        if int(stats_h[0]) == 0:
            break  # greedy and deterministic: a dry round stays dry
    return labels, False


def dist_balance(mesh, key, labels, graph, max_bw, *, k: int,
                 max_rounds: int = 16, donate: bool = False):
    """Drive balance rounds until feasible or the budget is exhausted.

    Node rounds first; when they go dry (3 consecutive rounds without a
    move — the reference's escalation point), whole-cluster moves take
    over (``dist_cluster_balance``).  Returns (labels, feasible).
    ``max_bw`` is a (k,) block-weight cap.  ``donate`` releases each
    round's input labels (incl. the caller's — the pipeline's rebind-only
    call sites opt in; external callers that reuse their array must not)."""
    from ..utils import sync_stats

    fn = make_dist_balance_round(mesh, k=k, donate=donate)
    feasible = False
    dry = 0
    for i in range(max_rounds):
        labels, stats = fn(
            jax.random.fold_in(key, i), labels, graph.node_w, graph.edge_u,
            graph.col_loc, graph.edge_w, max_bw, graph.send_idx,
            graph.recv_map,
        )
        # ONE counted mesh-wide readback per round: packed (moved, still).
        stats_h = sync_stats.pull(stats, shards=graph.num_shards)
        if not bool(stats_h[1]):
            feasible = True
            break
        # A probabilistic round can legitimately move nothing once; only
        # consecutive dry rounds mean stuck (cluster-balancer territory in
        # the reference).
        dry = dry + 1 if int(stats_h[0]) == 0 else 0
        if dry >= 3:
            break
    if not feasible:
        labels, feasible = dist_cluster_balance(
            mesh, jax.random.fold_in(key, 1 << 20), labels, graph, max_bw,
            k=k, donate=donate,
        )
    return labels, feasible
