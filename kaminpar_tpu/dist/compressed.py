"""Distributed compressed graph.

Role counterpart: kaminpar-dist/datastructures/distributed_compressed_graph
.{h,cc} (~800 LoC) — each PE keeps its node range's adjacency gap-encoded
and decodes neighborhoods on the fly, cutting per-PE resident memory.

TPU redesign, two tiers:

- **Host staging** (this module): between IO and device upload the graph
  exists only gap-packed (graph/compressed.py's fixed-width codec, applied
  per shard in shard-relative coordinates), and ``to_dist_graph``
  materializes ONE shard's CSR at a time — peak host memory
  O(compressed + one shard) instead of O(m).  Each shard is decoded
  exactly once (round 15; the original two-pass form decoded twice).
- **Device residency** (dist/device_compressed.py, round 15): under
  ``compression.device_decode`` the per-shard gap words + decode metadata
  become the *resident* adjacency on the mesh and the finest dist level's
  LP/contraction kernels decode in-trace — ``decompress_arrays`` is never
  called on that path after the view build.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..graph.compressed import CompressedGraph, compress
from ..graph.csr import CSRGraph
from ..utils.intmath import next_pow2
from .exchange import build_ghost_exchange, localize_columns
from .graph import DistGraph

__all__ = ["DistributedCompressedGraph", "compress_distributed"]


@dataclass
class DistributedCompressedGraph:
    """Per-shard compressed adjacency; columns stored shard-relative so the
    codec's row-anchored first gap stays small at shard boundaries."""

    shards: List[CompressedGraph]
    n: int
    m: int
    n_loc: int
    num_shards: int

    @property
    def total_node_weight(self) -> int:
        return int(sum(s.total_node_weight for s in self.shards))

    @property
    def max_node_weight(self) -> int:
        # CompressedGraph.node_w is host numpy by construction (the codec
        # never touches the device); a plain reduction, not a transfer.
        return int(max(
            (int(s.node_w.max(initial=0)) for s in self.shards), default=0,  # kpt: ignore[sync-discipline] — CompressedGraph.node_w is host numpy
        ))

    def memory_bytes(self) -> int:
        return int(sum(s.memory_bytes() for s in self.shards))

    def uncompressed_bytes(self) -> int:
        return int(sum(s.uncompressed_bytes() for s in self.shards))

    def compression_ratio(self) -> float:
        return self.uncompressed_bytes() / max(self.memory_bytes(), 1)

    def _shard_arrays(self, s: int):
        """Decode shard ``s`` to host numpy (row_ptr, col_GLOBAL, node_w,
        edge_w) — no CSRGraph wrapper, so nothing touches the device."""
        row_ptr, col, node_w, edge_w = self.shards[s].decompress_arrays()
        col = col.astype(np.int64) + s * self.n_loc
        if edge_w is None:
            edge_w = np.ones(len(col), dtype=np.int64)
        return row_ptr, col, node_w, edge_w

    def shard_csr(self, s: int) -> CSRGraph:
        """Decode shard ``s`` as a CSRGraph (public convenience; the
        staging paths below use the array form)."""
        g = CSRGraph(*self._shard_arrays(s))  # kpt: ignore[runtime-isolation] — host decode convenience; no owning engine, callers pin
        return g

    def to_dist_graph(self, dtype=np.int32) -> DistGraph:
        """Materialize the device-side DistGraph shard by shard (same
        layout contract as graph.distribute_graph, including its
        minimum-8 pow2 floors and ew>0 ghost filtering).

        Each shard is decoded exactly ONCE: the per-shard edge counts come
        from the compressed metadata (``CompressedGraph.m``), and the ghost
        routing is resolved against the shard's OWN sorted-unique external
        ids (``build_ghost_exchange`` derives the identical numbering), so
        the single decoded pass can both collect the routing externals and
        emit the device slices.  Only the pad *value* depends on the not-
        yet-known global ghost capacity ``g_loc`` — pads are written with a
        sentinel and rewritten in one fused device op at the end (a device
        compute, not a transfer).  Host peak stays O(compressed + one
        shard); the previous two-pass form decoded every shard twice."""
        P, n_loc = self.num_shards, self.n_loc
        m_loc = next_pow2(max(max(s.m for s in self.shards), 1), 8)
        # Provisional pad slot: localize_columns writes n_loc + g_loc; pass
        # a sentinel "g_loc" no real ghost count can reach, fix up below.
        g_sentinel = 2**30

        ext_cols = []
        node_w_parts, eu_parts, ew_parts, cl_parts = [], [], [], []
        for s in range(P):
            rp, col, nwr, ewr = self._shard_arrays(s)  # the ONE decode
            rp = rp.astype(np.int64)
            n_s = len(rp) - 1
            lo, hi = s * n_loc, (s + 1) * n_loc
            ext = ((col < lo) | (col >= hi)) & (ewr > 0)
            gg = np.unique(col[ext]).astype(dtype)
            ext_cols.append(gg)
            nw = np.zeros(n_loc, dtype=dtype)
            nw[:n_s] = nwr
            eu = np.zeros(m_loc, dtype=dtype)
            ew = np.zeros(m_loc, dtype=dtype)
            colbuf = np.zeros(m_loc, dtype=np.int64)
            valid = np.zeros(m_loc, dtype=bool)
            cnt = len(col)
            eu[:cnt] = np.repeat(np.arange(n_s, dtype=dtype), np.diff(rp))
            ew[:cnt] = ewr
            colbuf[:cnt] = col
            valid[:cnt] = ew[:cnt] > 0
            cl = localize_columns(
                colbuf, valid, gg, s, n_loc, g_sentinel, dtype
            )
            node_w_parts.append(jnp.asarray(nw))
            eu_parts.append(jnp.asarray(eu))
            ew_parts.append(jnp.asarray(ew))
            cl_parts.append(jnp.asarray(cl))
            del rp, col, nwr, ewr, nw, eu, ew, colbuf, valid, cl

        # The routing build re-derives each shard's ghost set from the
        # already-unique externals — np.unique is idempotent, so the slot
        # numbering matches the localization above exactly.
        send_idx, recv_map, ghost_global, cap_g, g_loc = build_ghost_exchange(
            ext_cols, [np.ones(len(e), bool) for e in ext_cols], n_loc, P,
            dtype=dtype,
        )
        col_loc = jnp.concatenate(cl_parts)
        col_loc = jnp.where(
            col_loc == n_loc + g_sentinel,
            jnp.asarray(n_loc + g_loc, col_loc.dtype), col_loc,
        )

        return DistGraph(
            node_w=jnp.concatenate(node_w_parts),
            edge_u=jnp.concatenate(eu_parts),
            col_loc=col_loc,
            edge_w=jnp.concatenate(ew_parts),
            send_idx=jnp.asarray(send_idx),
            recv_map=jnp.asarray(recv_map),
            ghost_global=tuple(ghost_global),
            n=self.n,
            m=self.m,
            n_loc=n_loc,
            m_loc=m_loc,
            g_loc=g_loc,
            cap_g=cap_g,
            num_shards=P,
        )


def compress_distributed(
    graph: CSRGraph, num_shards: int
) -> DistributedCompressedGraph:
    """Compress a host CSRGraph into per-shard gap streams (node-range
    sharding, same n_loc formula as distribute_graph)."""
    from types import SimpleNamespace

    P = num_shards
    n = graph.n
    n_loc = next_pow2((n + P) // P, 8)  # distribute_graph's formula + floor
    # One counted readback for the staging split (round 12, kptlint
    # sync-discipline: formerly four un-counted np.asarray transfers).
    from ..utils import sync_stats

    rp, col, ew, nw = sync_stats.pull(
        graph.row_ptr, graph.col_idx, graph.edge_w, graph.node_w,
        phase="dist_build",
    )
    rp = rp.astype(np.int64)
    col = col.astype(np.int64)

    shards = []
    for s in range(P):
        lo = min(s * n_loc, n)
        hi = min((s + 1) * n_loc, n)
        e0, e1 = int(rp[lo]), int(rp[hi])
        # duck-typed CSR view: compress() reads row_ptr/col_idx/n/edge_w
        # only, and a real CSRGraph would ship every array to the device
        sub = SimpleNamespace(
            row_ptr=(rp[lo : hi + 1] - e0),
            col_idx=col[e0:e1] - s * n_loc,  # shard-relative (may be negative)
            n=hi - lo,
            node_w=nw[lo:hi],
            edge_w=ew[e0:e1],
        )
        shards.append(compress(sub))
    return DistributedCompressedGraph(
        shards=shards, n=n, m=graph.m, n_loc=n_loc, num_shards=P
    )
