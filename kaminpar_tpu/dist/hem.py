"""Distributed heavy-edge matching (HEM) clusterer.

Reference: ``kaminpar-dist/coarsening/clustering/hem/hem_clusterer.cc``
(555 LoC) — matching rounds serialized through a distributed graph
coloring.  The TPU redesign keeps the shm handshake formulation
(coarsening/hem_clusterer.py): every unmatched node proposes to its
heaviest eligible neighbor, mutual proposals match.  Cross-shard pairs need
no coloring and no owner routing — two ghost exchanges per round (partner
state in, proposals back) make both sides of every cut edge see the same
handshake, and matches are mutual by construction.

Pairs may span shards; the cluster label is the pair's minimum global id,
which the global contraction pipeline already handles (clusters owned by
the min-id's shard).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .exchange import AXIS, ghost_exchange, psum
from .lp import _neighbor_labels

_I32MAX = jnp.iinfo(jnp.int32).max


def _hem_round_body(key, match_loc, node_w, edge_u, col_loc, edge_w, max_cw,
                    send_idx, recv_map):
    idx = jax.lax.axis_index(AXIS)
    kr = jax.random.fold_in(key, idx)
    n_loc = match_loc.shape[0]
    base = idx.astype(match_loc.dtype) * n_loc
    gid = base + jnp.arange(n_loc, dtype=match_loc.dtype)
    unmatched = (match_loc == gid) & (node_w > 0)

    fill = jnp.asarray(-1, match_loc.dtype)
    g_match = ghost_exchange(match_loc, send_idx, recv_map, fill=fill)
    g_gid = ghost_exchange(gid, send_idx, recv_map, fill=fill)
    g_w = ghost_exchange(node_w, send_idx, recv_map,
                         fill=jnp.asarray(0, node_w.dtype))

    nbr_gid = _neighbor_labels(gid, g_gid, col_loc, -1)
    nbr_w = _neighbor_labels(node_w, g_w, col_loc, 0)
    nbr_match = _neighbor_labels(match_loc, g_match, col_loc, -2)
    nbr_unmatched = (nbr_match == nbr_gid) & (nbr_w > 0)

    u = edge_u
    ok = (
        unmatched[u]
        & nbr_unmatched
        & (edge_w > 0)
        & (node_w[u] + nbr_w <= max_cw)
        & (nbr_gid != gid[u])
    )

    # Heaviest eligible neighbor, random tie-break (two segment-argmax
    # passes — same scheme as the shm handshake, hem_clusterer.py).
    w_ok = jnp.where(ok, edge_w, -1)
    best_w = jax.ops.segment_max(w_ok, u, num_segments=n_loc)
    at_max = ok & (w_ok == best_w[u]) & (best_w[u] > 0)
    jitter = jax.random.randint(kr, edge_w.shape, 0, _I32MAX, dtype=jnp.int32)
    j_ok = jnp.where(at_max, jitter, -1)
    best_j = jax.ops.segment_max(j_ok, u, num_segments=n_loc)
    is_best = at_max & (j_ok == best_j[u])
    slot = jnp.arange(u.shape[0], dtype=jnp.int32)
    first = jax.ops.segment_min(
        jnp.where(is_best, slot, _I32MAX), u, num_segments=n_loc
    )
    has_prop = first < _I32MAX
    safe = jnp.clip(first, 0, max(u.shape[0] - 1, 0))
    prop = jnp.where(has_prop, nbr_gid[safe], gid).astype(match_loc.dtype)

    # Handshake: neighbor's proposal must point back.  (Proposals are
    # deterministic per shard; the exchange makes both sides agree.)
    g_prop = ghost_exchange(prop, send_idx, recv_map, fill=fill)
    nbr_prop = _neighbor_labels(prop, g_prop, col_loc, -3)
    shake = ok & (prop[u] == nbr_gid) & (nbr_prop == gid[u])
    partner = jax.ops.segment_max(
        jnp.where(shake, nbr_gid, -1), u, num_segments=n_loc
    )
    hit = (partner >= 0) & unmatched
    new_match = jnp.where(hit, partner.astype(match_loc.dtype), match_loc)
    num_matched = psum(jnp.sum(hit).astype(jnp.int32), AXIS)
    return new_match, num_matched


@lru_cache(maxsize=None)
def make_dist_hem_round(mesh: Mesh):
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(),
                  P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P()),
    )
    def round_fn(key, match, node_w, edge_u, col_loc, edge_w, max_cw,
                 send_idx, recv_map):
        return _hem_round_body(
            key, match, node_w, edge_u, col_loc, edge_w, max_cw,
            send_idx, recv_map,
        )

    return jax.jit(round_fn)


def dist_hem_cluster(mesh, key, graph, max_cw, *, num_rounds: int = 5):
    """Distributed HEM clustering; returns (labels, num_pairs) with
    labels = min(own gid, partner gid), singletons for unmatched nodes.
    Both endpoints of a pair register a hit, so the psum'd per-round count
    is halved."""
    fn = make_dist_hem_round(mesh)
    N = graph.N
    match = jnp.arange(N, dtype=graph.dtype)
    from .lp import shard_arrays

    match, graph = shard_arrays(mesh, graph, match)
    from ..utils import sync_stats

    total = jnp.int32(0)
    for i in range(num_rounds):
        match, matched = fn(
            jax.random.fold_in(key, i), match, graph.node_w, graph.edge_u,
            graph.col_loc, graph.edge_w, jnp.asarray(max_cw, graph.dtype),
            graph.send_idx, graph.recv_map,
        )
        # Counted per-round convergence readback (round 13).
        if int(sync_stats.pull(matched, shards=graph.num_shards)) == 0:
            break
        total = total + matched
    labels = jnp.minimum(match, jnp.arange(N, dtype=graph.dtype))
    return labels, int(
        sync_stats.pull(total, shards=graph.num_shards)
    ) // 2
