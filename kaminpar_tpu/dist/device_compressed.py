"""Device-resident per-shard compressed streams — the dist TeraPart tier.

Role counterpart: ``kaminpar-dist/datastructures/distributed_compressed_graph
.{h,cc}`` — each PE keeps its node range's adjacency gap-encoded and decodes
neighborhoods on the fly.  The reference decodes inside its traversal loops;
PR 10 (graph/device_compressed.py) proved the TPU analog on a single chip:
fixed-width gap words decode with one two-word gather + funnel shift per
edge, fused into the consuming kernel.  This module carries that tier onto
the mesh:

- :class:`DistDeviceCompressedView` — the sharded twin of :class:`DistGraph`
  whose three m-sized structural arrays (``edge_u``/``col_loc``/``edge_w``)
  are replaced by per-shard packed gap words + per-node decode metadata
  (``wstart``/``width``/``deg``) and a per-shard sorted ghost-id table.
  Columns are stored *shard-relative* (graph/compressed.py's signed first
  gap keeps them small at shard boundaries), so decode recovers local slots
  without any m-sized resident array.  Everything is a flat ``(P * per,)``
  array so ``PartitionSpec('nodes')`` splits it per shard — exactly the
  DistGraph layout contract.
- :func:`decode_shard_adjacency` — the in-trace per-shard decode, emitting
  ``(edge_u, col_loc, edge_w)`` **bit-identical** to the dense DistGraph's
  shard slices (same pad conventions, same ghost-slot numbering), so the
  existing dist round bodies consume it unchanged and bit-identity with the
  dense path is by construction, not by hope.
- decode-fused ``shard_map`` kernels: the global LP clustering round, the LP
  refinement round, and contraction stage S2 (:func:`_s2c`) each start with
  the decode and then run the *shared* dense bodies
  (dist/lp.py / dist/contraction.py) on the transient arrays.
- :func:`materialize_dist_graph` — ONE sharded decode dispatch producing the
  dense :class:`DistGraph` (zero blocking transfers) for the refiners that
  stay dense (balancer / CLP / JET / extension), mirroring PR 10's finest
  re-materialization.

Envelope: the 32-bit build with ``GLOBAL_LP`` dist clustering (the other
clusterers walk matchings or need shard-local labels; they fall back to the
dense staging path, loudly under ``device_decode=finest``).
``GraphCompressionContext.device_decode`` gates the routing —
the SAME knob as the shm tier, so ``terapart`` presets engage both.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graph.device_compressed import _funnel_unpack
from ..utils import sync_stats
from ..utils.intmath import next_pow2
from .contraction import _assemble_coarse, _s1, _s2_core, _s3, _s4
from .exchange import AXIS, build_ghost_exchange
from .graph import DistGraph, compute_shard_work
from .lp import _cluster_round_body, _refine_round_body

__all__ = [
    "DistDeviceCompressedView",
    "build_dist_device_view",
    "build_dist_view_if_eligible",
    "decode_shard_adjacency",
    "materialize_dist_graph",
    "dist_cluster_iterate_compressed",
    "dist_lp_iterate_compressed",
    "contract_dist_compressed",
]

_GHOST_PAD = np.iinfo(np.int32).max  # sorted-table sentinel (> any global id)


class DistDeviceCompressedView(NamedTuple):
    """Sharded device arrays + host metadata; the NamedTuple itself is never
    traced (DistGraph convention).  ``edge_w_stream`` is a (P,) zero dummy
    when every shard's weights are uniform all-1 — ``has_edge_w`` is the
    static trace-time switch."""

    words: jax.Array  # (P * w_loc,) uint32 packed zig-zag gap words
    wstart: jax.Array  # (P * n_loc,) shard-local first word per node
    width: jax.Array  # (P * n_loc,) bits per gap (pads 1)
    deg: jax.Array  # (P * n_loc,) degree (pads 0)
    node_w: jax.Array  # (P * n_loc,) node weights, pads 0
    edge_w_stream: jax.Array  # (P * m_loc,) decode-order weights or (P,) dummy
    ghost_sorted: jax.Array  # (P * g_loc,) sorted ghost GLOBAL ids, pads MAX
    send_idx: jax.Array  # ghost-exchange routing (DistGraph contract)
    recv_map: jax.Array
    ghost_global: tuple  # host: per-shard np arrays of ghost global ids
    n: int
    m: int
    n_loc: int
    m_loc: int
    w_loc: int
    g_loc: int
    cap_g: int
    num_shards: int
    has_edge_w: bool
    shard_work: tuple = ()

    @property
    def N(self) -> int:
        return self.num_shards * self.n_loc

    @property
    def dtype(self):
        return self.node_w.dtype

    @property
    def is_compressed_view(self) -> bool:
        """Dispatch marker consumed by shard_arrays / contract_dist_clustering
        (DistGraph lacks the attribute; ``getattr(..., False)`` reads it)."""
        return True

    # -- memory accounting (bench shard_ab) ---------------------------------

    def resident_bytes(self) -> int:
        """Device-resident bytes of the compressed adjacency tier: the word
        stream + per-node decode metadata + ghost table + (when non-uniform)
        the weight side stream.  node_w and the exchange routing are common
        to both tiers and excluded — this measures the *adjacency* delta."""
        b = self.words.nbytes + self.wstart.nbytes + self.width.nbytes
        b += self.deg.nbytes + self.ghost_sorted.nbytes
        if self.has_edge_w:
            b += self.edge_w_stream.nbytes
        return int(b)

    def dense_resident_bytes(self) -> int:
        """What the dense DistGraph keeps resident for the same adjacency:
        the three (P * m_loc,) structural arrays."""
        itemsize = self.node_w.dtype.itemsize
        return int(3 * self.num_shards * self.m_loc * itemsize)


# -- in-trace decode ---------------------------------------------------------


def decode_shard_adjacency(words, wstart, width, deg, ew_stream, ghost_sorted,
                           *, m_loc: int, has_edge_w: bool):
    """Per-shard in-trace decode (inside ``shard_map``): rebuild this shard's
    ``(edge_u, col_loc, edge_w)`` slices exactly as the dense staging path
    lays them out (dist/graph.distribute_graph / compressed.to_dist_graph):

    - ``edge_u``: local row per real edge slot, 0 on pads;
    - ``col_loc``: local node slot for in-shard targets, ``n_loc + slot`` for
      ghosts (slot = position in the shard's sorted-unique ghost table, found
      here by binary search instead of the host's precomputed rewrite),
      ``n_loc + g_loc`` wherever the edge weight is zero (pads AND real
      zero-weight edges — the dense builder's ``valid = ew > 0`` rule);
    - ``edge_w``: decode-order weights (the side stream IS the dense array)
      or the constant 1 on real slots.

    Per edge: one gather of two consecutive words + funnel shift/mask
    (widths are <= 32 so a gap straddles at most one boundary), zig-zag
    decode, then a segmented cumsum turns gaps into shard-relative columns.
    The cumsum may wrap int32 across rows; the per-row rebase subtraction
    cancels the wrap exactly (two's complement), so columns are exact
    whenever they fit int32 — the 32-bit envelope.
    """
    idt = deg.dtype
    n_loc = deg.shape[0]
    g_loc = ghost_sorted.shape[0]
    rp = jnp.concatenate([jnp.zeros(1, idt), jnp.cumsum(deg).astype(idt)])
    m_real = rp[n_loc].astype(jnp.int32)
    slot = jnp.arange(m_loc, dtype=jnp.int32)
    # scatter-of-row-starts cumsum: each slot lands on its owning row; the
    # tail (>= m_real) accumulates every trailing empty row and is masked.
    marks = jnp.zeros(m_loc, jnp.int32).at[
        rp[:-1].astype(jnp.int32)
    ].add(1, mode="drop")
    eu_raw = jnp.clip(jnp.cumsum(marks) - 1, 0, n_loc - 1)
    pos = slot - rp[eu_raw].astype(jnp.int32)
    wd = width[eu_raw].astype(jnp.int32)
    bit = pos * wd
    w0 = wstart[eu_raw].astype(jnp.int32) + (bit >> 5)
    gap = _funnel_unpack(words, w0, bit & 31, wd)
    valid = slot < m_real
    firsts = pos == 0
    vals = jnp.where(valid, jnp.where(firsts, eu_raw + gap, gap), 0)
    c = jnp.cumsum(vals)
    row_base = jnp.concatenate([jnp.zeros(1, c.dtype), c])[
        rp[:-1].astype(jnp.int32)
    ]
    col_rel = c - row_base[eu_raw]

    if has_edge_w:
        ew = ew_stream.astype(idt)  # already the dense layout incl. 0 pads
    else:
        ew = valid.astype(idt)
    edge_u = jnp.where(valid, eu_raw, 0).astype(idt)
    live = valid & (ew > 0)
    local = live & (col_rel >= 0) & (col_rel < n_loc)
    idx = jax.lax.axis_index(AXIS)
    gcol = col_rel + idx.astype(col_rel.dtype) * n_loc
    gslot = jnp.searchsorted(
        ghost_sorted, gcol.astype(ghost_sorted.dtype)
    ).astype(jnp.int32)
    col_loc = jnp.where(
        local, col_rel,
        jnp.where(live, n_loc + gslot, n_loc + g_loc),
    ).astype(idt)
    return edge_u, col_loc, ew


def shard_view_arrays(mesh: Mesh, view: DistDeviceCompressedView, labels):
    """Place the view + label arrays with their 1D shardings (the
    :func:`~kaminpar_tpu.dist.lp.shard_arrays` twin for compressed levels)."""
    s = NamedSharding(mesh, P(AXIS))
    return (
        jax.device_put(labels, s),
        view._replace(
            words=jax.device_put(view.words, s),
            wstart=jax.device_put(view.wstart, s),
            width=jax.device_put(view.width, s),
            deg=jax.device_put(view.deg, s),
            node_w=jax.device_put(view.node_w, s),
            edge_w_stream=jax.device_put(view.edge_w_stream, s),
            ghost_sorted=jax.device_put(view.ghost_sorted, s),
            send_idx=jax.device_put(view.send_idx, s),
            recv_map=jax.device_put(view.recv_map, s),
        ),
    )


# -- host build --------------------------------------------------------------


def build_dist_device_view(dcg) -> DistDeviceCompressedView:
    """Build the device view from a host :class:`DistributedCompressedGraph`.

    Each shard is decoded ONCE, for the ghost-routing externals only (the
    columns the exchange builder needs); the resident device arrays come
    straight from the compressed fields — no dense per-shard CSR slice is
    ever materialized, host or device.  Peak host memory stays
    O(compressed + one decoded shard).
    """
    Pn, n_loc = dcg.num_shards, dcg.n_loc
    idt = np.int32
    m_loc = next_pow2(max(max(s.m for s in dcg.shards), 1), 8)

    ext_cols, owned_edges = [], []
    for s in range(Pn):
        _, col, _, ew = dcg._shard_arrays(s)  # the ONE decode of shard s
        lo, hi = s * n_loc, (s + 1) * n_loc
        ext = ((col < lo) | (col >= hi)) & (ew > 0)
        ext_cols.append(col[ext].astype(idt))
        owned_edges.append(int((ew > 0).sum()))
        del col, ew

    send_idx, recv_map, ghost_global, cap_g, g_loc = build_ghost_exchange(
        ext_cols, [np.ones(len(e), bool) for e in ext_cols], n_loc, Pn,
        dtype=idt,
    )

    # Word stream: strictly > real length per shard so the straddle read at
    # +1 stays in bounds at the last real word (compress() already appends a
    # sentinel word; the pow2 pad keeps one shape per bucket).
    w_loc = next_pow2(max(len(s.words) for s in dcg.shards) + 1, 8)
    words = np.zeros(Pn * w_loc, dtype=np.uint32)
    wstart = np.zeros(Pn * n_loc, dtype=idt)
    width = np.ones(Pn * n_loc, dtype=idt)
    deg = np.zeros(Pn * n_loc, dtype=idt)
    node_w = np.zeros(Pn * n_loc, dtype=idt)
    has_edge_w = any(s.edge_w is not None for s in dcg.shards)
    ew_stream = (
        np.zeros(Pn * m_loc, dtype=idt) if has_edge_w
        else np.zeros(Pn, dtype=idt)
    )
    ghost_sorted = np.full(Pn * g_loc, _GHOST_PAD, dtype=idt)
    for s in range(Pn):
        cg = dcg.shards[s]
        n_s, m_s = cg.n, cg.m
        words[s * w_loc : s * w_loc + len(cg.words)] = cg.words
        wstart[s * n_loc : s * n_loc + n_s] = cg.word_start[:n_s].astype(idt)
        width[s * n_loc : s * n_loc + n_s] = cg.width.astype(idt)
        deg[s * n_loc : s * n_loc + n_s] = cg.degree.astype(idt)
        node_w[s * n_loc : s * n_loc + n_s] = cg.node_w.astype(idt)
        if has_edge_w:
            ew_stream[s * m_loc : s * m_loc + m_s] = (
                np.ones(m_s, dtype=idt) if cg.edge_w is None
                else cg.edge_w.astype(idt)
            )
        gg = ghost_global[s]
        ghost_sorted[s * g_loc : s * g_loc + len(gg)] = gg

    shard_work = compute_shard_work(
        send_idx, ghost_global,
        owned_nodes=[
            max(0, min((s + 1) * n_loc, dcg.n) - s * n_loc) for s in range(Pn)
        ],
        owned_edges=owned_edges, n_loc=n_loc, num_shards=Pn,
    )

    from ..utils import compile_stats

    compile_stats.record(
        "dist_compressed_bucket", statics=(Pn, n_loc, m_loc, w_loc, g_loc)
    )
    return DistDeviceCompressedView(
        words=jnp.asarray(words),
        wstart=jnp.asarray(wstart),
        width=jnp.asarray(width),
        deg=jnp.asarray(deg),
        node_w=jnp.asarray(node_w),
        edge_w_stream=jnp.asarray(ew_stream),
        ghost_sorted=jnp.asarray(ghost_sorted),
        send_idx=jnp.asarray(send_idx),
        recv_map=jnp.asarray(recv_map),
        ghost_global=tuple(ghost_global),
        n=dcg.n, m=dcg.m, n_loc=n_loc, m_loc=m_loc, w_loc=w_loc,
        g_loc=g_loc, cap_g=cap_g, num_shards=Pn, has_edge_w=has_edge_w,
        shard_work=shard_work,
    )


def dist_device_decode_eligible(ctx) -> tuple:
    """(eligible, reason) for the sharded device-decode envelope: the 32-bit
    build with GLOBAL_LP dist clustering (HEM walks matchings, LOCAL_LP
    needs shard-local labels for its exchange-free contraction — both stay
    on the dense staging path)."""
    from ..context import DistClusteringAlgorithm as DCA

    if ctx.use_64bit_ids:
        return False, "64-bit build"
    if ctx.coarsening.dist_clustering != DCA.GLOBAL_LP:
        return False, f"dist clusterer {ctx.coarsening.dist_clustering.value}"
    return True, ""


def build_dist_view_if_eligible(ctx, dcg):
    """The dist partitioner's gate (PR 10's build_device_view_if_eligible
    twin): a view when the ``device_decode`` knob + envelope allow it, else
    None (dense staging fallback; ``finest`` warns, ``auto`` is silent)."""
    import os

    from ..graph.device_compressed import resolve_device_decode

    mode = resolve_device_decode(ctx.compression)
    if mode == "off":
        return None
    ok, reason = dist_device_decode_eligible(ctx)
    if not ok:
        requested = os.environ.get(
            "KAMINPAR_TPU_DEVICE_DECODE", ""
        ) or getattr(ctx.compression, "device_decode", "off")
        if requested == "finest":
            from ..utils.logger import Logger

            Logger.warning(
                f"compression.device_decode=finest requested but {reason}; "
                "the dist tier falls back to the dense staging path"
            )
        return None
    return build_dist_device_view(dcg)


# -- decode-fused kernels ----------------------------------------------------


@lru_cache(maxsize=None)
def make_dist_cluster_round_compressed(mesh: Mesh, *, cap_q: int, m_loc: int,
                                       has_edge_w: bool):
    """Decode-fused global clustering round: per-shard gap-word decode feeds
    the SHARED :func:`~kaminpar_tpu.dist.lp._cluster_round_body` (owner
    auction admission), so the round is bit-identical to the dense one."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                  P(AXIS), P(AXIS), P(), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(), P()),
    )
    def round_fn(key, labels, node_w, words, wstart, width, deg, ew_stream,
                 ghost_sorted, max_w, send_idx, recv_map):
        eu, cl, ew = decode_shard_adjacency(
            words, wstart, width, deg, ew_stream, ghost_sorted,
            m_loc=m_loc, has_edge_w=has_edge_w,
        )
        return _cluster_round_body(
            key, labels, node_w, eu, cl, ew, max_w, send_idx, recv_map,
            cap_q=cap_q,
        )

    return jax.jit(round_fn)


def dist_cluster_iterate_compressed(mesh, key, labels,
                                    view: DistDeviceCompressedView, max_w, *,
                                    num_rounds: int, cap_q: int | None = None):
    """Clustering LP loop off the compressed view — the dense
    :func:`~kaminpar_tpu.dist.lp.dist_cluster_iterate` drive (same
    overflow-adaptive cap escalation, same counted per-attempt overflow
    readback), with decode fused into each round's program."""
    n_loc = view.n_loc
    if cap_q is None:
        cap_q = min(
            next_pow2(max(64, 2 * n_loc // max(view.num_shards, 1)), 8), n_loc
        )
    fn = make_dist_cluster_round_compressed(
        mesh, cap_q=cap_q, m_loc=view.m_loc, has_edge_w=view.has_edge_w
    )
    total = jnp.int32(0)
    for i in range(num_rounds):
        while True:
            out, moved, ovf = fn(
                jax.random.fold_in(key, i), labels, view.node_w, view.words,
                view.wstart, view.width, view.deg, view.edge_w_stream,
                view.ghost_sorted, max_w, view.send_idx, view.recv_map,
            )
            ovf_h = int(sync_stats.pull(ovf, shards=view.num_shards))
            if ovf_h == 0 or cap_q >= n_loc:
                break
            cap_q = min(cap_q * 2, n_loc)
            fn = make_dist_cluster_round_compressed(
                mesh, cap_q=cap_q, m_loc=view.m_loc,
                has_edge_w=view.has_edge_w,
            )
        labels = out
        total = total + moved
    return labels, total


@lru_cache(maxsize=None)
def make_dist_lp_round_compressed(mesh: Mesh, *, num_labels: int, m_loc: int,
                                  has_edge_w: bool,
                                  external_only: bool = False,
                                  num_chunks: int = 1, donate: bool = False):
    """Decode-fused LP refinement round (shared
    :func:`~kaminpar_tpu.dist.lp._refine_round_body`); with ``donate`` the
    labels carry is released to XLA each round (drive loops rebind it)."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                  P(AXIS), P(AXIS), P(), P(AXIS), P(AXIS), P(), P()),
        out_specs=(P(AXIS), P()),
    )
    def round_fn(key, labels, node_w, words, wstart, width, deg, ew_stream,
                 ghost_sorted, max_w, send_idx, recv_map, chunk, salt):
        eu, cl, ew = decode_shard_adjacency(
            words, wstart, width, deg, ew_stream, ghost_sorted,
            m_loc=m_loc, has_edge_w=has_edge_w,
        )
        return _refine_round_body(
            key, labels, node_w, eu, cl, ew, max_w, send_idx, recv_map,
            chunk, salt, num_labels=num_labels, external_only=external_only,
            num_chunks=num_chunks,
        )

    return jax.jit(round_fn, donate_argnums=(1,) if donate else ())


def dist_lp_iterate_compressed(mesh, key, labels,
                               view: DistDeviceCompressedView, max_w, *,
                               num_labels: int, num_rounds: int,
                               external_only: bool = False,
                               num_chunks: int = 1, donate: bool = False):
    """LP refinement loop off the compressed view (the dense
    :func:`~kaminpar_tpu.dist.lp.dist_lp_iterate` drive, decode fused)."""
    fn = make_dist_lp_round_compressed(
        mesh, num_labels=num_labels, m_loc=view.m_loc,
        has_edge_w=view.has_edge_w, external_only=external_only,
        num_chunks=num_chunks, donate=donate,
    )
    total = jnp.int32(0)
    for i in range(num_rounds):
        for c in range(num_chunks):
            labels, moved = fn(
                jax.random.fold_in(key, i * num_chunks + c), labels,
                view.node_w, view.words, view.wstart, view.width, view.deg,
                view.edge_w_stream, view.ghost_sorted, max_w, view.send_idx,
                view.recv_map, jnp.int32(c), jnp.int32(i),
            )
            total = total + moved
    return labels, total


# -- decode-fused contraction stage (S2) -------------------------------------


@partial(
    jax.jit,
    static_argnames=("mesh", "n_loc", "n_loc_c", "cap_q", "m_loc",
                     "has_edge_w"),
)
def _s2c(mesh, labels, cmap_own, cw_own, words, wstart, width, deg, ew_stream,
         ghost_sorted, send_idx, recv_map, *, n_loc: int, n_loc_c: int,
         cap_q: int, m_loc: int, has_edge_w: bool):
    """Compressed twin of contraction._s2: decode this shard's adjacency
    in-trace, then run the shared S2 core (owner queries + routing).  The
    decoded edge arrays are XLA transients of ONE fused program — the dense
    slices never become resident buffers."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS),) * 11,
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                   P(AXIS), P(AXIS), P(AXIS), P()),
    )
    def body(labels_loc, cmap_own_loc, cw_own_loc, w_, ws_, wd_, dg_, ews_,
             gs_, sidx, rmap):
        eu, cl, ew = decode_shard_adjacency(
            w_, ws_, wd_, dg_, ews_, gs_, m_loc=m_loc, has_edge_w=has_edge_w,
        )
        return _s2_core(
            labels_loc, cmap_own_loc, cw_own_loc, eu, cl, ew, sidx, rmap,
            n_loc=n_loc, n_loc_c=n_loc_c, cap_q=cap_q,
        )

    return body(labels, cmap_own, cw_own, words, wstart, width, deg,
                ew_stream, ghost_sorted, send_idx, recv_map)


def contract_dist_compressed(mesh: Mesh, view: DistDeviceCompressedView,
                             labels, cap_q: int | None = None):
    """Contract a distributed clustering straight off the compressed view.

    The drive is the dense ``contract_dist_clustering`` step for step (same
    counted pulls, same overflow escalation); only S2 — the one stage that
    touches the adjacency — decodes in-kernel.  S3/S4 operate on the routed
    coarse-edge buffers and the shared host assembly builds the coarse
    DistGraph, which is DENSE (coarse levels shrink geometrically; the
    compressed tier is the finest level's problem, exactly as in PR 10)."""
    Pn = view.num_shards
    n_loc = view.n_loc
    if cap_q is None:
        cap_q = min(next_pow2(max(64, 2 * n_loc // Pn), 8), n_loc)

    while True:
        n_c, cw_own, cmap_own, ovf = _s1(
            mesh, labels, view.node_w, n_loc=n_loc, cap_q=cap_q
        )
        s1_stats = sync_stats.pull(jnp.stack([n_c, ovf]), shards=Pn)
        if int(s1_stats[1]) == 0 or cap_q >= n_loc:
            break
        cap_q = min(cap_q * 2, n_loc)
    n_c = int(s1_stats[0])
    n_loc_c = next_pow2((n_c + Pn) // Pn, 8)

    cap_q2 = cap_q
    while True:
        (coarse_of, s_cu, s_cv, s_w, counts, w_keys, w_vals, wcounts,
         ovf2) = _s2c(
            mesh, labels, cmap_own, cw_own, view.words, view.wstart,
            view.width, view.deg, view.edge_w_stream, view.ghost_sorted,
            view.send_idx, view.recv_map,
            n_loc=n_loc, n_loc_c=n_loc_c, cap_q=cap_q2, m_loc=view.m_loc,
            has_edge_w=view.has_edge_w,
        )
        ovf2_h = int(sync_stats.pull(ovf2, shards=Pn))
        if ovf2_h == 0 or cap_q2 >= n_loc + view.g_loc:
            break
        cap_q2 = min(cap_q2 * 2, n_loc + view.g_loc)

    counts_h, wcounts_h = sync_stats.pull(counts, wcounts, shards=Pn)
    cap = next_pow2(int(counts_h.max()), 8)
    cap_w = next_pow2(int(wcounts_h.max()), 8)

    agg_u, agg_v, agg_w, m_c_loc, node_w_c = _s3(
        mesh, s_cu, s_cv, s_w, counts, w_keys, w_vals, wcounts,
        num_shards=Pn, cap=cap, cap_w=cap_w, n_loc_c=n_loc_c,
    )
    m_c_loc = sync_stats.pull(m_c_loc, shards=Pn)
    m_loc_c = next_pow2(int(m_c_loc.max()), 8)
    m_loc_c = min(m_loc_c, Pn * cap)
    edge_u_g, col_g, edge_w_c = _s4(mesh, agg_u, agg_v, agg_w, m_loc_c=m_loc_c)

    coarse = _assemble_coarse(
        edge_u_g, col_g, edge_w_c, node_w_c, m_c_loc, n_c,
        n_loc_c=n_loc_c, m_loc_c=m_loc_c, num_shards=Pn,
    )
    return coarse, coarse_of, n_c


# -- dense materialization (one sharded decode dispatch) ---------------------


@lru_cache(maxsize=None)
def _make_materialize(mesh: Mesh, *, m_loc: int, has_edge_w: bool):
    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS),) * 6,
        out_specs=(P(AXIS), P(AXIS), P(AXIS)),
    )
    def decode_fn(words, wstart, width, deg, ew_stream, ghost_sorted):
        return decode_shard_adjacency(
            words, wstart, width, deg, ew_stream, ghost_sorted,
            m_loc=m_loc, has_edge_w=has_edge_w,
        )

    return jax.jit(decode_fn)


def materialize_dist_graph(mesh: Mesh,
                           view: DistDeviceCompressedView) -> DistGraph:
    """Decode the dense :class:`DistGraph` from the view in ONE sharded
    device dispatch — zero blocking transfers (every scalar a later phase
    needs rides the view's host metadata), zero host decompress.  Used at
    uncoarsening for the refiners that stay dense (balancer/CLP/JET) and
    for replicate-to-host when the coarsest level is still compressed."""
    eu, cl, ew = _make_materialize(
        mesh, m_loc=view.m_loc, has_edge_w=view.has_edge_w
    )(view.words, view.wstart, view.width, view.deg, view.edge_w_stream,
      view.ghost_sorted)
    return DistGraph(
        node_w=view.node_w,
        edge_u=eu,
        col_loc=cl,
        edge_w=ew,
        send_idx=view.send_idx,
        recv_map=view.recv_map,
        ghost_global=view.ghost_global,
        n=view.n,
        m=view.m,
        n_loc=view.n_loc,
        m_loc=view.m_loc,
        g_loc=view.g_loc,
        cap_g=view.cap_g,
        num_shards=view.num_shards,
        shard_work=view.shard_work,
    )
