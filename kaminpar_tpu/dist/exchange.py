"""Sparse ghost exchange + owner-routed query/aggregate primitives.

TPU-native replacement for the reference's sparse all-to-all library
(kaminpar-dist/graphutils/communication.h:55-130
``sparse_alltoall_interface_to_ghost/_to_pe`` — one message per cut edge /
interface node) and the growt global weight/label maps.  The MPI messages are
variable-size; XLA needs static shapes, so:

- **Ghost exchange** (labels of interface nodes) uses *precomputed static
  routing*: per level we know exactly which local nodes each neighbor shard
  needs, so the exchange is ``gather → all_to_all → gather`` over buffers
  sized by the measured max per-pair interface count (``cap_g``).  Per-round
  communication is O(interface), not O(N) — the fix for the all_gather
  design this replaces.

- **Owner-routed queries/aggregations** (cluster weights, coarse-id maps)
  route (key, value) pairs to the shard owning the key range
  (owner = key // n_loc, the analog of the reference's
  ``node_distribution[]`` ownership) via sort-pack + dense ``all_to_all``
  with a static per-destination cap.  Key→owner distribution is
  data-dependent, so packs report an **overflow count**; callers re-run the
  step with a doubled cap when overflow is nonzero (shape-bucket +
  recompile budget, SURVEY §7 hard part (d)).

Everything below the ``build_ghost_exchange`` host builder runs *inside*
``shard_map`` over mesh axis ``'nodes'`` and is written per-shard.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import collective_stats
from ..utils.intmath import next_pow2

AXIS = "nodes"


# ---------------------------------------------------------------------------
# Counted collective wrappers (round 13).  The reference's communication
# layer (kaminpar-mpi/sparse_alltoall.h, grid_alltoall.h) counts messages
# and bytes per call; the TPU analog counts at TRACE time — Python inside a
# jitted body runs once per compiled specialization — so the census adds
# zero collectives, zero readbacks, and zero per-execution work (semantics
# in utils/collective_stats.py + TPU_NOTES.md round 13).  Every dist-tier
# collective routes through these instead of jax.lax directly.
# ---------------------------------------------------------------------------


def _count(op: str, x, axis_name: str) -> None:
    collective_stats.record(
        op,
        collective_stats.traced_bytes(jnp.shape(x), jnp.result_type(x)),
        jax.lax.axis_size(axis_name),
    )


def psum(x, axis_name: str = AXIS):
    """Counted ``jax.lax.psum`` (single-array operands only)."""
    _count("psum", x, axis_name)
    return jax.lax.psum(x, axis_name)


def pmax(x, axis_name: str = AXIS):
    """Counted ``jax.lax.pmax``."""
    _count("pmax", x, axis_name)
    return jax.lax.pmax(x, axis_name)


def all_to_all(x, axis_name: str = AXIS, split_axis: int = 0,
               concat_axis: int = 0):
    """Counted ``jax.lax.all_to_all``."""
    _count("all_to_all", x, axis_name)
    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis)


def all_gather(x, axis_name: str = AXIS, **kwargs):
    """Counted ``jax.lax.all_gather``."""
    _count("all_gather", x, axis_name)
    return jax.lax.all_gather(x, axis_name, **kwargs)


class GhostExchange(NamedTuple):
    """Static routing for interface→ghost value exchange (device arrays,
    sharded along their leading flat axis).

    send_idx:  (P*P, cap_g) — shard s's slice [s*P:(s+1)*P] holds, per
               destination shard t, the *local* indices of s's interface
               nodes that t needs; pad = n_loc (a dummy slot).
    recv_map:  (P*g_loc,) — shard s's slice maps each of its ghost slots to
               a position in the flattened (P*cap_g,) receive buffer;
               pad = P*cap_g (a dummy fill slot).
    """

    send_idx: jax.Array
    recv_map: jax.Array
    cap_g: int
    g_loc: int


def build_ghost_exchange(
    col_global_per_shard: list[np.ndarray],
    valid_per_shard: list[np.ndarray],
    n_loc: int,
    num_shards: int,
    dtype=np.int32,
):
    """Host-side builder.  ``col_global_per_shard[s]`` are shard s's edge
    target global ids; ``valid_per_shard[s]`` masks real edges.

    Returns (GhostExchange arrays as host numpy, ghost_global list,
    col→local-slot remapping helper data).  Ghost slot numbering per shard:
    sorted unique external ids, so lookups are reproducible.
    """
    P = num_shards
    ghost_global: list[np.ndarray] = []
    for s in range(P):
        col = col_global_per_shard[s][valid_per_shard[s]]
        lo, hi = s * n_loc, (s + 1) * n_loc
        ext = col[(col < lo) | (col >= hi)]
        ghost_global.append(np.unique(ext).astype(dtype))

    g_loc = next_pow2(max(max((len(g) for g in ghost_global), default=1), 1), 8)

    # Per ordered pair (owner t, requester s): which of t's locals s needs.
    need = [[None] * P for _ in range(P)]  # need[t][s] = local ids on t
    cap_g = 1
    for s in range(P):
        gg = ghost_global[s]
        owners = gg // n_loc
        for t in range(P):
            ids = gg[owners == t] - t * n_loc
            need[t][s] = ids.astype(dtype)
            cap_g = max(cap_g, len(ids))
    cap_g = next_pow2(cap_g, 8)

    send_idx = np.full((P * P, cap_g), n_loc, dtype=dtype)
    for t in range(P):
        for s in range(P):
            ids = need[t][s]
            send_idx[t * P + s, : len(ids)] = ids

    # Receive layout: after all_to_all, shard s's buffer row t holds what
    # owner t sent it — t's interface nodes in need[t][s] order.
    recv_map = np.full(P * g_loc, P * cap_g, dtype=dtype)
    for s in range(P):
        gg = ghost_global[s]
        owners = gg // n_loc
        pos_of = {}
        for t in range(P):
            for j, gid in enumerate(need[t][s] + t * n_loc):
                pos_of[int(gid)] = t * cap_g + j
        for i, gid in enumerate(gg):
            recv_map[s * g_loc + i] = pos_of[int(gid)]

    return send_idx, recv_map, ghost_global, cap_g, g_loc


def localize_columns(
    col_global: np.ndarray,
    valid: np.ndarray,
    ghost_global: np.ndarray,
    shard: int,
    n_loc: int,
    g_loc: int,
    dtype,
) -> np.ndarray:
    """Host-side: rewrite one shard's global edge targets to local slots.

    Slot encoding (owned by this module alongside the routing convention):
    ``< n_loc`` local node, ``n_loc + ghost_slot`` ghost (slots are positions
    in the shard's sorted-unique ``ghost_global``), ``n_loc + g_loc`` pad.
    """
    lo = shard * n_loc
    out = np.full(col_global.shape[0], n_loc + g_loc, dtype=dtype)
    local = (col_global >= lo) & (col_global < lo + n_loc) & valid
    out[local] = (col_global[local] - lo).astype(dtype)
    is_ghost = valid & ~local
    if is_ghost.any():
        slots = np.searchsorted(ghost_global, col_global[is_ghost])
        out[is_ghost] = (n_loc + slots).astype(dtype)
    return out


def ghost_exchange(vals_loc, send_idx, recv_map, *, fill):
    """Exchange interface values → ghost values.  Per-shard inside shard_map.

    vals_loc: (n_loc,); send_idx: (P, cap_g); recv_map: (g_loc,).
    Returns (g_loc,) ghost values (pad slots = fill).
    """
    ext = jnp.concatenate([vals_loc, jnp.full((1,), fill, vals_loc.dtype)])
    send = ext[send_idx]  # (P, cap_g); pads read the fill slot
    recv = all_to_all(send, AXIS, 0, 0)  # (P, cap_g)
    recv_ext = jnp.concatenate(
        [recv.reshape(-1), jnp.full((1,), fill, vals_loc.dtype)]
    )
    return recv_ext[recv_map]


def pack_by_owner(keys, drop, n_loc: int, cap: int, *vals):
    """Sort-pack (key, *val) tuples into per-owner send buffers.

    keys: (Q,) global ids; drop: (Q,) bool — excluded entries.
    Returns (key_buf (P, cap), val_bufs [(P, cap)...], flat_pos (Q,),
    overflow).  ``flat_pos[q]`` is the send-buffer slot of query q (so the
    response at the same slot of the receive buffer answers it); dropped or
    overflowed entries point at the fill slot P*cap.

    Key fill value is -1 (never a valid global id), so owners can mask.
    """
    P = jax.lax.axis_size(AXIS)
    Q = keys.shape[0]
    dest = jnp.where(drop, P, keys // n_loc).astype(jnp.int32)
    order = jnp.argsort(dest, stable=True)
    d_s = dest[order]
    counts = jax.ops.segment_sum(
        jnp.ones(Q, jnp.int32), d_s, num_segments=P + 1, indices_are_sorted=True
    )
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1]])
    pos = jnp.arange(Q, dtype=jnp.int32) - starts[jnp.clip(d_s, 0, P)]
    valid = (d_s < P) & (pos < cap)
    slot_s = jnp.where(valid, d_s * cap + pos, P * cap)
    overflow = jnp.sum((d_s < P) & (pos >= cap)).astype(jnp.int32)

    def scatter(v, fill):
        buf = jnp.full(P * cap + 1, fill, v.dtype)
        return buf.at[slot_s].set(v[order], mode="drop")[: P * cap].reshape(P, cap)

    key_buf = scatter(keys, jnp.asarray(-1, keys.dtype))
    val_bufs = [scatter(v, jnp.asarray(0, v.dtype)) for v in vals]
    flat_pos = (
        jnp.full(Q, P * cap, dtype=jnp.int32).at[order].set(slot_s, mode="drop")
    )
    return key_buf, val_bufs, flat_pos, overflow


def owner_query(keys, drop, table_loc, n_loc: int, cap: int, *, fill):
    """Fetch ``table[key]`` from each key's owner shard.

    table_loc: (n_loc,) this shard's slice of the conceptual global table.
    Returns ((Q,) values — dropped entries get ``fill`` — , overflow).
    """
    P = jax.lax.axis_size(AXIS)
    base = jax.lax.axis_index(AXIS).astype(keys.dtype) * n_loc
    key_buf, _, flat_pos, overflow = pack_by_owner(keys, drop, n_loc, cap)
    recv = all_to_all(key_buf, AXIS, 0, 0)  # (P, cap) keys to serve
    local = recv.reshape(-1) - base
    ok = (local >= 0) & (local < n_loc)
    resp = jnp.where(
        ok, table_loc[jnp.clip(local, 0, n_loc - 1)], jnp.asarray(fill, table_loc.dtype)
    ).reshape(P, cap)
    back = all_to_all(resp, AXIS, 0, 0)  # (P, cap) answers
    back_ext = jnp.concatenate(
        [back.reshape(-1), jnp.full((1,), fill, table_loc.dtype)]
    )
    return back_ext[flat_pos], overflow


def owner_aggregate(keys, vals, drop, n_loc: int, cap: int):
    """Segment-sum (key, val) pairs at each key's owner shard.

    Returns ((n_loc,) per-owner sums over this shard's key range, overflow).
    Pairs are pre-aggregated locally by key (sort + run-reduce) before
    routing, so at most min(Q, n_loc) distinct pairs travel.
    """
    P = jax.lax.axis_size(AXIS)
    base = jax.lax.axis_index(AXIS).astype(keys.dtype) * n_loc
    Q = keys.shape[0]
    # local pre-aggregation: sort by key, reduce runs
    big = jnp.asarray(jnp.iinfo(keys.dtype).max, keys.dtype)
    k_sorted, v_sorted = jax.lax.sort(
        (jnp.where(drop, big, keys), jnp.where(drop, 0, vals)), dimension=0, num_keys=1
    )
    first = jnp.concatenate(
        [jnp.ones(1, bool), k_sorted[1:] != k_sorted[:-1]]
    )
    c = jnp.cumsum(v_sorted)
    run_base = jax.lax.cummax(jnp.where(first, c - v_sorted, 0))
    end = jnp.concatenate([first[1:], jnp.ones(1, bool)])
    run_sum = c - run_base
    send_drop = ~(end & (k_sorted != big))
    key_buf, (val_buf,), _, overflow = pack_by_owner(
        k_sorted, send_drop, n_loc, cap, jnp.where(send_drop, 0, run_sum)
    )
    rk = all_to_all(key_buf, AXIS, 0, 0).reshape(-1)
    rv = all_to_all(val_buf, AXIS, 0, 0).reshape(-1)
    local = rk - base
    ok = (local >= 0) & (local < n_loc)
    return (
        jax.ops.segment_sum(
            jnp.where(ok, rv, 0),
            jnp.clip(local, 0, n_loc - 1).astype(jnp.int32),
            num_segments=n_loc,
        ),
        overflow,
    )
