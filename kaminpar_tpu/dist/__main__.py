"""``python -m kaminpar_tpu.dist`` — the dKaMinPar binary equivalent.

Reference: ``apps/dKaMinPar.cc:546`` (MPI init + parse + read + facade).
The mesh replaces MPI_COMM_WORLD: by default all visible devices form a 1D
``('nodes',)`` mesh; ``--shards N --virtual-cpu`` forces N virtual CPU
devices — the CLI face of the KaTestrophe-style oversubscribed testing
(SURVEY §4) and the way to exercise the distributed pipeline on a laptop.
"""

from __future__ import annotations

import sys
import time

import numpy as np


def main(argv=None) -> int:
    import argparse

    from ..presets import get_preset_names

    p = argparse.ArgumentParser(
        prog="kaminpar_tpu.dist",
        description="Distributed TPU-native balanced k-way graph partitioner "
        "(dKaMinPar-equivalent; shards over a device mesh).",
    )
    p.add_argument("graph", help="input graph (METIS or ParHIP format)")
    p.add_argument("k", type=int, help="number of blocks")
    p.add_argument("-P", "--preset", default="default", choices=get_preset_names())
    p.add_argument("-e", "--epsilon", type=float, default=0.03)
    p.add_argument("-f", "--format", default=None, choices=["metis", "parhip"])
    p.add_argument("-o", "--output", default=None, help="partition output file")
    p.add_argument("-s", "--seed", type=int, default=None)
    p.add_argument("-q", "--quiet", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("--shards", type=int, default=None,
                   help="number of mesh shards (default: all visible devices)")
    p.add_argument("--virtual-cpu", action="store_true",
                   help="force --shards virtual CPU devices (test/dev mode; "
                        "the oversubscribed-MPI analog)")
    p.add_argument("--use-64bit", action="store_true",
                   help="64-bit node/edge ids and weights")
    args = p.parse_args(argv)

    if args.virtual_cpu:
        from ..utils.platform import force_cpu_devices

        force_cpu_devices(args.shards or 8)

    from ..utils.logger import Logger, OutputLevel

    prev_level = Logger.level
    if args.quiet:
        Logger.level = OutputLevel.QUIET
    elif args.verbose:
        Logger.level = OutputLevel.DEBUG
    try:
        return _run(args)
    finally:
        # Logger.level is process-global; restore it so in-process callers
        # (tests invoke main() as a function) are unaffected.
        Logger.level = prev_level


def _run(args) -> int:
    import jax
    from jax.sharding import Mesh

    from .. import io as kio
    from ..graph import metrics
    from ..presets import create_context_by_preset_name
    from ..utils.logger import Logger
    from .partitioner import DKaMinPar

    devs = jax.devices()
    num = args.shards or len(devs)
    if len(devs) < num:
        print(f"error: need {num} devices, have {len(devs)} "
              "(use --virtual-cpu for virtual shards)", file=sys.stderr)
        return 2
    mesh = Mesh(np.array(devs[:num]), ("nodes",))

    ctx = create_context_by_preset_name(args.preset)
    if args.seed is not None:
        ctx.seed = args.seed
    if args.use_64bit:
        ctx.use_64bit_ids = True
        jax.config.update("jax_enable_x64", True)

    t0 = time.perf_counter()
    graph = kio.read_graph(args.graph, args.format, use_64bit=ctx.use_64bit_ids,
                           decompress=True)
    Logger.log(
        f"Input graph: n={graph.n} m={graph.m // 2} "
        f"(read in {time.perf_counter() - t0:.2f}s); mesh={num} shards "
        f"on {devs[0].platform}"
    )

    solver = DKaMinPar(mesh, ctx)
    t0 = time.perf_counter()
    part = solver.compute_partition(graph, args.k, epsilon=args.epsilon)
    wall = time.perf_counter() - t0

    cut = metrics.edge_cut(graph, part)
    bw = np.bincount(part, weights=np.asarray(graph.node_w), minlength=args.k)
    avg = graph.total_node_weight / args.k
    Logger.log(
        f"Partition: cut={cut} imbalance={bw.max() / avg - 1.0:.4f} "
        f"k={args.k} wall={wall:.2f}s"
    )
    if args.output:
        kio.write_partition(args.output, part)
        Logger.log(f"Partition written to {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
