"""Distributed (multi-chip) tier — the dKaMinPar equivalent.

Node ranges are sharded 1D across a ``jax.sharding.Mesh`` axis (the analog of
the reference's ``node_distribution[]`` over MPI ranks,
kaminpar-dist/datastructures/distributed_csr_graph.h:39-100); per-round label
exchange rides XLA collectives over ICI instead of sparse MPI alltoalls
(SURVEY §2.2 TPU-native equivalent).
"""

from .graph import DistGraph, distribute_graph  # noqa: F401
from .lp import (  # noqa: F401
    dist_cluster_iterate,
    dist_lp_iterate,
    dist_lp_round,
)
from .compressed import (  # noqa: F401
    DistributedCompressedGraph,
    compress_distributed,
)
from .device_compressed import (  # noqa: F401
    DistDeviceCompressedView,
    build_dist_device_view,
    materialize_dist_graph,
)
