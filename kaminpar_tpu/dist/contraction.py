"""Distributed cluster contraction — sharded sort-reduce + all-to-all.

Reference: ``kaminpar-dist/coarsening/contraction/global_cluster_contraction.cc``
(assign coarse ids, migrate coarse edges to their owners via sparse alltoall,
build the coarse DistributedCSRGraph).  TPU re-design per SURVEY §2.2/§5:
the sparse MPI alltoall becomes a **dense padded ``jax.lax.all_to_all``**
over the mesh axis, with buffer capacities measured on device and read back
once per level (the multilevel loop is host orchestration anyway).

No per-shard array is O(N): cluster-id compaction is *owner-computed* —
the owner shard of each cluster id (owner = id // n_loc) marks used ids in
its own (n_loc,) range, shards exchange only the P used-counts for the
exclusive scan, and fine shards fetch compact ids via owner-routed queries
(``exchange.owner_query``).  This replaces the previous design's
psum-of-(N,)-presence arrays, which made per-device memory O(N).

Per level:
  S1 (jit)  owner-aggregate cluster weights → used marks → exscan compact
            ids; read back n_c + overflow.
  S2 (jit)  ghost-exchange labels, owner-query compact ids for every
            neighbor slot, route coarse edges + coarse node weights to
            their coarse-layout owners (sort by destination); read back
            send counts.
  S3 (jit)  dense all-to-all + local (cu, cv) sort-reduce aggregation +
            node-weight aggregation; read back coarse edge counts.
  S4 (jit)  compact to the coarse layout; host builds the coarse ghost
            routing from the aggregated global ids (O(m_c) host work on a
            geometrically shrinking series).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.segment import run_starts2
from ..utils import sync_stats
from ..utils.intmath import next_pow2
from .exchange import (
    AXIS,
    all_gather,
    all_to_all,
    build_ghost_exchange,
    ghost_exchange,
    localize_columns,
    owner_aggregate,
    owner_query,
    psum,
)
from .graph import DistGraph


def _next_pow2_dyn(x):
    """Device-side next power of two with minimum 8 — MUST match the host's
    ``next_pow2(x, 8)`` exactly (routing in S2 and buffer layout in S3/S4
    use the two interchangeably).  Integer bit-smear, no float rounding."""
    x = jnp.maximum(x, 8) - 1
    for s in (1, 2, 4, 8, 16):
        x = x | (x >> s)
    return x + 1


@partial(jax.jit, static_argnames=("mesh", "n_loc", "cap_q"))
def _s1(mesh, labels, node_w, *, n_loc: int, cap_q: int):
    """Owner-computed compaction: cluster weights + used marks + compact ids."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS)),
        out_specs=(P(), P(AXIS), P(AXIS), P()),
    )
    def body(labels_loc, node_w_loc):
        real = node_w_loc > 0
        cw_own, ovf = owner_aggregate(labels_loc, node_w_loc, ~real, n_loc, cap_q)
        used = cw_own > 0
        cnt = jnp.sum(used).astype(jnp.int32)
        cnts = all_gather(cnt, AXIS)  # (P,) — O(P), not O(N)
        idx = jax.lax.axis_index(AXIS)
        base = (jnp.cumsum(cnts) - cnts)[idx].astype(labels_loc.dtype)
        cmap_own = jnp.where(
            used, base + jnp.cumsum(used.astype(labels_loc.dtype)) - 1, -1
        )
        n_c = psum(cnt, AXIS)  # psum → statically replicated
        return n_c, cw_own, cmap_own, psum(ovf, AXIS)

    return body(labels, node_w)


def _s2_core(labels_loc, cmap_own_loc, cw_own_loc, eu, cl, ew, sidx, rmap, *,
             n_loc: int, n_loc_c: int, cap_q: int):
    """S2 per-shard core (inside shard_map), shared by the dense ``_s2``
    wrapper below and the decode-fused compressed twin
    (dist/device_compressed._s2c): coarse endpoints via owner queries, then
    route coarse edges + node weights by their coarse-layout owner."""
    nshards = jax.lax.axis_size(AXIS)
    ghost_labels = ghost_exchange(
        labels_loc, sidx, rmap, fill=jnp.asarray(-1, labels_loc.dtype)
    )
    qkeys = jnp.concatenate([labels_loc, ghost_labels])
    qdrop = qkeys < 0
    cvals, ovf = owner_query(
        qkeys, qdrop, cmap_own_loc, n_loc, cap_q,
        fill=jnp.asarray(-1, labels_loc.dtype),
    )
    g_loc = ghost_labels.shape[0]
    cmap_slot = jnp.concatenate(
        [cvals, jnp.full((1,), -1, cvals.dtype)]
    )  # (n_loc + g_loc + 1,)
    cu_node = cvals[:n_loc]  # coarse id of each local node (= coarse_of)
    cu = cu_node[eu]
    cv = cmap_slot[jnp.clip(cl, 0, n_loc + g_loc)]
    keep = (ew > 0) & (cu != cv) & (cu >= 0) & (cv >= 0)

    # route edges by owner shard of cu under the coarse layout
    dest = jnp.where(keep, cu // n_loc_c, nshards).astype(jnp.int32)
    order = jnp.argsort(dest, stable=True)
    counts = jax.ops.segment_sum(
        jnp.ones_like(dest), dest, num_segments=nshards + 1
    )[:nshards]

    # route coarse node weights by owner of the compact id
    used = cmap_own_loc >= 0
    wdest = jnp.where(used, cmap_own_loc // n_loc_c, nshards).astype(jnp.int32)
    worder = jnp.argsort(wdest, stable=True)
    wcounts = jax.ops.segment_sum(
        jnp.ones_like(wdest), wdest, num_segments=nshards + 1
    )[:nshards]

    return (
        cu_node,
        cu[order], cv[order], jnp.where(keep, ew, 0)[order], counts,
        cmap_own_loc[worder], cw_own_loc[worder], wcounts,
        psum(ovf, AXIS),
    )


@partial(
    jax.jit,
    static_argnames=("mesh", "n_loc", "n_loc_c", "cap_q"),
)
def _s2(mesh, labels, cmap_own, cw_own, edge_u, col_loc, edge_w, send_idx,
        recv_map, *, n_loc: int, n_loc_c: int, cap_q: int):
    """Coarse endpoints via owner queries; route edges + weights by coarse owner."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS),) * 8,
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                   P(AXIS), P(AXIS), P(AXIS), P()),
    )
    def body(labels_loc, cmap_own_loc, cw_own_loc, eu, cl, ew, sidx, rmap):
        return _s2_core(
            labels_loc, cmap_own_loc, cw_own_loc, eu, cl, ew, sidx, rmap,
            n_loc=n_loc, n_loc_c=n_loc_c, cap_q=cap_q,
        )

    return body(labels, cmap_own, cw_own, edge_u, col_loc, edge_w,
                send_idx, recv_map)


@partial(
    jax.jit,
    static_argnames=("mesh", "num_shards", "cap", "cap_w", "n_loc_c"),
)
def _s3(mesh, s_cu, s_cv, s_w, counts, w_keys, w_vals, wcounts, *,
        num_shards: int, cap: int, cap_w: int, n_loc_c: int):
    """Dense all-to-all of routed edges/weights + local aggregation."""
    P_ = num_shards

    def _pack(dest_sorted_vals, cnt, cap_, fill):
        m = dest_sorted_vals.shape[0]
        starts = jnp.concatenate([jnp.zeros(1, cnt.dtype), jnp.cumsum(cnt)[:-1]])
        dest = jnp.searchsorted(jnp.cumsum(cnt), jnp.arange(m), side="right")
        pos = jnp.arange(m) - starts[jnp.clip(dest, 0, P_ - 1)]
        valid = (dest < P_) & (pos < cap_)
        flat_pos = jnp.where(valid, jnp.clip(dest, 0, P_ - 1) * cap_ + pos, P_ * cap_)
        buf = jnp.full(P_ * cap_ + 1, fill, dest_sorted_vals.dtype)
        return buf.at[flat_pos].set(
            jnp.where(valid, dest_sorted_vals, fill), mode="drop"
        )[: P_ * cap_].reshape(P_, cap_)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS),) * 7,
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
    )
    def body(cu, cv, w, cnt, wk, wv, wcnt):
        idx = jax.lax.axis_index(AXIS)
        send_cu = _pack(cu, cnt, cap, jnp.asarray(0, cu.dtype))
        send_cv = _pack(cv, cnt, cap, jnp.asarray(0, cv.dtype))
        send_w = _pack(w, cnt, cap, jnp.asarray(0, w.dtype))
        r_cu = all_to_all(send_cu, AXIS, 0, 0).reshape(-1)
        r_cv = all_to_all(send_cv, AXIS, 0, 0).reshape(-1)
        r_w = all_to_all(send_w, AXIS, 0, 0).reshape(-1)

        # local aggregation by (cu_local, cv)
        S = r_cu.shape[0]  # P_ * cap
        cu_l = r_cu - idx.astype(r_cu.dtype) * n_loc_c
        key_u = jnp.where(r_w > 0, cu_l, n_loc_c)  # drops sort last
        su, sv, sw = jax.lax.sort((key_u, r_cv, r_w), dimension=0, num_keys=2)
        first = run_starts2(su, sv)
        c = jnp.cumsum(sw)
        run_base = jax.lax.cummax(jnp.where(first, c - sw, 0))
        end = jnp.concatenate([first[1:], jnp.ones(1, bool)])
        run_w = jnp.where(end & (su < n_loc_c), c - run_base, 0)
        valid_run = end & (su < n_loc_c) & (run_w > 0)
        m_c_loc = jnp.sum(valid_run)
        ridx = jnp.cumsum(valid_run) - 1
        pos2 = jnp.where(valid_run, ridx, S)
        out_u = jnp.zeros(S, su.dtype).at[pos2].set(su, mode="drop")
        out_v = jnp.zeros(S, sv.dtype).at[pos2].set(sv, mode="drop")
        out_w = jnp.zeros(S, sw.dtype).at[pos2].set(run_w, mode="drop")

        # coarse node weights: aggregate received (compact id, weight) pairs
        send_wk = _pack(wk, wcnt, cap_w, jnp.asarray(-1, wk.dtype))
        send_wv = _pack(wv, wcnt, cap_w, jnp.asarray(0, wv.dtype))
        r_wk = all_to_all(send_wk, AXIS, 0, 0).reshape(-1)
        r_wv = all_to_all(send_wv, AXIS, 0, 0).reshape(-1)
        wl = r_wk - idx.astype(r_wk.dtype) * n_loc_c
        wok = (wl >= 0) & (wl < n_loc_c)
        node_w_c = jax.ops.segment_sum(
            jnp.where(wok, r_wv, 0),
            jnp.clip(wl, 0, n_loc_c - 1).astype(jnp.int32),
            num_segments=n_loc_c,
        )
        return out_u, out_v, out_w, m_c_loc.astype(jnp.int32).reshape(1), node_w_c

    return body(s_cu, s_cv, s_w, counts, w_keys, w_vals, wcounts)


@partial(jax.jit, static_argnames=("mesh", "m_loc_c"))
def _s4(mesh, agg_u, agg_v, agg_w, *, m_loc_c: int):
    """Compact per-shard aggregated edges into the coarse layout."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS)),
    )
    def body(u, v, w):
        return u[:m_loc_c], v[:m_loc_c], w[:m_loc_c]

    return body(agg_u, agg_v, agg_w)


def contract_dist_clustering(
    mesh: Mesh, graph: DistGraph, labels, cap_q: int | None = None
) -> Tuple[DistGraph, jax.Array, int]:
    """Contract a distributed clustering; returns (coarse graph, coarse_of,
    n_c) where ``coarse_of`` holds each fine node's *global coarse id* (used
    by uncoarsening projection; -1 on pad nodes).

    ``graph`` may also be a :class:`~kaminpar_tpu.dist.device_compressed.
    DistDeviceCompressedView`: the decode-fused S2 twin runs instead and
    the adjacency never materializes as resident dense arrays."""
    if getattr(graph, "is_compressed_view", False):
        from .device_compressed import contract_dist_compressed

        return contract_dist_compressed(mesh, graph, labels, cap_q=cap_q)
    Pn = graph.num_shards
    n_loc = graph.n_loc
    if cap_q is None:
        cap_q = min(next_pow2(max(64, 2 * n_loc // Pn), 8), n_loc)

    while True:
        n_c, cw_own, cmap_own, ovf = _s1(
            mesh, labels, graph.node_w, n_loc=n_loc, cap_q=cap_q
        )
        # Packed (n_c, overflow) readback: both mesh-replicated scalars
        # leave the device in ONE counted transfer per attempt (round 13:
        # the int() coercions here were un-counted implicit pulls).
        s1_stats = sync_stats.pull(jnp.stack([n_c, ovf]), shards=Pn)
        if int(s1_stats[1]) == 0 or cap_q >= n_loc:
            break
        cap_q = min(cap_q * 2, n_loc)
    n_c = int(s1_stats[0])
    n_loc_c = next_pow2((n_c + Pn) // Pn, 8)

    cap_q2 = cap_q
    while True:
        (coarse_of, s_cu, s_cv, s_w, counts, w_keys, w_vals, wcounts, ovf2) = _s2(
            mesh, labels, cmap_own, cw_own, graph.edge_u, graph.col_loc,
            graph.edge_w, graph.send_idx, graph.recv_map,
            n_loc=n_loc, n_loc_c=n_loc_c, cap_q=cap_q2,
        )
        ovf2_h = int(sync_stats.pull(ovf2, shards=Pn))
        if ovf2_h == 0 or cap_q2 >= n_loc + graph.g_loc:
            break
        cap_q2 = min(cap_q2 * 2, n_loc + graph.g_loc)

    # Counted batched readback of the staging counts (round 12, kptlint
    # sync-discipline: these were un-counted np.asarray strays).
    counts_h, wcounts_h = sync_stats.pull(counts, wcounts, shards=Pn)
    cap = next_pow2(int(counts_h.max()), 8)
    cap_w = next_pow2(int(wcounts_h.max()), 8)

    agg_u, agg_v, agg_w, m_c_loc, node_w_c = _s3(
        mesh, s_cu, s_cv, s_w, counts, w_keys, w_vals, wcounts,
        num_shards=Pn, cap=cap, cap_w=cap_w, n_loc_c=n_loc_c,
    )
    m_c_loc = sync_stats.pull(m_c_loc, shards=Pn)
    m_loc_c = next_pow2(int(m_c_loc.max()), 8)
    m_loc_c = min(m_loc_c, Pn * cap)  # aggregation buffer bound (ADVICE r1)

    edge_u_g, col_g, edge_w_c = _s4(mesh, agg_u, agg_v, agg_w, m_loc_c=m_loc_c)

    coarse = _assemble_coarse(
        edge_u_g, col_g, edge_w_c, node_w_c, m_c_loc, n_c,
        n_loc_c=n_loc_c, m_loc_c=m_loc_c, num_shards=Pn,
    )
    return coarse, coarse_of, n_c


def _assemble_coarse(edge_u_g, col_g, edge_w_c, node_w_c,
                     m_c_loc: np.ndarray, n_c, *,
                     n_loc_c: int, m_loc_c: int, num_shards: int) -> DistGraph:
    """Host tail shared by global and local contraction: localize edge
    targets + build the coarse ghost routing (O(m_c) host work on a
    geometrically shrinking series).  The edge sources are ALREADY
    shard-local (cu_l subtraction in the aggregation bodies) — do not
    localize them again."""
    Pn = num_shards
    m_total = int(m_c_loc.sum())  # pulled by the caller alongside the caps
    # One counted batched readback for the host assembly inputs.
    eu_l, cv_g, w_np = sync_stats.pull(edge_u_g, col_g, edge_w_c, shards=Pn)
    eu_l = eu_l.reshape(Pn, m_loc_c)
    cv_g = cv_g.reshape(Pn, m_loc_c)
    w_np = w_np.reshape(Pn, m_loc_c)
    dtype = eu_l.dtype
    col_shards = [cv_g[s] for s in range(Pn)]
    valid_shards = [w_np[s] > 0 for s in range(Pn)]
    send_idx, recv_map, ghost_global, cap_g, g_loc = build_ghost_exchange(
        col_shards, valid_shards, n_loc_c, Pn, dtype=dtype
    )
    edge_u_c = np.where(w_np > 0, eu_l, 0)
    col_loc_c = np.stack(
        [
            localize_columns(
                cv_g[s], valid_shards[s], ghost_global[s], s, n_loc_c, g_loc,
                dtype,
            )
            for s in range(Pn)
        ]
    )

    # Per-shard work table from the SAME host arrays the assembly already
    # holds (round 13): the coarse level's mesh-telemetry lanes and
    # ShardStats cost zero extra readbacks.
    from .graph import compute_shard_work

    shard_work = compute_shard_work(
        send_idx, ghost_global,
        owned_nodes=[
            max(0, min((s + 1) * n_loc_c, int(n_c)) - s * n_loc_c)
            for s in range(Pn)
        ],
        owned_edges=[int((w_np[s] > 0).sum()) for s in range(Pn)],
        n_loc=n_loc_c, num_shards=Pn,
    )

    return DistGraph(
        node_w=jnp.asarray(node_w_c).reshape(-1),
        edge_u=jnp.asarray(edge_u_c.reshape(-1)),
        col_loc=jnp.asarray(col_loc_c.reshape(-1)),
        edge_w=jnp.asarray(edge_w_c).reshape(-1),
        send_idx=jnp.asarray(send_idx),
        recv_map=jnp.asarray(recv_map),
        ghost_global=tuple(ghost_global),
        n=n_c,
        m=m_total,
        n_loc=n_loc_c,
        m_loc=m_loc_c,
        g_loc=g_loc,
        cap_g=cap_g,
        num_shards=Pn,
        shard_work=shard_work,
    )


@lru_cache(maxsize=None)
def _make_project_up(mesh: Mesh, *, n_loc_c: int, cap: int):
    """Cached projection program: the old inline ``jax.jit`` closure
    re-traced (and re-counted its collectives) on EVERY uncoarsening level
    of every run — caching on (mesh, n_loc_c, cap) matches the other
    make_dist_* factories (found via the round-13 collective census, which
    showed a constant per-run trace delta on identical repeat runs)."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS)), out_specs=(P(AXIS), P()),
    )
    def body(c_of_loc, c_part_loc):
        drop = c_of_loc < 0
        vals, ovf = owner_query(
            c_of_loc, drop, c_part_loc, n_loc_c, cap,
            fill=jnp.asarray(0, c_part_loc.dtype),
        )
        return jnp.where(drop, 0, vals), psum(ovf, AXIS)

    return jax.jit(body)


def project_partition_up(mesh, coarse_of, coarse_part, *, n_loc_c: int,
                         cap_q: int | None = None):
    """fine_part[u] = coarse_part[coarse_of[u]] via owner-routed queries
    (reference: uncoarsening projection, kaminpar-dist deep_multilevel.cc:347).

    ``coarse_part`` is (P*n_loc_c,)-sharded; no O(N) gather."""
    n_loc_f = coarse_of.shape[0] // mesh.size
    if cap_q is None:
        cap_q = min(next_pow2(max(64, 2 * n_loc_f // mesh.size), 8), n_loc_f)

    while True:
        out, ovf = _make_project_up(mesh, n_loc_c=n_loc_c, cap=cap_q)(
            coarse_of, coarse_part
        )
        # Counted overflow readback (round 13; was an implicit int() pull).
        if int(sync_stats.pull(ovf, shards=mesh.size)) == 0 or cap_q >= n_loc_f:
            break
        cap_q = min(cap_q * 2, n_loc_f)
    return out


# ---------------------------------------------------------------------------
# Local contraction.  Reference: kaminpar-dist/coarsening/contraction/
# local_contraction.cc — when the clustering is shard-local (every cluster
# id is owned by the node's own shard, e.g. the LOCAL_LP clusterer), the
# expensive cluster-resolution machinery of the global path disappears:
# compaction is a per-shard rank (no owner_aggregate), neighbor coarse ids
# arrive with ONE ghost exchange (no two-phase owner_query), and edges are
# aggregated in-shard BEFORE the migration all-to-all, which then carries
# m_c_loc (deduplicated) instead of m_loc entries.  The output uses the
# same contiguous coarse layout as the global path — coarse ids are
# exscan(count) + rank, so the prefix-dense invariant ("real iff global id
# < n") that dist_color/_replicate_to_host/extension all rely on keeps
# holding; a shard-resident coarse layout (holes between shards) was tried
# first and silently lost ~25% of the node weight per level through that
# invariant.  _l2 therefore emits exactly _s2's output contract and the
# shared _s3/_s4/_assemble_coarse tail finishes the job.
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("mesh", "n_loc", "n_real"))
def _l1(mesh, labels, node_w, *, n_loc: int, n_real: int):
    """Per-shard cluster weights + compact ranks + counts + locality check."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P()),
    )
    def body(labels_loc, node_w_loc):
        idx = jax.lax.axis_index(AXIS)
        base = idx.astype(labels_loc.dtype) * n_loc
        real = base + jnp.arange(n_loc, dtype=labels_loc.dtype) < n_real
        lab_l = labels_loc - base
        nonlocal_count = psum(
            jnp.sum(real & ((lab_l < 0) | (lab_l >= n_loc))).astype(jnp.int32),
            AXIS,
        )
        lab_c = jnp.clip(lab_l, 0, n_loc - 1).astype(jnp.int32)
        cw = jax.ops.segment_sum(
            jnp.where(real, node_w_loc, 0), lab_c, num_segments=n_loc
        )
        used = cw > 0
        rank = jnp.cumsum(used.astype(jnp.int32)) - 1
        count = jnp.sum(used).astype(jnp.int32)
        return cw, rank, count.reshape(1), nonlocal_count

    return body(labels, node_w)


@partial(jax.jit,
         static_argnames=("mesh", "n_loc", "n_loc_c", "r_loc", "n_real"))
def _l2(mesh, labels, rank, cw, bases, edge_u, col_loc, edge_w, send_idx,
        recv_map, *, n_loc: int, n_loc_c: int, r_loc: int, n_real: int):
    """Contiguous coarse ids via one ghost exchange + in-shard (cu, cv)
    sort-reduce + route-by-coarse-owner.  Emits _s2's output contract
    (without its overflow flag — there is no owner_query to overflow).

    ``bases`` is the (P,) exclusive scan of per-shard cluster counts;
    ``r_loc`` bounds the per-shard local rank (>= max count, pow2)."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(), P(AXIS), P(AXIS), P(AXIS),
                  P(AXIS), P(AXIS)),
        out_specs=(P(AXIS),) * 8,
    )
    def body(labels_loc, rank_loc, cw_loc, bases_all, eu, cl, ew, sidx, rmap):
        nshards = jax.lax.axis_size(AXIS)
        idx = jax.lax.axis_index(AXIS)
        base = idx.astype(labels_loc.dtype) * n_loc
        base_c = bases_all[idx].astype(labels_loc.dtype)
        real = base + jnp.arange(n_loc, dtype=labels_loc.dtype) < n_real
        lab_c = jnp.clip(labels_loc - base, 0, n_loc - 1).astype(jnp.int32)
        coarse_of = jnp.where(
            real, base_c + rank_loc[lab_c].astype(labels_loc.dtype), -1
        )

        ghost_c = ghost_exchange(
            coarse_of, sidx, rmap, fill=jnp.asarray(-1, coarse_of.dtype)
        )
        ext = jnp.concatenate(
            [coarse_of, ghost_c, jnp.full((1,), -1, coarse_of.dtype)]
        )
        g_loc = ghost_c.shape[0]
        cu = coarse_of[eu]
        cv = ext[jnp.clip(cl, 0, n_loc + g_loc)]
        keep = (ew > 0) & (cu != cv) & (cu >= 0) & (cv >= 0)

        # in-shard aggregation by (local rank, cv) — the _s3 sort-reduce
        # shape, keyed by rank (bounded by r_loc, NOT n_loc_c: a skewed
        # shard can own more clusters than the contiguous layout's slot
        # count).
        S = eu.shape[0]
        cu_r = cu - base_c
        key_u = jnp.where(keep, cu_r, r_loc)  # drops sort last
        su, sv, sw = jax.lax.sort(
            (key_u, cv, jnp.where(keep, ew, 0)), dimension=0, num_keys=2
        )
        first = run_starts2(su, sv)
        c = jnp.cumsum(sw)
        run_base = jax.lax.cummax(jnp.where(first, c - sw, 0))
        end = jnp.concatenate([first[1:], jnp.ones(1, bool)])
        run_w = jnp.where(end & (su < r_loc), c - run_base, 0)
        valid_run = end & (su < r_loc) & (run_w > 0)

        # route the aggregated runs by the coarse owner under the
        # contiguous layout (the _s2 routing block, on m_c_loc entries)
        cu_g = su + base_c  # back to global contiguous ids
        dest = jnp.where(valid_run, cu_g // n_loc_c, nshards).astype(jnp.int32)
        order = jnp.argsort(dest, stable=True)
        counts = jax.ops.segment_sum(
            jnp.ones_like(dest), dest, num_segments=nshards + 1
        )[:nshards]
        s_cu = jnp.where(valid_run, cu_g, 0)[order]
        s_cv = jnp.where(valid_run, sv, 0)[order]
        s_w = jnp.where(valid_run, run_w, 0)[order]

        # route coarse node weights by owner of the final id (as in _s2)
        used = cw_loc > 0
        final_id = base_c + rank_loc.astype(labels_loc.dtype)
        wdest = jnp.where(used, final_id // n_loc_c, nshards).astype(jnp.int32)
        worder = jnp.argsort(wdest, stable=True)
        wcounts = jax.ops.segment_sum(
            jnp.ones_like(wdest), wdest, num_segments=nshards + 1
        )[:nshards]
        wk = jnp.where(used, final_id, -1)[worder]
        wv = jnp.where(used, cw_loc, 0)[worder]

        return coarse_of, s_cu, s_cv, s_w, counts, wk, wv, wcounts

    return body(labels, rank, cw, bases, edge_u, col_loc, edge_w,
                send_idx, recv_map)


def contract_local_clustering(
    mesh: Mesh, graph: DistGraph, labels
) -> Tuple[DistGraph, jax.Array, int]:
    """Contract a SHARD-LOCAL clustering (label // n_loc == own shard for
    every real node; the LOCAL_LP clusterer guarantees this).  Same return
    contract AND same coarse layout as :func:`contract_dist_clustering` —
    only cheaper: no owner-routed compaction/queries, and the migration
    all-to-all carries pre-aggregated edges.  Raises ValueError if the
    clustering is not local."""
    Pn = graph.num_shards
    n_loc = graph.n_loc

    cw, rank, counts, nonlocal_count = _l1(
        mesh, labels, graph.node_w, n_loc=n_loc, n_real=graph.n
    )
    nonlocal_h = int(sync_stats.pull(nonlocal_count, shards=Pn))
    if nonlocal_h > 0:
        raise ValueError(
            f"{nonlocal_h} nodes have non-local cluster ids; use "
            "contract_dist_clustering for clusterings that span shards"
        )
    counts = sync_stats.pull(counts, shards=Pn)
    n_c = int(counts.sum())
    n_loc_c = next_pow2((n_c + Pn) // Pn, 8)
    r_loc = next_pow2(int(counts.max()), 8)
    bases = jnp.asarray((np.cumsum(counts) - counts).astype(labels.dtype))

    (coarse_of, s_cu, s_cv, s_w, ecounts, w_keys, w_vals, wcounts) = _l2(
        mesh, labels, rank, cw, bases, graph.edge_u, graph.col_loc,
        graph.edge_w, graph.send_idx, graph.recv_map,
        n_loc=n_loc, n_loc_c=n_loc_c, r_loc=r_loc, n_real=graph.n,
    )
    ecounts_h, wcounts_h = sync_stats.pull(ecounts, wcounts, shards=Pn)
    cap = next_pow2(int(ecounts_h.max()), 8)
    cap_w = next_pow2(int(wcounts_h.max()), 8)

    agg_u, agg_v, agg_w, m_c_loc, node_w_c = _s3(
        mesh, s_cu, s_cv, s_w, ecounts, w_keys, w_vals, wcounts,
        num_shards=Pn, cap=cap, cap_w=cap_w, n_loc_c=n_loc_c,
    )
    m_c_loc = sync_stats.pull(m_c_loc, shards=Pn)
    m_loc_c = next_pow2(int(m_c_loc.max()), 8)
    m_loc_c = min(m_loc_c, Pn * cap)
    edge_u_g, col_g, edge_w_c = _s4(mesh, agg_u, agg_v, agg_w, m_loc_c=m_loc_c)

    coarse = _assemble_coarse(
        edge_u_g, col_g, edge_w_c, node_w_c, m_c_loc, n_c,
        n_loc_c=n_loc_c, m_loc_c=m_loc_c, num_shards=Pn,
    )
    return coarse, coarse_of, n_c
