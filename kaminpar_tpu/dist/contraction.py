"""Distributed cluster contraction — sharded sort-reduce + all-to-all.

Reference: ``kaminpar-dist/coarsening/contraction/global_cluster_contraction.cc``
(assign coarse ids, migrate coarse edges to their owners via sparse alltoall,
build the coarse DistributedCSRGraph).  TPU re-design per SURVEY §2.2/§5:
the sparse MPI alltoall becomes a **dense padded ``jax.lax.all_to_all``** over
the mesh axis; buffer capacities are measured on device, read back once per
level (the multilevel loop is host orchestration anyway), and the exchange
re-runs with static shapes.

Per level:  S1 (jit) relabel-compact + route coarse edges by owner →
host reads (n_c, send-capacity) → S2 (jit) dense all-to-all + local
(cu, cv)-aggregate → host reads coarse edge counts → S3 (jit) compact to the
coarse DistGraph layout.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.segment import run_starts2
from ..utils.intmath import next_pow2
from .graph import DistGraph
from .lp import AXIS


def _next_pow2_dyn(x):
    """Device-side next power of two with minimum 8 — MUST match the host's
    ``next_pow2(x, 8)`` exactly (routing in S1 and buffer layout in S2/S3
    use the two interchangeably).  Integer bit-smear, no float rounding."""
    x = jnp.maximum(x, 8) - 1
    for s in (1, 2, 4, 8, 16):
        x = x | (x >> s)
    return x + 1


@partial(jax.jit, static_argnames=("mesh", "num_shards"))
def _s1(mesh, labels, node_w, edge_u, col_idx, edge_w, *, num_shards: int):
    N = labels.shape[0]
    P_ = num_shards

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(), P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
    )
    def body(labels_loc, node_w_loc, eu, ci, ew):
        real = node_w_loc > 0
        # psum of per-shard marks, then clamp: a cluster spanning several
        # shards is marked by each of them and must still count once.
        presence = (
            jax.lax.psum(
                jnp.zeros(N, jnp.int32).at[jnp.where(real, labels_loc, 0)].max(
                    jnp.where(real, 1, 0)
                ),
                AXIS,
            )
            > 0
        ).astype(jnp.int32)
        cmap = (jnp.cumsum(presence) - 1).astype(jnp.int32)
        n_c = jnp.sum(presence)
        # replicated coarse node weights over the compact id space
        c_of_loc = jnp.clip(cmap[labels_loc], 0, N - 1)
        c_node_w = jax.lax.psum(
            jax.ops.segment_sum(node_w_loc, c_of_loc, num_segments=N), AXIS
        )

        # coarse endpoints of local edges
        labels_glob = jax.lax.all_gather(labels_loc, AXIS, tiled=True)
        cu = jnp.clip(cmap[labels_loc[eu]], 0, N - 1)
        cv = jnp.clip(cmap[labels_glob[ci]], 0, N - 1)
        keep = (ew > 0) & (cu != cv)

        # route by owner shard of cu under the coarse layout
        n_loc_c = _next_pow2_dyn((n_c + P_) // P_)
        dest = jnp.where(keep, cu // n_loc_c, P_)  # sentinel P_: dropped
        order = jnp.argsort(dest)
        counts = jax.ops.segment_sum(
            jnp.ones_like(dest), dest, num_segments=P_ + 1
        )[:P_]
        return n_c, c_node_w, c_of_loc, cu[order], cv[order], ew[order] * keep[order], counts

    return body(labels, node_w, edge_u, col_idx, edge_w)


@partial(jax.jit, static_argnames=("mesh", "num_shards", "cap", "n_loc_c"))
def _s2(mesh, s_cu, s_cv, s_w, counts, *, num_shards: int, cap: int, n_loc_c: int):
    """Dense all-to-all of routed coarse edges + local (cu, cv) aggregation."""
    P_ = num_shards

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
    )
    def body(cu, cv, w, cnt):
        m_loc = cu.shape[0]
        starts = jnp.concatenate([jnp.zeros(1, cnt.dtype), jnp.cumsum(cnt)[:-1]])
        dest = jnp.searchsorted(jnp.cumsum(cnt), jnp.arange(m_loc), side="right")
        pos = jnp.arange(m_loc) - starts[jnp.clip(dest, 0, P_ - 1)]
        valid = (dest < P_) & (pos < cap) & (w > 0)
        flat_pos = jnp.where(valid, jnp.clip(dest, 0, P_ - 1) * cap + pos, P_ * cap)

        def scatter(vals, fill):
            return jnp.full(P_ * cap, fill, vals.dtype).at[flat_pos].set(
                vals, mode="drop"
            )

        send_cu = scatter(cu, 0).reshape(P_, cap)
        send_cv = scatter(cv, 0).reshape(P_, cap)
        send_w = scatter(w, 0).reshape(P_, cap)
        r_cu = jax.lax.all_to_all(send_cu, AXIS, 0, 0, tiled=False).reshape(-1)
        r_cv = jax.lax.all_to_all(send_cv, AXIS, 0, 0, tiled=False).reshape(-1)
        r_w = jax.lax.all_to_all(send_w, AXIS, 0, 0, tiled=False).reshape(-1)

        # local aggregation by (cu_local, cv)
        S = r_cu.shape[0]  # P_ * cap
        cu_l = r_cu - jax.lax.axis_index(AXIS) * n_loc_c
        key_u = jnp.where(r_w > 0, cu_l, n_loc_c)  # drops sort last
        su, sv, sw = jax.lax.sort((key_u, r_cv, r_w), dimension=0, num_keys=2)
        first = run_starts2(su, sv)
        c = jnp.cumsum(sw)
        run_base = jax.lax.cummax(jnp.where(first, c - sw, 0))
        end = jnp.concatenate([first[1:], jnp.ones(1, bool)])
        run_w = jnp.where(end & (su < n_loc_c), c - run_base, 0)
        valid_run = end & (su < n_loc_c) & (run_w > 0)
        m_c_loc = jnp.sum(valid_run)
        ridx = jnp.cumsum(valid_run) - 1
        pos2 = jnp.where(valid_run, ridx, S)
        out_u = jnp.zeros(S, su.dtype).at[pos2].set(su, mode="drop")
        out_v = jnp.zeros(S, sv.dtype).at[pos2].set(sv, mode="drop")
        out_w = jnp.zeros(S, sw.dtype).at[pos2].set(run_w, mode="drop")
        return out_u, out_v, out_w, m_c_loc.astype(jnp.int32).reshape(1)

    return body(s_cu, s_cv, s_w, counts)


@partial(jax.jit, static_argnames=("mesh", "m_loc_c", "n_loc_c"))
def _s3(mesh, agg_u, agg_v, agg_w, c_node_w, *, m_loc_c: int, n_loc_c: int):
    """Compact per-shard aggregated edges into the coarse DistGraph layout."""

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P()),
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
    )
    def body(u, v, w, cw_full):
        idx = jax.lax.axis_index(AXIS)
        eu = u[:m_loc_c]
        cv = v[:m_loc_c]
        ew = w[:m_loc_c]
        nw = jax.lax.dynamic_slice(cw_full, (idx * n_loc_c,), (n_loc_c,))
        return nw, eu, cv, ew

    return body(agg_u, agg_v, agg_w, c_node_w)


def contract_dist_clustering(
    mesh: Mesh, graph: DistGraph, labels
) -> Tuple[DistGraph, jax.Array, int]:
    """Contract a distributed clustering; returns (coarse graph, coarse_of,
    n_c) where ``coarse_of`` is the (sharded) fine-node → coarse-id map used
    by uncoarsening projection."""
    Pn = graph.num_shards
    n_c, c_node_w, coarse_of, s_cu, s_cv, s_w, counts = _s1(
        mesh, labels, graph.node_w, graph.edge_u, graph.col_idx, graph.edge_w,
        num_shards=Pn,
    )
    n_c = int(n_c)
    n_loc_c = next_pow2((n_c + Pn) // Pn, 8)
    cap = next_pow2(int(np.max(np.asarray(counts))), 8)

    agg_u, agg_v, agg_w, m_c_loc = _s2(
        mesh, s_cu, s_cv, s_w, counts, num_shards=Pn, cap=cap, n_loc_c=n_loc_c
    )
    m_loc_c = next_pow2(int(np.max(np.asarray(m_c_loc))), 8)

    node_w_c, edge_u_c, col_c, edge_w_c = _s3(
        mesh, agg_u, agg_v, agg_w, c_node_w, m_loc_c=m_loc_c, n_loc_c=n_loc_c
    )
    m_total = int(np.sum(np.asarray(m_c_loc)))
    coarse = DistGraph(
        node_w=node_w_c, edge_u=edge_u_c, col_idx=col_c, edge_w=edge_w_c,
        n=n_c, m=m_total, n_loc=n_loc_c, m_loc=m_loc_c, num_shards=Pn,
    )
    return coarse, coarse_of, n_c


@partial(jax.jit, static_argnames=("mesh",))
def project_partition_up(mesh, coarse_of, coarse_part):
    """fine_part[u] = coarse_part[coarse_of[u]] across shards (reference:
    uncoarsening projection, kaminpar-dist deep_multilevel.cc:347)."""

    @partial(jax.shard_map, mesh=mesh, in_specs=(P(AXIS), P(AXIS)), out_specs=P(AXIS))
    def body(c_of, c_part):
        c_glob = jax.lax.all_gather(c_part, AXIS, tiled=True)
        return c_glob[c_of]

    return body(coarse_of, coarse_part)
