"""Per-shard min/mean/max statistics — the dist timer-aggregation analog.

The reference annotates every distributed timer-tree node with min/mean/max
over MPI ranks (kaminpar-dist/timer.cc:106-173): with one process per PE,
per-rank wall time *is* the load-imbalance signal, and the aggregated table
is how imbalance gets diagnosed.  Under SPMD/shard_map there is one host
program and one fused XLA program for all shards, so per-shard wall time is
not a host observable — XLA owns the schedule.  What the reference's table
is *used for* maps instead onto the per-shard work quantities that rank
wall time proxies there: owned nodes/edges, ghost and interface sizes, and
per-phase move counts.  ``ShardStats`` collects those and renders the same
``min / mean / max (imb)`` rows the reference prints, per pipeline phase.

Divergence note: a per-shard *time* column would require one dispatch per
shard (defeating SPMD) or on-device clocks (not exposed by XLA); the work
table plus the host timer tree together cover the reference's use cases.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

__all__ = ["ShardStats", "collect_graph_stats"]


class ShardStats:
    """Named (P,) per-shard samples with min/mean/max(+imbalance) rendering.

    ``imb`` is max/mean — the reference's convention for reporting load
    imbalance (a perfectly balanced quantity reads 1.00).
    """

    def __init__(self, num_shards: int):
        self.num_shards = num_shards
        self._rows: Dict[str, np.ndarray] = {}
        self._order: List[str] = []

    def record(self, name: str, values: Sequence[float]) -> None:
        arr = np.asarray(values, dtype=np.float64).reshape(-1)
        if arr.shape[0] != self.num_shards:
            raise ValueError(
                f"expected {self.num_shards} per-shard values for {name!r}, "
                f"got {arr.shape[0]}"
            )
        if name not in self._rows:
            self._order.append(name)
            self._rows[name] = arr
        else:  # accumulate repeated phases (e.g. moves per round)
            self._rows[name] = self._rows[name] + arr

    def stats(self, name: str) -> dict:
        arr = self._rows[name]
        mean = float(arr.mean())
        return {
            "min": float(arr.min()),
            "mean": mean,
            "max": float(arr.max()),
            "imb": float(arr.max() / mean) if mean > 0 else 1.0,
        }

    def imbalance_summary(self) -> dict:
        """Aggregate max/mean imbalance over all rows (round 13): the
        one-line shard-skew figure the 8-device dryrun artifacts carry
        without post-processing.  ``max_imb`` names the worst row."""
        if not self._order:
            return {"max_imb": 1.0, "mean_imb": 1.0, "worst": None}
        imbs = {name: self.stats(name)["imb"] for name in self._order}
        worst = max(imbs, key=lambda n: imbs[n])
        return {
            "max_imb": round(imbs[worst], 4),
            "mean_imb": round(sum(imbs.values()) / len(imbs), 4),
            "worst": worst,
        }

    def render(self) -> str:
        if not self._order:
            return "(no shard statistics recorded)"
        width = max(len(n) for n in self._order)
        lines = [
            f"shard statistics over {self.num_shards} shards "
            "(min / mean / max, imb = max/mean):"
        ]
        for name in self._order:
            s = self.stats(name)
            lines.append(
                f"  {name:<{width}}  {s['min']:>12.1f} / {s['mean']:>12.1f} / "
                f"{s['max']:>12.1f}  (imb {s['imb']:.2f})"
            )
        agg = self.imbalance_summary()
        lines.append(
            f"  {'imbalance':<{width}}  max {agg['max_imb']:.2f} "
            f"({agg['worst']}) / mean {agg['mean_imb']:.2f}"
        )
        return "\n".join(lines)

    def machine_readable(self) -> str:
        """One SHARDSTAT line per row plus a SHARDSTAT_SUMMARY aggregate
        (greppable, like TIME/RESULT lines)."""
        out = []
        for name in self._order:
            s = self.stats(name)
            out.append(
                f"SHARDSTAT {name} min={s['min']:.1f} mean={s['mean']:.1f} "
                f"max={s['max']:.1f} imb={s['imb']:.4f}"
            )
        if self._order:
            agg = self.imbalance_summary()
            out.append(
                f"SHARDSTAT_SUMMARY max_imb={agg['max_imb']:.4f} "
                f"mean_imb={agg['mean_imb']:.4f} worst={agg['worst']}"
            )
        return "\n".join(out)


def collect_graph_stats(dgraph) -> ShardStats:
    """Static layout statistics of a DistGraph: the load table the reference
    prints when a distributed graph is read (nodes/edges/ghosts per PE).

    Round 13: when the graph carries its build-time ``shard_work`` table
    (distribute_graph and the contraction assembly both populate it from
    arrays already host-resident) the collection costs ZERO device
    readbacks, so shard stats can ride every level of a telemetry-armed
    run; the counted-pull path below remains for graphs built without it
    (e.g. the compressed loader)."""
    P = dgraph.num_shards
    n_loc = dgraph.n_loc
    st = ShardStats(P)

    if dgraph.shard_work:
        for key in ("owned_nodes", "owned_edges", "ghost_nodes",
                    "interface_nodes"):
            st.record(key, [w[key] for w in dgraph.shard_work])
        return st

    owned = np.array(
        [max(0, min((s + 1) * n_loc, dgraph.n) - s * n_loc) for s in range(P)],
        dtype=np.float64,
    )
    st.record("owned_nodes", owned)
    # One counted readback for the work table's device inputs (round 12,
    # kptlint sync-discipline: these were un-counted np.asarray transfers).
    from ..utils import sync_stats

    edge_w, send = sync_stats.pull(
        dgraph.edge_w, dgraph.send_idx, phase="dist_stats",
        shards=dgraph.num_shards,
    )
    edge_w = edge_w.reshape(P, dgraph.m_loc)
    st.record("owned_edges", (edge_w > 0).sum(axis=1))
    st.record("ghost_nodes", [len(g) for g in dgraph.ghost_global])
    # interface = owned nodes referenced by at least one other shard
    # (send_idx rows (t*P+s) hold the slots shard t sends to shard s;
    # pad slots hold n_loc).
    send = send.reshape(P, P, dgraph.cap_g)
    iface = [
        len(np.unique(send[t][send[t] < n_loc])) for t in range(P)
    ]
    st.record("interface_nodes", iface)
    return st
