"""Distributed label propagation over a device mesh.

The dKaMinPar global LP clusterer re-designed for SPMD/XLA
(kaminpar-dist/coarsening/clustering/lp/global_lp_clusterer.cc): clusters may
span shards; each round is bulk-synchronous —

1. every shard rates its local nodes' candidate clusters from the round-start
   global label table (one ``all_gather`` over the mesh axis = the ghost-label
   exchange, replacing ``sparse_alltoall_interface_to_pe``),
2. global cluster weights are replicated via ``psum`` of shard-local
   segment sums (replacing the growt global weight map, :437-525),
3. moves commit **probabilistically** in proportion to the target cluster's
   remaining capacity (the reference dist LP refiner's PROBABILISTIC
   execution strategy, dkaminpar.h:116-120), then any cluster that still
   ended up overweight has this round's in-moves rolled back — the strict
   bulk-synchronous version of the reference's weight-rollback protocol
   (global_lp_clusterer.cc:437-525).

Everything here runs *inside* ``shard_map`` over mesh axis ``'nodes'``; the
host-facing entry points build the shard_map closure for a given mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.bucketed_gains import flat_best_moves, lookup

AXIS = "nodes"


def _round_body(key, labels_loc, node_w_loc, edge_u, col_idx, edge_w, max_w,
                *, num_labels: int, external_only: bool):
    """One bulk-synchronous LP round; runs per shard inside shard_map."""
    idx = jax.lax.axis_index(AXIS)
    kshard = jax.random.fold_in(key, idx)
    kr, kp = jax.random.split(kshard)
    n_loc = labels_loc.shape[0]

    # Ghost-label exchange: replicate the round-start label table.
    labels_glob = jax.lax.all_gather(labels_loc, AXIS, tiled=True)

    def global_weights(lab_loc):
        return jax.lax.psum(
            jax.ops.segment_sum(node_w_loc, lab_loc, num_segments=num_labels), AXIS
        )

    cluster_w = global_weights(labels_loc)

    # Per-shard best moves: the shared flat kernel with candidate labels read
    # from the gathered global table (ops/bucketed_gains.flat_best_moves).
    target, tconn, _, _ = flat_best_moves(
        kr, edge_u, labels_glob[col_idx], edge_w, labels_loc, node_w_loc,
        cluster_w, max_w, num_rows=n_loc,
        external_only=external_only, respect_caps=True,
    )
    desired = jnp.where(tconn > 0, target, labels_loc)
    mover = desired != labels_loc

    # Probabilistic commitment: accept ∝ remaining capacity / global demand.
    demand = jax.lax.psum(
        jax.ops.segment_sum(
            jnp.where(mover, node_w_loc, 0), desired, num_segments=num_labels
        ),
        AXIS,
    )
    remaining = jnp.maximum(lookup(max_w, jnp.arange(num_labels)) - cluster_w, 0)
    p_accept = jnp.where(demand > 0, remaining / jnp.maximum(demand, 1), 0.0)
    u = jax.random.uniform(kp, mover.shape)
    commit = mover & (u < jnp.clip(p_accept[desired], 0.0, 1.0))

    # Rollback to a feasibility fixpoint: reject in-moves of clusters that
    # ended overweight; a rejected node returns to its source cluster, which
    # can itself tip overweight, so iterate until no *fixable* (overweight
    # with kept in-moves) cluster remains.  Pre-existing overload without
    # in-moves is the balancer's job, not this round's — excluded from the
    # loop condition so it cannot spin.
    cap = lookup(max_w, jnp.arange(num_labels))

    def overweight_fixable(kept):
        w = global_weights(jnp.where(kept, desired, labels_loc))
        arrivals = jax.lax.psum(
            jax.ops.segment_sum(
                kept.astype(jnp.int32), desired, num_segments=num_labels
            ),
            AXIS,
        )
        return (w > cap) & (arrivals > 0)

    def cond(carry):
        _, ow_fix = carry
        return jnp.any(ow_fix)

    def body(carry):
        kept, ow_fix = carry
        kept = kept & ~ow_fix[desired]
        return kept, overweight_fixable(kept)

    kept, _ = jax.lax.while_loop(cond, body, (commit, overweight_fixable(commit)))
    final_labels = jnp.where(kept, desired, labels_loc)
    num_moved = jax.lax.psum(jnp.sum(kept).astype(jnp.int32), AXIS)
    return final_labels, num_moved


def make_dist_lp_round(mesh: Mesh, *, num_labels: int, external_only: bool = False):
    """Build the jitted one-round function for a mesh.

    Takes/returns flat (P*n_loc,)-sharded label arrays; graph arrays are
    (P*m_loc,)-sharded.  max_w may be a scalar or a (num_labels,) table."""

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P()),
        out_specs=(P(AXIS), P()),
    )
    def round_fn(key, labels, node_w, edge_u, col_idx, edge_w, max_w):
        return _round_body(
            key, labels, node_w, edge_u, col_idx, edge_w, max_w,
            num_labels=num_labels, external_only=external_only,
        )

    return jax.jit(round_fn)


def dist_lp_round(mesh, key, labels, graph, max_w, *, num_labels: int,
                  external_only: bool = False):
    """Convenience one-round entry (builds + caches nothing; for tests)."""
    fn = make_dist_lp_round(mesh, num_labels=num_labels, external_only=external_only)
    return fn(key, labels, graph.node_w, graph.edge_u, graph.col_idx, graph.edge_w, max_w)


def dist_lp_iterate(mesh, key, labels, graph, max_w, *, num_labels: int,
                    num_rounds: int, external_only: bool = False):
    """Fixed-round distributed LP loop (host loop; each round one dispatch)."""
    fn = make_dist_lp_round(mesh, num_labels=num_labels, external_only=external_only)
    total = jnp.int32(0)
    for i in range(num_rounds):
        labels, moved = fn(
            jax.random.fold_in(key, i), labels, graph.node_w, graph.edge_u,
            graph.col_idx, graph.edge_w, max_w,
        )
        total = total + moved
    return labels, total


def shard_arrays(mesh: Mesh, graph, labels):
    """Place the graph + label arrays with their 1D shardings."""
    s = NamedSharding(mesh, P(AXIS))
    return (
        jax.device_put(labels, s),
        graph._replace(
            node_w=jax.device_put(graph.node_w, s),
            edge_u=jax.device_put(graph.edge_u, s),
            col_idx=jax.device_put(graph.col_idx, s),
            edge_w=jax.device_put(graph.edge_w, s),
        ),
    )
